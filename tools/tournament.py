#!/usr/bin/env python
"""Policy-tournament driver: one compiled program, the whole scheduler zoo.

The generalization of tools/market_ab.py the policy-as-data refactor buys
(ARCHITECTURE.md §policy zoo): instead of one trace + one compile + one
run per policy variant, the engine compiles ONE program over a
``PolicySet`` and the driver sweeps the (policy, seed) grid as DATA — the
seed axis is ``vmap``-ed (all replications resident on device), the policy
axis is a ``PolicyParams`` row per variant fed to the same jitted function
(zero recompiles: the traced ``params.idx`` switch runs only the selected
kernel per call). Compile count is therefore independent of sweep size —
the driver asserts the jit cache holds exactly one entry after the whole
grid — and every cell is bit-identical to its standalone single-policy
run, which the driver re-runs as both the correctness oracle and the
serial-baseline wall clock the recorded speedup is measured against.

Trace-parallel mode (ROADMAP item 3b): with more than one device and a
divisible seed axis, the replication axis is sharded over the device mesh
(cells are embarrassingly parallel — sharding is bitwise invisible, the
equality gate proves it on every run).

Run: ``python tools/tournament.py [--quick]`` or ``python bench.py
--tournament``. Writes a markdown table to stdout and JSON to
tools/tournament.json (bench.py embeds the same detail dict).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# the default lineup: the reference repertoire, its parameter variants
# (free under policy-as-data — same compiled kernel, different leaves), and
# the heterogeneity/packing zoo members
DEFAULT_POLICIES = ("fifo", "delay", "delay-eager", "delay-patient",
                    "ffd", "ffd-memfirst", "gavel", "tesserae")


def sweep_policies():
    """Register and return the parameter-sweep lineup (48 variants — 16
    points each over the delay/gavel/tesserae kinds): a DELAY
    promotion-threshold grid (binds
    under the saturated tournament load — thresholds change promotion
    ticks, hence Level1 order, hence placements), a Gavel grid whose
    core-heavy-class throughput on accelerator nodes crosses the
    preference-flipping point 1.0 (sc < 1 avoids the accel nodes, sc > 1
    routes onto them), and a Tesserae mem-weight grid spanning four
    decades of the demand·free trade-off. This is the shape the refactor
    exists for — every variant here is pure parameter DATA (zero extra
    compiles in the tournament), while the serial loop pays one trace +
    one compile per variant."""
    from multi_cluster_simulator_tpu.policies import REGISTRY, variant

    names = []
    for w in (1_000, 2_000, 3_000, 4_000, 6_000, 8_000, 10_000, 12_000,
              14_000, 16_000, 20_000, 24_000, 28_000, 32_000, 36_000,
              40_000):
        n = f"delay-w{w}"
        if n not in REGISTRY:
            variant(n, "delay", max_wait_ms=w)
        names.append(n)
    for i, sc in enumerate((0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0, 1.1, 1.25,
                            1.5, 2.0, 2.5, 3.0, 4.0, 6.0, 8.0)):
        n = f"gavel-accel{i}"
        if n not in REGISTRY:
            variant(n, "gavel", gavel_tput=(
                (1.0, 1.0, 1.0, 1.0), (1.0, sc, 1.0, 1.0),
                (0.5, sc, 1.0, 1.0), (0.5, sc, 1.0, 1.0)))
        names.append(n)
    for i, mw in enumerate((1e-4, 2e-4, 3e-4, 5e-4, 1e-3, 2e-3, 3e-3, 5e-3,
                            1e-2, 2e-2, 3e-2, 5e-2, 0.1, 0.3, 1.0, 3.0)):
        n = f"tess-mem{i}"
        if n not in REGISTRY:
            variant(n, "tesserae", tess_w=(1.0, mw, 1.0))
        names.append(n)
    return tuple(names)


def _specs(C):
    """Heterogeneous clusters for the device-type-aware members: five
    uniform nodes, the last two typed as accelerators (device_type 1) —
    same capacities, so type-blind policies are unaffected."""
    from multi_cluster_simulator_tpu.core.spec import ClusterSpec, NodeSpec

    def cluster(cid):
        return ClusterSpec(id=cid, nodes=tuple(
            NodeSpec(id=i + 1, cores=32, memory=24_000,
                     device_type=1 if i >= 3 else 0) for i in range(5)))

    return [cluster(c + 1) for c in range(C)]


def _cfg(queue_capacity=96, max_running=96, jobs_per=120):
    from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig

    # One config every zoo member can run: parity semantics (the bounded
    # while-loop sweeps make them cheap), no borrowing/trader (policy-axis
    # A/B, not market A/B), bounds sized so no cell drops — the zero-drops
    # gate below keeps cells comparable across policies.
    return SimConfig(policy=PolicyKind.FIFO, parity=True, n_res=2,
                     queue_capacity=queue_capacity, max_running=max_running,
                     max_arrivals=jobs_per, max_ingest_per_tick=32,
                     max_nodes=5, max_virtual_nodes=0)


def _pack_seeds(arrs, n_ticks, tick_ms):
    """Pack each seed's stream once (pack_arrivals_by_tick), pad every
    bucket to the grid-global K, and stack on a leading seed axis — the
    'arrivals packed once and broadcast' half of the tournament contract.
    Padding rows are invalid sentinels the ingest masks off (the same
    invariant the ragged chunk pipeline relies on), so the shared K is
    invisible to every cell. Returns (stacked TickArrivals, per-seed
    unpadded buckets for the standalone oracle runs)."""
    import jax.numpy as jnp

    from multi_cluster_simulator_tpu.core.engine import pack_arrivals_by_tick
    from multi_cluster_simulator_tpu.core.state import TickArrivals
    from multi_cluster_simulator_tpu.ops import queues as Q

    tas = [pack_arrivals_by_tick(a, n_ticks, tick_ms) for a in arrs]
    K = max(ta.rows.shape[2] for ta in tas)
    rows = []
    for ta in tas:
        r = np.asarray(ta.rows)
        if r.shape[2] < K:
            pad = np.broadcast_to(
                np.asarray(Q._INVALID_ROW),
                r.shape[:2] + (K - r.shape[2], Q.NF))
            r = np.concatenate([r, pad], axis=2)
        rows.append(r)
    stacked = TickArrivals(rows=jnp.asarray(np.stack(rows)),
                           counts=jnp.asarray(np.stack(
                               [np.asarray(ta.counts) for ta in tas])))
    return stacked, tas


def _cell_stats(state, C, jobs_per):
    from multi_cluster_simulator_tpu.core.state import avg_wait_ms
    from multi_cluster_simulator_tpu.utils.trace import total_drops

    placed = int(np.asarray(state.placed_total).sum())
    waits = np.asarray(avg_wait_ms(state))
    return {"placed": placed, "of": C * jobs_per,
            "placed_frac": round(placed / max(C * jobs_per, 1), 4),
            "mean_avg_wait_ms": round(float(waits.mean()), 1),
            "drops": total_drops(state)}


def _grid_digest(policies, seeds, C, jobs_per, horizon_ms, drain_ticks,
                 cfg, variant_params):
    """Digest of everything that makes two sweeps THE SAME grid: lineup,
    seeds, shape, the full SimConfig, and every variant's concrete param
    leaves — the validity record the --resume cell cache is keyed by
    (the checkpoint-header discipline, core/checkpoint.py)."""
    from multi_cluster_simulator_tpu.core.checkpoint import (
        config_describe, digest_of,
    )
    from multi_cluster_simulator_tpu.policies import params_digest

    desc = {"policies": list(policies), "seeds": list(seeds), "C": C,
            "jobs_per": jobs_per, "horizon_ms": horizon_ms,
            "drain_ticks": drain_ticks, "config": config_describe(cfg),
            "params": [params_digest(p) for p in variant_params]}
    return digest_of(desc)


def run_tournament(policies=DEFAULT_POLICIES, n_seeds=4, C=64, jobs_per=120,
                   horizon_ms=240_000, drain_ticks=80, verify_cells=True,
                   shard_seeds="auto", device_ab=False, shard_devices=None,
                   resume_path=None):
    """Run the (policy, seed) grid; returns the tournament detail dict.

    Gates (raise on violation — CI runs this via bench.py --tournament):
    - the grid function compiles exactly once for the whole sweep;
    - every cell's final state is bit-identical to its standalone
      single-policy run (``verify_cells``);
    - no cell drops work (bounds sized for the lineup).

    ``resume_path`` makes a killed sweep a restartable unit (the
    preemption-plane discipline, core/preempt.py): after each variant's
    cells pass the standalone-equality gate, its (policy, seed) results
    are persisted to the JSON cache together with the GRID DIGEST
    (lineup + seeds + shape + config + concrete param leaves); a rerun
    with the same grid re-runs only the missing variants and merges the
    cached rows. Only VERIFIED cells are ever persisted — resume can
    never bypass the equality gate — and a digest mismatch fails fast
    naming the cache, never silently mixes two different sweeps.

    ``device_ab=True`` (with a sharded replication axis) re-runs the whole
    grid through a FRESH jit over single-device inputs and records both
    walls + the measured device speedup in
    ``detail["replication_shard_ab"]`` — plus a direct bitwise comparison
    of the two grids (sharding must be invisible). The re-run uses its own
    jit so the main compile-count gate stays exactly one program.
    ``device_ab=True`` raises if the replication axis cannot shard (a gate
    that silently verifies nothing is worse than a failure);
    ``device_ab="auto"`` runs the A/B only when sharding engaged (the
    bench full record, which also runs single-device).
    ``shard_devices`` caps the replication mesh at the first N devices
    (CI runs a 2-device cell on the 8-virtual-device suite mesh).
    """
    import jax
    import jax.numpy as jnp

    from multi_cluster_simulator_tpu.core.engine import Engine
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.policies import PolicySet, params_digest

    policies = tuple(policies)
    pset = PolicySet(policies)
    cfg = _cfg(jobs_per=jobs_per)
    specs = _specs(C)
    n_ticks = horizon_ms // cfg.tick_ms + drain_ticks  # drain tail
    seeds = list(range(17, 17 + n_seeds))

    from multi_cluster_simulator_tpu.workload.traces import uniform_stream
    # demands up to 24 cores on 32-core nodes: both demand-shape classes
    # exist (job_class splits at cores > 8) and the grid runs loaded, so
    # promotion thresholds, throughput matrices, and packing weights all
    # actually steer placements — a policy sweep over an idle grid ranks
    # noise. Per-cluster arrivals never exceed queue_capacity, so the
    # zero-drops gate holds by sizing.
    arrs = [uniform_stream(C, jobs_per, horizon_ms, max_cores=24,
                           max_mem=18_000, max_dur_ms=30_000, seed=s)
            for s in seeds]
    t_pack0 = time.time()
    stacked, tas = _pack_seeds(arrs, n_ticks, cfg.tick_ms)
    pack_s = time.time() - t_pack0

    state0 = init_state(cfg, specs)
    eng = Engine(cfg, policies=pset)

    def grid_fn(state, ta, params):
        # seed axis vmapped (state + params broadcast); the policy axis is
        # a params row per call of this SAME jitted function — lax.switch
        # on the scalar traced idx runs only the selected kernel
        return jax.vmap(lambda a: eng.run(state, a, n_ticks, params))(ta)

    fn = jax.jit(grid_fn)

    # trace-parallel mode: shard the replication (seed) axis over devices.
    # auto engages only on non-CPU backends: a host-CPU "mesh" is virtual
    # devices time-slicing cores XLA's intra-op threadpool already uses, so
    # sharding there only adds partitioning overhead (measured on the
    # 2-core CI host: 0.77x at the C=64 default lineup, 0.56x at the
    # bench full sweep's C=8 micro-cells — tools/tournament_shard_ab.json);
    # "always" forces it anyway, which is what the equality gates and the
    # honest A/B records use.
    devs = jax.devices()[:shard_devices] if shard_devices else jax.devices()
    n_dev = len(devs)
    sharded = (shard_seeds == "always"
               or (shard_seeds == "auto" and n_dev > 1
                   and jax.default_backend() != "cpu")) \
        and n_seeds % max(n_dev, 1) == 0 and n_dev > 1
    # an explicit request that cannot engage must fail, not silently run
    # unsharded — otherwise the CI gate ("--shard always --device-ab")
    # would exit 0 having verified nothing if the multi-device env var is
    # ever dropped or the seed count stops dividing
    if shard_seeds == "always" and not sharded:
        raise AssertionError(
            f"--shard always cannot engage: {n_dev} device(s), {n_seeds} "
            "seeds — need >1 device and a seed count divisible by it")
    if device_ab and not sharded:
        if device_ab == "auto":  # bench full mode: A/B only when sharded
            device_ab = False
        else:
            raise AssertionError(
                "--device-ab requires a sharded replication axis "
                f"({n_dev} device(s), {n_seeds} seeds)")
    stacked_host = stacked  # pre-placement copy for the device A/B re-run
    if sharded:
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P
        mesh = Mesh(np.array(devs), ("replications",))
        stacked = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh,
                                                      P("replications"))),
            stacked)

    variant_params = [pset.params_for(cfg, name) for name in policies]

    # --resume: the verified-cell cache (grid-digest keyed)
    resume_cells: dict = {}
    grid_dig = None
    if resume_path is not None:
        if not verify_cells:
            raise ValueError(
                "--resume requires cell verification (verify_cells=True): "
                "only verified cells are ever persisted, so an unverified "
                "sweep has nothing legal to cache")
        grid_dig = _grid_digest(policies, seeds, C, jobs_per, horizon_ms,
                                drain_ticks, cfg, variant_params)
        if os.path.exists(resume_path):
            with open(resume_path) as f:
                cache = json.load(f)
            if cache.get("grid_digest") != grid_dig:
                raise ValueError(
                    f"{resume_path}: tournament resume cache was written "
                    f"for a different grid (digest "
                    f"{cache.get('grid_digest')!r} vs {grid_dig!r}) — the "
                    "lineup, seeds, shape, config, or param leaves "
                    "changed; delete the cache or point --resume elsewhere")
            resume_cells = dict(cache.get("completed", {}))

    def _persist(name, rows_for_variant):
        if resume_path is None:
            return
        resume_cells[name] = rows_for_variant
        tmp = resume_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"grid_digest": grid_dig,
                       "completed": resume_cells}, f, indent=1)
        os.replace(tmp, resume_path)

    fresh = [v for v, name in enumerate(policies)
             if name not in resume_cells]
    from multi_cluster_simulator_tpu.obs.profile import annotate_dispatch
    t0 = time.time()
    with annotate_dispatch("tournament", variants=len(fresh),
                           seeds=n_seeds):
        grid = {v: jax.block_until_ready(fn(state0, stacked,
                                            variant_params[v]))
                for v in fresh}
    tournament_wall = time.time() - t0
    if fresh:
        cache_size = getattr(fn, "_cache_size", lambda: None)()
        if cache_size is None:
            # fail loudly rather than fabricate a passing gate: a jax that
            # renames the cache probe would otherwise let a recompile-per-
            # variant regression ship with compiled_programs silently "1"
            raise AssertionError(
                "jit cache probe unavailable (jax renamed _cache_size?) — "
                "update the compile-count gate in tools/tournament.py")
        if cache_size != 1:
            raise AssertionError(
                f"tournament compiled {cache_size} programs for "
                f"{len(fresh)}x{n_seeds} cells — compile count must be "
                "independent of sweep size (exactly one)")
    else:
        cache_size = 0  # everything resumed; no grid program ran

    shard_ab = None
    if device_ab and sharded and fresh:
        # the measured trace-parallel win: the SAME grid through a fresh
        # jit over single-device inputs (one compile each side — walls
        # compare runs only), plus the direct bitwise gate
        fn1 = jax.jit(grid_fn)
        one = {v: jax.block_until_ready(fn1(state0, stacked_host,
                                            variant_params[v]))
               for v in fresh}  # compile + correctness run
        for v in fresh:
            for la, lb in zip(jax.tree.leaves(grid[v]),
                              jax.tree.leaves(one[v])):
                if not np.array_equal(np.asarray(la), np.asarray(lb)):
                    raise AssertionError(
                        "sharded replication grid diverges from the "
                        "single-device grid — sharding must be invisible")
        t0 = time.time()
        for v in fresh:
            jax.block_until_ready(fn1(state0, stacked_host,
                                      variant_params[v]))
        one_wall = time.time() - t0
        t0 = time.time()
        for v in fresh:
            jax.block_until_ready(fn(state0, stacked, variant_params[v]))
        sh_wall = time.time() - t0
        shard_ab = {"devices": n_dev,
                    "sharded_wall_s": round(sh_wall, 3),
                    "single_device_wall_s": round(one_wall, 3),
                    "device_speedup": round(one_wall / max(sh_wall, 1e-9), 2),
                    "grids_bit_identical": True}

    # serial per-policy loop: the pre-zoo workflow (one Engine, one trace,
    # one compile per variant — the market_ab shape) — both the recorded
    # baseline wall AND the bit-equality oracle for every cell. Skipped
    # entirely under verify_cells=False: the loop exists only for the
    # comparison, so --no-verify also skips the baseline wall. Resumed
    # variants are skipped too: their cells passed this exact gate before
    # they were persisted (_persist runs only after verification).
    serial_wall = None
    mismatches = []
    variant_rows: dict = {}

    def _rows_for(v, name):
        digest = params_digest(variant_params[v])
        out = []
        for si, s in enumerate(seeds):
            cell = jax.tree.map(lambda a, i=si: a[i], grid[v])
            stats = _cell_stats(cell, C, jobs_per)
            if any(stats["drops"].values()):
                raise AssertionError(
                    f"tournament cell ({name}, seed {s}) dropped work "
                    f"({stats['drops']}) — resize the tournament config")
            out.append({"policy": name, "params_digest": digest,
                        "seed": s, **stats})
        return out

    if verify_cells:
        # the baseline wall times ONLY the engine-build + trace/compile +
        # runs (what the pre-zoo workflow actually paid per variant) —
        # the equality comparison below is verification overhead and is
        # timed out of the baseline
        serial_wall = 0.0
        for v, name in enumerate(policies):
            if v not in grid:
                continue  # resumed variant
            t0 = time.time()
            eng1 = Engine(cfg, policies=PolicySet((name,)))
            fn1 = eng1.run_jit()
            refs = [jax.block_until_ready(fn1(state0, tas[si], n_ticks))
                    for si in range(n_seeds)]
            serial_wall += time.time() - t0
            bad = False
            for si, ref in enumerate(refs):
                cell = jax.tree.map(lambda a, i=si: a[i], grid[v])
                for la, lb in zip(jax.tree.leaves(cell),
                                  jax.tree.leaves(ref)):
                    if not np.array_equal(np.asarray(la), np.asarray(lb)):
                        mismatches.append((name, seeds[si]))
                        bad = True
                        break
            if not bad:
                variant_rows[name] = _rows_for(v, name)
                _persist(name, variant_rows[name])
    if mismatches:
        raise AssertionError(
            "tournament cells diverge from their standalone runs: "
            f"{sorted(set(mismatches))}")

    rows = []
    resumed_variants = []
    for v, name in enumerate(policies):
        if name in variant_rows:
            rows.extend(variant_rows[name])
        elif v not in grid and name in resume_cells:
            resumed_variants.append(name)
            rows.extend([{**r, "resumed": True} for r in resume_cells[name]])
        else:  # verify_cells=False: stats straight off the grid
            rows.extend(_rows_for(v, name))

    # rank: most work placed, then lowest mean wait, aggregated over seeds
    agg = {}
    for r in rows:
        a = agg.setdefault(r["policy"], {"policy": r["policy"],
                                         "params_digest": r["params_digest"],
                                         "placed": 0, "waits": []})
        a["placed"] += r["placed"]
        a["waits"].append(r["mean_avg_wait_ms"])
    ranking = sorted(agg.values(),
                     key=lambda a: (-a["placed"], float(np.mean(a["waits"]))))
    for i, a in enumerate(ranking):
        a["rank"] = i + 1
        a["mean_avg_wait_ms"] = round(float(np.mean(a.pop("waits"))), 1)

    detail = {
        "policies": list(policies), "seeds": seeds, "clusters": C,
        "jobs_per_cluster": jobs_per, "cells": len(policies) * n_seeds,
        "ticks": n_ticks,
        "backend": jax.default_backend(), "devices": n_dev,
        "replication_axis_sharded": bool(sharded),
        "compiled_programs": cache_size,
        **({"replication_shard_ab": shard_ab} if shard_ab else {}),
        "pack_once_s": round(pack_s, 3),
        "tournament_wall_s": round(tournament_wall, 3),
        "cells_bit_identical_to_standalone": bool(verify_cells),
        **({"resumed_variants": resumed_variants,
            "grid_digest": grid_dig} if resume_path is not None else {}),
        "ranking": ranking,
        "rows": rows,
    }
    if serial_wall is not None:
        detail["serial_loop_wall_s"] = round(serial_wall, 3)
        detail["speedup_vs_serial"] = round(
            serial_wall / max(tournament_wall, 1e-9), 2)
    return detail


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--policies", nargs="+", default=list(DEFAULT_POLICIES))
    ap.add_argument("--seeds", type=int, default=4)
    ap.add_argument("--clusters", type=int, default=64)
    ap.add_argument("--jobs", type=int, default=120)
    ap.add_argument("--horizon-ms", type=int, default=240_000)
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke shape (4 policies x 2 seeds, small grid)")
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the per-cell standalone equality check "
                         "(also skips the serial baseline wall)")
    ap.add_argument("--shard", choices=("auto", "always", "never"),
                    default="auto",
                    help="shard the replication (seed) axis over the device "
                         "mesh (trace-parallel mode; auto = when >1 device "
                         "and the seed count divides)")
    ap.add_argument("--device-ab", action="store_true",
                    help="also run the grid single-device through a fresh "
                         "jit and record the measured device speedup + the "
                         "bitwise sharded==unsharded gate")
    ap.add_argument("--resume", metavar="PATH", default=None,
                    help="verified-cell cache: completed (policy, seed) "
                         "results persist here (with the grid digest) as "
                         "each variant passes the standalone-equality "
                         "gate, so a killed sweep re-runs only missing "
                         "cells; a digest mismatch fails fast")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tournament.json"))
    args = ap.parse_args(argv)
    kw = dict(policies=tuple(args.policies), n_seeds=args.seeds,
              C=args.clusters, jobs_per=args.jobs,
              horizon_ms=args.horizon_ms,
              verify_cells=not args.no_verify,
              shard_seeds=args.shard, device_ab=args.device_ab,
              resume_path=args.resume)
    if args.quick:
        kw.update(policies=tuple(args.policies[:4]) if len(args.policies) > 4
                  else tuple(args.policies),
                  n_seeds=2, C=16, jobs_per=60, horizon_ms=120_000)
    detail = run_tournament(**kw)
    with open(args.out, "w") as f:
        json.dump(detail, f, indent=2)
    speed = detail.get("speedup_vs_serial", "n/a (--no-verify)")
    print(f"# {detail['cells']} cells, {detail['compiled_programs']} "
          f"compile(s), {speed}x vs serial loop", file=sys.stderr)
    print("| rank | policy | params | placed | mean avg wait (ms) |")
    print("|---|---|---|---|---|")
    for a in detail["ranking"]:
        print(f"| {a['rank']} | {a['policy']} | {a['params_digest']} | "
              f"{a['placed']} | {a['mean_avg_wait_ms']} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
