#!/usr/bin/env python
"""Weak-scaling constellation driver (ROADMAP item 3a): the mesh as the
*bigger-problem* lever.

Holds a fixed per-device cluster count (~4k at full scale) and grows the
constellation with the mesh — 1/2/4/8 devices = 4k..32k clusters — running
the headline FIFO-parity semantics with the full single-device ladder
composed: compact SoA state, ragged streamed chunk pipeline with per-shard
H2D prefetch (the chunk placement routes through ShardedEngine.
shard_arrivals, so each device receives only its shard's slice), donated
state, and event-compressed time where the trace is sparse. Three record
sections land in MULTICHIP_r0N.json:

- ``rows``: the per-device-count weak-scaling curve (jobs/s, scaling
  efficiency, ticks executed/simulated, bytes, drops, policy provenance —
  per-row backend/device provenance like tools/cost_probe.py);
- ``market_row``: the federated market (DELAY + trader) composed at the
  full-mesh constellation shape — the exchange collectives at 8 x 4k
  clusters, which no prior record ever measured;
- ``record``: the Borg-scale streamed record — 10M+ jobs end-to-end
  through the composed pipeline (ROADMAP item 3c).

Honest-measurement note: on a CPU host the "devices" are virtual
(``--xla_force_host_platform_device_count``) and time-slice the physical
cores, so the recorded efficiency measures the sharded path's overhead at
shape, not real silicon scaling — the record names the bottleneck
(``bottleneck`` field) exactly like tools/multihost_scaling.py does. The
bit-exactness guarantee (every parity cell below) is what transfers to
real multi-chip hardware unchanged.

Divisibility: weak-scaling shapes (per_device x n) always divide; for an
arbitrary ``--clusters`` total the driver auto-pads to the next multiple
with inert always-full sentinel clusters (zero-capacity nodes, zero
arrivals — they can never place, lend, or borrow), and the parity gate
pins the real-cluster prefix bit-identical to the unpadded single-device
run. Padding is refused when the trader market is on: a sentinel's
utilization snapshot is visible to the request/approve policies, so a
padded market constellation would NOT be replay-invisible.

Run: ``python tools/weak_scaling.py [--quick]`` or ``python bench.py
--multichip``. ``--quick`` refuses to overwrite the full-scale record
(same guard as tools/cost_probe.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_SELF = os.path.abspath(__file__)
_ROOT = os.path.dirname(os.path.dirname(_SELF))
sys.path.insert(0, _ROOT)

import numpy as np

DEFAULT_OUT = os.path.join(_ROOT, "MULTICHIP_r06.json")
DEVICE_COUNTS = (1, 2, 4, 8)


def _fifo_constellation(C, jobs_per, horizon_ms, seed=9):
    """The headline FIFO-parity shape (bench._fifo_parity_scale's config) at
    an arbitrary cluster count — one definition so the weak-scaling rows
    measure the exact semantics the BENCH_r0N headline records."""
    from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.workload.traces import uniform_stream

    # the headline's bounds sized for this driver's 3x-denser stream
    # (jobs_per over a ~200 s horizon vs 250 over 1500 s): the measured
    # running-set peak tops 32 at 32k clusters, so 64 slots; the
    # zero-drops assert in _run_shape proves neither bound ever binds
    cfg = SimConfig(policy=PolicyKind.FIFO, queue_capacity=16,
                    max_running=64, max_arrivals=jobs_per,
                    max_ingest_per_tick=8, parity=True, n_res=2,
                    max_nodes=5, max_virtual_nodes=0)
    specs = [uniform_cluster(c + 1, 5) for c in range(C)]
    arrivals = uniform_stream(C, jobs_per, horizon_ms, max_cores=8,
                              max_mem=6_000, max_dur_ms=60_000, seed=seed)
    n_ticks = horizon_ms // cfg.tick_ms + 70  # drain tail
    return cfg, specs, arrivals, n_ticks


def _record_constellation(C, bursts, per_burst, interval_ms, seed=11):
    """The Borg-sparsity record shape: jobs arrive in 20 s bursts with long
    quiescent valleys, so the event-compressed driver leaps the valleys
    while the streamed pipeline feeds burst chunks shard-by-shard —
    the full composition ROADMAP item 3c names."""
    from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.workload.traces import bursty_stream

    window_ms = 20_000
    cfg = SimConfig(policy=PolicyKind.FIFO, queue_capacity=32,
                    max_running=64, max_arrivals=bursts * per_burst,
                    max_ingest_per_tick=16, parity=True, n_res=2,
                    max_nodes=5, max_virtual_nodes=0)
    specs = [uniform_cluster(c + 1, 5) for c in range(C)]
    arrivals = bursty_stream(C, bursts, per_burst, interval_ms, window_ms,
                             max_cores=8, max_mem=6_000, max_dur_ms=60_000,
                             seed=seed)
    n_ticks = bursts * interval_ms // cfg.tick_ms + 70
    return cfg, specs, arrivals, n_ticks


def pad_constellation(cfg, specs, arrivals, n_shards):
    """Pad a clusters-not-divisible-by-mesh constellation to the next valid
    count with inert sentinel clusters: one zero-capacity node (always
    full — nothing can ever place, so free stays 0) and a zero-length
    arrival stream. Sentinels can never place, lend, borrow, or promote, so
    the real-cluster prefix is bit-identical to the unpadded run
    (tests/test_sharded.py pins it). Returns ``(specs, arrivals, n_pad)``.

    Refused under the trader market: utilization/wait snapshots of a
    sentinel enter the request+approve policies, so market padding would
    change real clusters' trades."""
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.parallel.mesh import nearest_divisible

    C = len(specs)
    _, hi = nearest_divisible(C, n_shards)
    if hi == C:
        return specs, arrivals, 0
    if cfg.trader.enabled:
        raise ValueError(
            f"cannot auto-pad a trader-enabled constellation ({C} clusters "
            f"-> {hi}): sentinel utilization snapshots are visible to the "
            "market's request/approve policies; pick a divisible cluster "
            "count instead")
    import jax

    n_pad = hi - C
    specs = list(specs) + [uniform_cluster(C + i + 1, 1, cores=0, memory=0)
                           for i in range(n_pad)]

    def pad_leaf(x):
        x = np.asarray(x)
        return np.concatenate(
            [x, np.zeros((n_pad,) + x.shape[1:], x.dtype)], axis=0)

    # every Arrivals leaf leads with the cluster axis ([C, A] rows, [C] n)
    arrivals = jax.tree.map(pad_leaf, arrivals)
    return specs, arrivals, n_pad


def _run_shape(cfg, specs, arrivals, n_ticks, n_dev, repeats=2, chunk=200,
               compact=True, stream="auto", time_compress="auto",
               ckpt=None, resume=False):
    """One measured row through bench._engine_run with the mesh pinned to
    ``n_dev`` devices; returns (final_state, row_detail). ``ckpt`` arms
    the preemption plane for the row (core/preempt.py: async per-chunk
    RunCheckpoints + SIGTERM save-and-exit; ``resume`` continues a killed
    row bit-identically) — the long Borg-scale record row is the consumer,
    so a multi-hour 10M-job run is a restartable unit, not an
    all-or-nothing job."""
    import jax

    import bench

    bench._COMPACT["mode"] = "on" if compact else "off"
    bench._PIPELINE["mode"] = "on"
    bench._PIPELINE["stream"] = stream
    bench._TIME_COMPRESS["mode"] = time_compress
    saved_ckpt = dict(bench._CKPT)
    bench._CKPT.update(path=ckpt, resume=bool(resume))
    try:
        out, wall_s, compile_s, _, info = bench._engine_run(
            cfg, specs, arrivals, n_ticks, use_mesh=n_dev > 1, chunk=chunk,
            repeats=repeats, warmups=0, tick_indexed=True,
            mesh_devices=n_dev)
    finally:
        bench._CKPT.update(saved_ckpt)
    placed = int(np.asarray(out.placed_total).sum())
    drops = bench._assert_zero_drops(out, f"weak_scaling[{n_dev}dev]")
    row = {
        "n_devices": n_dev,
        "clusters": len(specs),
        "jobs": placed,
        "jobs_per_sec": round(placed / max(wall_s, 1e-9), 1),
        "wall_s": round(wall_s, 3),
        "walls": [round(w, 3) for w in info.get("walls", [])],
        "compile_s": round(compile_s, 1),
        "drops": drops,
        "backend": jax.default_backend(),
        "devices_visible": len(jax.devices()),
    }
    for k in ("policy", "state_bytes", "arrivals_bytes", "h2d_bytes",
              "tick_bytes_accessed", "time_compress", "pipeline", "compact",
              "checkpoint"):
        if info.get(k) is not None:
            row[k] = info[k]
    tc = info.get("time_compress", {})
    row["ticks_simulated"] = tc.get("ticks_simulated", n_ticks)
    row["ticks_executed"] = tc.get("ticks_executed", n_ticks)
    return out, row


def run_curve(per_device, jobs_per, horizon_ms, device_counts, repeats=2,
              chunk=200):
    """The weak-scaling curve: clusters = per_device x n for each device
    count, fixed per-device work. Efficiency is the weak-scaling form
    (rate_n / n) / (rate_min / n_min) — the smallest-mesh row is the
    per-device baseline (1.0 there by construction), so a --devices list
    that skips 1 or arrives unsorted still gets a correct column."""
    rows = []
    for n in sorted(set(device_counts)):
        cfg, specs, arrivals, n_ticks = _fifo_constellation(
            per_device * n, jobs_per, horizon_ms)
        _, row = _run_shape(cfg, specs, arrivals, n_ticks, n, repeats=repeats,
                            chunk=chunk)
        rows.append(row)
        print(f"# weak_scaling {n} dev x {per_device} clusters: "
              f"{row['jobs_per_sec']} jobs/s", file=sys.stderr)
    base_per_dev = rows[0]["jobs_per_sec"] / rows[0]["n_devices"]
    for row in rows:
        row["efficiency_vs_linear"] = round(
            row["jobs_per_sec"] / (row["n_devices"] * base_per_dev), 3)
    return rows


def run_market_row(per_device, n_dev, jobs_per, horizon_ms, repeats=1):
    """The federated market composed at the full-mesh constellation: the
    sinkhorn bench shape (DELAY + trader, greedy matching — the
    [C_loc, C_tot] sinkhorn plan matrix is quadratic in the constellation
    and not the scale instrument) across every device. Proves the
    borrow/trade exchange collectives at the 8 x 4k shape."""
    from bench import sinkhorn_market_setup

    C = per_device * n_dev
    cfg, specs, arrivals, n_ticks = sinkhorn_market_setup(
        C, jobs_per, horizon_ms, matching="greedy")
    out, row = _run_shape(cfg, specs, arrivals, n_ticks, n_dev,
                          repeats=repeats, chunk=100, time_compress="off")
    vnodes = int(np.asarray(out.node_active)[:, cfg.max_nodes:].sum())
    row["virtual_nodes_traded"] = vnodes
    if vnodes < 1:
        raise AssertionError(
            "market composition row: the federated market never traded a "
            "virtual node at the full-mesh shape")
    row["kind"] = "federated_market_composition"
    print(f"# market row {n_dev} dev x {per_device} clusters: "
          f"{row['jobs_per_sec']} jobs/s, {vnodes} vnodes traded",
          file=sys.stderr)
    return row


def run_record(n_dev, per_device, bursts, per_burst, interval_ms,
               ckpt=None, resume=False):
    """The Borg-scale streamed record: 10M+ jobs end-to-end with every
    composition engaged — compact state, per-shard streamed H2D prefetch
    (forced), donated buffers, event-compressed valleys. With ``ckpt``
    the record is preemption-proof: per-chunk async RunCheckpoints (the
    sharded state gathers at the boundary, restore re-shards), SIGTERM
    saves-and-exits, and a ``--resume`` rerun continues bit-identically."""
    C = per_device * n_dev
    cfg, specs, arrivals, n_ticks = _record_constellation(
        C, bursts, per_burst, interval_ms)
    total = C * bursts * per_burst
    out, row = _run_shape(cfg, specs, arrivals, n_ticks, n_dev, repeats=1,
                          chunk=100, stream="always", time_compress="auto",
                          ckpt=ckpt, resume=resume)
    assert row["jobs"] >= 0.99 * total, (
        f"record run placed only {row['jobs']}/{total}")
    row["kind"] = "borg_scale_streamed_record"
    row["jobs_total"] = total
    print(f"# record: {row['jobs']} jobs at {row['jobs_per_sec']} jobs/s "
          f"({row['ticks_executed']}/{row['ticks_simulated']} ticks "
          "executed)", file=sys.stderr)
    return row


def verify_parity_cells(device_counts, quick=False):
    """The CI-scale bit-equality gate: for every mesh size, a small
    weak-scaling constellation must be leaf-for-leaf identical to the
    single-device run of the same total shape — composed with the compact
    layout and event compression — and a non-divisible constellation
    auto-padded with sentinels must match the unpadded single-device run
    on the real-cluster prefix. Raises on any divergence; the record
    embeds the cell list so the parity claim is auditable."""
    import jax

    from multi_cluster_simulator_tpu.core.compact import derive_plan
    from multi_cluster_simulator_tpu.core.engine import (
        Engine, pack_arrivals_by_tick,
    )
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.parallel import ShardedEngine, make_mesh

    cells = []
    C, jobs_per, horizon = 16, 12, 40_000
    for compact in (False, True) if not quick else (True,):
        cfg, specs, arrivals, n_ticks = _fifo_constellation(
            C, jobs_per, horizon, seed=23)
        plan = derive_plan(cfg, specs, arrivals) if compact else None
        ta = pack_arrivals_by_tick(arrivals, n_ticks, cfg.tick_ms)
        s0 = init_state(cfg, specs, plan=plan)
        ref = Engine(cfg).run_jit()(s0, ta, n_ticks)
        for n in device_counts:
            if n == 1 or C % n:
                continue
            sh = ShardedEngine(cfg, make_mesh(n))
            got, stats = sh.run_fn(n_ticks, tick_indexed=True,
                                   time_compress=True)(
                sh.shard_state(s0), sh.shard_arrivals(ta))
            for la, lb in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
                if not np.array_equal(np.asarray(la), np.asarray(lb)):
                    raise AssertionError(
                        f"weak-scaling parity cell diverged: {n}-device "
                        f"mesh != single device (compact={compact})")
            cells.append({"n_devices": n, "clusters": C,
                          "compact": compact, "time_compress": True,
                          "ticks_executed": int(
                              np.asarray(stats.ticks_executed)),
                          "bit_identical": True})
    # padded cell: 13 clusters on the largest mesh — sentinel prefix pin
    n = max(d for d in device_counts if d > 1) if any(
        d > 1 for d in device_counts) else None
    if n:
        cfg, specs, arrivals, n_ticks = _fifo_constellation(
            13, jobs_per, horizon, seed=29)
        ref = Engine(cfg).run_jit()(
            init_state(cfg, specs),
            pack_arrivals_by_tick(arrivals, n_ticks, cfg.tick_ms), n_ticks)
        pspecs, parr, n_pad = pad_constellation(cfg, specs, arrivals, n)
        sh = ShardedEngine(cfg, make_mesh(n))
        ta = pack_arrivals_by_tick(parr, n_ticks, cfg.tick_ms)
        got = sh.run_fn(n_ticks, tick_indexed=True)(
            sh.shard_state(init_state(cfg, pspecs)), sh.shard_arrivals(ta))
        for la, lb in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
            a = np.asarray(la)
            if a.ndim and a.shape[0] == 13 + n_pad:
                a = a[:13]
            if not np.array_equal(a, np.asarray(lb)):
                raise AssertionError(
                    "sentinel-padded constellation diverged from the "
                    "unpadded run on the real-cluster prefix")
        cells.append({"n_devices": n, "clusters": 13, "padded_to": 13 + n_pad,
                      "prefix_bit_identical": True})
    print(f"# parity: {len(cells)} cells bit-identical", file=sys.stderr)
    return cells


def _respawn_with_devices(n, argv):
    """Re-exec self in a child whose CPU platform is pinned to ``n`` virtual
    devices BEFORE jax initializes (device count is fixed at backend init;
    same pattern as __graft_entry__.dryrun_multichip)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["MCS_WEAK_CHILD"] = "1"
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU")) or k == "PJRT_DEVICE":
            env.pop(k)
    proc = subprocess.run([sys.executable, _SELF] + argv, env=env,
                          cwd=_ROOT)
    return proc.returncode


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke shape (small constellation)")
    ap.add_argument("--out", default=None,
                    help=f"record path (default {DEFAULT_OUT}; --quick "
                         "refuses to overwrite the full-scale record)")
    ap.add_argument("--devices", type=int, nargs="+", default=None,
                    help="device counts for the curve (default 1 2 4 8; "
                         "quick default 1 2)")
    ap.add_argument("--per-device-clusters", type=int, default=None,
                    help="clusters per device (default 4096; quick 64)")
    ap.add_argument("--jobs-per", type=int, default=None,
                    help="jobs per cluster for the curve rows")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--min-efficiency", type=float, default=None,
                    help="exit nonzero if the max-device row's weak-scaling "
                         "efficiency lands below this (the CI gate)")
    ap.add_argument("--skip-market", action="store_true")
    ap.add_argument("--skip-record", action="store_true")
    ap.add_argument("--checkpoint", metavar="PATH", default=None,
                    help="arm the preemption plane for the Borg-scale "
                         "record row: async per-chunk RunCheckpoints to "
                         "PATH, SIGTERM save-and-exit (core/preempt.py)")
    ap.add_argument("--resume", action="store_true",
                    help="continue a killed record row from --checkpoint "
                         "(bit-exact)")
    args = ap.parse_args(argv)

    devices = tuple(args.devices or ((1, 2) if args.quick else DEVICE_COUNTS))
    out = args.out or DEFAULT_OUT
    # smoke shapes must never clobber the committed full record (shared
    # guard: tools/records.py). Guarded only when --out was DEFAULTED:
    # an explicit `--out <record path>` is a deliberate refresh and
    # passes, and an existing record that is itself a quick artifact
    # (marked {"quick": true}) may be refreshed either way.
    if args.out is None:
        from tools.records import guard_full_record
        guard_full_record(ap, quick=args.quick, out=out,
                          default_out=DEFAULT_OUT, flag="--out",
                          quick_key="quick")

    need = max(devices)
    import jax
    if len(jax.devices()) < need:
        if jax.default_backend() != "cpu" or os.environ.get(
                "MCS_WEAK_CHILD") == "1":
            raise SystemExit(
                f"need {need} devices, have {len(jax.devices())} on "
                f"{jax.default_backend()}")
        return _respawn_with_devices(need, argv)

    per_dev = args.per_device_clusters or (64 if args.quick else 4096)
    jobs_per = args.jobs_per or (20 if args.quick else 100)
    horizon = 60_000 if args.quick else 200_000

    t0 = time.time()
    cells = verify_parity_cells(devices, quick=args.quick)
    rows = run_curve(per_dev, jobs_per, horizon, devices,
                     repeats=args.repeats, chunk=100 if args.quick else 200)
    record = {
        "kind": "weak_scaling_record",
        "quick": bool(args.quick),
        "backend": jax.default_backend(),
        "devices_visible": len(jax.devices()),
        "host_cores": os.cpu_count(),
        "virtual_devices": jax.default_backend() == "cpu",
        "bottleneck": (
            f"{os.cpu_count()}-core CPU host time-slices "
            f"{len(jax.devices())} virtual devices: the efficiency column "
            "measures the sharded path's overhead at shape, not silicon "
            "scaling — the parity cells are what transfer to real "
            "multi-chip hardware unchanged"
            if jax.default_backend() == "cpu" else None),
        "per_device_clusters": per_dev,
        "rows": rows,
        "parity_cells": cells,
    }
    if not args.skip_market and not args.quick:
        # the market's DELAY sweeps cost ~30x the FIFO tick per cluster on
        # this backend (queue 256 / run 128 bounds), so the composition row
        # runs 1k clusters/device — full mesh, full exchange, honest wall
        record["market_row"] = run_market_row(min(per_dev, 1024),
                                              max(devices), jobs_per=40,
                                              horizon_ms=60_000)
    if not args.skip_record and not args.quick:
        # 10.49M jobs: 32768 clusters x 16 bursts x 20 jobs
        record["record"] = run_record(max(devices), per_dev, bursts=16,
                                      per_burst=20, interval_ms=180_000,
                                      ckpt=args.checkpoint,
                                      resume=args.resume)
    record["total_wall_s"] = round(time.time() - t0, 1)

    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"# record -> {out}", file=sys.stderr)
    print("| devices | clusters | jobs/s | efficiency | ticks exec/sim |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['n_devices']} | {r['clusters']} | {r['jobs_per_sec']} "
              f"| {r['efficiency_vs_linear']} | "
              f"{r['ticks_executed']}/{r['ticks_simulated']} |")
    if args.min_efficiency is not None:
        top = max(rows, key=lambda r: r["n_devices"])
        eff = top["efficiency_vs_linear"]
        if eff < args.min_efficiency:
            print(f"weak-scaling efficiency {eff} at "
                  f"{top['n_devices']} devices < floor "
                  f"{args.min_efficiency}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
