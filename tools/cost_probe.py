#!/usr/bin/env python
"""Per-tick cost decomposition for the bench configs' engine shapes.

For each shape this lowers one ``Engine.tick`` through XLA, pulls the
compiler's own cost model (``compiled.cost_analysis()``: flops, bytes
accessed), measures the real per-tick wall by timing a jitted
``lax.scan`` over N ticks, and derives the achieved HBM bandwidth. The
point is the evidence behind the no-Pallas design decision (README):
the tick is bandwidth/latency-bound small-integer work, not FLOPs —
arithmetic intensity is far below the MXU knee, so custom kernels would
be fighting the wrong bottleneck.

Run on the TPU (the default backend): ``python tools/cost_probe.py``.
Writes a table to stdout and JSON to tools/cost_probe.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def shapes():
    from multi_cluster_simulator_tpu.config import (
        MatchKind, PolicyKind, SimConfig, TraderConfig,
    )

    # (name, cfg, C, jobs_per, full_ticks) — jobs are scaled down by
    # n_ticks/full_ticks so the probe's per-tick load density matches the
    # bench config it models
    yield "headline_fifo_4k", SimConfig(
        policy=PolicyKind.FIFO, queue_capacity=8, max_running=32,
        max_arrivals=250, max_ingest_per_tick=8, parity=True, n_res=2,
        max_nodes=5, max_virtual_nodes=0), 4096, 250, 1570
    # both FFD sweep forms, so the JSON keeps carrying the serial-vs-wave
    # evidence the wave kernel's docstring cites (the serial row is the
    # latency-bound baseline; wave is the shipping default)
    yield "borg4k_ffd_serial", SimConfig(
        policy=PolicyKind.FFD, parity=False, max_placements_per_tick=16,
        queue_capacity=32, max_running=96, max_arrivals=250,
        max_ingest_per_tick=8, max_nodes=5, max_virtual_nodes=0,
        n_res=2, ffd_sweep="serial"), 4096, 250, 1600
    yield "borg4k_ffd_wave", SimConfig(
        policy=PolicyKind.FFD, parity=False, max_placements_per_tick=16,
        queue_capacity=32, max_running=96, max_arrivals=250,
        max_ingest_per_tick=8, max_nodes=5, max_virtual_nodes=0,
        n_res=2, ffd_sweep="wave"), 4096, 250, 1600
    yield "sinkhorn_market_4k", SimConfig(
        policy=PolicyKind.DELAY, parity=False, max_placements_per_tick=8,
        queue_capacity=256, max_running=128, max_arrivals=400,
        max_ingest_per_tick=16, max_nodes=5, max_virtual_nodes=4,
        trader=TraderConfig(enabled=True, matching=MatchKind.SINKHORN,
                            carve_mode="sane")), 4096, 400, 700


def probe(name, cfg, C, jobs_per, full_ticks, n_ticks=200):
    from multi_cluster_simulator_tpu.core.engine import Engine
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.workload.traces import uniform_stream

    import dataclasses

    jobs_probe = max(int(jobs_per * n_ticks / full_ticks), 8)
    cfg = dataclasses.replace(cfg, max_arrivals=jobs_probe)
    gpu_shape = cfg.n_res > 2
    specs = [uniform_cluster(c + 1, 5,
                             gpus=(8 if c % 2 == 0 else 0) if gpu_shape else 0)
             for c in range(C)]
    arr = uniform_stream(C, jobs_probe, n_ticks * cfg.tick_ms, max_cores=24,
                         max_mem=18_000, max_dur_ms=60_000, seed=7,
                         max_gpus=2 if cfg.n_res > 2 else 0,
                         gpu_frac=0.1 if cfg.n_res > 2 else 0.0)
    eng = Engine(cfg)
    state = init_state(cfg, specs)

    # compiler cost model for ONE tick (arrivals pre-packed once, exactly
    # as the scan path does at engine.py run())
    from multi_cluster_simulator_tpu.core.engine import pack_arrivals
    packed = pack_arrivals(arr)

    def one_tick(s):
        return eng._tick(s, packed, emit_io=False)[0]

    lowered = jax.jit(one_tick).lower(state)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_acc = float(cost.get("bytes accessed", 0.0))

    # measured per-tick wall from the scanned run (amortizes dispatch)
    f = eng.run_jit()
    out = jax.block_until_ready(f(state, arr, n_ticks))
    walls = []
    for _ in range(3):
        t0 = time.time()
        out = jax.block_until_ready(f(state, arr, n_ticks))
        walls.append(time.time() - t0)
    per_tick_ms = min(walls) / n_ticks * 1e3
    achieved_gbps = bytes_acc / (per_tick_ms / 1e3) / 1e9
    intensity = flops / bytes_acc if bytes_acc else float("nan")
    return {
        "config": name, "clusters": C, "backend": jax.default_backend(),
        "tick_flops": flops, "tick_bytes_accessed": bytes_acc,
        "arithmetic_intensity_flops_per_byte": round(intensity, 4),
        "measured_ms_per_tick": round(per_tick_ms, 3),
        "achieved_GB_per_s": round(achieved_gbps, 1),
        "placed": int(np.asarray(out.placed_total).sum()),
    }


def main():
    rows = [probe(*s) for s in shapes()]
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "cost_probe.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)
    hdr = ("config", "ms/tick", "GFLOP/tick", "MB/tick", "FLOP/byte",
           "achieved GB/s")
    print(f"{hdr[0]:<20}{hdr[1]:>9}{hdr[2]:>12}{hdr[3]:>10}{hdr[4]:>11}{hdr[5]:>15}")
    for r in rows:
        print(f"{r['config']:<20}{r['measured_ms_per_tick']:>9}"
              f"{r['tick_flops'] / 1e9:>12.3f}"
              f"{r['tick_bytes_accessed'] / 1e6:>10.1f}"
              f"{r['arithmetic_intensity_flops_per_byte']:>11}"
              f"{r['achieved_GB_per_s']:>15}")
    print(f"# wrote {out}")


if __name__ == "__main__":
    main()
