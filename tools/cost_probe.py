#!/usr/bin/env python
"""Per-tick cost decomposition for the bench configs' engine shapes.

For each shape this lowers one ``Engine.tick`` through XLA, pulls the
compiler's own cost model (``compiled.cost_analysis()``: flops, bytes
accessed), measures the real per-tick wall by timing a jitted
``lax.scan`` over N ticks, and derives the achieved HBM bandwidth. The
point is twofold:

- the evidence behind the no-Pallas design decision (README): the tick is
  bandwidth/latency-bound small-integer work, not FLOPs — arithmetic
  intensity is far below the MXU knee, so custom kernels would be fighting
  the wrong bottleneck;
- the measured bytes/tick ledger for the compact SoA state layout
  (core/compact.py, ``bench.py --compact``): each row carries a wide and a
  compact measurement plus the reduction, so "the narrow layout cuts the
  working set" is a number in the artifact, not an assertion.

The probe measures the TICK-INDEXED tick (pre-bucketed TickArrivals scan
inputs) — the path every scale bench config actually runs since the
streamed-pipeline PR; the windowed due-scan path is gone from the scale
drivers and would overstate arrival-stream bytes.

First-class CLI (runs in the CI bench-smoke job in --quick form):

  python -m tools.cost_probe [--out tools/cost_probe.json] [--quick]
                             [--configs NAME ...] [--compact both|off|on]

Exits nonzero on NaN/zero timings or byte counts (a roofline row that
silently degenerates would otherwise rot in the JSON unnoticed), and — when
both layouts are measured — on a compact layout that stops being
byte-smaller than the wide one.

The round-5 TPU record (cost-model bytes on the windowed-ingest tick, the
pre-rewrite methodology) is preserved verbatim in
tools/cost_probe_tpu_r05.json — README's no-Pallas roofline argument cites
it; tools/cost_probe.json is the live record this CLI regenerates, with
``backend``/``device`` stamped per row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def shapes(quick=False):
    from multi_cluster_simulator_tpu.config import (
        MatchKind, PolicyKind, SimConfig, TraderConfig,
    )

    scale = 16 if quick else 1

    # (name, cfg, C, jobs_per, full_ticks) — jobs are scaled down by
    # n_ticks/full_ticks so the probe's per-tick load density matches the
    # bench config it models
    yield "headline_fifo_4k", SimConfig(
        policy=PolicyKind.FIFO, queue_capacity=8, max_running=32,
        max_arrivals=250, max_ingest_per_tick=8, parity=True, n_res=2,
        max_nodes=5, max_virtual_nodes=0), 4096 // scale, 250, 1570
    # both FFD sweep forms, so the JSON keeps carrying the serial-vs-wave
    # evidence the wave kernel's docstring cites (the serial row is the
    # latency-bound baseline; wave is the shipping default)
    yield "borg4k_ffd_serial", SimConfig(
        policy=PolicyKind.FFD, parity=False, max_placements_per_tick=16,
        queue_capacity=32, max_running=96, max_arrivals=250,
        max_ingest_per_tick=8, max_nodes=5, max_virtual_nodes=0,
        n_res=2, ffd_sweep="serial"), 4096 // scale, 250, 1600
    yield "borg4k_ffd_wave", SimConfig(
        policy=PolicyKind.FFD, parity=False, max_placements_per_tick=16,
        queue_capacity=32, max_running=96, max_arrivals=250,
        max_ingest_per_tick=8, max_nodes=5, max_virtual_nodes=0,
        n_res=2, ffd_sweep="wave"), 4096 // scale, 250, 1600
    yield "sinkhorn_market_4k", SimConfig(
        policy=PolicyKind.DELAY, parity=False, max_placements_per_tick=8,
        queue_capacity=256, max_running=128, max_arrivals=400,
        max_ingest_per_tick=16, max_nodes=5, max_virtual_nodes=4,
        trader=TraderConfig(enabled=True, matching=MatchKind.SINKHORN,
                            carve_mode="sane")), 4096 // scale, 400, 700


def _cost(compiled) -> dict:
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    return cost


def probe_layout(cfg, n_ticks, specs, arr, plan):
    """One (shape, layout) measurement: XLA cost model of one tick-indexed
    tick + scanned-run wall timing. ``plan=None`` is the wide layout."""
    import jax

    from multi_cluster_simulator_tpu.core.compact import state_nbytes
    from multi_cluster_simulator_tpu.core.engine import (
        Engine, pack_arrivals_by_tick,
    )
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.utils.trace import total_drops

    eng = Engine(cfg)
    state = init_state(cfg, specs, plan=plan)
    ta = pack_arrivals_by_tick(arr, n_ticks, cfg.tick_ms)
    rows0 = jax.device_put(ta.rows[0])
    cnt0 = jax.device_put(ta.counts[0])

    def one_tick(s, rows, cnt):
        return eng._tick(s, (rows, cnt), emit_io=False, tick_indexed=True)[0]

    compiled = jax.jit(one_tick).lower(state, rows0, cnt0).compile()
    cost = _cost(compiled)
    flops = float(cost.get("flops", 0.0))
    # tick_bytes_accessed is the tick executable's BUFFER-BOUNDARY traffic
    # (argument + output bytes from the compiler's buffer assignment): the
    # bytes of resident state + scan inputs one tick must stream, which is
    # what the storage layout controls and what transfers across backends.
    # The raw cost-model sum is kept alongside (xla_cost_model_bytes): on
    # CPU it also counts the fuser's producer-duplication recomputation
    # (cheap mask chains cloned into every per-field consumer), which
    # overstates SoA layouts relative to real traffic; temp scratch is
    # reported separately for the same reason.
    cost_model_bytes = float(cost.get("bytes accessed", 0.0))
    note = None
    try:
        ma = compiled.memory_analysis()
        bytes_acc = float(ma.argument_size_in_bytes
                          + ma.output_size_in_bytes)
        temp_bytes = int(ma.temp_size_in_bytes)
    except Exception as e:  # jax builds without Compiled.memory_analysis
        bytes_acc, temp_bytes = cost_model_bytes, 0
        note = (f"memory_analysis unavailable ({type(e).__name__}); "
                "tick_bytes_accessed falls back to the cost-model sum")

    # measured per-tick wall from the scanned run (amortizes dispatch)
    f = eng.run_jit()
    out = jax.block_until_ready(f(state, ta, n_ticks))
    walls = []
    for _ in range(3):
        t0 = time.time()
        out = jax.block_until_ready(f(state, ta, n_ticks))
        walls.append(time.time() - t0)
    per_tick_ms = min(walls) / n_ticks * 1e3
    achieved_gbps = bytes_acc / (per_tick_ms / 1e3) / 1e9
    intensity = flops / bytes_acc if bytes_acc else float("nan")
    drops = total_drops(out)
    out_row = {
        "policy": eng.policy_provenance(),
        "tick_flops": flops, "tick_bytes_accessed": bytes_acc,
        "xla_cost_model_bytes": cost_model_bytes,
        "tick_temp_bytes": temp_bytes,
        "state_bytes": state_nbytes(state),
        "arithmetic_intensity_flops_per_byte": round(intensity, 4),
        "measured_ms_per_tick": round(per_tick_ms, 3),
        "achieved_GB_per_s": round(achieved_gbps, 1),
        "placed": int(np.asarray(out.placed_total).sum()),
        "drops": drops,
    }
    if note is not None:
        out_row["tick_bytes_note"] = note
    return out_row


def probe_fused_span(cfg, n_ticks, specs, arr, plan):
    """The fused-kernel instrument (kernels/fused_tick.py): measure the
    SPAN-level buffer-boundary collapse the kernel exists for, plus the
    fused full-tick wall as the same scanned-run timing the layout rows
    use.

    Under XLA the per-cluster prefix (the config's engaged span of
    faults->release->expire->ingest->schedule) is separate computations
    whose queue/runset/node columns cross a buffer boundary PER PHASE;
    fused, each column crosses once (one load + one store). The
    instrument makes that concrete: each engaged phase is compiled as its
    own executable and its argument+output bytes summed
    (``unfused_total`` — the per-phase boundary traffic), against the ONE
    fused-prefix executable's argument+output bytes (``fused``). The gate
    (``_check``) requires the fused number strictly lower. ``plan``
    should be the layout the comparison rows measured (compact when
    available — the acceptance bar is "below the compact unfused tick",
    not the easy wide one)."""
    import dataclasses

    import jax

    from multi_cluster_simulator_tpu.core.engine import (
        Engine, pack_arrivals_by_tick,
    )
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.kernels import fused_tick
    from multi_cluster_simulator_tpu.utils.trace import total_drops

    cfg_f = dataclasses.replace(cfg, fused="on")
    eng_f = Engine(cfg_f)
    state = init_state(cfg, specs, plan=plan)
    ta = pack_arrivals_by_tick(arr, n_ticks, cfg.tick_ms)
    rows0 = jax.device_put(ta.rows[0])
    cnt0 = jax.device_put(ta.counts[0])

    out = eng_f.fused_provenance()
    out["block_clusters"] = fused_tick.block_clusters(
        state.arr_ptr.shape[0], cfg.fused_block)
    try:
        out["span_bytes"] = fused_tick.span_boundary_bytes(
            cfg, state, rows0, cnt0, tick_indexed=True)
    except Exception as e:  # jax builds without Compiled.memory_analysis
        out["span_bytes_note"] = (f"memory_analysis unavailable "
                                  f"({type(e).__name__}); span gate skipped")

    # fused full-tick wall, same scanned-run methodology as probe_layout
    f = eng_f.run_jit()
    run_out = jax.block_until_ready(f(state, ta, n_ticks))
    walls = []
    for _ in range(3):
        t0 = time.time()
        run_out = jax.block_until_ready(f(state, ta, n_ticks))
        walls.append(time.time() - t0)
    out["measured_ms_per_tick"] = round(min(walls) / n_ticks * 1e3, 3)
    out["placed"] = int(np.asarray(run_out.placed_total).sum())
    out["drops"] = total_drops(run_out)
    return out


def probe(name, cfg, C, jobs_per, full_ticks, n_ticks=200, compact="both",
          fused="off"):
    import dataclasses

    import jax

    from multi_cluster_simulator_tpu.core.compact import derive_plan
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.workload.traces import uniform_stream

    jobs_probe = max(int(jobs_per * n_ticks / full_ticks), 8)
    cfg = dataclasses.replace(cfg, max_arrivals=jobs_probe)
    gpu_shape = cfg.n_res > 2
    specs = [uniform_cluster(c + 1, 5,
                             gpus=(8 if c % 2 == 0 else 0) if gpu_shape else 0)
             for c in range(C)]
    arr = uniform_stream(C, jobs_probe, n_ticks * cfg.tick_ms, max_cores=24,
                         max_mem=18_000, max_dur_ms=60_000, seed=7,
                         max_gpus=2 if cfg.n_res > 2 else 0,
                         gpu_frac=0.1 if cfg.n_res > 2 else 0.0)
    row = {"config": name, "clusters": C, "backend": jax.default_backend(),
           "device": jax.devices()[0].device_kind}
    plan = None
    if compact != "on":
        row.update(probe_layout(cfg, n_ticks, specs, arr, plan=None))
    if compact != "off":
        plan = derive_plan(cfg, specs, arr)
        crow = probe_layout(cfg, n_ticks, specs, arr, plan=plan)
        crow["plan"] = plan.describe()
        if compact == "on":
            row.update(crow)
            row["layout"] = "compact"
        else:
            if row["tick_bytes_accessed"]:
                crow["bytes_reduction"] = round(
                    1.0 - crow["tick_bytes_accessed"]
                    / row["tick_bytes_accessed"], 4)
                crow["state_bytes_reduction"] = round(
                    1.0 - crow["state_bytes"] / row["state_bytes"], 4)
            row["compact"] = crow
    if fused == "on":
        # the fused row rides the best unfused layout measured (compact
        # when available) — the acceptance bar for the kernel
        row["fused"] = probe_fused_span(cfg, n_ticks, specs, arr, plan)
    return row


def _check(rows, compact) -> list[str]:
    """Degenerate-measurement audit: the reasons this CLI exits nonzero."""
    problems = []
    for r in rows:
        fd = r.get("fused")
        if fd is not None:
            sb = fd.get("span_bytes")
            if sb is None:
                if "span_bytes_note" not in fd:
                    problems.append(f"{r['config']}: fused row carries no "
                                    "span_bytes measurement")
            elif sb["fused"] >= sb["unfused_total"]:
                problems.append(
                    f"{r['config']}: fused span streams MORE buffer-boundary "
                    f"bytes than the per-phase executables "
                    f"({sb['fused']} >= {sb['unfused_total']}) — the kernel "
                    "stopped collapsing the span")
            base = r.get("compact") or r
            if fd.get("placed") != base.get("placed"):
                problems.append(
                    f"{r['config']}: fused placed {fd.get('placed')} != "
                    f"unfused {base.get('placed')} — the kernel diverged")
            if fd.get("drops") and any(fd["drops"].values()):
                problems.append(
                    f"{r['config']}[fused]: nonzero drops {fd['drops']}")
        for scope, d in ((r["config"], r),
                         (r["config"] + "[compact]", r.get("compact", {}))):
            for k in ("measured_ms_per_tick", "tick_bytes_accessed"):
                v = d.get(k)
                if v is not None and (not np.isfinite(v) or v <= 0):
                    problems.append(f"{scope}: {k} degenerate ({v})")
            if d.get("drops") and any(d["drops"].values()):
                problems.append(f"{scope}: nonzero drops {d['drops']}")
        if compact == "both" and "compact" in r:
            if r["compact"].get("placed") != r.get("placed"):
                problems.append(
                    f"{r['config']}: compact placed {r['compact'].get('placed')} "
                    f"!= wide {r.get('placed')} — the layouts diverged")
            if r["compact"]["state_bytes"] >= r["state_bytes"]:
                problems.append(
                    f"{r['config']}: compact state is not byte-smaller "
                    f"({r['compact']['state_bytes']} >= {r['state_bytes']})")
            if (r["compact"].get("tick_bytes_accessed") or 0) >= \
                    (r.get("tick_bytes_accessed") or float("inf")):
                problems.append(
                    f"{r['config']}: compact tick streams MORE "
                    "buffer-boundary bytes than wide "
                    f"({r['compact']['tick_bytes_accessed']} >= "
                    f"{r['tick_bytes_accessed']})")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    default_out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "cost_probe.json")
    ap.add_argument("--out", default=default_out,
                    help="JSON output path (default: tools/cost_probe.json)")
    ap.add_argument("--quick", action="store_true",
                    help="1/16-scale cluster counts + short scans — the CI "
                         "bench-smoke variant (never write this over the "
                         "full-scale record; use --out)")
    ap.add_argument("--ticks", type=int, default=None,
                    help="probe scan length (default 200; quick 50)")
    ap.add_argument("--configs", nargs="*", default=None,
                    help="subset of shape names (default: all)")
    ap.add_argument("--compact", choices=("both", "off", "on"),
                    default="both",
                    help="state layouts to measure: wide + compact with the "
                         "per-shape reduction (both, default), wide only "
                         "(off), compact only (on)")
    ap.add_argument("--fused", choices=("off", "on"), default="off",
                    help="also measure the fused per-cluster prefix "
                         "(kernels/fused_tick.py, the engaged span of "
                         "phases faults->schedule) on each shape: "
                         "per-phase executable boundary bytes vs the ONE "
                         "fused-prefix executable's, plus the fused "
                         "full-tick wall — exits nonzero unless the fused "
                         "prefix streams strictly fewer bytes and places "
                         "identical work")
    args = ap.parse_args(argv)
    # same discipline as bench.py's quick-vs-full results files: smoke
    # shapes must never clobber the committed full-scale record (shared
    # guard: tools/records.py — weak_scaling rides the same helper)
    from tools.records import guard_full_record
    guard_full_record(ap, quick=args.quick, out=args.out,
                      default_out=default_out, flag="--out")
    n_ticks = args.ticks or (50 if args.quick else 200)

    all_shapes = list(shapes(quick=args.quick))
    known = [s[0] for s in all_shapes]
    if args.configs:
        unknown = set(args.configs) - set(known)
        if unknown:
            ap.error(f"unknown configs {sorted(unknown)}; known: {known}")
        all_shapes = [s for s in all_shapes if s[0] in args.configs]

    import jax

    print(f"# backend={jax.default_backend()} "
          f"device={jax.devices()[0].device_kind} "
          f"n_devices={len(jax.devices())} jax={jax.__version__}",
          file=sys.stderr)
    rows = [probe(*s, n_ticks=n_ticks, compact=args.compact,
                  fused=args.fused)
            for s in all_shapes]
    with open(args.out, "w") as f:
        json.dump(rows, f, indent=2)
    hdr = ("config", "ms/tick", "GFLOP/tick", "MB/tick", "FLOP/byte",
           "achieved GB/s", "compact MB/tick", "bytes win", "fused span win")
    print(f"{hdr[0]:<20}{hdr[1]:>9}{hdr[2]:>12}{hdr[3]:>10}{hdr[4]:>11}"
          f"{hdr[5]:>15}{hdr[6]:>17}{hdr[7]:>11}{hdr[8]:>16}")
    for r in rows:
        c = r.get("compact", {})
        win = (f"{c['bytes_reduction'] * 100:.1f}%"
               if "bytes_reduction" in c else "-")
        cmb = (f"{c['tick_bytes_accessed'] / 1e6:.1f}"
               if c.get("tick_bytes_accessed") else "-")
        sb = r.get("fused", {}).get("span_bytes")
        fwin = f"{sb['reduction'] * 100:.1f}%" if sb else "-"
        print(f"{r['config']:<20}{r.get('measured_ms_per_tick', '-'):>9}"
              f"{r.get('tick_flops', 0) / 1e9:>12.3f}"
              f"{r.get('tick_bytes_accessed', 0) / 1e6:>10.1f}"
              f"{r.get('arithmetic_intensity_flops_per_byte', '-'):>11}"
              f"{r.get('achieved_GB_per_s', '-'):>15}"
              f"{cmb:>17}{win:>11}{fwin:>16}")
    print(f"# wrote {args.out}")
    problems = _check(rows, args.compact)
    for p in problems:
        print(f"# PROBLEM: {p}", file=sys.stderr)
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
