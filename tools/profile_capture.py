#!/usr/bin/env python
"""Profile capture: a jax.profiler trace around a bench-shaped run plus a
per-phase cost table from the engine's own phase-prefix ablation.

Supersedes the old ``tools/phase_probe.py`` (which timed hand-copied phase
closures that silently rotted as the engine evolved): the ablation here
runs the REAL tick body truncated after the first k phases
(``Engine.run_prefix`` with ``phase_limit=k`` — obs.profile.TICK_PHASES
order), so phase k's cost at shape is wall(prefix k) - wall(prefix k-1) on
whatever config is being profiled, policies and trader included. The
trace capture is orthogonal: phases inside the tick are named scopes
(``tick.<phase>``), so the .xplane.pb/.trace.json.gz artifact attributes
device time per phase in any trace viewer; the dispatch sites are
TraceAnnotations on the host track.

Usage:
  python -m tools.profile_capture --config headline --quick --out DIR
  python -m tools.profile_capture --config delay --ticks 200 --no-trace

Exit is nonzero if the per-phase table is empty/NaN or (unless --no-trace)
the trace session produced no artifact — the CI smoke gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _build(config: str, quick: bool):
    """(cfg, specs, arrivals, n_ticks) for a profile shape. These mirror
    bench.py's configs at profile-friendly scale — the point is the REAL
    tick structure (policy pass, trader on/off), not a record."""
    from multi_cluster_simulator_tpu.config import (
        PolicyKind, SimConfig, TraderConfig,
    )
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.workload.traces import uniform_stream

    if config == "headline":
        C = 256 if quick else 4096
        cfg = SimConfig(policy=PolicyKind.FIFO, queue_capacity=8,
                        max_running=32, max_arrivals=250,
                        max_ingest_per_tick=8, parity=True, n_res=2,
                        max_nodes=5, max_virtual_nodes=0)
        specs = [uniform_cluster(c + 1, 5) for c in range(C)]
        arrivals = uniform_stream(C, 250, 1_500_000, max_cores=8,
                                  max_mem=6_000, max_dur_ms=60_000, seed=9)
    elif config == "delay":
        C = 64 if quick else 512
        cfg = SimConfig(policy=PolicyKind.DELAY, queue_capacity=64,
                        max_running=128, max_arrivals=250, parity=True,
                        n_res=2, max_nodes=5, max_virtual_nodes=0)
        specs = [uniform_cluster(c + 1, 5) for c in range(C)]
        arrivals = uniform_stream(C, 250, 1_500_000, max_cores=8,
                                  max_mem=6_000, max_dur_ms=60_000, seed=9)
    elif config == "trader":
        C = 16 if quick else 64
        cfg = SimConfig(policy=PolicyKind.DELAY, queue_capacity=64,
                        max_running=128, max_arrivals=250, parity=False,
                        n_res=3, max_nodes=5, max_virtual_nodes=4,
                        trader=TraderConfig(enabled=True))
        specs = [uniform_cluster(c + 1, 5) for c in range(C)]
        arrivals = uniform_stream(C, 250, 1_500_000, max_cores=8,
                                  max_mem=6_000, max_dur_ms=60_000, seed=9)
    else:
        raise SystemExit(f"unknown --config {config}")
    return cfg, specs, arrivals


def phase_table(cfg, specs, arrivals, n_ticks: int, repeats: int = 3):
    """Per-phase ms/tick via cumulative phase-prefix ablation over the
    real tick body, plus per-phase bytes from the SAME ablation: the XLA
    cost-model bytes of a one-tick prefix-k executable minus prefix-(k-1)'s
    is what running phase k adds to the tick's memory traffic at this
    shape. Returns [{phase, cum_ms_per_tick, ms_per_tick, fraction,
    prefix_bytes_delta}] in TICK_PHASES order, inactive phases (trader
    off, no borrowing) included at ~0 by construction — the two columns
    are the fusion-candidate evidence (``fusion_ranking`` below)."""
    import jax

    from multi_cluster_simulator_tpu.core.engine import (
        Engine, pack_arrivals_by_tick,
    )
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.obs.profile import TICK_PHASES

    eng = Engine(cfg)
    state0 = init_state(cfg, specs)
    ta = pack_arrivals_by_tick(arrivals, n_ticks, cfg.tick_ms)
    rows0 = jax.device_put(ta.rows[0])
    cnt0 = jax.device_put(ta.counts[0])

    def timed(limit):
        fn = jax.jit(eng.run_prefix, static_argnums=(2, 3))
        out = jax.block_until_ready(fn(state0, ta, n_ticks, limit))  # compile
        walls = []
        for _ in range(repeats):
            t0 = time.time()
            out = fn(state0, ta, n_ticks, limit)
            np.asarray(out.t)  # force a host read inside the timer
            walls.append(time.time() - t0)
        return min(walls) / n_ticks * 1e3  # ms/tick

    def prefix_bytes(limit):
        # one-tick prefix executable's cost-model bytes (compile only):
        # the per-phase delta is the ablation's bytes column
        def one_tick(s, rows, cnt):
            return eng._tick(s, (rows, cnt), emit_io=False,
                             tick_indexed=True, phase_limit=limit)[0]

        try:
            cost = jax.jit(one_tick).lower(state0, rows0,
                                           cnt0).compile().cost_analysis()
            if isinstance(cost, list):  # older jax returns [dict]
                cost = cost[0] if cost else {}
            return float(cost.get("bytes accessed", 0.0))
        except Exception:  # pragma: no cover - cost model unavailable
            return float("nan")

    cum = [timed(k) for k in range(len(TICK_PHASES) + 1)]  # k=0: carry only
    cum_b = [prefix_bytes(k) for k in range(len(TICK_PHASES) + 1)]
    full = cum[-1]
    rows = []
    for i, name in enumerate(TICK_PHASES):
        per = cum[i + 1] - cum[i]
        db = cum_b[i + 1] - cum_b[i]
        rows.append({"phase": name,
                     "cum_ms_per_tick": round(cum[i + 1], 4),
                     "ms_per_tick": round(per, 4),
                     "fraction": round(per / full, 4) if full > 0 else 0.0,
                     "prefix_bytes_delta": (int(db) if np.isfinite(db)
                                            else None)})
    rows.append({"phase": "(carry/clock)", "cum_ms_per_tick": round(cum[0], 4),
                 "ms_per_tick": round(cum[0], 4),
                 "fraction": round(cum[0] / full, 4) if full > 0 else 0.0,
                 "prefix_bytes_delta": (int(cum_b[0])
                                        if np.isfinite(cum_b[0]) else None)})
    return rows, full


def fusion_ranking(rows, span=()):
    """The machine-readable fusion-candidate ranking: tick phases ordered
    by wall share, each with its ablation bytes delta and whether it sits
    inside the engaged fused prefix — the recorded provenance behind
    kernels/fused_tick.FUSED_SPAN's phase choice (the whole per-cluster-
    local prefix, phases 1-5), so the choice is a measured artifact, not
    folklore. Phases OUTSIDE the span are the collective seams (borrow/
    snapshot/trade): their bytes deltas are what the fusion boundary
    still pays per tick, surfaced separately by ``seam_bytes``."""
    cand = [dict(r, in_fused_prefix=r["phase"] in span) for r in rows
            if not r["phase"].startswith("(")]
    return sorted(cand, key=lambda r: -r["fraction"])


def seam_bytes(rows, span):
    """Per-phase ablation bytes of the phases left OUTSIDE the fused
    prefix — the cross-cluster exchange seams the kernel boundary was
    drawn at. The fused prefix collapses its interior boundaries; these
    are the ones that remain (kernels.span_boundary_bytes measures the
    collapsed side of the same ledger)."""
    return {r["phase"]: r["prefix_bytes_delta"] for r in rows
            if not r["phase"].startswith("(") and r["phase"] not in span}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="headline",
                    choices=("headline", "delay", "trader"))
    ap.add_argument("--quick", action="store_true",
                    help="small shapes (the CI smoke)")
    ap.add_argument("--ticks", type=int, default=None,
                    help="ticks per timed scan (default 50 quick / 400)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="trace + table output dir "
                         "(default ./profile_capture)")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the jax.profiler capture; only the table")
    ap.add_argument("--fused", choices=("off", "on", "auto"), default="off",
                    help="profile the engine with the fused per-cluster "
                         "prefix kernel engaged (kernels/fused_tick.py, "
                         "phases faults->schedule; the resolved per-config "
                         "span lands in the table JSON either way). "
                         "Ablation prefixes that truncate INSIDE the span "
                         "fall back to the unfused body — the per-phase "
                         "columns stay honest")
    args = ap.parse_args()

    import dataclasses

    import jax

    from multi_cluster_simulator_tpu.core.engine import (
        Engine, pack_arrivals_by_tick,
    )
    from multi_cluster_simulator_tpu.core.state import init_state
    from multi_cluster_simulator_tpu.obs import profile as prof

    n_ticks = args.ticks or (50 if args.quick else 400)
    out_dir = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "profile_capture")
    os.makedirs(out_dir, exist_ok=True)
    cfg, specs, arrivals = _build(args.config, args.quick)
    cfg = dataclasses.replace(cfg, fused=args.fused)
    fused_prov = Engine(cfg).fused_provenance()
    print(f"# profile_capture: config={args.config} clusters={len(specs)} "
          f"ticks={n_ticks} backend={jax.default_backend()} "
          f"fused={args.fused}", file=sys.stderr)

    # ---- per-phase cost table (phase-prefix ablation on the real tick) --
    rows, full = phase_table(cfg, specs, arrivals, n_ticks,
                             repeats=args.repeats)
    if not rows or not np.isfinite(full) or full <= 0:
        print("profile_capture: per-phase table empty or degenerate",
              file=sys.stderr)
        return 1
    span = fused_prov.get("span", [])
    ranking = fusion_ranking(rows, span)
    seams = seam_bytes(rows, span)
    width = max(len(r["phase"]) for r in rows)
    print(f"{'phase':{width}s}  ms/tick   cum      frac   ablation MB")
    for r in rows:
        db = r.get("prefix_bytes_delta")
        mb = f"{db / 1e6:8.2f}" if db is not None else "       -"
        print(f"{r['phase']:{width}s}  {r['ms_per_tick']:7.4f}  "
              f"{r['cum_ms_per_tick']:7.4f}  {r['fraction']:6.1%}  {mb}")
    print("# fusion candidates (wall share desc): "
          + ", ".join(f"{r['phase']}={r['fraction']:.1%}"
                      + ("*" if r["in_fused_prefix"] else "")
                      for r in ranking[:4])
          + "  (* = inside the engaged fused prefix)", file=sys.stderr)
    print("# collective seams outside the prefix: "
          + (", ".join(f"{k}={v / 1e6:.2f}MB" if v is not None else f"{k}=-"
                       for k, v in seams.items()) or "(none)"),
          file=sys.stderr)

    # ---- profiler trace around one full-tick run ------------------------
    artifacts = []
    if not args.no_trace:
        eng = Engine(cfg)
        state0 = init_state(cfg, specs)
        ta = pack_arrivals_by_tick(arrivals, n_ticks, cfg.tick_ms)
        fn = jax.jit(eng.run, static_argnums=(2,))
        jax.block_until_ready(fn(state0, ta, n_ticks))  # compile OUTSIDE
        prof.start_trace(out_dir)
        try:
            with prof.annotate_dispatch("profile_capture", ticks=n_ticks):
                out = fn(state0, ta, n_ticks)
                np.asarray(out.t)
        finally:
            prof.stop_trace()
        artifacts = prof.trace_artifacts(out_dir)
        if not artifacts:
            print("profile_capture: trace session produced no artifact",
                  file=sys.stderr)
            return 1
        print(f"# trace: {len(artifacts)} file(s) under {out_dir}",
              file=sys.stderr)

    table_path = os.path.join(out_dir, f"phase_table_{args.config}.json")
    with open(table_path, "w") as f:
        json.dump({"config": args.config, "clusters": len(specs),
                   "ticks": n_ticks, "backend": jax.default_backend(),
                   "quick": args.quick, "full_ms_per_tick": round(full, 4),
                   "fused": fused_prov,
                   "phases": rows, "fusion_ranking": ranking,
                   "collective_seam_bytes": seams,
                   "trace_artifacts": artifacts}, f, indent=2)
    print(f"# table: {table_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
