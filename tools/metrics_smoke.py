#!/usr/bin/env python
"""CI gate for the serving observability surface: run a real localhost
serving window, scrape /metrics and /healthz over HTTP, and assert

- the Prometheus exposition PARSES (obs/promtext.py, strict),
- the core gauges are present and nonzero,
- the scraped gauge values MATCH the OTLP Meter export for the same
  window (the two surfaces render from one store — this pins it on a
  live process, not just in unit tests),
- /healthz answers 200 while the loops are alive.

Exit is nonzero on any violation. Runs on the CPU backend in-process
(the serving child pattern)."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
    from multi_cluster_simulator_tpu.core.spec import uniform_cluster
    from multi_cluster_simulator_tpu.obs.promtext import (
        parse_prometheus, scalar_samples,
    )
    from multi_cluster_simulator_tpu.services import httpd
    from multi_cluster_simulator_tpu.services.scheduler_host import (
        job_to_json,
    )
    from multi_cluster_simulator_tpu.services.serving import ServingScheduler

    C = 4
    cfg = SimConfig(policy=PolicyKind.FIFO, parity=True, n_res=2,
                    queue_capacity=64, max_running=128, max_arrivals=64,
                    max_ingest_per_tick=16, max_nodes=5,
                    max_virtual_nodes=0)
    specs = [uniform_cluster(c + 1, 5) for c in range(C)]
    s = ServingScheduler("metrics-smoke", specs, cfg, speed=200.0, window=4,
                         pacer=True, warm_k=(16,), k_cap=64,
                         max_staged=10 ** 5)
    s.start()
    try:
        # /healthz while alive
        code, body = httpd.get(s.url + "/healthz")
        health = json.loads(body)
        assert code == 200, f"/healthz -> {code}: {body!r}"
        assert health["status"] == "ok", health

        # drive real traffic through the batched front door, honoring
        # the 503 retry quotes (the wire contract back-pressured clients
        # follow — bench.py --serving drives the same loop)
        import time

        rng = np.random.default_rng(3)
        total = 0
        for batch_i in range(8):
            batch = [{**job_to_json(batch_i * 100 + i + 1,
                                    int(rng.integers(1, 4)),
                                    int(rng.integers(100, 2000)),
                                    int(rng.integers(500, 2000))),
                      "Cluster": int(rng.integers(0, C))}
                     for i in range(32)]
            total += len(batch)
            deadline = time.time() + 60
            while batch:
                code, body = httpd.post_json(s.url + "/submitBatch", batch)
                if code == 200:
                    break
                assert code == 503, f"submitBatch -> {code}"
                assert time.time() < deadline, "retry loop stuck on 503s"
                e = json.loads(body)
                batch = [batch[k] for k in e["RejectedIdx"]]
                time.sleep(e["RetryAfterMs"] / 1000.0)
        deadline = time.time() + 60
        while time.time() < deadline:
            if s.snapshot.placed >= total and s.snapshot.staged_jobs == 0:
                break
            time.sleep(0.05)
        assert s.snapshot.placed >= total, (
            f"only {s.snapshot.placed}/{total} placed")

        # freeze the loops so the scrape and the OTLP read see ONE
        # window (the pacer keeps dispatching empty windows otherwise);
        # quiesce also flips /healthz to 503 — assert that too
        s.quiesce()
        code, body = httpd.get(s.url + "/healthz")
        assert code == 503, f"/healthz after quiesce -> {code} ({body!r})"

        # scrape + parse + gauge presence
        code, text = httpd.get(s.url + "/metrics")
        assert code == 200, f"/metrics -> {code}"
        parsed = parse_prometheus(text.decode())
        flat = scalar_samples(parsed)
        # OTLP keeps the dashed service name; the exposition applies the
        # standard OTLP->Prometheus name translation (telemetry.
        # prom_metric_name) — compare through it
        from multi_cluster_simulator_tpu.services.telemetry import (
            prom_metric_name,
        )
        core = ["metrics-smoke_placed_total", "metrics-smoke_jobs_submitted",
                "metrics-smoke_dispatches", "metrics-smoke_ticks_dispatched",
                "metrics-smoke_obs_ticks", "metrics-smoke_obs_placed"]
        for name in core:
            pn = prom_metric_name(name)
            assert pn in flat, f"core gauge {pn} missing from /metrics"
            assert flat[pn] > 0, f"core gauge {pn} is zero"
        assert flat[prom_metric_name("metrics-smoke_obs_placed")] == total, (
            "device plane placement count diverged from the submitted total")

        # the OTLP export and the scrape must report identical numbers
        otlp = {}
        for rm in s.meter.otlp_payload()["resourceMetrics"]:
            for sm in rm["scopeMetrics"]:
                for m in sm["metrics"]:
                    arm = m.get("sum") or m.get("gauge")
                    if arm:
                        otlp[m["name"]] = arm["dataPoints"][0]["asDouble"]
        for name in core:
            assert name in otlp, f"{name} missing from the OTLP payload"
            assert otlp[name] == flat[prom_metric_name(name)], (
                f"surface mismatch for {name}: "
                f"/metrics={flat[prom_metric_name(name)]} OTLP={otlp[name]}")
        print(f"# metrics_smoke OK: {total} jobs, "
              f"{len(flat)} scalar samples parsed, "
              f"{len(core)} core gauges nonzero and OTLP-consistent",
              file=sys.stderr)
    finally:
        s.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
