"""Offline developer tools (sample generation, cost probes). A package so
bench.py and the tests can import the deterministic Borg-sample generator
without duplicating file-path module loading."""
