#!/usr/bin/env python
"""Generate assets/borg2019_sample.jsonl.gz — a slice in the exact raw
Borg-2019 ``instance_events`` schema (see workload/borg.py) with synthetic
values. This offline image has zero egress, so no rows of the real
clusterdata-2019 release can be vendored; this sample exists to exercise the
full parse -> join -> replay path byte-identically to how a real slice
would flow, and the bench labels its provenance honestly
(bench.py borg_replay detail.trace_provenance).

Value shapes follow the published characterizations of the 2019 trace
(heavy-tailed normalized cpu/memory requests, lognormal task durations,
diurnal submission intensity) without claiming to BE trace data.

The sample is NOT committed (it is a ~35 MB deterministic artifact — the
round-4 advisor flagged stacking regenerated binaries in git history);
``generate()`` builds it on first use from a fixed seed, so bench.py and
the tests call ``ensure()`` and get the identical file everywhere.

Deterministic: fixed seed, fixed gzip mtime, vectorized draws in a fixed
order. Force a rebuild with ``python tools/make_borg_sample.py``.
"""

import gzip
import json
import os

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "assets", "borg2019_sample.jsonl.gz")

# ~150k collections x ~7 instances ~= 1.05M replayable instances: fills the
# BASELINE config's 4,096 clusters at ~250 jobs each — the same load density
# as the borg4k synthetic, so the replay measures the engine, not a sparse
# trace (the round-4 245k-instance sample left borg_replay at 59 jobs/cluster
# and 112k jobs/s, 3x under borg4k purely on arrival density). A 4x sample
# was tried for a longer timed window and rejected: its tick-bucketed
# arrival tensor alone needs ~6.7 GB of HBM (bench.py borg_replay docstring)
N_COLLECTIONS = 150_000
MEAN_INSTANCES = 6  # geometric; real collections are heavy-tailed too
SPAN_US = 6 * 3600 * 1_000_000  # six trace-hours


def generate(out: str = OUT) -> str:
    """Build the sample at ``out``. Vectorized equivalent of drawing, per
    collection: submit-time bump center, a shared cpus/memory request, and
    per-instance exponential submit offsets, queueing delays, lognormal
    durations, and terminal event types; 3% of instances drop their
    SCHEDULE/terminal rows to exercise the parser's incomplete-lifecycle
    skip. Events are globally time-sorted like the real release files."""
    rng = np.random.Generator(np.random.PCG64(2019))

    n_inst = 1 + rng.geometric(1.0 / MEAN_INSTANCES, size=N_COLLECTIONS)
    total = int(n_inst.sum())
    coll_ids = 330_000_000_000 + np.arange(N_COLLECTIONS, dtype=np.int64) * 1_009
    bump = rng.choice([0.3, 0.75], p=[0.6, 0.4], size=N_COLLECTIONS)
    t_sub0 = np.clip(rng.normal(bump, 0.18), 0.0, 0.98) * SPAN_US
    cpus = np.clip(np.exp(rng.normal(-3.2, 1.1, size=N_COLLECTIONS)),
                   1e-4, 1.0).round(6)
    memn = np.clip(cpus * np.exp(rng.normal(0.1, 0.8, size=N_COLLECTIONS)),
                   1e-5, 1.0).round(6)

    # expand per-collection columns to per-instance rows
    coll_of = np.repeat(np.arange(N_COLLECTIONS), n_inst)
    inst_idx = np.concatenate([np.arange(n) for n in n_inst])
    t_sub = (t_sub0[coll_of] + rng.exponential(2e6, size=total)).astype(np.int64)
    queue_us = rng.exponential(3e6, size=total).astype(np.int64)
    dur_us = np.clip(np.exp(rng.normal(np.log(300e6), 1.4, size=total)),
                     5e6, SPAN_US).astype(np.int64)
    u_term = rng.random(size=total)
    u_term2 = rng.random(size=total)
    term = np.where(u_term < 0.88, "FINISH",
                    np.where(u_term2 < 0.7, "KILL", "EVICT"))
    incomplete = rng.random(size=total) < 0.03
    sched = t_sub + queue_us
    t_end = sched + dur_us

    # assemble (time, kind, row-index) for the global sort; kinds:
    # 0=SUBMIT (all), 1=SCHEDULE, 2=terminal (complete lifecycles only)
    comp = np.flatnonzero(~incomplete)
    times = np.concatenate([t_sub, sched[comp], t_end[comp]])
    kinds = np.concatenate([np.zeros(total, np.int8),
                            np.full(len(comp), 1, np.int8),
                            np.full(len(comp), 2, np.int8)])
    rows = np.concatenate([np.arange(total), comp, comp])
    order = np.argsort(times, kind="stable")

    cid_s = coll_ids[coll_of]
    out_dir = os.path.dirname(out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    # write to a temp path and os.replace: an interrupted generation must
    # never leave a truncated gzip at the final path (ensure() only checks
    # existence), and concurrent first runs must not interleave writes
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "wb") as raw:
        # filename="" keeps the gzip FNAME header empty — writing through a
        # PID-suffixed tmp path must not leak into the bytes (the sample is
        # byte-deterministic everywhere)
        with gzip.GzipFile(filename="", fileobj=raw, mode="wb", mtime=0) as gz:
            buf = []
            for t, k, r in zip(times[order], kinds[order], rows[order]):
                r = int(r)
                if k == 0:
                    line = json.dumps(
                        {"time": int(t), "type": "SUBMIT",
                         "collection_id": int(cid_s[r]),
                         "instance_index": int(inst_idx[r]),
                         "resource_request": {"cpus": float(cpus[coll_of[r]]),
                                              "memory": float(memn[coll_of[r]])}},
                        separators=(",", ":"))
                else:
                    line = json.dumps(
                        {"time": int(t),
                         "type": "SCHEDULE" if k == 1 else str(term[r]),
                         "collection_id": int(cid_s[r]),
                         "instance_index": int(inst_idx[r])},
                        separators=(",", ":"))
                buf.append(line)
                if len(buf) >= 100_000:
                    gz.write(("\n".join(buf) + "\n").encode())
                    buf = []
            if buf:
                gz.write(("\n".join(buf) + "\n").encode())
    os.replace(tmp, out)
    with open(_meta_path(out), "w") as f:
        json.dump(_params(), f)
    return out


def _params() -> dict:
    """Generator fingerprint: a stale on-disk sample (the file is
    gitignored and survives generator re-parameterizations — this build
    itself grew it 36k->150k collections) must be regenerated, not reused."""
    return {"n_collections": N_COLLECTIONS, "mean_instances": MEAN_INSTANCES,
            "span_us": SPAN_US, "seed": 2019, "format": 2}


def _meta_path(out: str) -> str:
    return out + ".meta.json"


def ensure(out: str = OUT) -> str:
    """Generate the sample only if absent or generated with different
    parameters — the bench/test entry point."""
    fresh = False
    if os.path.exists(out):
        try:
            with open(_meta_path(out)) as f:
                fresh = json.load(f) == _params()
        except (OSError, ValueError):
            fresh = False
    if not fresh:
        import sys
        print(f"# generating {out} (~3M events, one-time, <1 min)...",
              file=sys.stderr, flush=True)
        generate(out)
    return out


if __name__ == "__main__":
    path = generate()
    print(f"{path}: {os.path.getsize(path)} bytes")
