#!/usr/bin/env python
"""Generate assets/borg2019_sample.jsonl.gz — a slice in the exact raw
Borg-2019 ``instance_events`` schema (see workload/borg.py) with synthetic
values. This offline image has zero egress, so no rows of the real
clusterdata-2019 release can be vendored; this sample exists to exercise the
full parse -> join -> replay path byte-identically to how a real slice
would flow, and the bench labels its provenance honestly
(bench.py borg_replay detail.trace_provenance).

Value shapes follow the published characterizations of the 2019 trace
(heavy-tailed normalized cpu/memory requests, lognormal task durations,
diurnal submission intensity) without claiming to BE trace data.

Deterministic: fixed seed, fixed gzip mtime. Regenerate with
``python tools/make_borg_sample.py``.
"""

import gzip
import json
import os

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "assets", "borg2019_sample.jsonl.gz")

# ~36k collections x ~7 instances ~= 250k replayable instances: enough to
# fill the BASELINE config's 4,096 clusters at >=48 jobs each, so the
# graded replay runs at full cluster count with a multi-second wall
# (123k-event round-4 v1 filled only 512 clusters in 0.7s — too short to
# time meaningfully against tunnel noise)
N_COLLECTIONS = 36_000
MEAN_INSTANCES = 6  # geometric; real collections are heavy-tailed too
SPAN_US = 6 * 3600 * 1_000_000  # six trace-hours


def main():
    rng = np.random.Generator(np.random.PCG64(2019))
    rows = []
    for coll in range(N_COLLECTIONS):
        coll_id = 330_000_000_000 + coll * 1_009  # id shape like the release
        n_inst = 1 + rng.geometric(1.0 / MEAN_INSTANCES)
        # diurnal-ish submission: two gaussian bumps over the span
        bump = rng.choice([0.3, 0.75], p=[0.6, 0.4])
        t_sub0 = np.clip(rng.normal(bump, 0.18), 0.0, 0.98) * SPAN_US
        cpus = float(np.clip(np.exp(rng.normal(-3.2, 1.1)), 1e-4, 1.0))
        memn = float(np.clip(cpus * np.exp(rng.normal(0.1, 0.8)), 1e-5, 1.0))
        for idx in range(int(n_inst)):
            t_sub = int(t_sub0 + rng.exponential(2e6))
            queue_us = int(rng.exponential(3e6))
            dur_us = int(np.clip(np.exp(rng.normal(np.log(300e6), 1.4)),
                                 5e6, SPAN_US))
            sched = t_sub + queue_us
            term = "FINISH" if rng.random() < 0.88 else \
                ("KILL" if rng.random() < 0.7 else "EVICT")
            rows.append({"time": t_sub, "type": "SUBMIT",
                         "collection_id": coll_id, "instance_index": idx,
                         "resource_request": {"cpus": round(cpus, 6),
                                              "memory": round(memn, 6)}})
            if rng.random() < 0.03:  # incomplete lifecycle (parser skips)
                continue
            rows.append({"time": sched, "type": "SCHEDULE",
                         "collection_id": coll_id, "instance_index": idx})
            rows.append({"time": sched + dur_us, "type": term,
                         "collection_id": coll_id, "instance_index": idx})
    rows.sort(key=lambda r: r["time"])
    payload = "".join(json.dumps(r, separators=(",", ":")) + "\n" for r in rows)
    with open(OUT, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as gz:
            gz.write(payload.encode())
    print(f"{OUT}: {len(rows)} events")


if __name__ == "__main__":
    main()
