#!/usr/bin/env python
"""Train a tiny policy head end-to-end on device — the proof the RL loop
closes (ISSUE 7 / ROADMAP item 2).

The head is one linear map ``W: [n_obs] -> [N_JOB_CLASSES *
N_DEVICE_TYPES]`` from the mean-pooled cluster observation to the rl
action matrix (policies' ``rl_scores`` leaf). Training is evolution
strategies — the natural fit for a discrete integer simulator with no
gradient through the tick: every env instance in the batch rolls out one
perturbed head ``W + sigma * eps_i`` for a full episode (its own PRNG
stream drawing its own arrivals), and the update moves ``W`` along the
return-weighted perturbation mean. One jitted function per iteration does
B full episodes — rollouts, rewards, auto-resets, and the update never
leave the device; the host loop only reads back one scalar per iteration
to print.

Run: ``python tools/train_env_demo.py [--iters N] [--envs B]``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def train(iters=10, n_envs=32, n_clusters=4, episode_ticks=20, lr=0.5,
          sigma=0.3, seed=0, rate=2.0, reward="neg_mean_wait",
          checkpoint=None, resume=False, faults=None):
    """Run ``iters`` ES iterations; returns a dict with the per-iteration
    mean returns, the trained head, and timing. Deterministic for a fixed
    seed (common random numbers: every iteration reuses the same per-env
    reset keys, so fitness differences come from the head, not the
    draw).

    ``checkpoint`` saves a per-iteration training bundle (the preemption
    plane's format, core/preempt.py): the reset EnvState batch — which
    carries every env's fault-plane churn streams (``faults.reseed``) and
    PRNG state — the ES optimizer state (the head ``W`` + the ES key),
    and the per-iteration returns. ``resume=True`` continues a killed run
    bit-identically: the loaded bundle replaces BOTH the optimizer state
    and the reset batch (never re-derived — the test pins that the
    per-env fault streams survive the round-trip), and the remaining
    iterations produce exactly the uninterrupted run's head and returns
    (tests/test_preempt.py). ``faults`` is an optional FaultConfig for
    churn-during-training."""
    import jax
    import jax.numpy as jnp

    from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
    from multi_cluster_simulator_tpu.envs import ClusterEnv, StreamGen
    from multi_cluster_simulator_tpu.ops import fields as F
    from multi_cluster_simulator_tpu.policies import PolicySet

    cfg = SimConfig(policy=PolicyKind.FIFO, parity=True, n_res=2,
                    queue_capacity=16, max_running=64, max_arrivals=8,
                    max_ingest_per_tick=8, max_nodes=5, max_virtual_nodes=0)
    if faults is not None:
        import dataclasses
        cfg = dataclasses.replace(cfg, faults=faults)
    # heterogeneous nodes (the tournament's shape): the last two slots are
    # accelerator-typed, so the class -> device-type action matrix has
    # something real to steer
    from multi_cluster_simulator_tpu.core.spec import ClusterSpec, NodeSpec

    def cluster(cid):
        return ClusterSpec(id=cid, nodes=tuple(
            NodeSpec(id=i + 1, cores=32, memory=24_000,
                     device_type=1 if i >= 3 else 0) for i in range(5)))

    specs = [cluster(c + 1) for c in range(n_clusters)]
    env = ClusterEnv(cfg, specs, episode_ticks=episode_ticks,
                     gen=StreamGen(rate=rate, k_max=8, max_cores=24,
                                   max_mem=18_000, max_dur_ms=10_000),
                     policies=PolicySet(("rl",)), reward=reward)
    act_dim = F.N_JOB_CLASSES * F.N_DEVICE_TYPES
    obs0, es0 = env.reset_batch(jax.random.PRNGKey(seed), n_envs)
    sim0, arr = env._sim0, env._arr

    def head(W, obs):
        # mean-pool the cluster axis, one linear map to the action matrix
        return (obs.mean(axis=0) @ W).reshape(env.action_shape)

    def rollout(W_batch, obs, es):
        def body(carry, _):
            obs, es, ret = carry
            action = jax.vmap(head)(W_batch, obs)
            obs2, r, d, info, es2 = jax.vmap(
                env._step, in_axes=(0, 0, None, None))(es, action, sim0, arr)
            return (obs2, es2, ret + r), None

        (_, es2, ret), _ = jax.lax.scan(
            body, (obs, es, jnp.zeros(n_envs, jnp.float32)), None,
            length=episode_ticks)
        return ret, es2

    def es_iter(W, key):
        key, ke = jax.random.split(key)
        eps = jax.random.normal(ke, (n_envs,) + W.shape)
        ret, _ = rollout(W[None] + sigma * eps, obs0, es0)
        z = (ret - ret.mean()) / (ret.std() + 1e-6)
        W2 = W + (lr / n_envs) * jnp.einsum("b,b...->...", z, eps)
        return W2, key, ret.mean()

    it_fn = jax.jit(es_iter)
    W = jnp.zeros((env.n_obs, act_dim), jnp.float32)
    key = jax.random.PRNGKey(seed + 1)
    means = []
    start_iter = 0
    if checkpoint is not None and resume and os.path.exists(checkpoint):
        from multi_cluster_simulator_tpu.core import checkpoint as ckio

        bundle = ckio.load_tree(
            checkpoint, {"W": W, "key": key, "es0": es0, "obs0": obs0},
            cfg=cfg)
        extra = ckio.load_extra(checkpoint)
        W, key = bundle["W"], bundle["key"]
        # the RESET batch is restored, not re-derived: es0 carries every
        # env's per-env fault streams and PRNG state, and a resumed
        # iteration must roll out against the exact same batch
        es0, obs0 = bundle["es0"], bundle["obs0"]
        start_iter = int(extra.get("iter", 0))
        means = list(extra.get("means", []))
        print(f"# resumed ES training from {checkpoint} at iter "
              f"{start_iter}", file=sys.stderr)
    t0 = time.time()
    for i in range(start_iter, iters):
        W, key, mean_ret = it_fn(W, key)
        means.append(float(mean_ret))
        if checkpoint is not None:
            from multi_cluster_simulator_tpu.core import checkpoint as ckio

            ckio.save_tree(
                {"W": W, "key": key, "es0": es0, "obs0": obs0},
                checkpoint, t=i + 1,
                extra={"iter": i + 1, "means": means}, cfg=cfg)
    wall = time.time() - t0
    return {
        "mean_return_per_iter": means,
        "first_iter_return": means[0],
        "last_iter_return": means[-1],
        "head_norm": float(np.linalg.norm(np.asarray(W))),
        "W": np.asarray(W),
        "envs": n_envs, "episode_ticks": episode_ticks, "iters": iters,
        "episodes_simulated": iters * n_envs,
        "wall_s": round(wall, 3),
        "reward": reward,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--envs", type=int, default=32)
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--episode-ticks", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--reward", default="neg_mean_wait")
    ap.add_argument("--checkpoint", metavar="PATH", default=None,
                    help="save the training bundle (EnvState batch + ES "
                         "optimizer state + PRNG keys) after every "
                         "iteration")
    ap.add_argument("--resume", action="store_true",
                    help="continue a killed run from --checkpoint "
                         "bit-identically (per-env fault streams survive "
                         "the round-trip)")
    args = ap.parse_args(argv)
    res = train(iters=args.iters, n_envs=args.envs,
                n_clusters=args.clusters, episode_ticks=args.episode_ticks,
                seed=args.seed, reward=args.reward,
                checkpoint=args.checkpoint, resume=args.resume)
    print(f"# {res['episodes_simulated']} episodes "
          f"({res['envs']} envs x {res['iters']} iters x "
          f"{res['episode_ticks']} ticks) in {res['wall_s']} s, "
          f"reward={res['reward']}", file=sys.stderr)
    print("| iter | mean return |")
    print("|---|---|")
    for i, m in enumerate(res["mean_return_per_iter"]):
        print(f"| {i} | {m:.4f} |")
    if not np.isfinite(res["mean_return_per_iter"]).all():
        print("non-finite returns", file=sys.stderr)
        return 1
    if res["head_norm"] == 0.0:
        print("the head never moved — the update is dead", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
