"""Configuration layer.

The reference hardcodes every operational constant (catalogued in SURVEY.md §5);
this module lifts them all into dataclasses. Each field cites the reference
file:line its default value comes from. All times are virtual-time
milliseconds (int) — the reference's wall-clock durations map 1:1 onto the
virtual clock.
"""

from __future__ import annotations

import dataclasses
import enum


class PolicyKind(str, enum.Enum):
    """Scheduling policy. Reference: SchedulingType, pkg/scheduler/scheduler.go:40-45.

    FFD (first-fit-decreasing bin-pack) is a new TPU-side policy demanded by
    BASELINE.json config 3; the reference has only FIFO and DELAY.
    """

    FIFO = "FIFO"
    DELAY = "DELAY"
    FFD = "FFD"


class MatchKind(str, enum.Enum):
    """Trader market matching algorithm.

    GREEDY reproduces the reference's cheapest-approving-seller heap
    (pkg/trader/trader.go:169-191,236-276) deterministically; SINKHORN is the
    batched optimal-transport upgrade (BASELINE.json config 4); CVX solves the
    same assignment relaxation as an exact LP via fixed-iteration descending-
    price dual ascent (market/cvx.py) — the per-tick pricing backend the
    serving tier runs inside its coalesce window.
    """

    GREEDY = "greedy"
    SINKHORN = "sinkhorn"
    CVX = "cvx"


@dataclasses.dataclass(frozen=True)
class TraderConfig:
    """Per-cluster trader agent knobs. Reference: pkg/trader/trader.go:41-65."""

    enabled: bool = False
    # approvePolicy (seller side), trader.go:47-52
    approve_core_threshold: float = 0.8
    approve_mem_threshold: float = 0.8
    min_core_incentive: float = -1.0  # per core-second, trader.go:50
    min_mem_incentive: float = -1.0  # per MB-second, trader.go:51
    # requestPolicy (buyer side), trader.go:55-62
    request_max_wait_ms: float = 600_000.0  # requestPolicy_WaitTime, trader.go:57
    request_core_max: float = 0.8  # requestPolicy_Utilization, trader.go:60-61
    request_mem_max: float = 0.8
    # economics, trader.go:53 and (never-initialized, hence 0.0) trader.go:34-35
    budget: float = -1.0  # negative = unlimited
    max_core_cost: float = 0.0  # per core-second
    max_mem_cost: float = 0.0  # per MB-second
    # cadences
    monitor_period_ms: int = 10_000  # RequestPolicyMonitor loop, trader.go:323
    cooldown_success_ms: int = 240_000  # 4 min sleep after success, trader.go:298
    cooldown_failure_ms: int = 120_000  # 2 min sleep after failure, trader.go:302
    state_cadence_ms: int = 5_000  # scheduler state stream, trader_server.go:42
    contract_ttl_ms: int = 20_000  # seller contract validity, trader/server.go:49
    # Batch-market-only knob (market/trader.py). The live TraderService
    # always speaks the reference's pairwise gRPC protocol, which is greedy
    # by construction (fan-out + cheapest approver, trader.go:193-278) — a
    # live Sinkhorn would need a central matcher that protocol doesn't have.
    matching: MatchKind = MatchKind.GREEDY
    # Solver hyperparameters. The iteration counts are STATIC scan lengths
    # (the compiled loop trip count — fixed-iteration discipline, simlint
    # family 11); every value below also lands as a traced PolicyParams
    # ``mkt_*`` leaf (policies/base.py), so tournaments sweep the ACTIVE
    # iteration count / temperatures within the static bound in one
    # compiled program, and the values enter every params_digest.
    sinkhorn_iters: int = 16  # entropic-OT iterations (market/trader.py)
    sinkhorn_eps: float = 0.05  # entropic regularization temperature
    cvx_iters: int = 128  # dual-ascent iterations (market/cvx.py)
    cvx_step: float = 64.0  # primal sharpness 1/delta of the prox update
    # opening price step; decays harmonically (rho/(1+i) at iteration i),
    # so the total price sweep rho*H(n) ~ ln(n) diverges — an unmatched
    # buyer's price always reaches zero — while the step vanishes and the
    # equilibrium sharpens. The settle rule ties the three knobs: the
    # final dual step rho/(1+iters) must sit under the primal band width
    # 1/step, margin (1+iters)/(step*rho) >= 2 at the defaults, or the
    # price/plan limit cycle never lands (market/cvx.py, schedule note)
    cvx_rho: float = 1.0
    cvx_smooth: float = 0.0  # price carry-over across rounds (0 = cold start)
    # "asbuilt" reproduces the reference's observable arithmetic (quirks
    # included); "sane" is the documented intended behavior (MARKET.md).
    small_node_sizing: str = "asbuilt"  # scheduler_client.go:201-289
    carve_mode: str = "asbuilt"  # AllocateVirtualNodeResources, cluster.go:87-125
    # When True, borrowed virtual nodes expire after their contract duration
    # ("sane" mode). The reference keeps them forever (AddVirtualNode never
    # removes, pkg/scheduler/cluster.go:65-85), which the False default
    # reproduces.
    expire_virtual_nodes: bool = False
    # Live-host-only knob (services/trader_host.py). When a request policy
    # breaks while Level1 is empty, Go sizes a 0-core/0-MB contract and
    # trades it anyway — the buyer attaches a zero-capacity virtual node
    # that burns one of its finite virtual slots (trader.go:288-311 with an
    # empty ProvideJobs stream). The live TraderService skips such contracts
    # by default (with a log line); set False to reproduce Go's churn. The
    # batch market (market/trader.py) and the oracle are bit-parity surfaces
    # and always reproduce Go's zero-contract trades, ignoring this flag
    # (MARKET.md §divergences).
    skip_zero_contracts: bool = True


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """The fault plane (faults/): deterministic node churn as DATA.

    The reference simulates a fantasy datacenter — nodes never fail and a
    job, once placed, always completes. Real schedulers are shaped by churn
    (Gavel's rounds exist because placements must survive preemption,
    arxiv 2008.09213; Blox treats failure handling as a first-class
    toolkit axis, arxiv 2312.12621). With ``enabled`` the engine runs a
    fault phase at tick entry (core/engine.py): nodes fail on a per-node
    schedule, jobs running on a failed node are killed and requeued with
    their ``retries`` row field bumped (past ``max_retries`` they count
    into ``drops.failed``), the node's capacity is masked out while down,
    and repair restores an empty node. Failure schedules are either

    - ``mode="generative"`` — on-device inverse-CDF exponential sampling
      of per-node time-to-failure (``mttf_ms``) and time-to-repair
      (``mttr_ms``) from counter-based per-cluster PRNG streams (seeded by
      ``seed`` + global cluster index, so results are bit-identical under
      any sharding/chunking/compression of the run); or
    - ``mode="trace"`` — an explicit event list packed host-side into
      per-node interval tables (faults/schedule.pack_fault_trace), the
      ``pack_arrivals_by_tick`` move applied to failures.

    Sub-tick event times round up to the next tick boundary exactly like
    arrivals; within a tick, failures apply before repairs (a same-tick
    fail+repair is a zero-length outage that still kills)."""

    enabled: bool = False
    mode: str = "generative"  # "generative" | "trace"
    mttf_ms: int = 600_000  # mean time to failure per node (generative)
    mttr_ms: int = 60_000  # mean time to repair (generative)
    seed: int = 77
    max_retries: int = 3  # kills a job survives before drops.failed
    max_events: int = 8  # trace-mode fail/repair interval slots per node


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Workload generator. Reference: pkg/client/client.go:85-147."""

    arrival: str = "poisson"  # "poisson" | "weibull" | "trace"
    poisson_lambda_per_min: float = 10.0  # client.go:108
    weibull_lambda_s: float = 10.0  # client.go:133
    weibull_k: float = 3.0  # client.go:134
    beta_alpha: float = 2.0  # job-size distribution Beta(2,2), client.go:87-90
    beta_beta: float = 2.0
    max_duration_s: int = 600  # Duration ~ Uniform{0..599}s, client.go:98
    seed: int = 9  # the reference's fixed Poisson seed, client.go:109


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Engine-level configuration."""

    # --- capacities (static tensor shapes) ---
    # Width of the node resource axis: 2 = [cores, mem] (the reference's
    # Node, cluster.go:127-138), 3 = [cores, mem, gpu] (the BASELINE.json
    # config-4 extension). Narrowing to 2 shrinks every node tensor and
    # feasibility compare on gpu-free configs; the trader market requires 3.
    n_res: int = 3
    max_nodes: int = 8  # physical node slots per cluster
    max_virtual_nodes: int = 4  # reserved slots for borrowed virtual nodes
    queue_capacity: int = 128  # per-queue job slots
    max_running: int = 256  # concurrent running-job slots per cluster
    max_arrivals: int = 1024  # arrival-stream length per cluster
    max_msgs: int = 8  # cross-cluster messages per cluster per tick
    max_ingest_per_tick: int = 64  # arrivals consumed per cluster per tick

    # --- policy ---
    policy: PolicyKind = PolicyKind.DELAY  # hardcoded DELAY in Run, scheduler.go:116
    tick_ms: int = 1_000  # 1 s loop tick, scheduler.go:250,294,367
    max_wait_ms: int = 10_000  # Level0->Level1 promotion, scheduler.go:115
    borrowing: bool = False  # FIFO-path scheduler<->scheduler loans

    # --- parity vs fast mode ---
    # parity=True reproduces the Go loops' observable semantics exactly,
    # including the remove-then-skip iteration quirk in the Level1 sweep
    # (scheduler.go:305-327) and unbounded per-tick sweeps. parity=False caps
    # per-tick placement work at `max_placements_per_tick` for throughput.
    parity: bool = True
    max_placements_per_tick: int = 16
    # Fast-mode FFD sweep form: "wave" places speculative batches per
    # while_loop iteration (provably identical placements to "serial" —
    # engine._ffd_wave_local docstring; tests/test_kernel_equiv.py pins
    # it); "serial" keeps the one-job-per-iteration sweep. Parity mode
    # always runs the serial sweep (its float wait accumulation order is
    # part of bit-parity with the oracle).
    ffd_sweep: str = "wave"
    # FIFO ready-drain form: the wave version is exact in BOTH modes (the
    # drain body has no order-sensitive float accumulation — see
    # engine._fifo_drain_wave), so it is the default everywhere; "serial"
    # keeps the one-job-per-iteration loop. The oracle parity suite and
    # the TPU parity gate run the wave path and must stay bit-exact.
    fifo_drain: str = "wave"
    # Fast-mode DELAY Level1 sweep form (parity mode always serial: the
    # remove-then-skip quirk + ordered float wait accumulation are part
    # of bit-parity). Same wave technique as ffd_sweep.
    delay_sweep: str = "wave"

    # --- fused tick kernel (kernels/fused_tick.py) ---
    # Execution STRATEGY, not semantics: the fused path is bit-identical to
    # the unfused tick (the interpret-mode oracle + tests/test_kernels.py
    # pin it), so these fields are excluded from the checkpoint config
    # digest (core/checkpoint.config_describe) — a run may be checkpointed
    # unfused and resumed fused, or vice versa.
    #   "off"  — the unfused XLA tick (default; every pre-kernel path)
    #   "on"   — always run the per-cluster prefix (the engaged span of
    #            faults->release->expire->ingest->schedule) as ONE
    #            pallas_call that keeps the block's queue/runset/node
    #            columns in VMEM across the phase boundaries
    #            (interpret-mode on non-TPU backends unless
    #            fused_interpret pins it)
    #   "auto" — fuse only where it pays: a real TPU backend (interpret
    #            mode is an oracle, not a fast path — CPU stays unfused)
    fused: str = "off"
    # Cluster-block hint for the kernel grid: the actual block is the
    # largest divisor of the (shard-local) cluster count <= this, so C
    # never needs padding and blocking stays bitwise invisible.
    fused_block: int = 256
    # pallas_call(interpret=...) source of truth (simlint rule family 10
    # forbids hardcoding it at call sites). None = interpret everywhere
    # except a real TPU backend — the CPU/CI oracle contract.
    fused_interpret: bool | None = None

    # --- instrumentation ---
    record_trace: bool = False  # record per-placement events
    max_trace_events: int = 1 << 16
    # When True, Engine.run returns (state, MetricSample series): per-tick
    # jobs_in_queue + avg-wait stacked from the scan (metrics.go:11-31).
    record_metrics: bool = False

    trader: TraderConfig = dataclasses.field(default_factory=TraderConfig)
    workload: WorkloadConfig = dataclasses.field(default_factory=WorkloadConfig)
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)

    @property
    def total_nodes(self) -> int:
        return self.max_nodes + self.max_virtual_nodes


# Service-shell constants (reference values; see services/).
REGISTRY_PORT = 3000  # pkg/registry/server.go:15
HEARTBEAT_PERIOD_S = 3.0  # cmd/registry/main.go -> SetupRegistryService
HEARTBEAT_ATTEMPTS = 3  # pkg/registry/server.go:140
PROVIDE_JOBS_BATCH = 20  # pkg/scheduler/trader_server.go:75
TRADE_COLLECT_WINDOW_S = 3.0  # pkg/trader/trader.go:249
RETURN_ATTEMPTS = 3  # pkg/scheduler/server.go:275
