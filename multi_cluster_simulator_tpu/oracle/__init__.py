from multi_cluster_simulator_tpu.oracle.go_semantics import Oracle

__all__ = ["Oracle"]
