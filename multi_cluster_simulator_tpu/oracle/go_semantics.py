"""Deterministic pure-Python oracle of the reference's scheduler semantics.

This is the golden-trace generator for parity tests: a straight-line
re-implementation of the Go loops (Fifo/Delay, pkg/scheduler/scheduler.go:
216-369; borrow, server.go:160-248) under the determinization documented in
PARITY.md — same phase order, same quirks (the Level1 remove-then-skip
iteration, strict-vs-non-strict feasibility, whole-struct-equality dequeues),
written with plain lists and dicts so it can be independently reviewed
against the Go source. The TPU engine must produce bit-identical placement
traces to this oracle.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
from multi_cluster_simulator_tpu.core.spec import ClusterSpec
from multi_cluster_simulator_tpu.core.state import (
    SRC_L0, SRC_L1, SRC_LENT, SRC_READY, SRC_WAIT, Arrivals,
)


@dataclasses.dataclass
class OJob:
    id: int
    cores: int
    mem: int
    dur: int
    enq_t: int
    owner: int = -1  # borrower cluster index; -1 = own (Ownership == "")
    rec_wait: int = 0  # WaitTime.JobsMap entry

    def key(self):
        return (self.id, self.cores, self.mem, self.dur)


@dataclasses.dataclass
class ORunning:
    end_t: int
    node: int
    job: OJob


class OCluster:
    def __init__(self, spec: ClusterSpec):
        self.free = [[n.cores, n.memory] for n in spec.nodes]
        self.l0: list[OJob] = []
        self.l1: list[OJob] = []
        self.ready: list[OJob] = []
        self.wait: list[OJob] = []
        self.lent: list[OJob] = []
        self.borrowed: list[OJob] = []
        self.running: list[ORunning] = []
        self.wait_total = 0  # TotalTime, ms
        self.wait_jobs = 0  # JobsCount
        self.jobs_in_queue = 0
        self.arr_ptr = 0

    def first_fit(self, j: OJob) -> Optional[int]:
        """ScheduleJob's >= scan (scheduler.go:127-139)."""
        for i, (fc, fm) in enumerate(self.free):
            if fc >= j.cores and fm >= j.mem:
                return i
        return None

    def can_lend(self, j: OJob) -> bool:
        """Lend's strict > scan (scheduler.go:194-202)."""
        return any(fc > j.cores and fm > j.mem for fc, fm in self.free)


class Oracle:
    def __init__(self, cfg: SimConfig, specs: list[ClusterSpec], arrivals: Arrivals):
        self.cfg = cfg
        self.clusters = [OCluster(s) for s in specs]
        self.arr = arrivals
        self.t = 0
        # events: (t, cluster, job_id, node, src)
        self.trace: list[tuple[int, int, int, int, int]] = []

    # -- helpers --
    def _place(self, c: int, j: OJob, node: int, src: int):
        cl = self.clusters[c]
        cl.free[node][0] -= j.cores
        cl.free[node][1] -= j.mem
        cl.running.append(ORunning(end_t=self.t + j.dur, node=node, job=j))
        self.trace.append((self.t, c, j.id, node, src))

    # -- phases --
    def _releases(self):
        returns: list[tuple[int, OJob]] = []  # (borrower cluster, job)
        for c, cl in enumerate(self.clusters):
            done = [r for r in cl.running if r.end_t <= self.t]
            cl.running = [r for r in cl.running if r.end_t > self.t]
            for r in done:
                cl.free[r.node][0] += r.job.cores
                cl.free[r.node][1] += r.job.mem
                if r.job.owner >= 0:  # lent job -> ReturnToBorrower
                    returns.append((r.job.owner, r.job))
        for dst, j in returns:
            bq = self.clusters[dst].borrowed
            self.clusters[dst].borrowed = [b for b in bq if b.key() != j.key()]

    def _arrivals(self):
        to_delay = self.cfg.policy in (PolicyKind.DELAY, PolicyKind.FFD)
        a = self.arr
        for c, cl in enumerate(self.clusters):
            n = int(a.n[c])
            while cl.arr_ptr < n and int(a.t[c, cl.arr_ptr]) <= self.t:
                i = cl.arr_ptr
                j = OJob(id=int(a.id[c, i]), cores=int(a.cores[c, i]),
                         mem=int(a.mem[c, i]), dur=int(a.dur[c, i]),
                         enq_t=int(a.t[c, i]))
                if to_delay:
                    cl.l0.append(j)
                    cl.wait_jobs += 1
                    cl.jobs_in_queue += 1
                else:
                    cl.ready.append(j)
                cl.arr_ptr += 1

    def _record_wait(self, cl: OCluster, j: OJob):
        cur = self.t - j.enq_t
        cl.wait_total += cur - j.rec_wait
        j.rec_wait = cur

    def _delay_pass(self, c: int):
        cl = self.clusters[c]
        # Level1 sweep — the literal Go loop with in-place removal + i++
        i = 0
        while i < len(cl.l1):
            j = cl.l1[i]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L1)
                del cl.l1[i]
                cl.jobs_in_queue -= 1
            i += 1  # skips the element that slid into position i on removal
        # Level0 head
        if cl.l0:
            j = cl.l0[0]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L0)
                cl.l0.pop(0)
                cl.jobs_in_queue -= 1
            elif self.t - j.enq_t >= self.cfg.max_wait_ms:
                cl.l1.append(cl.l0.pop(0))

    def _ffd_pass(self, c: int):
        cl = self.clusters[c]
        order = sorted(range(len(cl.l0)),
                       key=lambda i: (-cl.l0[i].cores, -cl.l0[i].mem, i))
        placed = set()
        for i in order:
            j = cl.l0[i]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L0)
                placed.add(i)
                cl.jobs_in_queue -= 1
        cl.l0 = [j for i, j in enumerate(cl.l0) if i not in placed]

    def _fifo_pass(self, c: int) -> Optional[OJob]:
        """Returns the borrow-request job, if any (see PARITY.md §FIFO)."""
        cl = self.clusters[c]
        wait_active = len(cl.wait) > 0
        any_fail = False
        if not wait_active:
            while cl.ready and not any_fail:
                j = cl.ready.pop(0)
                node = cl.first_fit(j)
                if node is not None:
                    self._place(c, j, node, SRC_READY)
                else:
                    cl.wait.append(j)
                    any_fail = True
        borrow_req = None
        if cl.wait:
            j = cl.wait[0]
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_WAIT)
                cl.wait.pop(0)
            elif self.cfg.borrowing:
                borrow_req = j
        if (not wait_active) and (not any_fail) and not cl.ready and cl.lent:
            j = cl.lent[0]
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_LENT)
                cl.lent.pop(0)
        return borrow_req

    def _borrow_match(self, requests: dict[int, OJob]):
        """Feasibility over all lenders, lowest cluster index wins; no
        reservation between matches (the Go /borrow handler only checks)."""
        for b in sorted(requests):
            j = requests[b]
            winner = None
            for l, cl in enumerate(self.clusters):
                if l != b and cl.can_lend(j):
                    winner = l
                    break
            if winner is None:
                continue
            sent = dataclasses.replace(j, owner=b)
            bcl = self.clusters[b]
            bcl.borrowed.append(dataclasses.replace(j, owner=b))
            assert bcl.wait and bcl.wait[0].key() == j.key()
            bcl.wait.pop(0)
            self.clusters[winner].lent.append(sent)

    # -- driver --
    def tick(self):
        self.t += self.cfg.tick_ms
        self._releases()
        self._arrivals()
        requests: dict[int, OJob] = {}
        for c in range(len(self.clusters)):
            if self.cfg.policy == PolicyKind.DELAY:
                self._delay_pass(c)
            elif self.cfg.policy == PolicyKind.FFD:
                self._ffd_pass(c)
            else:
                req = self._fifo_pass(c)
                if req is not None:
                    requests[c] = req
        if self.cfg.borrowing and requests:
            self._borrow_match(requests)

    def run(self, n_ticks: int):
        for _ in range(n_ticks):
            self.tick()
        return self

    # -- stats accessors (for cross-checks beyond the trace) --
    def avg_wait(self, c: int) -> float:
        cl = self.clusters[c]
        return cl.wait_total / cl.wait_jobs if cl.wait_jobs else 0.0
