"""Deterministic pure-Python oracle of the reference's scheduler + trader
semantics.

This is the golden-trace generator for parity tests: a straight-line
re-implementation of the Go loops (Fifo/Delay, pkg/scheduler/scheduler.go:
216-369; borrow, server.go:160-248; the trader market, pkg/trader) under the
determinizations documented in PARITY.md and MARKET.md — same phase order,
same quirks (the Level1 remove-then-skip iteration, strict-vs-non-strict
feasibility, whole-struct-equality dequeues, the as-built contract sizing and
carving arithmetic), written with plain lists and dicts so it can be
independently reviewed against the Go source. The TPU engine must produce
bit-identical placement traces and state to this oracle.

Node layout mirrors the engine's padded axis: physical slots
[0, cfg.max_nodes), virtual slots [cfg.max_nodes, cfg.total_nodes), so node
indices in traces are directly comparable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
from multi_cluster_simulator_tpu.core.spec import ClusterSpec
from multi_cluster_simulator_tpu.core.state import (
    SRC_L0, SRC_L1, SRC_LENT, SRC_READY, SRC_WAIT, Arrivals,
)

NEVER = 2**31 - 1


@dataclasses.dataclass
class OJob:
    id: int
    cores: int
    mem: int
    dur: int
    enq_t: int
    owner: int = -1  # borrower cluster index; -1 own; -2 Foreign placeholder
    rec_wait: int = 0  # WaitTime.JobsMap entry

    def key(self):
        return (self.id, self.cores, self.mem, self.dur)


@dataclasses.dataclass
class ORunning:
    end_t: int
    node: int
    job: OJob


@dataclasses.dataclass
class OContract:
    cores: int = 0
    mem: int = 0
    time_ms: int = 0
    price: float = 0.0


class OCluster:
    def __init__(self, spec: ClusterSpec, cfg: SimConfig):
        N = cfg.total_nodes
        self.cap = [[0, 0] for _ in range(N)]
        self.free = [[0, 0] for _ in range(N)]
        self.active = [False] * N
        self.expire = [NEVER] * N
        for i, n in enumerate(spec.nodes):
            self.cap[i] = [n.cores, n.memory]
            self.free[i] = [n.cores, n.memory]
            self.active[i] = True
        self.l0: list[OJob] = []
        self.l1: list[OJob] = []
        self.ready: list[OJob] = []
        self.wait: list[OJob] = []
        self.lent: list[OJob] = []
        self.borrowed: list[OJob] = []
        self.running: list[ORunning] = []
        self.wait_total = 0  # TotalTime, ms
        self.wait_jobs = 0  # JobsCount
        self.jobs_in_queue = 0
        self.arr_ptr = 0
        # trader agent state (MARKET.md)
        self.snap_core_util = 0.0
        self.snap_mem_util = 0.0
        self.snap_avg_wait = 0.0
        self.snap_total_cores = sum(n.cores for n in spec.nodes)
        self.snap_total_mem = sum(n.memory for n in spec.nodes)
        self.cooldown_until = 0
        self.seller_locked_until = 0
        self.spent = 0.0

    def first_fit(self, j: OJob) -> Optional[int]:
        """ScheduleJob's >= scan (scheduler.go:127-139), active slots only."""
        for i in range(len(self.free)):
            if self.active[i] and self.free[i][0] >= j.cores and self.free[i][1] >= j.mem:
                return i
        return None

    def can_lend(self, j: OJob) -> bool:
        """Lend's strict > scan (scheduler.go:194-202)."""
        return any(self.active[i] and self.free[i][0] > j.cores and self.free[i][1] > j.mem
                   for i in range(len(self.free)))


def _f32(x: float) -> float:
    return float(np.float32(x))


class Oracle:
    def __init__(self, cfg: SimConfig, specs: list[ClusterSpec], arrivals: Arrivals):
        self.cfg = cfg
        self.clusters = [OCluster(s, cfg) for s in specs]
        self.arr = arrivals
        self.t = 0
        # events: (t, cluster, job_id, node, src)
        self.trace: list[tuple[int, int, int, int, int]] = []

    # -- helpers --
    def _place(self, c: int, j: OJob, node: int, src: int):
        cl = self.clusters[c]
        cl.free[node][0] -= j.cores
        cl.free[node][1] -= j.mem
        cl.running.append(ORunning(end_t=self.t + j.dur, node=node, job=j))
        self.trace.append((self.t, c, j.id, node, src))

    # -- phases --
    def _releases(self):
        returns: list[tuple[int, OJob]] = []  # (borrower cluster, job)
        for c, cl in enumerate(self.clusters):
            done = [r for r in cl.running if r.end_t <= self.t]
            cl.running = [r for r in cl.running if r.end_t > self.t]
            for r in done:
                cl.free[r.node][0] += r.job.cores
                cl.free[r.node][1] += r.job.mem
                if r.job.owner >= 0:  # lent job -> ReturnToBorrower
                    returns.append((r.job.owner, r.job))
        for dst, j in returns:
            bq = self.clusters[dst].borrowed
            self.clusters[dst].borrowed = [b for b in bq if b.key() != j.key()]

    def _expire_vnodes(self):
        for cl in self.clusters:
            for i in range(len(cl.cap)):
                if cl.active[i] and cl.expire[i] <= self.t:
                    cl.active[i] = False
                    cl.cap[i] = [0, 0]
                    cl.free[i] = [0, 0]
                    cl.expire[i] = NEVER

    def _arrivals(self):
        to_delay = self.cfg.policy in (PolicyKind.DELAY, PolicyKind.FFD)
        a = self.arr
        for c, cl in enumerate(self.clusters):
            n = int(a.n[c])
            while cl.arr_ptr < n and int(a.t[c, cl.arr_ptr]) <= self.t:
                i = cl.arr_ptr
                j = OJob(id=int(a.id[c, i]), cores=int(a.cores[c, i]),
                         mem=int(a.mem[c, i]), dur=int(a.dur[c, i]),
                         enq_t=int(a.t[c, i]))
                if to_delay:
                    cl.l0.append(j)
                    cl.wait_jobs += 1
                    cl.jobs_in_queue += 1
                else:
                    cl.ready.append(j)
                cl.arr_ptr += 1

    def _record_wait(self, cl: OCluster, j: OJob):
        cur = self.t - j.enq_t
        cl.wait_total += cur - j.rec_wait
        j.rec_wait = cur

    def _delay_pass(self, c: int):
        cl = self.clusters[c]
        # Level1 sweep — the literal Go loop with in-place removal + i++
        i = 0
        while i < len(cl.l1):
            j = cl.l1[i]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L1)
                del cl.l1[i]
                cl.jobs_in_queue -= 1
            i += 1  # skips the element that slid into position i on removal
        # Level0 head
        if cl.l0:
            j = cl.l0[0]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L0)
                cl.l0.pop(0)
                cl.jobs_in_queue -= 1
            elif self.t - j.enq_t >= self.cfg.max_wait_ms:
                cl.l1.append(cl.l0.pop(0))

    def _ffd_pass(self, c: int):
        cl = self.clusters[c]
        order = sorted(range(len(cl.l0)),
                       key=lambda i: (-cl.l0[i].cores, -cl.l0[i].mem, i))
        placed = set()
        for i in order:
            j = cl.l0[i]
            self._record_wait(cl, j)
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_L0)
                placed.add(i)
                cl.jobs_in_queue -= 1
        cl.l0 = [j for i, j in enumerate(cl.l0) if i not in placed]

    def _fifo_pass(self, c: int) -> Optional[OJob]:
        """Returns the borrow-request job, if any (see PARITY.md §FIFO)."""
        cl = self.clusters[c]
        wait_active = len(cl.wait) > 0
        any_fail = False
        if not wait_active:
            while cl.ready and not any_fail:
                j = cl.ready.pop(0)
                node = cl.first_fit(j)
                if node is not None:
                    self._place(c, j, node, SRC_READY)
                else:
                    cl.wait.append(j)
                    any_fail = True
        borrow_req = None
        if cl.wait:
            j = cl.wait[0]
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_WAIT)
                cl.wait.pop(0)
            elif self.cfg.borrowing:
                borrow_req = j
        if (not wait_active) and (not any_fail) and not cl.ready and cl.lent:
            j = cl.lent[0]
            node = cl.first_fit(j)
            if node is not None:
                self._place(c, j, node, SRC_LENT)
                cl.lent.pop(0)
        return borrow_req

    def _borrow_match(self, requests: dict[int, OJob]):
        """Feasibility over all lenders, lowest cluster index wins; no
        reservation between matches (the Go /borrow handler only checks)."""
        for b in sorted(requests):
            j = requests[b]
            winner = None
            for l, cl in enumerate(self.clusters):
                if l != b and cl.can_lend(j):
                    winner = l
                    break
            if winner is None:
                continue
            sent = dataclasses.replace(j, owner=b)
            bcl = self.clusters[b]
            bcl.borrowed.append(dataclasses.replace(j, owner=b))
            assert bcl.wait and bcl.wait[0].key() == j.key()
            bcl.wait.pop(0)
            self.clusters[winner].lent.append(sent)

    # -- trader market (MARKET.md) --
    def _snapshot(self):
        if self.t % self.cfg.trader.state_cadence_ms != 0:
            return
        for cl in self.clusters:
            uc = sum(cl.cap[i][0] - cl.free[i][0] for i in range(len(cl.cap)))
            um = sum(cl.cap[i][1] - cl.free[i][1] for i in range(len(cl.cap)))
            cl.snap_core_util = _f32(uc / max(cl.snap_total_cores, 1))
            cl.snap_mem_util = _f32(um / max(cl.snap_total_mem, 1))
            cl.snap_avg_wait = _f32(cl.wait_total / cl.wait_jobs) if cl.wait_jobs else 0.0

    def _price(self, cores, mem, time_ms):
        """Stepwise float32, mirroring the engine kernel's op order
        (ops/sizing.py:_price) so strict budget comparisons are bit-equal."""
        m = self.cfg.trader
        f = np.float32
        t_s = f(f(time_ms) / f(1000.0))
        a = f(f(f(t_s * f(cores)) * f(m.max_core_cost)))
        b = f(f(f(t_s * f(mem)) * f(m.max_mem_cost)))
        return float(f(a + b))

    def _fast_contract(self, cl: OCluster) -> OContract:
        m = self.cfg.trader
        con = OContract()
        for j in cl.l1:
            nt = max(con.time_ms, j.dur)
            nc, nm = con.cores + j.cores, con.mem + j.mem
            np_ = self._price(nc, nm, nt)
            if m.budget < 0 or np_ < m.budget:
                con = OContract(nc, nm, nt, np_)
            else:
                break
        return con

    def _small_contract(self, cl: OCluster) -> OContract:
        m = self.cfg.trader
        con = OContract()
        if m.small_node_sizing == "asbuilt":
            for j in cl.l1:
                nc = con.cores + (j.cores if j.cores > 0 else 0)
                nm = con.mem + (j.mem if j.mem > 0 else 0)
                nt = j.dur if j.dur > con.time_ms else 0
                np_ = self._price(nc, nm, nt)
                if m.budget < 0 or np_ < m.budget:
                    con = OContract(nc, nm, nt, np_)
                else:
                    break
        else:  # sane: max cores/mem, summed durations
            for j in cl.l1:
                nc, nm = max(con.cores, j.cores), max(con.mem, j.mem)
                nt = con.time_ms + j.dur
                np_ = self._price(nc, nm, nt)
                if m.budget < 0 or np_ < m.budget:
                    con = OContract(nc, nm, nt, np_)
                else:
                    break
        return con

    def _carve_plan(self, cl: OCluster, con: OContract):
        """AllocateVirtualNodeResources (cluster.go:87-125); as-built request
        arithmetic, occupancy clamped to [0, avail] (MARKET.md §carving)."""
        m = self.cfg.trader
        rc, rm = con.cores, con.mem
        amounts = []
        for i in range(len(cl.free)):
            if not cl.active[i]:  # the Go node list has no padded slots
                amounts.append((0, 0))
                continue
            ac, am = max(cl.free[i][0], 0), max(cl.free[i][1], 0)
            if m.carve_mode == "asbuilt":
                dc = abs(rc - ac) if rc > 0 else 0
                dm = abs(rm - am) if rm > 0 else 0
                rc = 0 if dc > rc else rc - dc
                rm = 0 if dm > rm else rm - dm
                oc, om = min(max(dc, 0), ac), min(max(dm, 0), am)
            else:
                oc, om = min(rc, ac), min(rm, am)
                rc, rm = rc - oc, rm - om
            amounts.append((oc, om))
        return amounts, (rc <= 0 and rm <= 0)

    def _approve(self, cl: OCluster, con: OContract) -> bool:
        """Stepwise float32 mirroring market/trader.py's ApproveTrade ops."""
        m = self.cfg.trader
        f = np.float32
        if not (f(cl.snap_core_util) < f(m.approve_core_threshold)
                and f(cl.snap_mem_util) < f(m.approve_mem_threshold)):
            return False
        tot_c, tot_m = f(cl.snap_total_cores), f(cl.snap_total_mem)
        avail_c = f(tot_c - f(tot_c * f(cl.snap_core_util)))
        avail_m = f(tot_m - f(tot_m * f(cl.snap_mem_util)))
        if not (avail_c >= f(con.cores) and avail_m >= f(con.mem)):
            return False
        t_s = f(f(con.time_ms) / f(1000.0))
        a = f(f(f(f(m.min_core_incentive) * f(con.cores)) * t_s))
        b = f(f(f(f(m.min_mem_incentive) * f(con.mem)) * t_s))
        return f(con.price) >= f(a + b)

    def _trade_round(self):
        m = self.cfg.trader
        if self.t % m.monitor_period_ms != 0:
            return
        C = len(self.clusters)
        # buyers
        contracts: dict[int, tuple[OContract, bool]] = {}
        for b, cl in enumerate(self.clusters):
            if cl.cooldown_until > self.t:
                continue
            if cl.snap_avg_wait > m.request_max_wait_ms:
                contracts[b] = (self._fast_contract(cl), True)
            elif (cl.snap_core_util > m.request_core_max
                  or cl.snap_mem_util > m.request_mem_max):
                contracts[b] = (self._small_contract(cl), False)
        # sellers: process lowest-index buyer; lock; approve; carve plan
        approves: dict[int, int] = {}  # seller -> buyer
        plans: dict[int, tuple[list, bool]] = {}
        for s, cl in enumerate(self.clusters):
            reqs = [b for b in sorted(contracts) if b != s]
            if not reqs:
                continue
            if cl.seller_locked_until > self.t:
                continue  # refuses everyone, no lock change
            b = reqs[0]
            cl.seller_locked_until = self.t + m.contract_ttl_ms
            con = contracts[b][0]
            if self._approve(cl, con):
                approves[s] = b
                plans[s] = self._carve_plan(cl, con)
        # match + apply
        for b in sorted(contracts):
            con, _ = contracts[b]
            cands = sorted(s for s, bb in approves.items() if bb == b)
            winner = None
            for s in cands:
                self.clusters[s].seller_locked_until = 0  # attempted -> reset
                if plans[s][1]:
                    winner = s
                    break
            bcl = self.clusters[b]
            if winner is None:
                bcl.cooldown_until = self.t + m.cooldown_failure_ms
                continue
            # seller carve: occupy amounts as Foreign placeholder jobs
            scl = self.clusters[winner]
            for n, (oc, om) in enumerate(plans[winner][0]):
                if oc > 0 or om > 0:
                    scl.free[n][0] -= oc
                    scl.free[n][1] -= om
                    scl.running.append(ORunning(
                        end_t=self.t + con.time_ms, node=n,
                        job=OJob(id=-3, cores=oc, mem=om, dur=con.time_ms,
                                 enq_t=self.t, owner=-2)))
            # buyer: AddVirtualNode at the first free virtual slot
            vstart = self.cfg.max_nodes
            slot = next((i for i in range(vstart, len(bcl.cap))
                         if not bcl.active[i]), None)
            if slot is not None:
                bcl.cap[slot] = [con.cores, con.mem]
                bcl.free[slot] = [con.cores, con.mem]
                bcl.active[slot] = True
                bcl.expire[slot] = (self.t + con.time_ms
                                    if m.expire_virtual_nodes else NEVER)
            bcl.cooldown_until = self.t + m.cooldown_success_ms
            bcl.spent = _f32(bcl.spent + con.price)

    # -- driver --
    def tick(self):
        self.t += self.cfg.tick_ms
        self._releases()
        if self.cfg.trader.enabled and self.cfg.trader.expire_virtual_nodes:
            self._expire_vnodes()
        self._arrivals()
        requests: dict[int, OJob] = {}
        for c in range(len(self.clusters)):
            if self.cfg.policy == PolicyKind.DELAY:
                self._delay_pass(c)
            elif self.cfg.policy == PolicyKind.FFD:
                self._ffd_pass(c)
            else:
                req = self._fifo_pass(c)
                if req is not None:
                    requests[c] = req
        if self.cfg.borrowing and requests:
            self._borrow_match(requests)
        if self.cfg.trader.enabled:
            self._snapshot()
            self._trade_round()

    def run(self, n_ticks: int):
        for _ in range(n_ticks):
            self.tick()
        return self

    # -- stats accessors (for cross-checks beyond the trace) --
    def avg_wait(self, c: int) -> float:
        cl = self.clusters[c]
        return cl.wait_total / cl.wait_jobs if cl.wait_jobs else 0.0
