"""multi_cluster_simulator_tpu — a TPU-native multi-cluster scheduling simulator.

A brand-new JAX/XLA framework with the capabilities of the Go reference
(hamzalsheikh/multi-cluster-simulator): multiple cluster schedulers with
pluggable policies, cross-cluster borrowing, a priced trader market, service
discovery with heartbeats, distribution-driven workload generation, and
metrics/tracing — redesigned TPU-first:

- world state lives in padded int32 tensors (clusters x nodes x resources,
  clusters x queue-slots x job-fields) instead of mutex-guarded Go structs;
- time is a discrete virtual clock driven by ``lax.scan`` instead of
  ``time.Sleep`` (reference: pkg/scheduler/cluster.go:141-161);
- the per-tick placement decision is a vmapped first-fit kernel over the node
  axis (reference: pkg/scheduler/scheduler.go:127-139);
- cross-cluster mechanisms (borrow broadcast, trader offer/accept) are batched
  array ops that lower to XLA collectives when the cluster axis is sharded
  over a device mesh (reference: pkg/scheduler/server.go:160-248,
  pkg/trader/trader.go:193-278).
"""

from multi_cluster_simulator_tpu.config import SimConfig, TraderConfig, WorkloadConfig
from multi_cluster_simulator_tpu.core.spec import ClusterSpec, NodeSpec, load_cluster_json
from multi_cluster_simulator_tpu.core.state import SimState, init_state
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.checkpoint import load_state, save_state

__version__ = "0.1.0"

__all__ = [
    "SimConfig",
    "TraderConfig",
    "WorkloadConfig",
    "ClusterSpec",
    "NodeSpec",
    "load_cluster_json",
    "SimState",
    "init_state",
    "Engine",
    "save_state",
    "load_state",
]
