"""Per-tenant knobs as traced data: the ``TenantParams`` pytree.

Multi-tenant hosting (ROADMAP item 3) puts T independent constellations
on one device mesh through ONE compiled program — which only works if
everything that varies per tenant is a traced leaf, never a Python
static. The audit of SimConfig's per-run fields sorts them into two
bins:

- **already leaves** — the policy selector and every policy/market
  hyperparameter live in ``PolicyParams`` (policies/base.py): ``idx``
  (lax.switch selection), the promotion threshold ``max_wait_ms`` (the
  delay zoo's l0->ready clock), the gavel/tesserae weights, and the
  convex-market solver knobs (``mkt_iters``/``mkt_step``/``mkt_rho``/
  ``mkt_smooth``/``mkt_sink_iters``/``mkt_sink_eps``). ``TenantParams``
  embeds the whole struct, so a tenant axis sweeps them for free.
- **hoisted here** — ``fault_seed`` (the generative churn stream root:
  per-tenant failure patterns from one shared FaultConfig shape) and
  ``quota_jobs`` (the serving tier's per-tenant admission budget; the
  engine never reads it — it rides the pytree so the front door and the
  bench share one provenance record of what each tenant was promised).

Shape statics stay static, padded to the tenant-max: ``queue_capacity``,
``max_nodes``, ``max_running`` and friends are array SHAPES, and a
per-tenant shape would be a per-tenant executable — exactly what the
one-compile contract forbids. A tenant needing a smaller queue gets the
shared shape and a smaller ``quota_jobs``.

Stacking follows ``PolicySet.stacked_params``: cells stack leaf-wise on
a leading [T] axis and the batched drivers (tenancy/host.py) vmap over
it — distinct values, one program, jit cache == 1 (tests/test_tenancy.py
asserts the cache count).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.policies.base import (
    PolicyParams, PolicySet, params_digest,
)


@struct.dataclass
class TenantParams:
    """One tenant's traced knobs (stack cells leaf-wise for a batch)."""

    policy: PolicyParams  # selector + policy/market hyperparams + promotion
    fault_seed: jax.Array  # u32 — generative churn stream root (per tenant)
    quota_jobs: jax.Array  # i32 — serving admission budget (-1 = unmetered)


def default_tenant_params(cfg: SimConfig, pset: Optional[PolicySet] = None,
                          name: Optional[str] = None,
                          policy: Optional[PolicyParams] = None,
                          fault_seed: int = 0,
                          quota_jobs: int = -1) -> TenantParams:
    """A single tenant cell: the config-derived policy defaults for
    ``name`` within ``pset`` (the singleton config set when omitted), or
    an explicit ``policy`` pytree, plus the hoisted per-tenant leaves."""
    if policy is None:
        pset = PolicySet.from_config(cfg) if pset is None else pset
        policy = pset.params_for(cfg, name)
    return TenantParams(
        policy=policy,
        fault_seed=jnp.uint32(fault_seed),
        quota_jobs=jnp.int32(quota_jobs))


def stack_tenant_params(cells: Sequence[TenantParams]) -> TenantParams:
    """Stack per-tenant cells on a leading [T] axis — the
    ``PolicySet.stacked_params`` move, applied to the tenant pytree."""
    if not cells:
        raise ValueError("stack_tenant_params needs at least one tenant")
    return jax.tree.map(lambda *ls: jnp.stack(ls), *cells)


def tenant_params_digest(tp: TenantParams) -> str:
    """12-hex provenance digest over every tenant leaf (the
    ``params_digest`` convention) — what bench rows record so a tenant
    sweep is joinable with the exact knobs it ran."""
    h = hashlib.sha1()
    h.update(params_digest(tp.policy).encode())
    extra = {
        "fault_seed": jnp.asarray(tp.fault_seed).tolist(),
        "quota_jobs": jnp.asarray(tp.quota_jobs).tolist(),
    }
    h.update(json.dumps(extra, sort_keys=True).encode())
    return h.hexdigest()[:12]
