"""The tenant axis: vmapped engine drivers over stacked constellations.

One device mesh hosts T independent tenants — each its own ``SimState``
cell, policy/market knobs (``TenantParams.policy`` leaves), generative
fault stream (``TenantParams.fault_seed``), and arrival trace — through
ONE compiled program: ``jax.vmap`` of the engine's existing drivers over
a leading tenant axis, exactly the way envs/cluster_env.py batches env
instances. Donated stacked state, traced per-tenant params, jit
cache == 1 for any T (tests/test_tenancy.py asserts the count).

Parity is the contract that makes the axis safe (PARITY.md): vmap of a
pure function is the function per lane, so every tenant cell of a T>1
run is bit-identical to its standalone single-tenant run — composed
with the compact layout (``plan``), event-compressed time
(``run_compressed_fn``), generative faults, and mesh sharding
(``shard_tenant_batch``'s pytree-prefix placement, no collectives:
tenants are independent, so data-parallel jit needs no shard_map).

Cross-tenant data flow is FORBIDDEN outside the sanctioned aggregate
helpers below (``aggregate_*``) — simlint family 13 ``tenant-isolation``
(LINTING.md §13) machine-checks the scope.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.state import SimState, init_state
from multi_cluster_simulator_tpu.tenancy.params import (
    TenantParams, default_tenant_params, stack_tenant_params,
)


def n_tenants(tp: TenantParams) -> int:
    """Tenant count of a stacked params pytree (0-d leaves = one cell)."""
    idx = jnp.asarray(tp.policy.idx)
    return int(idx.shape[0]) if idx.ndim else 1


def init_tenant_state(cfg: SimConfig, specs, tp: Optional[TenantParams] = None,
                      plan=None) -> SimState:
    """One tenant's reset constellation — the SAME init the standalone
    reference run uses, so stacked cells and standalone states start
    bit-identical. Leaves are cloned (init_state shares zero-filled
    buffers, which a donating dispatch may not receive twice), and with
    generative faults armed the churn streams reseed from the tenant's
    ``fault_seed`` leaf: per-tenant failure patterns from one shared
    FaultConfig shape (the envs/ reset discipline)."""
    state = jax.tree.map(jnp.copy, init_state(cfg, specs, plan=plan))
    if tp is not None and cfg.faults.enabled and cfg.faults.mode != "trace":
        from multi_cluster_simulator_tpu.faults import schedule as fsch
        key = jax.random.PRNGKey(jnp.asarray(tp.fault_seed, jnp.uint32))
        state = state.replace(faults=fsch.reseed(
            state.faults, key, cfg.faults, eligible=state.node_active))
    return state


def stack_tenant_states(cells: Sequence[SimState]) -> SimState:
    """Stack per-tenant states leaf-wise on a leading [T] axis."""
    if not cells:
        raise ValueError("stack_tenant_states needs at least one tenant")
    return jax.tree.map(lambda *ls: jnp.stack(ls), *cells)


def tenant_cell(tree, i: int):
    """Extract tenant ``i``'s cell from any tenant-stacked pytree (host
    side: parity probes, snapshots — never inside the traced program)."""
    return jax.tree.map(lambda leaf: leaf[i], tree)


def shard_tenant_batch(tree, mesh, axis: str = "tenants"):
    """Shard a tenant-stacked pytree over ``mesh``'s ``axis``: every leaf
    splits on its leading (tenant) dimension via the same pytree-prefix
    placement the cluster mesh and the env batch use
    (parallel/sharded_engine._device_put_tree). Tenants are independent,
    so data-parallel jit needs no shard_map and no new collectives —
    results are bitwise identical to the unsharded batch."""
    from jax.sharding import PartitionSpec as P

    from multi_cluster_simulator_tpu.parallel.mesh import nearest_divisible
    from multi_cluster_simulator_tpu.parallel.sharded_engine import (
        _device_put_tree,
    )

    n = mesh.shape[axis]
    lead = jax.tree.leaves(tree)[0].shape[0]
    if lead % n != 0:
        lo, hi = nearest_divisible(lead, n)
        valid = f"{hi}" if lo == 0 else f"{lo} or {hi}"
        raise ValueError(
            f"tenant batch ({lead}) must divide by mesh size ({n}); "
            f"nearest valid tenant counts: {valid}")
    return _device_put_tree(tree, P(axis), mesh)


class TenantBatch:
    """Batched multi-tenant drivers over one ``Engine``.

    The engine is shared (one config shape, one policy set — selection
    and hyperparameters are per-tenant TRACED leaves); only the state,
    arrivals, and params carry the tenant axis. Every ``*_fn`` builder
    returns a callable with the compiled program on ``._jit`` — the
    jit-cache-count probe surface (the envs/ ``batch_step_fn``
    convention, audited by tools/simtrace entry ``tenancy.run_io``)."""

    def __init__(self, cfg: SimConfig, specs, policies=None, plan=None):
        self.cfg = cfg
        self.specs = list(specs)
        self.plan = plan
        self.engine = Engine(cfg, policies=policies)

    # -- construction ------------------------------------------------------
    def default_params(self, T: int, name: Optional[str] = None,
                       fault_seed0: int = 0) -> TenantParams:
        """T identical-default tenants with DISTINCT fault seeds — the
        baseline a caller then perturbs leaf-wise per tenant. ``name``
        picks a member of this batch's PolicySet (the engine's set)."""
        cells = [default_tenant_params(self.cfg, pset=self.engine.pset,
                                       name=name, fault_seed=fault_seed0 + i)
                 for i in range(T)]
        return stack_tenant_params(cells)

    def init_stacked(self, tp: TenantParams) -> SimState:
        """The stacked reset constellation for every tenant in ``tp``."""
        T = n_tenants(tp)
        stacked = jnp.asarray(tp.policy.idx).ndim > 0
        return stack_tenant_states([
            init_tenant_state(self.cfg, self.specs, tenant_cell(tp, i)
                              if stacked else tp, plan=self.plan)
            for i in range(T)])

    # -- batched drivers ---------------------------------------------------
    def run_io_fn(self, donate: bool = True, obs: bool = False):
        """The tenant-batched dispatch unit: vmapped ``Engine.run_io``
        over (state, rows, counts, params[, mbuf]) — rows stacked to
        [T, Tt, C, K, NF], counts [T, Tt, C]. One executable for any
        tenant count at a fixed (T, Tt, K) shape; donated stacked state
        (the serving tier's dispatch contract, now with a tenant axis)."""
        eng = self.engine

        if obs:
            def cell(state, rows, counts, tp, mbuf):
                return eng.run_io(state, rows, counts, params=tp.policy,
                                  mbuf=mbuf)

            fn = jax.jit(jax.vmap(cell, in_axes=(0, 0, 0, 0, 0)),
                         donate_argnums=(0,) if donate else ())

            def call(state, rows, counts, tp, mbuf):
                return fn(state, rows, counts, tp, mbuf)
        else:
            def cell(state, rows, counts, tp):
                return eng.run_io(state, rows, counts, params=tp.policy)

            fn = jax.jit(jax.vmap(cell, in_axes=(0, 0, 0, 0)),
                         donate_argnums=(0,) if donate else ())

            def call(state, rows, counts, tp):
                return fn(state, rows, counts, tp)

        call._jit = fn
        return call

    def run_fn(self, n_ticks: int, donate: bool = True):
        """Vmapped tick-indexed ``Engine.run`` over stacked TickArrivals
        (the batch tier's form: [T]-stacked ``rows``/``counts``,
        ``n_ticks`` static and shared — ticks are a shape)."""
        eng = self.engine

        def cell(state, ta, tp):
            return eng.run(state, ta, n_ticks, params=tp.policy)

        fn = jax.jit(jax.vmap(cell, in_axes=(0, 0, 0)),
                     donate_argnums=(0,) if donate else ())

        def call(state, ta, tp):
            return fn(state, ta, tp)

        call._jit = fn
        return call

    def run_compressed_fn(self, n_ticks: int, donate: bool = True):
        """Vmapped event-compressed driver: each tenant leaps its own
        quiescent gaps (the batched while_loop masks finished lanes, so
        a leaping tenant never perturbs a dense one — bit-identical per
        cell to the standalone compressed run)."""
        eng = self.engine

        def cell(state, ta, tp):
            out = eng.run_compressed(state, ta, n_ticks, params=tp.policy)
            return out[0] if isinstance(out, tuple) else out

        fn = jax.jit(jax.vmap(cell, in_axes=(0, 0, 0)),
                     donate_argnums=(0,) if donate else ())

        def call(state, ta, tp):
            return fn(state, ta, tp)

        call._jit = fn
        return call


def stack_tick_arrivals(tas: Sequence[st.TickArrivals]) -> st.TickArrivals:
    """Stack per-tenant bucketed streams on a leading [T] axis. All
    tenants must share one (Tt, C, K) shape — pad K to the tenant-max
    bucket first (the grid-global-K move from tools/tournament.py)."""
    shapes = {tuple(np.asarray(ta.rows).shape) for ta in tas}
    if len(shapes) != 1:
        raise ValueError(
            f"tenant streams must share one (Tt, C, K, NF) shape before "
            f"stacking; got {sorted(shapes)} — pad K to the tenant-max "
            "bucket (pad_tick_arrivals)")
    return st.TickArrivals(
        rows=jnp.stack([jnp.asarray(ta.rows) for ta in tas]),
        counts=jnp.stack([jnp.asarray(ta.counts) for ta in tas]))


def pad_tick_arrivals(ta: st.TickArrivals, k: int) -> st.TickArrivals:
    """Pad a bucketed stream's K axis to the shared tenant-max bucket
    with invalid rows (ingest masks rows beyond each tick's count, so
    wider padding is semantically invisible)."""
    from multi_cluster_simulator_tpu.ops import queues as Q
    rows, counts = np.asarray(ta.rows), np.asarray(ta.counts)
    k0 = rows.shape[2]
    if k0 > k:
        raise ValueError(f"stream K {k0} exceeds the shared bucket {k}")
    if k0 == k:
        return st.TickArrivals(rows=rows, counts=counts)
    pad = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                          rows.shape[:2] + (k - k0, rows.shape[3])).copy()
    return st.TickArrivals(rows=np.concatenate([rows, pad], axis=2),
                           counts=counts)


# ---------------------------------------------------------------------------
# sanctioned cross-tenant aggregate sites (LINTING.md §13): the ONLY places
# a reduction may cross the tenant axis — everything else in tenancy/ is
# per-tenant by construction, and simlint's tenant-isolation family flags
# any reduction or cross-row indexing outside these functions.
# ---------------------------------------------------------------------------

def aggregate_placed(stacked_state: SimState) -> int:
    """Total placed jobs across every tenant (host-side, post-run)."""
    stacked_placed = np.asarray(stacked_state.placed_total)
    return int(np.sum(stacked_placed))


def aggregate_drops(stacked_state: SimState) -> dict:
    """Summed drop counters across tenants — the zero-drops gate's view
    (any nonzero names the tenant in the per-cell probe, not here)."""
    from multi_cluster_simulator_tpu.utils.trace import total_drops
    return total_drops(stacked_state)
