"""Multi-tenant constellation hosting: the tenant axis (ROADMAP item 3).

``TenantParams`` (params.py) holds everything that varies per tenant as
traced leaves; ``TenantBatch`` (host.py) vmaps the engine's drivers over
a leading tenant axis — T independent constellations, one compiled
program, donated stacked state, per-tenant fault streams, mesh sharding
via pytree-prefix replication. The serving front door hosts the batch
behind per-tenant routing/quota/stats (services/serving.py); bench.py
``--tenants`` records the aggregate-throughput row.
"""

from multi_cluster_simulator_tpu.tenancy.host import (  # noqa: F401
    TenantBatch, aggregate_drops, aggregate_placed, init_tenant_state,
    n_tenants, pad_tick_arrivals, shard_tenant_batch, stack_tenant_states,
    stack_tick_arrivals, tenant_cell,
)
from multi_cluster_simulator_tpu.tenancy.params import (  # noqa: F401
    TenantParams, default_tenant_params, stack_tenant_params,
    tenant_params_digest,
)
