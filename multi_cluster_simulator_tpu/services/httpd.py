"""Routed threading HTTP server + client helpers.

The reference builds every control-plane surface on Go's stdlib ``net/http``
(mux handlers registered per path, e.g. pkg/scheduler/server.go:22-153,
pkg/registry/server.go:180-217). This is the Python-stdlib equivalent: one
``ThreadingHTTPServer`` per service with a route table, plus tiny urllib
client helpers for the JSON POST / GET idioms the services use between each
other (http.Post with a JSON body, server.go:207; http.Get heartbeats,
registry/server.go:141).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from multi_cluster_simulator_tpu.services import telemetry

# A handler takes (body_bytes, headers_dict) and returns
# (status_code, body_bytes_or_None). Content type is JSON unless overridden.
Route = Callable[[bytes, dict], tuple[int, Optional[bytes]]]


class RoutedHTTPServer:
    """An HTTP server with a (method, path) route table.

    ``port=0`` binds an ephemeral port (the reference picks random ports in
    [1025, 49151), cmd/scheduler/main.go:62-63 — the OS-assigned ephemeral
    port is the same capability without the collision risk).

    When a ``tracer`` is supplied, every dispatched request runs inside a
    server span whose parent is read from the ``TRACE_HEADER`` request
    header — the otelhttp.NewHandler middleware the reference wraps every
    service mux with (internal/service/service.go:37-38).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, logger=None,
                 tracer: Optional[telemetry.Tracer] = None):
        self.routes: dict[tuple[str, str], Route] = {}
        self.logger = logger
        self.tracer = tracer
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _dispatch(self, method: str):
                path, _, query = self.path.partition("?")
                fn = outer.routes.get((method, path))
                if fn is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                headers = dict(self.headers)
                if query:
                    # query strings reach handlers through a synthetic
                    # header — the Route signature stays (body, headers)
                    # for every existing wire-parity handler (the serving
                    # tier's GET /quote?cluster=N reads it)
                    headers["X-MCS-Query"] = query
                try:
                    if outer.tracer is not None:
                        parent = headers.get(telemetry.TRACE_HEADER)
                        with outer.tracer.start_span(
                                f"{method} {path}", parent=parent,
                                kind="server"):
                            status, out = fn(body, headers)
                    else:
                        status, out = fn(body, headers)
                except Exception as e:  # route bug -> 500, keep serving
                    if outer.logger is not None:
                        outer.logger.error("handler %s %s failed: %r",
                                           method, path, e)
                    status, out = 500, repr(e).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out or b"")))
                self.end_headers()
                if out:
                    self.wfile.write(out)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, fmt, *args):  # quiet; services log themselves
                if outer.logger is not None:
                    outer.logger.debug("http: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self.host = host
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"

    def route(self, method: str, path: str, fn: Route) -> None:
        self.routes[(method, path)] = fn

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"httpd:{self.port}", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# client helpers
# ---------------------------------------------------------------------------

def _trace_headers(headers: dict) -> dict:
    """Inject the active span context, if any — otelhttp.NewTransport
    (pkg/scheduler/server.go:47, pkg/client/server.go:57)."""
    ctx = telemetry.current_context()
    if ctx is not None:
        headers = {**headers, telemetry.TRACE_HEADER: ctx}
    return headers


def post_json(url: str, obj, timeout: float = 5.0) -> tuple[int, bytes]:
    """http.Post(url, "application/json", body) — returns (status, body).
    Transport errors surface as status 0."""
    data = json.dumps(obj).encode()
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers=_trace_headers({"Content-Type": "application/json"}))
    return _do(req, timeout)


def post_bytes(url: str, data: bytes, content_type: str = "text/plain",
               timeout: float = 5.0) -> tuple[int, bytes]:
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers=_trace_headers({"Content-Type": content_type}))
    return _do(req, timeout)


def get(url: str, timeout: float = 5.0) -> tuple[int, bytes]:
    return _do(urllib.request.Request(url, method="GET",
                                      headers=_trace_headers({})), timeout)


def delete(url: str, data: bytes = b"", timeout: float = 5.0) -> tuple[int, bytes]:
    req = urllib.request.Request(
        url, data=data, method="DELETE",
        headers=_trace_headers({"Content-Type": "text/plain"}))
    return _do(req, timeout)


def _do(req, timeout: float) -> tuple[int, bytes]:
    import http.client

    try:
        with urllib.request.urlopen(req, timeout=timeout) as res:
            return res.status, res.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except (urllib.error.URLError, OSError, TimeoutError,
            http.client.HTTPException):
        # HTTPException covers a server dying MID-RESPONSE
        # (IncompleteRead after the status line — a kill -9 between write
        # and flush): same retryable transport failure as a refused
        # connection, and the caller must not assume the request was or
        # was not processed (tools/chaos.py leans on exactly that)
        return 0, b""
