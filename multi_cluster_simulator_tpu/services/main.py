"""Entry points — the cmd/* equivalents, as one CLI with subcommands.

Reference: cmd/{registry,scheduler,trader,client,log}/main.go. Launch the
same five-process topology:

  python -m multi_cluster_simulator_tpu.services.main registry
  python -m multi_cluster_simulator_tpu.services.main scheduler assets/cluster_small.json
  python -m multi_cluster_simulator_tpu.services.main trader 127.0.0.1:50051
  python -m multi_cluster_simulator_tpu.services.main client http://127.0.0.1:8080
  python -m multi_cluster_simulator_tpu.services.main log grading.log

Each subcommand blocks until EOF/newline on stdin (the reference's
"press any key to stop" lifecycle, internal/service/service.go:44-55) or
SIGINT, then deregisters and shuts down.
"""

from __future__ import annotations

import argparse
import sys

from multi_cluster_simulator_tpu.config import (
    REGISTRY_PORT, PolicyKind, SimConfig, TraderConfig,
)


def _wait_for_key(name: str) -> None:
    print(f"{name} started. Press Enter to stop", flush=True)
    try:
        sys.stdin.readline()
    except KeyboardInterrupt:
        pass


def cmd_registry(args) -> None:
    from multi_cluster_simulator_tpu.services.registry import RegistryServer
    from multi_cluster_simulator_tpu.services.telemetry import create_logger
    reg = RegistryServer(port=args.port, speed=args.speed,
                         logger=create_logger("registry"))
    reg.start()
    _wait_for_key(f"registry at {reg.url}")
    reg.shutdown()


def cmd_scheduler(args) -> None:
    from multi_cluster_simulator_tpu.core.spec import load_cluster_json
    from multi_cluster_simulator_tpu.services.scheduler_host import (
        SchedulerService,
    )
    cfg = SimConfig(policy=PolicyKind[args.policy],
                    borrowing=args.policy == "FIFO",
                    trader=TraderConfig(enabled=False))
    svc = SchedulerService(args.name, load_cluster_json(args.cluster_json),
                           cfg, registry_url=args.registry, speed=args.speed,
                           port=args.port, checkpoint_path=args.checkpoint)
    svc.start()
    print(f"scheduler HTTP {svc.url} gRPC {svc.grpc_addr}", flush=True)
    _wait_for_key(svc.name)
    svc.shutdown()


def cmd_trader(args) -> None:
    from multi_cluster_simulator_tpu.services.trader_host import TraderService
    svc = TraderService(args.name, args.scheduler_rpc,
                        registry_url=args.registry, speed=args.speed)
    svc.start()
    print(f"trader HTTP {svc.url} gRPC {svc.grpc_addr}", flush=True)
    _wait_for_key(svc.name)
    svc.shutdown()


def cmd_client(args) -> None:
    from multi_cluster_simulator_tpu.services.workload import (
        WorkloadClientService,
    )
    svc = WorkloadClientService(args.name, args.scheduler_url,
                                speed=args.speed, max_jobs=args.max_jobs)
    svc.start()
    _wait_for_key(svc.name)
    svc.shutdown()


def cmd_log(args) -> None:
    from multi_cluster_simulator_tpu.services.logsink import LogSinkServer
    svc = LogSinkServer(args.destination, port=args.port,
                        registry_url=args.registry)
    svc.start()
    _wait_for_key(f"log sink at {svc.url} -> {args.destination}")
    svc.shutdown()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="mcs-services")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="virtual-time speedup (1.0 = reference real-time)")
    ap.add_argument("--registry", default=f"http://127.0.0.1:{REGISTRY_PORT}")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def add_parser(name):
        # accept the global flags AFTER the subcommand too ("main scheduler
        # x.json --speed 200"); SUPPRESS keeps the top-level defaults in
        # force when the trailing flag is absent
        p = sub.add_parser(name)
        p.add_argument("--speed", type=float, default=argparse.SUPPRESS,
                       help="virtual-time speedup (1.0 = reference real-time)")
        p.add_argument("--registry", default=argparse.SUPPRESS)
        return p

    p = add_parser("registry")
    p.add_argument("--port", type=int, default=REGISTRY_PORT)
    p.set_defaults(fn=cmd_registry)

    p = add_parser("scheduler")
    p.add_argument("cluster_json")
    p.add_argument("--name", default="Scheduler")
    p.add_argument("--policy", default="DELAY", choices=["FIFO", "DELAY", "FFD"])
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="persist state here every 50 ticks and restore on "
                        "start (queued/running work survives restarts)")
    p.set_defaults(fn=cmd_scheduler)

    p = add_parser("trader")
    p.add_argument("scheduler_rpc", help="scheduler gRPC host:port")
    p.add_argument("--name", default="Trader")
    p.set_defaults(fn=cmd_trader)

    p = add_parser("client")
    p.add_argument("scheduler_url")
    p.add_argument("--name", default="Client")
    p.add_argument("--max-jobs", type=int, default=None)
    p.set_defaults(fn=cmd_client)

    p = add_parser("log")
    p.add_argument("destination", nargs="?", default="./grading.log")
    p.add_argument("--port", type=int, default=0)
    p.set_defaults(fn=cmd_log)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
