"""Retry discipline primitives: jittered exponential backoff + a per-peer
circuit breaker.

Shared by both sides of the service mesh's failure story:

- **callers of flaky peers** (services/trader_host.py): bounded RPC
  retries with ``jittered_backoff_ms`` between attempts, and a
  ``CircuitBreaker`` per peer so a dead trader stops costing every
  monitor round its full collect-window timeout — after
  ``fail_threshold`` consecutive failures the breaker OPENS (calls are
  skipped outright), and after ``reset_after_s`` it goes HALF-OPEN,
  letting exactly one probe through on the next cadence: success closes
  it, failure re-opens it.
- **clients of back-pressured servers** (bench serving/live clients,
  services/workload.py): 503 quotes carry ``RetryAfterMs``; the client
  sleeps a jittered exponential multiple of the quote (never a fixed
  sleep — synchronized clients re-collide — and never an immediate
  retry) under a bounded attempt budget, surfacing exhaustion instead of
  spinning forever.

The jitter is the standard "equal jitter" form: half the exponential
delay deterministic, half uniform — bounded below (no thundering
immediate retries) and decorrelated above.
"""

from __future__ import annotations

import threading
import time


def jittered_backoff_ms(attempt: int, base_ms: float, cap_ms: float,
                        rng) -> float:
    """Delay before retry ``attempt`` (0-based): equal-jitter exponential
    ``d = min(cap, base * 2^attempt); sleep in [d/2, d)``. ``rng`` is a
    ``numpy.random.Generator`` (callers own the seed/determinism
    policy)."""
    d = min(float(cap_ms), float(base_ms) * (2.0 ** max(int(attempt), 0)))
    return d / 2.0 + float(rng.uniform(0.0, d / 2.0))


class CircuitBreaker:
    """Three-state per-peer breaker (closed -> open -> half-open).

    Thread-safe; ``allow()`` is the gate callers consult before dialing,
    ``record_success``/``record_failure`` feed it outcomes. While OPEN all
    calls are skipped; after ``reset_after_s`` ONE probe is admitted
    (HALF-OPEN) — its outcome closes or re-opens the breaker. ``clock``
    is injectable for tests."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, fail_threshold: int = 3, reset_after_s: float = 10.0,
                 clock=time.monotonic):
        self.fail_threshold = max(int(fail_threshold), 1)
        self.reset_after_s = float(reset_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self.opened_total = 0  # lifetime opens (telemetry)

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == self.OPEN
                    and self._clock() - self._opened_at >= self.reset_after_s):
                return self.HALF_OPEN  # would admit a probe
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_after_s:
                    # admit exactly one probe; concurrent callers see
                    # HALF_OPEN and are refused until it reports back
                    self._state = self.HALF_OPEN
                    return True
                return False
            return False  # HALF_OPEN: a probe is already in flight

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (self._state == self.HALF_OPEN
                    or self._failures >= self.fail_threshold):
                if self._state != self.OPEN:
                    self.opened_total += 1
                self._state = self.OPEN
                self._opened_at = self._clock()
