"""Centralized log service + client redirect.

Reference: log/ — a tiny HTTP sink whose ``POST /log`` handler appends each
body to a destination file (log/server.go:12-40; cmd/log writes
``./grading.log``), and a client shim that redirects a service's stdlib
logger output to HTTP POSTs with a ``[ServiceName] - `` prefix
(log/client.go:12-32).
"""

from __future__ import annotations

import logging
import threading

from multi_cluster_simulator_tpu.services import httpd
from multi_cluster_simulator_tpu.services.registry import (
    RegistryClient, SERVICE_LOG,
)


class LogSinkServer:
    """The log service process (log/server.go + cmd/log/main.go)."""

    def __init__(self, destination: str, host: str = "127.0.0.1",
                 port: int = 0, registry_url: str | None = None):
        self.destination = destination
        self._lock = threading.Lock()
        self.httpd = httpd.RoutedHTTPServer(host, port)
        self.httpd.route("POST", "/log", self._handle_log)
        self.url = self.httpd.url
        self.registry: RegistryClient | None = None
        if registry_url is not None:
            self.registry = RegistryClient(self.httpd, registry_url)

    def start(self) -> None:
        self.httpd.start()
        if self.registry is not None:  # cmd/log/main.go:23-33
            self.registry.register(SERVICE_LOG, self.url, [])

    def shutdown(self) -> None:
        if self.registry is not None:
            self.registry.shutdown()
        self.httpd.shutdown()

    def _handle_log(self, body: bytes, headers: dict):
        if not body:
            return 400, None  # server.go:27-30
        # open/close per write, like the reference's fileLog (server.go:12-21)
        with self._lock, open(self.destination, "a") as f:
            f.write(body.decode(errors="replace").rstrip("\n") + "\n")
        return 200, None


class RemoteLogHandler(logging.Handler):
    """SetClientLogger (log/client.go:12-32): route a service's log records
    to the sink as ``[ServiceName] - <message>`` lines."""

    def __init__(self, sink_url: str, service_name: str):
        super().__init__()
        self.sink_url = sink_url
        self.prefix = f"[{service_name}] - "

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = self.prefix + record.getMessage()
            httpd.post_bytes(f"{self.sink_url}/log", msg.encode())
        except Exception:  # logging must never take the service down
            pass


def set_client_logger(logger: logging.Logger, sink_url: str,
                      service_name: str) -> None:
    logger.addHandler(RemoteLogHandler(sink_url, service_name))
