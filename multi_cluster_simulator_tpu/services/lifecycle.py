"""Service bootstrap + shutdown — the internal/service equivalent.

Reference: internal/service/service.go:13-63 — every ``cmd/`` main calls
``service.Start(ctx, host, port, registration, registerHandlers)`` which
(1) registers the HTTP handlers, (2) starts the server, (3) registers with
the registry; shutdown deregisters and stops the server. The telemetry
factories (telemetry.go:43-143) hang off the same bootstrap. ``Service``
bundles exactly that: httpd + logger + tracer + meter + registry client with
one ``start()`` / ``shutdown()`` pair. Subclasses add routes in
``register_handlers`` and extra threads via ``on_start``/``on_shutdown``.
"""

from __future__ import annotations

from typing import Optional

from multi_cluster_simulator_tpu.services import httpd, telemetry
from multi_cluster_simulator_tpu.services.registry import RegistryClient


class Service:
    """One microservice process: HTTP surface + telemetry + registration."""

    service_name: str = "Service"
    required_services: list = []

    def __init__(self, name: str, registry_url: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0, speed: float = 1.0,
                 log_mode: str = "development",
                 metrics_path: Optional[str] = None,
                 spans_path: Optional[str] = None):
        self.name = name
        self.speed = speed
        self.logger = telemetry.create_logger(name, mode=log_mode)
        self.tracer = telemetry.Tracer(name, path=spans_path)
        self.meter = telemetry.Meter(name, export_path=metrics_path,
                                     export_period_s=5.0 / speed)
        self.httpd = httpd.RoutedHTTPServer(host, port, logger=self.logger,
                                            tracer=self.tracer)
        self.url = self.httpd.url
        # What gets registered as ServiceURL. Defaults to the HTTP server;
        # the trader advertises its gRPC address instead (the reference
        # registers the trader's gRPC addr, cmd/trader/main.go:62-75).
        self.advertised_url = self.url
        self.registry: Optional[RegistryClient] = None
        if registry_url is not None:
            self.registry = RegistryClient(self.httpd, registry_url,
                                           logger=self.logger,
                                           on_update=self.on_providers_update)
        self._started = False

    # -- subclass hooks --
    def register_handlers(self) -> None:
        """Install routes on self.httpd (RegisterHandlers analogue)."""

    def health(self) -> tuple[bool, dict]:
        """Liveness verdict for ``/healthz``: (healthy, detail). Subclasses
        override to check their background loops (the serving tier's pacer
        and drive threads, the per-request host's tick loop) — a service
        whose loops have died must flip the surface to 503, not keep
        answering 200 off a wedged core."""
        return True, {}

    def wedged(self) -> bool:
        """True when a shutdown join timed out and a loop thread never
        exited (the thread may still own shared state). ``shutdown``
        checks this AFTER ``on_shutdown``: a wedged service keeps its
        HTTP diagnostic surface alive — /healthz answering 503 with the
        wedge named — instead of tearing the transport down and
        returning as if shutdown succeeded; the process owner decides
        what to kill."""
        return bool(getattr(self, "_wedged", None))

    def _handle_healthz(self, body: bytes, headers: dict):
        import json
        ok, detail = self.health()
        payload = {"status": "ok" if ok else "unhealthy",
                   "service": self.name, **detail}
        return (200 if ok else 503), json.dumps(payload).encode()

    def _handle_metrics(self, body: bytes, headers: dict):
        return 200, self.meter.render_prometheus().encode()

    def on_start(self) -> None:
        """Start background loops (tick threads, monitors)."""

    def on_shutdown(self) -> None:
        """Stop background loops (runs while the HTTP surface still serves)."""

    def on_stopped(self) -> None:
        """Runs after the HTTP server is down — for final state snapshots
        that must not race still-arriving mutations."""

    def on_providers_update(self, patch: dict) -> None:
        """Called when the registry pushes a provider patch."""

    # -- lifecycle (service.go:13-33) --
    def start(self) -> None:
        if self._started:
            return
        # default observability surface on EVERY service host: /healthz
        # (the health() hook) and a Prometheus-text /metrics off the
        # Meter. Registered before register_handlers so a subclass route
        # wins if it needs to specialize either path.
        self.httpd.route("GET", "/healthz", self._handle_healthz)
        self.httpd.route("GET", "/metrics", self._handle_metrics)
        self.register_handlers()
        self.httpd.start()
        self.meter.start_exporter()
        self.on_start()  # may set advertised_url (gRPC services)
        if self.registry is not None:
            self.registry.register(self.service_name, self.advertised_url,
                                   self.required_services)
        self._started = True
        self.logger.info("%s started at %s", self.service_name, self.url)

    def shutdown(self) -> None:
        if not self._started:
            return
        self._started = False
        self.on_shutdown()
        if self.registry is not None:
            self.registry.shutdown()
        if self.wedged():
            # wedged-shutdown honesty: a loop thread blew its join timeout
            # and may still own shared state. Leave the HTTP surface UP so
            # /healthz reports the 503 wedge verdict (health() checks the
            # flag unconditionally) — tearing the transport down here
            # would be returning as if shutdown succeeded.
            self.logger.error(
                "%s at %s: shutdown wedged — keeping the diagnostic HTTP "
                "surface alive (/healthz = 503)", self.service_name,
                self.url)
            return
        self.meter.stop_exporter()
        self.meter.export_otlp()  # final snapshot to the collector, if any
        self.tracer.shutdown()  # flush the last OTLP span batch
        self.httpd.shutdown()
        self.on_stopped()
        self.logger.info("%s at %s stopped", self.service_name, self.url)

    # -- context manager sugar for tests --
    def __enter__(self) -> "Service":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
