#!/bin/sh
# Regenerate *_pb2.py from the proto schemas (the reference's
# pkg/trader/proto/protoc.sh analogue). grpc_tools is not available in this
# image, so only message classes are generated; the service method tables
# live in services/rpc.py over grpcio's generic handlers.
set -e
cd "$(dirname "$0")"
protoc --python_out=. trader.proto resource_channel.proto
# package-qualify the cross-file import for package-relative loading
sed -i 's/^import trader_pb2 as trader__pb2$/from multi_cluster_simulator_tpu.services.proto import trader_pb2 as trader__pb2/' resource_channel_pb2.py
