#!/bin/sh
# Regenerate *_pb2.py from the proto schemas (the reference's
# pkg/trader/proto/protoc.sh analogue). grpc_tools is not available in this
# image, so only message classes are generated; the service method tables
# live in services/rpc.py over grpcio's generic handlers.
set -e
cd "$(dirname "$0")"
protoc --python_out=. trader.proto resource_channel.proto \
  otlp_common.proto otlp_resource.proto otlp_trace.proto otlp_metrics.proto \
  otlp_trace_service.proto otlp_metrics_service.proto
# package-qualify the cross-file imports for package-relative loading
sed -i -E 's/^import (trader|resource_channel|otlp_[a-z_]+)_pb2 as (\S+)$/from multi_cluster_simulator_tpu.services.proto import \1_pb2 as \2/' ./*_pb2.py
