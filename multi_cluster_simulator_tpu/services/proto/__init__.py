"""Generated protobuf message modules (see generate.sh)."""
