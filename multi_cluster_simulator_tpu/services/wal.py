"""Append-only staged-arrival write-ahead log for the serving tier.

The front door's 200-ack is a durability promise the process couldn't keep:
``kill -9`` between the ack and the dispatch silently lost every staged job
(only scheduler_host checkpoints; serving did not). This WAL closes that
hole: every ACCEPTED submit appends one record batch and ``fsync``s BEFORE
the handler answers 200, and restart = restore the latest atomic device
checkpoint (core/checkpoint.py) + replay the WAL suffix
(ServingScheduler._recover). tools/chaos.py kill-9s a live server at
random points and asserts zero acked-job loss and a recovered final state
bit-identical to an uninterrupted run over the same effective stream.

Format: an 8-byte magic + 16-byte random GENERATION id header, then
length-prefixed CRC-framed JSON records —
``<u32 len><u32 crc32(payload)><payload>`` — chosen for torn-tail safety,
not speed: a crash mid-append leaves at most one short/corrupt FINAL
record, which ``read_records`` detects (length short, or CRC mismatch) and
discards, reporting the last good byte offset so recovery can truncate the
tail before appending again. Double replay is idempotent by construction:
replay decides per record from the checkpoint's dispatch watermark, and a
second ``_recover`` call over the same files is a no-op
(tests/test_faults.py pins all three).

The log does NOT grow without bound: the serving checkpoint records the
byte offset of the first record the watermark has not fully covered, so
recovery SEEKS there instead of decoding the whole history, and the
checkpoint cadence COMPACTS the log once the dispatched prefix exceeds
``wal_rotate_bytes`` — ``rotate`` atomically rewrites the file as a fresh
generation holding only the live suffix (tmp + fsync + rename). The
generation id is the crash-safety net for both: a checkpoint whose stored
(generation, offset) doesn't match the current file falls back to the
full scan — offsets are purely an optimization; the replay filter is the
source of truth.

Record fields (compact keys — the log is on the ack path):
``c`` cluster, ``i`` job id, ``co`` cores, ``m`` mem, ``g`` gpu, ``du``
duration ms, ``dl`` delay-endpoint flag, ``t`` the arrival stamp (virtual
ms — identifies the destination staging tick), ``p`` 1 if the job parked
on the endpoint the policy never drains (applied at dispatch edges, so
recovery skips the first ``parked_applied`` parked records instead of
comparing ticks — and a WAL containing parked records disables the
offset/rotation optimizations wholesale: correctness first).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Optional

_HDR = struct.Struct("<II")
_MAGIC = b"MCSWAL1\0"
_GEN_LEN = 16
HEADER_LEN = len(_MAGIC) + _GEN_LEN


class WriteAheadLog:
    """Single-writer append log. ``append`` is called under the serving
    stage lock (WAL order == staging order, which is what makes replay
    reconstruct identical per-(tick, cluster) bucket order); ``fsync=True``
    is the durability contract — the 200-ack only goes out after the
    records are on disk."""

    def __init__(self, path: str, fsync: bool = True,
                 start_offset: Optional[int] = None):
        self.path = path
        self.fsync = fsync
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        if fresh:
            _write_atomic(path, _MAGIC + os.urandom(_GEN_LEN), fsync)
        elif start_offset is not None:
            # recovery truncates a torn tail before appending: a partial
            # final record followed by fresh appends would corrupt every
            # later read
            with open(path, "r+b") as f:
                f.truncate(max(start_offset, HEADER_LEN))
        with open(path, "rb") as f:
            hdr = f.read(HEADER_LEN)
        if hdr[:len(_MAGIC)] != _MAGIC:
            raise ValueError(f"{path}: not a serving WAL")
        self.generation = hdr[len(_MAGIC):].hex()
        self._f = open(path, "ab")
        self._offset = self._f.tell()

    def tell(self) -> int:
        """Current end-of-log byte offset (== the offset the NEXT record
        will start at). Callers snapshot it per staging tick so the
        checkpoint can record a seekable replay start."""
        return self._offset

    def append(self, records: list[dict]) -> None:
        if not records:
            return
        buf = bytearray()
        for rec in records:
            payload = json.dumps(rec, separators=(",", ":"),
                                 sort_keys=True).encode()
            buf += _HDR.pack(len(payload), zlib.crc32(payload))
            buf += payload
        self._f.write(bytes(buf))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._offset += len(buf)

    def rotate(self, keep_from: int) -> int:
        """Compact: drop every record byte before ``keep_from`` (all
        covered by the durable checkpoint watermark), atomically rewriting
        the file as a FRESH generation holding only the live suffix.
        Returns the byte delta callers subtract from any offsets they
        hold (``old_offset - delta`` is the new position). Crash-safe:
        tmp + fsync + rename, and a checkpoint still pointing into the
        old generation falls back to the full scan (read_records)."""
        keep_from = max(keep_from, HEADER_LEN)
        with open(self.path, "rb") as f:
            f.seek(keep_from)
            suffix = f.read(self._offset - keep_from)
        self._f.close()
        gen = os.urandom(_GEN_LEN)
        _write_atomic(self.path, _MAGIC + gen + suffix, True)
        self.generation = gen.hex()
        self._f = open(self.path, "ab")
        self._offset = self._f.tell()
        return keep_from - HEADER_LEN

    def close(self) -> None:
        self._f.close()


def _write_atomic(path: str, blob: bytes, fsync: bool) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


def read_header(path: str) -> Optional[str]:
    """The log's generation id (hex), or None for a missing/empty/alien
    file."""
    try:
        with open(path, "rb") as f:
            hdr = f.read(HEADER_LEN)
    except OSError:
        return None
    if len(hdr) < HEADER_LEN or hdr[:len(_MAGIC)] != _MAGIC:
        return None
    return hdr[len(_MAGIC):].hex()


def read_records(path: str, start: Optional[int] = None,
                 generation: Optional[str] = None
                 ) -> tuple[list[dict], list[int], int, bool]:
    """Read every intact record. Returns ``(records, offsets,
    good_offset, torn)``: ``offsets[i]`` is record i's starting byte (a
    recovering server reseeds its per-tick offset table from them),
    ``good_offset`` the byte offset after the last intact record (the
    truncation point for a recovering writer), ``torn`` whether a
    short/corrupt tail was discarded. A missing file is an empty log.

    ``start``/``generation`` enable the seek optimization: when the
    stored generation matches the file's and ``start`` is a plausible
    record boundary, decoding begins there — recovery cost scales with
    the live suffix, not the log's lifetime. Any mismatch falls back to
    the full scan (offsets are an optimization, never the truth)."""
    if not os.path.exists(path):
        return [], [], 0, False
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:len(_MAGIC)] != _MAGIC:
        # pre-header legacy/garbage file: nothing trustworthy
        return [], [], 0, len(blob) > 0
    off = HEADER_LEN
    if (start is not None and generation is not None
            and generation == read_header(path)
            and HEADER_LEN <= start <= len(blob)):
        off = start
    records: list[dict] = []
    offsets: list[int] = []
    while off + _HDR.size <= len(blob):
        ln, crc = _HDR.unpack_from(blob, off)
        begin = off + _HDR.size
        end = begin + ln
        if end > len(blob):
            break  # short final record (torn append)
        payload = blob[begin:end]
        if zlib.crc32(payload) != crc:
            break  # corrupt tail — nothing after it is trustworthy
        try:
            records.append(json.loads(payload))
        except ValueError:
            break
        offsets.append(off)
        off = end
    return records, offsets, off, off != len(blob)
