"""The trader service: one market agent per cluster, paired with a scheduler.

Reference: pkg/trader. The trader consumes its scheduler's ClusterState
stream into a cached mirror (trader.go:71-108, scheduler_client.go:14-47),
runs a request-policy monitor that goes shopping when a policy breaks
(RequestPolicyMonitor, trader.go:280-325), negotiates with peer traders over
gRPC (Trade, trader.go:193-278), and serves the seller side of the same
protocol (trader/server.go:14-85). Contract sizing reuses the *same jitted
kernels* the batch engine uses (ops/sizing.py) on a padded job queue, so a
live trader and the in-batch market request identical contracts for
identical backlogs.

Reproduced as-built quirks (MARKET.md): a seller's ``currentContract`` is
set even for a *denied* request, blocking it until the 20 s TTL
(trader/server.go:44-45); every offer echoes the buyer's price, so the
"cheapest" heap degenerates to response order (trader/server.go:44).
"""

from __future__ import annotations

import random
import threading
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Optional

import jax.numpy as jnp
import numpy as np

from multi_cluster_simulator_tpu.config import (
    TRADE_COLLECT_WINDOW_S, TraderConfig,
)
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import sizing
from multi_cluster_simulator_tpu.services import rpc, telemetry
from multi_cluster_simulator_tpu.services.backoff import (
    CircuitBreaker, jittered_backoff_ms,
)
from multi_cluster_simulator_tpu.services.lifecycle import Service
from multi_cluster_simulator_tpu.services.proto import (
    resource_channel_pb2 as rc_pb,
    trader_pb2 as t_pb,
)
from multi_cluster_simulator_tpu.services.registry import SERVICE_TRADER

_SIZING_CAP = 256  # padded Level1 capacity for the sizing kernels


def _job_queue(jobs: list[tuple[int, int, int]]) -> Q.JobQueue:
    """Pad a streamed (cores, mem, dur_ms) job list into the fixed-shape
    queue tensor the sizing kernels take (one compile for any backlog)."""
    data = np.zeros((_SIZING_CAP, Q.NF), np.int32)
    n = min(len(jobs), _SIZING_CAP)
    for i, (c, m, d) in enumerate(jobs[:n]):
        data[i, Q.FID] = i + 1
        data[i, Q.FCORES] = c
        data[i, Q.FMEM] = m
        data[i, Q.FDUR] = d
    return Q.JobQueue(data=jnp.asarray(data), count=jnp.int32(n))


class TraderService(Service):
    service_name = SERVICE_TRADER
    required_services = [SERVICE_TRADER]  # discovers peer traders

    def __init__(self, name: str, scheduler_rpc_addr: str,
                 tcfg: TraderConfig = TraderConfig(),
                 registry_url: Optional[str] = None, speed: float = 1.0,
                 grpc_port: int = 0, **kw):
        super().__init__(name, registry_url=registry_url, speed=speed, **kw)
        self.tcfg = tcfg
        self.scheduler_rpc_addr = scheduler_rpc_addr
        self.grpc_port = grpc_port
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._grpc_server = None
        self.grpc_addr: Optional[str] = None
        self.sched: Optional[rpc.ResourceChannelClient] = None
        # cached clusterState mirror (trader.go:71-108)
        self._cs_lock = threading.Lock()  # guards: _cs
        self._cs = {"cores_util": 0.0, "mem_util": 0.0,
                    "total_cpu": 0, "total_mem": 0, "avg_wait_ms": 0.0}
        # seller side (trader/server.go:14-29)
        self._sell_lock = threading.Lock()  # guards: _current, _serial
        self._current: Optional[t_pb.ContractResponse] = None
        self._serial = random.getrandbits(31) or 1  # s.id = rand.Uint32()
        # peer cache + trade counters are shared between the monitor thread,
        # gRPC handler threads, and shutdown
        self._peer_lock = threading.Lock()  # guards: _peer_clients, _breakers, trades_won, trades_sold
        self._peer_clients: dict[str, rpc.TraderClient] = {}
        # Peer RPC resilience: bounded per-call retries with jittered
        # exponential backoff, and one circuit breaker per peer — a dead
        # trader used to stall EVERY monitor round for the full
        # as_completed collect-window timeout and was re-dialed forever;
        # now it costs `breaker_fail_threshold` rounds, then opens and is
        # skipped until a half-open probe (on the monitor cadence — the
        # reset horizon) succeeds. Breaker state surfaces in /metrics
        # (peer_breakers_open gauge) and the /healthz detail.
        self._breakers: dict[str, CircuitBreaker] = {}
        self.rpc_attempts = 2  # bounded per-call retry budget
        self.rpc_backoff_base_ms = 50.0 / speed
        self.breaker_fail_threshold = 3
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix=f"{name}-rpc")
        self.trades_won = 0
        self.trades_sold = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def on_start(self) -> None:
        self._grpc_server, self.grpc_addr = rpc.start_server(
            [rpc.trader_handler(self)], port=self.grpc_port)
        self.advertised_url = self.grpc_addr  # cmd/trader/main.go:62-75
        self.sched = rpc.ResourceChannelClient(self.scheduler_rpc_addr)
        for fn, tag in ((self._consume_state_stream, "state"),
                        (self._monitor_loop, "monitor")):
            th = threading.Thread(target=fn, daemon=True,
                                  name=f"{self.name}-{tag}")
            th.start()
            self._threads.append(th)

    def on_shutdown(self) -> None:
        self._stop.set()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1)
        if self.sched is not None:
            self.sched.close()
        with self._peer_lock:
            clients = list(self._peer_clients.values())
        for c in clients:
            c.close()
        for th in self._threads:
            th.join(timeout=5)
        self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------
    # scheduler state stream consumer (scheduler_client.go:14-47)
    # ------------------------------------------------------------------
    def _consume_state_stream(self) -> None:
        while not self._stop.is_set():
            try:
                for msg in self.sched.start():
                    with self._cs_lock:
                        self._cs["cores_util"] = msg.cores_utilization
                        self._cs["mem_util"] = msg.memory_utilization
                        self._cs["avg_wait_ms"] = msg.average_wait_time
                        # full setState only when totals present
                        # (TotalCpu != 0 dispatch, scheduler_client.go:30-40)
                        if msg.HasField("total_cpu"):
                            self._cs["total_cpu"] = msg.total_cpu
                            self._cs["total_mem"] = msg.total_memory
                    if self._stop.is_set():
                        return
            except Exception:
                if self._stop.wait(0.2):
                    return

    # ------------------------------------------------------------------
    # buyer: policy monitor (RequestPolicyMonitor, trader.go:280-325)
    # ------------------------------------------------------------------
    def _monitor_loop(self) -> None:
        period = self.tcfg.monitor_period_ms / 1000.0 / self.speed
        while not self._stop.wait(period):
            try:
                with self._cs_lock:
                    cs = dict(self._cs)
                # policy order: WaitTime -> fastNode, else Utilization ->
                # smallNode (newTrader appends WaitTime then Utilization,
                # trader.go:55-62; monitor walks in order, trader.go:286-311)
                if cs["avg_wait_ms"] > self.tcfg.request_max_wait_ms:
                    contract = self._size_contract("fast")
                elif (cs["cores_util"] > self.tcfg.request_core_max
                      or cs["mem_util"] > self.tcfg.request_mem_max):
                    contract = self._size_contract("small")
                else:
                    continue
                if (self.tcfg.skip_zero_contracts
                        and contract.cores == 0 and contract.memory == 0):
                    # Level1 was empty when the policy broke; trading this
                    # would attach a zero-capacity virtual node at the buyer
                    # (config.py skip_zero_contracts; divergence from
                    # trader.go:288-311, documented in MARKET.md).
                    self.logger.info("skipping zero-size contract "
                                     "(empty Level1 backlog)")
                    continue
                # the buyer-side trade span (trader.go:195,289,305): root of
                # the cross-service trace; the gRPC fan-out below propagates
                # its context to every seller
                with self.tracer.start_span("Trade", cores=contract.cores,
                                            memory=contract.memory):
                    won = self._trade(contract)
                cooldown = (self.tcfg.cooldown_success_ms if won
                            else self.tcfg.cooldown_failure_ms)
                if self._stop.wait(cooldown / 1000.0 / self.speed):
                    return
            except Exception as e:
                self.logger.error("monitor iteration failed: %r", e)

    def _size_contract(self, kind: str) -> t_pb.ContractRequest:
        """calculateContractRequest (scheduler_client.go:75-123): pull the
        Level1 backlog over ProvideJobs, then run the jitted sizing kernel."""
        jobs = []
        for batch in self.sched.provide_jobs():
            for j in batch.jobs:
                jobs.append((j.cores_needed, j.memory_needed,
                             j.unix_time_seconds.ToMilliseconds()))
        q = _job_queue(jobs)
        budget = jnp.float32(self.tcfg.budget)
        cc = jnp.float32(self.tcfg.max_core_cost)
        mc = jnp.float32(self.tcfg.max_mem_cost)
        if kind == "fast":
            c = sizing.fast_node_contract(q, budget, cc, mc)
        elif self.tcfg.small_node_sizing == "asbuilt":
            c = sizing.small_node_contract_asbuilt(q, budget, cc, mc)
        else:
            c = sizing.small_node_contract_sane(q, budget, cc, mc)
        req = t_pb.ContractRequest(
            cores=int(c.cores), memory=int(c.mem), price=float(c.price),
            trader=self.grpc_addr or "")
        req.time.FromMilliseconds(int(c.time_ms))
        return req

    def _trade(self, contract: t_pb.ContractRequest) -> bool:
        """Trade (trader.go:193-278): fan RequestResource out to all peer
        traders, collect approvals in the window, walk offers cheapest-first
        calling ApproveContract until a seller carves, then hand the node to
        our scheduler.

        Resilience (no reference analogue — Go re-dials dead peers every
        round): peers whose circuit breaker is OPEN are skipped before any
        socket is touched, each RPC gets a bounded retry budget with
        jittered exponential backoff (``_rpc_call``), and every outcome
        feeds the peer's breaker."""
        if self.registry is None:
            return False
        try:
            peers = [u for u in self.registry.get_providers(SERVICE_TRADER)
                     if u != self.advertised_url]
        except LookupError:
            return False
        if not peers:
            return False
        allowed = [u for u in peers if self._breaker(u).allow()]
        skipped = len(peers) - len(allowed)
        if skipped:
            self.meter.add("peer_rpc_skipped_open", skipped)
        self._export_breaker_gauges()
        if not allowed:
            return False
        window = TRADE_COLLECT_WINDOW_S / self.speed
        # wrap_ctx carries the Trade span context onto the pool threads so
        # each RequestResource RPC propagates it to the seller
        futs = {self._pool.submit(
            telemetry.wrap_ctx(self._rpc_call), u,
            lambda u_=u: self._peer(u_).request_resource(
                contract, max(window, 0.5))): u for u in allowed}
        offers = []
        try:
            for fut in as_completed(futs, timeout=max(window, 0.5) + 1):
                try:
                    resp = fut.result()
                except Exception:
                    continue
                if resp.approve:
                    offers.append((resp, futs[fut]))
        except TimeoutError:
            pass
        # price min-heap; all sellers echo the buyer's price
        # (trader/server.go:44), so ties resolve by response order
        offers.sort(key=lambda o: o[0].price)
        for resp, url in offers:
            try:
                node = self._rpc_call(
                    url, lambda: self._peer(url).approve_contract(resp))
            except Exception:
                continue  # heap fall-through (trader.go:265-276)
            try:
                self.sched.receive_virtual_node(node)
            except Exception as e:
                self.logger.error("receive_virtual_node failed: %r", e)
                return False
            with self._peer_lock:
                self.trades_won += 1
            self.logger.info("trade won: %d cores / %d MB from %s",
                             node.cores, node.memory, url)
            return True
        return False

    def _rpc_call(self, url: str, fn):
        """One peer RPC under the retry + breaker discipline: up to
        ``rpc_attempts`` tries with jittered exponential backoff between
        them, every outcome recorded into the peer's breaker (a half-open
        probe that fails re-opens it immediately — no second attempt).
        Runs concurrently on the fan-out pool threads, so the jitter rng
        is per-call (numpy Generators are not thread-safe; OS-entropy
        seeding is exactly right for decorrelation)."""
        br = self._breaker(url)
        rng = np.random.default_rng()
        last: Exception = RuntimeError("no attempt ran")
        for attempt in range(self.rpc_attempts):
            try:
                out = fn()
                br.record_success()
                return out
            except Exception as e:
                last = e
                br.record_failure()
                self.meter.add("peer_rpc_failures", 1)
                if attempt + 1 >= self.rpc_attempts or not br.allow():
                    break
                delay = jittered_backoff_ms(
                    attempt, self.rpc_backoff_base_ms,
                    1000.0 / self.speed, rng) / 1000.0
                if self._stop.wait(delay):
                    break
        raise last

    def _breaker(self, url: str) -> CircuitBreaker:
        with self._peer_lock:
            if url not in self._breakers:
                # half-open probe horizon = the monitor cadence: the next
                # round after the reset window admits exactly one probe
                self._breakers[url] = CircuitBreaker(
                    fail_threshold=self.breaker_fail_threshold,
                    reset_after_s=self.tcfg.monitor_period_ms / 1000.0
                    / self.speed)
            return self._breakers[url]

    def _export_breaker_gauges(self) -> None:
        with self._peer_lock:
            states = {u: b.state for u, b in self._breakers.items()}
        self.meter.set_gauge(
            "peer_breakers_open",
            float(sum(1 for s in states.values()
                      if s != CircuitBreaker.CLOSED)))
        self.meter.set_gauge("peer_breakers_known", float(len(states)))

    def health(self) -> tuple[bool, dict]:
        """/healthz: the trader itself stays healthy when peers die (that
        is the point of the breakers) — but the per-peer breaker states
        ride the detail so an operator sees WHICH peers are being
        skipped."""
        with self._peer_lock:
            states = {u: b.state for u, b in self._breakers.items()}
        open_n = sum(1 for s in states.values()
                     if s != CircuitBreaker.CLOSED)
        return True, {"peer_breakers": states,
                      "peer_breakers_open": open_n}

    def _peer(self, url: str) -> rpc.TraderClient:
        """Lazily-built peer client cache (TraderClients, trader.go:33);
        raced by the monitor thread and shutdown."""
        with self._peer_lock:
            if url not in self._peer_clients:
                self._peer_clients[url] = rpc.TraderClient(url)
            return self._peer_clients[url]

    # ------------------------------------------------------------------
    # seller: gRPC Trader service (trader/server.go:31-85)
    # ------------------------------------------------------------------
    def request_resource(self, req: t_pb.ContractRequest) -> t_pb.ContractResponse:
        with self._sell_lock:
            if self._current is not None and self._current.id != 0:
                return t_pb.ContractResponse(approve=False)
            approve = self._approve_trade(req)
            resp = t_pb.ContractResponse(
                id=self._serial, approve=approve, cores=req.cores,
                memory=req.memory, price=req.price,
                trader=self.advertised_url)
            resp.time.CopyFrom(req.time)
            self._serial += 1
            # set even when denied — blocks this seller until the TTL
            # (trader/server.go:44-45, an as-built quirk)
            self._current = resp
            ttl = self.tcfg.contract_ttl_ms / 1000.0 / self.speed
            timer = threading.Timer(ttl, self._expire_contract, args=(resp.id,))
            timer.daemon = True
            timer.start()
            return resp

    def _expire_contract(self, contract_id: int) -> None:
        with self._sell_lock:
            if self._current is not None and self._current.id == contract_id:
                self._current = None

    def _approve_trade(self, c: t_pb.ContractRequest) -> bool:
        """ApproveTrade (trader.go:141-167): utilization below thresholds
        AND free capacity >= contract AND price >= incentive."""
        with self._cs_lock:
            cs = dict(self._cs)
        t_sec = c.time.ToMilliseconds() / 1000.0
        incentive = (self.tcfg.min_core_incentive * c.cores * t_sec
                     + self.tcfg.min_mem_incentive * c.memory * t_sec)
        avail_c = cs["total_cpu"] - cs["total_cpu"] * cs["cores_util"]
        avail_m = cs["total_mem"] - cs["total_mem"] * cs["mem_util"]
        return (cs["cores_util"] < self.tcfg.approve_core_threshold
                and cs["mem_util"] < self.tcfg.approve_mem_threshold
                and avail_c >= c.cores and avail_m >= c.memory
                and c.price >= incentive)

    def approve_contract(self, resp: t_pb.ContractResponse) -> Optional[t_pb.NodeObject]:
        """Seller-side finalize: id must still match (20 s TTL), then carve
        a virtual node out of our scheduler (trader/server.go:63-85).
        Returns None on TTL/id mismatch -> DEADLINE_EXCEEDED upstream."""
        with self._sell_lock:
            if self._current is None or self._current.id != resp.id:
                return None
            req = rc_pb.VirtualNodeRequest(cores=resp.cores,
                                           memory=resp.memory)
            req.time.CopyFrom(resp.time)
            try:
                node = self.sched.provide_virtual_node(req)
            finally:
                self._current = None  # reset for future activity
            with self._peer_lock:  # always inner to _sell_lock
                self.trades_sold += 1
            return node
