"""Telemetry: structured logging, spans, metrics — the OTel/zerolog layer.

The reference wires every service with an OTLP span exporter, a periodic
metric reader, and a zerolog console/file logger via its telemetry factory
(internal/service/telemetry.go:43-143); no collector ships with the repo, so
in practice the artifacts are the log files. Here the same three factories
exist without an external collector dependency:

- ``create_logger`` — structured key=value console/file logging
  (telemetry.go:121-143: development -> console, production -> file
  ``logs/<SERVICE_NAME>-log-<timestamp>``, "both" -> both).
- ``Tracer`` — spans as JSONL records with trace/span ids and a
  ``traceparent``-style HTTP propagation header (the otelhttp transport
  equivalent, pkg/scheduler/server.go:47).
- ``Meter`` — named up/down counters and histograms with a periodic
  export thread (CreateMeterProvider's PeriodicReader,
  telemetry.go:94-119); snapshots are JSONL + a Prometheus text rendering
  (with # HELP/# TYPE) for a /metrics route.

Ecosystem compatibility (the reference's env contract, telemetry.go:26-31):
when ``OTEL_EXPORTER_OTLP_ENDPOINT`` is set, every Tracer batches spans and
every Meter posts periodic snapshots to the collector. The transport
follows the standard ``OTEL_EXPORTER_OTLP_PROTOCOL`` selector:

- ``grpc`` — the reference's transport (otlptracegrpc/otlpmetricgrpc,
  telemetry.go:43-58,94-119): protobuf Export calls on
  ``/opentelemetry.proto.collector.{trace,metrics}.v1.*Service/Export``
  against a :4317-style gRPC collector, over stubs generated from the
  transcribed OTLP schema (services/proto/otlp_*.proto).
- ``http/json`` (default here) — protojson POSTs to
  ``<endpoint>/v1/{traces,metrics}`` via stdlib urllib.

Any other selector (e.g. the spec's ``http/protobuf``) fails fast at
Tracer/Meter construction when an endpoint is configured — it used to fall
silently through to the JSON POST path. ``OTEL_EXPORTER_OTLP_INSECURE``
(truthy) forces a plaintext gRPC channel even to an https:// endpoint, per
the standard env contract.

The JSONL paths stay the no-collector default, exactly like the reference
run without a collector.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import re
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

TRACE_HEADER = "X-Trace-Context"  # traceparent analogue (HTTP)
TRACE_METADATA_KEY = "x-trace-context"  # gRPC metadata (keys must be lowercase)

OTLP_ENDPOINT_ENV = "OTEL_EXPORTER_OTLP_ENDPOINT"  # telemetry.go:28
# Standard OTel transport selector: "grpc" exports over the reference's
# transport (otlptracegrpc/otlpmetricgrpc, telemetry.go:43-58,94-119 —
# what a gRPC-only collector on :4317 accepts); "http/json" (this
# framework's default) posts protojson to <endpoint>/v1/{traces,metrics}.
OTLP_PROTOCOL_ENV = "OTEL_EXPORTER_OTLP_PROTOCOL"
# Standard OTel TLS opt-out (the spec's OTEL_EXPORTER_OTLP_INSECURE): a
# truthy value forces a plaintext channel even to an https:// endpoint.
OTLP_INSECURE_ENV = "OTEL_EXPORTER_OTLP_INSECURE"

SUPPORTED_OTLP_PROTOCOLS = ("grpc", "http/json")


def _otlp_endpoint() -> Optional[str]:
    ep = os.environ.get(OTLP_ENDPOINT_ENV, "").strip()
    return ep.rstrip("/") or None


def _otlp_protocol() -> str:
    return os.environ.get(OTLP_PROTOCOL_ENV, "").strip() or "http/json"


def _check_otlp_protocol(protocol: str) -> str:
    """Fail fast on a transport this framework does not implement: an
    unrecognized selector (e.g. the spec's ``http/protobuf``) used to fall
    silently through to the JSON POST path, exporting a payload a
    protobuf-only collector rejects with no hint at the real cause."""
    if protocol not in SUPPORTED_OTLP_PROTOCOLS:
        raise ValueError(
            f"unsupported OTLP protocol {protocol!r} (from {OTLP_PROTOCOL_ENV}"
            " or otlp_protocol=): supported protocols are "
            f"{', '.join(SUPPORTED_OTLP_PROTOCOLS)}")
    return protocol


def _otlp_insecure() -> bool:
    return os.environ.get(OTLP_INSECURE_ENV, "").strip().lower() in (
        "1", "true", "yes")


def _make_grpc_channel(endpoint: str):
    """A long-lived channel to the collector; https:// selects TLS (a
    plaintext channel to a TLS collector fails every handshake silently)
    unless OTEL_EXPORTER_OTLP_INSECURE opts out."""
    import grpc

    secure = endpoint.startswith("https://") and not _otlp_insecure()
    target = endpoint
    for scheme in ("http://", "https://", "grpc://"):
        if target.startswith(scheme):
            target = target[len(scheme):]
            break
    if secure:
        return grpc.secure_channel(target, grpc.ssl_channel_credentials())
    return grpc.insecure_channel(target)


def _is_hex(s: str, n: int) -> bool:
    if len(s) != n:
        return False
    try:
        bytes.fromhex(s)
        return True
    except ValueError:
        return False


def _span_pb(span: dict):
    """One OTLP Span protobuf from the internal JSON-shaped span dict.
    Returns None for a span whose ids don't convert (a malformed propagated
    context must not poison the whole batch — see start_span's validation,
    the first line of defense)."""
    from multi_cluster_simulator_tpu.services.proto import otlp_trace_pb2 as T

    if not (_is_hex(span["traceId"], 32) and _is_hex(span["spanId"], 16)):
        return None
    pb = T.Span(trace_id=bytes.fromhex(span["traceId"]),
                span_id=bytes.fromhex(span["spanId"]),
                name=span["name"], kind=span.get("kind", 1),
                start_time_unix_nano=int(span["startTimeUnixNano"]),
                end_time_unix_nano=int(span["endTimeUnixNano"]))
    parent = span.get("parentSpanId")
    if parent and _is_hex(parent, 16):
        pb.parent_span_id = bytes.fromhex(parent)
    for kv in span.get("attributes", []):
        a = pb.attributes.add(key=kv["key"])
        v = kv["value"]
        if "boolValue" in v:
            a.value.bool_value = v["boolValue"]
        elif "intValue" in v:
            a.value.int_value = int(v["intValue"])
        elif "doubleValue" in v:
            a.value.double_value = v["doubleValue"]
        else:
            a.value.string_value = v.get("stringValue", "")
    return pb


def _grpc_export_spans(channel, service: str, batch: list[dict],
                       timeout: float = 3.0) -> bool:
    """Export over /opentelemetry.proto.collector.trace.v1.TraceService/
    Export — the reference's transport. Never raises."""
    try:
        from multi_cluster_simulator_tpu.services.proto import (
            otlp_trace_service_pb2 as TS,
        )
        req = TS.ExportTraceServiceRequest()
        rs = req.resource_spans.add()
        rs.resource.attributes.add(
            key="service.name").value.string_value = service
        ss = rs.scope_spans.add()
        ss.scope.name = "multi_cluster_simulator_tpu"
        for span in batch:
            pb = _span_pb(span)
            if pb is not None:
                ss.spans.append(pb)
        export = channel.unary_unary(
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export",
            request_serializer=TS.ExportTraceServiceRequest.SerializeToString,
            response_deserializer=TS.ExportTraceServiceResponse.FromString)
        export(req, timeout=timeout)
        return True
    except Exception:
        return False


def _grpc_export_metrics(channel, payload: dict,
                         timeout: float = 3.0) -> bool:
    """Export the Meter's OTLP envelope over /opentelemetry.proto.collector.
    metrics.v1.MetricsService/Export. ``otlp_payload()`` is already
    protojson-shaped, so json_format.Parse does the whole conversion (and
    cannot silently drop shapes a hand-rolled copier doesn't know)."""
    try:
        from google.protobuf import json_format

        from multi_cluster_simulator_tpu.services.proto import (
            otlp_metrics_service_pb2 as MS,
        )
        req = json_format.Parse(json.dumps(payload),
                                MS.ExportMetricsServiceRequest())
        export = channel.unary_unary(
            "/opentelemetry.proto.collector.metrics.v1.MetricsService/Export",
            request_serializer=MS.ExportMetricsServiceRequest.SerializeToString,
            response_deserializer=MS.ExportMetricsServiceResponse.FromString)
        export(req, timeout=timeout)
        return True
    except Exception:
        return False


def _otlp_post(url: str, payload: dict, timeout: float = 3.0) -> bool:
    """POST one OTLP/HTTP JSON envelope; never raises (telemetry must not
    take a service down — the reference's exporter retries silently too)."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout):
            return True
    except (urllib.error.URLError, OSError):
        return False


def _kv(key: str, value) -> dict:
    """An OTLP KeyValue with the matching AnyValue arm."""
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}  # protojson renders int64 as string
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}

# The active span context ("trace_id:span_id") for this thread of execution —
# the otel context.Context equivalent. start_span sets it for the span's
# extent; transports (httpd.post_json, rpc clients) read it to inject the
# propagation header, exactly as otelhttp.NewTransport / otelgrpc stats
# handlers do in the reference (pkg/scheduler/server.go:47, trader.go:216).
_CURRENT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "mcs_trace_ctx", default=None)


def current_context() -> Optional[str]:
    """The propagatable "trace_id:span_id" of the innermost active span."""
    return _CURRENT.get()


def wrap_ctx(fn: Callable) -> Callable:
    """Bind the caller's trace context (and the rest of its contextvars)
    into ``fn`` for execution on another thread — the hand-rolled version of
    Go's context.Context flowing through a goroutine fan-out
    (pkg/scheduler/server.go:183-215)."""
    ctx = contextvars.copy_context()
    return lambda *a, **kw: ctx.run(fn, *a, **kw)


def create_logger(service_name: str, mode: str = "development",
                  log_dir: str = "logs") -> logging.Logger:
    """zerolog factory (telemetry.go:121-143): console in development, file
    otherwise, both with mode="both"."""
    logger = logging.getLogger(f"mcs.{service_name}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    if logger.handlers:  # idempotent per process
        return logger
    fmt = logging.Formatter(
        "%(asctime)s " + service_name + " %(levelname)s %(message)s")
    if mode in ("development", "both"):
        h = logging.StreamHandler()
        h.setFormatter(fmt)
        logger.addHandler(h)
    if mode in ("production", "both"):
        os.makedirs(log_dir, exist_ok=True)
        stamp = time.strftime("%Y-%m-%dT%H-%M-%S")
        h = logging.FileHandler(os.path.join(
            log_dir, f"{service_name}-log-{stamp}"))
        h.setFormatter(fmt)
        logger.addHandler(h)
    if not logger.handlers:
        logger.addHandler(logging.NullHandler())
    return logger


class Tracer:
    """Span recorder. Spans land as JSONL rows in ``path`` (or are dropped
    when neither path nor an OTLP endpoint is configured — the no-collector
    default, matching the reference running without one). With
    ``OTEL_EXPORTER_OTLP_ENDPOINT`` set (or ``otlp_endpoint=`` passed),
    finished spans batch to ``<endpoint>/v1/traces`` as OTLP/HTTP JSON —
    the BatchSpanProcessor + otlptracegrpc equivalent of
    internal/service/telemetry.go:43-92.

    Ids are OTLP-sized (16-byte trace / 8-byte span, hex) so collectors
    like Jaeger accept them unmodified."""

    def __init__(self, service_name: str, path: Optional[str] = None,
                 otlp_endpoint: Optional[str] = None,
                 otlp_protocol: Optional[str] = None,
                 flush_period_s: float = 2.0):
        self.service = service_name
        self.path = path
        # explicit "" opts out even when the env var is set
        self.otlp = (otlp_endpoint if otlp_endpoint is not None
                     else _otlp_endpoint()) or None
        self.otlp_protocol = otlp_protocol or _otlp_protocol()
        if self.otlp is not None:  # exports would actually use it
            _check_otlp_protocol(self.otlp_protocol)
        self.flush_period_s = flush_period_s
        self._lock = threading.Lock()  # guards: _batch, _flusher, _channel
        self._batch: list[dict] = []
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._channel = None  # lazily-built long-lived gRPC channel

    def _grpc_channel(self):
        # raced by the periodic flusher thread and shutdown's final flush
        with self._lock:
            if self._channel is None:
                self._channel = _make_grpc_channel(self.otlp)
            return self._channel

    @contextmanager
    def start_span(self, name: str, parent: Optional[str] = None, **attrs):
        """Open a span. ``parent`` is a propagated "trace_id:span_id"
        context string (from TRACE_HEADER / gRPC metadata); when omitted,
        the innermost active span on this execution context is the parent —
        so nested ``start_span`` calls chain automatically, like OTel's
        implicit context."""
        parent = parent or _CURRENT.get()
        trace_id, _, parent_id = (parent or "").partition(":")
        # a malformed propagated header (non-hex / wrong length) must not
        # enter the system: it would poison binary exports downstream
        if not _is_hex(trace_id, 32):
            trace_id, parent_id = "", ""
        if not _is_hex(parent_id, 16):
            parent_id = ""
        trace_id = trace_id or secrets.token_hex(16)
        span_id = secrets.token_hex(8)
        ctx = f"{trace_id}:{span_id}"
        token = _CURRENT.set(ctx)
        t0 = time.time()
        try:
            yield ctx
        finally:
            _CURRENT.reset(token)
            t1 = time.time()
            if self.path is not None:
                row = {"service": self.service, "name": name,
                       "trace_id": trace_id, "span_id": span_id,
                       "parent_id": parent_id or None,
                       "start": t0, "dur_ms": (t1 - t0) * 1e3, **attrs}
                with self._lock, open(self.path, "a") as f:
                    f.write(json.dumps(row) + "\n")
            if self.otlp is not None:
                span = {"traceId": trace_id, "spanId": span_id,
                        "name": name, "kind": 1,  # SPAN_KIND_INTERNAL
                        "startTimeUnixNano": str(int(t0 * 1e9)),
                        "endTimeUnixNano": str(int(t1 * 1e9)),
                        "attributes": [_kv(k, v) for k, v in attrs.items()]}
                if parent_id:
                    span["parentSpanId"] = parent_id
                with self._lock:
                    self._batch.append(span)
                    self._start_flusher_locked()

    # -- OTLP batching (BatchSpanProcessor analogue) --
    def _start_flusher_locked(self) -> None:  # holds: _lock
        """Spawn the periodic flusher once; caller holds self._lock (the
        check and the assignment must be atomic or two first-span threads
        each spawn one)."""
        # a span ending concurrently with shutdown() must not resurrect the
        # flusher (and with it a gRPC channel nothing would ever close)
        if self._flusher is not None or self._stop.is_set():
            return

        def loop():
            while not self._stop.wait(self.flush_period_s):
                self.flush()
            self.flush()

        self._flusher = threading.Thread(target=loop, daemon=True,
                                         name=f"tracer:{self.service}")
        self._flusher.start()

    def flush(self) -> bool:
        """Export the pending batch to <endpoint>/v1/traces. Returns True
        when there was nothing to send or the send succeeded; a failed
        batch is re-queued (bounded: keeps the newest 4096 spans)."""
        with self._lock:
            batch, self._batch = self._batch, []
        if not batch or self.otlp is None:
            return True
        if self.otlp_protocol == "grpc":
            ok = _grpc_export_spans(self._grpc_channel(), self.service, batch)
        else:
            payload = {"resourceSpans": [{
                "resource": {"attributes": [_kv("service.name", self.service)]},
                "scopeSpans": [{
                    "scope": {"name": "multi_cluster_simulator_tpu"},
                    "spans": batch,
                }],
            }]}
            ok = _otlp_post(self.otlp + "/v1/traces", payload)
        if ok:
            return True
        with self._lock:
            self._batch = (batch + self._batch)[-4096:]
        return False

    def shutdown(self) -> None:
        self._stop.set()
        # take ownership under the lock, then join/close outside it (the
        # flusher's exit path flushes, which takes the lock itself)
        with self._lock:
            flusher, self._flusher = self._flusher, None
        if flusher is not None:
            flusher.join(timeout=3)  # its exit path flushes
        else:
            self.flush()
        with self._lock:
            channel, self._channel = self._channel, None
        if channel is not None:
            channel.close()


# Prometheus metric names admit only [a-zA-Z0-9_:] — service names here
# are dashed ("serve-tput"), which OTLP accepts but the exposition format
# does not. This is the standard OTLP->Prometheus name translation
# (invalid chars -> "_"), applied ONLY at the exposition rendering; the
# OTLP export keeps the original name (pinned by tests/test_tracing.py).
_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_metric_name(name: str) -> str:
    n = _PROM_NAME_BAD.sub("_", name)
    return ("_" + n) if n[:1].isdigit() else n


def prom_split_labels(key: str) -> tuple[str, str]:
    """Split a metric key into (name, label-suffix). Keys may carry a
    Prometheus label set verbatim — ``placed_total{tenant="3"}`` — which
    the name sanitizer must NOT eat (it would mangle the braces to
    underscores); only the name half passes through prom_metric_name."""
    base, brace, rest = key.partition("{")
    return base, (brace + rest) if brace else ""


class Meter:
    """Counters + histograms with periodic export.

    The reference declares ``<SERVICE_NAME>_jobs_in_queue`` (up/down counter)
    and ``<SERVICE_NAME>_waitTime`` (histogram) and records every 5 s
    (pkg/scheduler/metrics.go:11-31)."""

    _BOUNDS = (10, 50, 100, 500, 1_000, 5_000, 10_000, 60_000, 300_000)

    def __init__(self, service_name: str, export_path: Optional[str] = None,
                 export_period_s: float = 5.0,
                 otlp_endpoint: Optional[str] = None,
                 otlp_protocol: Optional[str] = None):
        self.service = service_name
        self.export_path = export_path
        self.export_period_s = export_period_s
        self.otlp = (otlp_endpoint if otlp_endpoint is not None
                     else _otlp_endpoint()) or None  # "" opts out
        self.otlp_protocol = otlp_protocol or _otlp_protocol()
        if self.otlp is not None:  # exports would actually use it
            _check_otlp_protocol(self.otlp_protocol)
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = {}
        self._lock = threading.Lock()  # guards: _counters, _gauges, _hists, _hist_sum, _thread, _channel
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel = None  # lazily-built long-lived gRPC channel

    def add(self, name: str, value: float) -> None:
        """Up/down counter add (Int64UpDownCounter.Add)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        """Absolute gauge set (ObservableGauge analogue) — the bridge the
        device metrics plane uses: the serving tier's snapshot refresh
        writes the harvested device rows here, so the Prometheus /metrics
        surface and the OTLP export render the SAME numbers from the same
        store (tests/test_obs.py pins the two surfaces equal)."""
        with self._lock:
            self._gauges[name] = value

    def record(self, name: str, value: float) -> None:
        """Histogram record (Float64Histogram.Record)."""
        with self._lock:
            buckets = self._hists.setdefault(name, [0] * (len(self._BOUNDS) + 1))
            i = sum(1 for b in self._BOUNDS if value > b)
            buckets[i] += 1
            self._hist_sum[name] = self._hist_sum.get(name, 0.0) + value

    def snapshot(self) -> dict:
        with self._lock:
            return {"service": self.service, "time": time.time(),
                    "counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "histograms": {k: {"buckets": list(v),
                                       "sum": self._hist_sum.get(k, 0.0),
                                       "bounds": list(self._BOUNDS)}
                                   for k, v in self._hists.items()}}

    def render_prometheus(self) -> str:
        """Prometheus exposition text (for a /metrics route), conformant
        with # HELP/# TYPE lines: counters here are up/down (OTel
        Int64UpDownCounter) so they expose as gauges, absolute gauges
        (set_gauge) expose as gauges, histograms as cumulative
        le-buckets."""
        snap = self.snapshot()
        lines = []
        seen = set()  # one HELP/TYPE per metric family (labeled series share)
        for kind, table in (("up/down counter", snap["counters"]),
                            ("gauge", snap["gauges"])):
            for k, v in table.items():
                base, labels = prom_split_labels(k)
                full = prom_metric_name(f"{self.service}_{base}")
                if full not in seen:
                    seen.add(full)
                    lines.append(
                        f"# HELP {full} {kind} {base} of {self.service}")
                    lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full}{labels} {v}")
        for k, h in snap["histograms"].items():
            full = prom_metric_name(f"{self.service}_{k}")
            lines.append(f"# HELP {full} histogram {k} of {self.service}")
            lines.append(f"# TYPE {full} histogram")
            acc = 0
            for bound, n in zip(list(self._BOUNDS) + ["+Inf"], h["buckets"]):
                acc += n
                lines.append(f'{full}_bucket{{le="{bound}"}} {acc}')
            lines.append(f"{full}_sum {h['sum']}")
            lines.append(f"{full}_count {acc}")
        return "\n".join(lines) + "\n"

    def otlp_payload(self) -> dict:
        """The current snapshot as one OTLP/HTTP JSON envelope
        (/v1/metrics): up/down counters as non-monotonic cumulative sums,
        histograms as cumulative explicit-bounds histograms — the shapes
        otlpmetricgrpc exports in the reference (telemetry.go:94-119)."""
        snap = self.snapshot()
        now = str(int(snap["time"] * 1e9))
        metrics = []
        for k, v in snap["counters"].items():
            metrics.append({"name": f"{self.service}_{k}", "sum": {
                "dataPoints": [{"asDouble": v, "timeUnixNano": now}],
                "aggregationTemporality": 2,  # CUMULATIVE
                "isMonotonic": False}})
        for k, v in snap["gauges"].items():
            metrics.append({"name": f"{self.service}_{k}", "gauge": {
                "dataPoints": [{"asDouble": v, "timeUnixNano": now}]}})
        for k, h in snap["histograms"].items():
            metrics.append({"name": f"{self.service}_{k}", "histogram": {
                "dataPoints": [{
                    "count": str(sum(h["buckets"])),
                    "sum": h["sum"],
                    "bucketCounts": [str(n) for n in h["buckets"]],
                    "explicitBounds": list(h["bounds"]),
                    "timeUnixNano": now}],
                "aggregationTemporality": 2}})
        return {"resourceMetrics": [{
            "resource": {"attributes": [_kv("service.name", self.service)]},
            "scopeMetrics": [{
                "scope": {"name": "multi_cluster_simulator_tpu"},
                "metrics": metrics,
            }],
        }]}

    def export_otlp(self) -> bool:
        """Push the current snapshot to the configured collector over the
        configured transport (grpc or http/json)."""
        if self.otlp is None:
            return True
        if self.otlp_protocol == "grpc":
            # raced by the exporter thread and the final shutdown export
            with self._lock:
                if self._channel is None:
                    self._channel = _make_grpc_channel(self.otlp)
                channel = self._channel
            return _grpc_export_metrics(channel, self.otlp_payload())
        return _otlp_post(self.otlp + "/v1/metrics", self.otlp_payload())

    def start_exporter(self) -> None:
        """PeriodicReader analogue: append snapshots to export_path and/or
        push them to the OTLP collector every period."""
        if self.export_path is None and self.otlp is None:
            return

        def loop():
            while not self._stop.wait(self.export_period_s):
                if self.export_path is not None:
                    with open(self.export_path, "a") as f:
                        f.write(json.dumps(self.snapshot()) + "\n")
                self.export_otlp()

        th = threading.Thread(target=loop, daemon=True,
                              name=f"meter:{self.service}")
        with self._lock:  # the once-check and the publish must be atomic
            if self._thread is not None:
                return
            self._thread = th
        th.start()

    def stop_exporter(self) -> None:
        self._stop.set()
        with self._lock:
            th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=2)
        with self._lock:
            channel, self._channel = self._channel, None
        if channel is not None:
            channel.close()
