"""Telemetry: structured logging, spans, metrics — the OTel/zerolog layer.

The reference wires every service with an OTLP span exporter, a periodic
metric reader, and a zerolog console/file logger via its telemetry factory
(internal/service/telemetry.go:43-143); no collector ships with the repo, so
in practice the artifacts are the log files. Here the same three factories
exist without an external collector dependency:

- ``create_logger`` — structured key=value console/file logging
  (telemetry.go:121-143: development -> console, production -> file
  ``logs/<SERVICE_NAME>-log-<timestamp>``, "both" -> both).
- ``Tracer`` — spans as JSONL records with trace/span ids and a
  ``traceparent``-style HTTP propagation header (the otelhttp transport
  equivalent, pkg/scheduler/server.go:47).
- ``Meter`` — named up/down counters and histograms with a periodic
  export thread (CreateMeterProvider's PeriodicReader,
  telemetry.go:94-119); snapshots are JSONL + a Prometheus-style text
  rendering for a /metrics route.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

TRACE_HEADER = "X-Trace-Context"  # traceparent analogue (HTTP)
TRACE_METADATA_KEY = "x-trace-context"  # gRPC metadata (keys must be lowercase)

# The active span context ("trace_id:span_id") for this thread of execution —
# the otel context.Context equivalent. start_span sets it for the span's
# extent; transports (httpd.post_json, rpc clients) read it to inject the
# propagation header, exactly as otelhttp.NewTransport / otelgrpc stats
# handlers do in the reference (pkg/scheduler/server.go:47, trader.go:216).
_CURRENT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "mcs_trace_ctx", default=None)


def current_context() -> Optional[str]:
    """The propagatable "trace_id:span_id" of the innermost active span."""
    return _CURRENT.get()


def wrap_ctx(fn: Callable) -> Callable:
    """Bind the caller's trace context (and the rest of its contextvars)
    into ``fn`` for execution on another thread — the hand-rolled version of
    Go's context.Context flowing through a goroutine fan-out
    (pkg/scheduler/server.go:183-215)."""
    ctx = contextvars.copy_context()
    return lambda *a, **kw: ctx.run(fn, *a, **kw)


def create_logger(service_name: str, mode: str = "development",
                  log_dir: str = "logs") -> logging.Logger:
    """zerolog factory (telemetry.go:121-143): console in development, file
    otherwise, both with mode="both"."""
    logger = logging.getLogger(f"mcs.{service_name}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    if logger.handlers:  # idempotent per process
        return logger
    fmt = logging.Formatter(
        "%(asctime)s " + service_name + " %(levelname)s %(message)s")
    if mode in ("development", "both"):
        h = logging.StreamHandler()
        h.setFormatter(fmt)
        logger.addHandler(h)
    if mode in ("production", "both"):
        os.makedirs(log_dir, exist_ok=True)
        stamp = time.strftime("%Y-%m-%dT%H-%M-%S")
        h = logging.FileHandler(os.path.join(
            log_dir, f"{service_name}-log-{stamp}"))
        h.setFormatter(fmt)
        logger.addHandler(h)
    if not logger.handlers:
        logger.addHandler(logging.NullHandler())
    return logger


class Tracer:
    """Span recorder. Spans land as JSONL rows in ``path`` (or are dropped
    when path is None — the no-collector default, matching the reference
    running without an OTLP endpoint)."""

    def __init__(self, service_name: str, path: Optional[str] = None):
        self.service = service_name
        self.path = path
        self._lock = threading.Lock()

    @contextmanager
    def start_span(self, name: str, parent: Optional[str] = None, **attrs):
        """Open a span. ``parent`` is a propagated "trace_id:span_id"
        context string (from TRACE_HEADER / gRPC metadata); when omitted,
        the innermost active span on this execution context is the parent —
        so nested ``start_span`` calls chain automatically, like OTel's
        implicit context."""
        parent = parent or _CURRENT.get()
        trace_id, _, parent_id = (parent or "").partition(":")
        trace_id = trace_id or secrets.token_hex(8)
        span_id = secrets.token_hex(4)
        ctx = f"{trace_id}:{span_id}"
        token = _CURRENT.set(ctx)
        t0 = time.time()
        try:
            yield ctx
        finally:
            _CURRENT.reset(token)
            if self.path is not None:
                row = {"service": self.service, "name": name,
                       "trace_id": trace_id, "span_id": span_id,
                       "parent_id": parent_id or None,
                       "start": t0, "dur_ms": (time.time() - t0) * 1e3, **attrs}
                with self._lock, open(self.path, "a") as f:
                    f.write(json.dumps(row) + "\n")


class Meter:
    """Counters + histograms with periodic export.

    The reference declares ``<SERVICE_NAME>_jobs_in_queue`` (up/down counter)
    and ``<SERVICE_NAME>_waitTime`` (histogram) and records every 5 s
    (pkg/scheduler/metrics.go:11-31)."""

    _BOUNDS = (10, 50, 100, 500, 1_000, 5_000, 10_000, 60_000, 300_000)

    def __init__(self, service_name: str, export_path: Optional[str] = None,
                 export_period_s: float = 5.0):
        self.service = service_name
        self.export_path = export_path
        self.export_period_s = export_period_s
        self._counters: dict[str, float] = {}
        self._hists: dict[str, list[int]] = {}
        self._hist_sum: dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, name: str, value: float) -> None:
        """Up/down counter add (Int64UpDownCounter.Add)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def record(self, name: str, value: float) -> None:
        """Histogram record (Float64Histogram.Record)."""
        with self._lock:
            buckets = self._hists.setdefault(name, [0] * (len(self._BOUNDS) + 1))
            i = sum(1 for b in self._BOUNDS if value > b)
            buckets[i] += 1
            self._hist_sum[name] = self._hist_sum.get(name, 0.0) + value

    def snapshot(self) -> dict:
        with self._lock:
            return {"service": self.service, "time": time.time(),
                    "counters": dict(self._counters),
                    "histograms": {k: {"buckets": list(v),
                                       "sum": self._hist_sum.get(k, 0.0),
                                       "bounds": list(self._BOUNDS)}
                                   for k, v in self._hists.items()}}

    def render_prometheus(self) -> str:
        """Prometheus-style text (for a /metrics route)."""
        snap = self.snapshot()
        lines = []
        for k, v in snap["counters"].items():
            lines.append(f"{self.service}_{k} {v}")
        for k, h in snap["histograms"].items():
            acc = 0
            for bound, n in zip(list(self._BOUNDS) + ["+Inf"], h["buckets"]):
                acc += n
                lines.append(f'{self.service}_{k}_bucket{{le="{bound}"}} {acc}')
            lines.append(f"{self.service}_{k}_sum {h['sum']}")
            lines.append(f"{self.service}_{k}_count {acc}")
        return "\n".join(lines) + "\n"

    def start_exporter(self) -> None:
        """PeriodicReader analogue: append snapshots to export_path."""
        if self.export_path is None or self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.export_period_s):
                with open(self.export_path, "a") as f:
                    f.write(json.dumps(self.snapshot()) + "\n")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"meter:{self.service}")
        self._thread.start()

    def stop_exporter(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
