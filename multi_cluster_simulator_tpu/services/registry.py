"""Service discovery + health: the registry server and its client library.

Reference: pkg/registry. The wire surface is preserved exactly — the same
Registration JSON field names, the same ``/services`` endpoint (POST register,
DELETE deregister, pkg/registry/server.go:180-217), the same push model where
the registry POSTs ``{Added, Removed}`` patches to each registrant's
ServiceUpdateURL (server.go:41-76), and the same heartbeat discipline: probe
each registrant's HeartbeatURL, 3 attempts 1 s apart, remove (with a Removed
patch broadcast) on failure and re-add on recovery (server.go:132-173).

Differences from the Go implementation (documented, deliberate):
- the registry port is a constructor argument (the reference hardcodes :3000,
  server.go:15) — tests run many registries concurrently;
- heartbeat probing is concurrent across registrants per cycle (the Go loop
  serializes on ``wg.Wait()`` inside the range, server.go:135-171 — an
  apparent bug that makes the probe period scale with registrant count);
- all sleeps scale by ``speed`` so integration tests run in milliseconds.
"""

from __future__ import annotations

import json
import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from multi_cluster_simulator_tpu.config import (
    HEARTBEAT_ATTEMPTS, HEARTBEAT_PERIOD_S, REGISTRY_PORT,
)
from multi_cluster_simulator_tpu.services import httpd

SERVICE_LOG = "LogService"  # registration.go:13-17
SERVICE_SCHEDULER = "Scheduler"
SERVICE_TRADER = "Trader"


@dataclass
class ServiceRegistration:
    """Registration (pkg/registry/registration.go:3-9); JSON field names are
    the Go struct's — byte-compatible with the reference wire format."""

    service_name: str
    service_url: str
    required_services: list = field(default_factory=list)
    service_update_url: str = ""
    heartbeat_url: str = ""

    def to_json(self) -> dict:
        return {"ServiceName": self.service_name,
                "ServiceURL": self.service_url,
                "RequiredServices": list(self.required_services),
                "ServiceUpdateURL": self.service_update_url,
                "HeartbeatURL": self.heartbeat_url}

    @staticmethod
    def from_json(d: dict) -> "ServiceRegistration":
        return ServiceRegistration(
            service_name=d["ServiceName"], service_url=d["ServiceURL"],
            required_services=list(d.get("RequiredServices") or []),
            service_update_url=d.get("ServiceUpdateURL", ""),
            heartbeat_url=d.get("HeartbeatURL", ""))


def _patch(added=(), removed=()) -> dict:
    """The {Added, Removed} push shape (registration.go:19-27). Empty lists
    marshal as null exactly like Go's nil slices, so the encoded patch is
    byte-identical to the reference registry's pushes; every receiver
    (ours at _handle_patch, Go's serviceUpdateHandler) treats null and []
    the same."""
    return {"Added": [{"Name": n, "URL": u} for n, u in added] or None,
            "Removed": [{"Name": n, "URL": u} for n, u in removed] or None}


class RegistryServer:
    """The registry process (pkg/registry/server.go)."""

    def __init__(self, host: str = "127.0.0.1", port: int = REGISTRY_PORT,
                 heartbeat_period_s: float = HEARTBEAT_PERIOD_S,
                 speed: float = 1.0, logger=None):
        self._regs: list[ServiceRegistration] = []
        self._lock = threading.RLock()  # guards: _regs
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.heartbeat_period_s = heartbeat_period_s / speed
        self.attempt_sleep_s = 1.0 / speed  # server.go:168
        self.logger = logger
        self.httpd = httpd.RoutedHTTPServer(host, port, logger=logger)
        self.httpd.route("POST", "/services", self._handle_register)
        self.httpd.route("DELETE", "/services", self._handle_deregister)
        self.url = self.httpd.url

    # -- lifecycle --
    def start(self, heartbeat: bool = True) -> None:
        self.httpd.start()
        if heartbeat and self._hb_thread is None:  # SetupRegistryService once
            self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                               daemon=True, name="registry-hb")
            self._hb_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)

    # -- handlers --
    def _handle_register(self, body: bytes, headers: dict):
        try:
            reg = ServiceRegistration.from_json(json.loads(body))
        except (ValueError, KeyError):
            return 400, None
        self._add(reg)
        return 200, None

    def _handle_deregister(self, body: bytes, headers: dict):
        ok = self._remove(body.decode().strip())
        return (200, None) if ok else (500, None)

    # -- core (server.go:23-130) --
    def _add(self, reg: ServiceRegistration) -> None:
        with self._lock:
            self._regs.append(reg)
        if self.logger:
            self.logger.info("registry: added %s at %s",
                             reg.service_name, reg.service_url)
        self._send_required_services(reg)
        self._notify(_patch(added=[(reg.service_name, reg.service_url)]))

    def _remove(self, url: str) -> bool:
        with self._lock:
            for i, r in enumerate(self._regs):
                if r.service_url == url:
                    victim = self._regs.pop(i)
                    break
            else:
                return False
        if self.logger:
            self.logger.info("registry: removed %s at %s",
                             victim.service_name, victim.service_url)
        self._notify(_patch(removed=[(victim.service_name,
                                      victim.service_url)]))
        return True

    def _send_required_services(self, reg: ServiceRegistration) -> None:
        """Tell a newcomer about already-registered providers it requires
        (server.go:80-100)."""
        if not reg.service_update_url:
            return
        with self._lock:
            added = [(r.service_name, r.service_url) for r in self._regs
                     if r.service_name in reg.required_services]
        if added:
            httpd.post_json(reg.service_update_url, _patch(added=added))

    def _notify(self, patch: dict) -> None:
        """Push the filtered patch to every registrant that requires an
        affected service (server.go:41-76)."""
        with self._lock:
            regs = list(self._regs)
        for reg in regs:
            if not reg.service_update_url:
                continue
            flt = {"Added": [e for e in patch["Added"] or []
                             if e["Name"] in reg.required_services] or None,
                   "Removed": [e for e in patch["Removed"] or []
                               if e["Name"] in reg.required_services] or None}
            if flt["Added"] or flt["Removed"]:
                threading.Thread(target=httpd.post_json,
                                 args=(reg.service_update_url, flt),
                                 daemon=True).start()

    # -- heartbeat (server.go:132-173) --
    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_period_s):
            with self._lock:
                regs = list(self._regs)
            threads = [threading.Thread(target=self._probe, args=(r,),
                                        daemon=True) for r in regs
                       if r.heartbeat_url]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)

    def _probe(self, reg: ServiceRegistration) -> None:
        """3 attempts 1 s apart; remove on first failure, re-add on
        recovery within the attempt budget (server.go:140-170)."""
        healthy = True
        for attempt in range(HEARTBEAT_ATTEMPTS):
            status, _ = httpd.get(reg.heartbeat_url, timeout=2.0)
            if status == 200:
                if not healthy:
                    self._add(reg)  # recovered
                return
            if healthy:
                healthy = False
                self._remove(reg.service_url)
            if self._stop.wait(self.attempt_sleep_s):
                return


# ---------------------------------------------------------------------------
# client side (pkg/registry/client.go)
# ---------------------------------------------------------------------------

class RegistryClient:
    """Per-service registry client: installs /heartbeat and /services
    handlers on the service's own HTTP server, registers with the registry,
    and maintains the pushed provider cache (client.go:14-136)."""

    def __init__(self, server: httpd.RoutedHTTPServer, registry_url: str,
                 logger=None,
                 on_update: Optional[Callable[[dict], None]] = None):
        self._providers: dict[str, list[str]] = {}
        self._lock = threading.Lock()  # guards: _providers
        self.registry_url = registry_url
        self.server = server
        self.logger = logger
        self.on_update = on_update
        self.registration: Optional[ServiceRegistration] = None
        server.route("GET", "/heartbeat", lambda b, h: (200, None))
        server.route("POST", "/services", self._handle_patch)

    def register(self, service_name: str, service_url: str,
                 required_services: list) -> None:
        """RegisterService (client.go:14-45)."""
        reg = ServiceRegistration(
            service_name=service_name, service_url=service_url,
            required_services=list(required_services),
            service_update_url=f"{self.server.url}/services",
            heartbeat_url=f"{self.server.url}/heartbeat")
        self.registration = reg
        status, _ = httpd.post_json(f"{self.registry_url}/services",
                                    reg.to_json())
        if status != 200:
            raise RuntimeError(
                f"failed to register {service_name}: registry says {status}")

    def shutdown(self) -> None:
        """ShutdownService (client.go:47-58)."""
        if self.registration is not None:
            httpd.delete(f"{self.registry_url}/services",
                         self.registration.service_url.encode())

    def _handle_patch(self, body: bytes, headers: dict):
        try:
            patch = json.loads(body)
        except ValueError:
            return 400, None
        with self._lock:
            for e in patch.get("Added") or []:
                urls = self._providers.setdefault(e["Name"], [])
                if e["URL"] not in urls:
                    urls.append(e["URL"])
            for e in patch.get("Removed") or []:
                urls = self._providers.get(e["Name"])
                if urls and e["URL"] in urls:
                    urls.remove(e["URL"])
        if self.logger:
            self.logger.info("providers updated: %s", patch)
        if self.on_update is not None:
            self.on_update(patch)
        return 200, None

    def get_provider(self, name: str) -> str:
        """Random provider (client.go:105-111)."""
        with self._lock:
            urls = list(self._providers.get(name) or [])
        if not urls:
            raise LookupError(f"no providers available for service {name}")
        return random.choice(urls)

    def get_providers(self, name: str) -> list[str]:
        with self._lock:
            urls = list(self._providers.get(name) or [])
        if not urls:
            raise LookupError(f"no providers available for service {name}")
        return urls
