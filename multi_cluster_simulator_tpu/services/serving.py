"""Scheduling-as-a-service: the async batched front door (serving tier).

The per-request live path (services/scheduler_host.py, BENCH ``live``) pays
~5 ms of host cost per tick because every HTTP arrival walks the service
stack one job at a time and every tick is its own ``tick_io`` device round
trip — 113 jobs/s against the batch engine's 406k (ROADMAP item 4). This
module closes that gap the way Blox argues schedulers should be built
(arxiv 2312.12621: modular services over a shared batched core): the HTTP
handlers only STAGE — parse JSON, stamp the arrival with the current
virtual tick, append to a bounded per-tick bucket — and a single drive
thread coalesces everything staged across ticks and clusters into the same
ragged ``TickArrivals`` chunk format the streamed bench pipeline ingests
(``engine.pack_arrivals_chunks`` discipline: rows padded to the chunk's own
pow2-bucketed K), then advances the device-resident, donated ``SimState``
with ONE ``Engine.run_io`` dispatch per coalesce window — N requests cost
one dispatch, not N.

Three contracts, each load-bearing:

- **Handlers never touch the device.** Submit handlers stage host tuples;
  read handlers (``/stats``, ``/quote``, ``/placed``) answer from the
  latest immutable ``Snapshot`` — a host-side numpy view the drive thread
  refreshes off the tick loop after a dispatch. No handler ever
  synchronizes the hot path (simlint rule ``serve-sync`` enforces this
  statically; LINTING.md family 8). Every query response carries
  ``snapshot_age_ms`` so clients know the consistency window.

- **Back-pressure is explicit.** Staging is bounded (``max_staged`` total,
  ``k_cap`` per (tick, cluster) — the latter also bounds the compiled K
  bucket); a full ring answers 503 with a machine-readable retry quote
  (``RetryAfterMs``, queue depth, snapshot age) and a
  ``submit_rejected`` telemetry count — never a silent drop. The engine's
  own drop counters stay asserted zero by the bench.

- **Coalescing is invisible to placement.** Dispatch is ``Engine.run_io``
  — a scan of the same tick body a window-1 driver would run — so a
  window-W front door is bit-identical to the per-request path over the
  same staged stream, and both are bit-identical to the batch engine over
  the equivalent bucketed Arrivals (tests/test_services.py pins all
  three; bench.py --serving asserts the A/B parity on every run).

Wire parity: ``POST /`` and ``POST /delay`` accept the reference's Go Job
JSON (an optional ``Cluster`` field routes among the hosted clusters;
endpoint routing follows the reference — a mismatched-endpoint job is
pushed into the queue the policy never drains, exactly as in Go).
``POST /submitBatch`` is the front door's native client API: a JSON array
of the same Job objects, one HTTP round trip for a client-side buffer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

import jax
import numpy as np

from multi_cluster_simulator_tpu.config import (MatchKind, PolicyKind,
                                                SimConfig)
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.engine import Engine, round_up_pow2
from multi_cluster_simulator_tpu.core.state import init_state
from multi_cluster_simulator_tpu.obs import device as obs_device
from multi_cluster_simulator_tpu.obs.profile import annotate_dispatch
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.services import host_ops
from multi_cluster_simulator_tpu.services.lifecycle import Service
from multi_cluster_simulator_tpu.services.registry import SERVICE_SCHEDULER
from multi_cluster_simulator_tpu.services.scheduler_host import job_from_json

_OWNER = int(np.asarray(Q.OWN))


def make_row(jid: int, cores: int, mem: int, gpu: int, dur_ms: int,
             enq_t: int) -> tuple:
    """One staged job as a queue row in the canonical field order
    (ops/fields.QUEUE_FIELDS) — the same row ``pack_arrivals_chunks``
    builds, so staged buckets and stream buckets are interchangeable."""
    return (int(jid), int(cores), int(mem), int(gpu), int(dur_ms),
            int(enq_t), _OWNER, 0,
            int(F.job_class(int(cores), int(gpu))), 0)


class Snapshot:
    """One immutable host-readable view of the device state, refreshed by
    the drive thread after a dispatch — the query side-channel's source of
    truth. Handlers read the latest snapshot by reference (one atomic
    attribute load); the device hot path is never synchronized on a
    request's behalf."""

    __slots__ = ("wall", "sim_t", "stage_t", "placed_total", "placed",
                 "jobs_in_queue", "queue_depth", "running", "avg_wait_ms",
                 "drops", "queue_ids", "run_ids", "run_active",
                 "dispatches", "staged_jobs", "tenants", "depth_tc",
                 "placed_t", "running_tc", "jobs_in_queue_tc",
                 "avg_wait_tc")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])

    def age_ms(self) -> float:
        return (time.time() - self.wall) * 1000.0

    def job_status(self, cluster: int, jid: int, tenant: int = 0) -> str:
        """queued | running | unknown — a placement lookup over the
        snapshot's id columns (host numpy, no device access). ``unknown``
        covers both never-seen and already-completed ids; the submit log
        (when latency tracking is on) disambiguates bench-side. Tenant-
        stacked snapshots (hosting T > 1) index the id columns by the
        tenant's row first."""
        for ids in self.queue_ids:
            col = ids[tenant] if ids.ndim == 3 else ids
            if (col[cluster] == jid).any():
                return "queued"
        rid = self.run_ids[tenant] if self.run_ids.ndim == 3 else self.run_ids
        act = (self.run_active[tenant] if self.run_active.ndim == 3
               else self.run_active)
        hit = rid[cluster] == jid
        if (hit & act[cluster]).any():
            return "running"
        return "unknown"


class ServingScheduler(Service):
    """The batched front door: one service hosts the WHOLE constellation
    (a [C]-cluster ``SimState`` resident on device) behind concurrent
    HTTP submit endpoints and snapshot-backed query endpoints.

    ``window`` is the coalesce window in ticks: the drive thread seals one
    staging bucket per virtual tick (``speed`` virtual seconds per wall
    second, the live host's pacing contract) and dispatches every
    ``window`` sealed ticks as one ``Engine.run_io`` call with donated
    state. ``pacer=False`` disables the drive thread for deterministic
    drivers (tests, the bench's parity A/B): the caller paces staging with
    ``seal_tick()`` and dispatches with ``dispatch_sealed()`` — a
    window-1 caller IS the per-request cost model, and both compose to
    bit-identical states.

    ``snapshot_every`` trades freshness for dispatch-pipeline depth: the
    drive thread refreshes the query snapshot (the only host
    synchronization in the loop) every N dispatches.
    """

    service_name = SERVICE_SCHEDULER
    required_services: list = []

    def __init__(self, name: str, specs, cfg: SimConfig,
                 registry_url: Optional[str] = None, speed: float = 1.0,
                 window: int = 16, k_cap: int = 128,
                 max_staged: Optional[int] = None, pacer: bool = True,
                 snapshot_every: int = 1, track_latency: bool = False,
                 warm_k=(1,), obs: bool = True,
                 snapshot_max_age_ms: Optional[float] = None,
                 wal_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 8, recover: bool = True,
                 wal_rotate_bytes: int = 64 << 20,
                 pricing_budget_ms: Optional[float] = None,
                 pricing_reprobe: int = 64, tenants: int = 1,
                 tenant_params=None, adaptive_window: bool = False,
                 adaptive_deadline_ms: Optional[float] = None, **kw):
        """Crash recovery (services/wal.py, ARCHITECTURE.md §fault plane):
        ``wal_path`` arms the staged-arrival write-ahead log — every
        accepted submit is fsync'd to it BEFORE the 200-ack, so an acked
        job survives kill -9; ``checkpoint_path`` adds periodic atomic
        device-state checkpoints (core/checkpoint.py, every
        ``checkpoint_every`` dispatches + one at clean shutdown/quiesce).
        Checkpoints are also what BOUND the WAL (seek offsets + rotation
        past ``wal_rotate_bytes`` anchor on the checkpoint watermark); a
        WAL without checkpoints is full-history by design — recovery
        replays it in its entirety — so arm both for a long-lived
        service.
        With ``recover`` (default) a restarting service restores the
        checkpoint and replays the WAL suffix — acked-but-undispatched
        jobs re-stage onto their original ticks, torn final records are
        discarded + truncated, and replay is exactly-once against the
        checkpoint's dispatch watermark. tools/chaos.py is the standing
        proof harness."""
        super().__init__(name, registry_url=registry_url, speed=speed, **kw)
        self.specs = list(specs)
        self.cfg = cfg
        self.window = int(window)
        self.k_cap = int(k_cap)
        self.C = len(self.specs)
        # multi-tenant hosting (tenancy/, ROADMAP item 3): T independent
        # constellations resident as ONE tenant-stacked SimState, advanced
        # by the tenant-batched run_io — per-tenant routing, staging
        # buckets, quotas and stats ride a tenant index through the same
        # stage->seal->coalesce->dispatch pipeline. T == 1 is byte-for-byte
        # the classic single-tenant front door.
        self.T = int(tenants)
        if self.T < 1:
            raise ValueError(f"tenants must be >= 1, got {tenants}")
        if self.T > 1:
            if wal_path is not None or checkpoint_path is not None:
                raise ValueError(
                    "multi-tenant serving does not arm WAL/checkpoint "
                    "durability — run one tenant per durable service")
            if pricing_budget_ms is not None:
                raise ValueError(
                    "pricing_budget_ms is single-tenant: the budget clock "
                    "times one constellation's trade rounds")
        # adaptive coalesce windows (tail latency, ROADMAP item 3): seal
        # the open tick early when a bucket fills, and let the drive loop
        # dispatch a PARTIAL window once the oldest sealed tick has waited
        # past the deadline — light traffic pays the deadline, not the
        # full fixed window wall. Placement is untouched (dispatch is the
        # same run_io scan, PARITY.md §serving); only pacing changes.
        self.adaptive_window = bool(adaptive_window)
        self.adaptive_deadline_ms = (float(adaptive_deadline_ms)
                                     if adaptive_deadline_ms is not None
                                     else None)
        self.max_staged = (int(max_staged) if max_staged is not None
                           else 4 * self.window * self.C * self.T)
        self.pacer = pacer
        self.snapshot_every = max(int(snapshot_every), 1)
        self.track_latency = track_latency
        self.warm_k = tuple(warm_k)
        self._warm_sorted = tuple(sorted(set(int(k) for k in warm_k)))
        self.obs = bool(obs)
        # Snapshot freshness bound (the staleness bugfix): snapshot_age_ms
        # was always REPORTED but never BOUNDED — a wedged refresh thread
        # kept serving arbitrarily stale /stats with 200s. Under the pacer
        # the drive loop refreshes on the dispatch cadence even with zero
        # traffic (the pacer seals ticks off wall time alone), so a
        # snapshot older than many windows means the loop is wedged and
        # queries answer 503 + the age (counted as stale_503) instead of
        # silently stale data. Deterministic drivers (pacer=False) pace
        # refreshes themselves, so the bound defaults off there; pass an
        # explicit value to arm it anyway (the staleness test does).
        if snapshot_max_age_ms is not None:
            self.snapshot_max_age_ms = float(snapshot_max_age_ms)
        elif pacer:
            self.snapshot_max_age_ms = max(
                20.0 * self.window * cfg.tick_ms / speed, 2_000.0)
        else:
            self.snapshot_max_age_ms = None
        self.engine = Engine(cfg)
        # per-tick pricing (the convex market kernel, market/cvx.py): when
        # the hosted config arms the trader with MatchKind.CVX, every
        # trade round inside a coalesced dispatch solves the contract LP —
        # all of it on the drive thread's run_io dispatch, never a handler
        # (the serve-sync contract is untouched: pricing is just more tick
        # phases inside the one compiled program). ``pricing_budget_ms``
        # arms a HARD per-round wall budget: the dispatch is then timed
        # against budget * rounds-in-window, and a blown budget flips a
        # sticky fallback to a pre-warmed greedy-matching executable
        # (same state shapes — mkt_price is always in SimState — so the
        # donated state moves between the two executables freely). Every
        # trip is counted in ``pricing_fallbacks`` and surfaced in
        # provenance()/metrics; the flag re-probes the solver every
        # ``pricing_reprobe`` dispatches so a transient stall does not
        # demote pricing forever. Arming a budget makes each dispatch
        # synchronous (a wall measurement needs the device to finish) —
        # the documented cost of the budget, not of the solver.
        self._pricing_armed = bool(cfg.trader.enabled
                                   and cfg.trader.matching is MatchKind.CVX)
        self.pricing_budget_ms = (float(pricing_budget_ms)
                                  if pricing_budget_ms is not None else None)
        self.pricing_reprobe = max(int(pricing_reprobe), 1)
        self.pricing_fallbacks = 0
        self._pricing_fallback = False
        self._pricing_since_probe = 0
        self._run_io_fallback = None
        if self._pricing_armed and self.pricing_budget_ms is not None:
            import dataclasses as _dc
            fb_cfg = _dc.replace(cfg, trader=_dc.replace(
                cfg.trader, matching=MatchKind.GREEDY))
            self._fallback_engine = Engine(fb_cfg)
            self._run_io_fallback = self._fallback_engine.run_io_jit(
                donate=True)
        # the device state has ONE owner — the drive thread (or the
        # deterministic driver): handlers never read or write it, so no
        # state lock exists by construction. Leaves are cloned once so
        # every buffer is unique — init_state shares zero-filled buffers
        # across leaves, which a donating dispatch may not receive twice
        import jax.numpy as jnp
        if self.T > 1:
            from multi_cluster_simulator_tpu import tenancy
            self._tenancy = tenancy
            self._batch = tenancy.TenantBatch(cfg, self.specs)
            tp = (tenant_params if tenant_params is not None
                  else self._batch.default_params(self.T))
            if tenancy.n_tenants(tp) != self.T:
                raise ValueError(
                    f"tenant_params holds {tenancy.n_tenants(tp)} tenants, "
                    f"service hosts {self.T}")
            self._tp = tp
            # per-tenant admission quota (the quota_jobs leaf): a bound on
            # the tenant's staged+queued backlog; -1 = unmetered
            self._quota = np.asarray(tp.quota_jobs).astype(np.int64)
            self._state = self._batch.init_stacked(tp)
        else:
            self._tenancy = None
            self._tp = None
            self._quota = None
            self._state = jax.tree.map(jnp.copy, init_state(cfg, self.specs))
        # the device metrics plane: one MetricsBuffer rides every run_io
        # dispatch (same single owner as the state — the drive thread) and
        # is harvested at the snapshot refresh, the sync point the loop
        # already pays; its gauges bridge into self.meter so /metrics and
        # the OTLP export report identical numbers. Multi-tenant hosting
        # stacks one buffer per tenant — the tenant row of the harvest.
        if not self.obs:
            self._mbuf = None
        elif self.T > 1:
            mb0 = obs_device.metrics_init(
                self._tenancy.tenant_cell(self._state, 0))
            self._mbuf = jax.tree.map(
                lambda leaf: jnp.stack([leaf] * self.T), mb0)
        else:
            self._mbuf = obs_device.metrics_init(self._state)
        self._obs_harvest: dict = {}
        self._run_io = (self._batch.run_io_fn(donate=True, obs=self.obs)
                        if self.T > 1
                        else self.engine.run_io_jit(donate=True))
        self._delay_policy = cfg.policy is not PolicyKind.FIFO
        # staging: one open bucket per cluster for the current tick, a
        # FIFO of sealed per-tick buckets awaiting dispatch, and the
        # parked mismatched-endpoint jobs (applied at dispatch time)
        self._stage_lock = threading.Lock()  # guards: _open, _sealed, _stage_t, _staged_jobs, _parked, _rejected, _rejected_t, _submit_wall, _unseen, _sealed_walls
        # staging buckets are per (tenant, cluster): tenant routing is a
        # staging index, never a device concern (the dispatch stacks the
        # buckets into the tenant-batched chunk). T == 1 keeps one row.
        self._open: list[list[list[tuple]]] = [
            [[] for _ in range(self.C)] for _ in range(self.T)]
        self._sealed: list[list[list[list[tuple]]]] = []
        self._stage_t = 0  # ticks staged (== index of the open tick)
        self._staged_jobs = 0  # staged, not yet dispatched (back-pressure)
        # per-(tenant, cluster) jobs admitted but not yet visible in a
        # snapshot's queue depth (staged OR dispatched-since-last-refresh):
        # the admission bound snap.depth_tc[tn, c] + _unseen[tn, c] <=
        # queue_capacity makes a device queue-overflow drop impossible by
        # construction — saturation surfaces as a 503 quote, never a
        # silent drop
        self._unseen = np.zeros((self.T, self.C), np.int64)
        self._parked: list[tuple] = []  # (c, row, to_delay) — T == 1 only
        self._rejected = 0
        self._rejected_t = np.zeros(self.T, np.int64)
        self._submit_wall: dict[tuple, float] = {}
        self._inflight = np.zeros((self.T, self.C), np.int64)  # drive-thread-owned
        # seal/dispatch cadence bookkeeping: per-sealed-tick walls feed the
        # adaptive deadline (oldest sealed tick's age), inter-dispatch
        # walls feed the MEASURED staging-latency quote (/quote)
        import collections as _c
        self._sealed_walls: _c.deque = _c.deque()
        self._dispatch_walls: _c.deque = _c.deque(maxlen=33)
        # dispatch bookkeeping (drive/driver thread only — single owner,
        # like the state): ticks dispatched, per-dispatch batch sizes, and
        # the snapshot visibility log the latency accounting reads. A
        # long-running service must not grow host memory per dispatch, so
        # the per-dispatch series are BOUNDED: batch sizes keep running
        # aggregates plus a recent window (for the p50), K values are a
        # set (at most log2(k_cap) members), and the visibility log is a
        # deque whose window comfortably covers any bench run (latency
        # accounting is a bench/driver concern — _submit_wall only grows
        # under track_latency, never in plain serving)
        import collections
        self.ticks_dispatched = 0
        self.dispatches = 0
        self.batch_jobs: collections.deque = collections.deque(maxlen=4096)
        self._batch_n = 0
        self._batch_sum = 0
        self._batch_max = 0
        self.chunk_k: set[int] = set()
        self.visibility_log: collections.deque = collections.deque(
            maxlen=1 << 16)  # (ticks_dispatched, wall)
        self._snap: Optional[Snapshot] = None
        self._stop = threading.Event()
        self._drive_thread: Optional[threading.Thread] = None
        self._pacer_thread: Optional[threading.Thread] = None
        # wedged-shutdown honesty: join timeouts are attributes so tests
        # can shrink them; a blown timeout flips _wedged (and /healthz)
        # instead of returning as if shutdown succeeded
        self.stop_join_timeout_s = 30.0
        self.pacer_join_timeout_s = 10.0
        self._wedged: Optional[str] = None  # thread name that never exited
        # /admin/quiesce single-flight state: one maintenance thread ever
        # owns the drain; late/retried requests attach to it
        self._quiesce_start_lock = threading.Lock()  # guards: _quiesce_done, _quiesce_result
        self._quiesce_done: Optional[threading.Event] = None
        self._quiesce_result: dict = {}
        # crash recovery (WAL + checkpoints — services/wal.py)
        self.wal_path = wal_path
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = max(int(checkpoint_every), 1)
        self.wal_rotate_bytes = int(wal_rotate_bytes)
        self._wal = None
        self._replaying = False  # replayed records must not re-append
        self._parked_applied = 0  # parked rows pushed at dispatch edges
        self.recovered_jobs = 0
        self.wal_torn_tail = False
        # per-staging-tick WAL byte offsets (first record of each tick) —
        # what lets a checkpoint record a SEEKABLE replay start and the
        # rotation drop the dispatched prefix; bounded: pruned to the
        # watermark at every checkpoint. guarded by _stage_lock.
        import collections as _collections
        self._wal_tick_off: _collections.deque = _collections.deque()
        self._wal_rotations = 0  # lifetime WAL compactions: once > 0,
        # the log is NOT full history and WAL-alone recovery would
        # silently lose the compacted prefix (_open_wal refuses)
        self._wal_parked = False  # any parked record ever logged disables
        #                           the offset/rotation optimizations
        # one compiled probe for the whole snapshot's scalar/vector reads:
        # the eager per-op form cost more than a full dispatch at serving
        # shapes (each eager op is its own device round trip on CPU)
        self._snap_probe = jax.jit(jax.vmap(self._snap_probe_fn)
                                   if self.T > 1 else self._snap_probe_fn)
        self._refresh_snapshot()
        if wal_path is not None:
            self._open_wal(recover=recover)

    # ------------------------------------------------------------------
    # HTTP surface
    # ------------------------------------------------------------------
    def register_handlers(self) -> None:
        self.httpd.route("POST", "/", self._handle_submit_fifo)
        self.httpd.route("POST", "/delay", self._handle_submit_delay)
        self.httpd.route("POST", "/submitBatch", self._handle_submit_batch)
        self.httpd.route("GET", "/stats", self._handle_stats)
        self.httpd.route("GET", "/quote", self._handle_quote)
        self.httpd.route("GET", "/placed", self._handle_placed)
        self.httpd.route("POST", "/admin/quiesce", self._handle_quiesce)
        # /metrics and /healthz ride the Service defaults (lifecycle.py):
        # the Prometheus render off the bridged Meter, and this service's
        # health() verdict below

    def _handle_submit_fifo(self, body: bytes, headers: dict):
        """POST / — the reference's ReadyQueue endpoint (server.go:23-51),
        stage-only: no device work, no lock shared with the dispatch."""
        return self._submit_one(body, delay=False)

    def _handle_submit_delay(self, body: bytes, headers: dict):
        """POST /delay — the reference's Level0 endpoint (server.go:53-78),
        stage-only."""
        return self._submit_one(body, delay=True)

    def _submit_one(self, body: bytes, delay: bool):
        try:
            d = json.loads(body)
            jid, cores, mem, dur_ms, _ = job_from_json(d)
            c = int(d.get("Cluster", 0))
            tn = int(d.get("Tenant", 0))
            gpu = int(d.get("GpusNeeded", 0))
        except (ValueError, TypeError):
            return 400, None
        if not (0 <= c < self.C):
            return 400, json.dumps({"Error": f"no cluster {c}"}).encode()
        if not (0 <= tn < self.T):
            return 400, json.dumps({"Error": f"no tenant {tn}"}).encode()
        if self.T > 1 and delay != self._delay_policy:
            # parked (mismatched-endpoint) jobs are single-tenant Go-wire
            # parity: under hosting, a job aimed at the queue the policy
            # never drains is a client bug answered up front
            return 400, json.dumps(
                {"Error": "endpoint does not match the hosted policy "
                          "(multi-tenant hosting has no parked queue)"}
            ).encode()
        rejected, reasons, accepted, depth = self._stage(
            [(tn, c, jid, cores, mem, gpu, dur_ms, delay)])
        if rejected:
            return 503, self._quote(rejected, reasons, accepted, depth)
        self.meter.add("jobs_submitted", 1)
        if delay:
            self.meter.add("jobs_in_queue", 1)
        return 200, None

    def _handle_submit_batch(self, body: bytes, headers: dict):
        """POST /submitBatch — the front door's native client API: a JSON
        array of Go Job objects (optional ``Cluster`` per job; optional
        ``Delay`` routes a job with the /delay endpoint's semantics
        instead of the policy-matching default), admitted per job. A
        partially back-pressured batch answers 503 naming the rejected
        indices: the accepted prefix IS staged, and the client resubmits
        only ``RejectedIdx`` after ``RetryAfterMs`` — no head-of-line
        blocking by one saturated cluster."""
        try:
            arr = json.loads(body)
            if isinstance(arr, dict):
                arr = arr["Jobs"]
            jobs = []
            for d in arr:
                jid, cores, mem, dur_ms, _ = job_from_json(d)
                jobs.append((int(d.get("Tenant", 0)),
                             int(d.get("Cluster", 0)), jid, cores, mem,
                             int(d.get("GpusNeeded", 0)), dur_ms,
                             bool(d.get("Delay", self._delay_policy))))
        except (ValueError, TypeError, KeyError):
            return 400, None
        if any(not (0 <= j[1] < self.C) for j in jobs):
            return 400, json.dumps({"Error": "bad Cluster"}).encode()
        if any(not (0 <= j[0] < self.T) for j in jobs):
            return 400, json.dumps({"Error": "bad Tenant"}).encode()
        if self.T > 1 and any(j[7] != self._delay_policy for j in jobs):
            return 400, json.dumps(
                {"Error": "Delay does not match the hosted policy "
                          "(multi-tenant hosting has no parked queue)"}
            ).encode()
        rejected, reasons, accepted, depth = self._stage(jobs)
        self.meter.add("jobs_submitted", accepted)
        # the handler-side jobs_in_queue counter moves for every accepted
        # delay-routed job, exactly as the equivalent POST /delay would
        # (server.go:75-76) — the two wire paths expose one meter
        rej = set(rejected)
        n_delay = sum(1 for i, j in enumerate(jobs)
                      if j[7] and i not in rej)
        if n_delay:
            self.meter.add("jobs_in_queue", n_delay)
        if rejected:
            return 503, self._quote(rejected, reasons, accepted, depth)
        return 200, json.dumps({"Accepted": accepted}).encode()

    def _stale_503(self, age_ms: float):
        """A query against a snapshot past the freshness bound: 503 with
        the age, never a 200 off arbitrarily stale data (the staleness
        bugfix — a wedged refresh loop used to serve forever)."""
        self.meter.add("stale_503", 1)
        return 503, json.dumps({
            "Error": "snapshot stale — refresh loop wedged?",
            "SnapshotAgeMs": round(age_ms, 3),
            "SnapshotMaxAgeMs": self.snapshot_max_age_ms,
            "RetryAfterMs": round(self._retry_quote_ms(), 3)}).encode()

    def _fresh_snap(self):
        """(snapshot, None) when within the freshness bound, else
        (None, age_ms). Handlers answer queries only off a fresh view."""
        s = self._snap
        age = s.age_ms()
        if (self.snapshot_max_age_ms is not None
                and age > self.snapshot_max_age_ms):
            return None, age
        return s, None

    def _handle_stats(self, body: bytes, headers: dict):
        """GET /stats[?tenant=i] — constellation totals from the latest
        snapshot (never the device); ``tenant`` narrows every figure to
        one hosted tenant's row."""
        tn = self._query_int(headers, "tenant", -1)
        s, stale_age = self._fresh_snap()
        if s is None:
            return self._stale_503(stale_age)
        if tn >= 0:
            if tn >= self.T:
                return 400, json.dumps(
                    {"Error": f"no tenant {tn}"}).encode()
            with self._stage_lock:
                rej = int(self._rejected_t[tn])
                unseen = int(self._unseen[tn].sum())
            return 200, json.dumps({
                "tenant": tn, "t_ms": s.sim_t,
                "stage_t_ticks": s.stage_t,
                "placed_total": int(s.placed_t[tn]),
                "running": int(s.running_tc[tn].sum()),
                "queue_depth": int(s.depth_tc[tn].sum()),
                "jobs_in_queue": int(s.jobs_in_queue_tc[tn].sum()),
                "staged_unseen": unseen, "dispatches": s.dispatches,
                "rejected_503": rej,
                "snapshot_age_ms": round(s.age_ms(), 3)}).encode()
        return 200, json.dumps({
            "t_ms": s.sim_t, "stage_t_ticks": s.stage_t,
            "tenants": self.T,
            "placed_total": s.placed, "running": int(s.running.sum()),
            "queue_depth": int(s.queue_depth.sum()),
            "jobs_in_queue": int(s.jobs_in_queue.sum()),
            "staged_jobs": s.staged_jobs, "dispatches": s.dispatches,
            "drops": s.drops, "rejected_503": self._rejected_count(),
            "snapshot_age_ms": round(s.age_ms(), 3)}).encode()

    def _handle_quote(self, body: bytes, headers: dict):
        """GET /quote?cluster=N[&tenant=i] — wait-time quote for a
        would-be submitter: the tenant row's average wait plus the
        MEASURED staging latency (recent seal-to-dispatch cadence, see
        ``_measured_window_ms``) — under adaptive windows the fixed
        window wall over-quotes, sometimes by the whole window. Pure
        snapshot + host-deque arithmetic."""
        c = self._query_int(headers, "cluster", 0)
        tn = self._query_int(headers, "tenant", 0)
        if not (0 <= c < self.C):
            return 400, None
        if not (0 <= tn < self.T):
            return 400, json.dumps({"Error": f"no tenant {tn}"}).encode()
        s, stale_age = self._fresh_snap()
        if s is None:
            return self._stale_503(stale_age)
        aw = float(s.avg_wait_tc[tn][c])
        return 200, json.dumps({
            "cluster": c, "tenant": tn,
            "wait_quote_ms": round(aw + self._measured_window_ms(), 3),
            "avg_wait_ms": round(aw, 3),
            "queue_depth": int(s.depth_tc[tn][c]),
            "snapshot_age_ms": round(s.age_ms(), 3)}).encode()

    def _handle_placed(self, body: bytes, headers: dict):
        """GET /placed?cluster=N&id=J[&tenant=i] — placement lookup over
        the snapshot id columns."""
        c = self._query_int(headers, "cluster", 0)
        jid = self._query_int(headers, "id", -1)
        tn = self._query_int(headers, "tenant", 0)
        if not (0 <= c < self.C):
            return 400, None
        if not (0 <= tn < self.T):
            return 400, json.dumps({"Error": f"no tenant {tn}"}).encode()
        s, stale_age = self._fresh_snap()
        if s is None:
            return self._stale_503(stale_age)
        return 200, json.dumps({
            "cluster": c, "id": jid,
            "status": s.job_status(c, jid, tenant=tn),
            "snapshot_age_ms": round(s.age_ms(), 3)}).encode()

    def _handle_quiesce(self, body: bytes, headers: dict):
        """POST /admin/quiesce — maintenance drain for operators and the
        chaos harness (tools/chaos.py): stop the loops, flush every sealed
        tick, refresh the snapshot, write the final checkpoint (when
        armed), and answer the drained truth. The HTTP surface keeps
        serving queries off the frozen core; /healthz flips not-live.

        The drain itself runs on a dedicated MAINTENANCE thread: the
        device state's single-owner discipline survives (ownership passes
        from the pacer/drive loops to that thread, never to an HTTP
        handler), and the serve-sync contract — no device coercion on a
        request thread — holds even for this endpoint; the handler only
        signals and waits on host events. Exactly ONE maintenance thread
        ever starts: concurrent/retried quiesce requests (including a
        retry after a 503 timeout answer) attach to the in-flight drain
        instead of spawning a second owner of the donated state."""
        with self._quiesce_start_lock:
            if self._quiesce_done is None:
                self._quiesce_done = threading.Event()
                self._quiesce_result = {}
                threading.Thread(
                    target=self._quiesce_and_report,
                    args=(self._quiesce_result, self._quiesce_done),
                    daemon=True, name=f"{self.name}-quiesce").start()
            done, result = self._quiesce_done, self._quiesce_result
        if not done.wait(timeout=300):
            return 503, json.dumps(
                {"Error": "quiesce still draining after 300s — retry to "
                          "re-attach"}).encode()
        if "Error" in result:
            return 503, json.dumps(result).encode()
        return 200, json.dumps(result).encode()

    def _quiesce_and_report(self, result: dict, done) -> None:
        """Maintenance-thread body of /admin/quiesce (never a handler)."""
        try:
            self.quiesce()
            s = self._snap
            result.update(
                ticks_dispatched=self.ticks_dispatched,
                dispatches=self.dispatches,
                placed=s.placed, sim_t=s.sim_t,
                staged_jobs=s.staged_jobs,
                queue_depth=int(s.queue_depth.sum()),
                running=int(s.running.sum()),
                recovered_jobs=self.recovered_jobs,
                checkpoint=self.checkpoint_path)
        except Exception as e:  # wedged loop — surfaced, not raced
            result["Error"] = str(e)
        finally:
            done.set()

    @staticmethod
    def _query_int(headers: dict, key: str, default: int) -> int:
        from urllib.parse import parse_qs
        q = parse_qs(headers.get("X-MCS-Query", ""))
        try:
            return int(q.get(key, [default])[0])
        except (ValueError, TypeError):
            return default

    def _rejected_count(self) -> int:
        with self._stage_lock:
            return self._rejected

    def _window_wall_ms(self) -> float:
        return self.window * self.cfg.tick_ms / self.speed

    def _measured_window_ms(self) -> float:
        """The staging latency a quote should promise: the MEAN measured
        inter-dispatch wall interval over the recent dispatch history,
        falling back to the configured window wall before two dispatches
        exist. Under adaptive windows ticks seal when buckets fill or
        deadlines pass, so the fixed ``_window_wall_ms`` bound can
        over-quote by nearly a whole window — quoting the measured
        cadence is the fix tests/test_services.py pins."""
        walls = list(self._dispatch_walls)
        if len(walls) < 2:
            return self._window_wall_ms()
        span = walls[-1] - walls[0]
        if span <= 0.0:
            return self._window_wall_ms()
        return (span / (len(walls) - 1)) * 1000.0

    # ------------------------------------------------------------------
    # staging (the only submit-path work: host tuples under one lock)
    # ------------------------------------------------------------------
    def _retry_quote_ms(self) -> float:
        """How long a back-pressured client should wait: admission budgets
        refill at the snapshot-refresh cadence (one per dispatch, i.e. per
        sealed window under load), so a quarter window is the expected
        wait for fresh room without oversleeping past a refill."""
        return min(max(self._window_wall_ms() / 4, 2.0), 200.0)

    def _stage(self, jobs: list[tuple], ta: Optional[int] = None,
               live_bounds: bool = True):
        """Stage (tenant, cluster, id, cores, mem, gpu, dur_ms, delay)
        tuples onto the open tick, admitting per job: a saturated
        (tenant, cluster) cell rejects its own jobs without
        head-of-line-blocking the rest of the batch. Four admission
        bounds, each surfacing as a quoted 503 (never a silent drop):

        - ``max_staged`` — total staging-ring room;
        - ``quota`` — the tenant's ``quota_jobs`` budget (TenantParams)
          against its staged+queued backlog, when metered;
        - ``queue`` — ``snapshot depth_tc[tn, c] + unseen[tn, c]``
          admitted against ``cfg.queue_capacity``, which makes a device
          queue-overflow drop impossible by construction (every admitted
          job is counted until a snapshot proves it left the queues);
        - ``k_cap`` — the per-(tick, tenant, cluster) bucket bound (also
          the compiled K ceiling).

        ``ta`` overrides the arrival stamp (deterministic drivers feeding
        a trace — it must bucket to the open tick, asserted);
        ``live_bounds=False`` drops the queue-budget and quota bounds for
        those drivers: they follow a fixed trace the caller has sized,
        assert zero drops afterwards, and must not have live back-pressure
        perturb trace-following (the HTTP handlers always keep it on).

        Returns ``(rejected_indices, reasons, accepted, depth)``."""
        now = time.time() if self.track_latency else 0.0
        rejected: list[int] = []
        reasons: set[str] = set()
        wal_recs: list[dict] = []
        filled = False
        with self._stage_lock:
            # the snapshot must be read under the SAME lock hold as the
            # unseen counters: _refresh_snapshot swaps the snapshot and
            # decrements _unseen in one atomic step, so reading the
            # snapshot before the lock could pair a STALE depth with the
            # NEW unseen — inflating the budget by a whole dispatch's
            # jobs and re-opening the silent-drop hole
            snap = self._snap
            room = self.max_staged - self._staged_jobs
            budget: dict[tuple, int] = {}
            qleft: dict[int, int] = {}
            tick = self.cfg.tick_ms
            stamp = (self._stage_t + 1) * tick if ta is None else int(ta)
            if ta is not None:
                dest = max((stamp + tick - 1) // tick, 1) - 1
                assert dest == self._stage_t, (
                    f"ta={stamp} buckets to tick {dest}, open tick is "
                    f"{self._stage_t} — pace seal_tick() to the stream")
            for idx, (tn, c, jid, cores, mem, gpu, dur, delay) in \
                    enumerate(jobs):
                if room <= 0:
                    rejected.append(idx)
                    reasons.add("max_staged")
                    self._rejected_t[tn] += 1
                    continue
                if live_bounds:
                    if (self._quota is not None
                            and self._quota[tn] >= 0):
                        if tn not in qleft:
                            qleft[tn] = (int(self._quota[tn])
                                         - int(snap.depth_tc[tn].sum())
                                         - int(self._unseen[tn].sum()))
                        if qleft[tn] <= 0:
                            rejected.append(idx)
                            reasons.add("quota")
                            self._rejected_t[tn] += 1
                            continue
                    if (tn, c) not in budget:
                        budget[(tn, c)] = (self.cfg.queue_capacity
                                           - int(snap.depth_tc[tn, c])
                                           - int(self._unseen[tn, c]))
                    if budget[(tn, c)] <= 0:
                        rejected.append(idx)
                        reasons.add("queue")
                        self._rejected_t[tn] += 1
                        continue
                parked = delay != self._delay_policy
                if parked and self.T > 1:
                    raise ValueError(
                        "mismatched-endpoint routing (parked jobs) is "
                        "single-tenant Go-wire parity — handlers answer "
                        "400 before staging under multi-tenant hosting")
                if not parked and len(self._open[tn][c]) >= self.k_cap:
                    rejected.append(idx)
                    reasons.add("k_cap")
                    self._rejected_t[tn] += 1
                    continue
                row = make_row(jid, cores, mem, gpu, dur, stamp)
                if parked:
                    # endpoint the policy never drains: pushed straight
                    # into the ignored queue at dispatch time
                    # (endpoint-faithful routing, server.go:22-78 — the
                    # job sits forever)
                    self._parked.append((c, row, delay))
                else:
                    self._open[tn][c].append(row)
                    if (self.adaptive_window
                            and len(self._open[tn][c]) >= self.k_cap):
                        filled = True  # seal early once the lock is off
                self._staged_jobs += 1
                self._unseen[tn, c] += 1
                if live_bounds:
                    budget[(tn, c)] -= 1
                    if tn in qleft:
                        qleft[tn] -= 1
                room -= 1
                if self.track_latency:
                    self._submit_wall[(tn, c, jid)] = now
                if self._wal is not None and not self._replaying:
                    rec = {"c": c, "i": int(jid), "co": int(cores),
                           "m": int(mem), "g": int(gpu), "du": int(dur),
                           "dl": bool(delay), "t": int(stamp)}
                    if parked:
                        rec["p"] = 1
                    wal_recs.append(rec)
            if rejected:
                self._rejected += len(rejected)
            depth = int(snap.queue_depth.sum())
            if wal_recs:
                # durability BEFORE the ack: the fsync'd append happens
                # under the same lock hold that staged the jobs, so WAL
                # order is exactly staging order (what replay reconstructs)
                # and a 200 can only reach the client for records already
                # on disk
                if (self.checkpoint_path is not None
                        and (not self._wal_tick_off
                             or self._wal_tick_off[-1][0] != self._stage_t)):
                    # seek/rotation bookkeeping only matters when a
                    # checkpoint can anchor it — without checkpoints the
                    # WHOLE log is the recovery source (full replay from
                    # a fresh state), growth is intrinsic to that config,
                    # and the deque would just leak an entry per tick
                    self._wal_tick_off.append(
                        (self._stage_t, self._wal.tell()))
                if any(r.get("p") for r in wal_recs):
                    self._wal_parked = True
                self._wal.append(wal_recs)
        if filled:
            # adaptive early seal: a bucket at k_cap means the open tick
            # already carries a full dispatch-K of work — sealing now (off
            # the lock; seal_tick re-acquires) hands it to the drive loop
            # instead of letting it ripen a full pacer period while new
            # arrivals bounce off k_cap
            self.seal_tick()
        if rejected:
            self.meter.add("submit_rejected", len(rejected))
        return rejected, reasons, len(jobs) - len(rejected), depth

    def _quote(self, rejected, reasons, accepted, depth) -> bytes:
        return json.dumps({
            "Error": f"staging ring full ({'+'.join(sorted(reasons))}) — "
                     "retry",
            "Accepted": accepted, "RejectedIdx": rejected,
            "RetryAfterMs": round(self._retry_quote_ms(), 3),
            "QueueDepth": depth,
            "SnapshotAgeMs": round(self._snap.age_ms(), 3)}).encode()

    def submit_direct(self, c: int, jid: int, cores: int, mem: int,
                      dur_ms: int, gpu: int = 0, delay: Optional[bool] = None,
                      ta: Optional[int] = None, tenant: int = 0) -> bool:
        """Driver-side staging without the HTTP hop (tests, fuzz drivers)
        — one job through the same ``_stage`` core the handlers use, with
        the queue-budget bound off (``live_bounds=False``): deterministic
        drivers follow a fixed trace the caller has sized and assert zero
        drops on the final state, so live back-pressure must not perturb
        trace-following. ``ta`` overrides the arrival stamp — it must
        bucket to the open tick exactly as ``pack_arrivals_chunks`` would
        (asserted), so staged buckets stay interchangeable with stream
        buckets."""
        delay = self._delay_policy if delay is None else delay
        rejected, _reasons, _acc, _depth = self._stage(
            [(int(tenant), c, jid, cores, mem, gpu, dur_ms, delay)], ta=ta,
            live_bounds=False)
        return not rejected

    def seal_tick(self) -> None:
        """Close the open staging tick and start the next — the virtual
        clock's staging edge. The drive thread calls this on the pacing
        cadence; deterministic drivers call it directly."""
        with self._stage_lock:
            self._sealed.append(self._open)
            self._open = [[[] for _ in range(self.C)]
                          for _ in range(self.T)]
            self._stage_t += 1
            self._sealed_walls.append(time.time())

    # ------------------------------------------------------------------
    # crash recovery: WAL + atomic checkpoints (services/wal.py)
    # ------------------------------------------------------------------
    def _open_wal(self, recover: bool) -> None:
        """Restore (checkpoint + WAL-suffix replay) if asked and possible,
        then open the log for appends — truncating any torn final record
        so fresh appends never land after corrupt bytes. When the
        checkpoint carries a matching-generation byte offset (and no
        parked records muddy the tick-monotone prefix rule), the read
        SEEKS to the live suffix instead of decoding the log's whole
        lifetime; any mismatch falls back to the full scan — offsets are
        an optimization, the replay watermark filter is the truth."""
        from multi_cluster_simulator_tpu.core import checkpoint as ckio
        from multi_cluster_simulator_tpu.services import wal as walmod
        extra: dict = {}
        if self.checkpoint_path and os.path.exists(self.checkpoint_path):
            try:
                # full header validation BEFORE any of it is trusted: an
                # unreadable, old-format, or wrong-config checkpoint must
                # not seed the WAL offset seek below (replaying a seeked
                # SUFFIX onto a fresh state would lose the prefix) — a
                # rejection here degrades to the coherent WAL-alone
                # full-history path, loudly, never a crash loop
                header = ckio._read_header(self.checkpoint_path)
                ckio._check_header(header, self.checkpoint_path,
                                   cfg=self.cfg)
                extra = header.get("extra") or {}
            except Exception as e:
                # WAL-alone is only a legal fallback when the log is FULL
                # history. A rotation compacted the dispatched prefix
                # away, so replaying the remainder onto a fresh state
                # would silently lose acked work — refuse loudly instead.
                # Evidence (best-effort from the raw header, readable
                # even when validation failed): a recorded rotation
                # count, or the log's current generation differing from
                # the one the checkpoint saw (rotate stamps a fresh one).
                evidence: dict = {}
                try:
                    evidence = ckio._read_header(
                        self.checkpoint_path).get("extra") or {}
                except Exception:
                    pass
                rotated = int(evidence.get("wal_rotations", 0) or 0) > 0
                if (not rotated and evidence.get("wal_gen") is not None
                        and self.wal_path and os.path.exists(self.wal_path)):
                    cur_gen = walmod.read_header(self.wal_path)
                    rotated = (cur_gen is not None
                               and cur_gen != evidence.get("wal_gen"))
                if rotated:
                    raise RuntimeError(
                        f"checkpoint {self.checkpoint_path} is not "
                        f"restorable ({e!r}) and the WAL has been "
                        "compacted (rotation evidence in the header): "
                        "WAL-alone recovery would silently lose the "
                        "dispatched prefix — restore a compatible build, "
                        "or delete BOTH files to start fresh") from e
                self.logger.error(
                    "checkpoint %s not restorable (%r); recovering from "
                    "the WAL alone", self.checkpoint_path, e)
                extra = {"_ckpt_unreadable": True}
        start = gen = None
        if recover and not extra.get("wal_parked"):
            start = extra.get("wal_offset")
            gen = extra.get("wal_gen")
        records, offsets, good_off, torn = walmod.read_records(
            self.wal_path, start=start, generation=gen)
        self.wal_torn_tail = torn
        if torn:
            self.logger.warning(
                "WAL %s has a torn final record (crash mid-append); "
                "discarding the tail at byte %d", self.wal_path, good_off)
        if recover and (records or (
                self.checkpoint_path
                and os.path.exists(self.checkpoint_path))):
            self._recover(records, extra)
        self._wal = walmod.WriteAheadLog(self.wal_path, fsync=True,
                                         start_offset=good_off)
        self._wal_parked = bool(extra.get("wal_parked")) or any(
            r.get("p") for r in records)
        if self.checkpoint_path is not None:
            # reseed the per-tick offset table from the surviving suffix:
            # a recovered process must keep pointing its NEXT checkpoint
            # at the oldest not-yet-dispatched record, not the log's end
            tick = self.cfg.tick_ms
            seed: dict[int, int] = {}
            for rec, off in zip(records, offsets):
                dest = max((int(rec["t"]) + tick - 1) // tick, 1) - 1
                if dest >= self.ticks_dispatched and dest not in seed:
                    seed[dest] = off
            with self._stage_lock:
                self._wal_tick_off.extend(sorted(seed.items()))

    def _recover(self, records: list[dict], extra: Optional[dict] = None
                 ) -> None:
        """Restart = restore the latest checkpoint + replay the WAL
        suffix. Exactly-once against the checkpoint's dispatch watermark
        T0 (``ticks_dispatched`` in the checkpoint header): a non-parked
        record staged on tick k was dispatched iff k < T0 — WAL order is
        staging order and dispatch consumes sealed ticks FIFO, so the
        dispatched set is exactly the tick-< T0 prefix; parked records
        are applied at dispatch edges regardless of tick, so the header's
        ``parked_applied`` count skips the applied prefix instead.
        Calling this again over the same files reproduces the same state
        (pure function of checkpoint + WAL), and a second in-process call
        is a no-op because the replayed jobs' ticks are already staged
        (tests/test_faults.py pins both)."""
        from multi_cluster_simulator_tpu.core.checkpoint import load_state
        import jax.numpy as jnp
        extra = extra or {}
        t0_ticks = 0
        parked_skip = 0
        if (self.checkpoint_path and os.path.exists(self.checkpoint_path)
                and not extra.get("_ckpt_unreadable")):
            # cfg verifies the v2 header's config digest: a checkpoint
            # from a differently-configured (or older-format) server must
            # never replay the WAL onto the wrong-shaped world. _open_wal
            # pre-validated the header, so a failure HERE (payload-level:
            # torn msgpack, leaf mismatch) is a corner — it degrades to
            # the WAL-alone fresh-state path like scheduler_host's
            # start-fresh fallback, UNLESS the records were offset-seeked
            # to a suffix (replaying a suffix onto a fresh state would
            # silently lose the prefix — refuse loudly instead).
            try:
                loaded = load_state(self.checkpoint_path, self._state,
                                    cfg=self.cfg)
            except (OSError, ValueError) as e:
                seeked = (extra.get("wal_offset") is not None
                          and not extra.get("wal_parked"))
                rotated = int(extra.get("wal_rotations", 0) or 0) > 0
                if seeked or rotated:
                    why = ("the WAL was offset-seeked to the "
                           "post-watermark suffix" if seeked else
                           "the WAL has been compacted by rotation")
                    raise RuntimeError(
                        f"checkpoint {self.checkpoint_path} failed to "
                        f"load ({e!r}) after its header validated, and "
                        f"{why} — cannot fall back to WAL-alone recovery "
                        "without losing the prefix; restore a compatible "
                        "build or delete BOTH files to start fresh"
                    ) from e
                self.logger.error(
                    "checkpoint %s not restorable (%r); recovering from "
                    "the WAL alone", self.checkpoint_path, e)
            else:
                # donation discipline: loaded leaves are distinct host
                # arrays, but clone anyway so no two leaves can alias one
                # buffer
                self._state = jax.tree.map(jnp.copy, loaded)
                t0_ticks = int(extra.get("ticks_dispatched", 0))
                parked_skip = int(extra.get("parked_applied", 0))
                self.ticks_dispatched = t0_ticks
                self.dispatches = int(extra.get("dispatches", 0))
                self._parked_applied = parked_skip
                self._wal_rotations = int(extra.get("wal_rotations", 0))
        with self._stage_lock:
            self._stage_t = t0_ticks
        self._refresh_snapshot()
        tick = self.cfg.tick_ms
        replayed = 0
        self._replaying = True
        try:
            for rec in records:
                stamp = int(rec["t"])
                if rec.get("p"):
                    if parked_skip > 0:
                        parked_skip -= 1
                        continue
                    # parked rows sit in a queue the policy never drains;
                    # their stamp is advisory — restage on the open tick
                    ta = None
                else:
                    dest = max((stamp + tick - 1) // tick, 1) - 1
                    if dest < t0_ticks:
                        continue  # already in the checkpointed state
                    while self._staged_ticks() < dest:
                        self.seal_tick()
                    ta = stamp
                rej, _r, _a, _d = self._stage(
                    [(0, int(rec["c"]), int(rec["i"]), int(rec["co"]),
                      int(rec["m"]), int(rec["g"]), int(rec["du"]),
                      bool(rec["dl"]))], ta=ta, live_bounds=False)
                if rej:
                    raise RuntimeError(
                        f"WAL replay: acked job {rec['i']} rejected at "
                        "restage — staging bounds shrank under recovery?")
                replayed += 1
        finally:
            self._replaying = False
        self.recovered_jobs = replayed
        if replayed or t0_ticks:
            self.logger.info(
                "recovered: checkpoint at %d dispatched ticks + %d WAL "
                "jobs replayed (%d parked applied pre-crash)",
                t0_ticks, replayed, self._parked_applied)

    def _save_checkpoint(self) -> None:
        """Atomic device-state checkpoint (core/checkpoint.py: tmp +
        rename) with the recovery watermarks in the header. Runs on the
        dispatch owner's thread between dispatches, so the state snapshot
        is consistent by construction.

        Also the WAL's growth bound: the header records the byte offset
        of the first record the watermark has not covered (recovery seeks
        there instead of decoding the log's lifetime), and once the
        dispatched prefix exceeds ``wal_rotate_bytes`` the log is
        compacted to the live suffix (WriteAheadLog.rotate — a fresh
        generation, so a checkpoint from before a crash mid-rotation
        falls back to the full scan). Both are disabled once a parked
        record enters the log: parked application order is by dispatch
        edge, not tick, so only the full-list skip count is correct."""
        from multi_cluster_simulator_tpu.core.checkpoint import save_state
        from multi_cluster_simulator_tpu.services.wal import HEADER_LEN
        extra = {"ticks_dispatched": self.ticks_dispatched,
                 "parked_applied": self._parked_applied,
                 "dispatches": self.dispatches}
        if self._wal is not None:
            with self._stage_lock:
                # replay starts at the first tick the watermark missed;
                # fully-covered entries are never needed again
                while (self._wal_tick_off
                       and self._wal_tick_off[0][0] < self.ticks_dispatched):
                    self._wal_tick_off.popleft()
                start = (self._wal_tick_off[0][1] if self._wal_tick_off
                         else self._wal.tell())
                if (not self._wal_parked
                        and start - HEADER_LEN > self.wal_rotate_bytes):
                    delta = self._wal.rotate(start)
                    self._wal_rotations += 1
                    self._wal_tick_off = type(self._wal_tick_off)(
                        (tk, off - delta) for tk, off in self._wal_tick_off)
                    start -= delta
                extra.update(wal_offset=start, wal_gen=self._wal.generation,
                             wal_parked=self._wal_parked,
                             wal_rotations=self._wal_rotations)
        save_state(self._state, self.checkpoint_path, extra=extra,
                   cfg=self.cfg)

    # ------------------------------------------------------------------
    # dispatch (single owner: the drive thread or the deterministic driver)
    # ------------------------------------------------------------------
    def _sealed_count(self) -> int:
        with self._stage_lock:
            return len(self._sealed)

    def _staged_ticks(self) -> int:
        with self._stage_lock:
            return self._stage_t

    def _pick_k(self, need: int) -> int:
        """K bucket for a chunk: the smallest WARMED bucket that fits
        (padding wider than needed is semantically invisible — ingest
        masks rows beyond each tick's count — and reusing a warmed
        executable beats a mid-traffic XLA compile), else pow2 of the
        need (one compile, then cached)."""
        for k in self._warm_sorted:
            if k >= need:
                return k
        return round_up_pow2(need)

    def _pop_chunk(self, W: int):
        with self._stage_lock:
            ticks = self._sealed[:W]
            del self._sealed[:W]
            for _ in range(min(W, len(self._sealed_walls))):
                self._sealed_walls.popleft()
            parked, self._parked = self._parked, []
            n = sum(len(lst) for tk in ticks for row in tk
                    for lst in row) + len(parked)
            self._staged_jobs -= n
        # dispatched jobs stay in _unseen (the admission bound's view of
        # the device queues) until a snapshot shows them; _inflight is
        # drive-thread-owned bookkeeping of that handoff
        for tk in ticks:
            for tn, row in enumerate(tk):
                for c, lst in enumerate(row):
                    self._inflight[tn, c] += len(lst)
        for c, _row, _d in parked:
            self._inflight[0, c] += 1
        return ticks, parked, n

    def _dispatch(self, W: int) -> int:
        """Consume W sealed ticks as ONE device dispatch (all hosted
        tenants advance together: the tenant axis rides the stacked
        rows, not extra dispatches). Returns the number of jobs
        dispatched."""
        ticks, parked, n_jobs = self._pop_chunk(W)
        # mismatched-endpoint jobs enter the queue their endpoint names
        # (which the policy ignores — inert rows, so applying them at the
        # chunk edge instead of mid-chunk is invisible to placement;
        # PARITY.md §serving). One async jitted push per parked row: the
        # dispatches queue without a host sync, and parked jobs exist
        # only when a client posts to the endpoint the policy never
        # drains — a client bug, not a traffic class worth a batched
        # kernel; max_staged bounds the worst case
        for c, row, delay in parked:
            op = host_ops.push_l0_at if delay else host_ops.push_ready_at
            self._state = op(self._state,
                             np.asarray(row, np.int32), np.int32(c))
        kmax = max((len(lst) for tk in ticks for row in tk for lst in row),
                   default=0)
        K = self._pick_k(max(kmax, 1))
        run_io, timed = self._pricing_exec()
        t_in = time.perf_counter() if timed else 0.0
        if self.T > 1:
            # tenant-batched dispatch: rows [T, W, C, K, NF] feed the ONE
            # vmapped executable with the traced TenantParams stack
            rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                                   (self.T, W, self.C, K, Q.NF)).copy()
            counts = np.zeros((self.T, W, self.C), np.int32)
            for ti, tk in enumerate(ticks):
                for tn, trow in enumerate(tk):
                    for c, lst in enumerate(trow):
                        if lst:
                            counts[tn, ti, c] = len(lst)
                            rows[tn, ti, c, :len(lst)] = np.asarray(
                                lst, np.int32)
            with annotate_dispatch("serving", ticks=W, jobs=n_jobs):
                if self.obs:
                    self._state, io, self._mbuf = run_io(
                        self._state, rows, counts, self._tp, self._mbuf)
                else:
                    self._state, io = run_io(
                        self._state, rows, counts, self._tp)
        else:
            rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                                   (W, self.C, K, Q.NF)).copy()
            counts = np.zeros((W, self.C), np.int32)
            for ti, tk in enumerate(ticks):
                for c, lst in enumerate(tk[0]):
                    if lst:
                        counts[ti, c] = len(lst)
                        rows[ti, c, :len(lst)] = np.asarray(lst, np.int32)
            with annotate_dispatch("serving", ticks=W, jobs=n_jobs):
                if self.obs:
                    self._state, io, self._mbuf = run_io(
                        self._state, rows, counts, None, self._mbuf)
                else:
                    self._state, io = run_io(self._state, rows, counts)
        if timed:
            # the budget needs the device finished — the one deliberate
            # sync a budgeted pricing dispatch pays (see ctor comment)
            jax.block_until_ready(self._state.t)
            self._pricing_account(W, (time.perf_counter() - t_in) * 1000.0)
        self._dispatch_walls.append(time.time())
        self.ticks_dispatched += W
        self.dispatches += 1
        self._parked_applied += len(parked)
        self.batch_jobs.append(n_jobs)
        self._batch_n += 1
        self._batch_sum += n_jobs
        self._batch_max = max(self._batch_max, n_jobs)
        self.chunk_k.add(K)
        # coalesce batch-size distribution on the wire-telemetry surface
        # (drive-thread-side: never a handler cost)
        self.meter.record("coalesce_batch_jobs", float(n_jobs))
        if self.cfg.borrowing:
            # host visibility of the cross-cluster events (the TickIO
            # side-channel): counted into telemetry; the in-batch borrow
            # phase already matched them on device
            self.meter.add("borrow_requests",
                           int(np.asarray(io.borrow_want).sum()))
            self.meter.add("returns_emitted",
                           int(np.asarray(io.ret_valid).sum()))
        if self.dispatches % self.snapshot_every == 0:
            self._refresh_snapshot()
        if (self.checkpoint_path is not None
                and self.dispatches % self.checkpoint_every == 0):
            self._save_checkpoint()
        return n_jobs

    def _pricing_exec(self):
        """(executable, timed) for the next dispatch. Untimed fast path
        unless a pricing budget is armed; under a tripped budget the
        greedy-matching fallback executable serves, except on re-probe
        dispatches (every ``pricing_reprobe``) where the solver gets one
        timed audition to win its seat back. Drive-thread-only state."""
        if not (self._pricing_armed and self.pricing_budget_ms is not None):
            return self._run_io, False
        if self._pricing_fallback:
            self._pricing_since_probe += 1
            if self._pricing_since_probe >= self.pricing_reprobe:
                self._pricing_since_probe = 0
                return self._run_io, True  # re-probe audition
            return self._run_io_fallback, False
        return self._run_io, True

    def _pricing_account(self, T: int, wall_ms: float) -> None:
        """Judge one timed pricing dispatch against the per-round budget.
        Rounds in a T-tick window follow the trade cadence (one round per
        ``monitor_period_ms``, and at least one — the conservative
        denominator, so a window with zero rounds can never trip)."""
        rounds = max(T * self.cfg.tick_ms // self.cfg.trader.monitor_period_ms,
                     1)
        blown = wall_ms > self.pricing_budget_ms * rounds
        if blown:
            self.pricing_fallbacks += 1
            self.meter.add("pricing_fallbacks", 1)
            if not self._pricing_fallback:
                self.logger.warning(
                    "pricing budget blown: %.2fms for %d round(s) against "
                    "%.2fms/round — falling back to greedy matching "
                    "(re-probe every %d dispatches)", wall_ms, rounds,
                    self.pricing_budget_ms, self.pricing_reprobe)
            self._pricing_fallback = True
            self._pricing_since_probe = 0
        elif self._pricing_fallback:
            self.logger.info(
                "pricing re-probe within budget (%.2fms for %d round(s)) "
                "— solver restored", wall_ms, rounds)
            self._pricing_fallback = False
            self._pricing_since_probe = 0

    def dispatch_sealed(self) -> int:
        """Dispatch every sealed tick: full coalesce windows first, then
        the tail (deterministic drivers; the drive thread only ever
        dispatches full windows). Returns jobs dispatched."""
        n = 0
        while self._sealed_count() >= self.window:
            n += self._dispatch(self.window)
        tail = self._sealed_count()
        if tail:
            n += self._dispatch(tail)
        return n

    _DROP_KEYS = ("queue", "msgs", "run_full", "vslot", "carve", "ingest",
                  "failed")

    @staticmethod
    def _snap_probe_fn(s):
        """The snapshot's derived reads as ONE jitted program (scalars and
        [C] vectors; the id columns are raw leaves read directly)."""
        import jax.numpy as jnp
        qd = obs_device.queue_depth(s)
        drops = jnp.stack([
            jnp.sum(getattr(s.drops, k)).astype(jnp.int32) for k in
            ServingScheduler._DROP_KEYS])
        return (s.t, s.placed_total, s.jobs_in_queue, qd,
                jnp.sum(s.run.active, axis=1), st.avg_wait_ms(s), drops)

    def _refresh_snapshot(self) -> None:
        """Build the next immutable query snapshot from the device state —
        the ONE host synchronization in the serving loop, paid by the
        drive thread off the request path. Also the latency visibility
        edge: everything dispatched so far is host-visible once the swap
        below lands, so the (ticks, wall) pair is appended after it."""
        s = self._state
        inflight, self._inflight = (self._inflight,
                                    np.zeros((self.T, self.C), np.int64))
        queues = (s.l0, s.l1, s.ready, s.wait)
        t, placed_c, jq, qd, running, aw, dr = self._snap_probe(s)
        # np.array, NOT np.asarray: on the CPU backend asarray returns a
        # ZERO-COPY view into the device buffer, and the next donating
        # dispatch hands that buffer back to XLA for reuse — a snapshot
        # must own its memory or its readers see silently-recycled bytes
        placed = np.array(placed_c)
        depth = np.array(qd)
        if self.T > 1:
            # tenant-stacked probe leaves ([T], [T, C]): the legacy
            # constellation-level slots become cross-tenant aggregates
            # (sums; avg_wait a plain mean — /quote answers per tenant),
            # the *_tc slots keep the per-tenant rows the admission
            # bound and the tenant queries read. All tenants advance in
            # lockstep, so any row's clock is THE clock.
            jq_tc, run_tc, aw_tc = (np.array(jq), np.array(running),
                                    np.array(aw))
            payload = dict(
                wall=time.time(), sim_t=int(np.asarray(t)[0]),
                placed_total=placed.sum(axis=0), placed=int(placed.sum()),
                jobs_in_queue=jq_tc.sum(axis=0),
                queue_depth=depth.sum(axis=0),
                running=run_tc.sum(axis=0),
                avg_wait_ms=aw_tc.mean(axis=0),
                drops=dict(zip(self._DROP_KEYS,
                               np.asarray(dr).sum(axis=0).tolist())),
                queue_ids=[np.array(q.id) for q in queues],
                run_ids=np.array(s.run.id),
                run_active=np.array(s.run.active),
                dispatches=self.dispatches,
                tenants=self.T, depth_tc=depth,
                placed_t=placed.sum(axis=1), running_tc=run_tc,
                jobs_in_queue_tc=jq_tc, avg_wait_tc=aw_tc)
        else:
            payload = dict(
                wall=time.time(), sim_t=int(np.asarray(t)),
                placed_total=placed, placed=int(placed.sum()),
                jobs_in_queue=np.array(jq),
                queue_depth=depth,
                running=np.array(running),
                avg_wait_ms=np.array(aw),
                drops=dict(zip(self._DROP_KEYS,
                               np.asarray(dr).tolist())),
                queue_ids=[np.array(q.id) for q in queues],
                run_ids=np.array(s.run.id),
                run_active=np.array(s.run.active),
                dispatches=self.dispatches,
                tenants=1, depth_tc=depth[None],
                placed_t=placed.sum(keepdims=True))
            # single-tenant rows are views of the owned aggregates (no
            # second coercion): the tenant axis is just [1, ...]
            payload["running_tc"] = payload["running"][None]
            payload["jobs_in_queue_tc"] = payload["jobs_in_queue"][None]
            payload["avg_wait_tc"] = payload["avg_wait_ms"][None]
        prev = self._snap
        with self._stage_lock:
            # the unseen decrement and the snapshot swap are ONE atomic
            # step: dispatched jobs leave the admission bound's unseen set
            # only when the snapshot that shows their queue residency is
            # the one _stage reads — decrementing before the swap would
            # let a concurrent submit pair the NEW unseen with the STALE
            # depth and over-admit into a full device queue (the silent-
            # drop class this bound exists to exclude)
            self._unseen -= inflight
            self._snap = Snapshot(stage_t=self._stage_t,
                                  staged_jobs=self._staged_jobs, **payload)
        self.visibility_log.append((self.ticks_dispatched,
                                    payload["wall"]))
        self._bridge_meter(prev)

    def _bridge_meter(self, prev: Optional[Snapshot]) -> None:
        """Bridge the refreshed snapshot + the harvested device metrics
        into the OTLP Meter (the one metrics store): the Prometheus
        /metrics route and the Go-wire OTLP export both render from it,
        so the two surfaces report identical numbers for the same window.
        Runs on the refresh thread, off the request path; the harvest is
        the plane's one chunk-boundary transfer (the refresh already
        synced the same dispatch)."""
        s = self._snap
        m = self.meter
        m.set_gauge("placed_total", float(s.placed))
        m.set_gauge("queue_depth", float(s.queue_depth.sum()))
        m.set_gauge("running", float(s.running.sum()))
        m.set_gauge("staged_jobs", float(s.staged_jobs))
        m.set_gauge("dispatches", float(s.dispatches))
        m.set_gauge("ticks_dispatched", float(self.ticks_dispatched))
        m.set_gauge("rejected_503", float(self._rejected_count()))
        m.set_gauge("sim_t_ms", float(s.sim_t))
        if self.T > 1:
            # per-tenant rows as labeled series off the SAME snapshot —
            # one harvest, T label values; /metrics renders them via
            # telemetry.prom_split_labels (never a per-tenant device sync)
            with self._stage_lock:
                rej_t = self._rejected_t.copy()
            for tn in range(self.T):
                lbl = f'{{tenant="{tn}"}}'
                m.set_gauge(f"tenant_placed_total{lbl}",
                            float(s.placed_t[tn]))
                m.set_gauge(f"tenant_queue_depth{lbl}",
                            float(s.depth_tc[tn].sum()))
                m.set_gauge(f"tenant_running{lbl}",
                            float(s.running_tc[tn].sum()))
                m.set_gauge(f"tenant_rejected_503{lbl}", float(rej_t[tn]))
        if prev is not None:
            # the retiring snapshot's final age — how stale queries could
            # have seen the surface this window (gauge + distribution)
            age = (s.wall - prev.wall) * 1000.0
            m.set_gauge("snapshot_age_ms", round(age, 3))
            m.record("snapshot_age_ms_hist", age)
        if self.obs and self._mbuf is not None:
            if self.T > 1:
                # one coercion for the whole stacked buffer, then cheap
                # per-tenant harvests over host views
                host_mb = jax.tree.map(np.array, self._mbuf)
                cells = [obs_device.harvest(
                    self._tenancy.tenant_cell(host_mb, tn))
                    for tn in range(self.T)]
                h = {
                    "ticks": cells[0]["ticks"],  # shared dispatch clock
                    "placed": sum(c["placed"] for c in cells),
                    "arrived": sum(c["arrived"] for c in cells),
                    "wait_accrued_ms": round(sum(
                        c["wait_accrued_ms"] for c in cells), 3),
                    "narrow_ovf": sum(c["narrow_ovf"] for c in cells),
                    "queue_depth_max": max(
                        c["queue_depth_max"] for c in cells),
                }
                for tn, c in enumerate(cells):
                    m.set_gauge(f'tenant_obs_placed{{tenant="{tn}"}}',
                                float(c["placed"]))
            else:
                h = obs_device.harvest(self._mbuf)
            self._obs_harvest = h
            m.set_gauge("obs_ticks", float(h["ticks"]))
            m.set_gauge("obs_placed", float(h["placed"]))
            m.set_gauge("obs_arrived", float(h["arrived"]))
            m.set_gauge("obs_queue_depth_max", float(h["queue_depth_max"]))
            m.set_gauge("obs_wait_accrued_ms", float(h["wait_accrued_ms"]))
            m.set_gauge("obs_narrow_ovf", float(h["narrow_ovf"]))

    @property
    def snapshot(self) -> Snapshot:
        return self._snap

    def health(self) -> tuple[bool, dict]:
        """/healthz verdict: the pacer and drive threads must be alive
        (pacer mode) and the snapshot within its freshness bound — a dead
        loop or a wedged refresh flips the surface to 503 while the HTTP
        server itself still answers (the whole point: the transport
        outliving the core must be VISIBLE)."""
        checks = {}
        if self._wedged:
            # unconditional (survives _started flipping off): a wedged
            # stop must read as unhealthy, never as a clean shutdown
            checks["shutdown_wedged"] = False
            checks["wedged_thread"] = self._wedged
        if self.pacer and self._started:
            checks["pacer_alive"] = (self._pacer_thread is not None
                                     and self._pacer_thread.is_alive())
            checks["drive_alive"] = (self._drive_thread is not None
                                     and self._drive_thread.is_alive())
        age = self._snap.age_ms() if self._snap is not None else None
        if self.snapshot_max_age_ms is not None and age is not None:
            checks["snapshot_fresh"] = age <= self.snapshot_max_age_ms
        ok = all(checks.values())
        detail = dict(checks)
        if age is not None:
            detail["snapshot_age_ms"] = round(age, 3)
        detail["dispatches"] = self.dispatches
        return ok, detail

    def warmup(self, ks=None) -> None:
        """Precompile the (window, K) dispatch executables on a throwaway
        state clone so no live dispatch pays an XLA compile. K buckets are
        pow2 (pack_arrivals_chunks discipline), so compile count is
        bounded at log2(k_cap) even if traffic exceeds the warmed set."""
        import jax.numpy as jnp
        ks = self.warm_k if ks is None else ks
        execs = [self._run_io]
        if self._run_io_fallback is not None:
            # the greedy fallback executable must be warm BEFORE a blown
            # pricing budget reaches for it — a mid-traffic XLA compile on
            # the escape path would itself blow the window it rescues
            execs.append(self._run_io_fallback)
        windows = [self.window]
        if self.adaptive_window and self.window > 1:
            windows.append(1)  # the early-dispatch shape (_adaptive_due)
        for W, K in ((w, k) for w in windows for k in ks):
            if self.T > 1:
                rows = np.broadcast_to(
                    np.asarray(Q._INVALID_ROW),
                    (self.T, W, self.C, int(K), Q.NF)).copy()
                counts = np.zeros((self.T, W, self.C), np.int32)
            else:
                rows = np.broadcast_to(
                    np.asarray(Q._INVALID_ROW),
                    (W, self.C, int(K), Q.NF)).copy()
                counts = np.zeros((W, self.C), np.int32)
            for run_io in execs:
                clone = jax.tree.map(jnp.copy, self._state)
                if self.obs:  # warm the executable shape the live path calls
                    mb = jax.tree.map(jnp.copy, self._mbuf)
                    out, _io, _mb = run_io(
                        clone, rows, counts,
                        self._tp if self.T > 1 else None, mb)
                elif self.T > 1:
                    out, _io = run_io(clone, rows, counts, self._tp)
                else:
                    out, _io = run_io(clone, rows, counts)
                jax.block_until_ready(out.t)  # compile-only: clone discarded

    # ------------------------------------------------------------------
    # drive loop (wall-clock pacing)
    # ------------------------------------------------------------------
    def on_start(self) -> None:
        self.warmup()
        self._refresh_snapshot()
        if self.pacer:
            self._pacer_thread = threading.Thread(
                target=self._pacer_loop, daemon=True,
                name=f"{self.name}-pacer")
            self._drive_thread = threading.Thread(
                target=self._drive_loop, daemon=True,
                name=f"{self.name}-drive")
            self._pacer_thread.start()
            self._drive_thread.start()

    def quiesce(self) -> None:
        """Stop the pacer/drive loops while the HTTP surface keeps
        serving (maintenance drain): every sealed tick is dispatched,
        the snapshot refreshed once, and from then on queries/metrics
        answer off a frozen core. /healthz flips unhealthy — a quiesced
        service is deliberately not live.

        The final flush only runs once BOTH loops have provably exited:
        a drive thread wedged past the join timeout still owns the
        donated device state, and dispatching from this thread too would
        make two concurrent owners (donated-buffer reuse, acked jobs
        lost) — exactly the wedge /healthz exists to surface, so flip
        the surface to 503 and raise instead of racing it."""
        self._stop.set()
        for th in (self._pacer_thread, self._drive_thread):
            if th is not None:
                th.join(timeout=self.stop_join_timeout_s)
                if th.is_alive():
                    self._wedged = th.name  # /healthz answers 503 now
                    self.logger.error(
                        "quiesce: %s did not exit within %.1fs — wedged; "
                        "/healthz flipped to 503", th.name,
                        self.stop_join_timeout_s)
                    raise RuntimeError(
                        f"quiesce: {th.name} did not exit within "
                        f"{self.stop_join_timeout_s:.0f}s — the loop is "
                        "wedged (it still owns the device state, so no "
                        "drain flush can run); /healthz is reporting it")
        self._pacer_thread = None
        self._drive_thread = None
        self.dispatch_sealed()
        self._refresh_snapshot()
        if self.checkpoint_path is not None:
            self._save_checkpoint()  # the drained truth, durably
        # a deliberately frozen core is not a wedged refresh loop: the
        # final snapshot above is the drained truth and stays servable,
        # so disarm the staleness bound (health() still reports the
        # service not-live via the dead-loop checks)
        self.snapshot_max_age_ms = None

    def on_shutdown(self) -> None:
        self._stop.set()
        if self._pacer_thread is not None:
            self._pacer_thread.join(timeout=self.pacer_join_timeout_s)
            if self._pacer_thread.is_alive():
                self._wedged = self._pacer_thread.name
        if self._drive_thread is not None:
            self._drive_thread.join(timeout=self.stop_join_timeout_s)
            if self._drive_thread.is_alive():
                self._wedged = self._drive_thread.name
        if self._wedged:
            # wedged-thread honesty: a loop that never exited still owns
            # the donated device state — a flush here would make two
            # concurrent owners. Log it, flip /healthz to 503 (the
            # lifecycle keeps the diagnostic surface up — Service.shutdown
            # checks wedged()), and do NOT pretend shutdown succeeded.
            self.logger.error(
                "shutdown: %s did not exit within its join timeout — "
                "wedged; skipping the final flush (the wedged loop still "
                "owns the device state) and flipping /healthz to 503",
                self._wedged)
            return
        if self.pacer:
            # final flush AFTER both threads have exited: a flush inside
            # the drive loop could race the still-running pacer and
            # strand a tick sealed after the flush read the backlog —
            # 200-acknowledged jobs silently lost at process exit. Here
            # the caller thread owns the state (both owners joined), so
            # every sealed tick is dispatched exactly once. Anything
            # still OPEN was never sealed into virtual time and stays
            # staged (durable in the WAL when one is armed — recovery
            # restages it).
            self.dispatch_sealed()
            self._refresh_snapshot()
        if self.checkpoint_path is not None:
            self._save_checkpoint()
        if self._wal is not None:
            self._wal.close()

    def _pacer_loop(self) -> None:
        """Seal staging ticks on the virtual-time cadence (``speed``
        virtual seconds per wall second, catching up in bursts when the
        host lags). Sealing is lock-append work and runs in its own
        thread so an in-flight dispatch never stalls the staging clock —
        which would pool every concurrent arrival into one open tick and
        trip the k_cap back-pressure for the whole dispatch duration.

        Virtual time slews, never runs away: when dispatch falls behind
        the requested speed, sealing stops at the lead cap and the
        achieved virtual rate degrades to dispatch-bound — the live
        host's achieved_speed contract, with back-pressure (503 quotes)
        instead of an unbounded sealed backlog."""
        period = self.cfg.tick_ms / 1000.0 / self.speed
        # sealed-backlog cap: 2 windows keeps the staging pipeline short —
        # a staged job is dispatched (and leaves the admission bound's
        # unseen set) within ~2 window walls, so the queue-budget refill
        # rate, admission_rate ≈ C·queue_capacity / lead_wall, stays high;
        # an 8-window lead measured 4x lower sustained admission
        max_lead = 2 * self.window
        t0 = time.time()
        # rebase on the staging clock at loop start: a RECOVERED service
        # resumes with _stage_t already at the checkpoint watermark, and
        # an elapsed-from-zero target would stall sealing until wall time
        # caught up with the whole pre-crash history
        base = self._staged_ticks()
        while not self._stop.is_set():
            due = min(base + int((time.time() - t0) / period),
                      self.ticks_dispatched + max_lead)
            while self._staged_ticks() < due:
                self.seal_tick()
            time.sleep(min(max(period / 2, 0.0005), 0.02))

    def _adaptive_due(self) -> int:
        """Sealed ticks to dispatch NOW under adaptive windows: the full
        window when one is ready, else ONE tick once the oldest sealed
        tick has waited past the deadline (tail-latency escape hatch — a
        light-traffic tick stops idling out the whole window wall).
        Single-tick granularity on the early path keeps the executable
        zoo at two (W, K) shape families — arbitrary partial widths
        would each compile mid-traffic. 0 = wait."""
        with self._stage_lock:
            n = len(self._sealed)
            oldest = self._sealed_walls[0] if self._sealed_walls else None
        if n >= self.window:
            return self.window
        if n == 0 or oldest is None:
            return 0
        deadline = self.adaptive_deadline_ms
        if deadline is None:
            deadline = max(self._window_wall_ms() / 4.0, 1.0)
        return 1 if (time.time() - oldest) * 1000.0 >= deadline else 0

    def _drive_loop(self) -> None:
        """Dispatch a coalesce window whenever one is sealed — back-to-
        back when the backlog is deep (throughput degrades to
        device-bound, never to drops), idle-waiting when traffic is
        light. With ``adaptive_window`` armed, partial windows whose
        oldest sealed tick has aged past the deadline dispatch early
        (p99 under light load stops paying the full window wall; the
        early-seal half lives in ``_stage``: a full k_cap bucket seals
        its tick without waiting for the pacer)."""
        period = self.cfg.tick_ms / 1000.0 / self.speed
        while not self._stop.is_set():
            if self.adaptive_window:
                due = self._adaptive_due()
                if due > 0:
                    self._dispatch(due)
                else:
                    time.sleep(min(max(period / 4, 0.0005), 0.005))
            elif self._sealed_count() >= self.window:
                self._dispatch(self.window)
            else:
                time.sleep(min(max(period, 0.001), 0.02))
        # the final flush happens in on_shutdown AFTER this thread and
        # the pacer are both joined — flushing here would race a pacer
        # still sealing and strand an acknowledged tick

    # ------------------------------------------------------------------
    # introspection (drivers/tests; syncs — never called from handlers)
    # ------------------------------------------------------------------
    def provenance(self) -> dict:
        """Serving provenance for bench detail dicts — joinable with
        tournament/env rows (the PR 6 contract). Batch-size mean/max are
        whole-run aggregates; the p50 comes from the bounded recent
        window."""
        return {
            "policy": self.engine.policy_provenance(),
            "market": dict(
                self.engine.market_provenance(),
                pricing_budget_ms=self.pricing_budget_ms,
                pricing_fallbacks=self.pricing_fallbacks,
                pricing_fallback_active=self._pricing_fallback),
            "coalesce_window_ticks": self.window,
            "adaptive_window": self.adaptive_window,
            "adaptive_deadline_ms": self.adaptive_deadline_ms,
            "tenants": self.T,
            "tenant_params_digest": (
                self._tenancy.tenant_params_digest(self._tp)
                if self._tp is not None else None),
            "clusters": self.C, "k_cap": self.k_cap,
            "max_staged": self.max_staged,
            "snapshot_every": self.snapshot_every,
            "dispatches": self.dispatches,
            "ticks_dispatched": self.ticks_dispatched,
            "batch_jobs": {
                "mean": round(self._batch_sum / self._batch_n, 2)
                if self._batch_n else 0.0,
                "max": self._batch_max,
                "p50": int(np.percentile(list(self.batch_jobs), 50))
                if self.batch_jobs else 0},
            "ragged_k": sorted(self.chunk_k),
            "rejected_503": self._rejected_count(),
            "obs": ({k: v for k, v in self._obs_harvest.items()
                     if k not in ("per_cluster", "ring")}
                    if self._obs_harvest else None),
        }

    def state_host(self):
        """The full device state coerced to OWNED host numpy (np.array,
        not a zero-copy view — see _refresh_snapshot) — the bench's
        parity-comparison and drain probes. Drive thread must be idle."""
        return jax.tree.map(np.array, self._state)

    def latencies_ms(self) -> list[float]:
        """Submit-to-placed-visible latency per tracked job: placement
        tick from the device trace (cfg.record_trace), visibility wall
        from the dispatch log (the snapshot that made the tick
        host-readable), submit wall from the staging log."""
        if not self.track_latency:
            return []
        from multi_cluster_simulator_tpu.utils.trace import extract_trace
        log = self.visibility_log
        tick = self.cfg.tick_ms
        out = []
        with self._stage_lock:
            submit = dict(self._submit_wall)
        if self.T > 1:
            host = jax.tree.map(np.array, self._state)
            cells = [self._tenancy.tenant_cell(host, tn)
                     for tn in range(self.T)]
        else:
            cells = [self._state]
        for tn, cell in enumerate(cells):
            for c, events in enumerate(extract_trace(cell)):
                for (t, jid, node, src) in events:
                    t0 = submit.get((tn, c, jid))
                    if t0 is None:
                        continue
                    # first snapshot whose dispatched ticks cover clock t
                    wall = next((w for (n, w) in log if n * tick >= t),
                                None)
                    if wall is not None:
                        out.append((wall - t0) * 1000.0)
        return out
