"""The workload-generator client service.

Reference: pkg/client — a service that learns its scheduler's cluster over
the ``/newClient`` handshake (client.go:44-66), derives max job sizes from
the biggest node (setMaxCluster, client.go:68-83), and then streams jobs
whose sizes are Beta(2,2)-scaled and durations Uniform[0,600) s, with
Poisson(λ=10/min) or Weibull(λ=10,k=3) arrival processes
(sendJobs, client.go:85-147). Jobs go out as POST ``/delay`` with a
``Referer`` header (SendJob, client/server.go:35-66).

Quirk handling: the Go Poisson loop computes ``60/jobs`` seconds between
jobs, which (a) divides by zero when the draw is 0 — the live generator
skips the empty minute instead of crashing — and (b) makes a "minute"
take ``n*floor(60/n) <= 60`` s, so batches drift early; the live client
reproduces that drift (workload/generator.py documents the batch-grid
divergence the *batch* generator chose instead).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from multi_cluster_simulator_tpu.config import WorkloadConfig
from multi_cluster_simulator_tpu.services import httpd
from multi_cluster_simulator_tpu.services.backoff import jittered_backoff_ms
from multi_cluster_simulator_tpu.services.lifecycle import Service
from multi_cluster_simulator_tpu.services.scheduler_host import job_to_json


class WorkloadClientService(Service):
    service_name = "Client"
    required_services: list = []  # cmd/client never registers (main.go:14-41)

    def __init__(self, name: str, scheduler_url: str,
                 wcfg: WorkloadConfig = WorkloadConfig(),
                 speed: float = 1.0, max_jobs: Optional[int] = None, **kw):
        super().__init__(name, speed=speed, **kw)
        self.scheduler_url = scheduler_url.rstrip("/")
        self.wcfg = wcfg
        self.max_jobs = max_jobs
        self.max_job_cores = 0
        self.max_job_mem = 0
        self.jobs_sent = 0
        self.acks = 0
        # client-side backoff discipline: a 503 quote's RetryAfterMs is a
        # BASE delay, not a fixed sleep — retries are jittered exponential
        # under a bounded attempt budget, and exhaustion is counted +
        # logged instead of spinning forever
        self.retry_attempts = 8
        self.retries_503 = 0
        self.conn_retries = 0  # transport failures (dead/restarting server)
        self.retries_exhausted = 0
        self._rng = np.random.default_rng(wcfg.seed)
        # the ack counter is bumped by HTTP handler threads and read by the
        # generator thread / tests
        self._ack_lock = threading.Lock()  # guards: acks
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register_handlers(self) -> None:
        self.httpd.route("GET", "/", lambda b, h: (200, b"Hello!"))
        self.httpd.route("GET", "/jobAdded", self._handle_ack)

    def _handle_ack(self, body: bytes, headers: dict):
        with self._ack_lock:  # handler threads race each other here
            self.acks += 1  # the "ack!" print (client/server.go:27-31)
        return 200, None

    def on_start(self) -> None:
        self._new_client()
        self._thread = threading.Thread(target=self._send_jobs, daemon=True,
                                        name=f"{self.name}-gen")
        self._thread.start()

    def on_shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- the /newClient handshake (client.go:44-83) --
    def _new_client(self) -> None:
        status, body = httpd.get(self.scheduler_url + "/newClient")
        if status != 200:
            raise RuntimeError(f"newClient handshake failed: {status}")
        cluster = json.loads(body)
        for node in cluster.get("Nodes", []):
            self.max_job_cores = max(self.max_job_cores, int(node["Cores"]))
            self.max_job_mem = max(self.max_job_mem, int(node["Memory"]))
        self.logger.info("learned cluster %s: max job %d cores / %d MB",
                         cluster.get("Id"), self.max_job_cores,
                         self.max_job_mem)

    # -- job generation (sendJobs, client.go:85-147) --
    def _get_job(self) -> dict:
        self.jobs_sent += 1
        cores = int(self._rng.beta(self.wcfg.beta_alpha, self.wcfg.beta_beta)
                    * self.max_job_cores)
        mem = int(self._rng.beta(self.wcfg.beta_alpha, self.wcfg.beta_beta)
                  * self.max_job_mem)
        dur_s = int(self._rng.integers(0, self.wcfg.max_duration_s))
        return job_to_json(self.jobs_sent, cores, mem, dur_s * 1000)

    def _send_one(self, payload: dict) -> None:
        """POST one job; a 503 back-pressure quote honors RetryAfterMs and
        a transport failure (status 0 — a dead or restarting scheduler,
        including one killed mid-response, which httpd maps to 0) is
        equally retryable, both with jittered exponential backoff under
        the bounded attempt budget (services/backoff.py)."""
        body = json.dumps(payload).encode()
        for attempt in range(self.retry_attempts):
            status, resp = httpd.post_bytes(
                self.scheduler_url + "/delay", body,
                content_type="application/json")
            if status == 200:
                return
            if status not in (0, 503):
                self.logger.error("job %s rejected: %s", payload["Id"],
                                  status)
                return
            quote_ms = 100.0
            if status == 503:
                self.retries_503 += 1
                try:
                    # the server's quote is already wall-scaled (it
                    # divides by its own speed): used as-is for the base
                    quote_ms = float(json.loads(resp)["RetryAfterMs"])
                except (ValueError, TypeError, KeyError):
                    pass
            else:
                self.conn_retries += 1
            delay = jittered_backoff_ms(
                attempt, max(quote_ms, 1.0), 5_000.0 / self.speed,
                self._rng) / 1000.0
            if self._stop.wait(delay):
                return
        self.retries_exhausted += 1
        self.logger.error(
            "job %s: retry budget (%d attempts) exhausted against "
            "back-pressure/transport failures — giving up", payload["Id"],
            self.retry_attempts)

    def _send_jobs(self) -> None:
        if self.wcfg.arrival == "weibull":
            self._weibull_loop()
        else:
            self._poisson_loop()

    def _poisson_loop(self) -> None:
        lam = self.wcfg.poisson_lambda_per_min
        while not self._done():
            jobs = int(self._rng.poisson(lam))
            if jobs == 0:  # Go would panic on 60/0 (client.go:116)
                if self._stop.wait(60.0 / self.speed):
                    return
                continue
            gap = (60 // jobs) / self.speed  # Go integer division
            for _ in range(jobs):
                if self._done():
                    return
                self._send_one(self._get_job())
                if self._stop.wait(gap):
                    return

    def _weibull_loop(self) -> None:
        lam, k = self.wcfg.weibull_lambda_s, self.wcfg.weibull_k
        while not self._done():
            self._send_one(self._get_job())
            gap = lam * float(self._rng.weibull(k))
            if self._stop.wait(gap / self.speed):
                return

    def _done(self) -> bool:
        return self._stop.is_set() or (
            self.max_jobs is not None and self.jobs_sent >= self.max_jobs)
