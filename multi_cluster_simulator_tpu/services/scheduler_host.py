"""The scheduler service: a live host process around the device engine.

Reference: pkg/scheduler's HTTP surface + run loop (server.go:22-153,
scheduler.go:101-124) and cmd/scheduler/main.go wiring. One service hosts one
cluster. The Go scheduler's 1 s loop *is* its decision engine; here the loop
body is one jitted ``Engine.tick_io`` call on a C=1 ``SimState`` — the
placement kernels, queue bookkeeping, and wait accounting all run on the
device, and the host acts on the returned ``TickIO`` over the network:
borrow fan-out (BorrowResources, server.go:160-248) and finished-foreign-job
returns (ReturnToBorrower, server.go:260-290).

Wire parity: the HTTP endpoints (``/``, ``/delay``, ``/borrow``, ``/lent``,
``/newClient``) accept and emit the reference's Go-struct JSON shapes —
``Job`` fields ``Id/CoresNeeded/MemoryNeeded/Duration`` (int64 nanoseconds,
Go ``time.Duration``) ``/Ownership``; ``/newClient`` returns the Go
``Cluster`` JSON (spec.to_json). A Go client of the reference could talk to
this service unchanged.

``speed`` scales virtual time against wall time: the reference's 1 s tick
becomes ``tick_ms / 1000 / speed`` wall seconds (speed=1000 → ~1 ms/tick,
used by the integration tests).
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Optional

import jax
import numpy as np

from multi_cluster_simulator_tpu.config import (
    RETURN_ATTEMPTS, PolicyKind, SimConfig,
)
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.spec import ClusterSpec
from multi_cluster_simulator_tpu.core.state import Arrivals, init_state
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R
from multi_cluster_simulator_tpu.services import host_ops, httpd, telemetry
from multi_cluster_simulator_tpu.services.lifecycle import Service
from multi_cluster_simulator_tpu.services.registry import SERVICE_SCHEDULER


# -- Go Job JSON wire format (scheduler.go:65-73): struct field order,
# Duration in int64 nanoseconds, State a StateType STRING (zero value ""),
# WaitTime a time.Time (zero marshals as 0001-01-01T00:00:00Z) — pinned
# byte-for-byte against Go's json.Marshal by tests/test_wire_fixtures.py --

GO_ZERO_TIME = "0001-01-01T00:00:00Z"


def job_to_json(id, cores, mem, dur_ms, ownership="", state="") -> dict:
    return {"Id": int(id), "MemoryNeeded": int(mem),
            "CoresNeeded": int(cores), "State": state,
            "Duration": int(dur_ms) * 1_000_000,
            "WaitTime": GO_ZERO_TIME, "Ownership": ownership}


def job_from_json(d: dict) -> tuple[int, int, int, int, str]:
    """(id, cores, mem, dur_ms, ownership); accepts Go field names."""
    dur_ns = int(d.get("Duration", 0))
    return (int(d.get("Id", 0)), int(d.get("CoresNeeded", 0)),
            int(d.get("MemoryNeeded", 0)), dur_ns // 1_000_000,
            str(d.get("Ownership", "") or ""))


class SchedulerService(Service):
    service_name = SERVICE_SCHEDULER
    # discovers *peer* schedulers for borrowing (cmd/scheduler/main.go:81-86)
    required_services = [SERVICE_SCHEDULER]

    def __init__(self, name: str, spec: ClusterSpec, cfg: SimConfig,
                 registry_url: Optional[str] = None, speed: float = 1.0,
                 grpc_port: Optional[int] = 0,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_period_ticks: int = 50, **kw):
        super().__init__(name, registry_url=registry_url, speed=speed, **kw)
        # Live checkpointing (a capability the reference lacks — a Go
        # scheduler restart loses every queue, SURVEY.md §5): persist the
        # device state every N ticks; on start, restore from the file if it
        # exists so queued/running work survives a process restart.
        self.checkpoint_path = checkpoint_path
        self.checkpoint_period_ticks = checkpoint_period_ticks
        # gRPC ResourceChannel for this cluster's trader; None disables it
        # (cmd/scheduler starts one alongside the HTTP server, main.go:62-79)
        self.grpc_port = grpc_port
        self.grpc_addr: Optional[str] = None
        self._grpc_server = None
        self.spec = spec
        self.cfg = cfg
        self.engine = Engine(cfg)
        self._tick_fn = jax.jit(self.engine.tick_io)
        self._slock = threading.RLock()  # guards: state, _arr, _arr_n, _journal, _owner_urls, _owner_idx
        self.state = init_state(cfg, [spec])
        # host-side arrival staging ring ([1, A] to match the engine shapes)
        A = cfg.max_arrivals
        self._arr = {k: np.zeros((1, A), np.int32)
                     for k in ("t", "id", "cores", "mem", "gpu", "dur")}
        self._arr_n = 0
        # submit handlers append here without touching the device lock;
        # the tick thread drains it (so an in-flight compile or device step
        # never blocks the HTTP surface)
        self._pending: list[tuple] = []
        # staged-but-not-consumed jobs (pending + unconsumed ring rows):
        # the submit handlers' back-pressure bound. Kept <= max_arrivals,
        # which makes the drain-time ring-full drop structurally
        # unreachable — a full ring answers 503 at submit time (with the
        # client still holding the job) instead of 200-then-silent-drop.
        # Conservative between ticks (the device may have consumed more
        # than the last recount saw); _drain_pending recomputes it.
        self._staged_n = 0
        self._plock = threading.Lock()  # guards: _pending, _staged_n
        # mutation journal: a list while a tick's device call is in flight
        # (handlers' state ops are replayed onto the tick result at swap
        # time — see _mutate/_tick_once), None otherwise
        self._journal: Optional[list] = None
        # borrower table: Ownership URL <-> owner index (>=1; 0 is this
        # cluster's own index in batch-engine semantics)
        self._owner_urls: list[str] = ["<self>"]
        self._owner_idx: dict[str, int] = {}
        self._stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None
        # wedged-shutdown honesty: the join timeout is an attribute so
        # tests can shrink it; a blown timeout flips _wedged (and the
        # /healthz verdict) instead of returning as if shutdown succeeded
        self.stop_join_timeout_s = 10.0
        self._wedged: Optional[str] = None
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix=f"{name}-io")
        self.ticks_run = 0
        self._last_tick_wall: Optional[float] = None  # tick-loop liveness
        # Restore here, in __init__ — before Service.start() brings the
        # HTTP surface up — so no acknowledged mutation can ever precede
        # (and be clobbered by) the state swap.
        if checkpoint_path is not None and os.path.exists(checkpoint_path):
            try:
                self._restore_checkpoint()
            except Exception as e:
                # an unreadable/incompatible checkpoint (older format,
                # different config) must not brick the service — start
                # fresh and say so loudly
                self.state = init_state(cfg, [spec])
                self.logger.error(
                    "checkpoint %s not restorable (%r); starting fresh",
                    checkpoint_path, e)

    def _restore_checkpoint(self) -> None:
        from multi_cluster_simulator_tpu.core.checkpoint import (
            load_extra, load_state,
        )
        # cfg engages the v2 header digest: a checkpoint from a
        # differently-configured scheduler is refused with the field
        # named (and the caller's start-fresh fallback engages)
        self.state = load_state(self.checkpoint_path, self.state,
                                cfg=self.cfg)
        # the host arrival ring died with the old process; rebase the
        # device cursor to the now-empty ring
        consumed = int(np.asarray(self.state.arr_ptr)[0])
        if consumed:
            self.state = host_ops.rebase_arrivals(self.state, consumed)
        extra = load_extra(self.checkpoint_path)
        if extra:
            # borrower table — without it, owner indices in the restored
            # lent queue could never be returned
            self._owner_urls = extra["owner_urls"]
            self._owner_idx = {u: i for i, u
                               in enumerate(self._owner_urls) if i}
            # acknowledged-but-not-ingested jobs re-stage for the first
            # tick (they re-arrive at the restored clock); the submit
            # bound counts them from the start
            self._pending.extend(tuple(p) for p in extra.get("pending", []))
            self._staged_n = len(self._pending)
        self.logger.info(
            "restored checkpoint %s (t=%d ms, %d running, %d queued)",
            self.checkpoint_path, int(np.asarray(self.state.t)),
            int(np.asarray(self.state.run.active).sum()),
            int(np.asarray(self.state.jobs_in_queue)[0]))

    # ------------------------------------------------------------------
    # HTTP surface (RegisterHandlers, server.go:22-153)
    # ------------------------------------------------------------------
    def register_handlers(self) -> None:
        self.httpd.route("POST", "/", self._handle_submit_fifo)
        self.httpd.route("POST", "/delay", self._handle_submit_delay)
        self.httpd.route("POST", "/borrow", self._handle_borrow)
        self.httpd.route("POST", "/lent", self._handle_lent)
        self.httpd.route("GET", "/newClient", self._handle_new_client)
        # /metrics + /healthz come from the Service defaults
        # (lifecycle.py); health() below watches the tick loop

    def _handle_submit_fifo(self, body: bytes, headers: dict):
        """POST / — submit to the ReadyQueue (server.go:23-51) *regardless
        of the configured algorithm*, exactly as the reference's handler
        does; echoes a GET <Referer>/jobAdded acknowledgement. A full
        staging ring answers a retryable 503 (the job was NOT accepted)
        instead of the old 200-then-silent-drop."""
        try:
            job = job_from_json(json.loads(body))
        except ValueError:
            return 400, None
        # manual job-receipt span nested under the middleware's server span
        # (the reference opens one at the top of the handler, server.go:24)
        with self.tracer.start_span("receive_job", job_id=job[0]):
            if not self._stage_arrival(job, delay=False):
                return 503, self._ring_full_quote()
        referer = headers.get("Referer")
        if referer:
            self._pool.submit(httpd.get, referer.rstrip("/") + "/jobAdded")
        return 200, None

    def _handle_submit_delay(self, body: bytes, headers: dict):
        """POST /delay — submit to Level0 + wait-timer start
        (server.go:53-78), again endpoint-routed, not policy-routed. The
        device ingest phase starts the wait timer and the on-state
        jobs_in_queue counter; the meter here mirrors the handler-side OTel
        counter (server.go:75-76). 503 + quote when the staging ring is
        full, like POST /."""
        try:
            job = job_from_json(json.loads(body))
        except ValueError:
            return 400, None
        with self.tracer.start_span("receive_job", job_id=job[0]):
            if not self._stage_arrival(job, delay=True):
                return 503, self._ring_full_quote()
        self.meter.add("jobs_in_queue", 1)
        return 200, None

    def _ring_full_quote(self) -> bytes:
        """Machine-readable retry quote for a back-pressured submit: the
        ring turns over as the tick loop drains it, so one tick period is
        the natural retry horizon."""
        return json.dumps({
            "Error": "arrival ring full — retry",
            "RetryAfterMs": round(self.cfg.tick_ms / self.speed, 3),
        }).encode()

    def _mutate(self, op, replay=None):
        """Apply a state op (state -> (state', aux)) under the lock and
        return aux. While a tick's device call is in flight (_tick_once
        computes outside the lock), the op is also journaled and re-applied
        onto the tick's output at swap time — the "handler ran just after
        the tick" interleaving, which the reference's handlers race against
        its scheduling goroutine the same way (server.go:80-137 vs
        scheduler.go:298-369).

        ``replay`` (state -> (state', aux)), when given, is what the
        journal re-applies instead of ``op``: a decision the handler has
        ALREADY acknowledged must not vanish silently if the tick consumed
        the capacity it was based on — replay variants surface that as a
        drop counter + error log instead (the Go analogue commits under
        the node lock and the scheduler sees it afterwards; the one
        remaining soft spot, commit_borrow's still-same-head gate, is the
        first-200-wins race the reference also has)."""
        with self._slock:
            self.state, aux = op(self.state)
            if self._journal is not None:
                self._journal.append(replay or op)
        return aux

    def _handle_borrow(self, body: bytes, headers: dict):
        """POST /borrow — a peer asks me to host a job: Lend() feasibility,
        then append to the LentQueue with the borrower's ownership
        (server.go:80-113). 406 when infeasible."""
        try:
            jid, cores, mem, dur_ms, ownership = job_from_json(json.loads(body))
        except ValueError:
            return 400, None
        with self._slock:
            if not bool(host_ops.lend_feasible(self.state, cores, mem)):
                return 406, None
            owner = self._intern_owner(ownership)
            vec = Q.JobRec.make(id=jid, cores=cores, mem=mem, dur=dur_ms,
                                enq_t=int(self.state.t), owner=owner).vec

            def replay(s):
                s2 = host_ops.push_lent(s, vec)
                if int(np.asarray(s2.lent.count)[0]) == int(np.asarray(s.lent.count)[0]):
                    # acked 200 but the post-tick LentQueue is full — surface
                    self.logger.error(
                        "replay: lent queue full, acked /borrow job %d dropped", jid)
                    s2 = s2.replace(drops=s2.drops.replace(
                        queue=s2.drops.queue + 1))
                return s2, None

            self._mutate(lambda s: (host_ops.push_lent(s, vec), None),
                         replay=replay)
        self.logger.info("lent: accepted job %d from %s", jid, ownership)
        return 200, None

    def _handle_lent(self, body: bytes, headers: dict):
        """POST /lent — a lender returns my finished job: remove it from the
        BorrowedQueue by field equality (server.go:115-137)."""
        try:
            jid, cores, mem, dur_ms, _ = job_from_json(json.loads(body))
        except ValueError:
            return 400, None
        vec = Q.JobRec.make(id=jid, cores=cores, mem=mem, dur=dur_ms).vec
        self._mutate(lambda s: (host_ops.remove_borrowed(s, vec), None))
        return 200, None

    def _handle_new_client(self, body: bytes, headers: dict):
        """GET /newClient — serialize my cluster for a joining workload
        client (server.go:139-153)."""
        return 200, json.dumps(self.spec.to_json(url=self.url or "")).encode()

    # ------------------------------------------------------------------
    # arrival staging (the tensor form of the submit handlers)
    # ------------------------------------------------------------------
    def _stage_arrival(self, job, delay: bool) -> bool:
        """Stage a submitted job for the tick thread. Returns False —
        nothing staged — when the ring bound is reached: the handler
        answers 503 and the telemetry counts the rejection, so a full ring
        is the CLIENT's signal to retry, never a silent drop at drain
        time."""
        jid, cores, mem, dur_ms, _ = job
        with self._plock:
            if self._staged_n >= self.cfg.max_arrivals:
                rejected = True
            else:
                rejected = False
                self._staged_n += 1
                self._pending.append((jid, cores, mem, dur_ms, delay))
        if rejected:
            self.meter.add("submit_rejected", 1)
            self.logger.warning(
                "arrival ring full; rejecting job %d with 503", jid)
            return False
        return True

    def _drain_pending(self) -> None:  # holds: _slock
        """Move submitted jobs into the engine, timestamped at the current
        virtual time. Caller holds the state lock.

        Routing is by *endpoint*, as in the reference (server.go:22-78):
        jobs submitted on the endpoint matching the configured policy
        (``/delay`` for DELAY/FFD, ``/`` for FIFO) flow through the batched
        arrival ring into the queue the policy drains; mismatched-endpoint
        jobs are pushed straight into the queue the policy *ignores* —
        where, exactly as in Go, they sit forever."""
        with self._plock:
            pending, self._pending = self._pending, []
        if not pending:
            self._recount_staged()
            return
        now = int(np.asarray(self.state.t))
        delay_policy = self.cfg.policy is not PolicyKind.FIFO
        for jid, cores, mem, dur_ms, delay in pending:
            if delay != delay_policy:  # endpoint the policy never drains
                vec = Q.JobRec.make(id=jid, cores=cores, mem=mem, dur=dur_ms,
                                    enq_t=now).vec
                op = host_ops.push_l0 if delay else host_ops.push_ready
                self.state = op(self.state, vec)
                continue
            if self._arr_n == self.cfg.max_arrivals:
                self._compact_arrivals()
            if self._arr_n == self.cfg.max_arrivals:
                # structurally unreachable since the submit bound
                # (_stage_arrival keeps staged <= max_arrivals, and the
                # compaction above removes every consumed row) — but if a
                # future edit breaks that invariant, COUNT the loss so no
                # acknowledged job ever vanishes silently
                self.logger.error(
                    "arrival ring full at drain; dropping acked job %d "
                    "(staging bound violated?)", jid)
                self.state = self.state.replace(drops=self.state.drops.replace(
                    queue=self.state.drops.queue.at[0].add(1)))
                continue
            i = self._arr_n
            self._arr["t"][0, i] = now
            self._arr["id"][0, i] = jid
            self._arr["cores"][0, i] = cores
            self._arr["mem"][0, i] = mem
            self._arr["dur"][0, i] = dur_ms
            self._arr_n += 1
        self._recount_staged()

    def _recount_staged(self) -> None:  # holds: _slock
        """Re-anchor the submit-path back-pressure counter to ground
        truth: unconsumed ring rows (the device cursor advanced since the
        last drain) plus whatever landed in _pending meanwhile."""
        consumed = int(np.asarray(self.state.arr_ptr)[0])
        with self._plock:
            self._staged_n = (self._arr_n - consumed) + len(self._pending)

    def _compact_arrivals(self) -> None:  # holds: _slock
        """Drop the consumed prefix of the ring and rebase the device
        cursor (host_ops.rebase_arrivals). Caller holds the state lock."""
        consumed = int(np.asarray(self.state.arr_ptr)[0])
        if consumed <= 0:
            return
        for a in self._arr.values():
            a[0, :self._arr_n - consumed] = a[0, consumed:self._arr_n]
        self._arr_n -= consumed
        self.state = host_ops.rebase_arrivals(self.state, consumed)

    def _arrivals_device(self) -> Arrivals:  # holds: _slock
        return Arrivals(
            t=self._arr["t"], id=self._arr["id"], cores=self._arr["cores"],
            mem=self._arr["mem"], gpu=self._arr["gpu"], dur=self._arr["dur"],
            n=np.array([self._arr_n], np.int32))

    # ------------------------------------------------------------------
    # tick loop (the Run goroutine, scheduler.go:101-124)
    # ------------------------------------------------------------------
    def on_start(self) -> None:
        self._warmup()
        if self.grpc_port is not None:
            from multi_cluster_simulator_tpu.services import rpc
            cadence_s = self.cfg.trader.state_cadence_ms / 1000.0 / self.speed
            self._grpc_server, self.grpc_addr = rpc.start_server(
                [rpc.resource_channel_handler(self, cadence_s, self._stop)],
                port=self.grpc_port)
        # anchor the /healthz recency check at loop start: without this,
        # a tick thread wedged inside its very FIRST device call would
        # never set the timestamp and the None-guard would skip the
        # recency check forever — alive-but-stuck reporting 200
        self._last_tick_wall = time.time()
        self._tick_thread = threading.Thread(target=self._tick_loop,
                                             daemon=True,
                                             name=f"{self.name}-tick")
        self._tick_thread.start()

    def on_shutdown(self) -> None:
        self._stop.set()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1)
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=self.stop_join_timeout_s)
            if self._tick_thread.is_alive():
                # the tick loop never exited — it may be mid-device-call
                # and still owns the state lock's cadence; say so loudly
                # and flip /healthz to 503 (lifecycle keeps the surface
                # up for a wedged service) instead of a silent "stopped"
                self._wedged = self._tick_thread.name
                self.logger.error(
                    "shutdown: %s did not exit within %.1fs — wedged; "
                    "/healthz flipped to 503", self._wedged,
                    self.stop_join_timeout_s)
        self._pool.shutdown(wait=False)

    def on_stopped(self) -> None:
        # final graceful snapshot — taken only after the HTTP surface is
        # down, so no acknowledged mutation (e.g. a 200'd /borrow) can land
        # after the state we persist
        if self.checkpoint_path is not None:
            self._save_checkpoint()

    def _save_checkpoint(self) -> None:
        """Persist the device state plus the host-side pieces the state's
        indices are meaningless without: the borrower table (owner indices
        in the lent queue) and every 200-acknowledged job that hasn't been
        device-ingested yet (the pending list and the unconsumed tail of
        the arrival ring). Everything lands in ONE atomic file (the extra
        header of core/checkpoint.py), so a kill can never leave a
        state/sidecar pair from different moments.

        Only the reference snapshot happens under the lock — SimState is an
        immutable pytree, so serialization and disk I/O run outside it and
        never stall the HTTP handlers or the tick loop."""
        from multi_cluster_simulator_tpu.core.checkpoint import save_state
        delay_policy = self.cfg.policy is not PolicyKind.FIFO
        with self._slock:
            state = self.state  # immutable snapshot by reference
            arr_n = self._arr_n
            ring = {k: a[0, :arr_n].copy() for k, a in self._arr.items()}
            with self._plock:
                pending = [list(p) for p in self._pending]
            owner_urls = list(self._owner_urls)
        consumed = int(np.asarray(state.arr_ptr)[0])
        for i in range(consumed, arr_n):  # staged but not ingested
            pending.append([int(ring["id"][i]), int(ring["cores"][i]),
                            int(ring["mem"][i]), int(ring["dur"][i]),
                            delay_policy])
        save_state(state, self.checkpoint_path,
                   extra={"owner_urls": owner_urls, "pending": pending},
                   cfg=self.cfg)

    def _warmup(self) -> None:
        """Compile the tick and the handler-path host ops before serving
        traffic, so no HTTP request ever waits on an XLA compile. The HTTP
        surface is already up when on_start runs, so even this read-only
        pass takes the state lock."""
        import jax
        with self._slock:
            jax.block_until_ready(
                self._tick_fn(self.state, self._arrivals_device()))  # discarded
            vec = Q.JobRec.make(id=0, cores=1, mem=1, dur=1).vec
            host_ops.lend_feasible(self.state, 1, 1)
            host_ops.push_lent(self.state, vec)
            host_ops.remove_borrowed(self.state, vec)
            host_ops.commit_borrow(self.state, vec)
            host_ops.push_ready(self.state, vec)
            host_ops.push_l0(self.state, vec)

    def _tick_loop(self) -> None:
        period = self.cfg.tick_ms / 1000.0 / self.speed
        while not self._stop.wait(period):
            try:
                self._tick_once()
            except Exception as e:  # keep the loop alive; report loudly
                self.logger.error("tick failed: %r", e)

    def _tick_once(self) -> None:
        # Double-buffered: snapshot under the lock, run the jitted device
        # call OUTSIDE it (it is the long pole — /borrow, /lent and the
        # gRPC handlers must never stall a full tick on it), then swap,
        # replaying any handler mutations that landed mid-tick (_mutate).
        with self._slock:
            self._drain_pending()
            snap = self.state
            arr = self._arrivals_device()
            self._journal = []
        try:
            state, io = self._tick_fn(snap, arr)
            io = jax.tree.map(np.asarray, io)
        except Exception:
            # journaled ops already live in self.state (the interim copy we
            # keep by skipping the swap); disarm so the list can't grow
            # unboundedly while the loop logs and retries
            with self._slock:
                self._journal = None
            raise
        with self._slock:
            try:
                for op in self._journal:
                    state, _ = op(state)
                self.state = state
            finally:
                # a replay failure keeps the interim self.state (ops were
                # already applied to it) — the tick is lost, not the acks
                self._journal = None
            t = int(np.asarray(self.state.t))
        self.ticks_run += 1
        self._last_tick_wall = time.time()
        if (self.checkpoint_path is not None
                and self.ticks_run % self.checkpoint_period_ticks == 0):
            self._save_checkpoint()
        # waitTime histogram on the reference's 5 s metric cadence
        # (metrics.go:19-30), plus the state gauges the /metrics surface
        # serves (tick-thread-side under the lock the read needs anyway —
        # never a handler-path device sync)
        if t % 5_000 == 0:
            with self._slock:
                self.meter.record("waitTime",
                                  float(np.asarray(st.avg_wait_ms(self.state))[0]))
                self.meter.set_gauge(
                    "placed_total",
                    float(np.asarray(self.state.placed_total)[0]))
                from multi_cluster_simulator_tpu.obs.device import (
                    queue_depth,
                )
                self.meter.set_gauge(
                    "queue_depth",
                    float(np.asarray(queue_depth(self.state))[0]))
        self.meter.set_gauge("ticks_run", float(self.ticks_run))
        self._process_returns(io)
        self._process_borrow(io)

    # -- TickIO actions --
    def _process_returns(self, io) -> None:
        """POST each finished foreign job back to its borrower's /lent,
        up to 3 attempts (ReturnToBorrower, server.go:260-290)."""
        # the borrower table grows from handler threads (under _slock);
        # snapshot it once instead of indexing it race-ily per message
        with self._slock:
            owner_urls = list(self._owner_urls)
        for m in range(io.ret_valid.shape[1]):
            if not io.ret_valid[0, m]:
                continue
            row = io.ret_rows[0, m]
            owner = int(row[R.ROWNER])
            if not (1 <= owner < len(owner_urls)):
                continue
            url = owner_urls[owner]
            payload = job_to_json(row[R.RID], row[R.RCORES], row[R.RMEM],
                                  row[R.RDUR], ownership=url)
            self._pool.submit(telemetry.wrap_ctx(self._post_return),
                              url, payload)

    def _post_return(self, url: str, payload: dict) -> None:
        """POST the finished job to the borrower's /lent, under a
        ReturnToBorrower span (server.go:260-290)."""
        with self.tracer.start_span("ReturnToBorrower", job_id=payload["Id"]):
            for _ in range(RETURN_ATTEMPTS):
                status, _ = httpd.post_json(url.rstrip("/") + "/lent", payload)
                if status == 200:
                    return
        self.logger.error("return to %s failed after %d attempts", url,
                          RETURN_ATTEMPTS)

    def _process_borrow(self, io) -> None:
        """BorrowResources (server.go:160-248): broadcast the failing
        wait-head to every peer scheduler; first 200 OK wins and the job
        moves WaitQueue -> BorrowedQueue. Lenders that also said OK keep
        their LentQueue copies — the reference never aborts them."""
        if not (self.cfg.borrowing and bool(io.borrow_want[0])):
            return
        if self.registry is None:
            return
        try:
            peers = [u for u in self.registry.get_providers(SERVICE_SCHEDULER)
                     if u != self.url]
        except LookupError:
            return
        if not peers:
            return
        vec = io.borrow_job[0]
        job = Q.JobRec(vec=vec)
        payload = job_to_json(int(job.id), int(job.cores), int(job.mem),
                              int(job.dur), ownership=self.url)
        # BorrowResources span: the /borrow POSTs inherit it via wrap_ctx,
        # so the lender's server span parents onto this one (the
        # borrower→lender causality the reference's otelhttp gives it)
        with self.tracer.start_span("BorrowResources", job_id=int(job.id)):
            futs = {self._pool.submit(
                telemetry.wrap_ctx(httpd.post_json),
                p.rstrip("/") + "/borrow", payload): p for p in peers}
            for fut in as_completed(futs, timeout=10):
                status, _ = fut.result()
                if status == 200:
                    self._mutate(lambda s: (host_ops.commit_borrow(s, vec), None))
                    self.logger.info("borrowed: job %d hosted by %s",
                                     int(job.id), futs[fut])
                    break

    def _intern_owner(self, url: str) -> int:  # holds: _slock
        if url not in self._owner_idx:
            self._owner_idx[url] = len(self._owner_urls)
            self._owner_urls.append(url)
        return self._owner_idx[url]

    # ------------------------------------------------------------------
    # ResourceChannel surface (trader_server.go) — called by the rpc layer
    # ------------------------------------------------------------------
    def cluster_state(self) -> dict:
        """One ClusterState sample (trader_server.go:24-47)."""
        with self._slock:
            cu, mu = st.snapshot_utilization(self.state)
            return {
                "cores_utilization": float(np.asarray(cu)[0]),
                "memory_utilization": float(np.asarray(mu)[0]),
                "total_cpu": int(np.asarray(self.state.trader.snap_total_cores)[0]),
                "total_memory": int(np.asarray(self.state.trader.snap_total_mem)[0]),
                "average_wait_time": float(np.asarray(st.avg_wait_ms(self.state))[0]),
            }

    def level1_jobs(self) -> list[dict]:
        """GetLevel1 for ProvideJobs (scheduler.go:204-214)."""
        with self._slock:
            l1 = jax.tree.map(np.asarray, self.state.l1)
        n = int(l1.count[0])
        return [{"cores": int(l1.data[0, i, Q.FCORES]),
                 "mem": int(l1.data[0, i, Q.FMEM]),
                 "dur_ms": int(l1.data[0, i, Q.FDUR])} for i in range(n)]

    def provide_virtual_node(self, cores: int, mem: int, dur_ms: int) -> bool:
        """Lender-side carve (ProvideVirtualNode -> cluster.go:87-125)."""
        def op(s):
            s2, ok = host_ops.carve_occupy(
                s, cores, mem, dur_ms, mode=self.cfg.trader.carve_mode)
            ok = bool(ok)
            return (s2 if ok else s), ok

        def replay(s):
            s2, ok = op(s)
            if not ok:
                # the carve was already acked to the buyer; the tick consumed
                # the capacity it was based on — count it, don't lose it
                self.logger.error(
                    "replay: acked carve (%d cores, %d MB) no longer fits", cores, mem)
                s2 = s2.replace(drops=s2.drops.replace(carve=s2.drops.carve + 1))
            return s2, ok

        return self._mutate(op, replay=replay)

    def receive_virtual_node(self, cores: int, mem: int, dur_ms: int) -> bool:
        """Borrower-side attach (ReceiveVirtualNode -> cluster.go:65-85)."""
        def op(s):
            s2, ok = host_ops.add_virtual_node(
                s, cores, mem, dur_ms, vstart=self.cfg.max_nodes,
                expire=self.cfg.trader.expire_virtual_nodes)
            ok = bool(ok)
            return (s2 if ok else s), ok

        def replay(s):
            s2, ok = op(s)
            if not ok:
                self.logger.error(
                    "replay: acked virtual node (%d cores, %d MB) lost its slot",
                    cores, mem)
                s2 = s2.replace(drops=s2.drops.replace(vslot=s2.drops.vslot + 1))
            return s2, ok

        return self._mutate(op, replay=replay)

    def health(self) -> tuple[bool, dict]:
        """/healthz verdict for the per-request host: the tick loop (the
        Go scheduler's Run goroutine equivalent) must be alive AND
        actually ticking — a loop thread wedged on a device call stays
        is_alive() forever, so recency is the real check (10 tick periods
        of slack covers a slow dispatch; the loop's own exception guard
        already keeps transient tick failures from killing it)."""
        checks = {}
        if self._wedged:
            # unconditional (survives _started flipping off): a wedged
            # stop must read as unhealthy, never as a clean shutdown
            checks["shutdown_wedged"] = False
            checks["wedged_thread"] = self._wedged
        if self._started:
            checks["tick_thread_alive"] = (self._tick_thread is not None
                                           and self._tick_thread.is_alive())
            period = self.cfg.tick_ms / 1000.0 / self.speed
            if self._last_tick_wall is not None:
                lag = time.time() - self._last_tick_wall
                checks["tick_loop_ticking"] = lag < max(10 * period, 2.0)
                checks["last_tick_s_ago"] = round(lag, 3)
        ok = all(v for v in checks.values() if isinstance(v, bool))
        return ok, {**checks, "ticks_run": self.ticks_run}

    # -- introspection for tests/operators --
    def stats(self) -> dict:
        with self._slock:
            s = self.state
            return {"t_ms": int(np.asarray(s.t)),
                    "placed_total": int(np.asarray(s.placed_total)[0]),
                    "jobs_in_queue": int(np.asarray(s.jobs_in_queue)[0]),
                    "ready": int(np.asarray(s.ready.count)[0]),
                    "l0": int(np.asarray(s.l0.count)[0]),
                    "lent": int(np.asarray(s.lent.count)[0]),
                    "borrowed": int(np.asarray(s.borrowed.count)[0]),
                    "running": int(np.asarray(s.run.active).sum()),
                    "avg_wait_ms": float(np.asarray(st.avg_wait_ms(s))[0])}
