"""The service shell — the reference's microservice constellation, rebuilt.

The reference deploys as five independently-launched OS processes wired over
HTTP/JSON and gRPC (SURVEY.md §1): a registry (service discovery + heartbeat,
pkg/registry), N schedulers (pkg/scheduler), N traders (pkg/trader), workload
clients (pkg/client), and a log sink (log/). This package preserves that
topology and its wire surface — the same HTTP endpoints, the same proto
messages — while the *decisions* inside each scheduler/trader host run as
jitted kernels on the accelerator (the north-star architecture: hosts keep
the service fabric, placement moves to the device).

Modules:
  httpd      — routed threading HTTP server + client helpers (net/http analogue)
  telemetry  — structured logging, spans, metrics (internal/service/telemetry.go)
  registry   — discovery server + client cache + heartbeat (pkg/registry)
  logsink    — centralized log service (log/)
  lifecycle  — service bootstrap/shutdown (internal/service/service.go)
  host_ops   — jitted device-boundary ops the live hosts call between ticks
  scheduler_host — the scheduler service (pkg/scheduler servers)
  trader_host    — the trader service (pkg/trader)
  workload       — the workload-generator client service (pkg/client)
  rpc        — gRPC bindings over the proto messages (pkg/trader/gen)
  main       — entry points (cmd/*)
  serving    — the batched front door (scheduling-as-a-service): staged
               concurrent submits coalesced into one multi-tick device
               dispatch per window, snapshot-backed queries, explicit
               503 back-pressure (ARCHITECTURE.md §serving tier)
"""

from multi_cluster_simulator_tpu.services.registry import (  # noqa: F401
    RegistryServer, ServiceRegistration, SERVICE_SCHEDULER, SERVICE_TRADER,
    SERVICE_LOG,
)
