"""Jitted device-boundary ops for the live service hosts.

A live scheduler host owns a C=1 ``SimState`` on the device and advances it
with ``Engine.tick_io``. Between ticks, HTTP/gRPC handlers must mutate that
state the way the reference's handlers mutate the Go scheduler's queues —
append to LentQueue on ``/borrow`` (pkg/scheduler/server.go:94-107), remove
from BorrowedQueue on ``/lent`` (server.go:115-137), carve lender capacity on
``ProvideVirtualNode`` (cluster.go:87-125), attach a virtual node on
``ReceiveVirtualNode`` (cluster.go:65-85). Each such mutation is one small
jitted pure function here: host threads hold a lock, call the op, and swap
the state pointer. This is the "host keeps the service surface, the device
keeps the state" boundary of the north-star design.

All ops take and return the full batched (C=1) SimState so the same state
object flows between the tick loop and the handlers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.core.spec import CORES, GPU, MEM
from multi_cluster_simulator_tpu.core.state import SimState
from multi_cluster_simulator_tpu.market.trader import FOREIGN, PLACEHOLDER_ID
from multi_cluster_simulator_tpu.ops import carve as carve_ops
from multi_cluster_simulator_tpu.ops import placement as P
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R


def _c0(tree):
    """View of cluster 0 (the live host's only cluster)."""
    return jax.tree.map(lambda a: a[0], tree)


def _put0(tree, sub):
    return jax.tree.map(lambda a, b: a.at[0].set(b), tree, sub)


@jax.jit
def lend_feasible(state: SimState, cores, mem) -> jax.Array:
    """The /borrow handler's Lend() probe (scheduler.go:194-202): any node
    with strictly more free cores AND memory."""
    job = Q.JobRec.make(cores=cores, mem=mem)
    return P.can_lend(state.node_free[0], state.node_active[0], job)


@jax.jit
def push_lent(state: SimState, job_vec) -> SimState:
    """Append a foreign job to the LentQueue (server.go:94-107). The host
    sets the row's owner field to its borrower-table index beforehand."""
    lent0 = Q.push_back(_c0(state.lent), Q.JobRec(vec=job_vec),
                        jnp.ones((), bool))
    return state.replace(lent=_put0(state.lent, lent0))


@jax.jit
def push_ready(state: SimState, job_vec) -> SimState:
    """POST / under a non-FIFO algorithm: the reference's handler appends to
    the ReadyQueue regardless of the configured algorithm
    (server.go:23-51), and the Delay() loop then never drains it — the job
    sits forever. Endpoint-faithful routing (VERDICT r2 weak #7)."""
    ready0 = _c0(state.ready)
    dropped = Q.push_back_dropped(ready0, jnp.ones((), bool))
    ready0 = Q.push_back(ready0, Q.JobRec(vec=job_vec), jnp.ones((), bool))
    return state.replace(
        ready=_put0(state.ready, ready0),
        drops=state.drops.replace(queue=state.drops.queue.at[0].add(dropped)))


@jax.jit
def push_l0(state: SimState, job_vec) -> SimState:
    """POST /delay under FIFO: Level0 append + wait-timer start +
    jobs_in_queue increment (server.go:53-78 runs for any algorithm); the
    Fifo() loop never drains Level0 — the job sits forever, but its
    counters still move exactly as in Go."""
    l00 = _c0(state.l0)
    dropped = Q.push_back_dropped(l00, jnp.ones((), bool))
    l00 = Q.push_back(l00, Q.JobRec(vec=job_vec), jnp.ones((), bool))
    return state.replace(
        l0=_put0(state.l0, l00),
        wait_jobs=state.wait_jobs.at[0].add(1 - dropped),
        jobs_in_queue=state.jobs_in_queue.at[0].add(1 - dropped),
        drops=state.drops.replace(queue=state.drops.queue.at[0].add(dropped)))


def _cat(tree, c):
    """View of cluster ``c`` (traced index — the serving tier hosts the
    whole constellation in one state, unlike the C=1 live hosts)."""
    return jax.tree.map(lambda a: a[c], tree)


def _putat(tree, sub, c):
    return jax.tree.map(lambda a, b: a.at[c].set(b), tree, sub)


@jax.jit
def push_ready_at(state: SimState, job_vec, c) -> SimState:
    """``push_ready`` for cluster ``c`` of a multi-cluster serving state
    (services/serving.py parks mismatched-endpoint jobs here — the
    endpoint-faithful routing the C=1 live host does via ``push_ready``)."""
    ready_c = _cat(state.ready, c)
    dropped = Q.push_back_dropped(ready_c, jnp.ones((), bool))
    ready_c = Q.push_back(ready_c, Q.JobRec(vec=job_vec), jnp.ones((), bool))
    return state.replace(
        ready=_putat(state.ready, ready_c, c),
        drops=state.drops.replace(queue=state.drops.queue.at[c].add(dropped)))


@jax.jit
def push_l0_at(state: SimState, job_vec, c) -> SimState:
    """``push_l0`` for cluster ``c`` of a multi-cluster serving state —
    Level0 append + wait-timer start + jobs_in_queue increment, exactly
    the C=1 ``push_l0`` semantics at a traced cluster index."""
    l0_c = _cat(state.l0, c)
    dropped = Q.push_back_dropped(l0_c, jnp.ones((), bool))
    l0_c = Q.push_back(l0_c, Q.JobRec(vec=job_vec), jnp.ones((), bool))
    return state.replace(
        l0=_putat(state.l0, l0_c, c),
        wait_jobs=state.wait_jobs.at[c].add(1 - dropped),
        jobs_in_queue=state.jobs_in_queue.at[c].add(1 - dropped),
        drops=state.drops.replace(queue=state.drops.queue.at[c].add(dropped)))


@jax.jit
def remove_borrowed(state: SimState, job_vec) -> SimState:
    """The /lent handler (server.go:115-137): a returned finished job is
    removed from the BorrowedQueue by field equality."""
    b0 = Q.remove_matching(_c0(state.borrowed), Q.JobRec(vec=job_vec))
    return state.replace(borrowed=_put0(state.borrowed, b0))


@jax.jit
def commit_borrow(state: SimState, job_vec) -> SimState:
    """Borrower side of a successful /borrow round (scheduler.go:239-242):
    pop the wait head (gated on it still being the same job) and append it
    to the BorrowedQueue."""
    job = Q.JobRec(vec=job_vec)
    wait0 = _c0(state.wait)
    do = jnp.logical_and(wait0.count > 0, Q.head(wait0).id == job.id)
    wait0 = Q.pop_front(wait0, do)
    b0 = Q.push_back(_c0(state.borrowed), job, do)
    return state.replace(wait=_put0(state.wait, wait0),
                         borrowed=_put0(state.borrowed, b0))


@functools.partial(jax.jit, static_argnames=("mode",))
def carve_occupy(state: SimState, cores, mem, dur_ms,
                 mode: str = "asbuilt") -> tuple[SimState, jax.Array]:
    """Lender side of ApproveContract: AllocateVirtualNodeResources
    (cluster.go:87-125) — plan per-node carve amounts, subtract them from
    free, and occupy them as Foreign placeholder running jobs for the
    contract duration. Returns (state', ok)."""
    free0 = state.node_free[0]
    amounts, ok = carve_ops.carve_plan(
        free0, state.node_active[0], jnp.asarray(cores, jnp.int32),
        jnp.asarray(mem, jnp.int32), mode=mode)
    free0 = free0 - jnp.where(ok, amounts, 0)
    t = state.t
    dur = jnp.asarray(dur_ms, jnp.int32)

    def add_placeholder(rn, n):
        occ = jnp.logical_and(ok, jnp.any(amounts[n] > 0))
        slot = jnp.argmin(rn.active).astype(jnp.int32)
        okk = jnp.logical_and(occ, jnp.logical_not(rn.active[slot]))
        row = R.make_row(t + dur, n, amounts[n, CORES], amounts[n, MEM],
                         amounts[n, GPU], PLACEHOLDER_ID, FOREIGN, dur, t)
        hot = jnp.logical_and(
            jnp.arange(rn.capacity, dtype=jnp.int32) == slot, okk)
        return R.insert_row(rn, hot, row), None

    run0, _ = jax.lax.scan(add_placeholder, _c0(state.run),
                           jnp.arange(free0.shape[0], dtype=jnp.int32))
    state = state.replace(node_free=state.node_free.at[0].set(free0),
                          run=_put0(state.run, run0))
    return state, ok


@functools.partial(jax.jit, static_argnames=("vstart", "expire"))
def add_virtual_node(state: SimState, cores, mem, dur_ms, vstart: int,
                     expire: bool = False) -> tuple[SimState, jax.Array]:
    """Borrower side: AddVirtualNode (cluster.go:65-85) — activate the first
    free virtual node slot with the contract's capacity. The reference never
    removes virtual nodes; ``expire=True`` arms the engine's expiry phase
    instead (config.trader.expire_virtual_nodes)."""
    cap0, free0 = state.node_cap[0], state.node_free[0]
    act0, exp0 = state.node_active[0], state.node_expire[0]
    is_v = jnp.arange(cap0.shape[0]) >= vstart
    # skip DOWN slots (fault plane): inactive-but-unhealthy means parked
    # for repair, not vacant (market/trader.py buyer_apply, same rule)
    slot_free = jnp.logical_and(
        is_v, jnp.logical_and(jnp.logical_not(act0),
                              state.faults.health[0]))
    slot = jnp.argmax(slot_free).astype(jnp.int32)
    ok = jnp.any(slot_free)
    newcap = jnp.stack([jnp.asarray(cores, jnp.int32),
                        jnp.asarray(mem, jnp.int32),
                        jnp.zeros((), jnp.int32)])
    cap0 = cap0.at[slot].set(jnp.where(ok, newcap, cap0[slot]))
    free0 = free0.at[slot].set(jnp.where(ok, newcap, free0[slot]))
    act0 = act0.at[slot].set(jnp.where(ok, True, act0[slot]))
    exp_val = (state.t + jnp.asarray(dur_ms, jnp.int32)) if expire else R.NEVER
    exp0 = exp0.at[slot].set(jnp.where(ok, exp_val, exp0[slot]))
    return state.replace(
        node_cap=state.node_cap.at[0].set(cap0),
        node_free=state.node_free.at[0].set(free0),
        node_active=state.node_active.at[0].set(act0),
        node_expire=state.node_expire.at[0].set(exp0)), ok


@jax.jit
def rebase_arrivals(state: SimState, shift) -> SimState:
    """Shift the arrival cursor left by ``shift`` — the host compacted its
    arrival ring by dropping ``shift`` consumed entries from the front."""
    return state.replace(arr_ptr=jnp.maximum(
        state.arr_ptr - jnp.asarray(shift, jnp.int32), 0))
