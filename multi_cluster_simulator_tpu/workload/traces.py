"""Bulk / trace-style workload synthesis for the scale harness.

The reference's only workload is the live Poisson client (generator.py).
The BASELINE.json scale configs need two more shapes, generated vectorized
(one numpy call per field, no per-cluster Python loops):

- ``uniform_stream`` — N jobs per cluster with sorted-uniform arrival times:
  the load shape used by the throughput benchmarks.
- ``borg_like_stream`` — a Google-Borg-2019-shaped synthetic trace: machine
  counts per job drawn heavy-tailed (lognormal), memory correlated with
  cores, lognormal durations, and a diurnal (sinusoidal) arrival intensity.
  Real Borg trace CSVs can be replayed through ``from_arrays``.
"""

from __future__ import annotations

import numpy as np

from multi_cluster_simulator_tpu.core.state import Arrivals


def _pack(t, cores, mem, dur, gpu=None):
    C, A = t.shape
    order = np.argsort(t, axis=1, kind="stable")
    g = lambda a: np.take_along_axis(a, order, axis=1).astype(np.int32)
    return Arrivals(
        t=g(t), id=np.broadcast_to(np.arange(A, dtype=np.int32), (C, A)).copy(),
        cores=g(cores), mem=g(mem),
        gpu=np.zeros((C, A), np.int32) if gpu is None else g(gpu),
        dur=g(dur), n=np.full((C,), A, np.int32))


def uniform_stream(n_clusters: int, jobs_per_cluster: int, horizon_ms: int,
                   max_cores: int, max_mem: int, max_dur_ms: int,
                   seed: int = 0, beta: float = 2.0,
                   max_gpus: int = 0, gpu_frac: float = 0.0) -> Arrivals:
    """Sorted-uniform arrivals; Beta(b,b) sizes (the reference's job-size
    family, client.go:87-99); uniform durations. With ``max_gpus > 0``, a
    ``gpu_frac`` fraction of jobs additionally request 1..max_gpus
    accelerators (the 3-dim-resource workload of BASELINE config 4)."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, jobs_per_cluster
    t = rng.integers(0, horizon_ms, (C, A))
    cores = np.floor(rng.beta(beta, beta, (C, A)) * max_cores)
    mem = np.floor(rng.beta(beta, beta, (C, A)) * max_mem)
    dur = rng.integers(0, max_dur_ms, (C, A))
    gpu = None
    if max_gpus > 0:
        gpu = np.where(rng.random((C, A)) < gpu_frac,
                       rng.integers(1, max_gpus + 1, (C, A)), 0)
    return _pack(t, cores, mem, dur, gpu)


def borg_like_stream(n_clusters: int, jobs_per_cluster: int, horizon_ms: int,
                     max_cores: int, max_mem: int, seed: int = 0) -> Arrivals:
    """Borg-2019-shaped synthetic trace (heavy tails + diurnal arrivals)."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, jobs_per_cluster
    # diurnal arrival times by inverse-CDF of 1 + 0.6*sin(2*pi*t/day)
    u = rng.random((C, A))
    grid = np.linspace(0.0, 1.0, 1025)
    day_ms = 86_400_000.0
    intens = 1.0 + 0.6 * np.sin(2 * np.pi * grid * horizon_ms / day_ms)
    cdf = np.cumsum(intens)
    cdf = (cdf - cdf[0]) / (cdf[-1] - cdf[0])
    t = np.interp(u, cdf, grid) * horizon_ms
    # heavy-tailed sizes: lognormal cores clipped to node size
    cores = np.clip(np.round(np.exp(rng.normal(0.4, 1.0, (C, A)))), 1, max_cores)
    mem_frac = np.clip(rng.normal(0.6, 0.35, (C, A)), 0.05, 2.0)
    mem = np.clip(np.round(cores / max_cores * max_mem * mem_frac), 1, max_mem)
    # lognormal durations, median ~90 s, clipped to 1 h
    dur = np.clip(np.exp(rng.normal(np.log(90_000.0), 1.2, (C, A))), 1_000, 3_600_000)
    return _pack(t, cores, mem, dur)


def bursty_stream(n_clusters: int, bursts: int, jobs_per_burst: int,
                  interval_ms: int, window_ms: int, max_cores: int,
                  max_mem: int, max_dur_ms: int, seed: int = 0,
                  beta: float = 2.0) -> Arrivals:
    """Burst-sparse arrivals — the Borg-sparsity regime the
    event-compressed driver leaps over (ARCHITECTURE.md §time
    compression): ``bursts`` bursts per cluster, ``jobs_per_burst`` jobs
    each, burst ``b``'s jobs landing uniformly inside
    ``[b*interval_ms, b*interval_ms + window_ms)``. With
    ``max_dur_ms + window_ms`` well under ``interval_ms`` the whole
    constellation drains and idles between bursts, so the vast majority of
    ticks are provably no-ops."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, bursts * jobs_per_burst
    base = np.repeat(np.arange(bursts, dtype=np.int64) * interval_ms,
                     jobs_per_burst)  # [A]
    t = base[None, :] + rng.integers(0, window_ms, (C, A))
    cores = np.floor(rng.beta(beta, beta, (C, A)) * max_cores)
    mem = np.floor(rng.beta(beta, beta, (C, A)) * max_mem)
    dur = rng.integers(0, max_dur_ms, (C, A))
    return _pack(t, cores, mem, dur)


def from_arrays(t_ms, cores, mem, dur_ms, gpus=None) -> Arrivals:
    """Replay an externally loaded trace (e.g. parsed Borg CSV) — inputs are
    [C, A] arrays; times need not be sorted."""
    return _pack(np.asarray(t_ms), np.asarray(cores), np.asarray(mem),
                 np.asarray(dur_ms),
                 None if gpus is None else np.asarray(gpus))


def tick_arrivals_device(key, t, n_clusters: int, k_max: int, rate,
                         max_cores, max_mem, max_dur_ms, beta=2.0):
    """[jax] One tick's arrival rows drawn ON DEVICE — the environment
    mode's generative workload (envs/cluster_env.py): the same
    distribution family as ``uniform_stream`` (Beta(b,b) sizes, uniform
    durations), but sampled per tick from an explicit PRNG key instead of
    host numpy, so thousands of vmapped env instances each carry their own
    stream with zero host round-trips. Per-(tick, cluster) arrival counts
    are Binomial(k_max, rate/k_max) — the per-tick marginal of ``rate *
    n_ticks`` jobs landing uniformly over the horizon, truncated at the
    static fanout bound ``k_max``.

    Returns ``(rows [C, K, NF] i32, counts [C] i32)`` in exactly the
    TickArrivals per-tick slice shape ``Engine.step_tick`` ingests; row
    order/sentinels come from the canonical schema (ops/fields.py), the
    same one site the host pack paths derive theirs from. ``key`` must be
    a per-env stream key (simlint: env-rng); ``t`` is the tick's clock —
    it becomes the rows' ``enq_t``, so wait accounting starts at arrival
    exactly as the host-bucketed path's does."""
    import jax
    import jax.numpy as jnp

    from multi_cluster_simulator_tpu.ops import fields as F
    from multi_cluster_simulator_tpu.ops import queues as Q

    C, K = int(n_clusters), int(k_max)
    ka, kc, km, kd = jax.random.split(key, 4)
    # candidates are iid, so "count admitted, take the row prefix" draws
    # the same joint distribution as compacting the admitted rows — and
    # ingest consumes exactly the [0, count) prefix (_ingest_packed_local)
    admit = jax.random.uniform(ka, (C, K)) < (
        jnp.float32(rate) / jnp.float32(K))
    counts = jnp.sum(admit, axis=1).astype(jnp.int32)

    def beta_bb(k, shape):
        # Beta(b, b) for integer b as the b-th order statistic of 2b-1
        # uniforms (exact). jax.random.beta lowers to rejection-sampled
        # gamma while_loops, which under the env vmap cost ~25x the whole
        # tick on CPU; a sort over 3 uniforms (b=2) is pure vector ops.
        b = int(beta)
        if b != beta or b < 1:  # non-integer b: the general (slow) sampler
            return jax.random.beta(k, beta, beta, shape)
        u = jax.random.uniform(k, (*shape, 2 * b - 1))
        return jnp.sort(u, axis=-1)[..., b - 1]

    cores = jnp.floor(beta_bb(kc, (C, K)) * max_cores).astype(jnp.int32)
    mem = jnp.floor(beta_bb(km, (C, K)) * max_mem).astype(jnp.int32)
    dur = jax.random.randint(kd, (C, K), 0, max(int(max_dur_ms), 1),
                             dtype=jnp.int32)
    tt = jnp.broadcast_to(jnp.asarray(t, jnp.int32), (C, K))
    gpu = jnp.zeros((C, K), jnp.int32)
    # ids are tick-local (the generative stream has no global cursor);
    # nothing in the tick keys on id uniqueness — the borrowed-row match
    # compares (id, cores, mem, dur) and env configs run borrowing off
    ids = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :], (C, K))
    vals = {"id": ids, "cores": cores, "mem": mem, "gpu": gpu, "dur": dur,
            "enq_t": tt, "owner": jnp.full((C, K), int(Q.OWN), jnp.int32),
            "rec_wait": jnp.zeros((C, K), jnp.int32),
            "jclass": F.job_class(cores, gpu).astype(jnp.int32),
            "retries": jnp.zeros((C, K), jnp.int32)}
    rows = jnp.stack([vals[n] for n in F.QUEUE_FIELDS], axis=-1)
    return rows, counts
