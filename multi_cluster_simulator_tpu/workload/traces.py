"""Bulk / trace-style workload synthesis for the scale harness.

The reference's only workload is the live Poisson client (generator.py).
The BASELINE.json scale configs need two more shapes, generated vectorized
(one numpy call per field, no per-cluster Python loops):

- ``uniform_stream`` — N jobs per cluster with sorted-uniform arrival times:
  the load shape used by the throughput benchmarks.
- ``borg_like_stream`` — a Google-Borg-2019-shaped synthetic trace: machine
  counts per job drawn heavy-tailed (lognormal), memory correlated with
  cores, lognormal durations, and a diurnal (sinusoidal) arrival intensity.
  Real Borg trace CSVs can be replayed through ``from_arrays``.
"""

from __future__ import annotations

import numpy as np

from multi_cluster_simulator_tpu.core.state import Arrivals


def _pack(t, cores, mem, dur, gpu=None):
    C, A = t.shape
    order = np.argsort(t, axis=1, kind="stable")
    g = lambda a: np.take_along_axis(a, order, axis=1).astype(np.int32)
    return Arrivals(
        t=g(t), id=np.broadcast_to(np.arange(A, dtype=np.int32), (C, A)).copy(),
        cores=g(cores), mem=g(mem),
        gpu=np.zeros((C, A), np.int32) if gpu is None else g(gpu),
        dur=g(dur), n=np.full((C,), A, np.int32))


def uniform_stream(n_clusters: int, jobs_per_cluster: int, horizon_ms: int,
                   max_cores: int, max_mem: int, max_dur_ms: int,
                   seed: int = 0, beta: float = 2.0,
                   max_gpus: int = 0, gpu_frac: float = 0.0) -> Arrivals:
    """Sorted-uniform arrivals; Beta(b,b) sizes (the reference's job-size
    family, client.go:87-99); uniform durations. With ``max_gpus > 0``, a
    ``gpu_frac`` fraction of jobs additionally request 1..max_gpus
    accelerators (the 3-dim-resource workload of BASELINE config 4)."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, jobs_per_cluster
    t = rng.integers(0, horizon_ms, (C, A))
    cores = np.floor(rng.beta(beta, beta, (C, A)) * max_cores)
    mem = np.floor(rng.beta(beta, beta, (C, A)) * max_mem)
    dur = rng.integers(0, max_dur_ms, (C, A))
    gpu = None
    if max_gpus > 0:
        gpu = np.where(rng.random((C, A)) < gpu_frac,
                       rng.integers(1, max_gpus + 1, (C, A)), 0)
    return _pack(t, cores, mem, dur, gpu)


def borg_like_stream(n_clusters: int, jobs_per_cluster: int, horizon_ms: int,
                     max_cores: int, max_mem: int, seed: int = 0) -> Arrivals:
    """Borg-2019-shaped synthetic trace (heavy tails + diurnal arrivals)."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, jobs_per_cluster
    # diurnal arrival times by inverse-CDF of 1 + 0.6*sin(2*pi*t/day)
    u = rng.random((C, A))
    grid = np.linspace(0.0, 1.0, 1025)
    day_ms = 86_400_000.0
    intens = 1.0 + 0.6 * np.sin(2 * np.pi * grid * horizon_ms / day_ms)
    cdf = np.cumsum(intens)
    cdf = (cdf - cdf[0]) / (cdf[-1] - cdf[0])
    t = np.interp(u, cdf, grid) * horizon_ms
    # heavy-tailed sizes: lognormal cores clipped to node size
    cores = np.clip(np.round(np.exp(rng.normal(0.4, 1.0, (C, A)))), 1, max_cores)
    mem_frac = np.clip(rng.normal(0.6, 0.35, (C, A)), 0.05, 2.0)
    mem = np.clip(np.round(cores / max_cores * max_mem * mem_frac), 1, max_mem)
    # lognormal durations, median ~90 s, clipped to 1 h
    dur = np.clip(np.exp(rng.normal(np.log(90_000.0), 1.2, (C, A))), 1_000, 3_600_000)
    return _pack(t, cores, mem, dur)


def bursty_stream(n_clusters: int, bursts: int, jobs_per_burst: int,
                  interval_ms: int, window_ms: int, max_cores: int,
                  max_mem: int, max_dur_ms: int, seed: int = 0,
                  beta: float = 2.0) -> Arrivals:
    """Burst-sparse arrivals — the Borg-sparsity regime the
    event-compressed driver leaps over (ARCHITECTURE.md §time
    compression): ``bursts`` bursts per cluster, ``jobs_per_burst`` jobs
    each, burst ``b``'s jobs landing uniformly inside
    ``[b*interval_ms, b*interval_ms + window_ms)``. With
    ``max_dur_ms + window_ms`` well under ``interval_ms`` the whole
    constellation drains and idles between bursts, so the vast majority of
    ticks are provably no-ops."""
    # simlint: ignore[det-wallclock] -- explicitly seeded: the same seed
    # reproduces the same stream bit-for-bit
    rng = np.random.Generator(np.random.PCG64(seed))
    C, A = n_clusters, bursts * jobs_per_burst
    base = np.repeat(np.arange(bursts, dtype=np.int64) * interval_ms,
                     jobs_per_burst)  # [A]
    t = base[None, :] + rng.integers(0, window_ms, (C, A))
    cores = np.floor(rng.beta(beta, beta, (C, A)) * max_cores)
    mem = np.floor(rng.beta(beta, beta, (C, A)) * max_mem)
    dur = rng.integers(0, max_dur_ms, (C, A))
    return _pack(t, cores, mem, dur)


def from_arrays(t_ms, cores, mem, dur_ms, gpus=None) -> Arrivals:
    """Replay an externally loaded trace (e.g. parsed Borg CSV) — inputs are
    [C, A] arrays; times need not be sorted."""
    return _pack(np.asarray(t_ms), np.asarray(cores), np.asarray(mem),
                 np.asarray(dur_ms),
                 None if gpus is None else np.asarray(gpus))
