"""Workload generation — the reference's ``pkg/client`` as data, not a process.

The Go client draws job sizes from Beta(2,2) scaled to the biggest node,
durations from Uniform{0..599} s, and arrival times from either a per-minute
Poisson(λ=10) batch process or Weibull(λ=10, k=3) inter-arrivals
(pkg/client/client.go:85-147), then POSTs each job over HTTP. Here the whole
stream is pre-generated into a time-sorted ``Arrivals`` tensor with explicit
seeding — deterministic replay by construction (the reference seeds only the
Poisson source, client.go:109).

Reproduced quirks (documented, not accidental):
- Go computes ``time_between_jobs = 60 / jobs`` with *integer* division
  (client.go:116), so a minute's n jobs land on a floor(60/n)-second grid
  starting at the minute boundary. A Poisson draw of 0 would crash the Go
  client (division by zero); we emit no jobs for such a minute.
- ``Duration(dist.Rand()) * time.Second`` truncates the Weibull draw toward
  zero before scaling (client.go:143); we floor likewise.
"""

from __future__ import annotations

import numpy as np

from multi_cluster_simulator_tpu.config import WorkloadConfig
from multi_cluster_simulator_tpu.core.state import Arrivals


def generate_arrivals(
    cfg: WorkloadConfig,
    n_clusters: int,
    max_arrivals: int,
    horizon_ms: int,
    max_cores: int,
    max_mem: int,
    seed: int | None = None,
) -> Arrivals:
    """Generate per-cluster arrival streams as numpy arrays (host-side input
    prep; the engine consumes the result on device).

    Each cluster gets an independent substream (seed + cluster index), the
    analogue of one workload client per scheduler (cmd/client/main.go).
    Job ids are per-cluster serials starting at 0 (client.go:91-100).
    """
    seed = cfg.seed if seed is None else seed
    C, A = n_clusters, max_arrivals
    out_t = np.zeros((C, A), np.int32)
    out_id = np.full((C, A), -1, np.int32)
    out_cores = np.zeros((C, A), np.int32)
    out_mem = np.zeros((C, A), np.int32)
    out_dur = np.zeros((C, A), np.int32)
    out_n = np.zeros((C,), np.int32)

    for c in range(C):
        rng = np.random.Generator(np.random.PCG64([seed, c]))  # simlint: ignore[det-wallclock] -- explicitly seeded per-cluster substream: replay-deterministic by construction
        times_ms: list[int] = []
        if cfg.arrival == "poisson":
            minute = 0
            while minute * 60_000 < horizon_ms and len(times_ms) < A:
                n = int(rng.poisson(cfg.poisson_lambda_per_min))
                if n > 0:
                    spacing_s = 60 // n  # Go integer division, client.go:116
                    for i in range(n):
                        t = minute * 60_000 + i * spacing_s * 1_000
                        if t < horizon_ms and len(times_ms) < A:
                            times_ms.append(t)
                minute += 1
        elif cfg.arrival == "weibull":
            t = 0.0
            while t < horizon_ms and len(times_ms) < A:
                gap_s = int(rng.weibull(cfg.weibull_k) * cfg.weibull_lambda_s)
                t += gap_s * 1_000
                if t < horizon_ms:
                    times_ms.append(int(t))
        else:
            raise ValueError(f"unknown arrival process {cfg.arrival!r}")

        n = len(times_ms)
        out_n[c] = n
        out_t[c, :n] = np.sort(np.asarray(times_ms, np.int64)).astype(np.int32)
        out_id[c, :n] = np.arange(n, dtype=np.int32)
        # sizes ~ Beta(2,2) x max node, floored (client.go:97-99)
        out_cores[c, :n] = np.floor(
            rng.beta(cfg.beta_alpha, cfg.beta_beta, n) * max_cores).astype(np.int32)
        out_mem[c, :n] = np.floor(
            rng.beta(cfg.beta_alpha, cfg.beta_beta, n) * max_mem).astype(np.int32)
        out_dur[c, :n] = (rng.integers(0, cfg.max_duration_s, n) * 1_000).astype(np.int32)

    return Arrivals(t=out_t, id=out_id, cores=out_cores, mem=out_mem,
                    gpu=np.zeros((C, A), np.int32), dur=out_dur, n=out_n)


def silence_clusters(arrivals: Arrivals, idx) -> Arrivals:
    """Zero out the named clusters' arrival counts (numpy fancy index or
    slice) — the standard way tests and benches force a cross-cluster
    mechanism to fire: starve some clusters, idle the rest so they can
    only lend/sell."""
    n = np.asarray(arrivals.n).copy()
    n[idx] = 0
    return arrivals.replace(n=n)
