from multi_cluster_simulator_tpu.workload.generator import generate_arrivals

__all__ = ["generate_arrivals"]
