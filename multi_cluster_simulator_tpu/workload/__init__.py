from multi_cluster_simulator_tpu.workload.generator import (
    generate_arrivals, silence_clusters,
)

__all__ = ["generate_arrivals", "silence_clusters"]
