"""Device-mesh construction for the cluster axis.

One mesh axis ("clusters") — the simulator's scale axis is clusters, the
analogue of the reference running one scheduler process per cluster
(cmd/scheduler). Sharding the cluster axis places each device's cluster
shard entirely locally; the only ICI traffic is the per-tick borrow/trade
decision exchange (parallel/exchange.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: Optional[int] = None, axis: str = "clusters",
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def nearest_divisible(c: int, n: int) -> tuple[int, int]:
    """The two cluster counts bracketing ``c`` that divide evenly over an
    ``n``-way mesh: ``(floor, ceil)`` multiples of ``n`` (floor can be 0).
    Shared by ShardedEngine.shard_inputs' failure message and the
    weak-scaling driver's sentinel auto-pad (tools/weak_scaling.py pads up
    to the ceil count)."""
    lo = (c // n) * n
    hi = lo if lo == c else lo + n
    return lo, hi
