from multi_cluster_simulator_tpu.parallel.exchange import Exchange, LocalExchange, MeshExchange
from multi_cluster_simulator_tpu.parallel.mesh import make_mesh
from multi_cluster_simulator_tpu.parallel.sharded_engine import ShardedEngine

__all__ = ["Exchange", "LocalExchange", "MeshExchange", "make_mesh", "ShardedEngine"]
