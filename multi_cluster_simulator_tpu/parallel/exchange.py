"""The cross-cluster communication boundary.

The reference's cross-cluster fabric is goroutine fan-out over HTTP/gRPC with
first-response-wins races (BorrowResources, pkg/scheduler/server.go:183-243;
Trade, pkg/trader/trader.go:211-258). In the TPU engine every cross-cluster
decision is already a batched array op over the cluster axis; this module
abstracts the three collective primitives those ops need so the same engine
code runs single-device (identity ops) or sharded over a device mesh
(XLA collectives over ICI):

- ``gather``     — see every cluster's request row   (lax.all_gather)
- ``allmin``     — global minimum across shards       (lax.pmin)
- ``allmax``     — global maximum across shards       (lax.pmax)
- ``allsum``     — deterministic cross-shard sum      (all_gather + fixed-order sum)
- ``offset``     — my shard's global cluster offset   (lax.axis_index)

This is the idiomatic-TPU replacement for NCCL/MPI-style messaging: the
borrow broadcast becomes an all-gather of feasibility bits and the market's
offer collection a min-reduction over seller indices (SURVEY.md §2.9).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Exchange:
    """Interface; see LocalExchange / MeshExchange."""

    def gather(self, x):
        raise NotImplementedError

    def allmin(self, x):
        raise NotImplementedError

    def allmax(self, x):
        raise NotImplementedError

    def allsum(self, x):
        raise NotImplementedError

    def offset(self, c_local: int):
        raise NotImplementedError

    def alland(self, x):
        """Cross-shard logical AND of a bool — ``allmin`` over the 0/1 form.
        The event-compressed driver uses it for the quiescence vote: every
        shard must see a fixed point before any shard may leap."""
        return self.allmin(x.astype(jnp.int32)) > 0

    def global_index(self, c_local: int):
        """Global cluster indices of this shard's local clusters."""
        return self.offset(c_local) + jnp.arange(c_local, dtype=jnp.int32)


class LocalExchange(Exchange):
    """Single-device: the cluster axis is whole; collectives are identities."""

    def gather(self, x):
        return x

    def allmin(self, x):
        return x

    def allmax(self, x):
        return x

    def allsum(self, x):
        return x

    def offset(self, c_local: int):
        return jnp.int32(0)


class MeshExchange(Exchange):
    """Inside ``shard_map`` over a mesh axis: per-shard arrays carry
    ``C_local = C_total / n_shards`` clusters; decisions that need every
    cluster's row ride ICI collectives."""

    def __init__(self, axis_name: str = "clusters"):
        self.axis_name = axis_name

    def gather(self, x):
        return jax.lax.all_gather(x, self.axis_name, axis=0, tiled=True)

    def allmin(self, x):
        return jax.lax.pmin(x, self.axis_name)

    def allmax(self, x):
        return jax.lax.pmax(x, self.axis_name)

    def allsum(self, x):
        """Cross-shard float sum with a deterministic combining order:
        all_gather the per-shard partials, reduce the stacked [n_shards, ...]
        axis in one fixed-order jnp.sum — psum's device combining tree is
        backend-chosen, which would make the result topology-dependent in an
        uncontrolled way."""
        parts = jax.lax.all_gather(x, self.axis_name, axis=0, tiled=False)
        return jnp.sum(parts, axis=0)

    def offset(self, c_local: int):
        return (jax.lax.axis_index(self.axis_name) * c_local).astype(jnp.int32)
