"""Multi-device engine: the cluster axis sharded over a jax Mesh.

The reference scales out by launching one scheduler+trader OS process per
cluster and wiring them over HTTP/gRPC (cmd/, SURVEY.md §1). Here scale-out
is a sharding annotation: every per-cluster tensor is split over the mesh's
"clusters" axis, the per-cluster phases run locally on each device, and the
three cross-cluster decisions exchange compact rows over ICI
(parallel/exchange.py). The same Engine code runs in both regimes — shard_map
just swaps the exchange implementation.
"""

from __future__ import annotations

import inspect

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# jax < 0.5 ships shard_map under jax.experimental; newer jax promotes it
# to jax.shard_map. The disable-the-replication-check kwarg was also
# renamed (check_rep -> check_vma) on a different schedule than the
# promotion, so pick the spelling from the chosen function's signature.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 CI images
    from jax.experimental.shard_map import shard_map as _shard_map
try:
    _SHARD_MAP_KW = (
        {"check_vma": False}
        if "check_vma" in inspect.signature(_shard_map).parameters
        else {"check_rep": False})
except (TypeError, ValueError):  # pragma: no cover - unintrospectable wrap
    _SHARD_MAP_KW = {}

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.state import (
    Arrivals, SimState, TickArrivals,
)
from multi_cluster_simulator_tpu.parallel.exchange import MeshExchange


def _state_specs(axis: str):
    """Pytree prefix: every per-cluster field sharded on its leading axis,
    the scalar clock replicated. The fault plane's leaves (faults/) are
    all per-cluster by construction — including the interval tables and
    the per-cluster PRNG keys — so churn shards with the state and needs
    zero new collectives."""
    shard, rep = P(axis), P()
    return SimState(
        t=rep, node_cap=shard, node_free=shard, node_active=shard,
        node_expire=shard, node_type=shard, l0=shard, l1=shard, ready=shard,
        wait=shard, lent=shard, borrowed=shard, run=shard, arr_ptr=shard,
        wait_total=shard, wait_jobs=shard, jobs_in_queue=shard,
        placed_total=shard, drops=shard, trader=shard, trace=shard,
        faults=shard)


def _arr_specs(axis: str):
    shard = P(axis)
    return Arrivals(t=shard, id=shard, cores=shard, mem=shard, gpu=shard,
                    dur=shard, n=shard)


def _metrics_specs(axis: str):
    """MetricsBuffer placement (obs/device.py): per-cluster leaves shard
    with the state; the shard-local partials (histogram + ring value rows)
    shard on their leading size-1-per-shard axis so the buffer round-trips
    chunk calls without double counting; ticks/ring_t/leap_hist are
    replicated (identical on every shard by construction)."""
    from multi_cluster_simulator_tpu.obs.device import MetricsBuffer
    shard, rep = P(axis), P()
    return MetricsBuffer(
        ticks=rep, placed=shard, arrived=shard, borrows=shard,
        wait_accrued=shard, ovf=shard, depth_sum=shard, depth_max=shard,
        kills=shard, requeues=shard, fail_drops=shard, node_down_ms=shard,
        depth_hist=P(axis, None), ring_placed=P(axis, None),
        ring_depth=P(axis, None), ring_t=rep, leap_hist=rep)


def _tick_arr_specs(axis: str):
    """TickArrivals shard on the cluster axis (axis 1; axis 0 is ticks)."""
    return TickArrivals(rows=P(None, axis), counts=P(None, axis))


class ShardedEngine:
    """Engine whose cluster axis is sharded over ``mesh``'s first axis.

    The number of clusters must be divisible by the mesh size. Use
    ``shard_inputs`` to place host-built state/arrivals onto the mesh.
    """

    def __init__(self, cfg: SimConfig, mesh: Mesh, axis: str = "clusters",
                 policies=None):
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.engine = Engine(cfg, ex=MeshExchange(axis), policies=policies)

    def shard_inputs(self, state: SimState, arrivals: Arrivals, place=None):
        """Place state/arrivals onto the mesh. ``place(leaf, sharding)``
        overrides how each leaf lands on devices — the default device_put
        works single-process; parallel.multihost passes the
        make_array_from_callback variant for multi-controller meshes."""
        n = self.mesh.shape[self.axis]
        C = state.arr_ptr.shape[0]
        if C % n != 0:
            from multi_cluster_simulator_tpu.parallel.mesh import (
                nearest_divisible,
            )
            lo, hi = nearest_divisible(C, n)
            valid = f"{hi}" if lo == 0 else f"{lo} or {hi}"
            raise ValueError(
                f"clusters ({C}) must divide by mesh size ({n}); nearest "
                f"valid cluster counts: {valid} (tools/weak_scaling.py "
                f"auto-pads to {hi} with inert always-full sentinel "
                "clusters)")
        return (self.shard_state(state, place),
                self.shard_arrivals(arrivals, place))

    def shard_state(self, state: SimState, place=None):
        return _device_put_tree(state, _state_specs(self.axis), self.mesh,
                                place)

    def shard_arrivals(self, arrivals, place=None):
        """Place an Arrivals stream or TickArrivals bucket onto the mesh."""
        specs = (_tick_arr_specs(self.axis)
                 if isinstance(arrivals, TickArrivals)
                 else _arr_specs(self.axis))
        return _device_put_tree(arrivals, specs, self.mesh, place)

    def shard_metrics(self, mbuf):
        """Place a host-built MetricsBuffer (obs.metrics_init) onto the
        mesh with the carry placement ``run_fn(with_metrics=True)``
        expects. The shard-local partial leaves (leading axis 1 on one
        device) expand to one row per shard — row 0 keeps the incoming
        partial, new rows are zero, so totals are preserved whether the
        buffer is fresh or mid-run."""
        import numpy as np
        n = self.mesh.shape[self.axis]

        def widen(leaf):
            a = np.asarray(leaf)
            pad = np.zeros((n - a.shape[0],) + a.shape[1:], a.dtype)
            return np.concatenate([a, pad], axis=0)

        mbuf = mbuf.replace(depth_hist=widen(mbuf.depth_hist),
                            ring_placed=widen(mbuf.ring_placed),
                            ring_depth=widen(mbuf.ring_depth))
        return _device_put_tree(mbuf, _metrics_specs(self.axis), self.mesh)

    def collect_metrics(self, mbuf):
        """The global view of a sharded MetricsBuffer carry: the
        shard-local partials reduce through the sanctioned exchange
        (``obs.reduce_metrics`` -> ``ex.allsum``) and the per-cluster
        leaves gather — ONE device program per harvest, one transfer of
        the reduced buffer (the obs plane's chunk-boundary contract under
        sharding)."""
        from multi_cluster_simulator_tpu.obs.device import reduce_metrics
        ex = self.engine.ex
        _PER_CLUSTER = ("placed", "arrived", "borrows", "wait_accrued",
                        "ovf", "depth_sum", "depth_max", "kills",
                        "requeues", "fail_drops", "node_down_ms")

        def body(mb):
            mb = reduce_metrics(mb, ex)  # partials -> replicated allsums
            return mb.replace(**{k: ex.gather(getattr(mb, k))
                                 for k in _PER_CLUSTER})

        mapped = _shard_map(body, mesh=self.mesh,
                            in_specs=(_metrics_specs(self.axis),),
                            out_specs=P(), **_SHARD_MAP_KW)
        return jax.jit(mapped)(mbuf)

    def run_fn(self, n_ticks: int, tick_indexed: bool = False,
               donate: bool = False, time_compress: bool = False,
               with_params: bool = False, with_metrics: bool = False):
        """A jitted (state, arrivals) -> state advancing n_ticks under
        shard_map (``(state, MetricSample)`` when cfg.record_metrics: the
        [T, C] series stays cluster-sharded on its second axis).
        ``tick_indexed=True`` takes TickArrivals instead of a stream.
        ``donate=True`` donates the sharded input state's buffers so the
        multi-GB constellation state is updated in place per shard instead
        of double-buffered in HBM (same contract as Engine.run_jit: the
        caller's state arrays are invalid after the call).
        ``time_compress=True`` (requires ``tick_indexed``) runs the
        event-compressed driver instead of the dense scan: the per-shard
        quiescence votes and leap targets ride the mesh exchange
        (``alland``/``allmin``) so every shard executes the same ticks,
        and a replicated ``LeapStats`` is appended to the outputs.
        ``with_params=True`` adds a third argument — a replicated
        ``PolicyParams`` pytree selecting the policy per call (the
        policy-as-data axis; every shard must receive the same cell).
        ``with_metrics=True`` adds a trailing MetricsBuffer argument
        (place with ``shard_metrics``) and appends the updated buffer
        LAST to the outputs — a shard-local CARRY; reduce a harvest view
        with ``collect_metrics``."""
        eng = self.engine
        if time_compress and not tick_indexed:
            raise ValueError("time_compress requires tick_indexed "
                             "(pre-bucketed TickArrivals)")

        def body(state, arrivals, *rest):
            params = rest[0] if with_params else None
            mbuf = rest[-1] if with_metrics else None
            if time_compress:
                return eng.run_compressed(state, arrivals, n_ticks,
                                          params=params, mbuf=mbuf)
            return eng.run(state, arrivals, n_ticks, params=params,
                           mbuf=mbuf)

        out_specs = _state_specs(self.axis)
        if self.cfg.record_metrics:
            from multi_cluster_simulator_tpu.core.state import MetricSample
            out_specs = (out_specs, MetricSample(
                t=P(), jobs_in_queue=P(None, self.axis),
                avg_wait_ms=P(None, self.axis)))
        if time_compress:
            from multi_cluster_simulator_tpu.core.state import LeapStats
            stats_spec = LeapStats(ticks_executed=P(), leaps=P())
            if self.cfg.record_metrics:
                out_specs = out_specs + (stats_spec,)
            else:
                out_specs = (out_specs, stats_spec)
        if with_metrics:
            # the buffer is a CARRY: it comes back shard-local (the same
            # placement it went in with) and only collect_metrics reduces
            mspec = _metrics_specs(self.axis)
            out_specs = (out_specs + (mspec,)
                         if isinstance(out_specs, tuple)
                         else (out_specs, mspec))
        arr_specs = (_tick_arr_specs(self.axis) if tick_indexed
                     else _arr_specs(self.axis))
        in_specs = (_state_specs(self.axis), arr_specs)
        if with_params:
            in_specs = in_specs + (P(),)  # params replicated on every shard
        if with_metrics:
            in_specs = in_specs + (_metrics_specs(self.axis),)
        mapped = _shard_map(
            body, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            **_SHARD_MAP_KW)
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def _device_put_tree(tree, spec_prefix, mesh, place=None):
    """Place each array leaf with the sharding from a pytree-prefix of
    PartitionSpecs (a prefix node applies to the whole subtree beneath it);
    ``place(leaf, sharding)`` defaults to jax.device_put."""
    if place is None:
        place = jax.device_put
    flat_specs = _expand_prefix(spec_prefix, tree)
    leaves, treedef = jax.tree.flatten(tree)
    out = [place(x, NamedSharding(mesh, s))
           for x, s in zip(leaves, flat_specs)]
    return jax.tree.unflatten(treedef, out)


def _expand_prefix(prefix, tree):
    """Expand a pytree prefix of PartitionSpecs to one spec per leaf."""
    out = []

    def rec(p, t):
        if isinstance(p, P):
            out.extend([p] * len(jax.tree.leaves(t)))
        else:
            pk = jax.tree.structure(p, is_leaf=lambda x: isinstance(x, P))
            ps = jax.tree.leaves(p, is_leaf=lambda x: isinstance(x, P))
            ts = pk.flatten_up_to(t)
            for pp, tt in zip(ps, ts):
                rec(pp, tt)

    rec(prefix, tree)
    return out
