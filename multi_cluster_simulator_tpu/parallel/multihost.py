"""Multi-host (DCN) scale-out: the cluster axis sharded across processes.

The reference spans hosts by launching scheduler/trader OS processes
anywhere and wiring them over HTTP/gRPC through the registry (SURVEY.md
§2.9). The TPU-native equivalent is multi-controller JAX: every host runs
this same program, ``jax.distributed.initialize`` forms the global device
set (the registry-analogue coordinator), and the ONE ShardedEngine code
path then runs with its mesh spanning hosts — per-cluster phases stay
host-local, the three cross-cluster exchanges (borrow match, trade round,
return delivery) ride the same collectives, now over ICI within a host and
DCN between hosts. Nothing in engine/ or exchange.py changes: a multi-host
mesh is just a bigger mesh.

The only genuinely multi-host-specific piece is input placement: a global
host-built array must be distributed shard-by-shard (each process owns only
its addressable devices), which ``shard_inputs_global`` does via
``jax.make_array_from_callback``. Every process builds the same global
inputs deterministically (seeded workloads make this free), and each
callback hands JAX the slice it asks for.

Validated end-to-end by tests/test_multihost.py: two OS processes x 4
virtual CPU devices form an 8-device global mesh, run the sharded engine,
and the gathered per-cluster results are bit-identical to a single-process
run of the same config.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

# NOTE: jax.distributed.initialize must run before anything initializes
# the XLA backend — and importing this package does (module-level jnp
# constants). A multi-process entrypoint must therefore call
# ``jax.distributed.initialize(coordinator_address=..., num_processes=...,
# process_id=...)`` after a bare ``import jax`` and only then import
# multi_cluster_simulator_tpu (see tests/_multihost_worker.py). The
# coordinator plays the role the registry plays for the live service
# constellation: the well-known address every process meets at.


def global_mesh(axis: str = "clusters") -> Mesh:
    """A mesh over the GLOBAL device set (all processes' devices)."""
    from multi_cluster_simulator_tpu.parallel.mesh import make_mesh

    return make_mesh(axis=axis)


def _make_global(x, sharding: NamedSharding):
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding,
                                        lambda idx: x[idx])


def shard_inputs_global(sh, state, arrivals):
    """Multi-process form of ShardedEngine.shard_inputs: every process
    passes the same deterministically built global state/arrivals; each
    contributes the shards its devices own. One placement walk exists —
    shard_inputs' — this just swaps device_put for the per-shard
    callback form a multi-controller mesh requires."""
    return sh.shard_inputs(state, arrivals, place=_make_global)


def gather_to_host(x) -> np.ndarray:
    """Fetch a (possibly cross-process) sharded array fully to every host —
    the readback half of the DCN story (result collection)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
