"""The policy zoo: scheduling policies as data (ARCHITECTURE.md §policy zoo).

``PolicySet`` + ``PolicyParams`` turn the scheduler repertoire into one
compiled program whose active policy is a traced index and whose knobs are
pytree leaves — the interface the tournament driver (tools/tournament.py),
the RL-environment mode, and the serving tier all plug into. Kernels live
in ``policies.kernels``; registration in ``policies.base``.
"""

from multi_cluster_simulator_tpu.policies.base import (
    KINDS, REGISTRY, PolicyParams, PolicySet, PolicySpec, default_params,
    params_digest, register, variant,
)

__all__ = [
    "KINDS", "REGISTRY", "PolicyParams", "PolicySet", "PolicySpec",
    "default_params", "params_digest", "register", "variant",
]
