"""Policy-as-data: the parameter pytree, the registry, and the dispatcher.

The seed engine chose its scheduling pass with Python-level ``cfg.policy``
branches — every policy variant was a distinct compiled program, so an A/B
across the repertoire paid one trace + one compile + one H2D pipeline per
variant (tools/market_ab.py was the template). Here a policy is DATA:

- ``PolicyParams`` — one pytree of parameter leaves shared by every kernel
  (a traced selector index plus each family's knobs). Leaves, not config:
  a vmapped tournament batches them over the (policy, seed) axis and a
  single compiled program evaluates the whole repertoire
  (tools/tournament.py).
- ``PolicySpec`` / ``register`` — the registered table: name -> kernel
  KIND (the compute body in policies/kernels.py), ingest target, and
  default parameter overrides. Registration is additive; the eight
  built-ins below cover the reference repertoire plus the Gavel- and
  Tesserae-style zoo members.
- ``PolicySet`` — the STATIC tuple of registered names compiled into one
  program. Which member runs is the TRACED ``params.idx``: members of one
  kernel kind share code (their differences are parameter leaves — free to
  sweep), distinct kinds become branches of one ``lax.switch``. A
  singleton set (every pre-tournament entry point: ``Engine(cfg)``)
  short-circuits to a direct call — the exact seed code path, pinned
  bit-identical by tests/test_policies.py.

The RL-environment (ROADMAP item 2) and serving (item 4) PRs plug in here:
a learned scheduler is one more registered kind whose params happen to be
network outputs.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core.state import STATE_AXES, SimState
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.policies import kernels as K


@struct.dataclass
class PolicyParams:
    """Per-policy parameter leaves — the data in policy-as-data.

    One flat schema shared by every kernel family (a kernel reads the
    leaves it understands and ignores the rest), so a batched sweep can
    stack heterogeneous policies along one axis. All leaves are traced;
    none may steer Python control flow (simlint: policy-kernel)."""

    idx: jax.Array  # [] i32 — which PolicySet member this cell runs
    max_wait_ms: jax.Array  # [] i32 — DELAY Level0->Level1 promotion
    ffd_mem_first: jax.Array  # [] i32 — FFD sort tie-break (0: cores-first)
    gavel_tput: jax.Array  # [N_JOB_CLASSES, N_DEVICE_TYPES] f32 throughput
    tess_w: jax.Array  # [3] f32 — tesserae resource weights (cores/mem/gpu)
    rl_scores: jax.Array  # [N_JOB_CLASSES, N_DEVICE_TYPES] f32 — the RL
    #   action port (envs/): per-env NETWORK OUTPUTS scoring node device
    #   types per job class, fed through the same scored sweep as gavel.
    #   The zero default makes every score equal, which degenerates to
    #   first-fit (ops/placement.best_scored_fit ties -> lowest index).
    # -- market solver hyperparameters (market/trader.py, market/cvx.py):
    # the trader's pricing backends read these so a tournament sweeps the
    # solvers alongside the scheduling policies in the same compiled
    # program. Iteration counts are the ACTIVE counts, masked within the
    # static scan length cfg.trader.*_iters compiles (values above the
    # static bound clamp to it — the trip count is shape, not data).
    mkt_sink_iters: jax.Array  # [] i32 — active Sinkhorn iterations
    mkt_sink_eps: jax.Array  # [] f32 — entropic temperature
    mkt_iters: jax.Array  # [] i32 — active cvx dual-ascent iterations
    mkt_step: jax.Array  # [] f32 — cvx primal sharpness (1/delta)
    mkt_rho: jax.Array  # [] f32 — cvx price step per iteration
    mkt_smooth: jax.Array  # [] f32 — cvx price carry-over across rounds


# Default Gavel throughput matrix [job class, device type]: gpu-class work
# (classes 2-3) runs ~3x faster on accelerator nodes (type 1) and pays a
# penalty on standard ones; cpu-class work is indifferent. Types 2-3 are
# spec-defined and default to standard throughput.
_DEFAULT_GAVEL_TPUT = (
    (1.0, 1.0, 1.0, 1.0),
    (1.0, 1.0, 1.0, 1.0),
    (0.5, 3.0, 1.0, 1.0),
    (0.5, 3.0, 1.0, 1.0),
)
# Tesserae alignment weights: mem is O(1000x) cores in magnitude — weigh it
# down so neither axis dominates the demand·free dot product by units alone.
_DEFAULT_TESS_W = (1.0, 1e-3, 1.0)


@dataclasses.dataclass(frozen=True)
class PolicySpec:
    """One registered policy: a kernel KIND plus parameter overrides.

    ``kind`` names the compute body (policies/kernels.py); ``to_delay``
    picks the arrival ingest target (Level0 for the queue-sweep families,
    ReadyQueue for FIFO — the engine's phase-3 split). ``overrides`` is a
    hashable tuple of (PolicyParams leaf name, value) pairs applied over
    the config-derived defaults — what makes two same-kind variants
    different policies."""

    name: str
    kind: str  # "fifo" | "delay" | "ffd" | "gavel" | "tesserae" | "rl"
    to_delay: bool
    overrides: tuple = ()


KINDS = ("fifo", "delay", "ffd", "gavel", "tesserae", "rl")

REGISTRY: dict[str, PolicySpec] = {}


def register(spec: PolicySpec) -> PolicySpec:
    """Add a policy to the registered table (idempotent re-registration of
    an identical spec is allowed; changing an existing name is an error —
    recorded digests would silently stop being joinable)."""
    if spec.kind not in KINDS:
        raise ValueError(f"unknown policy kind {spec.kind!r}; one of {KINDS}")
    prev = REGISTRY.get(spec.name)
    if prev is not None and prev != spec:
        raise ValueError(f"policy {spec.name!r} already registered as {prev}")
    REGISTRY[spec.name] = spec
    return spec


def variant(name: str, base: str, **overrides) -> PolicySpec:
    """Register a parameter variant of an existing policy: same kernel
    kind, different parameter leaves — the free axis of a tournament."""
    b = REGISTRY[base]
    ov = dict(b.overrides)
    ov.update(overrides)
    return register(PolicySpec(name=name, kind=b.kind, to_delay=b.to_delay,
                               overrides=tuple(sorted(ov.items()))))


# The built-in zoo: the reference repertoire, the heterogeneity/packing
# members, and enough parameter variants for an 8-wide tournament out of
# the box. Names are the provenance key recorded in every bench detail.
register(PolicySpec("fifo", kind="fifo", to_delay=False))
register(PolicySpec("delay", kind="delay", to_delay=True))
register(PolicySpec("ffd", kind="ffd", to_delay=True))
register(PolicySpec("gavel", kind="gavel", to_delay=True))
register(PolicySpec("tesserae", kind="tesserae", to_delay=True))
# The RL action port (ROADMAP item 2, envs/): a learned scheduler is this
# one registered kind — its ``rl_scores`` leaf is a per-env network output
# the environment's step substitutes per action (envs/cluster_env.py).
register(PolicySpec("rl", kind="rl", to_delay=True))
variant("delay-eager", "delay", max_wait_ms=2_000)
variant("delay-patient", "delay", max_wait_ms=30_000)
variant("ffd-memfirst", "ffd", ffd_mem_first=1)
# The convex market kernel's sweep axis (market/cvx.py): same scheduling
# kernel, different pricing-solver leaves — under a trader-enabled cvx
# config these are distinct market policies a tournament runs in one
# compiled program (the static scan length stays cfg.trader.cvx_iters;
# the leaves move the active count / steps within it).
variant("delay-cvx-fast", "delay", mkt_iters=64)
variant("delay-cvx-tight", "delay", mkt_rho=1.5)
variant("delay-cvx-smooth", "delay", mkt_smooth=0.5)


def default_params(cfg: SimConfig, spec: PolicySpec, idx: int = 0) -> PolicyParams:
    """The spec's parameter pytree: config-derived defaults + the spec's
    overrides, as concrete device-committable arrays. With no overrides
    this reproduces the seed ``cfg.*`` constants exactly (the dispatch
    bit-equality contract)."""
    vals = {
        "max_wait_ms": np.int32(cfg.max_wait_ms),
        "ffd_mem_first": np.int32(0),
        "gavel_tput": np.asarray(_DEFAULT_GAVEL_TPUT, np.float32),
        "tess_w": np.asarray(_DEFAULT_TESS_W, np.float32),
        "rl_scores": np.zeros(
            (F.N_JOB_CLASSES, F.N_DEVICE_TYPES), np.float32),
        "mkt_sink_iters": np.int32(cfg.trader.sinkhorn_iters),
        "mkt_sink_eps": np.float32(cfg.trader.sinkhorn_eps),
        "mkt_iters": np.int32(cfg.trader.cvx_iters),
        "mkt_step": np.float32(cfg.trader.cvx_step),
        "mkt_rho": np.float32(cfg.trader.cvx_rho),
        "mkt_smooth": np.float32(cfg.trader.cvx_smooth),
    }
    for name, val in spec.overrides:
        if name not in vals:
            raise ValueError(f"{spec.name}: unknown param override {name!r}")
        vals[name] = np.asarray(val, vals[name].dtype)
    return PolicyParams(idx=jnp.int32(idx),
                        max_wait_ms=jnp.asarray(vals["max_wait_ms"]),
                        ffd_mem_first=jnp.asarray(vals["ffd_mem_first"]),
                        gavel_tput=jnp.asarray(vals["gavel_tput"]),
                        tess_w=jnp.asarray(vals["tess_w"]),
                        rl_scores=jnp.asarray(vals["rl_scores"]),
                        mkt_sink_iters=jnp.asarray(vals["mkt_sink_iters"]),
                        mkt_sink_eps=jnp.asarray(vals["mkt_sink_eps"]),
                        mkt_iters=jnp.asarray(vals["mkt_iters"]),
                        mkt_step=jnp.asarray(vals["mkt_step"]),
                        mkt_rho=jnp.asarray(vals["mkt_rho"]),
                        mkt_smooth=jnp.asarray(vals["mkt_smooth"]))


def params_digest(params: PolicyParams) -> str:
    """Provenance digest of concrete parameter leaves: bench/tournament
    rows carry (policy name, digest) so results are joinable across
    BENCH_*.json rounds even as defaults evolve. Host-side only."""
    h = hashlib.sha1()
    for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(params)[0],
            key=lambda kv: str(kv[0])):
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()[:12]


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------


def _zero_io(state: SimState):
    C = state.arr_ptr.shape[0]
    return jnp.zeros((C,), bool), jnp.zeros((C, Q.NF), jnp.int32)


def _run_kind(spec: PolicySpec, state: SimState, t, params, cfg: SimConfig):
    """One policy's whole scheduling pass, vmapped over the cluster axis.
    Uniform output shape across kinds — (state, borrow_want, borrow_job
    rows) — so kinds can be branches of one ``lax.switch``; the non-FIFO
    families emit an all-False want (the engine's borrow phase is then a
    bitwise no-op for their cells)."""
    if spec.kind == "fifo":
        state, want, bjobs = jax.vmap(
            functools.partial(K._fifo_local, cfg=cfg, params=params),
            in_axes=(STATE_AXES, None),
            out_axes=(STATE_AXES, 0, 0))(state, t)
        return state, want, bjobs.vec
    if spec.kind == "delay":
        fn = (K._delay_wave_local
              if not cfg.parity and cfg.delay_sweep == "wave"
              else K._delay_local)
    elif spec.kind == "ffd":
        fn = (K._ffd_wave_local
              if not cfg.parity and cfg.ffd_sweep == "wave"
              else K._ffd_local)
    elif spec.kind == "gavel":
        fn = K._gavel_local
    elif spec.kind == "rl":
        fn = K._rl_local
    else:  # tesserae
        fn = K._tesserae_local
    state = jax.vmap(functools.partial(fn, cfg=cfg, params=params),
                     in_axes=(STATE_AXES, None),
                     out_axes=STATE_AXES)(state, t)
    want, bjob = _zero_io(state)
    return state, want, bjob


@dataclasses.dataclass(frozen=True)
class PolicySet:
    """The static tuple of registered policy names one compiled program can
    run; ``params.idx`` (traced) selects the member. Hashable, so it rides
    Engine closures and jit caches like the config does."""

    names: tuple

    def __post_init__(self):
        if not self.names:
            raise ValueError("PolicySet needs at least one policy name")
        for n in self.names:
            if n not in REGISTRY:
                raise ValueError(
                    f"unregistered policy {n!r}; known: {sorted(REGISTRY)}")

    @classmethod
    def from_config(cls, cfg: SimConfig) -> "PolicySet":
        """The singleton set for a classic ``cfg.policy`` run."""
        return cls((cfg.policy.value.lower(),))

    @property
    def specs(self) -> tuple:
        return tuple(REGISTRY[n] for n in self.names)

    @property
    def kinds(self) -> tuple:
        return tuple(s.kind for s in self.specs)

    @property
    def has_fifo(self) -> bool:
        return "fifo" in self.kinds

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def params_for(self, cfg: SimConfig, name=None) -> PolicyParams:
        """Concrete PolicyParams for one member (the first by default),
        idx set to its position in this set."""
        name = self.names[0] if name is None else name
        i = self.index_of(name)
        return default_params(cfg, self.specs[i], idx=i)

    def stacked_params(self, cfg: SimConfig) -> PolicyParams:
        """All members' params stacked on a leading axis — the policy axis
        a tournament vmaps over."""
        cells = [self.params_for(cfg, n) for n in self.names]
        return jax.tree.map(lambda *ls: jnp.stack(ls), *cells)

    def provenance(self, cfg: SimConfig, name=None) -> dict:
        """(registered name, param digest) for detail dicts."""
        name = self.names[0] if name is None else name
        return {"name": name,
                "params_digest": params_digest(self.params_for(cfg, name))}

    # -- traced dispatch ---------------------------------------------------

    def ingest_to_delay(self):
        """Arrival ingest target across the set: a static bool when every
        member agrees, else None (the engine then switches on a traced
        per-member table)."""
        targets = {s.to_delay for s in self.specs}
        return targets.pop() if len(targets) == 1 else None

    def to_delay_table(self) -> jax.Array:
        return jnp.asarray([s.to_delay for s in self.specs])

    def kind_flag_table(self, kind: str) -> jax.Array:
        return jnp.asarray([s.kind == kind for s in self.specs])

    def dispatch(self, state: SimState, t, params: PolicyParams,
                 cfg: SimConfig):
        """The phase-4 scheduling pass: run the member ``params.idx``
        selects. Same-kind members share one code path (their differences
        are parameter leaves); distinct kinds are ``lax.switch`` branches
        over a static member->branch table. A scalar (per-cell) index
        executes only the selected branch; only a vmap that batches the
        index itself pays for all branches."""
        distinct = []
        branch_of = []
        for spec in self.specs:
            key = (spec.kind, spec.to_delay)
            if key not in [(s.kind, s.to_delay) for s in distinct]:
                distinct.append(spec)
            branch_of.append(
                [(s.kind, s.to_delay) for s in distinct].index(key))
        if len(distinct) == 1:
            return _run_kind(distinct[0], state, t, params, cfg)
        branches = tuple(functools.partial(_run_kind, spec, cfg=cfg)
                         for spec in distinct)
        bidx = jnp.asarray(branch_of, jnp.int32)[params.idx]
        return jax.lax.switch(bidx, branches, state, t, params)

    def leap_masks(self, s: SimState, cfg: SimConfig, params: PolicyParams):
        """Per-kind leap-accrual masks (kernels.leap_wait_masks) under the
        same member->branch dispatch as the scheduling pass; single-cluster
        view (called inside the engine's per-cluster vmap)."""
        kinds = []
        branch_of = []
        for spec in self.specs:
            if spec.kind not in kinds:
                kinds.append(spec.kind)
            branch_of.append(kinds.index(spec.kind))
        if len(kinds) == 1:
            return K.leap_wait_masks(kinds[0], s, cfg, params)

        def mask_fn(kind):
            return lambda s_, p_: K.leap_wait_masks(kind, s_, cfg, p_)

        bidx = jnp.asarray(branch_of, jnp.int32)[params.idx]
        return jax.lax.switch(bidx, tuple(mask_fn(k) for k in kinds),
                              s, params)
