"""Per-cluster scheduling-pass kernels — the policy zoo's compute bodies.

Every policy is a batched kernel over the SoA state columns: a pure
function ``(SimState-slice, t, cfg, params) -> SimState-slice`` vmapped
over the cluster axis by the dispatch layer (policies/base.py). The
reference policies (FIFO/DELAY — pkg/scheduler/scheduler.go; FFD — the
TPU-side upgrade) moved here verbatim from core/engine.py when placement
became policy-as-data (PR 6): their semantics, docstrings, and bit-parity
obligations are unchanged, and the engine re-exports their names.

``params`` is the policy's parameter pytree (policies.PolicyParams):
TRACED data, not config — a vmapped tournament batches it over the
(policy, seed) axis with zero recompiles. Kernels must read policy knobs
from it (never from ``cfg.policy``-style static branches) and must stay
tracer-pure and branchless on traced values — simlint's ``policy-kernel``
rule family enforces both over this package. ``params=None`` falls back to
the config values (the pre-refactor standalone call shape, kept for the
phase probes).

New zoo members (no reference analogue, hence no Go-parity constraint):

- ``_gavel_local`` — round-based heterogeneity-aware placement in the
  spirit of Gavel (arxiv 2008.09213): each tick is an allocation round;
  jobs pick the feasible node whose device type maximizes the job class's
  throughput (``params.gavel_tput``, a [N_JOB_CLASSES, N_DEVICE_TYPES]
  leaf), so gpu-class work lands on accelerator nodes while cpu-class work
  keeps standard nodes free.
- ``_tesserae_local`` — packing-aware scoring in the spirit of Tesserae
  (arxiv 2508.04953) / Tetris: jobs sweep in decreasing-demand order and
  pick the feasible node with the highest demand·free alignment
  (``params.tess_w`` weighs the resource axes), steering complementary
  shapes onto the same node instead of first-fit fragmentation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.state import SimState, Trace
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import placement as P
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R


def _trace_append(tr: Trace, do, t, job_id, node, src):
    """Per-cluster capped event append (single-cluster view)."""
    cap = tr.t.shape[-1]
    ok = jnp.logical_and(do, tr.n < cap)
    i = jnp.clip(tr.n, 0, cap - 1)

    def w(a, v):
        return a.at[i].set(jnp.where(ok, v, a[i]))

    return Trace(t=w(tr.t, t), job=w(tr.job, job_id), node=w(tr.node, node),
                 src=w(tr.src, jnp.int32(src)), n=tr.n + ok.astype(jnp.int32))


def _trace_append_many(tr, take, t, job_ids, nodes, src):
    """Batch form of ``_trace_append``: append events for positions where
    ``take``, in position order — bit-identical to appending them one by
    one. One [K, cap] one-hot contraction instead of K cursor writes."""
    cap = tr.t.shape[-1]
    rank = jnp.cumsum(take.astype(jnp.int32)) - 1
    idx = tr.n + rank
    ok = jnp.logical_and(take, idx < cap)
    hot = jnp.logical_and(
        ok[:, None], idx[:, None] == jnp.arange(cap, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)  # [K, cap]
    untouched = hot.sum(axis=0) == 0  # [cap]

    def w(a, vals):
        return jnp.where(untouched, a, jnp.einsum("kc,k->c", hot,
                                                  vals.astype(jnp.int32)))

    src_v = jnp.full(take.shape, jnp.int32(src))
    t_v = jnp.full(take.shape, jnp.asarray(t, jnp.int32))
    return tr.replace(t=w(tr.t, t_v), job=w(tr.job, job_ids),
                      node=w(tr.node, nodes), src=w(tr.src, src_v),
                      n=tr.n + ok.sum().astype(jnp.int32))


def _attempt(s: SimState, job: Q.JobRec, t, do, src, record_trace: bool):
    """One ScheduleJob(j) attempt (scheduler.go:127-139) on a single cluster:
    first-fit over nodes; on success occupy resources and start the job.

    A full running set makes the attempt fail (job stays queued) rather than
    leak resources — a documented divergence (PARITY.md): size
    ``max_running`` so it never binds.

    One shared body with the sweep loops: a single-row deferred buffer
    flushed immediately (start_many of one row == start), so placement
    accounting can never drift between the head attempts and the sweeps."""
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    buf = jnp.zeros((1, R.RF), jnp.int32)
    s, success, buf, cnt = _attempt_deferred(s, job, t, do, src, record_trace,
                                             buf, jnp.int32(0), n_active)
    return s.replace(run=R.start_many(s.run, buf, cnt)), success


def _attempt_deferred(s: SimState, job: Q.JobRec, t, do, src,
                      record_trace: bool, buf, cnt, n_active, node=None):
    """``_attempt`` for placement-sweep loops: identical semantics, but the
    RunningSet insertion is deferred — the placed row lands in ``buf`` at
    position ``cnt`` (a [SW, RF] scratch, SW = sweep bound) and the caller
    flushes the batch with ``R.start_many`` after the loop. The [S]-sized
    set is then touched once per tick instead of once per sweep step, which
    dominated the per-tick cost at thousands of clusters. ``n_active`` is
    the set's occupancy at loop entry; ``n_active + cnt`` reproduces the
    sequential has-slot check exactly.

    ``node`` overrides the target-selection rule: ``None`` keeps the
    reference's first-fit scan; the scored policies (gavel/tesserae) pass
    their own pick (``P.best_scored_fit``) — everything else (occupancy,
    accounting, trace, drops) is shared so the zoo can never drift on the
    bookkeeping."""
    if node is None:
        node = P.first_fit(s.node_free, s.node_active, job)
    has_slot = (n_active + cnt) < s.run.capacity
    success = jnp.logical_and(jnp.logical_and(do, has_slot), node >= 0)
    free = P.occupy(s.node_free, node, job, success)
    row = R.row_from_job(job, node, t)
    hot = jnp.logical_and(jnp.arange(buf.shape[0], dtype=jnp.int32) == cnt,
                          success)
    buf = jnp.where(hot[:, None], row, buf)
    cnt = cnt + success.astype(jnp.int32)
    trace = _trace_append(s.trace, success, t, job.id, node, src) if record_trace else s.trace
    run_full = jnp.logical_and(jnp.logical_and(do, node >= 0),
                               jnp.logical_not(has_slot))
    drops = s.drops.replace(run_full=s.drops.run_full + run_full.astype(jnp.int32))
    s = s.replace(node_free=free, trace=trace, drops=drops,
                  placed_total=s.placed_total + success.astype(jnp.int32))
    return s, success, buf, cnt


def _sweep_len(cfg: SimConfig) -> int:
    """Per-tick placement-sweep length: the whole queue in parity mode, the
    fast-mode cap otherwise (PARITY.md §divergences)."""
    if cfg.parity:
        return cfg.queue_capacity
    return min(cfg.queue_capacity, cfg.max_placements_per_tick)


def _record_wait(total, rec_wait, enq_t, t, do):
    """JobsMap bookkeeping on a scheduling attempt (scheduler.go:309-312):
    TotalTime -= map[id]; map[id] = since(enqueue); TotalTime += map[id]."""
    cur = (t - enq_t).astype(jnp.int32)
    delta = jnp.where(do, (cur - rec_wait).astype(jnp.float32), 0.0)
    return total + delta, jnp.where(do, cur, rec_wait)


def _max_wait_ms(cfg: SimConfig, params):
    """The DELAY Level0->Level1 promotion threshold: a policy parameter
    (traced leaf) when params are given, the config constant otherwise —
    bitwise the same compare either way for the config-derived default."""
    if params is None:
        return jnp.int32(cfg.max_wait_ms)
    return params.max_wait_ms.astype(jnp.int32)


def _bfd_order(q, params):
    """Best-fit-decreasing slot order with the FFD tie-break as data:
    ``params.ffd_mem_first`` swaps the (cores, mem) sort-key priority.
    ``params=None`` (and the default 0) is exactly
    ``P.best_fit_decreasing_order`` — the seed FFD semantics."""
    if params is None:
        return P.best_fit_decreasing_order(q.cores, q.mem, q.slot_valid())
    valid = q.slot_valid()
    big = jnp.int32(2**31 - 1)
    mem_first = params.ffd_mem_first > 0
    primary = jnp.where(valid, jnp.where(mem_first, -q.mem, -q.cores), big)
    secondary = jnp.where(valid, jnp.where(mem_first, -q.cores, -q.mem), big)
    return jnp.lexsort((secondary, primary)).astype(jnp.int32)


# --------------------------------------------------------------------------
# DELAY — the reference's live algorithm
# --------------------------------------------------------------------------

def _delay_local(s: SimState, t, cfg: SimConfig, params=None):
    """Delay() — the reference's live algorithm (scheduler.go:298-369).

    In fast mode (parity=False) the Level1 sweep attempts only the first
    ``max_placements_per_tick`` queue slots — a throughput knob for scale
    configs (PARITY.md §divergences); the queue still drains in FIFO order
    via compaction."""
    QC = _sweep_len(cfg)

    # ---- Level1 sweep: a bounded while loop — under vmap it runs only
    # max-over-clusters(|Level1|) iterations, so an idle constellation pays
    # ~nothing and parity mode costs the same as the capped fast mode.
    # RunningSet insertions are deferred to one start_many after the loop
    # (_attempt_deferred) — the per-step body touches only [SW]-sized
    # scratch, not the [S]-sized set ----
    n_sweep = jnp.minimum(s.l1.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def cond(carry):
        s2, i, rec, placed, skip_next, buf, cnt = carry
        return i < n_sweep

    def step(carry):
        s2, i, rec, placed, skip_next, buf, cnt = carry
        process = jnp.logical_and(i < n_sweep, jnp.logical_not(skip_next))
        # one-hot slot access: dynamic row gathers/scatters serialize when
        # the loop body is vmapped over thousands of clusters
        hot = jnp.arange(s2.l1.capacity, dtype=jnp.int32) == i
        rec_i = jnp.einsum("q,q->", hot.astype(jnp.int32), rec)
        job = Q.select_row(s2.l1, hot).with_(rec_wait=rec_i)
        total, new_rec = _record_wait(s2.wait_total, rec_i, job.enq_t, t, process)
        rec = jnp.where(jnp.logical_and(hot, process), new_rec, rec)
        s2 = s2.replace(wait_total=total)
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_L1, cfg.record_trace, buf, cnt, n_active)
        s2 = s2.replace(jobs_in_queue=s2.jobs_in_queue - success.astype(jnp.int32))
        placed = jnp.logical_or(placed, jnp.logical_and(hot, success))
        # Parity: Go removes L1[i] in place and `i++` skips the element that
        # slides into position i (scheduler.go:319) — equivalent on the
        # original order to "after a success, skip the next element".
        skip_next = success if cfg.parity else jnp.zeros((), bool)
        return (s2, i + 1, rec, placed, skip_next, buf, cnt)

    init = (s, jnp.int32(0), s.l1.rec_wait,
            jnp.zeros((cfg.queue_capacity,), bool), jnp.zeros((), bool),
            jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0))
    t_in = s.t
    s, _, rec, placed, _, buf, cnt = jax.lax.while_loop(cond, step, init)
    # the loop never writes the clock, but under vmap a batched loop
    # predicate makes older jax batching rules batch EVERY carry leaf —
    # including the replicated scalar t, which then trips the engine's
    # out_axes=None spec. Restoring the pre-loop leaf is a semantic no-op
    # that keeps t replicated on every jax version.
    s = s.replace(t=t_in)
    l1 = Q.compact(Q.set_field(s.l1, "rec_wait", rec), jnp.logical_not(placed))
    s = s.replace(l1=l1, run=R.start_many(s.run, buf, cnt))
    return _delay_l0_head(s, t, cfg, params)


def _delay_l0_head(s: SimState, t, cfg: SimConfig, params=None):
    """The Level0-head half of Delay() (scheduler.go:332-366): one
    placement attempt on the head, else promote to Level1 after
    MaxWaitTime. Shared by the serial and wave Level1 sweeps."""
    process = s.l0.count > 0
    job = Q.head(s.l0)
    total, new_rec = _record_wait(s.wait_total, job.rec_wait, job.enq_t, t, process)
    l0 = Q.set_field_elem(s.l0, "rec_wait", 0, new_rec)
    s = s.replace(wait_total=total, l0=l0)
    job = job.with_(rec_wait=new_rec)
    s, success = _attempt(s, job, t, process, st.SRC_L0, cfg.record_trace)
    s = s.replace(jobs_in_queue=s.jobs_in_queue - success.astype(jnp.int32))
    promote = jnp.logical_and(
        jnp.logical_and(process, jnp.logical_not(success)),
        (t - job.enq_t) >= _max_wait_ms(cfg, params),
    )
    s = s.replace(
        l0=Q.pop_front(s.l0, jnp.logical_or(success, promote)),
        l1=Q.push_back(s.l1, job, promote),
        drops=s.drops.replace(
            queue=s.drops.queue + Q.push_back_dropped(s.l1, promote)),
    )
    return s


def _delay_wave_local(s: SimState, t, cfg: SimConfig, params=None):
    """Fast-mode Delay(): the Level1 sweep as speculative waves
    (``_wave_place``; equivalence argument in ``_ffd_wave_local``) plus
    the shared Level0-head attempt. Parity mode keeps the serial sweep —
    its remove-then-skip quirk and ordered float wait accumulation are
    part of bit-parity (PARITY.md)."""
    QC = min(cfg.queue_capacity, cfg.max_placements_per_tick)
    n_sweep = jnp.minimum(s.l1.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    act0 = jnp.arange(QC, dtype=jnp.int32) < n_sweep
    rows = Q.rows_prefix(s.l1, QC)  # sweep order == queue order (no sort)
    jobs = Q.JobRec(vec=rows)

    # wait accounting, vectorized over the processed prefix (fast mode:
    # no serial-float-order constraint)
    processed_slot = s.l1.slot_valid() & (
        jnp.arange(s.l1.capacity, dtype=jnp.int32) < n_sweep)
    cur = (t - s.l1.enq_t).astype(jnp.int32)
    frec = s.l1.rec_wait
    delta = jnp.where(processed_slot, (cur - frec).astype(jnp.float32), 0.0)
    l1 = Q.set_field(s.l1, "rec_wait", jnp.where(processed_slot, cur, frec))
    s = s.replace(wait_total=s.wait_total + delta.sum(), l1=l1)

    free, node_sel, cnt, run_full = _wave_place(
        s.node_free, s.node_active, s.run.capacity, n_active, jobs, act0)

    placed_pos = node_sel >= jnp.int32(0)
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_L1)
    placed_slot = jnp.pad(placed_pos, (0, s.l1.capacity - QC))
    s = s.replace(
        node_free=free, trace=trace,
        drops=s.drops.replace(run_full=s.drops.run_full + run_full),
        placed_total=s.placed_total + cnt,
        jobs_in_queue=s.jobs_in_queue - cnt,
        l1=Q.compact(s.l1, jnp.logical_not(placed_slot)),
        run=R.start_many(s.run, buf, cnt))
    return _delay_l0_head(s, t, cfg, params)


# --------------------------------------------------------------------------
# FFD — first-fit-decreasing bin-pack (TPU-side upgrade)
# --------------------------------------------------------------------------

def _ffd_local(s: SimState, t, cfg: SimConfig, params=None):
    """First-fit-decreasing bin-pack over Level0 — one XLA sort + the shared
    placement sweep (``_scored_sweep_local`` with the default first-fit
    node pick). Not in the reference; BASELINE.json config 3. Fast mode
    caps the sweep at ``max_placements_per_tick`` (largest jobs first)."""
    return _scored_sweep_local(s, t, cfg, params, _bfd_order(s.l0, params),
                               score_fn=None)


# --------------------------------------------------------------------------
# speculative-wave machinery (shared by the FFD/DELAY/FIFO wave forms)
# --------------------------------------------------------------------------

def _wave_probe(free, node_active, jobs: Q.JobRec, active):
    """The per-wave feasibility core shared by every speculative sweep
    (``_wave_place``, ``_fifo_drain_wave``): first-fit target selection and
    cumulative-overflow detection for the active rows under the current
    ``free``. This is the equivalence-critical logic — any edit here changes
    all wave forms together (tests/test_kernel_equiv.py pins wave==serial).

    A wave accepts *whole same-target groups*, not just distinct targets:
    for jobs targeting the same node, the running group total (job k's own
    demand plus all earlier same-target rows) is compared against the
    node's free vector, and only the row that overflows it (and everything
    after, via the callers' prefix rules) defers to the next wave. This is
    exact by the same monotonicity argument as the original
    distinct-target rule (``_ffd_wave_local`` docstring), extended one
    step: for an accepted job k targeting node n, earlier accepted jobs on
    other nodes leave n untouched, earlier accepted jobs ON n are exactly
    k's group predecessors — whose total including k fits — so when the
    serial sweep reaches k, nodes before n are still infeasible (free only
    shrinks) and n is still feasible: the serial sweep picks n too. Without
    the group rule, homogeneous clusters degrade to one placement per wave
    (every queued job first-fits the same node), which left the FIFO
    headline latency-bound at ~backlog iterations per tick.

    Returns ``(feas_any, tgt, tgt_hot, overflow)``: per-row feasibility,
    first-fit node index, its one-hot [QC, N] form (zero rows where
    infeasible/inactive), and whether the row's cumulative group demand
    overflows its target's free capacity this wave."""
    feas = jax.vmap(lambda c, m, g: P.feasible(
        free, node_active, c, m, g))(jobs.cores, jobs.mem, jobs.gpu)
    feas = jnp.logical_and(feas, active[:, None])  # [QC, N]
    feas_any = jnp.any(feas, axis=-1)
    tgt = jnp.argmax(feas, axis=-1).astype(jnp.int32)  # first-fit node
    tgt_hot = jnp.logical_and(
        feas_any[:, None],
        tgt[:, None] == jnp.arange(feas.shape[1],
                                   dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    res = jobs.res[..., : free.shape[-1]]  # [QC, R]
    cum = jnp.cumsum(tgt_hot[:, :, None] * res[:, None, :], axis=0)  # [QC, N, R]
    group_dem = jnp.einsum("kn,knr->kr", tgt_hot, cum)  # incl. the row itself
    tgt_free = jnp.einsum("kn,nr->kr", tgt_hot, free)
    overflow = jnp.logical_and(feas_any,
                               jnp.any(group_dem > tgt_free, axis=-1))
    return feas_any, tgt, tgt_hot, overflow


def _wave_occupy(free, tgt_hot, place, jobs: Q.JobRec):
    """Subtract the accepted rows' resources from ``free``: one [QC, N] x
    [QC, R] contraction instead of per-row scatter-subtracts."""
    used = jnp.einsum("kn,kr->nr", tgt_hot * place[:, None].astype(jnp.int32),
                      jobs.res[..., : free.shape[-1]])
    return free - used


def _wave_place(free0, node_active, run_cap, n_active, jobs: Q.JobRec, act0):
    """The wave-placement core shared by the FFD and DELAY fast-mode
    sweeps: place ``jobs`` (a [QC]-batched JobRec in sweep order, active
    where ``act0``) by speculative conflict-free-prefix waves. Returns
    ``(free', node_sel, cnt, run_full)`` with ``node_sel[k]`` the placed
    node per position (NO_NODE where unplaced). Equivalence argument:
    ``_ffd_wave_local`` docstring."""
    QC = act0.shape[0]

    def cond(carry):
        free, resolved, node_sel, cnt, run_full = carry
        return jnp.any(jnp.logical_and(act0, jnp.logical_not(resolved)))

    def step(carry):
        free, resolved, node_sel, cnt, run_full = carry
        active = jnp.logical_and(act0, jnp.logical_not(resolved))
        feas_any, tgt, tgt_hot, overflow = _wave_probe(free, node_active,
                                                       jobs, active)
        blocked = jnp.cumsum(overflow.astype(jnp.int32)) > 0  # self included
        place_try = jnp.logical_and(feas_any, jnp.logical_not(blocked))
        rank = jnp.cumsum(place_try.astype(jnp.int32)) - 1
        has_slot = (n_active + cnt + rank) < run_cap
        place = jnp.logical_and(place_try, has_slot)
        slot_full = jnp.logical_and(place_try, jnp.logical_not(has_slot))
        # infeasible-now is infeasible-forever (free only shrinks): resolve
        # failed even past the block point; slot-exhausted jobs resolve too
        # (run_full drop), exactly as the serial sweep counts them
        resolved = jnp.logical_or(
            resolved, jnp.logical_or(
                place, jnp.logical_or(
                    slot_full,
                    jnp.logical_and(active, jnp.logical_not(feas_any)))))
        free = _wave_occupy(free, tgt_hot, place, jobs)
        node_sel = jnp.where(place, tgt, node_sel)
        cnt = cnt + place.sum().astype(jnp.int32)
        run_full = run_full + slot_full.sum().astype(jnp.int32)
        return free, resolved, node_sel, cnt, run_full

    free, _, node_sel, cnt, run_full = jax.lax.while_loop(
        cond, step, (free0, jnp.logical_not(act0),
                     jnp.full((QC,), P.NO_NODE), jnp.int32(0), jnp.int32(0)))
    return free, node_sel, cnt, run_full


def _ffd_wave_local(s: SimState, t, cfg: SimConfig, params=None):
    """``_ffd_local`` restructured as speculative placement waves — same
    placements, a fraction of the serial steps.

    Sequential first-fit has a loop-carried dependency (each placement
    shrinks ``free`` for the next job), which on TPU costs one
    latency-bound while_loop iteration per queued job, maxed over all
    vmapped clusters (tools/cost_probe.json: the FFD sweep achieves less
    than half the headline's HBM bandwidth). The wave form places many
    jobs per iteration and is *provably identical* to the serial sweep:

    each wave, every unresolved job computes its first-fit target under
    the current ``free``; the accepted set is the longest prefix (in FFD
    order) in which every job's cumulative same-target group demand fits
    its target node (``_wave_probe`` — whole groups land in one wave).
    For an accepted job, earlier accepted jobs on other nodes leave its
    target untouched, earlier accepted jobs on the SAME node are its
    group predecessors whose total including it fits, and ``free`` only
    ever shrinks — so nodes before its target stay infeasible and its
    target stays feasible: exactly the node the serial sweep would pick.
    A job infeasible under the current ``free`` is infeasible forever
    (monotonicity) and resolves as failed immediately; the first
    group-capacity overflow defers itself and everything after it to the
    next wave. The earliest unresolved job can never overflow (it is
    feasible and heads its group), so every wave makes progress and the
    loop runs one iteration per capacity epoch instead of one per job.

    Used in fast mode (``parity=False`` — the Go reference has no FFD, so
    there is no Go-semantics constraint either way; ``ffd_sweep="serial"``
    keeps the old path, and tests/test_kernel_equiv.py pins wave == serial
    on trace, queue, and node state across seeds)."""
    QC = min(cfg.queue_capacity, cfg.max_placements_per_tick)
    cap_q = s.l0.capacity
    order = _bfd_order(s.l0, params)[:QC]  # [QC]
    n_sweep = jnp.minimum(s.l0.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    act0 = jnp.arange(QC, dtype=jnp.int32) < n_sweep

    # ordered job rows: one [QC, Q] @ [Q, NF] integer contraction
    sel = (order[:, None] ==
           jnp.arange(cap_q, dtype=jnp.int32)[None, :]).astype(jnp.int32)
    rows = Q.gather_rows(s.l0, sel)
    jobs = Q.JobRec(vec=rows)

    # wait accounting, vectorized at the slot level (every processed job is
    # recorded exactly once per tick; fast mode has no serial-float-order
    # constraint — parity mode keeps the serial sweep)
    processed_slot = jnp.einsum("kq,k->q", sel, act0.astype(jnp.int32)) > 0
    cur = (t - s.l0.enq_t).astype(jnp.int32)
    frec = s.l0.rec_wait
    delta = jnp.where(processed_slot, (cur - frec).astype(jnp.float32), 0.0)
    l0 = Q.set_field(s.l0, "rec_wait", jnp.where(processed_slot, cur, frec))
    s = s.replace(wait_total=s.wait_total + delta.sum(), l0=l0)

    free, node_sel, cnt, run_full = _wave_place(
        s.node_free, s.node_active, s.run.capacity, n_active, jobs, act0)

    placed_pos = node_sel >= jnp.int32(0)  # [QC], in FFD order
    # runset rows in position order, compacted to the buffer prefix
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)  # [QC, QC]
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_L0)
    placed_slot = jnp.einsum("kq,k->q", sel, placed_pos.astype(jnp.int32)) > 0
    return s.replace(
        node_free=free, trace=trace,
        drops=s.drops.replace(run_full=s.drops.run_full + run_full),
        placed_total=s.placed_total + cnt,
        jobs_in_queue=s.jobs_in_queue - cnt,
        l0=Q.compact(s.l0, jnp.logical_not(placed_slot)),
        run=R.start_many(s.run, buf, cnt))


# --------------------------------------------------------------------------
# FIFO — wait-head / ready-drain / lent best-effort
# --------------------------------------------------------------------------

def _fifo_drain_wave(s: SimState, t, cfg: SimConfig, wait_active, n_active,
                     QC: int):
    """The FIFO ready drain (place from the head until the first failure)
    as speculative waves — same outcome as the serial loop in
    ``_fifo_local``, a fraction of the while_loop iterations.

    The equivalence argument mirrors ``_ffd_wave_local`` (prefix-restricted
    group acceptance via ``_wave_probe``; free only shrinks, so accepted
    first-fit targets and observed infeasibilities are both stable), with
    one extra rule for the drain-stops-at-first-failure semantics: each
    wave accepts candidates only up to the first *breaker* — a group
    capacity overflow (defer to the next wave), an infeasible job, or a
    run-slot-exhausted job (both of the latter ARE the drain's failing
    job: it pops to the wait queue and the drain stops). Unlike the FFD
    sweep this is exact in parity mode too — the drain body performs no
    order-sensitive float accumulation (wait recording happens at the
    wait-head attempt, not here)."""
    ready = s.ready
    n_sweep = jnp.where(wait_active, 0,
                        jnp.minimum(ready.count, QC)).astype(jnp.int32)
    pos = jnp.arange(QC, dtype=jnp.int32)
    act0 = pos < n_sweep
    rows = Q.rows_prefix(ready, QC)  # queue order: position == slot
    jobs = Q.JobRec(vec=rows)

    def cond(carry):
        free, resolved, node_sel, cnt, run_full, stopped, fail_idx = carry
        return jnp.logical_and(
            jnp.logical_not(stopped),
            jnp.any(jnp.logical_and(act0, jnp.logical_not(resolved))))

    def step(carry):
        free, resolved, node_sel, cnt, run_full, stopped, fail_idx = carry
        active = jnp.logical_and(act0, jnp.logical_not(resolved))
        feas_any, tgt, tgt_hot, overflow = _wave_probe(free, s.node_active,
                                                       jobs, active)
        infeas = jnp.logical_and(active, jnp.logical_not(feas_any))
        cand = jnp.logical_and(feas_any, jnp.logical_not(overflow))
        r = jnp.cumsum(cand.astype(jnp.int32)) - cand.astype(jnp.int32)
        cap_left = s.run.capacity - n_active - cnt
        slotviol = jnp.logical_and(cand, r >= cap_left)
        breaker = jnp.logical_or(overflow, jnp.logical_or(infeas, slotviol))
        # positions strictly before the first breaker
        before_break = jnp.cumsum(breaker.astype(jnp.int32)) == 0
        place = jnp.logical_and(cand, before_break)
        any_break = jnp.any(breaker)
        b = jnp.argmax(breaker).astype(jnp.int32)  # first breaker position
        b_hot = jnp.logical_and(pos == b, any_break)
        failed = jnp.logical_and(
            any_break,
            jnp.logical_or(jnp.any(jnp.logical_and(b_hot, infeas)),
                           jnp.any(jnp.logical_and(b_hot, slotviol))))
        run_full = run_full + jnp.any(
            jnp.logical_and(b_hot, slotviol)).astype(jnp.int32)
        resolved = jnp.logical_or(resolved,
                                  jnp.logical_or(place,
                                                 jnp.logical_and(b_hot, failed)))
        free = _wave_occupy(free, tgt_hot, place, jobs)
        node_sel = jnp.where(place, tgt, node_sel)
        cnt = cnt + place.sum().astype(jnp.int32)
        stopped = jnp.logical_or(stopped, failed)
        fail_idx = jnp.where(failed, b, fail_idx)
        return free, resolved, node_sel, cnt, run_full, stopped, fail_idx

    free, resolved, node_sel, cnt, run_full, stopped, fail_idx = \
        jax.lax.while_loop(cond, step, (
            s.node_free, jnp.logical_not(act0), jnp.full((QC,), P.NO_NODE),
            jnp.int32(0), jnp.int32(0), jnp.zeros((), bool), jnp.int32(-1)))

    placed_pos = node_sel >= jnp.int32(0)
    n_taken = cnt + stopped.astype(jnp.int32)  # pops include the failure
    fhot = (pos == fail_idx).astype(jnp.int32)
    fail_job = Q.JobRec(vec=jnp.einsum("k,kf->f", fhot, rows))
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_READY)
    s = s.replace(node_free=free, trace=trace,
                  drops=s.drops.replace(run_full=s.drops.run_full + run_full),
                  placed_total=s.placed_total + cnt)
    return s, n_taken, fail_job, stopped, buf, cnt


def _fifo_local(s: SimState, t, cfg: SimConfig, params=None):
    """Fifo() (scheduler.go:216-296) as ordered masked phases; see PARITY.md
    for the derivation of the per-tick semantics from the Go loop's
    sleep/continue structure. Returns (state, borrow_want, borrow_job).

    Fast mode (parity=False) caps the ready drain at
    ``max_placements_per_tick`` steps — identical semantics whenever fewer
    than that many jobs would drain in one tick (PARITY.md §divergences)."""
    QC = _sweep_len(cfg)
    wait_active = s.wait.count > 0

    # ---- ready drain (only when the wait queue is empty): place from the
    # head until the first failure; the failing job moves to WaitQueue.
    # Bounded while loop — exits as soon as every cluster drained/stopped ----
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def dcond(carry):
        s2, i, stopped, n_taken, fail_job, any_fail, buf, cnt = carry
        return jnp.logical_and(
            jnp.logical_not(wait_active),
            jnp.logical_and(i < jnp.minimum(s2.ready.count, QC),
                            jnp.logical_not(stopped)))

    def dstep(carry):
        s2, i, stopped, n_taken, fail_job, any_fail, buf, cnt = carry
        process = jnp.logical_and(
            jnp.logical_not(wait_active),
            jnp.logical_and(i < jnp.minimum(s2.ready.count, QC),
                            jnp.logical_not(stopped)))
        hot = jnp.arange(s2.ready.capacity, dtype=jnp.int32) == i
        job = Q.select_row(s2.ready, hot)
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_READY, cfg.record_trace, buf, cnt,
            n_active)
        fail = jnp.logical_and(process, jnp.logical_not(success))
        n_taken = n_taken + process.astype(jnp.int32)  # pops regardless of outcome
        fail_job = jax.tree.map(lambda a, b: jnp.where(fail, b, a), fail_job, job)
        return (s2, i + 1, jnp.logical_or(stopped, fail), n_taken, fail_job,
                jnp.logical_or(any_fail, fail), buf, cnt)

    if cfg.fifo_drain == "wave":
        s, n_taken, fail_job, any_fail, buf, cnt = _fifo_drain_wave(
            s, t, cfg, wait_active, n_active, QC)
    else:
        init = (s, jnp.int32(0), jnp.zeros((), bool), jnp.int32(0),
                Q.JobRec.invalid(), jnp.zeros((), bool),
                jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0))
        t_in = s.t
        s, _, _, n_taken, fail_job, any_fail, buf, cnt = jax.lax.while_loop(
            dcond, dstep, init)
        # keep the replicated clock out of the batched carry (_delay_local)
        s = s.replace(t=t_in)
    # the drain consumes a strict prefix of the ready queue; its placements
    # flush into the set before the wait-head attempt reads occupancy
    s = s.replace(run=R.start_many(s.run, buf, cnt),
                  ready=Q.pop_front_n(s.ready, n_taken),
                  wait=Q.push_back(s.wait, fail_job, any_fail),
                  drops=s.drops.replace(
                      queue=s.drops.queue + Q.push_back_dropped(s.wait, any_fail)))

    # ---- wait-head attempt (the branch at scheduler.go:219-252) ----
    process_w = s.wait.count > 0
    wjob = Q.head(s.wait)
    s, wsuccess = _attempt(s, wjob, t, process_w, st.SRC_WAIT, cfg.record_trace)
    s = s.replace(wait=Q.pop_front(s.wait, wsuccess))
    borrow_want = jnp.logical_and(process_w, jnp.logical_not(wsuccess))
    if not cfg.borrowing:
        borrow_want = jnp.zeros((), bool)

    # ---- lent best-effort (scheduler.go:277-291): reached only in a tick
    # where wait was empty and ready drained clean ----
    lent_ok = jnp.logical_and(
        jnp.logical_and(jnp.logical_not(wait_active), jnp.logical_not(any_fail)),
        jnp.logical_and(s.ready.count == 0, s.lent.count > 0))
    ljob = Q.head(s.lent)
    s, lsuccess = _attempt(s, ljob, t, lent_ok, st.SRC_LENT, cfg.record_trace)
    s = s.replace(lent=Q.pop_front(s.lent, lsuccess))
    return s, borrow_want, wjob


# --------------------------------------------------------------------------
# GAVEL — round-based heterogeneity-aware placement (arxiv 2008.09213)
# --------------------------------------------------------------------------

def _class_device_scores(node_type, jclass, matrix):
    """[N] per-node score for a job of class ``jclass``: one row of a
    [N_JOB_CLASSES, N_DEVICE_TYPES] score matrix, spread over the node
    slots by device type. One-hot contractions, no gathers (the kernel is
    vmapped over thousands of clusters). Shared by the gavel kernel (the
    matrix is a throughput table) and the RL action port (the matrix is a
    per-env network output — envs/)."""
    jc = jnp.clip(jclass, 0, F.N_JOB_CLASSES - 1)
    row_hot = (jnp.arange(F.N_JOB_CLASSES, dtype=jnp.int32) == jc)
    row = jnp.einsum("c,cd->d", row_hot.astype(jnp.float32), matrix)  # [DT]
    nt = jnp.clip(node_type, 0, F.N_DEVICE_TYPES - 1)
    nt_hot = (nt[:, None] ==
              jnp.arange(F.N_DEVICE_TYPES, dtype=jnp.int32)[None, :])
    return jnp.einsum("nd,d->n", nt_hot.astype(jnp.float32), row)  # [N]


def _gavel_scores(node_type, jclass, params):
    """Gavel's node scores: the policy's throughput matrix row for the
    job's class (``_class_device_scores``)."""
    return _class_device_scores(node_type, jclass, params.gavel_tput)


def _tesserae_scores(node_free, job, params):
    """[N] packing-alignment score: the Tetris/Tesserae demand·free dot
    product under ``params.tess_w`` resource weights — high where the
    node's remaining shape matches the job's demand shape, so complementary
    jobs pack onto the same node instead of fragmenting first-fit order."""
    n_res = node_free.shape[-1]
    res = job.res[..., :n_res].astype(jnp.float32)  # [R]
    w = params.tess_w[:n_res]
    return jnp.einsum("nr,r->n", node_free.astype(jnp.float32), res * w)


def _scored_sweep_local(s: SimState, t, cfg: SimConfig, params, order,
                        score_fn):
    """The ONE serial Level0 placement sweep behind FFD, gavel, and
    tesserae: a bounded while loop over ``order`` with one-hot slot access
    (see ``_delay_local``), per-slot wait recording, deferred RunningSet
    insertion, and queue compaction — shared, so the zoo members differ
    ONLY in sweep order and target selection and accounting can never
    drift per policy. ``score_fn(state, job) -> [N]`` swaps the node pick
    for ``P.best_scored_fit``; ``None`` keeps the reference first-fit
    (``_attempt_deferred``'s default)."""
    QC = _sweep_len(cfg)
    n_sweep = jnp.minimum(s.l0.count, QC)  # order puts valid slots first
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def cond(carry):
        s2, k, placed, buf, cnt = carry
        return k < n_sweep

    def step(carry):
        s2, k, placed, buf, cnt = carry
        process = k < n_sweep
        # one-hot slot access (see _delay_local): i = order[k], then row i
        cap = s2.l0.capacity
        hot_k = jnp.arange(cap, dtype=jnp.int32) == k
        i = jnp.einsum("q,q->", hot_k.astype(jnp.int32), order)
        hot = jnp.arange(cap, dtype=jnp.int32) == i
        job = Q.select_row(s2.l0, hot)
        total, new_rec = _record_wait(s2.wait_total, job.rec_wait, job.enq_t,
                                      t, process)
        frec = s2.l0.rec_wait
        frec = jnp.where(jnp.logical_and(hot, process), new_rec, frec)
        s2 = s2.replace(wait_total=total,
                        l0=Q.set_field(s2.l0, "rec_wait", frec))
        node = None if score_fn is None else P.best_scored_fit(
            s2.node_free, s2.node_active, job, score_fn(s2, job))
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_L0, cfg.record_trace, buf, cnt,
            n_active, node=node)
        s2 = s2.replace(
            jobs_in_queue=s2.jobs_in_queue - success.astype(jnp.int32))
        placed = jnp.logical_or(placed, jnp.logical_and(hot, success))
        return (s2, k + 1, placed, buf, cnt)

    t_in = s.t
    s, _, placed, buf, cnt = jax.lax.while_loop(
        cond, step, (s, jnp.int32(0), jnp.zeros((cfg.queue_capacity,), bool),
                     jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0)))
    s = s.replace(t=t_in)  # keep the replicated clock unbatched (_delay_local)
    return s.replace(l0=Q.compact(s.l0, jnp.logical_not(placed)),
                     run=R.start_many(s.run, buf, cnt))


def _gavel_local(s: SimState, t, cfg: SimConfig, params):
    """Gavel-style round: sweep Level0 in queue order, each job placed on
    the feasible node whose device type maximizes the job class's
    throughput (ties -> lowest node index, the reference's first-fit
    orientation). With the uniform throughput matrix this IS first-fit in
    queue order; the matrix is the policy."""
    order = jnp.arange(s.l0.capacity, dtype=jnp.int32)  # queue order

    def score(s2, job):
        return _gavel_scores(s2.node_type, job.jclass, params)

    return _scored_sweep_local(s, t, cfg, params, order, score)


def _tesserae_local(s: SimState, t, cfg: SimConfig, params):
    """Tesserae-style packing pass: decreasing-demand sweep (the FFD
    order), each job placed on the feasible node with the highest
    weighted demand·free alignment — best-shape-fit instead of
    first-index-fit."""
    order = _bfd_order(s.l0, None)

    def score(s2, job):
        return _tesserae_scores(s2.node_free, job, params)

    return _scored_sweep_local(s, t, cfg, params, order, score)


# --------------------------------------------------------------------------
# RL — the environment mode's action port (envs/, ROADMAP item 2)
# --------------------------------------------------------------------------

def _rl_local(s: SimState, t, cfg: SimConfig, params):
    """The learned-scheduler kind: a Level0 sweep in queue order whose node
    pick is scored by ``params.rl_scores`` — a [N_JOB_CLASSES,
    N_DEVICE_TYPES] matrix that in environment mode is a per-env NETWORK
    OUTPUT substituted per step (envs/cluster_env.py feeds the action in as
    this leaf). The scores ride the same one-hot class/device-type
    contraction as gavel (``_class_device_scores``) and the same shared
    ``_scored_sweep_local`` accounting, so a learned policy can never
    drift from the zoo on bookkeeping; the zero default scores every node
    equally, which is exactly first-fit in queue order
    (P.best_scored_fit ties -> lowest index)."""
    order = jnp.arange(s.l0.capacity, dtype=jnp.int32)  # queue order

    def score(s2, job):
        return _class_device_scores(s2.node_type, job.jclass,
                                    params.rl_scores)

    return _scored_sweep_local(s, t, cfg, params, order, score)


# --------------------------------------------------------------------------
# leap-accrual masks (the event-compressed driver's closed-form wait)
# --------------------------------------------------------------------------

def leap_wait_masks(kind: str, s: SimState, cfg: SimConfig, params=None):
    """Queue slots whose wait clock the scheduling pass advances every tick
    at a placement fixed point — exactly the slots the dense pass calls
    ``_record_wait`` on when nothing places: (l0_mask, l1_mask), single
    cluster view. FIFO records no wait in the pass; DELAY processes the
    first ``min(|L1|, QC)`` Level1 slots plus the Level0 head; the Level0
    sweeps (FFD/gavel/tesserae/rl) record their first ``min(|L0|, QC)``
    processed slots — in sweep order, which for the sorted sweeps means
    the first n positions of the (possibly param-swapped) BFD order.
    ``kind`` is the policy KIND (static — one mask shape per registered
    kernel family, policies/base.py dispatches it)."""
    cap0 = s.l0.capacity
    zl1 = jnp.zeros((s.l1.capacity,), bool)
    if kind == "fifo":
        return jnp.zeros((cap0,), bool), zl1
    QC = _sweep_len(cfg)
    if kind == "delay":
        l1_mask = jnp.logical_and(
            s.l1.slot_valid(),
            jnp.arange(s.l1.capacity, dtype=jnp.int32)
            < jnp.minimum(s.l1.count, QC))
        l0_mask = jnp.logical_and(
            jnp.arange(cap0, dtype=jnp.int32) == 0, s.l0.count > 0)
        return l0_mask, l1_mask
    if kind in ("gavel", "rl"):
        # queue-order sweeps: the first min(|L0|, QC) slots ARE positions
        l0_mask = jnp.logical_and(
            s.l0.slot_valid(),
            jnp.arange(cap0, dtype=jnp.int32) < jnp.minimum(s.l0.count, QC))
        return l0_mask, zl1
    # ffd / tesserae: slots selected by the first n_sweep positions of the
    # sweep's BFD order (ffd's tie-break is a param; tesserae uses default)
    order = _bfd_order(s.l0, params if kind == "ffd" else None)
    n_sweep = jnp.minimum(s.l0.count, QC)
    hot = order[:, None] == jnp.arange(cap0, dtype=jnp.int32)[None, :]
    taken = jnp.arange(cap0, dtype=jnp.int32) < n_sweep  # order positions
    l0_mask = jnp.any(jnp.logical_and(hot, taken[:, None]), axis=0)
    return l0_mask, zl1
