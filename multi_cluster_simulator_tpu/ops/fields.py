"""Canonical packed-row field schemas + narrow-storage store primitives.

One table per row kind — the queue row (ops/queues.py) and the running-set
row (ops/runset.py) — defining field NAMES, ORDER, and INVALID sentinels in
exactly one place. The wide AoS layouts (``data[Q, NF]``), the SoA compact
layouts (per-field leaves), the engine's arrival pack paths, and the
storage-width planner (core/compact.py) all derive their indices from these
tuples, so adding a ninth job field is a one-site change instead of the
previous four parallel index derivations (queues row ctor, engine
pack_arrivals, _bucket_arrivals_host, runset row ctor).

The store primitives at the bottom are the ONLY sanctioned way to move
int32 compute values into a narrower storage leaf: ``narrow_store`` clamps
out-of-range values to the dtype minimum and COUNTS them (never a silent
two's-complement wrap), so a mis-derived storage plan surfaces as a nonzero
overflow counter that parity and bench runs assert stays zero (the same
contract as ``Drops``, core/state.py). simlint's ``compact-store`` rule
flags narrowing stores that bypass them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# --------------------------------------------------------------------------
# queue row schema (ops/queues.py; mirrors the reference's Job struct,
# pkg/scheduler/scheduler.go:65-73 — see ops/queues.py module docstring)
# --------------------------------------------------------------------------

# (cores, mem, gpu) are contiguous and ordered like the node-tensor resource
# axis (core/spec.py RES) so JobRec.res is one slice. ``jclass`` (the ninth
# field, PR 6) is the job's canonical demand-shape class — the row index
# into a heterogeneity-aware policy's per-(class, device-type) throughput
# matrix (policies/kernels.py gavel; Gavel, arxiv 2008.09213). It is derived
# once at stream entry (``job_class``) and rides the row thereafter.
# ``retries`` (the tenth field, the fault plane — faults/) counts how many
# times a node failure has killed-and-requeued this job: arrival streams
# enter at 0, the fault phase bumps it on every requeue, and a kill past
# ``FaultConfig.max_retries`` drops the job into ``drops.failed`` instead
# of requeueing (core/engine.py fault phase). It rides both row kinds so a
# running job's budget survives placement.
QUEUE_FIELDS = ("id", "cores", "mem", "gpu", "dur", "enq_t", "owner",
                "rec_wait", "jclass", "retries")
QUEUE_INDEX = {name: i for i, name in enumerate(QUEUE_FIELDS)}
# invalid-slot sentinel per field: id=-1, owner=OWN(-1), zeros elsewhere
QUEUE_INVALID = (-1, 0, 0, 0, 0, 0, -1, 0, 0, 0)

# --------------------------------------------------------------------------
# heterogeneity schema: job demand-shape classes x node device types
# (the one-site extension the Gavel-style policy keys on)
# --------------------------------------------------------------------------

# Classes bucket the demand SHAPE, not the amount: the gpu/cpu split and a
# big/small core split — the axes along which per-device-type throughput
# plausibly differs. Device types label node slots (core/spec.py
# node_types_array; SimState.node_type): 0 = standard, 1 = accelerator
# (derived from gpu capacity unless a NodeSpec pins it), 2-3 reserved for
# explicit spec overrides. Both counts are STATIC schema constants — they
# size the policy-parameter throughput matrix (policies.PolicyParams
# .gavel_tput), which is a pytree leaf and must have one shape across a
# vmapped policy sweep.
N_JOB_CLASSES = 4
N_DEVICE_TYPES = 4


def job_class(cores, gpu):
    """Canonical demand-shape class in [0, N_JOB_CLASSES): bit 1 = needs
    gpu, bit 0 = core-heavy. Pure elementwise integer arithmetic — works on
    host numpy (the arrival pack paths) and on tracers alike; callers cast
    to their storage dtype."""
    return (gpu > 0) * 2 + (cores > 8) * 1

# --------------------------------------------------------------------------
# running-set row schema (ops/runset.py)
# --------------------------------------------------------------------------

NEVER_I = 2**31 - 1  # end_t sentinel for "no completion scheduled"

# (cores, mem, gpu) contiguous, ordered like spec.RES (release's slice)
RUN_FIELDS = ("end_t", "node", "cores", "mem", "gpu", "id", "owner", "dur",
              "enq_t", "retries")
RUN_INDEX = {name: i for i, name in enumerate(RUN_FIELDS)}
RUN_INVALID = (NEVER_I, 0, 0, 0, 0, -1, -1, 0, 0, 0)

# Fields eligible for sub-int32 storage in the compact layouts. Everything
# else stays int32 BY DESIGN, not by audit: timestamps, durations, and
# accumulated waits are unbounded by the config (a stream can carry ms
# timestamps near 2^31), and end_t must hold the NEVER sentinel. The
# narrowable set is the fields whose range the config + stream provably
# bound: resource demands, cluster indices (owner), node indices, and job
# ids (narrowed only when a stream audit proves the range — the planner
# keeps i32 otherwise, and the checked store counts any host-injected id
# beyond the audited bound instead of wrapping).
NARROWABLE = frozenset({"id", "cores", "mem", "gpu", "owner", "node",
                        "jclass", "retries"})

WIDE_DTYPE = np.dtype(np.int32)


# --------------------------------------------------------------------------
# narrow-storage primitives (re-exported by core/compact.py — the public
# home; they live here so ops/queues.py can import them without pulling the
# core package's __init__ into the ops import chain)
# --------------------------------------------------------------------------


# jax < 0.5 ships optimization_barrier without a vmap batching rule, and
# every SoA queue op runs inside the engine's per-cluster vmap. The barrier
# is an identity, so the rule is a pass-through — same compat-shim idiom as
# the shard_map shim in parallel/sharded_engine.py. Falls back to a plain
# identity (no pinning, only a fusion-dedup pessimization) if the internal
# primitive moves.
def _install_barrier_batching():
    try:
        from jax._src.lax import lax as _lax_impl
        from jax.interpreters import batching
        prim = getattr(_lax_impl, "optimization_barrier_p", None)
        if prim is None or prim in batching.primitive_batchers:
            return prim is not None
        batching.primitive_batchers[prim] = (
            lambda args, dims: (prim.bind(*args), list(dims)))
        return True
    except Exception:  # pragma: no cover - exercised on future jax layouts
        return False


_HAVE_BARRIER = _install_barrier_batching()


def pin(*xs):
    """Materialize shared SoA-op intermediates exactly once.

    A per-field SoA op hands the same mask/rank computation (a one-hot, a
    cumsum, a live-prefix compare) to NF independent per-leaf consumers;
    XLA's fuser classifies those producers as cheap and DUPLICATES them
    into every consumer fusion — NF recomputations of the same [Q]/[S]
    intermediate (a measured ~40% on the whole tick's bytes accessed).
    ``optimization_barrier`` pins the values as materialized buffers the
    consumers share. Only used in the SoA paths: the wide layout has a
    single consumer per op, so there is nothing to deduplicate."""
    if not _HAVE_BARRIER:
        return xs if len(xs) > 1 else xs[0]
    out = jax.lax.optimization_barrier(xs)
    return out if len(xs) > 1 else out[0]


def widen(leaf: jax.Array) -> jax.Array:
    """Load a storage leaf for compute: everything is int32 arithmetic, so
    results are bit-identical to the wide layout (a no-op for i32 leaves —
    XLA folds the convert)."""
    return leaf.astype(jnp.int32)


def narrow_store(values: jax.Array, dtype, do=None, checked: bool = True):
    """Checked narrow of int32 compute values into storage dtype ``dtype``.

    Returns ``(stored, n_overflow)``: out-of-range values are clamped to the
    dtype minimum (a deterministic poison, never a silent wrap) and counted
    — but only where ``do`` (the store-actually-happens mask; None = all
    lanes). Callers accumulate ``n_overflow`` into the layout's ``ovf``
    counter, which parity and bench runs assert stays zero; a nonzero value
    means the storage plan (core/compact.py) under-sized a field and the
    run's results can no longer claim bit-equality with the wide layout.

    ``checked=False`` elides the range compare (overflow count is zero by
    construction) and is ONLY legal for values whose in-range-ness is
    provable, not assumed: permutations of already-stored leaf values, or
    moves from a checked storage leaf whose plan bound is covered by the
    destination's (the plan derives both row kinds from the same bounds
    table, so queue->runset moves qualify — core/compact.derive_plan).
    Every range-checking obligation stays at the system's value ENTRY
    points (arrival ingest, host job injection, market carve), which all
    pass ``checked=True``; the boundary fuzz tests pin that the counter
    fires there (tests/test_fuzz_parity.py).

    For int32 ``dtype`` this is a free passthrough: nothing can be out of
    range, so no compare is emitted.
    """
    dtype = np.dtype(dtype)
    if not checked or dtype.itemsize >= WIDE_DTYPE.itemsize:
        return values.astype(dtype), jnp.int32(0)
    info = np.iinfo(dtype)
    fits = jnp.logical_and(values >= info.min, values <= info.max)
    bad = jnp.logical_not(fits)
    bad = bad if do is None else jnp.logical_and(bad, do)
    stored = jnp.where(fits, values, info.min).astype(dtype)
    return stored, jnp.sum(bad).astype(jnp.int32)
