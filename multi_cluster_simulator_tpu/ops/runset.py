"""Running-job occupancy set — wide (AoS) and compact (SoA) forms.

The reference simulates execution with one goroutine per running job:
decrement node counters, ``time.Sleep(j.Duration)``, increment them back,
notify the scheduler (Node.RunJob, pkg/scheduler/cluster.go:141-161). Here a
running job is a row in a packed table carrying its end time on the
virtual clock; completion is a masked scatter-add back into the free tensor —
no goroutines, no sleeps, and completion notification (JobFinished,
scheduler.go:158-191) is a mask the engine consumes.

Like the job queues (ops/queues.py), the set exists in two bit-identical
layouts: the wide ``RunningSet`` (one int32 ``data[S, RF]`` tensor) and the
compact ``SoARunningSet`` (per-field leaves with range-audited storage
dtypes from core/compact.py — the [S]-sized set is the largest per-cluster
tensor in the headline shape, so its bytes dominate the memory-bound tick).
All arithmetic is int32 on widened loads; narrowing stores ride the checked
``fields.narrow_store`` helper and count overflows into ``ovf``. The row
schema (order + invalid sentinels) is ops/fields.RUN_FIELDS — one site
shared with the queue schema and the storage planner.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops.queues import JobRec

NEVER = jnp.int32(F.NEVER_I)

# packed row layout, derived from the canonical schema (ops/fields.py)
RF = len(F.RUN_FIELDS)
REND, RNODE, RCORES, RMEM, RGPU, RID, ROWNER, RDUR, RENQ, RRETRIES = (
    F.RUN_INDEX[n] for n in F.RUN_FIELDS)

_INVALID_ROW = jnp.array(F.RUN_INVALID, jnp.int32)


@struct.dataclass
class RunningSet:
    data: jax.Array  # [S, RF] int32
    active: jax.Array  # [S] bool

    @property
    def capacity(self) -> int:
        return self.active.shape[-1]

    @property
    def end_t(self):
        return self.data[..., REND]

    @property
    def node(self):
        return self.data[..., RNODE]

    @property
    def cores(self):
        return self.data[..., RCORES]

    @property
    def mem(self):
        return self.data[..., RMEM]

    @property
    def gpu(self):
        return self.data[..., RGPU]

    @property
    def id(self):
        return self.data[..., RID]

    @property
    def owner(self):
        return self.data[..., ROWNER]

    @property
    def dur(self):
        return self.data[..., RDUR]

    @property
    def enq_t(self):
        return self.data[..., RENQ]

    @property
    def retries(self):
        return self.data[..., RRETRIES]


@struct.dataclass
class SoARunningSet:
    """Compact layout: per-field leaves (``f_<name>``, storage dtypes from a
    CompactPlan) + the checked-narrow overflow counter ``ovf``. The widened
    accessors keep the wide layout's property API — readers always get
    int32. Stores into ``f_*`` leaves must go through
    ``fields.narrow_store`` (simlint: compact-store)."""

    f_end_t: jax.Array  # [S]
    f_node: jax.Array
    f_cores: jax.Array
    f_mem: jax.Array
    f_gpu: jax.Array
    f_id: jax.Array
    f_owner: jax.Array
    f_dur: jax.Array
    f_enq_t: jax.Array
    f_retries: jax.Array
    active: jax.Array  # [S] bool
    ovf: jax.Array  # [] int32

    @property
    def capacity(self) -> int:
        return self.active.shape[-1]

    @property
    def end_t(self):
        return F.widen(self.f_end_t)

    @property
    def node(self):
        return F.widen(self.f_node)

    @property
    def cores(self):
        return F.widen(self.f_cores)

    @property
    def mem(self):
        return F.widen(self.f_mem)

    @property
    def gpu(self):
        return F.widen(self.f_gpu)

    @property
    def id(self):
        return F.widen(self.f_id)

    @property
    def owner(self):
        return F.widen(self.f_owner)

    @property
    def dur(self):
        return F.widen(self.f_dur)

    @property
    def enq_t(self):
        return F.widen(self.f_enq_t)

    @property
    def retries(self):
        return F.widen(self.f_retries)


def _leaf(rs: SoARunningSet, name: str) -> jax.Array:
    return getattr(rs, "f_" + name)


def _invalid(name: str, dtype) -> jax.Array:
    return jnp.asarray(F.RUN_INVALID[F.RUN_INDEX[name]], dtype)


def empty(capacity: int) -> RunningSet:
    return RunningSet(
        data=jnp.broadcast_to(_INVALID_ROW, (capacity, RF)).copy(),
        active=jnp.zeros((capacity,), bool))


def empty_soa(capacity: int, dtypes: dict) -> SoARunningSet:
    """Compact-layout empty set; ``dtypes`` maps field name -> storage dtype
    (CompactPlan.run_dtypes())."""
    leaves = {
        "f_" + n: jnp.full((capacity,), F.RUN_INVALID[i], dtypes[n])
        for i, n in enumerate(F.RUN_FIELDS)}
    return SoARunningSet(active=jnp.zeros((capacity,), bool),
                         ovf=jnp.int32(0), **leaves)


def soa_to_wide(rs: SoARunningSet) -> RunningSet:
    """Canonicalize to the wide layout (widen + restack; batched leaves ok).
    ``ovf`` is dropped — assert it zero separately."""
    data = jnp.stack([F.widen(_leaf(rs, n)) for n in F.RUN_FIELDS], axis=-1)
    return RunningSet(data=data, active=rs.active)


def gather_rows_along(rs, order: jax.Array) -> jax.Array:
    """[..., M, RF] int32 rows selected along the slot axis by ``order``
    [..., M] (batched; the finished-foreign message pack,
    engine._pack_returns)."""
    if isinstance(rs, SoARunningSet):
        return jnp.stack(
            [jnp.take_along_axis(F.widen(_leaf(rs, n)), order, axis=-1)
             for n in F.RUN_FIELDS], axis=-1)
    return jnp.take_along_axis(rs.data, order[..., None], axis=-2)


def make_row(end_t, node, cores, mem, gpu, id, owner, dur, enq_t,
             retries=0) -> jax.Array:
    parts = [end_t, node, cores, mem, gpu, id, owner, dur, enq_t, retries]
    return jnp.stack([jnp.asarray(p, jnp.int32) for p in parts], axis=-1)


def row_from_job(job: JobRec, node, t) -> jax.Array:
    return make_row(t + job.dur, node, job.cores, job.mem, job.gpu, job.id,
                    job.owner, job.dur, job.enq_t, job.retries)


def insert_row(rs, hot: jax.Array, row: jax.Array):
    """Write one packed int32 ``row`` into the slots where ``hot`` [S] is
    set (one-hot in practice) and mark them active. One-hot select, not
    scatter — scatters serialize on TPU. The generic single-row insert
    shared by ``start``, the market's Foreign-placeholder carve
    (market/trader.py), and the live host's carve path
    (services/host_ops.py)."""
    if isinstance(rs, SoARunningSet):
        hot = F.pin(hot)
        do = jnp.any(hot)
        new, bad = {}, rs.ovf
        for n in F.RUN_FIELDS:
            leaf = _leaf(rs, n)
            stored, nbad = F.narrow_store(row[..., F.RUN_INDEX[n]],
                                          leaf.dtype, do=do)
            new[n] = jnp.where(hot, stored, leaf)
            bad = bad + nbad
        return rs.replace(active=jnp.logical_or(rs.active, hot),
                          ovf=bad, **{"f_" + n: v for n, v in new.items()})
    return RunningSet(data=jnp.where(hot[:, None], row, rs.data),
                      active=jnp.logical_or(rs.active, hot))


def start(rs, job: JobRec, node: jax.Array, t: jax.Array, do: jax.Array):
    """Occupy the first free slot with a newly placed job (end = t + dur)."""
    slot = jnp.argmin(rs.active).astype(jnp.int32)  # first inactive slot
    ok = jnp.logical_and(do, jnp.logical_not(rs.active[slot]))
    row = row_from_job(job, node, t)
    hot = jnp.logical_and(
        jnp.arange(rs.capacity, dtype=jnp.int32) == slot, ok)  # [S]
    return insert_row(rs, hot, row)


def start_many(rs, rows: jax.Array, n_take: jax.Array):
    """Batch-insert ``rows[:n_take]`` (insertion order) into the lowest
    inactive slots, ascending — the exact slot layout a sequence of
    ``start`` calls produces, at one [S, M] contraction instead of M
    argmin+one-hot passes over the set. Callers guarantee
    ``n_take <= free slots`` (the sweep's has-slot check).

    This is what makes wide placement sweeps affordable at scale: the
    per-iteration work inside the sweep loop shrinks to a row write into a
    [M, RF] buffer, and the [S]-sized set is touched once per tick."""
    # free_rank[s] = how many inactive slots precede s (valid where inactive)
    inactive = jnp.logical_not(rs.active)
    free_rank = jnp.cumsum(inactive.astype(jnp.int32)) - 1
    M = rows.shape[0]
    j = jnp.arange(M, dtype=jnp.int32)
    hot = jnp.logical_and(
        jnp.logical_and(free_rank[:, None] == j[None, :], inactive[:, None]),
        (j < n_take)[None, :])  # [S, M]
    written = jnp.any(hot, axis=1)
    if isinstance(rs, SoARunningSet):
        # Narrowing here is checked=False by provenance: every narrowable
        # column of a runset row comes from a checked queue leaf
        # (row_from_job copies the job's fields) or a config-bounded index
        # (first_fit node < total_nodes), and the plan derives the runset
        # bounds from the same table as the queue bounds — nothing fresh
        # enters the system at this site (fields.narrow_store docstring).
        new, bad = {}, rs.ovf
        if M == 1:
            # single-row insert (the _attempt head-placement path): scalar
            # broadcast stores — the [S, RF] outer-product form below is
            # "cheap" to XLA's fuser, which duplicates it into every
            # per-field consumer (a measured ~9x on this op's bytes)
            hot1 = F.pin(hot[:, 0])
            for n in F.RUN_FIELDS:
                leaf = _leaf(rs, n)
                stored, nbad = F.narrow_store(rows[0, F.RUN_INDEX[n]],
                                              leaf.dtype, checked=False)
                new[n] = jnp.where(hot1, stored, leaf)
                bad = bad + nbad
        else:
            # ONE one-hot matmul in wide int32 (compute), then each column
            # narrows into its leaf — a per-field contraction would
            # re-materialize the [S, M] one-hot RF times
            packed = hot.astype(rows.dtype) @ rows  # [S, RF]
            written = F.pin(written)
            for n in F.RUN_FIELDS:
                leaf = _leaf(rs, n)
                stored, nbad = F.narrow_store(packed[:, F.RUN_INDEX[n]],
                                              leaf.dtype, do=written,
                                              checked=False)
                new[n] = jnp.where(written, stored, leaf)
                bad = bad + nbad
        return rs.replace(active=jnp.logical_or(rs.active, written),
                          ovf=bad, **{"f_" + n: v for n, v in new.items()})
    data = jnp.where(written[:, None], hot.astype(rows.dtype) @ rows, rs.data)
    return RunningSet(data=data, active=jnp.logical_or(rs.active, written))


def next_end_t(rs) -> jax.Array:
    """Earliest completion time in the set (NEVER when empty) — the
    min-``end_t`` probe the event-compressed driver folds into its
    next-event time (core/engine.py _next_event_t): no release can fire
    before the first tick whose clock reaches this value."""
    return jnp.min(jnp.where(rs.active, rs.end_t, NEVER))


def kill(rs, dead: jax.Array):
    """Clear the slots where ``dead`` [S] is set WITHOUT returning their
    resources to the free tensor — the fault plane's removal half
    (faults/apply.py): a killed job's node just lost its whole capacity to
    the failure, so there is nothing to return; repair restores
    ``free = cap`` on an empty node. Same slot-clearing discipline as
    ``release``."""
    dead = jnp.logical_and(rs.active, dead)
    if isinstance(rs, SoARunningSet):
        dead = F.pin(dead)
        new = {("f_" + n): jnp.where(dead, _invalid(n, _leaf(rs, n).dtype),
                                     _leaf(rs, n))
               for n in F.RUN_FIELDS}
        return rs.replace(active=jnp.logical_and(rs.active,
                                                 jnp.logical_not(dead)),
                          **new)
    return RunningSet(
        data=jnp.where(dead[:, None], _INVALID_ROW, rs.data),
        active=jnp.logical_and(rs.active, jnp.logical_not(dead)))


def release(rs, free: jax.Array, t: jax.Array):
    """Complete all jobs with ``end_t <= t``: return their resources to
    ``free`` (RunJob's increment half, cluster.go:153-157) and clear slots.

    Returns (rs', free', done_mask) — ``done_mask`` over slots is the
    JobFinished notification; the engine uses it for lent-job returns.
    """
    done = jnp.logical_and(rs.active, rs.end_t <= t)
    n_nodes = free.shape[0]
    node_idx = jnp.clip(rs.node, 0, n_nodes - 1)
    if isinstance(rs, SoARunningSet):
        res = jnp.stack([rs.cores, rs.mem, rs.gpu],
                        axis=-1)[:, : free.shape[-1]]
    else:
        res = rs.data[:, RCORES:RCORES + free.shape[-1]]
    back = jnp.where(done[:, None], res, 0)
    # scatter-add as a one-hot contraction (scatters serialize on TPU)
    hot = (node_idx[:, None] == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
    free = free + jnp.einsum("sn,sr->nr", hot.astype(back.dtype), back)
    if isinstance(rs, SoARunningSet):
        done = F.pin(done)
        new = {("f_" + n): jnp.where(done, _invalid(n, _leaf(rs, n).dtype),
                                     _leaf(rs, n))
               for n in F.RUN_FIELDS}
        rs = rs.replace(active=jnp.logical_and(rs.active,
                                               jnp.logical_not(done)), **new)
        return rs, free, done
    rs = RunningSet(
        data=jnp.where(done[:, None], _INVALID_ROW, rs.data),
        active=jnp.logical_and(rs.active, jnp.logical_not(done)))
    return rs, free, done
