"""Running-job occupancy set.

The reference simulates execution with one goroutine per running job:
decrement node counters, ``time.Sleep(j.Duration)``, increment them back,
notify the scheduler (Node.RunJob, pkg/scheduler/cluster.go:141-161). Here a
running job is a slot in a fixed-size table carrying its end time on the
virtual clock; completion is a masked scatter-add back into the free tensor —
no goroutines, no sleeps, and completion notification (JobFinished,
scheduler.go:158-191) is a mask the engine consumes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.ops.queues import INVALID_ID, OWN, JobRec

NEVER = jnp.int32(2**31 - 1)


@struct.dataclass
class RunningSet:
    end_t: jax.Array  # [S] int32 ms; NEVER when slot inactive
    node: jax.Array  # [S] int32 node index
    cores: jax.Array  # [S] int32
    mem: jax.Array  # [S] int32
    id: jax.Array  # [S] int32 job id
    owner: jax.Array  # [S] int32 (OWN = my job; else borrower cluster)
    dur: jax.Array  # [S] int32 (kept for the lent-return message)
    enq_t: jax.Array  # [S] int32
    active: jax.Array  # [S] bool

    @property
    def capacity(self) -> int:
        return self.active.shape[-1]


def empty(capacity: int) -> RunningSet:
    z = jnp.zeros((capacity,), jnp.int32)
    return RunningSet(
        end_t=jnp.full((capacity,), NEVER, jnp.int32),
        node=z,
        cores=z,
        mem=z,
        id=jnp.full((capacity,), INVALID_ID, jnp.int32),
        owner=jnp.full((capacity,), OWN, jnp.int32),
        dur=z,
        enq_t=z,
        active=jnp.zeros((capacity,), bool),
    )


def start(rs: RunningSet, job: JobRec, node: jax.Array, t: jax.Array, do: jax.Array) -> RunningSet:
    """Occupy the first free slot with a newly placed job (end = t + dur)."""
    slot = jnp.argmin(rs.active).astype(jnp.int32)  # first inactive slot
    ok = jnp.logical_and(do, jnp.logical_not(rs.active[slot]))

    def w(a, v):
        return a.at[slot].set(jnp.where(ok, v, a[slot]))

    return RunningSet(
        end_t=w(rs.end_t, t + job.dur),
        node=w(rs.node, node),
        cores=w(rs.cores, job.cores),
        mem=w(rs.mem, job.mem),
        id=w(rs.id, job.id),
        owner=w(rs.owner, job.owner),
        dur=w(rs.dur, job.dur),
        enq_t=w(rs.enq_t, job.enq_t),
        active=w(rs.active, ok),
    )


def release(rs: RunningSet, free: jax.Array, t: jax.Array):
    """Complete all jobs with ``end_t <= t``: return their resources to
    ``free`` (RunJob's increment half, cluster.go:153-157) and clear slots.

    Returns (rs', free', done_mask) — ``done_mask`` over slots is the
    JobFinished notification; the engine uses it for lent-job returns.
    """
    done = jnp.logical_and(rs.active, rs.end_t <= t)
    n_nodes = free.shape[0]
    node_idx = jnp.clip(rs.node, 0, n_nodes - 1)
    dc = jax.ops.segment_sum(jnp.where(done, rs.cores, 0), node_idx, num_segments=n_nodes)
    dm = jax.ops.segment_sum(jnp.where(done, rs.mem, 0), node_idx, num_segments=n_nodes)
    free = free.at[:, 0].add(dc).at[:, 1].add(dm)
    rs = rs.replace(
        end_t=jnp.where(done, NEVER, rs.end_t),
        id=jnp.where(done, INVALID_ID, rs.id),
        owner=jnp.where(done, OWN, rs.owner),
        active=jnp.logical_and(rs.active, jnp.logical_not(done)),
    )
    return rs, free, done
