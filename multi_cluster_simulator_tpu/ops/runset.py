"""Running-job occupancy set.

The reference simulates execution with one goroutine per running job:
decrement node counters, ``time.Sleep(j.Duration)``, increment them back,
notify the scheduler (Node.RunJob, pkg/scheduler/cluster.go:141-161). Here a
running job is a row in one packed int32 table carrying its end time on the
virtual clock; completion is a masked scatter-add back into the free tensor —
no goroutines, no sleeps, and completion notification (JobFinished,
scheduler.go:158-191) is a mask the engine consumes. Packed rows keep the
per-tick op count low (see ops/queues.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.ops.queues import JobRec

NEVER = jnp.int32(2**31 - 1)

# packed row layout; (cores, mem, gpu) contiguous, ordered like spec.RES
RF = 9
REND, RNODE, RCORES, RMEM, RGPU, RID, ROWNER, RDUR, RENQ = range(RF)

_INVALID_ROW = jnp.array([NEVER, 0, 0, 0, 0, -1, -1, 0, 0], jnp.int32)


@struct.dataclass
class RunningSet:
    data: jax.Array  # [S, RF] int32
    active: jax.Array  # [S] bool

    @property
    def capacity(self) -> int:
        return self.active.shape[-1]

    @property
    def end_t(self):
        return self.data[..., REND]

    @property
    def node(self):
        return self.data[..., RNODE]

    @property
    def cores(self):
        return self.data[..., RCORES]

    @property
    def mem(self):
        return self.data[..., RMEM]

    @property
    def gpu(self):
        return self.data[..., RGPU]

    @property
    def id(self):
        return self.data[..., RID]

    @property
    def owner(self):
        return self.data[..., ROWNER]

    @property
    def dur(self):
        return self.data[..., RDUR]

    @property
    def enq_t(self):
        return self.data[..., RENQ]


def empty(capacity: int) -> RunningSet:
    return RunningSet(
        data=jnp.broadcast_to(_INVALID_ROW, (capacity, RF)).copy(),
        active=jnp.zeros((capacity,), bool))


def make_row(end_t, node, cores, mem, gpu, id, owner, dur, enq_t) -> jax.Array:
    parts = [end_t, node, cores, mem, gpu, id, owner, dur, enq_t]
    return jnp.stack([jnp.asarray(p, jnp.int32) for p in parts], axis=-1)


def row_from_job(job: JobRec, node, t) -> jax.Array:
    return make_row(t + job.dur, node, job.cores, job.mem, job.gpu, job.id,
                    job.owner, job.dur, job.enq_t)


def start(rs: RunningSet, job: JobRec, node: jax.Array, t: jax.Array, do: jax.Array) -> RunningSet:
    """Occupy the first free slot with a newly placed job (end = t + dur).

    The slot write is a one-hot select, not a scatter — scatters serialize
    on TPU and this runs once per placement-sweep step."""
    slot = jnp.argmin(rs.active).astype(jnp.int32)  # first inactive slot
    ok = jnp.logical_and(do, jnp.logical_not(rs.active[slot]))
    row = row_from_job(job, node, t)
    hot = jnp.logical_and(
        jnp.arange(rs.capacity, dtype=jnp.int32) == slot, ok)  # [S]
    data = jnp.where(hot[:, None], row, rs.data)
    active = jnp.logical_or(rs.active, hot)
    return RunningSet(data=data, active=active)


def start_many(rs: RunningSet, rows: jax.Array, n_take: jax.Array) -> RunningSet:
    """Batch-insert ``rows[:n_take]`` (insertion order) into the lowest
    inactive slots, ascending — the exact slot layout a sequence of
    ``start`` calls produces, at one [S, M] contraction instead of M
    argmin+one-hot passes over the set. Callers guarantee
    ``n_take <= free slots`` (the sweep's has-slot check).

    This is what makes wide placement sweeps affordable at scale: the
    per-iteration work inside the sweep loop shrinks to a row write into a
    [M, RF] buffer, and the [S]-sized set is touched once per tick."""
    # free_rank[s] = how many inactive slots precede s (valid where inactive)
    inactive = jnp.logical_not(rs.active)
    free_rank = jnp.cumsum(inactive.astype(jnp.int32)) - 1
    M = rows.shape[0]
    j = jnp.arange(M, dtype=jnp.int32)
    hot = jnp.logical_and(
        jnp.logical_and(free_rank[:, None] == j[None, :], inactive[:, None]),
        (j < n_take)[None, :])  # [S, M]
    written = jnp.any(hot, axis=1)
    data = jnp.where(written[:, None], hot.astype(rows.dtype) @ rows, rs.data)
    return RunningSet(data=data, active=jnp.logical_or(rs.active, written))


def next_end_t(rs: RunningSet) -> jax.Array:
    """Earliest completion time in the set (NEVER when empty) — the
    min-``end_t`` probe the event-compressed driver folds into its
    next-event time (core/engine.py _next_event_t): no release can fire
    before the first tick whose clock reaches this value."""
    return jnp.min(jnp.where(rs.active, rs.end_t, NEVER))


def release(rs: RunningSet, free: jax.Array, t: jax.Array):
    """Complete all jobs with ``end_t <= t``: return their resources to
    ``free`` (RunJob's increment half, cluster.go:153-157) and clear slots.

    Returns (rs', free', done_mask) — ``done_mask`` over slots is the
    JobFinished notification; the engine uses it for lent-job returns.
    """
    done = jnp.logical_and(rs.active, rs.end_t <= t)
    n_nodes = free.shape[0]
    node_idx = jnp.clip(rs.node, 0, n_nodes - 1)
    back = jnp.where(done[:, None], rs.data[:, RCORES:RCORES + free.shape[-1]], 0)
    # scatter-add as a one-hot contraction (scatters serialize on TPU)
    hot = (node_idx[:, None] == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
    free = free + jnp.einsum("sn,sr->nr", hot.astype(back.dtype), back)
    rs = RunningSet(
        data=jnp.where(done[:, None], _INVALID_ROW, rs.data),
        active=jnp.logical_and(rs.active, jnp.logical_not(done)))
    return rs, free, done
