"""Padded, mask-disciplined job queues.

The reference keeps six mutex-guarded Go slices per scheduler (ReadyQueue,
WaitQueue, LentQueue, BorrowedQueue, Level0, Level1 —
pkg/scheduler/scheduler.go:19-30). Here a queue is a struct-of-arrays pytree
with a scalar ``count``: valid entries occupy slots ``[0, count)`` in FIFO
order, so "head" is slot 0 and append writes at slot ``count``. All ops are
pure, static-shape, and written for a single cluster — the engine ``vmap``s
them over the cluster axis.

Job fields mirror the reference's ``Job`` struct (scheduler.go:65-73):
id, cores, mem, duration, enqueue-time (``WaitTime time.Time``), owner
(``Ownership string`` — here the borrower's cluster index, -1 for "my own
job"), plus ``rec_wait``, the last wait recorded in the scheduler's
``WaitTime.JobsMap`` (scheduler.go:48-63).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

INVALID_ID = jnp.int32(-1)
OWN = jnp.int32(-1)  # owner value for "my own job" (Ownership == "")


@struct.dataclass
class JobQueue:
    id: jax.Array  # [Q] int32; INVALID_ID in empty slots
    cores: jax.Array  # [Q] int32
    mem: jax.Array  # [Q] int32
    dur: jax.Array  # [Q] int32 (ms)
    enq_t: jax.Array  # [Q] int32 (ms, virtual clock)
    owner: jax.Array  # [Q] int32 (borrower cluster index; OWN = mine)
    rec_wait: jax.Array  # [Q] int32 (ms, last JobsMap record)
    count: jax.Array  # [] int32

    @property
    def capacity(self) -> int:
        return self.id.shape[-1]

    def slot_valid(self) -> jax.Array:
        """[Q] bool — which slots hold live jobs."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count


@struct.dataclass
class JobRec:
    """A single job as a pytree of scalars (one row of a JobQueue)."""

    id: jax.Array
    cores: jax.Array
    mem: jax.Array
    dur: jax.Array
    enq_t: jax.Array
    owner: jax.Array
    rec_wait: jax.Array

    @staticmethod
    def invalid() -> "JobRec":
        z = jnp.int32(0)
        return JobRec(id=INVALID_ID, cores=z, mem=z, dur=z, enq_t=z, owner=OWN, rec_wait=z)


_FIELDS = ("id", "cores", "mem", "dur", "enq_t", "owner", "rec_wait")


def empty(capacity: int) -> JobQueue:
    z = jnp.zeros((capacity,), jnp.int32)
    return JobQueue(
        id=jnp.full((capacity,), INVALID_ID, jnp.int32),
        cores=z,
        mem=z,
        dur=z,
        enq_t=z,
        owner=jnp.full((capacity,), OWN, jnp.int32),
        rec_wait=z,
        count=jnp.int32(0),
    )


def get(q: JobQueue, i: Any) -> JobRec:
    return JobRec(**{f: getattr(q, f)[i] for f in _FIELDS})


def head(q: JobQueue) -> JobRec:
    return get(q, 0)


def push_back(q: JobQueue, job: JobRec, do: jax.Array) -> JobQueue:
    """Append one job if ``do`` (and capacity allows)."""
    ok = jnp.logical_and(do, q.count < q.capacity)
    idx = jnp.clip(q.count, 0, q.capacity - 1)
    new = {
        f: getattr(q, f).at[idx].set(
            jnp.where(ok, getattr(job, f), getattr(q, f)[idx])
        )
        for f in _FIELDS
    }
    return q.replace(count=q.count + ok.astype(jnp.int32), **new)


def push_many(q: JobQueue, jobs: JobQueue, take: jax.Array) -> JobQueue:
    """Append all rows of ``jobs`` where ``take`` is set, preserving order.

    ``take`` is a [Qj] bool mask over ``jobs`` slots. Overflowing entries are
    dropped (sized configs should make this impossible).
    """
    order = jnp.argsort(jnp.logical_not(take), stable=True)  # taken rows first
    n_take = jnp.sum(take).astype(jnp.int32)
    dst = q.count + jnp.arange(jobs.capacity, dtype=jnp.int32)  # dst for k-th taken
    ok = jnp.logical_and(jnp.arange(jobs.capacity) < n_take, dst < q.capacity)
    dst = jnp.where(ok, dst, q.capacity)  # out-of-range writes are dropped
    new = {}
    for f in _FIELDS:
        src = getattr(jobs, f)[order]
        new[f] = getattr(q, f).at[dst].set(src, mode="drop")
    added = jnp.minimum(n_take, q.capacity - q.count)
    return q.replace(count=q.count + added, **new)


def pop_front(q: JobQueue, do: jax.Array) -> JobQueue:
    """Drop the head job if ``do`` (FIFO pop), shifting everything left."""
    inv = empty(1)
    new = {}
    for f in _FIELDS:
        a = getattr(q, f)
        shifted = jnp.roll(a, -1).at[-1].set(getattr(inv, f)[0])
        new[f] = jnp.where(do, shifted, a)
    n = jnp.maximum(q.count - do.astype(jnp.int32), 0)
    return q.replace(count=n, **new)


def compact(q: JobQueue, keep: jax.Array) -> JobQueue:
    """Stable-remove all valid slots where ``keep`` is False.

    This is the tensor analogue of the Go in-place slice deletions
    (scheduler.go:319,165,184). ``keep`` is evaluated on valid slots only.
    """
    keep = jnp.logical_and(keep, q.slot_valid())
    drop = jnp.logical_not(keep)
    order = jnp.argsort(drop, stable=True)  # kept rows first, stable
    n_keep = jnp.sum(keep).astype(jnp.int32)
    live = jnp.arange(q.capacity, dtype=jnp.int32) < n_keep
    inv = JobRec.invalid()
    new = {}
    for f in _FIELDS:
        a = getattr(q, f)[order]
        new[f] = jnp.where(live, a, getattr(inv, f))
    return q.replace(count=n_keep, **new)


def remove_matching(q: JobQueue, job: JobRec, match_fields=("id", "cores", "mem", "dur")) -> JobQueue:
    """Remove entries equal to ``job`` on the given fields.

    Mirrors the reference's whole-struct-equality dequeues
    (``if j == sched.BorrowedQueue[i]``, server.go:131-135, scheduler.go:164,
    172, 184). Matching on (id, cores, mem, dur) is the documented
    determinization — the Go structs also compare State/WaitTime/Ownership,
    which survive the borrow round-trip unchanged.
    """
    m = jnp.ones((q.capacity,), bool)
    for f in match_fields:
        m = jnp.logical_and(m, getattr(q, f) == getattr(job, f))
    return compact(q, jnp.logical_not(m))
