"""Padded, mask-disciplined job queues — wide (AoS) and compact (SoA) forms.

The reference keeps six mutex-guarded Go slices per scheduler (ReadyQueue,
WaitQueue, LentQueue, BorrowedQueue, Level0, Level1 —
pkg/scheduler/scheduler.go:19-30). Here a queue is either:

- ``JobQueue`` (wide): ONE packed int32 tensor ``data[Q, NF]`` plus a scalar
  ``count`` — valid entries occupy rows ``[0, count)`` in FIFO order, so
  "head" is row 0 and append writes row ``count``. The packed layout keeps
  the per-op dispatch count low, which was the tick-loop cost at 4k
  clusters before the tick became memory-bound.
- ``SoAJobQueue`` (compact): the same queue split into per-field leaves
  with range-audited storage dtypes (core/compact.py), so a phase that
  reads only ``enq_t`` streams one narrow column instead of eight int32
  ones — the bytes/tick lever for the memory-bound headline
  (ARCHITECTURE.md §state layout). All arithmetic stays int32 (leaves are
  widened on load); every narrowing store goes through the checked
  ``fields.narrow_store`` helper, which counts out-of-range values into
  ``ovf`` instead of wrapping.

Every module-level op below accepts either layout (the engine is
layout-blind); the two layouts are bit-identical in results by
construction — integer ops on widened values match the wide ops exactly
(tests/test_compact.py pins it across the parity matrix).

Row fields mirror the reference's ``Job`` struct (scheduler.go:65-73):
id, cores, mem, duration, enqueue-time (``WaitTime time.Time``), owner
(``Ownership string`` — here the borrower's cluster index, -1 for "my own
job"), plus ``rec_wait``, the last wait recorded in the scheduler's
``WaitTime.JobsMap`` (scheduler.go:48-63). The canonical field order /
invalid sentinels live in ops/fields.py — one site shared with the engine's
arrival pack paths and the storage planner.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.ops import fields as F

INVALID_ID = jnp.int32(-1)
OWN = jnp.int32(-1)  # owner value for "my own job" (Ownership == "")

# packed row layout, derived from the canonical schema (ops/fields.py)
NF = len(F.QUEUE_FIELDS)
(FID, FCORES, FMEM, FGPU, FDUR, FENQ, FOWNER, FREC, FJCLASS, FRETRIES) = (
    F.QUEUE_INDEX[n]
    for n in ("id", "cores", "mem", "gpu", "dur", "enq_t", "owner",
              "rec_wait", "jclass", "retries"))
_FIDX = dict(F.QUEUE_INDEX)

_INVALID_ROW = jnp.array(F.QUEUE_INVALID, jnp.int32)


@struct.dataclass
class JobRec:
    """A single job: one packed [NF] int32 row (both layouts hand jobs
    around in this wide form — it is compute, not storage)."""

    vec: jax.Array

    @property
    def id(self):
        return self.vec[..., FID]

    @property
    def cores(self):
        return self.vec[..., FCORES]

    @property
    def mem(self):
        return self.vec[..., FMEM]

    @property
    def gpu(self):
        return self.vec[..., FGPU]

    @property
    def dur(self):
        return self.vec[..., FDUR]

    @property
    def enq_t(self):
        return self.vec[..., FENQ]

    @property
    def owner(self):
        return self.vec[..., FOWNER]

    @property
    def rec_wait(self):
        return self.vec[..., FREC]

    @property
    def jclass(self):
        return self.vec[..., FJCLASS]

    @property
    def retries(self):
        return self.vec[..., FRETRIES]

    @property
    def res(self):
        """[..., RES] (cores, mem, gpu) — matches the node free/cap layout."""
        return self.vec[..., FCORES:FGPU + 1]

    @staticmethod
    def make(id=-1, cores=0, mem=0, gpu=0, dur=0, enq_t=0, owner=OWN,
             rec_wait=0, jclass=None, retries=0) -> "JobRec":
        if jclass is None:
            jclass = F.job_class(jnp.asarray(cores), jnp.asarray(gpu))
        parts = [id, cores, mem, gpu, dur, enq_t, owner, rec_wait, jclass,
                 retries]
        return JobRec(vec=jnp.stack([jnp.asarray(p, jnp.int32) for p in parts], axis=-1))

    @staticmethod
    def invalid() -> "JobRec":
        return JobRec(vec=_INVALID_ROW)

    def with_(self, **kw) -> "JobRec":
        vec = self.vec
        for name, val in kw.items():
            vec = vec.at[..., _FIDX[name]].set(jnp.asarray(val, jnp.int32))
        return JobRec(vec=vec)


@struct.dataclass
class JobQueue:
    data: jax.Array  # [Q, NF] int32
    count: jax.Array  # [] int32

    @property
    def capacity(self) -> int:
        return self.data.shape[-2]

    # field views (each is one slice op — use sparingly in hot loops)
    @property
    def id(self):
        return self.data[..., FID]

    @property
    def cores(self):
        return self.data[..., FCORES]

    @property
    def mem(self):
        return self.data[..., FMEM]

    @property
    def gpu(self):
        return self.data[..., FGPU]

    @property
    def dur(self):
        return self.data[..., FDUR]

    @property
    def enq_t(self):
        return self.data[..., FENQ]

    @property
    def owner(self):
        return self.data[..., FOWNER]

    @property
    def rec_wait(self):
        return self.data[..., FREC]

    @property
    def jclass(self):
        return self.data[..., FJCLASS]

    @property
    def retries(self):
        return self.data[..., FRETRIES]

    def slot_valid(self) -> jax.Array:
        """[Q] bool — which slots hold live jobs."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count


@struct.dataclass
class SoAJobQueue:
    """The compact layout: one leaf per field, storage dtypes from a
    ``CompactPlan`` (core/compact.py), plus the narrow-store overflow
    counter ``ovf`` (a ``Drops``-style surface-don't-swallow counter —
    parity and bench runs assert it stays zero).

    Leaves are named ``f_<field>`` (not the field name itself) so the
    widened accessors below can keep the wide layout's property API: code
    reading ``q.cores`` always gets int32 compute values, whatever the
    storage width. Direct stores into ``f_*`` leaves must go through
    ``fields.narrow_store`` — simlint's ``compact-store`` rule flags
    bypasses."""

    f_id: jax.Array  # [Q]
    f_cores: jax.Array
    f_mem: jax.Array
    f_gpu: jax.Array
    f_dur: jax.Array
    f_enq_t: jax.Array
    f_owner: jax.Array
    f_rec_wait: jax.Array
    f_jclass: jax.Array
    f_retries: jax.Array
    count: jax.Array  # [] int32
    ovf: jax.Array  # [] int32 — checked-narrow overflow events

    @property
    def capacity(self) -> int:
        return self.f_id.shape[-1]

    # widened field views — same API (and dtype) as the wide layout's
    @property
    def id(self):
        return F.widen(self.f_id)

    @property
    def cores(self):
        return F.widen(self.f_cores)

    @property
    def mem(self):
        return F.widen(self.f_mem)

    @property
    def gpu(self):
        return F.widen(self.f_gpu)

    @property
    def dur(self):
        return F.widen(self.f_dur)

    @property
    def enq_t(self):
        return F.widen(self.f_enq_t)

    @property
    def owner(self):
        return F.widen(self.f_owner)

    @property
    def rec_wait(self):
        return F.widen(self.f_rec_wait)

    @property
    def jclass(self):
        return F.widen(self.f_jclass)

    @property
    def retries(self):
        return F.widen(self.f_retries)

    def slot_valid(self) -> jax.Array:
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count


def _leaf(q: SoAJobQueue, name: str) -> jax.Array:
    return getattr(q, "f_" + name)


def _invalid(name: str, dtype) -> jax.Array:
    return jnp.asarray(F.QUEUE_INVALID[_FIDX[name]], dtype)


def field(q, name: str) -> jax.Array:
    """[..., Q] int32 view of one field, either layout."""
    if isinstance(q, SoAJobQueue):
        return F.widen(_leaf(q, name))
    return q.data[..., _FIDX[name]]


def rows_of(q) -> jax.Array:
    """[..., Q, NF] int32 packed rows of either layout — the wide compute
    form the whole-row contractions run in (stacking the SoA leaves once
    beats re-materializing a one-hot operand per field)."""
    if isinstance(q, SoAJobQueue):
        return jnp.stack([F.widen(_leaf(q, n)) for n in F.QUEUE_FIELDS],
                         axis=-1)
    return q.data


def _replace_fields(q: SoAJobQueue, new: dict, count=None, ovf=None):
    kw = {"f_" + n: v for n, v in new.items()}
    kw.update({} if count is None else {"count": count})
    kw.update({} if ovf is None else {"ovf": ovf})
    return q.replace(**kw)


def empty(capacity: int) -> JobQueue:
    return JobQueue(data=jnp.broadcast_to(_INVALID_ROW, (capacity, NF)).copy(),
                    count=jnp.int32(0))


def empty_soa(capacity: int, dtypes: dict) -> SoAJobQueue:
    """Compact-layout empty queue; ``dtypes`` maps field name -> storage
    dtype (CompactPlan.queue_dtypes())."""
    leaves = {
        "f_" + n: jnp.full((capacity,), F.QUEUE_INVALID[i], dtypes[n])
        for i, n in enumerate(F.QUEUE_FIELDS)}
    return SoAJobQueue(count=jnp.int32(0), ovf=jnp.int32(0), **leaves)


def soa_to_wide(q: SoAJobQueue) -> JobQueue:
    """Canonicalize a compact queue to the wide layout (widen + restack) —
    compact-vs-wide equality checks compare in this form. Works on batched
    ([C, Q]-leaf) queues too. The ``ovf`` counter is dropped; assert it
    zero separately."""
    data = jnp.stack([F.widen(_leaf(q, n)) for n in F.QUEUE_FIELDS], axis=-1)
    return JobQueue(data=data, count=jnp.asarray(q.count, jnp.int32))


def from_fields(id, cores, mem, gpu, dur, enq_t, owner, rec_wait, count,
                jclass=None, retries=None) -> JobQueue:
    """Build a wide queue from per-field [Q] arrays (one stack op)."""
    if jclass is None:
        jclass = F.job_class(jnp.asarray(cores), jnp.asarray(gpu))
    if retries is None:
        retries = jnp.zeros_like(jnp.asarray(id))
    data = jnp.stack([id, cores, mem, gpu, dur, enq_t, owner, rec_wait,
                      jclass, retries], axis=-1).astype(jnp.int32)
    return JobQueue(data=data, count=jnp.asarray(count, jnp.int32))


def get(q, i: Any) -> JobRec:
    if isinstance(q, SoAJobQueue):
        return JobRec(vec=jnp.stack(
            [F.widen(_leaf(q, n))[i] for n in F.QUEUE_FIELDS], axis=-1))
    return JobRec(vec=q.data[i])


def head(q) -> JobRec:
    return get(q, 0)


def select_row(q, hot: jax.Array) -> JobRec:
    """The row whose one-hot mask is ``hot`` [Q], as a one-hot contraction
    (dynamic row gathers serialize when vmapped over thousands of clusters
    — see the sweep loops in core/engine.py)."""
    h = hot.astype(jnp.int32)
    if isinstance(q, SoAJobQueue):
        return JobRec(vec=jnp.stack(
            [jnp.einsum("q,q->", h, F.widen(_leaf(q, n)))
             for n in F.QUEUE_FIELDS], axis=-1))
    return JobRec(vec=jnp.einsum("q,qf->f", h, q.data))


def rows_prefix(q, n: int) -> jax.Array:
    """The first ``n`` slots as packed [n, NF] int32 rows (sweep-order job
    batches for the wave kernels)."""
    if isinstance(q, SoAJobQueue):
        return jnp.stack([F.widen(_leaf(q, f))[:n] for f in F.QUEUE_FIELDS],
                         axis=-1)
    return q.data[:n]


def gather_rows(q, sel: jax.Array) -> jax.Array:
    """Packed [K, NF] int32 rows selected by a [K, Q] one-hot matrix (the
    BFD-ordered gather in the FFD sweeps) — integer contractions, exact."""
    s = sel.astype(jnp.int32)
    if isinstance(q, SoAJobQueue):
        return jnp.stack([jnp.einsum("kq,q->k", s, F.widen(_leaf(q, n)))
                          for n in F.QUEUE_FIELDS], axis=-1)
    return jnp.einsum("kq,qf->kf", s, q.data)


def push_back(q, job: JobRec, do: jax.Array):
    """Append one job if ``do`` (and capacity allows). One-hot select, not
    scatter — scatters serialize on TPU and this is per-tick hot."""
    ok = jnp.logical_and(do, q.count < q.capacity)
    hot = jnp.logical_and(jnp.arange(q.capacity, dtype=jnp.int32) == q.count, ok)
    if isinstance(q, SoAJobQueue):
        hot, ok = F.pin(hot, ok)
        new, bad = {}, q.ovf
        for n in F.QUEUE_FIELDS:
            leaf = _leaf(q, n)
            stored, nbad = F.narrow_store(job.vec[..., _FIDX[n]], leaf.dtype,
                                          do=ok)
            new[n] = jnp.where(hot, stored, leaf)
            bad = bad + nbad
        return _replace_fields(q, new, count=q.count + ok.astype(jnp.int32),
                               ovf=bad)
    data = jnp.where(hot[:, None], job.vec, q.data)
    return q.replace(data=data, count=q.count + ok.astype(jnp.int32))


def push_many(q, jobs, take: jax.Array, prefix: bool = False):
    """Append all rows of ``jobs`` where ``take`` is set, preserving order.

    ``take`` is a [Qj] bool mask over ``jobs`` slots. Overflowing entries are
    dropped (sized configs should make this impossible). ``prefix=True``
    asserts the mask is a leading prefix (e.g. time-sorted arrival ingestion)
    and skips the stable argsort — a per-tick hot path at scale.

    ``jobs`` may be either layout (the engine's ingest and borrow paths hand
    in small wide batches regardless of the state layout).
    """
    n_take = jnp.sum(take).astype(jnp.int32)
    jcap = jobs.capacity
    dst = q.count + jnp.arange(jcap, dtype=jnp.int32)  # k-th taken row
    ok = jnp.logical_and(jnp.arange(jcap) < n_take, dst < q.capacity)
    added = jnp.minimum(n_take, q.capacity - q.count)
    if isinstance(q, SoAJobQueue):
        order = (None if prefix
                 else jnp.argsort(jnp.logical_not(take), stable=True))
        new, bad = {}, q.ovf
        if prefix and jcap <= 128:
            # per-tick hot path (arrival ingest): ONE one-hot contraction on
            # the packed int32 rows (scatters serialize on TPU — see the
            # wide path below), then each column narrows into its leaf;
            # a per-field contraction re-materializes the [cap, Qj] one-hot
            # NF times (measured ~2x on the whole op)
            hot = jnp.logical_and(
                dst[None, :] == jnp.arange(q.capacity, dtype=jnp.int32)[:, None],
                ok[None, :])  # [cap, Qj]
            written = F.pin(jnp.any(hot, axis=1))
            src = rows_of(jobs)
            packed = hot.astype(src.dtype) @ src  # [cap, NF]
            for n in F.QUEUE_FIELDS:
                leaf = _leaf(q, n)
                stored, nbad = F.narrow_store(packed[:, _FIDX[n]],
                                              leaf.dtype, do=written)
                new[n] = jnp.where(written, stored, leaf)
                bad = bad + nbad
        else:
            dstc, ok = F.pin(jnp.where(ok, dst, q.capacity), ok)
            for n in F.QUEUE_FIELDS:
                leaf = _leaf(q, n)
                src = field(jobs, n)
                src = src if order is None else src[order]
                stored, nbad = F.narrow_store(src, leaf.dtype, do=ok)
                new[n] = leaf.at[dstc].set(stored, mode="drop")
                bad = bad + nbad
        return _replace_fields(q, new, count=q.count + added, ovf=bad)
    src = (jobs if isinstance(jobs, JobQueue) else soa_to_wide(jobs)).data
    if not prefix:
        src = src[jnp.argsort(jnp.logical_not(take), stable=True)]
    if prefix and jcap <= 128:
        # per-tick hot path (arrival ingest): scatter as a one-hot
        # contraction — scatters serialize on TPU. O(cap x Qj), so only for
        # small source batches; the borrow path (source capacity == total
        # clusters) keeps the scatter below.
        hot = jnp.logical_and(
            dst[None, :] == jnp.arange(q.capacity, dtype=jnp.int32)[:, None],
            ok[None, :])  # [cap, Qj]
        written = jnp.any(hot, axis=1)
        data = jnp.where(written[:, None],
                         hot.astype(src.dtype) @ src, q.data)
    else:
        dst = jnp.where(ok, dst, q.capacity)  # out-of-range writes dropped
        data = q.data.at[dst].set(src, mode="drop")
    return q.replace(data=data, count=q.count + added)


def push_back_dropped(q, do: jax.Array) -> jax.Array:
    """0/1: whether push_back(q, ., do) would overflow (SimState.drops)."""
    return jnp.logical_and(do, q.count >= q.capacity).astype(jnp.int32)


def push_many_dropped(q, take: jax.Array) -> jax.Array:
    """How many of ``take`` push_many(q, ., take) would overflow."""
    n_take = jnp.sum(take).astype(jnp.int32)
    return jnp.maximum(n_take - (q.capacity - q.count), 0)


def pop_front(q, do: jax.Array):
    """Drop the head job if ``do`` (FIFO pop), shifting everything left."""
    count = jnp.maximum(q.count - do.astype(jnp.int32), 0)
    if isinstance(q, SoAJobQueue):
        new = {}
        for n in F.QUEUE_FIELDS:
            leaf = _leaf(q, n)
            shifted = jnp.roll(leaf, -1).at[-1].set(_invalid(n, leaf.dtype))
            new[n] = jnp.where(do, shifted, leaf)
        return _replace_fields(q, new, count=count)
    shifted = jnp.roll(q.data, -1, axis=0).at[-1].set(_INVALID_ROW)
    data = jnp.where(do, shifted, q.data)
    return q.replace(data=data, count=count)


def pop_front_n(q, n: jax.Array):
    """Drop the first ``n`` jobs (FIFO pop of a prefix) — one dynamic roll
    instead of the general compact()'s argsort."""
    n = jnp.clip(n, 0, q.count)
    newcount = q.count - n
    live = jnp.arange(q.capacity, dtype=jnp.int32) < newcount
    if isinstance(q, SoAJobQueue):
        live, n = F.pin(live, n)
        new = {}
        for f in F.QUEUE_FIELDS:
            leaf = _leaf(q, f)
            new[f] = jnp.where(live, jnp.roll(leaf, -n),
                               _invalid(f, leaf.dtype))
        return _replace_fields(q, new, count=newcount)
    data = jnp.where(live[:, None], jnp.roll(q.data, -n, axis=0), _INVALID_ROW)
    return q.replace(data=data, count=newcount)


def compact(q, keep: jax.Array):
    """Stable-remove all valid slots where ``keep`` is False.

    This is the tensor analogue of the Go in-place slice deletions
    (scheduler.go:319,165,184). ``keep`` is evaluated on valid slots only.

    Small capacities (the per-tick hot queues) compact via a cumsum-rank
    one-hot contraction — dest[i] = #kept before i — an integer matmul,
    which is exact on TPU (float matmuls there run bf16 passes by default
    and corrupt packed int rows); the vmapped argsort+gather alternative
    was a measured ~2 ms/tick at 4k clusters. Large capacities keep the
    argsort+gather form: a [Q, Q] one-hot operand scales quadratically in
    memory.

    Compaction only PERMUTES already-stored values (plus the in-range
    invalid fill), so the SoA narrow stores here can never overflow; they
    still ride the checked helper for a single uniform store discipline.
    """
    keep = jnp.logical_and(keep, q.slot_valid())
    n_keep = jnp.sum(keep).astype(jnp.int32)
    live = jnp.arange(q.capacity, dtype=jnp.int32) < n_keep
    if isinstance(q, SoAJobQueue):
        live = F.pin(live)
        new, bad = {}, q.ovf
        if q.capacity <= 256:
            dest = jnp.cumsum(keep.astype(jnp.int32)) - 1  # rank among kept
            hot = jnp.logical_and(
                dest[None, :] == jnp.arange(q.capacity)[:, None],
                keep[None, :])  # [dst, src]
            packed = hot.astype(jnp.int32) @ rows_of(q)  # ONE contraction
            for n in F.QUEUE_FIELDS:
                leaf = _leaf(q, n)
                # checked=False: compaction permutes this queue's own
                # already-stored values (see the docstring above)
                stored, nbad = F.narrow_store(packed[:, _FIDX[n]],
                                              leaf.dtype, do=live,
                                              checked=False)
                new[n] = jnp.where(live, stored, _invalid(n, leaf.dtype))
                bad = bad + nbad
        else:
            order = F.pin(jnp.argsort(jnp.logical_not(keep), stable=True))
            for n in F.QUEUE_FIELDS:
                leaf = _leaf(q, n)
                new[n] = jnp.where(live, leaf[order],
                                   _invalid(n, leaf.dtype))
        return _replace_fields(q, new, count=n_keep, ovf=bad)
    if q.capacity <= 256:
        dest = jnp.cumsum(keep.astype(jnp.int32)) - 1  # rank among kept
        hot = jnp.logical_and(dest[None, :] == jnp.arange(q.capacity)[:, None],
                              keep[None, :])  # [dst, src]
        packed = hot.astype(jnp.int32) @ q.data
        data = jnp.where(live[:, None], packed, _INVALID_ROW)
    else:
        order = jnp.argsort(jnp.logical_not(keep), stable=True)  # kept first
        data = jnp.where(live[:, None], q.data[order], _INVALID_ROW)
    return q.replace(data=data, count=n_keep)


def set_col(q: JobQueue, col: int, values: jax.Array) -> JobQueue:
    """Overwrite one field column by index (wide layout only — layout-blind
    callers use ``set_field``)."""
    return q.replace(data=q.data.at[..., col].set(values.astype(jnp.int32)))


def set_field(q, name: str, values: jax.Array):
    """Overwrite one field column (e.g. rec_wait) for all slots."""
    if isinstance(q, SoAJobQueue):
        leaf = _leaf(q, name)
        stored, nbad = F.narrow_store(jnp.asarray(values, jnp.int32),
                                      leaf.dtype)
        return _replace_fields(q, {name: stored}, ovf=q.ovf + nbad)
    return set_col(q, _FIDX[name], values)


def set_field_elem(q, name: str, i, value):
    """Overwrite one field of one slot (e.g. the head's rec_wait)."""
    if isinstance(q, SoAJobQueue):
        leaf = _leaf(q, name)
        stored, nbad = F.narrow_store(jnp.asarray(value, jnp.int32),
                                      leaf.dtype)
        return _replace_fields(q, {name: leaf.at[i].set(stored)},
                               ovf=q.ovf + nbad)
    return q.replace(data=q.data.at[i, _FIDX[name]].set(
        jnp.asarray(value, jnp.int32)))


def remove_matching(q, job: JobRec, match_fields=("id", "cores", "mem", "dur")):
    """Remove entries equal to ``job`` on the given fields.

    Mirrors the reference's whole-struct-equality dequeues
    (``if j == sched.BorrowedQueue[i]``, server.go:131-135, scheduler.go:164,
    172, 184). Matching on (id, cores, mem, dur) is the documented
    determinization — the Go structs also compare State/WaitTime/Ownership,
    which survive the borrow round-trip unchanged.
    """
    m = jnp.ones((q.capacity,), bool)
    for f in match_fields:
        m = jnp.logical_and(m, field(q, f) == job.vec[..., _FIDX[f]])
    return compact(q, jnp.logical_not(m))
