"""Padded, mask-disciplined job queues.

The reference keeps six mutex-guarded Go slices per scheduler (ReadyQueue,
WaitQueue, LentQueue, BorrowedQueue, Level0, Level1 —
pkg/scheduler/scheduler.go:19-30). Here a queue is ONE packed int32 tensor
``data[Q, NF]`` plus a scalar ``count``: valid entries occupy rows
``[0, count)`` in FIFO order, so "head" is row 0 and append writes row
``count``. The packed layout matters: queue ops (gather/scatter/roll/where)
touch one tensor instead of seven, and at 4k clusters per-op dispatch — not
FLOPs — is the tick-loop cost. All ops are pure, static-shape, and written
for a single cluster — the engine ``vmap``s them over the cluster axis.

Row fields mirror the reference's ``Job`` struct (scheduler.go:65-73):
id, cores, mem, duration, enqueue-time (``WaitTime time.Time``), owner
(``Ownership string`` — here the borrower's cluster index, -1 for "my own
job"), plus ``rec_wait``, the last wait recorded in the scheduler's
``WaitTime.JobsMap`` (scheduler.go:48-63).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

INVALID_ID = jnp.int32(-1)
OWN = jnp.int32(-1)  # owner value for "my own job" (Ownership == "")

# packed row layout; (cores, mem, gpu) are contiguous and ordered like the
# node-tensor resource axis (core/spec.py RES) so ``res`` is one slice
NF = 8
FID, FCORES, FMEM, FGPU, FDUR, FENQ, FOWNER, FREC = range(NF)

_INVALID_ROW = jnp.array([-1, 0, 0, 0, 0, 0, -1, 0], jnp.int32)  # id=-1, owner=OWN


@struct.dataclass
class JobRec:
    """A single job: one packed [NF] int32 row."""

    vec: jax.Array

    @property
    def id(self):
        return self.vec[..., FID]

    @property
    def cores(self):
        return self.vec[..., FCORES]

    @property
    def mem(self):
        return self.vec[..., FMEM]

    @property
    def gpu(self):
        return self.vec[..., FGPU]

    @property
    def dur(self):
        return self.vec[..., FDUR]

    @property
    def enq_t(self):
        return self.vec[..., FENQ]

    @property
    def owner(self):
        return self.vec[..., FOWNER]

    @property
    def rec_wait(self):
        return self.vec[..., FREC]

    @property
    def res(self):
        """[..., RES] (cores, mem, gpu) — matches the node free/cap layout."""
        return self.vec[..., FCORES:FGPU + 1]

    @staticmethod
    def make(id=-1, cores=0, mem=0, gpu=0, dur=0, enq_t=0, owner=OWN,
             rec_wait=0) -> "JobRec":
        parts = [id, cores, mem, gpu, dur, enq_t, owner, rec_wait]
        return JobRec(vec=jnp.stack([jnp.asarray(p, jnp.int32) for p in parts], axis=-1))

    @staticmethod
    def invalid() -> "JobRec":
        return JobRec(vec=_INVALID_ROW)

    def with_(self, **kw) -> "JobRec":
        vec = self.vec
        for name, val in kw.items():
            vec = vec.at[..., _FIDX[name]].set(jnp.asarray(val, jnp.int32))
        return JobRec(vec=vec)


_FIDX = {"id": FID, "cores": FCORES, "mem": FMEM, "gpu": FGPU, "dur": FDUR,
         "enq_t": FENQ, "owner": FOWNER, "rec_wait": FREC}


@struct.dataclass
class JobQueue:
    data: jax.Array  # [Q, NF] int32
    count: jax.Array  # [] int32

    @property
    def capacity(self) -> int:
        return self.data.shape[-2]

    # field views (each is one slice op — use sparingly in hot loops)
    @property
    def id(self):
        return self.data[..., FID]

    @property
    def cores(self):
        return self.data[..., FCORES]

    @property
    def mem(self):
        return self.data[..., FMEM]

    @property
    def gpu(self):
        return self.data[..., FGPU]

    @property
    def dur(self):
        return self.data[..., FDUR]

    @property
    def enq_t(self):
        return self.data[..., FENQ]

    @property
    def owner(self):
        return self.data[..., FOWNER]

    @property
    def rec_wait(self):
        return self.data[..., FREC]

    def slot_valid(self) -> jax.Array:
        """[Q] bool — which slots hold live jobs."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count


def empty(capacity: int) -> JobQueue:
    return JobQueue(data=jnp.broadcast_to(_INVALID_ROW, (capacity, NF)).copy(),
                    count=jnp.int32(0))


def from_fields(id, cores, mem, gpu, dur, enq_t, owner, rec_wait, count) -> JobQueue:
    """Build a queue from per-field [Q] arrays (one stack op)."""
    data = jnp.stack([id, cores, mem, gpu, dur, enq_t, owner, rec_wait],
                     axis=-1).astype(jnp.int32)
    return JobQueue(data=data, count=jnp.asarray(count, jnp.int32))


def get(q: JobQueue, i: Any) -> JobRec:
    return JobRec(vec=q.data[i])


def head(q: JobQueue) -> JobRec:
    return get(q, 0)


def push_back(q: JobQueue, job: JobRec, do: jax.Array) -> JobQueue:
    """Append one job if ``do`` (and capacity allows). One-hot select, not
    scatter — scatters serialize on TPU and this is per-tick hot."""
    ok = jnp.logical_and(do, q.count < q.capacity)
    hot = jnp.logical_and(jnp.arange(q.capacity, dtype=jnp.int32) == q.count, ok)
    data = jnp.where(hot[:, None], job.vec, q.data)
    return q.replace(data=data, count=q.count + ok.astype(jnp.int32))


def push_many(q: JobQueue, jobs: JobQueue, take: jax.Array,
              prefix: bool = False) -> JobQueue:
    """Append all rows of ``jobs`` where ``take`` is set, preserving order.

    ``take`` is a [Qj] bool mask over ``jobs`` slots. Overflowing entries are
    dropped (sized configs should make this impossible). ``prefix=True``
    asserts the mask is a leading prefix (e.g. time-sorted arrival ingestion)
    and skips the stable argsort — a per-tick hot path at scale.
    """
    n_take = jnp.sum(take).astype(jnp.int32)
    src = jobs.data if prefix else jobs.data[jnp.argsort(jnp.logical_not(take),
                                                         stable=True)]
    dst = q.count + jnp.arange(jobs.capacity, dtype=jnp.int32)  # k-th taken row
    ok = jnp.logical_and(jnp.arange(jobs.capacity) < n_take, dst < q.capacity)
    if prefix and jobs.capacity <= 128:
        # per-tick hot path (arrival ingest): scatter as a one-hot
        # contraction — scatters serialize on TPU. O(cap x Qj), so only for
        # small source batches; the borrow path (source capacity == total
        # clusters) keeps the scatter below.
        hot = jnp.logical_and(
            dst[None, :] == jnp.arange(q.capacity, dtype=jnp.int32)[:, None],
            ok[None, :])  # [cap, Qj]
        written = jnp.any(hot, axis=1)
        data = jnp.where(written[:, None],
                         hot.astype(src.dtype) @ src, q.data)
    else:
        dst = jnp.where(ok, dst, q.capacity)  # out-of-range writes dropped
        data = q.data.at[dst].set(src, mode="drop")
    added = jnp.minimum(n_take, q.capacity - q.count)
    return q.replace(data=data, count=q.count + added)


def push_back_dropped(q: JobQueue, do: jax.Array) -> jax.Array:
    """0/1: whether push_back(q, ., do) would overflow (SimState.drops)."""
    return jnp.logical_and(do, q.count >= q.capacity).astype(jnp.int32)


def push_many_dropped(q: JobQueue, take: jax.Array) -> jax.Array:
    """How many of ``take`` push_many(q, ., take) would overflow."""
    n_take = jnp.sum(take).astype(jnp.int32)
    return jnp.maximum(n_take - (q.capacity - q.count), 0)


def pop_front(q: JobQueue, do: jax.Array) -> JobQueue:
    """Drop the head job if ``do`` (FIFO pop), shifting everything left."""
    shifted = jnp.roll(q.data, -1, axis=0).at[-1].set(_INVALID_ROW)
    data = jnp.where(do, shifted, q.data)
    return q.replace(data=data, count=jnp.maximum(q.count - do.astype(jnp.int32), 0))


def pop_front_n(q: JobQueue, n: jax.Array) -> JobQueue:
    """Drop the first ``n`` jobs (FIFO pop of a prefix) — one dynamic roll
    instead of the general compact()'s argsort."""
    n = jnp.clip(n, 0, q.count)
    newcount = q.count - n
    live = jnp.arange(q.capacity, dtype=jnp.int32) < newcount
    data = jnp.where(live[:, None], jnp.roll(q.data, -n, axis=0), _INVALID_ROW)
    return q.replace(data=data, count=newcount)


def compact(q: JobQueue, keep: jax.Array) -> JobQueue:
    """Stable-remove all valid slots where ``keep`` is False.

    This is the tensor analogue of the Go in-place slice deletions
    (scheduler.go:319,165,184). ``keep`` is evaluated on valid slots only.

    Small capacities (the per-tick hot queues) compact via a cumsum-rank
    one-hot contraction — dest[i] = #kept before i — an integer matmul,
    which is exact on TPU (float matmuls there run bf16 passes by default
    and corrupt packed int rows); the vmapped argsort+gather alternative
    was a measured ~2 ms/tick at 4k clusters. Large capacities keep the
    argsort+gather form: a [Q, Q] one-hot operand scales quadratically in
    memory.
    """
    keep = jnp.logical_and(keep, q.slot_valid())
    n_keep = jnp.sum(keep).astype(jnp.int32)
    live = jnp.arange(q.capacity, dtype=jnp.int32) < n_keep
    if q.capacity <= 256:
        dest = jnp.cumsum(keep.astype(jnp.int32)) - 1  # rank among kept
        hot = jnp.logical_and(dest[None, :] == jnp.arange(q.capacity)[:, None],
                              keep[None, :])  # [dst, src]
        packed = hot.astype(jnp.int32) @ q.data
        data = jnp.where(live[:, None], packed, _INVALID_ROW)
    else:
        order = jnp.argsort(jnp.logical_not(keep), stable=True)  # kept first
        data = jnp.where(live[:, None], q.data[order], _INVALID_ROW)
    return q.replace(data=data, count=n_keep)


def set_col(q: JobQueue, col: int, values: jax.Array) -> JobQueue:
    """Overwrite one field column (e.g. rec_wait) for all slots."""
    return q.replace(data=q.data.at[..., col].set(values.astype(jnp.int32)))


def remove_matching(q: JobQueue, job: JobRec, match_fields=("id", "cores", "mem", "dur")) -> JobQueue:
    """Remove entries equal to ``job`` on the given fields.

    Mirrors the reference's whole-struct-equality dequeues
    (``if j == sched.BorrowedQueue[i]``, server.go:131-135, scheduler.go:164,
    172, 184). Matching on (id, cores, mem, dur) is the documented
    determinization — the Go structs also compare State/WaitTime/Ownership,
    which survive the borrow round-trip unchanged.
    """
    m = jnp.ones((q.capacity,), bool)
    for f in match_fields:
        m = jnp.logical_and(m, q.data[..., _FIDX[f]] == job.vec[..., _FIDX[f]])
    return compact(q, jnp.logical_not(m))
