"""Placement kernels — THE decision the framework moves to TPU.

The reference's placement is a linear first-fit scan over nodes under mutexes
(ScheduleJob, pkg/scheduler/scheduler.go:127-139); its lend-feasibility probe
is the same scan with strict inequalities (Lend, scheduler.go:194-202). Here
both are branch-free vector ops over the padded node axis, ``vmap``-able over
clusters and trivially fusible by XLA.

Node axis layout: physical slots first (in spec order), then reserved virtual
slots — matching Go's ``append`` of virtual nodes after physical ones
(cluster.go:79), so first-fit order is identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.core.spec import CORES, GPU, MEM
from multi_cluster_simulator_tpu.ops.queues import JobRec

NO_NODE = jnp.int32(-1)


def feasible(free: jax.Array, active: jax.Array, cores: jax.Array,
             mem: jax.Array, gpu=0, strict: bool = False) -> jax.Array:
    """[N] bool feasibility mask.

    ``strict=False`` is ScheduleJob's ``>=`` (scheduler.go:131);
    ``strict=True`` is Lend's ``>`` (scheduler.go:197) — the reference is
    deliberately inconsistent here and we preserve both. The gpu axis (a
    3-dim extension with no reference analogue) is always ``>=`` so that
    gpu-less nodes stay feasible for gpu-less jobs in both modes; it is
    only present when ``free`` carries 3 resources (SimConfig.n_res).
    """
    if strict:
        ok = jnp.logical_and(free[:, CORES] > cores, free[:, MEM] > mem)
    else:
        ok = jnp.logical_and(free[:, CORES] >= cores, free[:, MEM] >= mem)
    if free.shape[-1] > GPU:
        ok = jnp.logical_and(ok, free[:, GPU] >= gpu)
    else:
        # narrowed axis (n_res=2) == zero gpu capacity everywhere: a job
        # that demands gpu must fail closed, not silently place
        ok = jnp.logical_and(ok, jnp.asarray(gpu, jnp.int32) <= 0)
    return jnp.logical_and(ok, active)


def first_fit(free: jax.Array, active: jax.Array, job: JobRec, strict: bool = False) -> jax.Array:
    """Lowest-index feasible node, or NO_NODE. free: [N, RES], active: [N]."""
    mask = feasible(free, active, job.cores, job.mem, job.gpu, strict=strict)
    idx = jnp.argmax(mask).astype(jnp.int32)  # first True (argmax of bool)
    return jnp.where(jnp.any(mask), idx, NO_NODE)


def best_scored_fit(free: jax.Array, active: jax.Array, job: JobRec,
                    scores: jax.Array) -> jax.Array:
    """Highest-scoring feasible node (ties -> lowest index, matching the
    reference's first-fit orientation), or NO_NODE. ``scores`` is a finite
    [N] f32 preference vector — the scored-policy kernels (policies/
    kernels.py: gavel throughput, tesserae packing alignment) supply it;
    with a constant vector this degenerates to ``first_fit``."""
    mask = feasible(free, active, job.cores, job.mem, job.gpu)
    sc = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
    idx = jnp.argmax(sc).astype(jnp.int32)  # first max: lowest-index tie win
    return jnp.where(jnp.any(mask), idx, NO_NODE)


def can_lend(free: jax.Array, active: jax.Array, job: JobRec) -> jax.Array:
    """Lend() feasibility: any node with strictly more free than needed."""
    return jnp.any(feasible(free, active, job.cores, job.mem, job.gpu,
                            strict=True))


def occupy(free: jax.Array, node: jax.Array, job: JobRec, do: jax.Array) -> jax.Array:
    """Subtract job resources from ``free[node]`` when ``do``. (RunJob's
    decrement half, cluster.go:144-148.) One-hot select, not scatter."""
    res = job.res[..., : free.shape[-1]]
    hot = jnp.logical_and(jnp.arange(free.shape[0], dtype=jnp.int32) == node, do)
    return free - hot[:, None] * res


def best_fit_decreasing_order(q_cores: jax.Array, q_mem: jax.Array, valid: jax.Array) -> jax.Array:
    """Slot processing order for the FFD policy: valid jobs by decreasing
    (cores, then mem), stable. Returns [Q] int32 slot indices.

    A TPU-side upgrade over the reference (BASELINE.json config 3); the sort
    is one XLA sort op, the subsequent placement sweep is shared with FIFO.
    """
    big = jnp.int32(2**31 - 1)
    primary = jnp.where(valid, -q_cores, big)  # invalid slots sort last
    secondary = jnp.where(valid, -q_mem, big)
    return jnp.lexsort((secondary, primary)).astype(jnp.int32)
