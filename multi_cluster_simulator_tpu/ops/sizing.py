"""Contract-sizing kernels — the trader's greedy node-size calculators.

The reference sizes a resource request by streaming Level1 jobs from its
scheduler and folding them greedily (pkg/trader/scheduler_client.go:126-289).
Both algorithms are re-expressed as masked scans over the Level1 queue
tensor; "as-built" mode reproduces the Go code's observable arithmetic —
including its quirks — and "sane" mode is the documented intended behavior
(see MARKET.md).

Times here are int32 ms; prices float32 (Go mixes float32/float64 — a
documented divergence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from multi_cluster_simulator_tpu.ops.queues import JobQueue


@struct.dataclass
class Contract:
    """ContractRequest (proto/trader.proto:21-28), minus the transport bits.

    ``gpu`` is the 3-dim resource extension (BASELINE config 4); it has no
    wire field in the reference proto and no price contribution — it sizes
    and carves like the other axes but trades at cost 0."""

    cores: jax.Array  # [] i32
    mem: jax.Array  # [] i32
    gpu: jax.Array  # [] i32
    time_ms: jax.Array  # [] i32
    price: jax.Array  # [] f32

    @staticmethod
    def zero() -> "Contract":
        return Contract(cores=jnp.int32(0), mem=jnp.int32(0),
                        gpu=jnp.int32(0), time_ms=jnp.int32(0),
                        price=jnp.float32(0.0))


def _price(cores, mem, time_ms, core_cost, mem_cost):
    """price = t_sec*cores*coreCost + t_sec*mem*memCost
    (scheduler_client.go:150, 271)."""
    t_s = time_ms.astype(jnp.float32) / 1000.0
    return t_s * cores.astype(jnp.float32) * core_cost + t_s * mem.astype(jnp.float32) * mem_cost


def fast_node_contract(l1: JobQueue, budget, core_cost, mem_cost) -> Contract:
    """calculateFastNodeSize (scheduler_client.go:126-170): size a node to
    run every Level1 job concurrently from t=0 — cores/mem are running sums,
    time the running max of durations — stopping before the job whose
    inclusion would reach the budget (strict <; negative budget = unlimited).

    The running price is monotone, so the accepted set is a prefix: one
    cumsum + cummax and a masked argmax, no sequential scan."""
    valid = l1.slot_valid()
    cores = jnp.cumsum(jnp.where(valid, l1.cores, 0))
    mem = jnp.cumsum(jnp.where(valid, l1.mem, 0))
    gpu = jnp.cumsum(jnp.where(valid, l1.gpu, 0))
    time_ms = jax.lax.cummax(jnp.where(valid, l1.dur, 0))
    price = _price(cores, mem, time_ms, core_cost, mem_cost)
    ok = jnp.logical_and(valid, jnp.logical_or(budget < 0, price < budget))
    k = jnp.sum(ok.astype(jnp.int32)) - 1  # last accepted prefix index
    has = k >= 0
    g = lambda a, z: jnp.where(has, a[jnp.maximum(k, 0)], z)
    return Contract(cores=g(cores, jnp.int32(0)), mem=g(mem, jnp.int32(0)),
                    gpu=g(gpu, jnp.int32(0)),
                    time_ms=g(time_ms, jnp.int32(0)), price=g(price, jnp.float32(0.0)))


def small_node_contract_asbuilt(l1: JobQueue, budget, core_cost, mem_cost) -> Contract:
    """calculateSmallNodeSize *as built* (scheduler_client.go:201-289).

    The Go timeline bookkeeping is inert (``atTime`` is never appended to, so
    every job sees a single t=0 slot with zero load); the observable fold is:
    cores/mem accumulate sums (a zero-sized need leaves them unchanged), and
    the contract time becomes ``dur_k`` when ``dur_k > T_{k-1}`` **and is
    reset to 0 otherwise** (``jobState.time`` keeps its zero value when the
    new job doesn't extend the contract, scheduler_client.go:263-265).
    Budget stop as in fast-node. Preserved quirks and all — this is what the
    reference actually requests.

    Vectorized, not a sequential fold: the cores/mem/gpu sums are cumsums,
    and the time recurrence ``t_k = dur_k * [t_{k-1} < dur_k]`` is a
    composition of one-threshold step functions ``t -> A*[t<theta] + B``,
    a class closed under composition — so the whole trajectory is one
    ``associative_scan`` (log-depth) instead of a Q-step serial scan, which
    dominated the trade-round cost at large queue capacities. The budget
    stop is a prefix property (the fold freezes at the first rejection), so
    the accepted set is recoverable from the unstopped trajectories."""
    valid = l1.slot_valid()
    cores = jnp.cumsum(jnp.where(valid, jnp.maximum(l1.cores, 0), 0))
    mem = jnp.cumsum(jnp.where(valid, jnp.maximum(l1.mem, 0), 0))
    gpu = jnp.cumsum(jnp.where(valid, jnp.maximum(l1.gpu, 0), 0))

    # time trajectory: represent f_k(t) = dur_k * [t < dur_k] as the triple
    # (theta, A, B) meaning t -> A*[t<theta] + B*[t>=theta]; composition
    # keeps the leftmost threshold and maps both branch values, so the
    # prefix compositions F_k are computed associatively and t_k = F_k(0).
    dur = jnp.where(valid, l1.dur, 0)

    def compose(a, b):  # apply a, then b
        th_a, A_a, B_a = a
        th_b, A_b, B_b = b
        apply_b = lambda x: jnp.where(x < th_b, A_b, B_b)
        return (th_a, apply_b(A_a), apply_b(B_a))

    th, A, B = jax.lax.associative_scan(
        compose, (dur, dur, jnp.zeros_like(dur)))
    time_ms = jnp.where(0 < th, A, B)

    price = _price(cores, mem, time_ms, core_cost, mem_cost)
    ok = jnp.logical_and(valid, jnp.logical_or(budget < 0, price < budget))
    # the fold stops at the first rejection: accepted = the ok-prefix of
    # valid slots before the first valid-but-rejected index
    reject = jnp.logical_and(valid, jnp.logical_not(ok))
    stopped = jnp.cumsum(reject.astype(jnp.int32)) - reject.astype(jnp.int32) > 0
    acc = jnp.logical_and(ok, jnp.logical_not(stopped))
    k = jnp.sum(acc.astype(jnp.int32)) - 1
    has = k >= 0
    g = lambda a, z: jnp.where(has, a[jnp.maximum(k, 0)], z)
    return Contract(cores=g(cores, jnp.int32(0)), mem=g(mem, jnp.int32(0)),
                    gpu=g(gpu, jnp.int32(0)),
                    time_ms=g(time_ms, jnp.int32(0)),
                    price=g(price, jnp.float32(0.0)))


def small_node_contract_sane(l1: JobQueue, budget, core_cost, mem_cost) -> Contract:
    """The *intended* small node: the cheapest node that can run the Level1
    backlog sequentially — max individual cores/mem, summed durations —
    truncated at the budget. (The reference's cost-minimizing timeline never
    executes; this is the documented sane replacement, MARKET.md §sizing.)"""
    valid = l1.slot_valid()
    cores = jax.lax.cummax(jnp.where(valid, l1.cores, 0))
    mem = jax.lax.cummax(jnp.where(valid, l1.mem, 0))
    gpu = jax.lax.cummax(jnp.where(valid, l1.gpu, 0))
    time_ms = jnp.cumsum(jnp.where(valid, l1.dur, 0))
    price = _price(cores, mem, time_ms, core_cost, mem_cost)
    ok = jnp.logical_and(valid, jnp.logical_or(budget < 0, price < budget))
    k = jnp.sum(ok.astype(jnp.int32)) - 1
    has = k >= 0
    g = lambda a, z: jnp.where(has, a[jnp.maximum(k, 0)], z)
    return Contract(cores=g(cores, jnp.int32(0)), mem=g(mem, jnp.int32(0)),
                    gpu=g(gpu, jnp.int32(0)),
                    time_ms=g(time_ms, jnp.int32(0)), price=g(price, jnp.float32(0.0)))
