from multi_cluster_simulator_tpu.ops import placement, queues, runset

__all__ = ["placement", "queues", "runset"]
