"""Lender-side virtual-node carving — AllocateVirtualNodeResources
(pkg/scheduler/cluster.go:87-125) as a node-axis scan.

The Go walk computes, per node, ``diff = |req - avail|`` per resource,
decrements the request by ``diff`` (zeroing it when ``diff > req``) and
occupies ``diff`` on the node as a placeholder "Foreign" job for the contract
duration. Two consequences of that arithmetic are handled explicitly here:

- **as-built request bookkeeping is preserved** — whether the carve succeeds
  (request fully consumed) matches the Go outcome exactly;
- **occupied amounts are clamped to [0, avail]** — the Go code can occupy
  more than a node has free, which underflows its *unsigned* counters and
  turns the node into effectively infinite capacity. Reproducing that wrap
  would poison the whole simulation, so parity mode clamps the occupancy
  while keeping the request arithmetic (MARKET.md §carving documents this
  as the one deliberate deviation).

``mode="sane"`` instead takes ``min(req, avail)`` per node — the obvious
intended behavior.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.core.spec import RES


def carve_plan(free: jax.Array, active: jax.Array, req_cores, req_mem,
               req_gpu=0, mode: str = "asbuilt"):
    """Plan a carve across the node axis.

    free: [N, RES] current free resources; active: [N] — the Go walk visits
    every *real* node in order, including virtual ones (``c.Nodes`` has no
    padding), so inactive padded slots must be skipped: an avail=0 slot
    would otherwise zero the remaining request under the as-built abs-diff
    arithmetic and fake a successful carve. The per-resource arithmetic is
    identical for every axis, so it runs vectorized over [RES] (the gpu
    component is the 3-dim extension; a zero request leaves it inert).
    Returns (amounts [N, RES] i32, ok bool) where ok means the request was
    fully consumed (cluster.go:119-122's error check).
    """
    N = free.shape[0]
    req0 = jnp.stack([jnp.asarray(req_cores, jnp.int32),
                      jnp.asarray(req_mem, jnp.int32),
                      jnp.asarray(req_gpu, jnp.int32)])
    assert req0.shape == (RES,)

    def step(req, n):
        avail = jnp.maximum(free[n], 0)  # [RES]
        if mode == "asbuilt":
            # diff = |req - avail| when req > 0 (cluster.go:96-102)
            d = jnp.where(req > 0, jnp.abs(req - avail), 0)
            # request decrement (cluster.go:104-114)
            new_req = jnp.where(d > req, 0, req - d)
            # occupancy, clamped to what the node actually has
            occ = jnp.clip(d, 0, avail)
        elif mode == "sane":
            occ = jnp.minimum(req, avail)
            new_req = req - occ
        else:
            raise ValueError(f"unknown carve mode {mode!r}")
        skip = jnp.logical_not(active[n])
        return (jnp.where(skip, req, new_req),
                jnp.where(skip, jnp.zeros_like(occ), occ))

    req, amounts = jax.lax.scan(step, req0, jnp.arange(N, dtype=jnp.int32))
    ok = jnp.all(req <= 0)
    return amounts.astype(jnp.int32), ok
