"""Lender-side virtual-node carving — AllocateVirtualNodeResources
(pkg/scheduler/cluster.go:87-125) as a node-axis scan.

The Go walk computes, per node, ``diff = |req - avail|`` per resource,
decrements the request by ``diff`` (zeroing it when ``diff > req``) and
occupies ``diff`` on the node as a placeholder "Foreign" job for the contract
duration. Two consequences of that arithmetic are handled explicitly here:

- **as-built request bookkeeping is preserved** — whether the carve succeeds
  (request fully consumed) matches the Go outcome exactly;
- **occupied amounts are clamped to [0, avail]** — the Go code can occupy
  more than a node has free, which underflows its *unsigned* counters and
  turns the node into effectively infinite capacity. Reproducing that wrap
  would poison the whole simulation, so parity mode clamps the occupancy
  while keeping the request arithmetic (MARKET.md §carving documents this
  as the one deliberate deviation).

``mode="sane"`` instead takes ``min(req, avail)`` per node — the obvious
intended behavior.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.core.spec import CORES, MEM


def carve_plan(free: jax.Array, active: jax.Array, req_cores, req_mem,
               mode: str = "asbuilt"):
    """Plan a carve across the node axis.

    free: [N, RES] current free resources; active: [N] — the Go walk visits
    every *real* node in order, including virtual ones (``c.Nodes`` has no
    padding), so inactive padded slots must be skipped: an avail=0 slot
    would otherwise zero the remaining request under the as-built abs-diff
    arithmetic and fake a successful carve. Returns (amounts [N, RES] i32,
    ok bool) where ok means the request was fully consumed
    (cluster.go:119-122's error check).
    """
    N = free.shape[0]

    def step(carry, n):
        rc0, rm0 = carry
        rc, rm = rc0, rm0
        avail_c = jnp.maximum(free[n, CORES], 0)
        avail_m = jnp.maximum(free[n, MEM], 0)
        if mode == "asbuilt":
            # diff = |req - avail| when req > 0 (cluster.go:96-102)
            dc = jnp.where(rc > 0, jnp.abs(rc - avail_c), 0)
            dm = jnp.where(rm > 0, jnp.abs(rm - avail_m), 0)
            # request decrement (cluster.go:104-114)
            rc = jnp.where(dc > rc, 0, rc - dc)
            rm = jnp.where(dm > rm, 0, rm - dm)
            # occupancy, clamped to what the node actually has
            oc = jnp.clip(dc, 0, avail_c)
            om = jnp.clip(dm, 0, avail_m)
        elif mode == "sane":
            oc = jnp.minimum(rc, avail_c)
            om = jnp.minimum(rm, avail_m)
            rc = rc - oc
            rm = rm - om
        else:
            raise ValueError(f"unknown carve mode {mode!r}")
        skip = jnp.logical_not(active[n])
        rc = jnp.where(skip, rc0, rc)
        rm = jnp.where(skip, rm0, rm)
        oc = jnp.where(skip, 0, oc)
        om = jnp.where(skip, 0, om)
        return (rc, rm), jnp.stack([oc, om])

    (rc, rm), amounts = jax.lax.scan(
        step, (req_cores.astype(jnp.int32), req_mem.astype(jnp.int32)),
        jnp.arange(N, dtype=jnp.int32))
    ok = jnp.logical_and(rc <= 0, rm <= 0)
    return amounts.astype(jnp.int32), ok
