"""Observation featurization: SimState -> a compact fixed-shape [C, N_OBS]
float32 tensor a policy head can consume.

The features are deliberately LAYOUT-BLIND: every read goes through the
accessors both state layouts share — queue ``count`` scalars, the running
set's ``active`` mask, ``avg_wait_ms``, and node tensors widened through
``ops/fields.widen`` — so the same observation function works bit-for-bit
over the wide int32 AoS state and the ``--compact`` SoA state
(tests/test_env.py pins obs(wide) == obs(compact)). Counts and occupancies
are normalized by their static capacity bounds so the feature scale is
shape-independent; free capacity is bucketed by node DEVICE TYPE (the axis
the rl action matrix scores — ops/fields.N_DEVICE_TYPES), matching the
action geometry: what the policy can steer is what it observes.
"""

from __future__ import annotations

import jax.numpy as jnp

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.state import SimState
from multi_cluster_simulator_tpu.ops import fields as F

# scalar features per cluster, before the per-device-type blocks:
# 4 queue depths (l0, l1, ready, wait), running occupancy, jobs_in_queue,
# and the wait-time accrual (avg_wait in seconds)
_N_SCALAR = 7


def n_obs_features(cfg: SimConfig) -> int:
    """Static observation width per cluster: the scalar block plus, per
    device type, an active-node fraction and one free-fraction per
    resource axis."""
    return _N_SCALAR + F.N_DEVICE_TYPES * (1 + cfg.n_res)


def observe(s: SimState, cfg: SimConfig) -> jnp.ndarray:
    """[C, n_obs_features(cfg)] f32 for one constellation (no env batch
    axis — the environment vmaps this per env)."""
    qc = jnp.float32(max(cfg.queue_capacity, 1))
    run_frac = (jnp.sum(s.run.active, axis=-1).astype(jnp.float32)
                / jnp.float32(max(cfg.max_running, 1)))
    scalars = [
        s.l0.count.astype(jnp.float32) / qc,
        s.l1.count.astype(jnp.float32) / qc,
        s.ready.count.astype(jnp.float32) / qc,
        s.wait.count.astype(jnp.float32) / qc,
        run_frac,
        s.jobs_in_queue.astype(jnp.float32) / qc,
        st.avg_wait_ms(s) * 1e-3,  # seconds — same scale as the reward
    ]
    # per-device-type buckets: one-hot over the type axis, contracted
    # against active/free/cap (no gathers — the env batch vmaps this)
    free = F.widen(s.node_free).astype(jnp.float32)  # [C, N, R]
    cap = F.widen(s.node_cap).astype(jnp.float32)
    active = s.node_active.astype(jnp.float32)  # [C, N]
    nt = jnp.clip(s.node_type, 0, F.N_DEVICE_TYPES - 1)
    type_hot = (nt[..., None] == jnp.arange(
        F.N_DEVICE_TYPES, dtype=jnp.int32)) * active[..., None]  # [C, N, DT]
    n_nodes = jnp.float32(max(cfg.total_nodes, 1))
    active_frac = jnp.sum(type_hot, axis=1) / n_nodes  # [C, DT]
    free_dt = jnp.einsum("cnd,cnr->cdr", type_hot, free)  # [C, DT, R]
    cap_dt = jnp.einsum("cnd,cnr->cdr", type_hot, cap)
    free_frac = free_dt / jnp.maximum(cap_dt, 1.0)  # [C, DT, R]
    C = s.arr_ptr.shape[0]
    return jnp.concatenate(
        [jnp.stack(scalars, axis=-1), active_frac,
         free_frac.reshape(C, F.N_DEVICE_TYPES * cfg.n_res)], axis=-1)
