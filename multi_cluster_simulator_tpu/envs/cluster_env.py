"""The simulator as an on-device batched gym (ROADMAP item 2).

Decima (arxiv 1810.01963) and Blox (arxiv 2312.12621) train schedulers
against cluster simulators stepped ON THE HOST, one transition at a time.
Here the whole environment is the pure-JAX tick: ``ClusterEnv.step`` is the
engine's 7-phase tick body (``Engine.step_tick`` — the same code
``run_jit`` scans, bit-identical by construction) wrapped with observation,
reward, and auto-reset, and the batch axis is ``vmap`` over env instances —
thousands of constellations resident in device memory, stepping in one
compiled program with zero host round-trips:

- **per-env PRNG streams**: every ``EnvState`` carries its own key;
  ``step`` splits it (``jax.random.split``) and the generative workload
  draws each tick's arrivals from the split — never a key shared across
  the batch axis (simlint rule ``env-rng`` enforces the discipline).
- **auto-reset inside the compiled step**: ``done`` selects every state
  leaf back to the cached reset constellation (a ``jnp.where`` — i.e.
  ``lax.select`` — per leaf), so a 4k-env batch never syncs to the host to
  restart finished episodes.
- **actions are policy parameters**: the action enters the placement phase
  as the ``rl`` policy kind's ``rl_scores`` leaf (policies/), a
  [N_JOB_CLASSES, N_DEVICE_TYPES] score matrix feeding the same
  ``_scored_sweep_local`` accounting as the Gavel/Tesserae zoo members —
  scoring reused, not duplicated. Under the env vmap the leaf is per-env:
  exactly "a policy whose params are network outputs".
- **reward is data**: ``EnvState.reward_w`` weighs (negative mean wait,
  throughput, drop penalty); switching variants is a leaf write, not a
  recompile (REWARD_VARIANTS names the built-ins).
- **two workload modes**: ``arrivals=`` replays a host-bucketed
  ``TickArrivals`` episode shared by every env (batching is invisible to
  replay — PARITY.md), which is how the batch=1 cell is pinned
  bit-identical to ``Engine.run_jit``; ``gen=`` draws arrivals on device
  per tick from the env's key (workload/traces.tick_arrivals_device), the
  fully device-resident training regime.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.state import SimState, TickArrivals, init_state
from multi_cluster_simulator_tpu.envs.obs import n_obs_features, observe
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.workload.traces import tick_arrivals_device

# reward variants as data: (wait, throughput, drop) weights for
# EnvState.reward_w. wait is negated mean avg-wait in SECONDS, throughput
# is jobs placed this step, drop the summed drop-counter delta.
REWARD_VARIANTS = {
    "neg_mean_wait": (1.0, 0.0, 0.0),
    "throughput": (0.0, 1.0, 0.0),
    "drop_penalty": (1.0, 0.0, 10.0),
}


@dataclasses.dataclass(frozen=True)
class StreamGen:
    """Generative-mode workload parameters (static: they size the per-tick
    candidate tensor). ``rate`` is expected jobs per cluster per tick;
    ``k_max`` the static per-(tick, cluster) fanout bound — the analogue of
    the bucketed path's K."""

    rate: float = 2.0
    k_max: int = 8
    max_cores: int = 8
    max_mem: int = 6_000
    max_dur_ms: int = 20_000
    beta: float = 2.0


@struct.dataclass
class EnvState:
    """One env instance's carried state. All leaves are per-env (the batch
    axis is the leading vmap axis); ``key`` is this env's OWN stream —
    ``step`` splits it, auto-reset keeps splitting it, and no key is ever
    shared across the batch (env-rng)."""

    sim: SimState
    key: jax.Array  # per-env PRNG stream
    t_ep: jax.Array  # [] i32 — tick index within the current episode
    episodes: jax.Array  # [] i32 — completed (auto-reset) episodes
    reward_w: jax.Array  # [3] f32 — (wait, throughput, drop) weights


@struct.dataclass
class EnvInfo:
    """Per-step diagnostics (device values; coerce outside the step loop)."""

    placed: jax.Array  # [] i32 — jobs placed this step
    dropped: jax.Array  # [] i32 — drop-counter delta this step
    episodes: jax.Array  # [] i32 — completed episodes after this step
    t: jax.Array  # [] i32 — sim clock after the tick (pre-reset)


def _drop_sum(s: SimState) -> jax.Array:
    """In-graph total of every drop counter (plus the compact layouts'
    narrow-store overflow counters) — the traced form of
    utils/trace.total_drops, for the drop-penalty reward."""
    d = s.drops
    total = (jnp.sum(d.queue) + jnp.sum(d.msgs) + jnp.sum(d.run_full)
             + jnp.sum(d.vslot) + jnp.sum(d.carve) + jnp.sum(d.ingest)
             + jnp.sum(d.failed))
    for part in (s.l0, s.l1, s.ready, s.wait, s.lent, s.borrowed, s.run):
        if hasattr(part, "ovf"):
            total = total + jnp.sum(part.ovf)
    return total.astype(jnp.int32)


class ClusterEnv:
    """Batched ``reset(key) -> (obs, EnvState)`` /
    ``step(EnvState, action) -> (obs, reward, done, info, EnvState)`` over
    the simulation engine.

    ``policies`` defaults to the config's singleton set; pass
    ``PolicySet(("rl",))`` for the learned-scheduler action port (any other
    set ignores the action and runs its own policy — how the fifo oracle
    pin steps the env). Exactly one of ``arrivals`` (a host-bucketed
    TickArrivals covering >= episode_ticks, replayed identically by every
    env and every episode) or ``gen`` (a StreamGen drawn per tick from the
    env key) selects the workload mode. ``plan`` builds the compact SoA
    state layout (core/compact.py) — the env is layout-blind like the
    engine."""

    def __init__(self, cfg: SimConfig, specs, episode_ticks: int,
                 arrivals: TickArrivals | None = None,
                 gen: StreamGen | None = None, policies=None,
                 reward="neg_mean_wait", plan=None):
        if (arrivals is None) == (gen is None):
            raise ValueError("pass exactly one of arrivals= (replay) or "
                             "gen= (on-device generation)")
        if gen is not None and cfg.borrowing:
            raise ValueError(
                "generative mode emits tick-local job ids, and the "
                "borrowing return path matches borrowed rows on (id, "
                "cores, mem, dur) — gen= requires cfg.borrowing=False "
                "(replay a globally-id'd TickArrivals stream instead)")
        self.cfg = cfg
        self.specs = list(specs)
        self.engine = Engine(cfg, policies=policies)
        self.pset = self.engine.pset
        self.episode_ticks = int(episode_ticks)
        if self.episode_ticks < 1:
            raise ValueError("episode_ticks must be >= 1")
        if arrivals is not None and arrivals.rows.shape[0] < self.episode_ticks:
            raise ValueError(
                f"replay TickArrivals covers {arrivals.rows.shape[0]} ticks, "
                f"episode needs {self.episode_ticks}")
        self.gen = gen
        # commit replay rows to the device ONCE: numpy leaves passed to jit
        # re-transfer per call, which would be a per-step H2D
        self._arr = None if arrivals is None else jax.device_put(arrivals)
        self._params = self.pset.params_for(cfg)
        w = REWARD_VARIANTS[reward] if isinstance(reward, str) else reward
        self.reward_name = reward if isinstance(reward, str) else "custom"
        self._reward_w = jnp.asarray(np.asarray(w, np.float32))
        if self._reward_w.shape != (3,):
            raise ValueError("reward weights must be 3 floats "
                             "(wait, throughput, drop)")
        self._sim0 = init_state(cfg, specs, plan=plan)
        # generative churn trains under failure (ROADMAP "as many
        # scenarios as you can imagine"): each env folds its OWN reset key
        # into the per-cluster fault streams, so the batch sees
        # independent failure patterns (trace-mode tables replay
        # identically in every env, like replay arrivals)
        self._fault_gen = (cfg.faults.enabled
                           and cfg.faults.mode == "generative")
        # churn eligibility: the reset constellation's real machines
        # (faults/schedule.initial_next_fail — padding/vacant slots never
        # fail generatively)
        self._fault_eligible = self._sim0.node_active

    # -- geometry ----------------------------------------------------------

    @property
    def n_clusters(self) -> int:
        return len(self.specs)

    @property
    def n_obs(self) -> int:
        return n_obs_features(self.cfg)

    @property
    def action_shape(self) -> tuple:
        """The rl action matrix: per-class scores over node device types
        (the PolicyParams.rl_scores leaf the step substitutes)."""
        return (F.N_JOB_CLASSES, F.N_DEVICE_TYPES)

    def provenance(self, action=None) -> dict:
        """Policy provenance for bench/detail dicts: the registered policy
        name(s) + the concrete param digest (with the zero-action default
        when no action is given), plus the reward variant name."""
        params = self._params if action is None else self._params.replace(
            rl_scores=jnp.asarray(action, jnp.float32))
        return {"policy": self.engine.policy_provenance(params),
                "reward": self.reward_name}

    # -- reset -------------------------------------------------------------

    def reset(self, key):
        """One env instance: (obs, EnvState) from a per-env key. Batched
        form: ``reset_batch`` (vmap over split keys). With generative
        faults the env's churn streams derive from a branch of the reset
        key (``faults.reseed``) — never the base config seed shared across
        the batch (the env-rng discipline)."""
        sim = self._sim0
        if self._fault_gen:
            from multi_cluster_simulator_tpu.faults import schedule as fsch
            key, kf = jax.random.split(key)
            sim = sim.replace(faults=fsch.reseed(
                sim.faults, kf, self.cfg.faults,
                eligible=self._fault_eligible))
        es = EnvState(sim=sim, key=key, t_ep=jnp.int32(0),
                      episodes=jnp.int32(0), reward_w=self._reward_w)
        return observe(es.sim, self.cfg), es

    def reset_batch(self, key, n_envs: int):
        """B env instances with independent streams: the root key is split
        once and each env owns one branch."""
        keys = jax.random.split(key, n_envs)
        return jax.vmap(self.reset)(keys)

    # -- step --------------------------------------------------------------

    def _step(self, es: EnvState, action, sim0: SimState,
              arr: TickArrivals | None):
        """Single-env step body (vmapped/jitted by the *_fn builders).
        ``sim0``/``arr`` ride as broadcast arguments rather than closed-over
        constants so the compiled step does not bake a copy of the reset
        state per program."""
        cfg = self.cfg
        key, karr = jax.random.split(es.key)
        if arr is not None:
            rows = jax.lax.dynamic_index_in_dim(arr.rows, es.t_ep, 0,
                                                keepdims=False)
            counts = jax.lax.dynamic_index_in_dim(arr.counts, es.t_ep, 0,
                                                  keepdims=False)
        else:
            g = self.gen
            rows, counts = tick_arrivals_device(
                karr, es.sim.t + cfg.tick_ms, self.n_clusters, g.k_max,
                g.rate, g.max_cores, g.max_mem, g.max_dur_ms, g.beta)
        params = self._params if action is None else self._params.replace(
            rl_scores=jnp.asarray(action, jnp.float32))
        sim2 = self.engine.step_tick(es.sim, rows, counts, params=params)

        placed_d = (jnp.sum(sim2.placed_total)
                    - jnp.sum(es.sim.placed_total)).astype(jnp.int32)
        drops_d = _drop_sum(sim2) - _drop_sum(es.sim)
        wait_s = jnp.mean(st.avg_wait_ms(sim2)) * 1e-3
        reward = (es.reward_w[0] * (-wait_s)
                  + es.reward_w[1] * placed_d.astype(jnp.float32)
                  + es.reward_w[2] * (-drops_d.astype(jnp.float32)))

        done = (es.t_ep + 1) >= self.episode_ticks
        # auto-reset INSIDE the compiled step: done selects every sim leaf
        # back to the cached reset constellation — no host round-trip, ever
        sim3 = jax.tree.map(lambda fresh, cur: jnp.where(done, fresh, cur),
                            sim0, sim2)
        if self._fault_gen:
            # the broadcast reset state carries the BASE fault streams;
            # this env's churn must survive auto-reset, so keep the env's
            # per-cluster keys and re-derive the episode-0 failure clocks
            # from them (the same draw reseed() makes at reset time)
            from multi_cluster_simulator_tpu.faults import schedule as fsch
            fkeys = sim2.faults.key  # constant within an episode
            C, N = sim2.faults.health.shape
            nf0 = jax.vmap(lambda k, e: fsch.initial_next_fail(
                k, N, self.cfg.faults, e))(fkeys, self._fault_eligible)
            f3 = sim3.faults.replace(
                key=fkeys,
                next_fail=jnp.where(done, nf0, sim2.faults.next_fail))
            sim3 = sim3.replace(faults=f3)
        es2 = EnvState(
            sim=sim3, key=key,
            t_ep=jnp.where(done, jnp.int32(0), es.t_ep + 1),
            episodes=es.episodes + done.astype(jnp.int32),
            reward_w=es.reward_w)
        info = EnvInfo(placed=placed_d, dropped=drops_d,
                       episodes=es2.episodes, t=sim2.t)
        return observe(sim3, cfg), reward, done, info, es2

    def step_fn(self, donate: bool = False):
        """Jitted single-env step: ``(EnvState, action) -> (obs, reward,
        done, info, EnvState)``. The returned callable's ``_jit`` attribute
        is the underlying jit function (cache-count probes)."""
        fn = jax.jit(self._step, donate_argnums=(0,) if donate else ())
        sim0, arr = self._sim0, self._arr

        def call(es, action=None):
            return fn(es, action, sim0, arr)

        call._jit = fn
        return call

    def batch_step_fn(self, donate: bool = True):
        """The batched step: one compiled program advancing every env —
        ``(EnvState[B], action[B]) -> (obs[B], reward[B], done[B],
        info[B], EnvState[B])``. ``donate=True`` (default) donates the
        EnvState buffers so the whole batch updates in place in HBM; the
        caller's pre-call EnvState is invalid afterwards (clone with
        ``jax.tree.map(jnp.copy, es)`` if it must survive). The reset
        state and replay rows are broadcast arguments — one resident copy,
        not per-env, not per-program."""
        v = jax.vmap(self._step, in_axes=(0, 0, None, None))
        fn = jax.jit(v, donate_argnums=(0,) if donate else ())
        sim0, arr = self._sim0, self._arr

        def call(es, action=None):
            return fn(es, action, sim0, arr)

        call._jit = fn
        return call


def shard_env_batch(es: EnvState, mesh, axis: str = "envs"):
    """Shard a batched EnvState over ``mesh``'s ``axis``: every leaf splits
    on its leading (env) dimension via the same pytree-prefix placement the
    cluster mesh uses (parallel/sharded_engine) — envs are independent, so
    data-parallel jit needs no shard_map and results are bitwise identical
    to the unsharded batch (tests/test_env.py). The replication-sharding
    half of trace-parallel mode (ROADMAP item 3b): bench.py --env-bench
    records the measured device speedup when the mesh has more than one
    device."""
    from jax.sharding import PartitionSpec as P

    from multi_cluster_simulator_tpu.parallel.mesh import nearest_divisible
    from multi_cluster_simulator_tpu.parallel.sharded_engine import (
        _device_put_tree,
    )

    n = mesh.shape[axis]
    B = es.t_ep.shape[0] if es.t_ep.ndim else 1
    if B % n != 0:
        lo, hi = nearest_divisible(B, n)
        valid = f"{hi}" if lo == 0 else f"{lo} or {hi}"
        raise ValueError(
            f"env batch ({B}) must divide by mesh size ({n}); nearest "
            f"valid batch sizes: {valid}")
    return _device_put_tree(es, P(axis), mesh)
