"""envs/ — the simulator as an on-device batched gym (ARCHITECTURE.md
§environment mode): vmapped env instances over the engine's tick with
per-env PRNG streams, compiled auto-reset, the rl policy kind as the
action port, and pluggable reward weights as data."""

from multi_cluster_simulator_tpu.envs.cluster_env import (
    REWARD_VARIANTS, ClusterEnv, EnvInfo, EnvState, StreamGen,
    shard_env_batch,
)
from multi_cluster_simulator_tpu.envs.obs import n_obs_features, observe

__all__ = [
    "REWARD_VARIANTS", "ClusterEnv", "EnvInfo", "EnvState", "StreamGen",
    "shard_env_batch", "n_obs_features", "observe",
]
