"""The preemption plane: resumable bit-identical long runs for the batch tier.

PR 13 made the *serving* tier crash-proof (WAL + checkpointed watermark);
this module is the batch half — on TPU pods preemption is the dominant
failure mode, and a multi-hour sharded record must be a restartable unit,
not an all-or-nothing job (the Blox framing, arxiv 2312.12621). Three
pieces, composed by the chunked drivers (bench._engine_run,
tools/weak_scaling.py) and chaos-gated by ``tools/chaos.py --batch``:

- **RunCheckpoint** — the widened checkpoint bundle: everything a resumed
  run needs to be bit-exact AND to report whole-run provenance. SimState
  (which carries the fault plane's churn clocks ``next_fail``/
  ``down_until``, retry budgets, and interval cursors — churn is state, so
  it rides for free), the obs ``MetricsBuffer`` carry (so a resumed run's
  harvest covers the whole logical run), and the driver's resume cursors:
  completed tick, chunk index (the stream position ``pack_arrivals_chunks``
  re-buckets from via ``start=``), and the time-compression provenance
  accumulated so far (``ticks_executed`` + the log2 leap histogram), which
  telescopes across kill/resume cycles to exactly the uninterrupted run's
  totals. The header embeds the SimConfig/compact-plan/policy-params
  validity record (core/checkpoint.py v2), so a wrong-config or
  wrong-plan resume fails fast with a named field.

- **AsyncCheckpointer** — checkpoint writes OFF the dispatch path. At a
  chunk boundary the driver ``submit``s the live device refs; submit takes
  a device-side snapshot (``jnp.copy`` — an async device op enqueued
  BEFORE the next chunk's donating dispatch can consume the buffers) and
  returns immediately; a background worker thread then blocks on the
  snapshot, gathers it to host (a sharded state's global leaves gather
  across the addressable mesh), serializes, and atomic-renames. This
  retires the pragma'd blocking ``block_until_ready`` + synchronous
  ``save_state`` the bench chunk loop used to pay per boundary (the one
  sanctioned ``det-chunk-sync`` suppression — gone). Submissions are
  latest-wins: if the disk cannot keep up, intermediate snapshots are
  skipped (counted), never queued without bound — a skipped checkpoint
  only means a resume redoes more ticks, still bit-identically.

- **PreemptionGuard** — SIGTERM (the preemption signal pods actually get)
  sets a flag the driver checks at every chunk boundary: save, flush,
  and exit ``EXIT_PREEMPTED`` cleanly. kill -9 needs no handler — the
  latest atomic checkpoint is the resume point (tools/chaos.py --batch
  proves both paths).
"""

from __future__ import annotations

import dataclasses
import signal
import sys
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from multi_cluster_simulator_tpu.core import checkpoint as ck
from multi_cluster_simulator_tpu.core.state import LEAP_BUCKETS

# sysexits EX_TEMPFAIL: "try again later" — the conventional exit code for
# a clean save-and-exit under preemption; schedulers treat it as retryable
EXIT_PREEMPTED = 75

_UNSET = ck._UNSET


def policy_digest_for(cfg) -> str:
    """The default policy-params digest a config-built engine runs with —
    what the checkpoint header records so a resume under edited policy
    parameters fails fast (params are DATA, so shapes alone cannot catch
    it). Matches ``Engine(cfg).policy_provenance()['params_digest']``."""
    from multi_cluster_simulator_tpu.policies.base import (
        PolicySet, params_digest,
    )
    pset = PolicySet.from_config(cfg)
    return params_digest(pset.params_for(cfg))


@dataclasses.dataclass
class RunCheckpoint:
    """A loaded run bundle: the restored state (+ optional MetricsBuffer
    carry) and the resume cursors from the header."""

    state: Any
    mbuf: Any  # MetricsBuffer or None
    meta: dict  # tick, chunk_idx, ticks_executed, leap_hist, ...

    @property
    def tick(self) -> int:
        return int(self.meta.get("tick", 0))


def fold_cursors(dense_ticks: int, leap_stats, prior: Optional[dict] = None
                 ) -> tuple[int, list]:
    """THE telescoping fold for the time-compression cursors — one
    definition, used by both the checkpoint writer (``_finalize_meta``)
    and the bench detail reporting, so the chaos gate's
    cursors-must-telescope assertion can never drift against the numbers
    the detail prints. Host-side (coerces device LeapStats refs).
    Returns ``(ticks_executed, leap_hist)``: this run's dense-chunk ticks
    plus the compressed chunks' executed ticks, accumulated onto the
    ``prior`` cursors a resume loaded."""
    prior = prior or {}
    executed = int(dense_ticks)
    hist = np.zeros((LEAP_BUCKETS,), np.int64)
    for ls in leap_stats or []:
        executed += int(np.asarray(ls.ticks_executed))
        hist += np.asarray(ls.leaps, np.int64)
    prior_hist = prior.get("leap_hist") or []
    hist[: len(prior_hist)] += np.asarray(prior_hist, np.int64)
    executed += int(prior.get("ticks_executed", 0))
    nz = np.flatnonzero(hist)
    return executed, (hist[: nz[-1] + 1].tolist() if len(nz) else [])


def _finalize_meta(meta: dict) -> dict:
    """Resolve the device-ref provenance a submit carried into host ints —
    runs on the WORKER thread (host coercions here never stall the
    dispatch loop). ``dense_ticks`` counts this run's dense-chunk ticks;
    ``leap_stats`` is the compressed chunks' device LeapStats list;
    ``prior`` is the meta loaded at resume, so the cursors accumulate
    across kill/resume cycles exactly like the state does."""
    meta = dict(meta)
    prior = meta.pop("prior", None) or {}
    leap_stats = meta.pop("leap_stats", None) or []
    executed, hist = fold_cursors(meta.pop("dense_ticks", 0), leap_stats,
                                  prior)
    meta["ticks_executed"] = executed
    meta["leap_hist"] = hist
    return meta


def save_run(path: str, state, mbuf=None, meta: Optional[dict] = None,
             cfg=None, plan=_UNSET, policy_digest: Optional[str] = None,
             tick_ms: int = 1000) -> None:
    """Write a RunCheckpoint synchronously (the AsyncCheckpointer's worker
    calls this; tests and small drivers call it directly). ``meta`` may
    carry device refs under ``leap_stats``/``dense_ticks``/``prior`` —
    they are resolved here, host-side."""
    meta = _finalize_meta(meta or {})
    mbuf = _reduce_mbuf_partials(mbuf)
    bundle = {"state": state}
    if mbuf is not None:
        bundle["mbuf"] = mbuf
    meta.setdefault("tick", int(np.asarray(state.t)) // max(int(tick_ms), 1))
    ck.save_tree(bundle, path, t=int(np.asarray(state.t)),
                 extra={"run": {**meta, "has_mbuf": mbuf is not None}},
                 cfg=cfg, plan=plan, policy_digest=policy_digest)


def _reduce_mbuf_partials(mbuf):
    """Fold a MetricsBuffer's shard-local partial leaves (leading axis =
    one row per shard) down to a single row before serializing: totals are
    preserved, and the saved buffer becomes MESH-INDEPENDENT — a run
    checkpointed on the 8-device mesh resumes on 1 device (or vice versa)
    with ``ShardedEngine.shard_metrics`` re-widening row 0 + zeros. The
    contract for the obs carry across a cut is harvest-equality, which the
    reduction preserves exactly (harvest sums the shard axis anyway)."""
    if mbuf is None:
        return None
    host = jax.tree.map(np.asarray, mbuf)

    def fold(a):  # keep the storage dtype (np.sum promotes to int64)
        return a.sum(axis=0, keepdims=True, dtype=a.dtype)

    return host.replace(depth_hist=fold(host.depth_hist),
                        ring_placed=fold(host.ring_placed),
                        ring_depth=fold(host.ring_depth))


def load_run(path: str, state_template, cfg=None, plan=_UNSET,
             policy_digest: Optional[str] = None) -> RunCheckpoint:
    """Load a RunCheckpoint (header verified first — version, config,
    plan, policy). The MetricsBuffer template is derived from the state
    template (``obs.metrics_init``), so callers need no obs plumbing to
    restore a buffer-carrying bundle."""
    header = ck._read_header(path)
    ck._check_header(header, path, cfg=cfg, plan=plan,
                     policy_digest=policy_digest)
    run_meta = dict((header.get("extra") or {}).get("run") or {})
    has_mbuf = bool(run_meta.pop("has_mbuf", False))
    template = {"state": state_template}
    if has_mbuf:
        from multi_cluster_simulator_tpu.obs.device import metrics_init
        template["mbuf"] = metrics_init(state_template)
    bundle = ck.load_tree(path, template, cfg=cfg, plan=plan,
                          policy_digest=policy_digest)
    return RunCheckpoint(state=bundle["state"], mbuf=bundle.get("mbuf"),
                         meta=run_meta)


class AsyncCheckpointer:
    """Background-thread checkpoint writer for chunked drivers.

    ``submit`` is what the dispatch loop calls at a chunk boundary: it
    snapshots the live device refs with ``jnp.copy`` (async device-side
    copies, enqueued before the next chunk's donating dispatch can consume
    the originals — donation safety is exactly why the snapshot exists)
    and hands them to the worker. All blocking work — waiting for the
    snapshot to compute, the device→host gather, serialization, fsync,
    atomic rename — happens on the worker thread. ``flush`` drains the
    queue and re-raises any worker error; call it after the run loop (and
    before trusting the final checkpoint).

    Latest-wins: a submit that arrives while an older snapshot is still
    waiting REPLACES it (``skipped`` counts them). The final submit of a
    run is therefore always written; intermediate cadence under a slow
    disk degrades to sparser resume points, never to unbounded memory or
    a stalled dispatch loop."""

    def __init__(self, path: str, cfg=None, plan=_UNSET,
                 policy_digest: Optional[str] = None, tick_ms: int = 1000,
                 save_fn=None):
        self.path = path
        self._cfg, self._plan, self._pdigest = cfg, plan, policy_digest
        self._tick_ms = tick_ms
        self._save_fn = save_fn if save_fn is not None else save_run
        self._cond = threading.Condition()
        self._pending = None  # (state_snap, mbuf_snap, meta) — latest wins
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self.writes = 0
        self.skipped = 0
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mcs-ckpt-writer")
        self._thread.start()

    def submit(self, state, mbuf=None, meta: Optional[dict] = None) -> None:
        snap_state = jax.tree.map(jnp.copy, state)
        snap_mbuf = (jax.tree.map(jnp.copy, mbuf)
                     if mbuf is not None else None)
        with self._cond:
            if self._error is not None:
                raise RuntimeError(
                    "async checkpoint writer already failed"
                ) from self._error
            if self._pending is not None:
                self.skipped += 1
            self._pending = (snap_state, snap_mbuf, dict(meta or {}))
            self._cond.notify_all()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None and self._stop:
                    return
                state, mbuf, meta = self._pending
                self._pending = None
                self._busy = True
            try:
                self._save_fn(self.path, state, mbuf=mbuf, meta=meta,
                              cfg=self._cfg, plan=self._plan,
                              policy_digest=self._pdigest,
                              tick_ms=self._tick_ms)
                with self._cond:
                    self.writes += 1
            except BaseException as e:  # surfaced by flush/close
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted snapshot is durably on disk (or the
        worker failed — the stored error re-raises here, never silently)."""
        with self._cond:
            self._cond.wait_for(
                lambda: (self._pending is None and not self._busy)
                or self._error is not None, timeout=timeout)
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    f"async checkpoint write to {self.path} failed") from err
            if self._pending is not None or self._busy:
                raise TimeoutError(
                    f"async checkpoint flush timed out after {timeout}s")

    def close(self) -> None:
        """Flush (raising any stored worker error), then stop the worker.
        Idempotent; ``abort`` afterwards is a no-op."""
        try:
            self.flush()
        finally:
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            self._thread.join(timeout=30)

    def abort(self) -> None:
        """Best-effort shutdown for cleanup paths: drop any pending
        snapshot, stop the worker, never raise — exception unwinds must
        not leak the thread (the success path calls ``close``, which DOES
        surface errors, first)."""
        with self._cond:
            self._pending = None
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)


class PreemptionGuard:
    """SIGTERM → save-and-exit at the next chunk boundary.

    Installing replaces the handler (previous one restored on
    ``uninstall``/context exit); the handler only sets a flag — all real
    work (submit, flush, exit) happens on the driver thread at a chunk
    boundary, where the state is a consistent cut. Drivers exit with
    ``EXIT_PREEMPTED`` so wrappers can distinguish a clean preemption
    save from a failure. Signal handlers only install from the main
    thread; elsewhere the guard degrades to an inert flag (``installed``
    False) rather than raising — a library must not fight the host
    process over signal ownership."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._old: dict = {}
        self.installed = False

    def install(self) -> "PreemptionGuard":
        for sig in self._signals:
            try:
                self._old[sig] = signal.signal(sig, self._on_signal)
                self.installed = True
            except (ValueError, OSError):  # non-main thread / exotic host
                pass
        return self

    def uninstall(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        self.installed = False

    def _on_signal(self, signum, frame) -> None:
        self._event.set()

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def save_and_exit(self, checkpointer: AsyncCheckpointer, state,
                      mbuf=None, meta: Optional[dict] = None) -> None:
        """The boundary action: submit the current cut, wait until it is
        durable, announce, exit. Never returns."""
        checkpointer.submit(state, mbuf=mbuf, meta=meta)
        checkpointer.flush()
        tick = ck.peek_checkpoint_t(checkpointer.path)
        print(f"# preempted: checkpoint saved at t={tick} ms -> "
              f"{checkpointer.path}", file=sys.stderr)
        sys.stderr.flush()
        sys.exit(EXIT_PREEMPTED)
