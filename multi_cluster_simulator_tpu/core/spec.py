"""Cluster topology specs and loaders.

The reference's whole topology config surface is a JSON file decoded straight
into its Go ``Cluster`` struct (cmd/scheduler/main.go:52-59,
assets/cluster_small.json). We accept the same JSON schema (capitalized Go
field names) plus a snake_case variant, and convert to padded arrays.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Sequence

import numpy as np

# Resource axis: [cores, memory, gpus]. The reference tracks only
# cores/memory (Node, cluster.go:127-138); the gpu axis is the 3-dim
# extension demanded by BASELINE.json config 4 ("Sinkhorn trader matching,
# ... 3-dim resources (cpu/mem/gpu)"). Reference-parity configs leave every
# gpu count at 0, which makes the axis inert (0 >= 0 feasibility).
RES = 3
CORES, MEM, GPU = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class NodeSpec:
    """One machine. Reference: Node, pkg/scheduler/cluster.go:127-138."""

    id: int
    cores: int
    memory: int
    gpus: int = 0  # 3-dim extension; 0 in every reference asset
    type: str = "physical"
    # Device type for the heterogeneity-aware policies (ops/fields.py
    # N_DEVICE_TYPES; Gavel, arxiv 2008.09213). -1 = derive: accelerator
    # (1) when the node has gpu capacity, standard (0) otherwise. The
    # reference has no analogue — parity policies never read it.
    device_type: int = -1


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """One cluster of nodes. Reference: Cluster, pkg/scheduler/cluster.go:14-24."""

    id: int
    nodes: tuple[NodeSpec, ...]

    @property
    def total_cores(self) -> int:
        return sum(n.cores for n in self.nodes)

    @property
    def total_memory(self) -> int:
        return sum(n.memory for n in self.nodes)

    def to_json(self, url: str = "") -> dict:
        """Serialize in the reference's Go-struct JSON shape (for
        /newClient): the full exported field set of Cluster/Node
        (cluster.go:14-24,127-138) in struct order, so ``json.dumps(...,
        separators=(",", ":"))`` is byte-identical to Go's json.Marshal of
        a fresh cluster (nil RunningJobs map -> null, zero Durations -> 0).
        ``Gpus`` (a 3-dim-resource extension with no Go analogue) is
        appended only when nonzero — Go decoders ignore unknown fields, and
        gpu-less specs stay byte-exact."""
        nodes = []
        for n in self.nodes:
            d = {
                "Id": n.id,
                "Type": n.type,
                "URL": "",
                "Memory": n.memory,
                "Cores": n.cores,
                "MemoryAvailable": n.memory,
                "CoresAvailable": n.cores,
                "RunningJobs": None,
                "Time": 0,
            }
            if n.gpus:
                d["Gpus"] = n.gpus
            nodes.append(d)
        return {
            "Id": self.id,
            "Nodes": nodes,
            "URL": url,
            "TotalMemory": self.total_memory,
            "TotalCore": self.total_cores,
            "MemoryUtilization": 0,
            "CoreUtilization": 0,
        }


def _node_from_json(d: dict) -> NodeSpec:
    def g(*names, default=None):
        for n in names:
            if n in d:
                return d[n]
        if default is not None:
            return default
        raise KeyError(f"missing any of {names} in node spec {d}")

    return NodeSpec(
        id=int(g("Id", "id")),
        cores=int(g("Cores", "cores")),
        memory=int(g("Memory", "memory")),
        gpus=int(g("Gpus", "gpus", default=0)),
        type=str(g("Type", "type", default="physical")),
        device_type=int(g("DeviceType", "device_type", default=-1)),
    )


def cluster_from_json(d: dict) -> ClusterSpec:
    nodes = tuple(_node_from_json(n) for n in d.get("Nodes", d.get("nodes", [])))
    return ClusterSpec(id=int(d.get("Id", d.get("id", 0))), nodes=nodes)


def load_cluster_json(path: str) -> ClusterSpec:
    """Load a cluster spec from the reference's assets JSON schema."""
    with open(path) as f:
        return cluster_from_json(json.load(f))


def uniform_cluster(cluster_id: int, n_nodes: int, cores: int = 32,
                    memory: int = 24_000, gpus: int = 0,
                    device_type: int = -1) -> ClusterSpec:
    """Synthesize a cluster of identical nodes (the shape of both reference
    assets: 5 or 10 nodes x 32 cores x 24000 MB)."""
    return ClusterSpec(
        id=cluster_id,
        nodes=tuple(NodeSpec(id=i + 1, cores=cores, memory=memory, gpus=gpus,
                             device_type=device_type)
                    for i in range(n_nodes)),
    )


def node_types_array(specs: Sequence[ClusterSpec], max_nodes: int) -> np.ndarray:
    """Stack per-node device types into a padded [C, max_nodes] int32 tensor
    (the node half of the heterogeneity schema — ops/fields.py). A spec's
    explicit ``device_type`` wins; -1 derives accelerator (1) from gpu
    capacity; padded slots are standard (0, and never feasible anyway)."""
    C = len(specs)
    types = np.zeros((C, max_nodes), dtype=np.int32)
    for c, spec in enumerate(specs):
        for i, n in enumerate(spec.nodes[:max_nodes]):
            types[c, i] = n.device_type if n.device_type >= 0 else (
                1 if n.gpus > 0 else 0)
    return types


def capacities_array(specs: Sequence[ClusterSpec], max_nodes: int) -> np.ndarray:
    """Stack cluster specs into a padded [C, max_nodes, RES] int32 capacity
    tensor. Padded node slots have zero capacity (never feasible)."""
    C = len(specs)
    cap = np.zeros((C, max_nodes, RES), dtype=np.int32)
    for c, spec in enumerate(specs):
        if len(spec.nodes) > max_nodes:
            raise ValueError(
                f"cluster {spec.id} has {len(spec.nodes)} nodes > max_nodes={max_nodes}"
            )
        for i, n in enumerate(spec.nodes):
            cap[c, i, CORES] = n.cores
            cap[c, i, MEM] = n.memory
            cap[c, i, GPU] = n.gpus
    return cap
