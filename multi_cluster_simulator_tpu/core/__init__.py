from multi_cluster_simulator_tpu.core.spec import ClusterSpec, NodeSpec, load_cluster_json
from multi_cluster_simulator_tpu.core.state import SimState, init_state
from multi_cluster_simulator_tpu.core.engine import Engine
from multi_cluster_simulator_tpu.core.checkpoint import load_state, peek_checkpoint_t, save_state

__all__ = ["ClusterSpec", "NodeSpec", "load_cluster_json", "SimState", "init_state",
           "Engine", "save_state", "load_state", "peek_checkpoint_t"]
