"""The virtual-time simulation engine.

The reference advances time by sleeping: scheduler loops tick every wall
second (pkg/scheduler/scheduler.go:250,294,367) and every running job is a
goroutine in ``time.Sleep(j.Duration)`` (cluster.go:141-161), so simulating
X seconds of cluster time takes X seconds of wall time. Here one ``tick``
is a pure function on ``SimState`` advanced under ``lax.scan`` — 1 ms of
virtual time costs nanoseconds — with every per-cluster phase ``vmap``-ed
over the cluster axis and every cross-cluster phase written as batched array
ops (which become XLA collectives when the cluster axis is sharded).

Tick phase order (the documented determinization of the reference's
concurrent goroutines — see PARITY.md):

  1. completions with ``end_t <= t`` release resources (RunJob wakeups);
     finished foreign jobs are returned to their borrower (JobFinished ->
     ReturnToBorrower -> /lent, scheduler.go:158-191, server.go:260-290)
  2. expired virtual nodes deactivate (optional; the reference never
     removes them — cluster.go:65-85)
  3. arrivals with ``arr_t <= t`` enqueue (client POST /delay or /,
     server.go:22-78)
  4. the policy's scheduling pass:
     DELAY — Level1 sweep then Level0 head + promotion (Delay(),
       scheduler.go:298-369), including in parity mode the remove-then-skip
       iteration quirk of the Level1 loop (scheduler.go:305-327)
     FIFO — wait-head attempt / ready drain-to-first-failure / lent
       best-effort (Fifo(), scheduler.go:216-296), emitting borrow
       requests on wait-head failure (BorrowResources, server.go:160-248)
     FFD — first-fit-decreasing bin-pack over Level0 (TPU-side upgrade,
       BASELINE.json config 3)
  5. cross-cluster borrow matching: feasibility over all lenders, lowest
     cluster index wins (the deterministic version of Go's
     first-200-OK-wins race, server.go:219-247)
  6. trader state snapshot on the 5 s stream cadence (trader_server.go:24-47)
     — refreshed before any trade in the same tick (MARKET.md §clock)
  7. trader market round on the monitor cadence (market/, trader.go:280-325)
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import PolicyKind, SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.state import Arrivals, SimState, Trace
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import placement as P
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R

# vmap prefix: map every per-cluster field over axis 0, broadcast the clock.
_STATE_AXES = SimState(
    t=None, node_cap=0, node_free=0, node_active=0, node_expire=0,
    l0=0, l1=0, ready=0, wait=0, lent=0, borrowed=0, run=0, arr_ptr=0,
    wait_total=0, wait_jobs=0, jobs_in_queue=0, placed_total=0, drops=0,
    trader=0, trace=0,
)
_ARR_AXES = Arrivals(t=0, id=0, cores=0, mem=0, gpu=0, dur=0, n=0)


@struct.dataclass
class TickIO:
    """Per-tick host-visible events — what a live service host must act on
    over the network instead of in-batch (services/scheduler_host.py).

    ``borrow_want``/``borrow_job`` are the failing wait-head *before* any
    in-batch borrow matching (the BorrowResources call site,
    scheduler.go:234); ``ret_rows``/``ret_valid`` are the finished
    foreign-job return messages (ReturnToBorrower, server.go:260-290)."""

    borrow_want: jax.Array  # [C] bool
    borrow_job: jax.Array  # [C, Q.NF] i32
    ret_rows: jax.Array  # [C, max_msgs, R.RF] i32
    ret_valid: jax.Array  # [C, max_msgs] bool


def _trace_append(tr: Trace, do, t, job_id, node, src):
    """Per-cluster capped event append (single-cluster view)."""
    cap = tr.t.shape[-1]
    ok = jnp.logical_and(do, tr.n < cap)
    i = jnp.clip(tr.n, 0, cap - 1)

    def w(a, v):
        return a.at[i].set(jnp.where(ok, v, a[i]))

    return Trace(t=w(tr.t, t), job=w(tr.job, job_id), node=w(tr.node, node),
                 src=w(tr.src, jnp.int32(src)), n=tr.n + ok.astype(jnp.int32))


def _attempt(s: SimState, job: Q.JobRec, t, do, src, record_trace: bool):
    """One ScheduleJob(j) attempt (scheduler.go:127-139) on a single cluster:
    first-fit over nodes; on success occupy resources and start the job.

    A full running set makes the attempt fail (job stays queued) rather than
    leak resources — a documented divergence (PARITY.md): size
    ``max_running`` so it never binds.

    One shared body with the sweep loops: a single-row deferred buffer
    flushed immediately (start_many of one row == start), so placement
    accounting can never drift between the head attempts and the sweeps."""
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    buf = jnp.zeros((1, R.RF), jnp.int32)
    s, success, buf, cnt = _attempt_deferred(s, job, t, do, src, record_trace,
                                             buf, jnp.int32(0), n_active)
    return s.replace(run=R.start_many(s.run, buf, cnt)), success


def _attempt_deferred(s: SimState, job: Q.JobRec, t, do, src,
                      record_trace: bool, buf, cnt, n_active):
    """``_attempt`` for placement-sweep loops: identical semantics, but the
    RunningSet insertion is deferred — the placed row lands in ``buf`` at
    position ``cnt`` (a [SW, RF] scratch, SW = sweep bound) and the caller
    flushes the batch with ``R.start_many`` after the loop. The [S]-sized
    set is then touched once per tick instead of once per sweep step, which
    dominated the per-tick cost at thousands of clusters. ``n_active`` is
    the set's occupancy at loop entry; ``n_active + cnt`` reproduces the
    sequential has-slot check exactly."""
    node = P.first_fit(s.node_free, s.node_active, job)
    has_slot = (n_active + cnt) < s.run.capacity
    success = jnp.logical_and(jnp.logical_and(do, has_slot), node >= 0)
    free = P.occupy(s.node_free, node, job, success)
    row = R.row_from_job(job, node, t)
    hot = jnp.logical_and(jnp.arange(buf.shape[0], dtype=jnp.int32) == cnt,
                          success)
    buf = jnp.where(hot[:, None], row, buf)
    cnt = cnt + success.astype(jnp.int32)
    trace = _trace_append(s.trace, success, t, job.id, node, src) if record_trace else s.trace
    run_full = jnp.logical_and(jnp.logical_and(do, node >= 0),
                               jnp.logical_not(has_slot))
    drops = s.drops.replace(run_full=s.drops.run_full + run_full.astype(jnp.int32))
    s = s.replace(node_free=free, trace=trace, drops=drops,
                  placed_total=s.placed_total + success.astype(jnp.int32))
    return s, success, buf, cnt


def _sweep_len(cfg: SimConfig) -> int:
    """Per-tick placement-sweep length: the whole queue in parity mode, the
    fast-mode cap otherwise (PARITY.md §divergences)."""
    if cfg.parity:
        return cfg.queue_capacity
    return min(cfg.queue_capacity, cfg.max_placements_per_tick)


def _record_wait(total, rec_wait, enq_t, t, do):
    """JobsMap bookkeeping on a scheduling attempt (scheduler.go:309-312):
    TotalTime -= map[id]; map[id] = since(enqueue); TotalTime += map[id]."""
    cur = (t - enq_t).astype(jnp.int32)
    delta = jnp.where(do, (cur - rec_wait).astype(jnp.float32), 0.0)
    return total + delta, jnp.where(do, cur, rec_wait)


# --------------------------------------------------------------------------
# time compression: quiescence predicate + next-event probe + leap accrual
# (the event-compressed driver, Engine.run_compressed)
# --------------------------------------------------------------------------

def _quiescence_sig(state: SimState) -> jax.Array:
    """Fixed-point fingerprint for the leap driver: a vector of scalar
    sums that changes whenever a tick mutates anything the NEXT tick's
    decisions can read. Queue membership, placements, completions,
    arrivals, node activations, and every drop counter are covered; the
    fields deliberately excluded — the clock, wait accounting
    (wait_total / FREC), and the trader's snapshot/cooldown/lock columns —
    either evolve in closed form over a leap or are only read at cadence
    boundaries the driver never skips (market.trader.next_cadence_t).

    Two executed ticks with equal fingerprints around an event-free gap
    therefore prove every tick in the gap is a no-op modulo wait accrual:
    the pass is a pure function of (queues, nodes, run, t), and its only
    t-dependence is wait recording plus the promotion threshold, which the
    next-event probe handles (``_next_event_t``)."""
    d = state.drops
    # compact layouts carry narrow-store overflow counters; fold them into
    # the drop sum so an overflow tick can never be judged quiescent
    ovf = jnp.int32(0)
    for part in (state.l0, state.l1, state.ready, state.wait, state.lent,
                 state.borrowed, state.run):
        if hasattr(part, "ovf"):
            ovf = ovf + jnp.sum(part.ovf)
    parts = [
        jnp.sum(state.placed_total), jnp.sum(state.arr_ptr),
        jnp.sum(state.run.active.astype(jnp.int32)),
        jnp.sum(state.l0.count), jnp.sum(state.l1.count),
        jnp.sum(state.ready.count), jnp.sum(state.wait.count),
        jnp.sum(state.lent.count), jnp.sum(state.borrowed.count),
        jnp.sum(state.node_active.astype(jnp.int32)),
        jnp.sum(d.queue) + jnp.sum(d.msgs) + jnp.sum(d.run_full)
        + jnp.sum(d.vslot) + jnp.sum(d.carve) + jnp.sum(d.ingest) + ovf,
    ]
    return jnp.stack([p.astype(jnp.int32) for p in parts])


def _next_event_t(state: SimState, t, cfg: SimConfig) -> jax.Array:
    """Earliest future virtual time at which a quiescent constellation can
    change state again (shard-local; the driver ``allmin``s across shards
    and folds in the next nonempty arrival tick separately):

    - a completion: min ``end_t`` over the RunningSet (R.next_end_t) —
      releases fire at the first tick clock >= end_t;
    - a DELAY Level0->Level1 promotion: at a fixed point the head keeps
      failing, so it promotes at the first tick clock >=
      ``enq_t + max_wait_ms`` (scheduler.go:348-366);
    - a market cadence boundary (stream snapshot / monitor round) and, in
      sane mode, a virtual-node expiry.

    Values are raw event times; the driver rounds up to the tick grid."""
    ev = jnp.min(jax.vmap(R.next_end_t)(state.run))
    if cfg.policy == PolicyKind.DELAY:
        head_enq = state.l0.enq_t[:, 0]  # [C]
        promote = jnp.where(state.l0.count > 0,
                            head_enq + jnp.int32(cfg.max_wait_ms), R.NEVER)
        ev = jnp.minimum(ev, jnp.min(promote))
    if cfg.trader.enabled:
        from multi_cluster_simulator_tpu.market.trader import next_cadence_t
        ev = jnp.minimum(ev, next_cadence_t(t, cfg.trader))
        if cfg.trader.expire_virtual_nodes:
            ev = jnp.minimum(ev, jnp.min(jnp.where(
                state.node_active, state.node_expire, R.NEVER)))
    return ev


def _leap_wait_masks_local(s: SimState, cfg: SimConfig):
    """Queue slots whose wait clock the scheduling pass advances every tick
    at a placement fixed point — exactly the slots the dense pass calls
    ``_record_wait`` on when nothing places: (l0_mask, l1_mask), single
    cluster view. FIFO records no wait in the pass, DELAY processes the
    first ``min(|L1|, QC)`` Level1 slots plus the Level0 head, FFD the
    first ``min(|L0|, QC)`` slots in best-fit-decreasing order."""
    cap0 = s.l0.capacity
    if cfg.policy == PolicyKind.FIFO:
        z = jnp.zeros((cap0,), bool)
        return z, jnp.zeros((s.l1.capacity,), bool)
    QC = _sweep_len(cfg)
    if cfg.policy == PolicyKind.DELAY:
        l1_mask = jnp.logical_and(
            s.l1.slot_valid(),
            jnp.arange(s.l1.capacity, dtype=jnp.int32)
            < jnp.minimum(s.l1.count, QC))
        l0_mask = jnp.logical_and(
            jnp.arange(cap0, dtype=jnp.int32) == 0, s.l0.count > 0)
        return l0_mask, l1_mask
    # FFD: slots selected by the first n_sweep positions of the BFD order
    order = P.best_fit_decreasing_order(s.l0.cores, s.l0.mem, s.l0.slot_valid())
    n_sweep = jnp.minimum(s.l0.count, QC)
    hot = order[:, None] == jnp.arange(cap0, dtype=jnp.int32)[None, :]
    taken = jnp.arange(cap0, dtype=jnp.int32) < n_sweep  # order positions
    l0_mask = jnp.any(jnp.logical_and(hot, taken[:, None]), axis=0)
    return l0_mask, jnp.zeros((s.l1.capacity,), bool)


def _leap_local(s: SimState, new_t, do, cfg: SimConfig):
    """Advance one cluster's wait accounting from ``s.t`` to ``new_t`` in
    closed form — the per-tick ``_record_wait`` deltas over a quiescent gap
    telescope: TotalTime -= map[id]; map[id] = since(enqueue); TotalTime +=
    map[id] per tick sums to ``new_cur - old_rec`` per still-queued
    processed slot (scheduler.go:309-312). Returns ``(state', rate)`` with
    ``rate`` the per-tick f32 accrual (processed slots x tick_ms) the
    metric reconstruction uses for the skipped samples.

    ``do`` is the quiescence vote and must gate the whole accrual, not
    just the leap distance: after a NON-quiescent tick the masks below are
    computed from post-tick state and can cover slots the pass did not
    process this tick (a successor rotated into the Level0 head, say),
    whose stale FREC would accrue a delta the dense driver only records a
    tick later — wrong at a run or chunk boundary even though it
    telescopes out mid-run.

    Bit-parity domain: the dense path folds one float32 add per tick (per
    slot in the serial sweeps); the closed form adds the telescoped sum
    once. Both are exact — hence bit-identical — while the accrued values
    are integer-valued float32 below 2^24 ms, which every parity surface
    satisfies by orders of magnitude (PARITY.md §time compression)."""
    l0_mask, l1_mask = _leap_wait_masks_local(s, cfg)
    l0_mask = jnp.logical_and(l0_mask, do)
    l1_mask = jnp.logical_and(l1_mask, do)

    def accrue(q, mask, total):
        cur = (new_t - q.enq_t).astype(jnp.int32)
        frec = q.rec_wait
        delta = jnp.where(mask, (cur - frec).astype(jnp.float32), 0.0)
        q = Q.set_field(q, "rec_wait", jnp.where(mask, cur, frec))
        return q, total + delta.sum()

    # dense tick order: the Level1 sweep accrues before the Level0 head
    l1, total = accrue(s.l1, l1_mask, s.wait_total)
    l0, total = accrue(s.l0, l0_mask, total)
    rate = (l0_mask.sum() + l1_mask.sum()).astype(jnp.float32) * cfg.tick_ms
    return s.replace(l0=l0, l1=l1, wait_total=total), rate


# --------------------------------------------------------------------------
# phase 1/2: completions, lent returns, virtual-node expiry
# --------------------------------------------------------------------------

def _release_local(s: SimState, t):
    run, free, done = R.release(s.run, s.node_free, t)
    return s.replace(run=run, node_free=free), done


def _expire_vnodes_local(s: SimState, t):
    expired = jnp.logical_and(s.node_active, s.node_expire <= t)
    zero = jnp.zeros_like(s.node_cap)
    return s.replace(
        node_active=jnp.logical_and(s.node_active, jnp.logical_not(expired)),
        node_cap=jnp.where(expired[:, None], zero, s.node_cap),
        node_free=jnp.where(expired[:, None], zero, s.node_free),
        node_expire=jnp.where(expired, R.NEVER, s.node_expire),
    )


def _pack_returns(run, done, M: int):
    """First M finished-foreign-job slots per cluster as packed rows.

    ``run`` is the running set *before* release cleared the completed slots.
    Returns (rows [C, M, RF], take [C, M]): the outbound JobFinished ->
    ReturnToBorrower messages (scheduler.go:158-191). owner >= 0 is a
    borrower index; FOREIGN (-2) trader placeholders are returned to nobody
    (Go posts to the literal URL "Foreign" and gives up)."""
    is_ret = jnp.logical_and(done, run.owner >= 0)  # [C_loc, S]
    order = jnp.argsort(jnp.logical_not(is_ret), axis=1, stable=True)[:, :M]
    take = jnp.take_along_axis(is_ret, order, axis=1)  # [C_loc, M]
    rows = R.gather_rows_along(run, order)  # [C_loc, M, RF] i32
    dropped = jnp.sum(is_ret, axis=1) - jnp.sum(take, axis=1)  # beyond M
    return rows, take, dropped.astype(jnp.int32)


def _deliver_returns(state: SimState, rows, take, ex) -> SimState:
    """Cross-cluster half of JobFinished: finished foreign jobs (owner >= 0)
    are posted back to their borrower, which removes them from its
    BorrowedQueue (server.go:115-137, 260-290). Global (non-vmapped) phase;
    under sharding the message block rides one all-gather.

    ``rows``/``take`` come from ``_pack_returns``.
    """
    C_loc, M = take.shape
    # dst = global borrower index; -1 marks an empty message slot
    dst_local = jnp.where(take, rows[..., R.ROWNER], -1)
    msg_dst = ex.gather(dst_local).reshape(-1)  # [C_tot*M]
    msg_rows = ex.gather(rows).reshape(-1, R.RF)
    gidx = ex.global_index(C_loc)

    def remove_for_cluster(borrowed_q, c):
        # One union mask over all messages, then ONE compact. Equivalent to
        # applying the messages sequentially: each message removes every row
        # equal to it on (id, cores, mem, dur) — field equality, not slot
        # index — so the removed set is the union regardless of order, and a
        # per-message scan-of-compacts (n_msgs argsorts per tick) is wasted
        # work.
        q = borrowed_q
        hit = jnp.logical_and(
            jnp.logical_and(q.id[None, :] == msg_rows[:, None, R.RID],
                            q.cores[None, :] == msg_rows[:, None, R.RCORES]),
            jnp.logical_and(q.mem[None, :] == msg_rows[:, None, R.RMEM],
                            q.dur[None, :] == msg_rows[:, None, R.RDUR]))
        matched = jnp.logical_and(
            jnp.any(jnp.logical_and(hit, (msg_dst == c)[:, None]), axis=0),
            q.slot_valid())
        return Q.compact(q, jnp.logical_not(matched))

    borrowed = jax.vmap(remove_for_cluster)(state.borrowed, gidx)
    return state.replace(borrowed=borrowed)


# --------------------------------------------------------------------------
# phase 3: arrivals
# --------------------------------------------------------------------------

def pack_arrivals(arr: Arrivals) -> tuple[jax.Array, jax.Array]:
    """Pre-stack the arrival stream as ready-made queue rows [C, A, Q.NF].

    Done once per run (outside the tick scan): the per-tick ingest then
    extracts its window with a one-hot contraction instead of six batched
    gathers — TPU gathers serialize and were the single largest per-tick
    cost at 4k clusters. Column order comes from the canonical field schema
    (ops/fields.py), the same one site the queue layouts derive theirs
    from."""
    own = jnp.full(arr.t.shape, Q.OWN, jnp.int32)
    zero = jnp.zeros(arr.t.shape, jnp.int32)
    vals = {"id": arr.id, "cores": arr.cores, "mem": arr.mem, "gpu": arr.gpu,
            "dur": arr.dur, "enq_t": arr.t, "owner": own, "rec_wait": zero}
    rows = jnp.stack([vals[n] for n in F.QUEUE_FIELDS],
                     axis=-1).astype(jnp.int32)
    return rows, arr.n


def _bucket_arrivals_host(arr: Arrivals, n_ticks: int, tick_ms: int):
    """The shared host-side bucketing core behind ``pack_arrivals_by_tick``
    and ``pack_arrivals_chunks``: computes each arrival's destination tick
    and rank-in-tick without materializing any padded rows tensor.

    Returns ``(fields [C, A, NF], dest [C, A], ok [C, A], rank [C, A],
    counts [T, C])`` — ``ok`` marks arrivals landing inside the horizon,
    ``dest`` parks the rest on a virtual overflow tick ``n_ticks``."""
    t = np.asarray(arr.t)
    C, A = t.shape
    n = np.asarray(arr.n)
    valid = np.arange(A)[None, :] < n[:, None]
    # the rank-in-group computation below requires time-sorted rows; an
    # unsorted stream would produce negative ranks that wrap into wrong
    # slots (silently corrupt buckets) — fail fast instead
    if A > 1 and not np.all(np.diff(t, axis=1)[valid[:, 1:]] >= 0):
        raise ValueError("pack_arrivals_by_tick requires per-cluster "
                         "time-sorted arrivals")
    # destination tick index (0-based scan step); tick k has clock (k+1)*tick_ms.
    # Computed in int64: the stream's int32 dtype would wrap `t + tick_ms - 1`
    # negative for arrivals near 2^31 and bucket a beyond-horizon job into
    # tick 0 instead of parking it on the overflow tick (ADVICE r5).
    dest = np.maximum((t.astype(np.int64) + tick_ms - 1) // tick_ms, 1) - 1
    ok = valid & (dest < n_ticks)
    dest = np.where(ok, dest, n_ticks)  # parked on a virtual overflow tick
    # per-cluster arrivals are time-sorted, so same-dest rows are contiguous
    # and rank-in-group = global position - group start
    counts2d = np.zeros((C, n_ticks + 1), np.int32)
    np.add.at(counts2d, (np.arange(C)[:, None], dest), 1)
    firsts = np.zeros((C, n_ticks + 1), np.int64)
    firsts[:, 1:] = np.cumsum(counts2d, axis=1)[:, :-1]
    rank = np.arange(A)[None, :] - firsts[np.arange(C)[:, None], dest]
    vals = {"id": np.asarray(arr.id), "cores": np.asarray(arr.cores),
            "mem": np.asarray(arr.mem), "gpu": np.asarray(arr.gpu),
            "dur": np.asarray(arr.dur), "enq_t": t,
            "owner": np.full_like(t, int(Q.OWN)),
            "rec_wait": np.zeros_like(t)}
    fields = np.stack([vals[n] for n in F.QUEUE_FIELDS], axis=-1)  # [C, A, NF]
    return fields, dest, ok, rank, counts2d.T[:n_ticks].copy()


def pack_arrivals_by_tick(arr: Arrivals, n_ticks: int,
                          tick_ms: int) -> st.TickArrivals:
    """Bucket the stream by destination tick (host-side numpy, once per
    run): a job arriving at ``ta`` is ingested at the first tick whose
    clock ``t = k * tick_ms`` satisfies ``ta <= t`` — exactly the engine's
    ``due`` rule and Go's per-tick drain of everything already posted
    (server.go:53-78 + the 1 s loop). Arrivals beyond the horizon are
    dropped here exactly as the windowed path never reaches them.

    Rows are padded to the STREAM-GLOBAL max arrivals-per-tick ``K``; at
    trace-scale burstiness that tensor is mostly padding and can be GBs —
    chunked drivers should use ``pack_arrivals_chunks``, which pads each
    chunk to its own max instead."""
    fields, dest, ok, rank, counts = _bucket_arrivals_host(arr, n_ticks,
                                                           tick_ms)
    C = fields.shape[0]
    K = max(int(counts.max(initial=1)), 1)
    rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                           (n_ticks, C, K, Q.NF)).copy()
    cc, aa = np.nonzero(ok)
    rows[dest[cc, aa], cc, rank[cc, aa]] = fields[cc, aa]
    # host numpy, not device arrays: the bucketed tensor can be GBs at
    # trace scale, and callers chunk/shard it — committing it to the
    # default device here would hold a full extra HBM copy alive next to
    # the per-chunk placements (jit transfers numpy leaves on use)
    return st.TickArrivals(rows=rows, counts=counts)


def round_up_pow2(k: int) -> int:
    """Smallest power of two >= k (>= 1). The K-bucket rounding that bounds
    the number of distinct chunk shapes — and hence XLA compiles — at
    log2(max K) for a whole run."""
    return 1 << max(int(k) - 1, 0).bit_length()


def pack_arrivals_chunks(arr: Arrivals, chunk_sizes: Sequence[int],
                         tick_ms: int, start: int = 0,
                         k_bucket=round_up_pow2) -> list[st.TickArrivals]:
    """Ragged per-chunk bucketing: ``pack_arrivals_by_tick`` for a chunked
    driver, padding each chunk's ``[ticks, C, K_chunk, NF]`` rows tensor to
    that CHUNK's own max arrivals-per-tick instead of the stream-global max.
    ``K_chunk`` is rounded up by ``k_bucket`` (powers of two by default) so
    the per-chunk run functions compile once per bucket, not once per
    chunk. Chunk ``i`` covers ticks ``[start + sum(chunk_sizes[:i]),
    start + sum(chunk_sizes[:i+1]))``; ``start`` supports checkpoint-resumed
    drivers that re-bucket only the remaining ticks.

    Semantically identical to slicing the global-K tensor: ingest masks
    rows beyond each tick's count (``_ingest_packed_local``), so padding
    width is invisible to the simulation — only to HBM and the H2D link.
    All tensors are host numpy; callers stream them to the device
    (bench._engine_run double-buffers the transfer under the previous
    chunk's scan)."""
    n_ticks = start + sum(chunk_sizes)
    fields, dest, ok, rank, counts = _bucket_arrivals_host(arr, n_ticks,
                                                           tick_ms)
    C = fields.shape[0]
    cc, aa = np.nonzero(ok)
    d, r = dest[cc, aa], rank[cc, aa]
    # one stable sort by destination tick, then each chunk is a contiguous
    # slice (searchsorted) — not a per-chunk mask over the whole stream,
    # which would be O(chunks x arrivals) host work at trace scale
    order = np.argsort(d, kind="stable")
    d, cc, aa, r = d[order], cc[order], aa[order], r[order]
    bounds = np.searchsorted(
        d, np.cumsum([start] + list(chunk_sizes)))
    # clamp buckets at the exact stream-global max: pow2 rounding must
    # never pad a chunk PAST what the global-K path would have used (a
    # near-uniform stream whose max is e.g. 6 should not inflate to 8) —
    # the shape set stays bounded: {pow2 < K_global} ∪ {K_global}
    k_global = max(int(counts.max(initial=1)), 1)
    out = []
    off = start
    for i, nt in enumerate(chunk_sizes):
        kc = int(counts[off:off + nt].max(initial=0))
        K = max(min(int(k_bucket(max(kc, 1))), k_global), kc, 1)
        rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                               (nt, C, K, Q.NF)).copy()
        sl = slice(bounds[i], bounds[i + 1])
        rows[d[sl] - off, cc[sl], r[sl]] = fields[cc[sl], aa[sl]]
        out.append(st.TickArrivals(rows=rows,
                                   counts=counts[off:off + nt].copy()))
        off += nt
    return out


def _ingest_packed_local(s: SimState, rows: jax.Array, cnt: jax.Array, t,
                         cfg: SimConfig, to_delay: bool):
    """``_ingest_local`` for pre-bucketed TickArrivals: the tick's rows
    arrive as a scan input, so there is no due/window scan and no ingest
    deferral (K covers the data's maximum by construction)."""
    K = rows.shape[0]
    valid = jnp.arange(K, dtype=jnp.int32) < cnt
    batch = Q.JobQueue(data=rows, count=cnt)
    tgt = s.l0 if to_delay else s.ready
    dropped = Q.push_many_dropped(tgt, valid)
    s = s.replace(drops=s.drops.replace(queue=s.drops.queue + dropped))
    if to_delay:
        s = s.replace(l0=Q.push_many(s.l0, batch, valid, prefix=True),
                      wait_jobs=s.wait_jobs + cnt,
                      jobs_in_queue=s.jobs_in_queue + cnt)
    else:
        s = s.replace(ready=Q.push_many(s.ready, batch, valid, prefix=True))
    return s.replace(arr_ptr=s.arr_ptr + cnt)


def _ingest_local(s: SimState, arr_rows: jax.Array, arr_n: jax.Array, t,
                  cfg: SimConfig, to_delay: bool):
    """Enqueue arrivals with arr_t <= t. DELAY path appends to Level0 and
    starts the wait timer + JobsCount + jobs_in_queue counter (the /delay
    handler, server.go:53-78); FIFO path appends to ReadyQueue (the /
    handler, server.go:23-51).

    ``arr_rows``: [A, Q.NF] pre-packed queue rows (pack_arrivals), enq_t
    column = arrival time. The window [arr_ptr, arr_ptr+K) is extracted as a
    one-hot matmul (no gather)."""
    A = arr_rows.shape[0]
    K = min(cfg.max_ingest_per_tick, A)
    a = jnp.arange(A, dtype=jnp.int32)
    in_window = jnp.logical_and(a >= s.arr_ptr, a < s.arr_ptr + K)
    due = jnp.logical_and(jnp.logical_and(a >= s.arr_ptr, a < arr_n),
                          arr_rows[:, Q.FENQ] <= t)  # everything Go ingests now
    elig = jnp.logical_and(due, in_window)  # what fits this tick's window
    n = jnp.sum(elig).astype(jnp.int32)
    # due arrivals beyond the window slip to the next tick — a timing
    # divergence from Go; count it so parity runs can assert the window
    # never bound (Drops.ingest)
    deferred = (jnp.sum(due) - n).astype(jnp.int32)
    s = s.replace(drops=s.drops.replace(ingest=s.drops.ingest + deferred))
    # one-hot window extraction: a [K, A] contraction against the packed
    # rows. Measured alternatives at 4k clusters: vmapped dynamic_slice
    # lowers to a serializing gather (2x the whole tick); the int32 matmul
    # is exact and the fastest form XLA offers here.
    hot = (a[None, :] == (s.arr_ptr + jnp.arange(K, dtype=jnp.int32))[:, None])
    rows = hot.astype(arr_rows.dtype) @ arr_rows  # [K, NF]
    valid = jnp.arange(K, dtype=jnp.int32) < n
    batch = Q.JobQueue(data=rows, count=n)
    tgt = s.l0 if to_delay else s.ready
    dropped = Q.push_many_dropped(tgt, valid)
    s = s.replace(drops=s.drops.replace(queue=s.drops.queue + dropped))
    if to_delay:
        q = Q.push_many(s.l0, batch, valid, prefix=True)
        s = s.replace(l0=q, wait_jobs=s.wait_jobs + n, jobs_in_queue=s.jobs_in_queue + n)
    else:
        q = Q.push_many(s.ready, batch, valid, prefix=True)
        s = s.replace(ready=q)
    return s.replace(arr_ptr=s.arr_ptr + n)


# --------------------------------------------------------------------------
# phase 4: scheduling passes
# --------------------------------------------------------------------------

def _delay_local(s: SimState, t, cfg: SimConfig):
    """Delay() — the reference's live algorithm (scheduler.go:298-369).

    In fast mode (parity=False) the Level1 sweep attempts only the first
    ``max_placements_per_tick`` queue slots — a throughput knob for scale
    configs (PARITY.md §divergences); the queue still drains in FIFO order
    via compaction."""
    QC = cfg.queue_capacity if cfg.parity else min(
        cfg.queue_capacity, cfg.max_placements_per_tick)

    # ---- Level1 sweep: a bounded while loop — under vmap it runs only
    # max-over-clusters(|Level1|) iterations, so an idle constellation pays
    # ~nothing and parity mode costs the same as the capped fast mode.
    # RunningSet insertions are deferred to one start_many after the loop
    # (_attempt_deferred) — the per-step body touches only [SW]-sized
    # scratch, not the [S]-sized set ----
    n_sweep = jnp.minimum(s.l1.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def cond(carry):
        s2, i, rec, placed, skip_next, buf, cnt = carry
        return i < n_sweep

    def step(carry):
        s2, i, rec, placed, skip_next, buf, cnt = carry
        process = jnp.logical_and(i < n_sweep, jnp.logical_not(skip_next))
        # one-hot slot access: dynamic row gathers/scatters serialize when
        # the loop body is vmapped over thousands of clusters
        hot = jnp.arange(s2.l1.capacity, dtype=jnp.int32) == i
        rec_i = jnp.einsum("q,q->", hot.astype(jnp.int32), rec)
        job = Q.select_row(s2.l1, hot).with_(rec_wait=rec_i)
        total, new_rec = _record_wait(s2.wait_total, rec_i, job.enq_t, t, process)
        rec = jnp.where(jnp.logical_and(hot, process), new_rec, rec)
        s2 = s2.replace(wait_total=total)
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_L1, cfg.record_trace, buf, cnt, n_active)
        s2 = s2.replace(jobs_in_queue=s2.jobs_in_queue - success.astype(jnp.int32))
        placed = jnp.logical_or(placed, jnp.logical_and(hot, success))
        # Parity: Go removes L1[i] in place and `i++` skips the element that
        # slides into position i (scheduler.go:319) — equivalent on the
        # original order to "after a success, skip the next element".
        skip_next = success if cfg.parity else jnp.zeros((), bool)
        return (s2, i + 1, rec, placed, skip_next, buf, cnt)

    init = (s, jnp.int32(0), s.l1.rec_wait,
            jnp.zeros((cfg.queue_capacity,), bool), jnp.zeros((), bool),
            jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0))
    t_in = s.t
    s, _, rec, placed, _, buf, cnt = jax.lax.while_loop(cond, step, init)
    # the loop never writes the clock, but under vmap a batched loop
    # predicate makes older jax batching rules batch EVERY carry leaf —
    # including the replicated scalar t, which then trips the engine's
    # out_axes=None spec. Restoring the pre-loop leaf is a semantic no-op
    # that keeps t replicated on every jax version.
    s = s.replace(t=t_in)
    l1 = Q.compact(Q.set_field(s.l1, "rec_wait", rec), jnp.logical_not(placed))
    s = s.replace(l1=l1, run=R.start_many(s.run, buf, cnt))
    return _delay_l0_head(s, t, cfg)


def _delay_l0_head(s: SimState, t, cfg: SimConfig):
    """The Level0-head half of Delay() (scheduler.go:332-366): one
    placement attempt on the head, else promote to Level1 after
    MaxWaitTime. Shared by the serial and wave Level1 sweeps."""
    process = s.l0.count > 0
    job = Q.head(s.l0)
    total, new_rec = _record_wait(s.wait_total, job.rec_wait, job.enq_t, t, process)
    l0 = Q.set_field_elem(s.l0, "rec_wait", 0, new_rec)
    s = s.replace(wait_total=total, l0=l0)
    job = job.with_(rec_wait=new_rec)
    s, success = _attempt(s, job, t, process, st.SRC_L0, cfg.record_trace)
    s = s.replace(jobs_in_queue=s.jobs_in_queue - success.astype(jnp.int32))
    promote = jnp.logical_and(
        jnp.logical_and(process, jnp.logical_not(success)),
        (t - job.enq_t) >= cfg.max_wait_ms,
    )
    s = s.replace(
        l0=Q.pop_front(s.l0, jnp.logical_or(success, promote)),
        l1=Q.push_back(s.l1, job, promote),
        drops=s.drops.replace(
            queue=s.drops.queue + Q.push_back_dropped(s.l1, promote)),
    )
    return s


def _delay_wave_local(s: SimState, t, cfg: SimConfig):
    """Fast-mode Delay(): the Level1 sweep as speculative waves
    (``_wave_place``; equivalence argument in ``_ffd_wave_local``) plus
    the shared Level0-head attempt. Parity mode keeps the serial sweep —
    its remove-then-skip quirk and ordered float wait accumulation are
    part of bit-parity (PARITY.md)."""
    QC = min(cfg.queue_capacity, cfg.max_placements_per_tick)
    n_sweep = jnp.minimum(s.l1.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    act0 = jnp.arange(QC, dtype=jnp.int32) < n_sweep
    rows = Q.rows_prefix(s.l1, QC)  # sweep order == queue order (no sort)
    jobs = Q.JobRec(vec=rows)

    # wait accounting, vectorized over the processed prefix (fast mode:
    # no serial-float-order constraint)
    processed_slot = s.l1.slot_valid() & (
        jnp.arange(s.l1.capacity, dtype=jnp.int32) < n_sweep)
    cur = (t - s.l1.enq_t).astype(jnp.int32)
    frec = s.l1.rec_wait
    delta = jnp.where(processed_slot, (cur - frec).astype(jnp.float32), 0.0)
    l1 = Q.set_field(s.l1, "rec_wait", jnp.where(processed_slot, cur, frec))
    s = s.replace(wait_total=s.wait_total + delta.sum(), l1=l1)

    free, node_sel, cnt, run_full = _wave_place(
        s.node_free, s.node_active, s.run.capacity, n_active, jobs, act0)

    placed_pos = node_sel >= jnp.int32(0)
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_L1)
    placed_slot = jnp.pad(placed_pos, (0, s.l1.capacity - QC))
    s = s.replace(
        node_free=free, trace=trace,
        drops=s.drops.replace(run_full=s.drops.run_full + run_full),
        placed_total=s.placed_total + cnt,
        jobs_in_queue=s.jobs_in_queue - cnt,
        l1=Q.compact(s.l1, jnp.logical_not(placed_slot)),
        run=R.start_many(s.run, buf, cnt))
    return _delay_l0_head(s, t, cfg)


def _ffd_local(s: SimState, t, cfg: SimConfig):
    """First-fit-decreasing bin-pack over Level0 — one XLA sort + the shared
    placement sweep. Not in the reference; BASELINE.json config 3. Fast mode
    caps the sweep at ``max_placements_per_tick`` (largest jobs first)."""
    QC = cfg.queue_capacity if cfg.parity else min(
        cfg.queue_capacity, cfg.max_placements_per_tick)
    order = P.best_fit_decreasing_order(s.l0.cores, s.l0.mem, s.l0.slot_valid())
    n_sweep = jnp.minimum(s.l0.count, QC)  # order puts valid slots first
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def cond(carry):
        s2, k, placed, buf, cnt = carry
        return k < n_sweep

    def step(carry):
        s2, k, placed, buf, cnt = carry
        process = k < n_sweep
        # one-hot slot access (see _delay_local): i = order[k], then row i
        cap = s2.l0.capacity
        hot_k = jnp.arange(cap, dtype=jnp.int32) == k
        i = jnp.einsum("q,q->", hot_k.astype(jnp.int32), order)
        hot = jnp.arange(cap, dtype=jnp.int32) == i
        job = Q.select_row(s2.l0, hot)
        total, new_rec = _record_wait(s2.wait_total, job.rec_wait, job.enq_t, t, process)
        frec = s2.l0.rec_wait
        frec = jnp.where(jnp.logical_and(hot, process), new_rec, frec)
        s2 = s2.replace(wait_total=total,
                        l0=Q.set_field(s2.l0, "rec_wait", frec))
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_L0, cfg.record_trace, buf, cnt, n_active)
        s2 = s2.replace(jobs_in_queue=s2.jobs_in_queue - success.astype(jnp.int32))
        placed = jnp.logical_or(placed, jnp.logical_and(hot, success))
        return (s2, k + 1, placed, buf, cnt)

    t_in = s.t
    s, _, placed, buf, cnt = jax.lax.while_loop(
        cond, step, (s, jnp.int32(0), jnp.zeros((cfg.queue_capacity,), bool),
                     jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0)))
    # keep the replicated clock out of the batched carry (see _delay_local)
    s = s.replace(t=t_in)
    return s.replace(l0=Q.compact(s.l0, jnp.logical_not(placed)),
                     run=R.start_many(s.run, buf, cnt))


def _trace_append_many(tr, take, t, job_ids, nodes, src):
    """Batch form of ``_trace_append``: append events for positions where
    ``take``, in position order — bit-identical to appending them one by
    one. One [K, cap] one-hot contraction instead of K cursor writes."""
    cap = tr.t.shape[-1]
    rank = jnp.cumsum(take.astype(jnp.int32)) - 1
    idx = tr.n + rank
    ok = jnp.logical_and(take, idx < cap)
    hot = jnp.logical_and(
        ok[:, None], idx[:, None] == jnp.arange(cap, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)  # [K, cap]
    untouched = hot.sum(axis=0) == 0  # [cap]

    def w(a, vals):
        return jnp.where(untouched, a, jnp.einsum("kc,k->c", hot,
                                                  vals.astype(jnp.int32)))

    src_v = jnp.full(take.shape, jnp.int32(src))
    t_v = jnp.full(take.shape, jnp.asarray(t, jnp.int32))
    return tr.replace(t=w(tr.t, t_v), job=w(tr.job, job_ids),
                      node=w(tr.node, nodes), src=w(tr.src, src_v),
                      n=tr.n + ok.sum().astype(jnp.int32))


def _wave_probe(free, node_active, jobs: Q.JobRec, active):
    """The per-wave feasibility core shared by every speculative sweep
    (``_wave_place``, ``_fifo_drain_wave``): first-fit target selection and
    cumulative-overflow detection for the active rows under the current
    ``free``. This is the equivalence-critical logic — any edit here changes
    all wave forms together (tests/test_kernel_equiv.py pins wave==serial).

    A wave accepts *whole same-target groups*, not just distinct targets:
    for jobs targeting the same node, the running group total (job k's own
    demand plus all earlier same-target rows) is compared against the
    node's free vector, and only the row that overflows it (and everything
    after, via the callers' prefix rules) defers to the next wave. This is
    exact by the same monotonicity argument as the original
    distinct-target rule (``_ffd_wave_local`` docstring), extended one
    step: for an accepted job k targeting node n, earlier accepted jobs on
    other nodes leave n untouched, earlier accepted jobs ON n are exactly
    k's group predecessors — whose total including k fits — so when the
    serial sweep reaches k, nodes before n are still infeasible (free only
    shrinks) and n is still feasible: the serial sweep picks n too. Without
    the group rule, homogeneous clusters degrade to one placement per wave
    (every queued job first-fits the same node), which left the FIFO
    headline latency-bound at ~backlog iterations per tick.

    Returns ``(feas_any, tgt, tgt_hot, overflow)``: per-row feasibility,
    first-fit node index, its one-hot [QC, N] form (zero rows where
    infeasible/inactive), and whether the row's cumulative group demand
    overflows its target's free capacity this wave."""
    feas = jax.vmap(lambda c, m, g: P.feasible(
        free, node_active, c, m, g))(jobs.cores, jobs.mem, jobs.gpu)
    feas = jnp.logical_and(feas, active[:, None])  # [QC, N]
    feas_any = jnp.any(feas, axis=-1)
    tgt = jnp.argmax(feas, axis=-1).astype(jnp.int32)  # first-fit node
    tgt_hot = jnp.logical_and(
        feas_any[:, None],
        tgt[:, None] == jnp.arange(feas.shape[1],
                                   dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    res = jobs.res[..., : free.shape[-1]]  # [QC, R]
    cum = jnp.cumsum(tgt_hot[:, :, None] * res[:, None, :], axis=0)  # [QC, N, R]
    group_dem = jnp.einsum("kn,knr->kr", tgt_hot, cum)  # incl. the row itself
    tgt_free = jnp.einsum("kn,nr->kr", tgt_hot, free)
    overflow = jnp.logical_and(feas_any,
                               jnp.any(group_dem > tgt_free, axis=-1))
    return feas_any, tgt, tgt_hot, overflow


def _wave_occupy(free, tgt_hot, place, jobs: Q.JobRec):
    """Subtract the accepted rows' resources from ``free``: one [QC, N] x
    [QC, R] contraction instead of per-row scatter-subtracts."""
    used = jnp.einsum("kn,kr->nr", tgt_hot * place[:, None].astype(jnp.int32),
                      jobs.res[..., : free.shape[-1]])
    return free - used


def _wave_place(free0, node_active, run_cap, n_active, jobs: Q.JobRec, act0):
    """The wave-placement core shared by the FFD and DELAY fast-mode
    sweeps: place ``jobs`` (a [QC]-batched JobRec in sweep order, active
    where ``act0``) by speculative conflict-free-prefix waves. Returns
    ``(free', node_sel, cnt, run_full)`` with ``node_sel[k]`` the placed
    node per position (NO_NODE where unplaced). Equivalence argument:
    ``_ffd_wave_local`` docstring."""
    QC = act0.shape[0]

    def cond(carry):
        free, resolved, node_sel, cnt, run_full = carry
        return jnp.any(jnp.logical_and(act0, jnp.logical_not(resolved)))

    def step(carry):
        free, resolved, node_sel, cnt, run_full = carry
        active = jnp.logical_and(act0, jnp.logical_not(resolved))
        feas_any, tgt, tgt_hot, overflow = _wave_probe(free, node_active,
                                                       jobs, active)
        blocked = jnp.cumsum(overflow.astype(jnp.int32)) > 0  # self included
        place_try = jnp.logical_and(feas_any, jnp.logical_not(blocked))
        rank = jnp.cumsum(place_try.astype(jnp.int32)) - 1
        has_slot = (n_active + cnt + rank) < run_cap
        place = jnp.logical_and(place_try, has_slot)
        slot_full = jnp.logical_and(place_try, jnp.logical_not(has_slot))
        # infeasible-now is infeasible-forever (free only shrinks): resolve
        # failed even past the block point; slot-exhausted jobs resolve too
        # (run_full drop), exactly as the serial sweep counts them
        resolved = jnp.logical_or(
            resolved, jnp.logical_or(
                place, jnp.logical_or(
                    slot_full,
                    jnp.logical_and(active, jnp.logical_not(feas_any)))))
        free = _wave_occupy(free, tgt_hot, place, jobs)
        node_sel = jnp.where(place, tgt, node_sel)
        cnt = cnt + place.sum().astype(jnp.int32)
        run_full = run_full + slot_full.sum().astype(jnp.int32)
        return free, resolved, node_sel, cnt, run_full

    free, _, node_sel, cnt, run_full = jax.lax.while_loop(
        cond, step, (free0, jnp.logical_not(act0),
                     jnp.full((QC,), P.NO_NODE), jnp.int32(0), jnp.int32(0)))
    return free, node_sel, cnt, run_full


def _ffd_wave_local(s: SimState, t, cfg: SimConfig):
    """``_ffd_local`` restructured as speculative placement waves — same
    placements, a fraction of the serial steps.

    Sequential first-fit has a loop-carried dependency (each placement
    shrinks ``free`` for the next job), which on TPU costs one
    latency-bound while_loop iteration per queued job, maxed over all
    vmapped clusters (tools/cost_probe.json: the FFD sweep achieves less
    than half the headline's HBM bandwidth). The wave form places many
    jobs per iteration and is *provably identical* to the serial sweep:

    each wave, every unresolved job computes its first-fit target under
    the current ``free``; the accepted set is the longest prefix (in FFD
    order) in which every job's cumulative same-target group demand fits
    its target node (``_wave_probe`` — whole groups land in one wave).
    For an accepted job, earlier accepted jobs on other nodes leave its
    target untouched, earlier accepted jobs on the SAME node are its
    group predecessors whose total including it fits, and ``free`` only
    ever shrinks — so nodes before its target stay infeasible and its
    target stays feasible: exactly the node the serial sweep would pick.
    A job infeasible under the current ``free`` is infeasible forever
    (monotonicity) and resolves as failed immediately; the first
    group-capacity overflow defers itself and everything after it to the
    next wave. The earliest unresolved job can never overflow (it is
    feasible and heads its group), so every wave makes progress and the
    loop runs one iteration per capacity epoch instead of one per job.

    Used in fast mode (``parity=False`` — the Go reference has no FFD, so
    there is no Go-semantics constraint either way; ``ffd_sweep="serial"``
    keeps the old path, and tests/test_kernel_equiv.py pins wave == serial
    on trace, queue, and node state across seeds)."""
    QC = min(cfg.queue_capacity, cfg.max_placements_per_tick)
    cap_q = s.l0.capacity
    order = P.best_fit_decreasing_order(s.l0.cores, s.l0.mem,
                                        s.l0.slot_valid())[:QC]  # [QC]
    n_sweep = jnp.minimum(s.l0.count, QC)
    n_active = jnp.sum(s.run.active).astype(jnp.int32)
    act0 = jnp.arange(QC, dtype=jnp.int32) < n_sweep

    # ordered job rows: one [QC, Q] @ [Q, NF] integer contraction
    sel = (order[:, None] ==
           jnp.arange(cap_q, dtype=jnp.int32)[None, :]).astype(jnp.int32)
    rows = Q.gather_rows(s.l0, sel)
    jobs = Q.JobRec(vec=rows)

    # wait accounting, vectorized at the slot level (every processed job is
    # recorded exactly once per tick; fast mode has no serial-float-order
    # constraint — parity mode keeps the serial sweep)
    processed_slot = jnp.einsum("kq,k->q", sel, act0.astype(jnp.int32)) > 0
    cur = (t - s.l0.enq_t).astype(jnp.int32)
    frec = s.l0.rec_wait
    delta = jnp.where(processed_slot, (cur - frec).astype(jnp.float32), 0.0)
    l0 = Q.set_field(s.l0, "rec_wait", jnp.where(processed_slot, cur, frec))
    s = s.replace(wait_total=s.wait_total + delta.sum(), l0=l0)

    free, node_sel, cnt, run_full = _wave_place(
        s.node_free, s.node_active, s.run.capacity, n_active, jobs, act0)

    placed_pos = node_sel >= jnp.int32(0)  # [QC], in FFD order
    # runset rows in position order, compacted to the buffer prefix
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)  # [QC, QC]
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_L0)
    placed_slot = jnp.einsum("kq,k->q", sel, placed_pos.astype(jnp.int32)) > 0
    return s.replace(
        node_free=free, trace=trace,
        drops=s.drops.replace(run_full=s.drops.run_full + run_full),
        placed_total=s.placed_total + cnt,
        jobs_in_queue=s.jobs_in_queue - cnt,
        l0=Q.compact(s.l0, jnp.logical_not(placed_slot)),
        run=R.start_many(s.run, buf, cnt))


def _fifo_drain_wave(s: SimState, t, cfg: SimConfig, wait_active, n_active,
                     QC: int):
    """The FIFO ready drain (place from the head until the first failure)
    as speculative waves — same outcome as the serial loop in
    ``_fifo_local``, a fraction of the while_loop iterations.

    The equivalence argument mirrors ``_ffd_wave_local`` (prefix-restricted
    group acceptance via ``_wave_probe``; free only shrinks, so accepted
    first-fit targets and observed infeasibilities are both stable), with
    one extra rule for the drain-stops-at-first-failure semantics: each
    wave accepts candidates only up to the first *breaker* — a group
    capacity overflow (defer to the next wave), an infeasible job, or a
    run-slot-exhausted job (both of the latter ARE the drain's failing
    job: it pops to the wait queue and the drain stops). Unlike the FFD
    sweep this is exact in parity mode too — the drain body performs no
    order-sensitive float accumulation (wait recording happens at the
    wait-head attempt, not here)."""
    ready = s.ready
    n_sweep = jnp.where(wait_active, 0,
                        jnp.minimum(ready.count, QC)).astype(jnp.int32)
    pos = jnp.arange(QC, dtype=jnp.int32)
    act0 = pos < n_sweep
    rows = Q.rows_prefix(ready, QC)  # queue order: position == slot
    jobs = Q.JobRec(vec=rows)

    def cond(carry):
        free, resolved, node_sel, cnt, run_full, stopped, fail_idx = carry
        return jnp.logical_and(
            jnp.logical_not(stopped),
            jnp.any(jnp.logical_and(act0, jnp.logical_not(resolved))))

    def step(carry):
        free, resolved, node_sel, cnt, run_full, stopped, fail_idx = carry
        active = jnp.logical_and(act0, jnp.logical_not(resolved))
        feas_any, tgt, tgt_hot, overflow = _wave_probe(free, s.node_active,
                                                       jobs, active)
        infeas = jnp.logical_and(active, jnp.logical_not(feas_any))
        cand = jnp.logical_and(feas_any, jnp.logical_not(overflow))
        r = jnp.cumsum(cand.astype(jnp.int32)) - cand.astype(jnp.int32)
        cap_left = s.run.capacity - n_active - cnt
        slotviol = jnp.logical_and(cand, r >= cap_left)
        breaker = jnp.logical_or(overflow, jnp.logical_or(infeas, slotviol))
        # positions strictly before the first breaker
        before_break = jnp.cumsum(breaker.astype(jnp.int32)) == 0
        place = jnp.logical_and(cand, before_break)
        any_break = jnp.any(breaker)
        b = jnp.argmax(breaker).astype(jnp.int32)  # first breaker position
        b_hot = jnp.logical_and(pos == b, any_break)
        failed = jnp.logical_and(
            any_break,
            jnp.logical_or(jnp.any(jnp.logical_and(b_hot, infeas)),
                           jnp.any(jnp.logical_and(b_hot, slotviol))))
        run_full = run_full + jnp.any(
            jnp.logical_and(b_hot, slotviol)).astype(jnp.int32)
        resolved = jnp.logical_or(resolved,
                                  jnp.logical_or(place,
                                                 jnp.logical_and(b_hot, failed)))
        free = _wave_occupy(free, tgt_hot, place, jobs)
        node_sel = jnp.where(place, tgt, node_sel)
        cnt = cnt + place.sum().astype(jnp.int32)
        stopped = jnp.logical_or(stopped, failed)
        fail_idx = jnp.where(failed, b, fail_idx)
        return free, resolved, node_sel, cnt, run_full, stopped, fail_idx

    free, resolved, node_sel, cnt, run_full, stopped, fail_idx = \
        jax.lax.while_loop(cond, step, (
            s.node_free, jnp.logical_not(act0), jnp.full((QC,), P.NO_NODE),
            jnp.int32(0), jnp.int32(0), jnp.zeros((), bool), jnp.int32(-1)))

    placed_pos = node_sel >= jnp.int32(0)
    n_taken = cnt + stopped.astype(jnp.int32)  # pops include the failure
    fhot = (pos == fail_idx).astype(jnp.int32)
    fail_job = Q.JobRec(vec=jnp.einsum("k,kf->f", fhot, rows))
    all_rows = jax.vmap(lambda v, n: R.row_from_job(Q.JobRec(vec=v), n, t)
                        )(rows, node_sel)
    rankp = jnp.cumsum(placed_pos.astype(jnp.int32)) - 1
    bhot = jnp.logical_and(
        placed_pos[:, None],
        rankp[:, None] == jnp.arange(QC, dtype=jnp.int32)[None, :],
    ).astype(jnp.int32)
    buf = jnp.einsum("kb,kf->bf", bhot, all_rows)
    trace = s.trace
    if cfg.record_trace:
        trace = _trace_append_many(trace, placed_pos, t, jobs.id, node_sel,
                                   st.SRC_READY)
    s = s.replace(node_free=free, trace=trace,
                  drops=s.drops.replace(run_full=s.drops.run_full + run_full),
                  placed_total=s.placed_total + cnt)
    return s, n_taken, fail_job, stopped, buf, cnt


def _fifo_local(s: SimState, t, cfg: SimConfig):
    """Fifo() (scheduler.go:216-296) as ordered masked phases; see PARITY.md
    for the derivation of the per-tick semantics from the Go loop's
    sleep/continue structure. Returns (state, borrow_want, borrow_job).

    Fast mode (parity=False) caps the ready drain at
    ``max_placements_per_tick`` steps — identical semantics whenever fewer
    than that many jobs would drain in one tick (PARITY.md §divergences)."""
    QC = cfg.queue_capacity if cfg.parity else min(
        cfg.queue_capacity, cfg.max_placements_per_tick)
    wait_active = s.wait.count > 0

    # ---- ready drain (only when the wait queue is empty): place from the
    # head until the first failure; the failing job moves to WaitQueue.
    # Bounded while loop — exits as soon as every cluster drained/stopped ----
    n_active = jnp.sum(s.run.active).astype(jnp.int32)

    def dcond(carry):
        s2, i, stopped, n_taken, fail_job, any_fail, buf, cnt = carry
        return jnp.logical_and(
            jnp.logical_not(wait_active),
            jnp.logical_and(i < jnp.minimum(s2.ready.count, QC),
                            jnp.logical_not(stopped)))

    def dstep(carry):
        s2, i, stopped, n_taken, fail_job, any_fail, buf, cnt = carry
        process = jnp.logical_and(
            jnp.logical_not(wait_active),
            jnp.logical_and(i < jnp.minimum(s2.ready.count, QC),
                            jnp.logical_not(stopped)))
        hot = jnp.arange(s2.ready.capacity, dtype=jnp.int32) == i
        job = Q.select_row(s2.ready, hot)
        s2, success, buf, cnt = _attempt_deferred(
            s2, job, t, process, st.SRC_READY, cfg.record_trace, buf, cnt,
            n_active)
        fail = jnp.logical_and(process, jnp.logical_not(success))
        n_taken = n_taken + process.astype(jnp.int32)  # pops regardless of outcome
        fail_job = jax.tree.map(lambda a, b: jnp.where(fail, b, a), fail_job, job)
        return (s2, i + 1, jnp.logical_or(stopped, fail), n_taken, fail_job,
                jnp.logical_or(any_fail, fail), buf, cnt)

    if cfg.fifo_drain == "wave":
        s, n_taken, fail_job, any_fail, buf, cnt = _fifo_drain_wave(
            s, t, cfg, wait_active, n_active, QC)
    else:
        init = (s, jnp.int32(0), jnp.zeros((), bool), jnp.int32(0),
                Q.JobRec.invalid(), jnp.zeros((), bool),
                jnp.zeros((QC, R.RF), jnp.int32), jnp.int32(0))
        t_in = s.t
        s, _, _, n_taken, fail_job, any_fail, buf, cnt = jax.lax.while_loop(
            dcond, dstep, init)
        # keep the replicated clock out of the batched carry (_delay_local)
        s = s.replace(t=t_in)
    # the drain consumes a strict prefix of the ready queue; its placements
    # flush into the set before the wait-head attempt reads occupancy
    s = s.replace(run=R.start_many(s.run, buf, cnt),
                  ready=Q.pop_front_n(s.ready, n_taken),
                  wait=Q.push_back(s.wait, fail_job, any_fail),
                  drops=s.drops.replace(
                      queue=s.drops.queue + Q.push_back_dropped(s.wait, any_fail)))

    # ---- wait-head attempt (the branch at scheduler.go:219-252) ----
    process_w = s.wait.count > 0
    wjob = Q.head(s.wait)
    s, wsuccess = _attempt(s, wjob, t, process_w, st.SRC_WAIT, cfg.record_trace)
    s = s.replace(wait=Q.pop_front(s.wait, wsuccess))
    borrow_want = jnp.logical_and(process_w, jnp.logical_not(wsuccess))
    if not cfg.borrowing:
        borrow_want = jnp.zeros((), bool)

    # ---- lent best-effort (scheduler.go:277-291): reached only in a tick
    # where wait was empty and ready drained clean ----
    lent_ok = jnp.logical_and(
        jnp.logical_and(jnp.logical_not(wait_active), jnp.logical_not(any_fail)),
        jnp.logical_and(s.ready.count == 0, s.lent.count > 0))
    ljob = Q.head(s.lent)
    s, lsuccess = _attempt(s, ljob, t, lent_ok, st.SRC_LENT, cfg.record_trace)
    s = s.replace(lent=Q.pop_front(s.lent, lsuccess))
    return s, borrow_want, wjob


def _borrow_match(state: SimState, want, jobs: Q.JobRec, cfg: SimConfig, ex) -> SimState:
    """Global borrow phase: BorrowResources' broadcast + first-win
    (server.go:160-248) determinized to lowest-lender-cluster-index.

    ``want``: [C_loc] bool, ``jobs``: JobRec with [C_loc] leaves (each
    cluster's failing wait-head). Feasibility is Lend()'s strict > check
    (scheduler.go:194-202) against the lender's current state — i.e. after
    this tick's scheduling pass, per PARITY.md phase 4 — and no reservation
    is made, matching the Go handler. Under sharding: one all-gather of the
    probe jobs, one min-reduction for the winner — the collective form of
    the goroutine fan-out/first-win idiom."""
    C_loc = want.shape[0]
    gidx = ex.global_index(C_loc)  # my lenders, global indices
    g_want = ex.gather(want)  # [C_tot]
    g_jobs: Q.JobRec = jax.tree.map(ex.gather, jobs)
    C_tot = g_want.shape[0]
    bidx = jnp.arange(C_tot, dtype=jnp.int32)

    # feas[l_local, b_global]: can my lender l host borrower b's job?
    def lender_view(free_l, active_l):
        return jax.vmap(lambda c, m: P.can_lend(
            free_l, active_l, Q.JobRec.make(cores=c, mem=m))
        )(g_jobs.cores, g_jobs.mem)

    feas = jax.vmap(lender_view)(state.node_free, state.node_active)
    feas = jnp.logical_and(feas, gidx[:, None] != bidx[None, :])  # no self-lend
    feas = jnp.logical_and(feas, g_want[None, :])
    INF = jnp.int32(2**31 - 1)
    local_best = jnp.min(jnp.where(feas, gidx[:, None], INF), axis=0)  # [C_tot]
    winner = ex.allmin(local_best)  # lowest feasible lender, global
    matched_g = winner < INF  # [C_tot]

    # Borrower side (local): j.Ownership = own URL (server.go:166), push to
    # BorrowedQueue, pop WaitQueue (scheduler.go:239-242).
    matched_loc = jnp.logical_and(matched_g[gidx], want)
    owned = jobs.with_(owner=gidx)

    def borrower_update(s_wait, s_borrowed, job, m):
        return (Q.pop_front(s_wait, m), Q.push_back(s_borrowed, job, m),
                Q.push_back_dropped(s_borrowed, m))

    wait, borrowed, bdrop = jax.vmap(borrower_update)(
        state.wait, state.borrowed, owned, matched_loc)

    # Lender side (local): append to LentQueue (server.go:94-107). Several
    # borrowers may win the same lender in one tick (the Go handler takes
    # them all); deliver in global borrower-index order.
    send_rows = Q.JobQueue(data=g_jobs.with_(owner=bidx).vec,
                           count=jnp.sum(matched_g).astype(jnp.int32))

    def lender_update(lent_q, gl):
        take = jnp.logical_and(matched_g, winner == gl)
        return Q.push_many(lent_q, send_rows, take), Q.push_many_dropped(lent_q, take)

    lent, ldrop = jax.vmap(lender_update)(state.lent, gidx)
    return state.replace(wait=wait, borrowed=borrowed, lent=lent,
                         drops=state.drops.replace(
                             queue=state.drops.queue + bdrop + ldrop))


# --------------------------------------------------------------------------
# phase 6: trader-visible state snapshot
# --------------------------------------------------------------------------

def _snapshot(state: SimState, t, cfg: SimConfig) -> SimState:
    """Refresh each trader's cached cluster state on the stream cadence
    (trader_server.go:24-47: 5 s ClusterState stream; trader.go:71-108)."""
    do = (t % cfg.trader.state_cadence_ms) == 0
    cu, mu = st.snapshot_utilization(state)
    aw = st.avg_wait_ms(state)
    tr = state.trader
    pick = lambda new, old: jnp.where(do, new, old)
    return state.replace(trader=tr.replace(
        snap_core_util=pick(cu, tr.snap_core_util),
        snap_mem_util=pick(mu, tr.snap_mem_util),
        snap_avg_wait=pick(aw, tr.snap_avg_wait)))


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------

class Engine:
    """Builds the jitted tick/run functions for a given SimConfig.

    ``ex`` is the cross-cluster exchange (parallel/exchange.py):
    LocalExchange for a whole cluster axis on one device, MeshExchange when
    the tick runs inside shard_map over a mesh (parallel/sharded_engine.py).
    """

    def __init__(self, cfg: SimConfig, ex=None):
        from multi_cluster_simulator_tpu.parallel.exchange import LocalExchange
        self.cfg = cfg
        self.ex = ex if ex is not None else LocalExchange()
        if cfg.n_res not in (2, 3):
            raise ValueError(f"n_res must be 2 or 3, got {cfg.n_res}")
        for field in ("ffd_sweep", "fifo_drain", "delay_sweep"):
            v = getattr(cfg, field)
            if v not in ("wave", "serial"):
                raise ValueError(
                    f"{field} must be 'wave' or 'serial', got {v!r}")
        if cfg.trader.enabled and cfg.n_res != 3:
            raise ValueError("the trader market carves 3-dim resources; "
                             "set n_res=3 when trader.enabled")
        if cfg.trader.enabled:
            try:
                from multi_cluster_simulator_tpu.market import trader as market
            except ModuleNotFoundError as e:  # pragma: no cover
                raise NotImplementedError(
                    "the trader market (market/) is not available in this build"
                ) from e
            self._trade_round = functools.partial(market.trade_round, cfg=cfg,
                                                  ex=self.ex)
        else:
            self._trade_round = None

    # -- single tick (pure; vmap/global composition) --
    def tick(self, state: SimState, arrivals: Arrivals) -> SimState:
        return self._tick(state, pack_arrivals(arrivals), emit_io=False)[0]

    def tick_io(self, state: SimState, arrivals: Arrivals) -> tuple[SimState, TickIO]:
        """One tick, also returning the host-visible TickIO events."""
        return self._tick(state, pack_arrivals(arrivals), emit_io=True)

    def _tick(self, state: SimState, packed_arrivals, emit_io: bool,
              tick_indexed: bool = False):
        """The tick body. ``emit_io=False`` (the batch/scan path) skips the
        TickIO packing work when borrowing doesn't need it — the return-slot
        argsort is per-tick cost the headline config shouldn't pay.
        ``tick_indexed``: ``packed_arrivals`` is this tick's
        (rows [C, K, NF], counts [C]) TickArrivals slice instead of the
        whole stream."""
        cfg = self.cfg
        t = state.t + cfg.tick_ms

        # compact node storage: widen ONCE at tick entry so every phase
        # (placement compares, occupy/release arithmetic, market carves)
        # computes in int32 exactly as the wide layout does; the exit
        # narrow below restores the storage dtype. checked=False by the
        # conservation invariant: free stays in [0, cap] (utils/trace.
        # check_conservation) and cap is bounded by the plan's audit —
        # nothing fresh enters the system here.
        node_dt = state.node_free.dtype
        node_narrow = node_dt != jnp.int32
        if node_narrow:
            state = state.replace(node_free=F.widen(state.node_free),
                                  node_cap=F.widen(state.node_cap))

        # 1. completions (+ returns of finished foreign jobs)
        run_before = state.run
        st2, done = jax.vmap(_release_local, in_axes=(_STATE_AXES, None),
                             out_axes=(_STATE_AXES, 0))(state, t)
        state = st2
        if cfg.borrowing or emit_io:
            ret_rows, ret_valid, ret_dropped = _pack_returns(
                run_before, done, cfg.max_msgs)
            state = state.replace(drops=state.drops.replace(
                msgs=state.drops.msgs + ret_dropped))
        else:
            C = done.shape[0]
            ret_rows = jnp.zeros((C, cfg.max_msgs, R.RF), jnp.int32)
            ret_valid = jnp.zeros((C, cfg.max_msgs), bool)
        if cfg.borrowing:
            state = _deliver_returns(state, ret_rows, ret_valid, self.ex)

        # 2. virtual-node expiry (off in parity mode — reference keeps them)
        if cfg.trader.enabled and cfg.trader.expire_virtual_nodes:
            state = jax.vmap(_expire_vnodes_local, in_axes=(_STATE_AXES, None),
                             out_axes=_STATE_AXES)(state, t)

        # 3. arrivals
        arr_rows, arr_n = packed_arrivals
        to_delay = cfg.policy in (PolicyKind.DELAY, PolicyKind.FFD)
        ingest = _ingest_packed_local if tick_indexed else _ingest_local
        state = jax.vmap(functools.partial(ingest, cfg=cfg, to_delay=to_delay),
                         in_axes=(_STATE_AXES, 0, 0, None),
                         out_axes=_STATE_AXES)(state, arr_rows, arr_n, t)

        # 4. scheduling pass
        C = state.arr_ptr.shape[0]
        want = jnp.zeros((C,), bool)
        bjob_vec = jnp.zeros((C, Q.NF), jnp.int32)
        if cfg.policy == PolicyKind.DELAY:
            delay = (_delay_wave_local
                     if not cfg.parity and cfg.delay_sweep == "wave"
                     else _delay_local)
            state = jax.vmap(functools.partial(delay, cfg=cfg),
                             in_axes=(_STATE_AXES, None), out_axes=_STATE_AXES)(state, t)
        elif cfg.policy == PolicyKind.FFD:
            ffd = (_ffd_wave_local
                   if not cfg.parity and cfg.ffd_sweep == "wave"
                   else _ffd_local)
            state = jax.vmap(functools.partial(ffd, cfg=cfg),
                             in_axes=(_STATE_AXES, None), out_axes=_STATE_AXES)(state, t)
        else:  # FIFO
            state, want, bjobs = jax.vmap(
                functools.partial(_fifo_local, cfg=cfg),
                in_axes=(_STATE_AXES, None),
                out_axes=(_STATE_AXES, 0, 0))(state, t)
            bjob_vec = bjobs.vec
            # 5. borrow matching
            if cfg.borrowing:
                state = _borrow_match(state, want, bjobs, cfg, self.ex)

        # 6. trader state snapshot (before any trade in the same tick — the
        # stream lands just ahead of the monitor wakeup, MARKET.md §clock)
        if cfg.trader.enabled:
            state = _snapshot(state, t, cfg)

        # 7. trader market round
        if self._trade_round is not None:
            state = self._trade_round(state, t)

        if node_narrow:
            # CHECKED, unlike the interior permutation narrows: the plan's
            # node bound is derived (physical caps, plus contract totals
            # under the trader — a buyer's virtual node holds a backlog
            # cumsum, not a per-node amount), and a derivation gap here
            # must surface as a counted overflow, never a wrapped
            # capacity. The count lands in the running set's ovf (the
            # node tensors have no counter of their own); it is a scalar
            # total folded into every cluster's counter — the parity and
            # bench gates assert ==0, so magnitude only matters as
            # nonzero-ness.
            free_n, bad_f = F.narrow_store(state.node_free, node_dt)
            cap_n, bad_c = F.narrow_store(state.node_cap, node_dt)
            state = state.replace(
                node_free=free_n, node_cap=cap_n,
                run=state.run.replace(ovf=state.run.ovf + bad_f + bad_c))

        io = TickIO(borrow_want=want, borrow_job=bjob_vec,
                    ret_rows=ret_rows, ret_valid=ret_valid) if emit_io else None
        return state.replace(t=t), io

    # -- scan driver --
    def run(self, state: SimState, arrivals: Arrivals, n_ticks: int):
        """Advance ``n_ticks``. Returns the final state — or, when
        ``cfg.record_metrics`` is set, ``(state, MetricSample)`` with [T] /
        [T, C] stacked per-tick series (the batch-engine form of RunMetrics'
        recorder goroutine, pkg/scheduler/metrics.go:11-31; decimate to the
        reference's 5 s marks host-side, e.g.
        ``jax.tree.map(lambda a: a[4::5], series)`` — sample 0 is t=1 s).

        ``arrivals`` may be an ``Arrivals`` stream or a pre-bucketed
        ``TickArrivals`` (pack_arrivals_by_tick) — the latter feeds each
        tick its slice as a scan input, skipping the per-tick due-window
        scan over the whole stream."""
        record = self.cfg.record_metrics
        if isinstance(arrivals, st.TickArrivals):
            if arrivals.rows.shape[0] < n_ticks:
                raise ValueError(
                    f"TickArrivals covers {arrivals.rows.shape[0]} ticks, "
                    f"run asked for {n_ticks}")

            def body_ta(s, x):
                s2 = self._tick(s, x, emit_io=False, tick_indexed=True)[0]
                return s2, (st.metric_sample(s2) if record else None)

            xs = (arrivals.rows[:n_ticks], arrivals.counts[:n_ticks])
            state, series = jax.lax.scan(body_ta, state, xs, length=n_ticks)
            return (state, series) if record else state

        packed = pack_arrivals(arrivals)  # once, outside the tick scan

        def body(s, _):
            s2 = self._tick(s, packed, emit_io=False)[0]
            return s2, (st.metric_sample(s2) if record else None)

        state, series = jax.lax.scan(body, state, None, length=n_ticks)
        return (state, series) if record else state

    def run_jit(self, donate: bool = False):
        """A jitted ``run``: (state, arrivals, n_ticks-static) -> state, or
        (state, MetricSample series) when cfg.record_metrics is set.

        ``donate=True`` donates the input ``SimState`` buffers to the call
        (``donate_argnums``), so the state is updated in place in HBM
        instead of double-buffered — the chunked drivers thread one state
        through many calls and never reread an input. The caller's state
        arrays are INVALID after the call; clone first (``jnp.copy``) if
        the initial state must survive, e.g. for repeat timings."""
        return jax.jit(self.run, static_argnums=(2,),
                       donate_argnums=(0,) if donate else ())

    # -- event-compressed driver --
    def run_compressed(self, state: SimState, arrivals: st.TickArrivals,
                       n_ticks: int):
        """``run`` with event-compressed virtual time: a ``while_loop`` that
        executes a real 7-phase tick only when something can happen, and
        otherwise leaps the clock to the next event in one step — the
        classic fixed-increment -> next-event DES speedup, bit-identical to
        the dense scan (ARCHITECTURE.md §time compression).

        After each executed tick the driver compares state fingerprints
        (``_quiescence_sig``): an unchanged fingerprint proves the
        constellation is at a fixed point, so every tick before the next
        event — the next nonempty arrival tick (from the pre-bucketed
        counts), the earliest RunningSet completion, the next DELAY
        promotion threshold, the next market cadence boundary or vnode
        expiry (``_next_event_t``) — is a no-op modulo wait accrual, which
        ``_leap_local`` applies in closed form. Under sharding both the
        quiescence vote and the leap distance ride the exchange
        (``alland``/``allmin``), so every shard jumps together.

        Returns ``(state, LeapStats)``, or ``(state, series, LeapStats)``
        when ``cfg.record_metrics``: the dense per-tick series is
        reconstructed exactly — executed ticks write their sample at their
        tick index, skipped ticks replicate the fixed point with the
        closed-form wait accrual folded into ``avg_wait_ms``."""
        cfg = self.cfg
        if not isinstance(arrivals, st.TickArrivals):
            raise ValueError("time compression requires pre-bucketed "
                             "TickArrivals (pack_arrivals_by_tick / "
                             "pack_arrivals_chunks)")
        if arrivals.rows.shape[0] < n_ticks:
            raise ValueError(
                f"TickArrivals covers {arrivals.rows.shape[0]} ticks, "
                f"run asked for {n_ticks}")
        record = cfg.record_metrics
        C = state.arr_ptr.shape[0]
        stats = st.leap_stats_init()
        if record:
            ser0 = st.MetricSample(
                t=jnp.zeros((n_ticks,), jnp.int32),
                jobs_in_queue=jnp.zeros((n_ticks, C), jnp.int32),
                avg_wait_ms=jnp.zeros((n_ticks, C), jnp.float32))
        else:
            ser0 = None
        if n_ticks == 0:
            return (state, ser0, stats) if record else (state, stats)

        rows, counts = arrivals.rows[:n_ticks], arrivals.counts[:n_ticks]
        tick = jnp.int32(cfg.tick_ms)
        t0 = state.t
        t_end = t0 + jnp.int32(n_ticks) * tick
        inf_t = t_end + tick  # "no event inside this run"
        # next nonempty arrival tick index, shard-local: next_arr[i] is the
        # smallest j >= i with arrivals on any local cluster (n_ticks when
        # none) — one reverse cummin over the pre-bucketed counts; the
        # cross-shard min happens on the leap target itself
        nonempty = jnp.any(counts > 0, axis=1)
        idxs = jnp.where(nonempty, jnp.arange(n_ticks, dtype=jnp.int32),
                         jnp.int32(n_ticks))
        next_arr = jnp.flip(jax.lax.cummin(jnp.flip(idxs)))
        next_arr = jnp.concatenate(
            [next_arr, jnp.full((1,), n_ticks, jnp.int32)])

        def cond(carry):
            return carry[0].t < t_end

        def body(carry):
            s, stats, ser = carry
            i = ((s.t - t0) // tick).astype(jnp.int32)  # tick index to run
            rows_i = jax.lax.dynamic_index_in_dim(rows, i, 0, keepdims=False)
            cnt_i = jax.lax.dynamic_index_in_dim(counts, i, 0, keepdims=False)
            sig0 = _quiescence_sig(s)
            s2 = self._tick(s, (rows_i, cnt_i), emit_io=False,
                            tick_indexed=True)[0]
            quiet = self.ex.alland(jnp.all(_quiescence_sig(s2) == sig0))
            # leap target: the clock of the next tick that must execute
            ev = jnp.minimum(_next_event_t(s2, s2.t, cfg), inf_t)
            ev_clock = ((ev + tick - 1) // tick) * tick  # ceil to tick grid
            na = next_arr[jnp.minimum(i + 1, jnp.int32(n_ticks))]
            arr_clock = t0 + (na + 1) * tick
            target = self.ex.allmin(
                jnp.minimum(jnp.minimum(ev_clock, arr_clock), inf_t))
            new_t = jnp.where(quiet, jnp.maximum(target - tick, s2.t), s2.t)
            n_skip = ((new_t - s2.t) // tick).astype(jnp.int32)

            # the whole accrual rides a scalar cond, not just the masks:
            # non-quiescent executed ticks (most of a burst/drain phase)
            # must not pay the mask computation (the FFD branch re-sorts
            # the queue) plus two full queue rewrites for an identity
            def leap(s):
                return jax.vmap(
                    functools.partial(_leap_local, cfg=cfg),
                    in_axes=(_STATE_AXES, None, None),
                    out_axes=(_STATE_AXES, 0))(s, new_t, jnp.bool_(True))

            s3, rate = jax.lax.cond(
                quiet, leap, lambda s: (s, jnp.zeros((C,), jnp.float32)), s2)
            s3 = s3.replace(t=new_t)
            bucket = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(
                n_skip, 1).astype(jnp.float32))).astype(jnp.int32),
                0, st.LEAP_BUCKETS - 1)
            stats = st.LeapStats(
                ticks_executed=stats.ticks_executed + 1,
                leaps=stats.leaps.at[bucket].add(
                    (n_skip > 0).astype(jnp.int32)))
            if record:
                samp = st.metric_sample(s2)
                jr = jnp.arange(n_ticks, dtype=jnp.int32)
                skip_m = jnp.logical_and(jr > i, jr <= i + n_skip)
                # skipped samples: jobs_in_queue replicates the fixed
                # point; avg_wait folds the per-tick accrual rate in
                totals = (s2.wait_total[None, :]
                          + (jr - i).astype(jnp.float32)[:, None]
                          * rate[None, :])
                avg = jnp.where(s2.wait_jobs[None, :] > 0,
                                totals / jnp.maximum(s2.wait_jobs, 1)[None, :],
                                0.0)
                ser = st.MetricSample(
                    t=jnp.where(skip_m, t0 + (jr + 1) * tick,
                                ser.t).at[i].set(samp.t),
                    jobs_in_queue=jnp.where(
                        skip_m[:, None], s2.jobs_in_queue[None, :],
                        ser.jobs_in_queue).at[i].set(samp.jobs_in_queue),
                    avg_wait_ms=jnp.where(
                        skip_m[:, None], avg,
                        ser.avg_wait_ms).at[i].set(samp.avg_wait_ms))
            return (s3, stats, ser)

        state, stats, series = jax.lax.while_loop(
            cond, body, (state, stats, ser0))
        return (state, series, stats) if record else (state, stats)

    def run_compressed_jit(self, donate: bool = False):
        """A jitted ``run_compressed`` (same donation contract as
        ``run_jit``): (state, TickArrivals, n_ticks-static) ->
        (state, LeapStats) or (state, MetricSample series, LeapStats)."""
        return jax.jit(self.run_compressed, static_argnums=(2,),
                       donate_argnums=(0,) if donate else ())
