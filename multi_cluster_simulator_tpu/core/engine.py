"""The virtual-time simulation engine.

The reference advances time by sleeping: scheduler loops tick every wall
second (pkg/scheduler/scheduler.go:250,294,367) and every running job is a
goroutine in ``time.Sleep(j.Duration)`` (cluster.go:141-161), so simulating
X seconds of cluster time takes X seconds of wall time. Here one ``tick``
is a pure function on ``SimState`` advanced under ``lax.scan`` — 1 ms of
virtual time costs nanoseconds — with every per-cluster phase ``vmap``-ed
over the cluster axis and every cross-cluster phase written as batched array
ops (which become XLA collectives when the cluster axis is sharded).

Tick phase order (the documented determinization of the reference's
concurrent goroutines — see PARITY.md):

  0. fault phase (faults/ — no reference analogue: the Go system never
     fails a node): due node failures kill + requeue the jobs running on
     them, mask the node's capacity out, and due repairs restore it
  1. completions with ``end_t <= t`` release resources (RunJob wakeups);
     finished foreign jobs are returned to their borrower (JobFinished ->
     ReturnToBorrower -> /lent, scheduler.go:158-191, server.go:260-290)
  2. expired virtual nodes deactivate (optional; the reference never
     removes them — cluster.go:65-85)
  3. arrivals with ``arr_t <= t`` enqueue (client POST /delay or /,
     server.go:22-78)
  4. the policy's scheduling pass — dispatched through the policy zoo
     (policies/: each policy is a batched kernel selected by a traced
     index, its knobs a PolicyParams pytree; a singleton set folds to the
     direct call below):
     DELAY — Level1 sweep then Level0 head + promotion (Delay(),
       scheduler.go:298-369), including in parity mode the remove-then-skip
       iteration quirk of the Level1 loop (scheduler.go:305-327)
     FIFO — wait-head attempt / ready drain-to-first-failure / lent
       best-effort (Fifo(), scheduler.go:216-296), emitting borrow
       requests on wait-head failure (BorrowResources, server.go:160-248)
     FFD — first-fit-decreasing bin-pack over Level0 (TPU-side upgrade,
       BASELINE.json config 3)
  5. cross-cluster borrow matching: feasibility over all lenders, lowest
     cluster index wins (the deterministic version of Go's
     first-200-OK-wins race, server.go:219-247)
  6. trader state snapshot on the 5 s stream cadence (trader_server.go:24-47)
     — refreshed before any trade in the same tick (MARKET.md §clock)
  7. trader market round on the monitor cadence (market/, trader.go:280-325)
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import MatchKind, SimConfig
from multi_cluster_simulator_tpu.core import state as st
from multi_cluster_simulator_tpu.core.state import Arrivals, SimState
from multi_cluster_simulator_tpu.faults import apply as faults_apply
from multi_cluster_simulator_tpu.obs import device as obs_device
from multi_cluster_simulator_tpu.obs.profile import phase_scope
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import placement as P
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R
from multi_cluster_simulator_tpu.policies.base import PolicySet

# The scheduling-pass kernels live in the policy zoo now (policies/ — PR 6,
# policy-as-data); the engine dispatches through PolicySet and re-exports
# the kernel names for the phase probes and older callers.
from multi_cluster_simulator_tpu.policies.kernels import (  # noqa: F401
    _attempt, _attempt_deferred, _delay_l0_head, _delay_local,
    _delay_wave_local, _ffd_local, _ffd_wave_local, _fifo_drain_wave,
    _fifo_local, _record_wait, _sweep_len, _trace_append, _trace_append_many,
    _wave_occupy, _wave_place, _wave_probe,
)

# vmap prefix: map every per-cluster field over axis 0, broadcast the clock
# (canonical home: core/state.py — the policy kernels share it).
_STATE_AXES = st.STATE_AXES
_ARR_AXES = Arrivals(t=0, id=0, cores=0, mem=0, gpu=0, dur=0, n=0)


@struct.dataclass
class TickIO:
    """Per-tick host-visible events — what a live service host must act on
    over the network instead of in-batch (services/scheduler_host.py).

    ``borrow_want``/``borrow_job`` are the failing wait-head *before* any
    in-batch borrow matching (the BorrowResources call site,
    scheduler.go:234); ``ret_rows``/``ret_valid`` are the finished
    foreign-job return messages (ReturnToBorrower, server.go:260-290)."""

    borrow_want: jax.Array  # [C] bool
    borrow_job: jax.Array  # [C, Q.NF] i32
    ret_rows: jax.Array  # [C, max_msgs, R.RF] i32
    ret_valid: jax.Array  # [C, max_msgs] bool


# --------------------------------------------------------------------------
# time compression: quiescence predicate + next-event probe + leap accrual
# (the event-compressed driver, Engine.run_compressed)
# --------------------------------------------------------------------------

def _quiescence_sig(state: SimState) -> jax.Array:
    """Fixed-point fingerprint for the leap driver: a vector of scalar
    sums that changes whenever a tick mutates anything the NEXT tick's
    decisions can read. Queue membership, placements, completions,
    arrivals, node activations, and every drop counter are covered; the
    fields deliberately excluded — the clock, wait accounting
    (wait_total / FREC), and the trader's snapshot/cooldown/lock columns —
    either evolve in closed form over a leap or are only read at cadence
    boundaries the driver never skips (market.trader.next_cadence_t).

    Two executed ticks with equal fingerprints around an event-free gap
    therefore prove every tick in the gap is a no-op modulo wait accrual:
    the pass is a pure function of (queues, nodes, run, t), and its only
    t-dependence is wait recording plus the promotion threshold, which the
    next-event probe handles (``_next_event_t``)."""
    d = state.drops
    # compact layouts carry narrow-store overflow counters; fold them into
    # the drop sum so an overflow tick can never be judged quiescent
    ovf = jnp.int32(0)
    for part in (state.l0, state.l1, state.ready, state.wait, state.lent,
                 state.borrowed, state.run):
        if hasattr(part, "ovf"):
            ovf = ovf + jnp.sum(part.ovf)
    parts = [
        jnp.sum(state.placed_total), jnp.sum(state.arr_ptr),
        jnp.sum(state.run.active.astype(jnp.int32)),
        jnp.sum(state.l0.count), jnp.sum(state.l1.count),
        jnp.sum(state.ready.count), jnp.sum(state.wait.count),
        jnp.sum(state.lent.count), jnp.sum(state.borrowed.count),
        jnp.sum(state.node_active.astype(jnp.int32)),
        jnp.sum(d.queue) + jnp.sum(d.msgs) + jnp.sum(d.run_full)
        + jnp.sum(d.vslot) + jnp.sum(d.carve) + jnp.sum(d.ingest)
        + jnp.sum(d.failed) + ovf,
        # fault plane: health membership, completed outages, kill/requeue
        # counters — a tick that only fails or repairs an (empty) node
        # must never be judged quiescent (faults/apply.py sig_parts)
        *faults_apply.sig_parts(state),
    ]
    return jnp.stack([p.astype(jnp.int32) for p in parts])


def _next_event_t(state: SimState, t, cfg: SimConfig, pset: PolicySet,
                  params) -> jax.Array:
    """Earliest future virtual time at which a quiescent constellation can
    change state again (shard-local; the driver ``allmin``s across shards
    and folds in the next nonempty arrival tick separately):

    - a completion: min ``end_t`` over the RunningSet (R.next_end_t) —
      releases fire at the first tick clock >= end_t;
    - a DELAY Level0->Level1 promotion: at a fixed point the head keeps
      failing, so it promotes at the first tick clock >=
      ``enq_t + max_wait_ms`` (scheduler.go:348-366) — the threshold is the
      policy parameter ``params.max_wait_ms`` (a traced leaf; for a
      config-built engine it carries exactly ``cfg.max_wait_ms``), gated
      by the traced policy index when the compiled set mixes kinds;
    - a market cadence boundary (stream snapshot / monitor round) and, in
      sane mode, a virtual-node expiry.

    Values are raw event times; the driver rounds up to the tick grid."""
    ev = jnp.min(jax.vmap(R.next_end_t)(state.run))
    if "delay" in pset.kinds:
        head_enq = state.l0.enq_t[:, 0]  # [C]
        promote = jnp.where(state.l0.count > 0,
                            head_enq + params.max_wait_ms.astype(jnp.int32),
                            R.NEVER)
        if any(k != "delay" for k in pset.kinds):
            is_delay = pset.kind_flag_table("delay")[params.idx]
            promote = jnp.where(is_delay, promote, R.NEVER)
        ev = jnp.minimum(ev, jnp.min(promote))
    if cfg.trader.enabled:
        from multi_cluster_simulator_tpu.market.trader import next_cadence_t
        ev = jnp.minimum(ev, next_cadence_t(t, cfg.trader))
        if cfg.trader.expire_virtual_nodes:
            ev = jnp.minimum(ev, jnp.min(jnp.where(
                state.node_active, state.node_expire, R.NEVER)))
    if cfg.faults.enabled:
        # a leap can never jump over a failure or a repair: the next fault
        # event joins the bound exactly like completions and promotions
        ev = jnp.minimum(ev, faults_apply.next_fault_event_t(state.faults))
    return ev


def _leap_local(s: SimState, new_t, do, cfg: SimConfig, pset: PolicySet,
                params):
    """Advance one cluster's wait accounting from ``s.t`` to ``new_t`` in
    closed form — the per-tick ``_record_wait`` deltas over a quiescent gap
    telescope: TotalTime -= map[id]; map[id] = since(enqueue); TotalTime +=
    map[id] per tick sums to ``new_cur - old_rec`` per still-queued
    processed slot (scheduler.go:309-312). Returns ``(state', rate)`` with
    ``rate`` the per-tick f32 accrual (processed slots x tick_ms) the
    metric reconstruction uses for the skipped samples.

    ``do`` is the quiescence vote and must gate the whole accrual, not
    just the leap distance: after a NON-quiescent tick the masks below are
    computed from post-tick state and can cover slots the pass did not
    process this tick (a successor rotated into the Level0 head, say),
    whose stale FREC would accrue a delta the dense driver only records a
    tick later — wrong at a run or chunk boundary even though it
    telescopes out mid-run.

    Bit-parity domain: the dense path folds one float32 add per tick (per
    slot in the serial sweeps); the closed form adds the telescoped sum
    once. Both are exact — hence bit-identical — while the accrued values
    are integer-valued float32 below 2^24 ms, which every parity surface
    satisfies by orders of magnitude (PARITY.md §time compression).

    Which slots accrue is the POLICY's business — each kernel family
    declares its fixed-point processing set (policies/kernels.py
    ``leap_wait_masks``) and ``pset.leap_masks`` dispatches it under the
    same traced index as the scheduling pass."""
    l0_mask, l1_mask = pset.leap_masks(s, cfg, params)
    l0_mask = jnp.logical_and(l0_mask, do)
    l1_mask = jnp.logical_and(l1_mask, do)

    def accrue(q, mask, total):
        cur = (new_t - q.enq_t).astype(jnp.int32)
        frec = q.rec_wait
        delta = jnp.where(mask, (cur - frec).astype(jnp.float32), 0.0)
        q = Q.set_field(q, "rec_wait", jnp.where(mask, cur, frec))
        return q, total + delta.sum()

    # dense tick order: the Level1 sweep accrues before the Level0 head
    l1, total = accrue(s.l1, l1_mask, s.wait_total)
    l0, total = accrue(s.l0, l0_mask, total)
    rate = (l0_mask.sum() + l1_mask.sum()).astype(jnp.float32) * cfg.tick_ms
    return s.replace(l0=l0, l1=l1, wait_total=total), rate


# --------------------------------------------------------------------------
# phase 1/2: completions, lent returns, virtual-node expiry
# --------------------------------------------------------------------------

def _release_local(s: SimState, t):
    run, free, done = R.release(s.run, s.node_free, t)
    return s.replace(run=run, node_free=free), done


def _expire_vnodes_local(s: SimState, t):
    expired = jnp.logical_and(s.node_active, s.node_expire <= t)
    zero = jnp.zeros_like(s.node_cap)
    return s.replace(
        node_active=jnp.logical_and(s.node_active, jnp.logical_not(expired)),
        node_cap=jnp.where(expired[:, None], zero, s.node_cap),
        node_free=jnp.where(expired[:, None], zero, s.node_free),
        node_expire=jnp.where(expired, R.NEVER, s.node_expire),
    )


def _pack_returns(run, done, M: int):
    """First M finished-foreign-job slots per cluster as packed rows.

    ``run`` is the running set *before* release cleared the completed slots.
    Returns (rows [C, M, RF], take [C, M]): the outbound JobFinished ->
    ReturnToBorrower messages (scheduler.go:158-191). owner >= 0 is a
    borrower index; FOREIGN (-2) trader placeholders are returned to nobody
    (Go posts to the literal URL "Foreign" and gives up)."""
    is_ret = jnp.logical_and(done, run.owner >= 0)  # [C_loc, S]
    order = jnp.argsort(jnp.logical_not(is_ret), axis=1, stable=True)[:, :M]
    take = jnp.take_along_axis(is_ret, order, axis=1)  # [C_loc, M]
    rows = R.gather_rows_along(run, order)  # [C_loc, M, RF] i32
    dropped = jnp.sum(is_ret, axis=1) - jnp.sum(take, axis=1)  # beyond M
    return rows, take, dropped.astype(jnp.int32)


def _deliver_returns(state: SimState, rows, take, ex) -> SimState:
    """Cross-cluster half of JobFinished: finished foreign jobs (owner >= 0)
    are posted back to their borrower, which removes them from its
    BorrowedQueue (server.go:115-137, 260-290). Global (non-vmapped) phase;
    under sharding the message block rides one all-gather.

    ``rows``/``take`` come from ``_pack_returns``.
    """
    C_loc, M = take.shape
    # dst = global borrower index; -1 marks an empty message slot
    dst_local = jnp.where(take, rows[..., R.ROWNER], -1)
    msg_dst = ex.gather(dst_local).reshape(-1)  # [C_tot*M]
    msg_rows = ex.gather(rows).reshape(-1, R.RF)
    gidx = ex.global_index(C_loc)

    def remove_for_cluster(borrowed_q, c):
        # One union mask over all messages, then ONE compact. Equivalent to
        # applying the messages sequentially: each message removes every row
        # equal to it on (id, cores, mem, dur) — field equality, not slot
        # index — so the removed set is the union regardless of order, and a
        # per-message scan-of-compacts (n_msgs argsorts per tick) is wasted
        # work.
        q = borrowed_q
        hit = jnp.logical_and(
            jnp.logical_and(q.id[None, :] == msg_rows[:, None, R.RID],
                            q.cores[None, :] == msg_rows[:, None, R.RCORES]),
            jnp.logical_and(q.mem[None, :] == msg_rows[:, None, R.RMEM],
                            q.dur[None, :] == msg_rows[:, None, R.RDUR]))
        matched = jnp.logical_and(
            jnp.any(jnp.logical_and(hit, (msg_dst == c)[:, None]), axis=0),
            q.slot_valid())
        return Q.compact(q, jnp.logical_not(matched))

    borrowed = jax.vmap(remove_for_cluster)(state.borrowed, gidx)
    return state.replace(borrowed=borrowed)


# --------------------------------------------------------------------------
# phase 3: arrivals
# --------------------------------------------------------------------------

def pack_arrivals(arr: Arrivals) -> tuple[jax.Array, jax.Array]:
    """Pre-stack the arrival stream as ready-made queue rows [C, A, Q.NF].

    Done once per run (outside the tick scan): the per-tick ingest then
    extracts its window with a one-hot contraction instead of six batched
    gathers — TPU gathers serialize and were the single largest per-tick
    cost at 4k clusters. Column order comes from the canonical field schema
    (ops/fields.py), the same one site the queue layouts derive theirs
    from."""
    own = jnp.full(arr.t.shape, Q.OWN, jnp.int32)
    zero = jnp.zeros(arr.t.shape, jnp.int32)
    vals = {"id": arr.id, "cores": arr.cores, "mem": arr.mem, "gpu": arr.gpu,
            "dur": arr.dur, "enq_t": arr.t, "owner": own, "rec_wait": zero,
            "jclass": F.job_class(arr.cores, arr.gpu), "retries": zero}
    rows = jnp.stack([vals[n] for n in F.QUEUE_FIELDS],
                     axis=-1).astype(jnp.int32)
    return rows, arr.n


def _bucket_arrivals_host(arr: Arrivals, n_ticks: int, tick_ms: int):
    """The shared host-side bucketing core behind ``pack_arrivals_by_tick``
    and ``pack_arrivals_chunks``: computes each arrival's destination tick
    and rank-in-tick without materializing any padded rows tensor.

    Returns ``(fields [C, A, NF], dest [C, A], ok [C, A], rank [C, A],
    counts [T, C])`` — ``ok`` marks arrivals landing inside the horizon,
    ``dest`` parks the rest on a virtual overflow tick ``n_ticks``."""
    t = np.asarray(arr.t)
    C, A = t.shape
    n = np.asarray(arr.n)
    valid = np.arange(A)[None, :] < n[:, None]
    # the rank-in-group computation below requires time-sorted rows; an
    # unsorted stream would produce negative ranks that wrap into wrong
    # slots (silently corrupt buckets) — fail fast instead
    if A > 1 and not np.all(np.diff(t, axis=1)[valid[:, 1:]] >= 0):
        raise ValueError("pack_arrivals_by_tick requires per-cluster "
                         "time-sorted arrivals")
    # destination tick index (0-based scan step); tick k has clock (k+1)*tick_ms.
    # Computed in int64: the stream's int32 dtype would wrap `t + tick_ms - 1`
    # negative for arrivals near 2^31 and bucket a beyond-horizon job into
    # tick 0 instead of parking it on the overflow tick (ADVICE r5).
    dest = np.maximum((t.astype(np.int64) + tick_ms - 1) // tick_ms, 1) - 1
    ok = valid & (dest < n_ticks)
    dest = np.where(ok, dest, n_ticks)  # parked on a virtual overflow tick
    # per-cluster arrivals are time-sorted, so same-dest rows are contiguous
    # and rank-in-group = global position - group start
    counts2d = np.zeros((C, n_ticks + 1), np.int32)
    np.add.at(counts2d, (np.arange(C)[:, None], dest), 1)
    firsts = np.zeros((C, n_ticks + 1), np.int64)
    firsts[:, 1:] = np.cumsum(counts2d, axis=1)[:, :-1]
    rank = np.arange(A)[None, :] - firsts[np.arange(C)[:, None], dest]
    vals = {"id": np.asarray(arr.id), "cores": np.asarray(arr.cores),
            "mem": np.asarray(arr.mem), "gpu": np.asarray(arr.gpu),
            "dur": np.asarray(arr.dur), "enq_t": t,
            "owner": np.full_like(t, int(Q.OWN)),
            "rec_wait": np.zeros_like(t),
            "jclass": F.job_class(np.asarray(arr.cores),
                                  np.asarray(arr.gpu)).astype(np.int32),
            "retries": np.zeros_like(t)}
    fields = np.stack([vals[n] for n in F.QUEUE_FIELDS], axis=-1)  # [C, A, NF]
    return fields, dest, ok, rank, counts2d.T[:n_ticks].copy()


def pack_arrivals_by_tick(arr: Arrivals, n_ticks: int,
                          tick_ms: int) -> st.TickArrivals:
    """Bucket the stream by destination tick (host-side numpy, once per
    run): a job arriving at ``ta`` is ingested at the first tick whose
    clock ``t = k * tick_ms`` satisfies ``ta <= t`` — exactly the engine's
    ``due`` rule and Go's per-tick drain of everything already posted
    (server.go:53-78 + the 1 s loop). Arrivals beyond the horizon are
    dropped here exactly as the windowed path never reaches them.

    Rows are padded to the STREAM-GLOBAL max arrivals-per-tick ``K``; at
    trace-scale burstiness that tensor is mostly padding and can be GBs —
    chunked drivers should use ``pack_arrivals_chunks``, which pads each
    chunk to its own max instead."""
    fields, dest, ok, rank, counts = _bucket_arrivals_host(arr, n_ticks,
                                                           tick_ms)
    C = fields.shape[0]
    K = max(int(counts.max(initial=1)), 1)
    rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                           (n_ticks, C, K, Q.NF)).copy()
    cc, aa = np.nonzero(ok)
    rows[dest[cc, aa], cc, rank[cc, aa]] = fields[cc, aa]
    # host numpy, not device arrays: the bucketed tensor can be GBs at
    # trace scale, and callers chunk/shard it — committing it to the
    # default device here would hold a full extra HBM copy alive next to
    # the per-chunk placements (jit transfers numpy leaves on use)
    return st.TickArrivals(rows=rows, counts=counts)


def round_up_pow2(k: int) -> int:
    """Smallest power of two >= k (>= 1). The K-bucket rounding that bounds
    the number of distinct chunk shapes — and hence XLA compiles — at
    log2(max K) for a whole run."""
    return 1 << max(int(k) - 1, 0).bit_length()


def pack_arrivals_chunks(arr: Arrivals, chunk_sizes: Sequence[int],
                         tick_ms: int, start: int = 0,
                         k_bucket=round_up_pow2) -> list[st.TickArrivals]:
    """Ragged per-chunk bucketing: ``pack_arrivals_by_tick`` for a chunked
    driver, padding each chunk's ``[ticks, C, K_chunk, NF]`` rows tensor to
    that CHUNK's own max arrivals-per-tick instead of the stream-global max.
    ``K_chunk`` is rounded up by ``k_bucket`` (powers of two by default) so
    the per-chunk run functions compile once per bucket, not once per
    chunk. Chunk ``i`` covers ticks ``[start + sum(chunk_sizes[:i]),
    start + sum(chunk_sizes[:i+1]))``; ``start`` supports checkpoint-resumed
    drivers that re-bucket only the remaining ticks.

    Semantically identical to slicing the global-K tensor: ingest masks
    rows beyond each tick's count (``_ingest_packed_local``), so padding
    width is invisible to the simulation — only to HBM and the H2D link.
    All tensors are host numpy; callers stream them to the device
    (bench._engine_run double-buffers the transfer under the previous
    chunk's scan)."""
    n_ticks = start + sum(chunk_sizes)
    fields, dest, ok, rank, counts = _bucket_arrivals_host(arr, n_ticks,
                                                           tick_ms)
    C = fields.shape[0]
    cc, aa = np.nonzero(ok)
    d, r = dest[cc, aa], rank[cc, aa]
    # one stable sort by destination tick, then each chunk is a contiguous
    # slice (searchsorted) — not a per-chunk mask over the whole stream,
    # which would be O(chunks x arrivals) host work at trace scale
    order = np.argsort(d, kind="stable")
    d, cc, aa, r = d[order], cc[order], aa[order], r[order]
    bounds = np.searchsorted(
        d, np.cumsum([start] + list(chunk_sizes)))
    # clamp buckets at the exact stream-global max: pow2 rounding must
    # never pad a chunk PAST what the global-K path would have used (a
    # near-uniform stream whose max is e.g. 6 should not inflate to 8) —
    # the shape set stays bounded: {pow2 < K_global} ∪ {K_global}
    k_global = max(int(counts.max(initial=1)), 1)
    out = []
    off = start
    for i, nt in enumerate(chunk_sizes):
        kc = int(counts[off:off + nt].max(initial=0))
        K = max(min(int(k_bucket(max(kc, 1))), k_global), kc, 1)
        rows = np.broadcast_to(np.asarray(Q._INVALID_ROW),
                               (nt, C, K, Q.NF)).copy()
        sl = slice(bounds[i], bounds[i + 1])
        rows[d[sl] - off, cc[sl], r[sl]] = fields[cc[sl], aa[sl]]
        out.append(st.TickArrivals(rows=rows,
                                   counts=counts[off:off + nt].copy()))
        off += nt
    return out


def _ingest_packed_local(s: SimState, rows: jax.Array, cnt: jax.Array, t,
                         cfg: SimConfig, to_delay: bool):
    """``_ingest_local`` for pre-bucketed TickArrivals: the tick's rows
    arrive as a scan input, so there is no due/window scan and no ingest
    deferral (K covers the data's maximum by construction)."""
    K = rows.shape[0]
    valid = jnp.arange(K, dtype=jnp.int32) < cnt
    batch = Q.JobQueue(data=rows, count=cnt)
    tgt = s.l0 if to_delay else s.ready
    dropped = Q.push_many_dropped(tgt, valid)
    s = s.replace(drops=s.drops.replace(queue=s.drops.queue + dropped))
    if to_delay:
        s = s.replace(l0=Q.push_many(s.l0, batch, valid, prefix=True),
                      wait_jobs=s.wait_jobs + cnt,
                      jobs_in_queue=s.jobs_in_queue + cnt)
    else:
        s = s.replace(ready=Q.push_many(s.ready, batch, valid, prefix=True))
    return s.replace(arr_ptr=s.arr_ptr + cnt)


def _ingest_local(s: SimState, arr_rows: jax.Array, arr_n: jax.Array, t,
                  cfg: SimConfig, to_delay: bool):
    """Enqueue arrivals with arr_t <= t. DELAY path appends to Level0 and
    starts the wait timer + JobsCount + jobs_in_queue counter (the /delay
    handler, server.go:53-78); FIFO path appends to ReadyQueue (the /
    handler, server.go:23-51).

    ``arr_rows``: [A, Q.NF] pre-packed queue rows (pack_arrivals), enq_t
    column = arrival time. The window [arr_ptr, arr_ptr+K) is extracted as a
    one-hot matmul (no gather)."""
    A = arr_rows.shape[0]
    K = min(cfg.max_ingest_per_tick, A)
    a = jnp.arange(A, dtype=jnp.int32)
    in_window = jnp.logical_and(a >= s.arr_ptr, a < s.arr_ptr + K)
    due = jnp.logical_and(jnp.logical_and(a >= s.arr_ptr, a < arr_n),
                          arr_rows[:, Q.FENQ] <= t)  # everything Go ingests now
    elig = jnp.logical_and(due, in_window)  # what fits this tick's window
    n = jnp.sum(elig).astype(jnp.int32)
    # due arrivals beyond the window slip to the next tick — a timing
    # divergence from Go; count it so parity runs can assert the window
    # never bound (Drops.ingest)
    deferred = (jnp.sum(due) - n).astype(jnp.int32)
    s = s.replace(drops=s.drops.replace(ingest=s.drops.ingest + deferred))
    # one-hot window extraction: a [K, A] contraction against the packed
    # rows. Measured alternatives at 4k clusters: vmapped dynamic_slice
    # lowers to a serializing gather (2x the whole tick); the int32 matmul
    # is exact and the fastest form XLA offers here.
    hot = (a[None, :] == (s.arr_ptr + jnp.arange(K, dtype=jnp.int32))[:, None])
    rows = hot.astype(arr_rows.dtype) @ arr_rows  # [K, NF]
    valid = jnp.arange(K, dtype=jnp.int32) < n
    batch = Q.JobQueue(data=rows, count=n)
    tgt = s.l0 if to_delay else s.ready
    dropped = Q.push_many_dropped(tgt, valid)
    s = s.replace(drops=s.drops.replace(queue=s.drops.queue + dropped))
    if to_delay:
        q = Q.push_many(s.l0, batch, valid, prefix=True)
        s = s.replace(l0=q, wait_jobs=s.wait_jobs + n, jobs_in_queue=s.jobs_in_queue + n)
    else:
        q = Q.push_many(s.ready, batch, valid, prefix=True)
        s = s.replace(ready=q)
    return s.replace(arr_ptr=s.arr_ptr + n)


def _borrow_match(state: SimState, want, jobs: Q.JobRec, cfg: SimConfig, ex) -> SimState:
    """Global borrow phase: BorrowResources' broadcast + first-win
    (server.go:160-248) determinized to lowest-lender-cluster-index.

    ``want``: [C_loc] bool, ``jobs``: JobRec with [C_loc] leaves (each
    cluster's failing wait-head). Feasibility is Lend()'s strict > check
    (scheduler.go:194-202) against the lender's current state — i.e. after
    this tick's scheduling pass, per PARITY.md phase 4 — and no reservation
    is made, matching the Go handler. Under sharding: one all-gather of the
    probe jobs, one min-reduction for the winner — the collective form of
    the goroutine fan-out/first-win idiom."""
    C_loc = want.shape[0]
    gidx = ex.global_index(C_loc)  # my lenders, global indices
    g_want = ex.gather(want)  # [C_tot]
    g_jobs: Q.JobRec = jax.tree.map(ex.gather, jobs)
    C_tot = g_want.shape[0]
    bidx = jnp.arange(C_tot, dtype=jnp.int32)

    # feas[l_local, b_global]: can my lender l host borrower b's job?
    def lender_view(free_l, active_l):
        return jax.vmap(lambda c, m: P.can_lend(
            free_l, active_l, Q.JobRec.make(cores=c, mem=m))
        )(g_jobs.cores, g_jobs.mem)

    feas = jax.vmap(lender_view)(state.node_free, state.node_active)
    feas = jnp.logical_and(feas, gidx[:, None] != bidx[None, :])  # no self-lend
    feas = jnp.logical_and(feas, g_want[None, :])
    INF = jnp.int32(2**31 - 1)
    local_best = jnp.min(jnp.where(feas, gidx[:, None], INF), axis=0)  # [C_tot]
    winner = ex.allmin(local_best)  # lowest feasible lender, global
    matched_g = winner < INF  # [C_tot]

    # Borrower side (local): j.Ownership = own URL (server.go:166), push to
    # BorrowedQueue, pop WaitQueue (scheduler.go:239-242).
    matched_loc = jnp.logical_and(matched_g[gidx], want)
    owned = jobs.with_(owner=gidx)

    def borrower_update(s_wait, s_borrowed, job, m):
        return (Q.pop_front(s_wait, m), Q.push_back(s_borrowed, job, m),
                Q.push_back_dropped(s_borrowed, m))

    wait, borrowed, bdrop = jax.vmap(borrower_update)(
        state.wait, state.borrowed, owned, matched_loc)

    # Lender side (local): append to LentQueue (server.go:94-107). Several
    # borrowers may win the same lender in one tick (the Go handler takes
    # them all); deliver in global borrower-index order.
    send_rows = Q.JobQueue(data=g_jobs.with_(owner=bidx).vec,
                           count=jnp.sum(matched_g).astype(jnp.int32))

    def lender_update(lent_q, gl):
        take = jnp.logical_and(matched_g, winner == gl)
        return Q.push_many(lent_q, send_rows, take), Q.push_many_dropped(lent_q, take)

    lent, ldrop = jax.vmap(lender_update)(state.lent, gidx)
    return state.replace(wait=wait, borrowed=borrowed, lent=lent,
                         drops=state.drops.replace(
                             queue=state.drops.queue + bdrop + ldrop))


# --------------------------------------------------------------------------
# phase 6: trader-visible state snapshot
# --------------------------------------------------------------------------

def _snapshot(state: SimState, t, cfg: SimConfig) -> SimState:
    """Refresh each trader's cached cluster state on the stream cadence
    (trader_server.go:24-47: 5 s ClusterState stream; trader.go:71-108)."""
    do = (t % cfg.trader.state_cadence_ms) == 0
    cu, mu = st.snapshot_utilization(state)
    aw = st.avg_wait_ms(state)
    tr = state.trader
    pick = lambda new, old: jnp.where(do, new, old)
    return state.replace(trader=tr.replace(
        snap_core_util=pick(cu, tr.snap_core_util),
        snap_mem_util=pick(mu, tr.snap_mem_util),
        snap_avg_wait=pick(aw, tr.snap_avg_wait)))


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------

class Engine:
    """Builds the jitted tick/run functions for a given SimConfig.

    ``ex`` is the cross-cluster exchange (parallel/exchange.py):
    LocalExchange for a whole cluster axis on one device, MeshExchange when
    the tick runs inside shard_map over a mesh (parallel/sharded_engine.py).

    ``policies`` selects the compiled policy repertoire (a
    ``policies.PolicySet``): ``None`` builds the singleton set for
    ``cfg.policy`` — the classic one-policy engine, bit-identical to the
    pre-zoo dispatch (tests/test_policies.py). A multi-member set compiles
    every member into one program; the run entry points then take a
    ``PolicyParams`` pytree whose traced ``idx`` picks the member — the
    axis the tournament driver vmaps over (tools/tournament.py).
    """

    def __init__(self, cfg: SimConfig, ex=None, policies=None):
        from multi_cluster_simulator_tpu.parallel.exchange import LocalExchange
        self.cfg = cfg
        self.ex = ex if ex is not None else LocalExchange()
        self.pset = policies if policies is not None else \
            PolicySet.from_config(cfg)
        self._default_params = self.pset.params_for(cfg)
        if cfg.n_res not in (2, 3):
            raise ValueError(f"n_res must be 2 or 3, got {cfg.n_res}")
        for field in ("ffd_sweep", "fifo_drain", "delay_sweep"):
            v = getattr(cfg, field)
            if v not in ("wave", "serial"):
                raise ValueError(
                    f"{field} must be 'wave' or 'serial', got {v!r}")
        if cfg.fused not in ("off", "on", "auto"):
            raise ValueError(
                f"fused must be 'off', 'on' or 'auto', got {cfg.fused!r}")
        if cfg.fused_block < 1:
            raise ValueError(f"fused_block must be >= 1, got {cfg.fused_block}")
        if cfg.trader.enabled and cfg.n_res != 3:
            raise ValueError("the trader market carves 3-dim resources; "
                             "set n_res=3 when trader.enabled")
        if cfg.trader.enabled:
            try:
                from multi_cluster_simulator_tpu.market import trader as market
            except ModuleNotFoundError as e:  # pragma: no cover
                raise NotImplementedError(
                    "the trader market (market/) is not available in this build"
                ) from e
            self._trade_round = functools.partial(market.trade_round, cfg=cfg,
                                                  ex=self.ex)
        else:
            self._trade_round = None

    def fused_active(self) -> bool:
        """Does this engine run the per-cluster prefix as the Pallas
        kernel? ``off`` never, ``on`` always (interpret-mode on non-TPU
        backends — the CPU/CI oracle), ``auto`` only where it pays: a real
        TPU backend (kernels.fused_tick.is_active is the one definition)."""
        from multi_cluster_simulator_tpu.kernels import fused_tick
        return fused_tick.is_active(self.cfg)

    def fused_provenance(self) -> dict:
        """The fused-kernel provenance fields every bench/probe detail
        dict records (mode + resolved block shape + phase span +
        interpret), so a recorded number names the executable that
        produced it."""
        from multi_cluster_simulator_tpu.kernels import fused_tick
        return fused_tick.provenance(self.cfg)

    def prefix_phases(self) -> tuple[str, ...]:
        """The tick phases THIS config's per-cluster prefix engages, in
        obs.profile.TICK_PHASES order — the span the fused kernel replays
        (kernels.fused_tick.engaged_span is the one definition; faults and
        vnode expiry are config-gated Python branches, so a faults-off
        config fuses a shorter prefix rather than paying dead phases)."""
        from multi_cluster_simulator_tpu.kernels import fused_tick
        return fused_tick.engaged_span(self.cfg)

    def prefix_terminal(self) -> bool:
        """Does the tick END with the per-cluster prefix? True when every
        post-span phase is structurally off: no return delivery or borrow
        matching (``cfg.borrowing``) and no trader snapshot/trade round.
        When terminal, the checked exit-narrow and the obs metrics tap
        fold into the span (the fused kernel's epilogue) — the span-end
        state IS the post-tick state, so the folded tap reads exactly
        what the post-tick tap would."""
        return (not self.cfg.borrowing and not self.cfg.trader.enabled
                and self._trade_round is None)

    def _span_ingest_schedule(self, state: SimState, arr_rows, arr_n, t,
                              params, tick_indexed: bool,
                              do_ingest: bool = True,
                              do_schedule: bool = True):
        """Phases 4+5 on a batched state — the HOT SPAN. This one function
        is both the unfused path (called on the full [C] state) and the
        fused kernel's body (called on a [block] slice whose columns are
        VMEM-resident, kernels/fused_tick.py), which is what makes the
        fused path bit-identical by construction rather than by porting.

        4. arrivals — the ingest target is the active policy's (Level0
        for the queue-sweep families, ReadyQueue for FIFO). Static when
        every compiled set member agrees (the singleton/classic case —
        identical to the old cfg.policy branch); a mixed set switches on
        the traced index, each branch bitwise the seed path.
        5. scheduling pass: the policy zoo's dispatch (policies/base.py) —
        the member params.idx selects runs its batched kernel; non-FIFO
        members emit an all-False borrow_want."""
        cfg = self.cfg
        ingest = _ingest_packed_local if tick_indexed else _ingest_local

        def run_ingest(s_, to_delay):
            return jax.vmap(
                functools.partial(ingest, cfg=cfg, to_delay=to_delay),
                in_axes=(_STATE_AXES, 0, 0, None),
                out_axes=_STATE_AXES)(s_, arr_rows, arr_n, t)

        if do_ingest:
            with phase_scope("ingest"):
                to_delay = self.pset.ingest_to_delay()
                if to_delay is not None:
                    state = run_ingest(state, to_delay)
                else:
                    flag = self.pset.to_delay_table()[params.idx]
                    state = jax.lax.cond(flag,
                                         lambda s_: run_ingest(s_, True),
                                         lambda s_: run_ingest(s_, False),
                                         state)
        if do_schedule:
            with phase_scope("schedule"):
                state, want, bjob_vec = self.pset.dispatch(state, t, params,
                                                           cfg)
        else:
            C = state.arr_ptr.shape[0]
            want = jnp.zeros((C,), bool)
            bjob_vec = jnp.zeros((C, Q.NF), jnp.int32)
        return state, want, bjob_vec

    def policy_provenance(self, params=None) -> dict:
        """(registered policy name(s), param digest) for detail dicts — the
        provenance key every bench/probe row records so results stay
        joinable across BENCH_*.json rounds. With the default params this
        names the singleton policy; a multi-member engine lists the set."""
        if params is None and len(self.pset.names) == 1:
            return self.pset.provenance(self.cfg)
        from multi_cluster_simulator_tpu.policies.base import params_digest
        p = params if params is not None else self._default_params
        return {"name": "|".join(self.pset.names),
                "params_digest": params_digest(p)}

    def market_provenance(self, params=None) -> dict:
        """Market-backend provenance for detail dicts: which matching
        kernel priced this run's trade rounds, at what solver depth, under
        which traced hyperparameters (the params digest covers the mkt_*
        leaves — policies/base.py), so A/B rows across market backends
        stay joinable exactly like policy rows."""
        from multi_cluster_simulator_tpu.policies.base import params_digest
        tc = self.cfg.trader
        out = {"enabled": bool(tc.enabled),
               "matching": tc.matching.value if tc.enabled else None}
        if tc.enabled:
            out["params_digest"] = params_digest(
                params if params is not None else self._default_params)
            if tc.matching is MatchKind.SINKHORN:
                out.update(iters=tc.sinkhorn_iters, eps=tc.sinkhorn_eps)
            elif tc.matching is MatchKind.CVX:
                out.update(iters=tc.cvx_iters, step=tc.cvx_step,
                           rho=tc.cvx_rho, smooth=tc.cvx_smooth)
        return out

    # -- single tick (pure; vmap/global composition) --
    def tick(self, state: SimState, arrivals: Arrivals) -> SimState:
        return self._tick(state, pack_arrivals(arrivals), emit_io=False)[0]

    def tick_io(self, state: SimState, arrivals: Arrivals) -> tuple[SimState, TickIO]:
        """One tick, also returning the host-visible TickIO events."""
        return self._tick(state, pack_arrivals(arrivals), emit_io=True)[:2]

    def step_tick(self, state: SimState, rows: jax.Array, counts: jax.Array,
                  params=None) -> SimState:
        """One tick with pre-bucketed per-tick arrivals — the environment
        mode's step entry (envs/cluster_env.py). ``rows [C, K, NF]`` /
        ``counts [C]`` are exactly one tick's TickArrivals slice, so this
        is the scan body of the tick-indexed ``run`` called once: the env's
        T-step trajectory is bit-identical to one ``run_jit`` call over the
        same bucketed stream (tests/test_env.py pins it). ``params`` is
        the PolicyParams pytree — the RL action enters here as the
        ``rl_scores`` leaf."""
        return self._tick(state, (rows, counts), emit_io=False,
                          tick_indexed=True, params=params)[0]

    def _span_prefix(self, state: SimState, arr_rows, arr_n, t, params,
                     tick_indexed: bool, emit_returns: bool, obs=None,
                     phase_limit=None, only_phase=None):
        """Phases 1–5 — the per-cluster-local PREFIX of the tick: faults →
        completions/returns-pack → vnode expiry → ingest → schedule. Every
        op in here is per-cluster (vmapped over the cluster axis), which is
        what makes the whole prefix blockable: with ``cfg.fused`` the
        kernel body replays THIS function on block-resident values
        (kernels/fused_tick.py), so fused == unfused is equality of the
        same code. The first cross-cluster exchange — return delivery,
        borrow matching, snapshot, trade — stays in ``_tick``; the
        prefix's outputs are exactly what those phases consume.

        ``emit_returns``: pack the finished-foreign-job return rows
        (needed by borrowing's delivery or an ``emit_io`` tick); when off,
        ``ret_rows``/``ret_valid`` return as None so the fused path
        carries no dead outputs. ``obs``: an optional ``(pc, cursor)``
        pair (obs.device.tap_pc form) engaging the metrics tap as the
        span EPILOGUE — legal only when ``prefix_terminal()`` (the
        span-end state is the post-tick state). ``phase_limit`` truncates
        as in ``_tick``; ``only_phase`` (static int, exclusive with
        ``phase_limit``) runs exactly ONE phase — the boundary-bytes
        probe's per-phase-executable hook (kernels.span_boundary_bytes),
        never a simulation path.

        Compact node storage: widened ONCE at span entry so every phase
        computes in int32 exactly as the wide layout does (checked=False
        by the conservation invariant: free stays in [0, cap] and cap is
        bounded by the plan's audit — nothing fresh enters here). When
        the prefix is terminal the CHECKED exit narrow folds in too, so
        the fused kernel loads AND stores the narrow columns.

        Returns ``(state, want, bjob_vec, ret_rows, ret_valid, obs_out)``
        with ``obs_out = (pc', cursor', placed_d, depth)`` or None."""
        cfg = self.cfg
        if only_phase is not None:
            phase_on = lambda k: k == only_phase  # noqa: E731
        else:
            phase_on = (lambda k: True) if phase_limit is None else \
                (lambda k: k <= phase_limit)
        node_dt = state.node_free.dtype
        node_narrow = node_dt != jnp.int32
        if node_narrow:
            state = state.replace(node_free=F.widen(state.node_free),
                                  node_cap=F.widen(state.node_cap))

        # 1. fault phase (faults/apply.py): node failures kill + requeue
        # the jobs running on them BEFORE completions fire (a job ending
        # on the tick its node dies is killed, not completed), capacity
        # masks out, repairs restore. The requeue target is the policy's
        # ingest queue — same static/traced dispatch as the arrival phase.
        if cfg.faults.enabled and phase_on(1):
            with phase_scope("faults"):
                def run_faults(s_, to_delay):
                    return jax.vmap(
                        functools.partial(faults_apply.fault_phase_local,
                                          cfg=cfg, to_delay=to_delay),
                        in_axes=(_STATE_AXES, None),
                        out_axes=_STATE_AXES)(s_, t)

                fdelay = self.pset.ingest_to_delay()
                if fdelay is not None:
                    state = run_faults(state, fdelay)
                else:
                    flag = self.pset.to_delay_table()[params.idx]
                    state = jax.lax.cond(
                        flag, lambda s_: run_faults(s_, True),
                        lambda s_: run_faults(s_, False), state)

        # 2. completions + the returns PACK (per-cluster argsort). The
        # cross-cluster half — delivering the packed rows to their owners
        # — happens in ``_tick`` after the prefix; the reorder is bitwise
        # free because delivery touches ONLY ``state.borrowed`` and no
        # prefix phase reads or writes it (expire: node columns; ingest:
        # arrival queues; schedule: queues/runset/nodes).
        with phase_scope("release"):
            if phase_on(2):
                run_before = state.run
                st2, done = jax.vmap(_release_local,
                                     in_axes=(_STATE_AXES, None),
                                     out_axes=(_STATE_AXES, 0))(state, t)
                state = st2
            if phase_on(2) and emit_returns:
                ret_rows, ret_valid, ret_dropped = _pack_returns(
                    run_before, done, cfg.max_msgs)
                state = state.replace(drops=state.drops.replace(
                    msgs=state.drops.msgs + ret_dropped))
            else:
                ret_rows, ret_valid = None, None

        # 3. virtual-node expiry (off in parity mode — reference keeps them)
        if cfg.trader.enabled and cfg.trader.expire_virtual_nodes \
                and phase_on(3):
            with phase_scope("expire"):
                state = jax.vmap(_expire_vnodes_local,
                                 in_axes=(_STATE_AXES, None),
                                 out_axes=_STATE_AXES)(state, t)

        # 4+5. the ingest -> schedule span
        state, want, bjob_vec = self._span_ingest_schedule(
            state, arr_rows, arr_n, t, params, tick_indexed,
            do_ingest=phase_on(4), do_schedule=phase_on(5))

        if node_narrow and self.prefix_terminal():
            # CHECKED narrow (see _tick's exit narrow for the rationale);
            # folded into the span when nothing runs after it
            free_n, bad_f = F.narrow_store(state.node_free, node_dt)
            cap_n, bad_c = F.narrow_store(state.node_cap, node_dt)
            state = state.replace(
                node_free=free_n, node_cap=cap_n,
                run=state.run.replace(ovf=state.run.ovf + bad_f + bad_c))

        obs_out = None
        if obs is not None:
            if not self.prefix_terminal():
                raise ValueError(
                    "epilogue tap requested on a non-terminal prefix — "
                    "post-span phases would move the counters after the "
                    "tap (obs belongs to the driver's post-tick tap)")
            pc, cur = obs
            obs_out = obs_device.tap_tick_local(pc, cur, state)
        return state, want, bjob_vec, ret_rows, ret_valid, obs_out

    def _tick(self, state: SimState, packed_arrivals, emit_io: bool,
              tick_indexed: bool = False, params=None, phase_limit=None,
              obs=None):
        """The tick body. ``emit_io=False`` (the batch/scan path) skips the
        TickIO packing work when borrowing doesn't need it — the return-slot
        argsort is per-tick cost the headline config shouldn't pay.
        ``tick_indexed``: ``packed_arrivals`` is this tick's
        (rows [C, K, NF], counts [C]) TickArrivals slice instead of the
        whole stream. ``params``: the PolicyParams pytree selecting and
        parameterizing the scheduling pass (None = this engine's
        config-derived defaults, baked as constants). ``phase_limit``:
        static int truncating the body after the first N phases
        (obs.profile.TICK_PHASES order) — the profile plane's ablation
        hook (``run_prefix``/tools/profile_capture.py); None runs all
        phases (obs.profile.TICK_PHASES has the authoritative count).
        Every phase is wrapped in a ``jax.named_scope`` so profiler
        captures attribute device time per phase (trace-time metadata
        only — bitwise invisible to the compiled program's results).

        ``obs``: an optional ``(MetricsBuffer, TapCursor)`` pair. On the
        fused TERMINAL path the metrics tap runs as the kernel epilogue
        and the finished ``(mbuf, cursor)`` returns as the third element;
        otherwise the third element is None and the driver applies the
        ordinary post-tick tap (same code either way — obs.device splits
        ``tap_tick`` into the halves the kernel boundary needs).

        Returns ``(state, io, obs_out)``."""
        cfg = self.cfg
        if params is None:
            params = self._default_params
        phase_on = (lambda k: True) if phase_limit is None else \
            (lambda k: k <= phase_limit)
        t = state.t + cfg.tick_ms
        node_dt = state.node_free.dtype
        node_narrow = node_dt != jnp.int32
        terminal = self.prefix_terminal()
        emit_returns = cfg.borrowing or emit_io
        arr_rows, arr_n = packed_arrivals

        # Phases 1-5 — the per-cluster prefix. With ``cfg.fused`` it runs
        # as ONE Pallas kernel that loads each cluster block's columns
        # once, replays ``_span_prefix`` on the VMEM-resident values, and
        # writes each column back once (kernels/fused_tick.py) — the tick
        # then resumes at the first cross-cluster exchange with exactly
        # the kernel's outputs (want/bjob_vec/packed return rows).
        # ``run_prefix`` truncations INSIDE the prefix fall back to the
        # unfused path (a half-span is a diagnostic, not a kernel).
        fuse = self.fused_active() and \
            (phase_limit is None or phase_limit >= 5)
        # simlint: ignore[purity-traced-branch] -- `fuse` is a Python bool
        # from config + the static phase_limit argnum, never a tracer:
        # fused-vs-unfused is an execution strategy decided before tracing
        if fuse:
            from multi_cluster_simulator_tpu.kernels import fused_tick
            tap_in = None
            if obs is not None and terminal:
                mb0, cur0 = obs
                tap_in = (obs_device.tap_pc(mb0), cur0)
            with phase_scope("fused_prefix"):
                state, want, bjob_vec, ret_rows, ret_valid, tap_out = \
                    fused_tick.fused_prefix(
                        self, state, arr_rows, arr_n, t, params,
                        tick_indexed, emit_returns=emit_returns,
                        obs=tap_in)
            if tap_in is not None:
                # the cross-cluster tap half (scalar tick count, ring
                # rows, histogram scatter) on the kernel's tiny [C]
                # outputs; t is the post-tick clock the dense tap reads
                pc2, cur2, placed_d, depth = tap_out
                obs_out = (obs_device.tap_tick_global(
                    mb0.replace(**pc2), placed_d, depth, t, cfg.tick_ms),
                    cur2)
            else:
                obs_out = None
        else:
            state, want, bjob_vec, ret_rows, ret_valid, _ = \
                self._span_prefix(state, arr_rows, arr_n, t, params,
                                  tick_indexed, emit_returns=emit_returns,
                                  phase_limit=phase_limit)
            obs_out = None

        if ret_rows is None:
            C = state.arr_ptr.shape[0]
            ret_rows = jnp.zeros((C, cfg.max_msgs, R.RF), jnp.int32)
            ret_valid = jnp.zeros((C, cfg.max_msgs), bool)
        # 2b. return delivery — the cross-cluster half of the completions
        # phase (exchange gather). Runs after the whole prefix: bitwise
        # identical to delivering before expiry/ingest/schedule because it
        # touches ONLY ``state.borrowed``, which no prefix phase reads.
        if cfg.borrowing and phase_on(2):
            with phase_scope("release"):
                state = _deliver_returns(state, ret_rows, ret_valid, self.ex)

        # 6. borrow matching (FIFO-family cells only: want is identically
        # False elsewhere, making the match a bitwise no-op for those cells)
        if cfg.borrowing and self.pset.has_fifo and phase_on(6):
            with phase_scope("borrow"):
                state = _borrow_match(state, want, Q.JobRec(vec=bjob_vec),
                                      cfg, self.ex)

        # 7. trader state snapshot (before any trade in the same tick — the
        # stream lands just ahead of the monitor wakeup, MARKET.md §clock)
        if cfg.trader.enabled and phase_on(7):
            with phase_scope("snapshot"):
                state = _snapshot(state, t, cfg)

        # 8. trader market round (params carries the solver hyperparameter
        # leaves — the pricing backends are sweepable policy data)
        if self._trade_round is not None and phase_on(8):
            with phase_scope("trade"):
                state = self._trade_round(state, t, params=params)

        if node_narrow and not terminal:
            # CHECKED, unlike the interior permutation narrows: the plan's
            # node bound is derived (physical caps, plus contract totals
            # under the trader — a buyer's virtual node holds a backlog
            # cumsum, not a per-node amount), and a derivation gap here
            # must surface as a counted overflow, never a wrapped
            # capacity. The count lands in the running set's ovf (the
            # node tensors have no counter of their own); it is a scalar
            # total folded into every cluster's counter — the parity and
            # bench gates assert ==0, so magnitude only matters as
            # nonzero-ness. On a TERMINAL prefix this already happened
            # inside ``_span_prefix`` (folded into the kernel).
            free_n, bad_f = F.narrow_store(state.node_free, node_dt)
            cap_n, bad_c = F.narrow_store(state.node_cap, node_dt)
            state = state.replace(
                node_free=free_n, node_cap=cap_n,
                run=state.run.replace(ovf=state.run.ovf + bad_f + bad_c))

        io = TickIO(borrow_want=want, borrow_job=bjob_vec,
                    ret_rows=ret_rows, ret_valid=ret_valid) if emit_io else None
        return state.replace(t=t), io, obs_out

    # -- scan driver --
    def run(self, state: SimState, arrivals: Arrivals, n_ticks: int,
            params=None, mbuf=None):
        """Advance ``n_ticks``. Returns the final state — or, when
        ``cfg.record_metrics`` is set, ``(state, MetricSample)`` with [T] /
        [T, C] stacked per-tick series (the batch-engine form of RunMetrics'
        recorder goroutine, pkg/scheduler/metrics.go:11-31; decimate to the
        reference's 5 s marks host-side, e.g.
        ``jax.tree.map(lambda a: a[4::5], series)`` — sample 0 is t=1 s).

        ``arrivals`` may be an ``Arrivals`` stream or a pre-bucketed
        ``TickArrivals`` (pack_arrivals_by_tick) — the latter feeds each
        tick its slice as a scan input, skipping the per-tick due-window
        scan over the whole stream.

        ``params`` (PolicyParams) selects/parameterizes the policy per call
        — traced data, so a tournament can vmap this function over a
        (policy, seed) axis with one compile (tools/tournament.py); None
        bakes this engine's config-derived defaults.

        ``mbuf`` (obs.MetricsBuffer) engages the device metrics plane: the
        buffer rides the scan carry, a tap after every tick reads the
        state (never writes it — the obs-tap contract), and the updated
        buffer is appended LAST to the return tuple for the caller to
        thread into the next chunk and harvest at a chunk boundary."""
        record = self.cfg.record_metrics
        obs = mbuf is not None
        tick_ms = self.cfg.tick_ms

        def finish(state, series, mb):
            # the returned buffer stays SHARD-LOCAL (it is a carry: the
            # caller threads it into the next chunk, and a reduction here
            # would double count partials on the next boundary) — the
            # global view reduces through the exchange exactly once, at
            # harvest (ShardedEngine.collect_metrics / obs.reduce_metrics)
            out = (state,) + ((series,) if record else ()) \
                + ((mb,) if obs else ())
            return out if len(out) > 1 else out[0]

        cur0 = obs_device.cursor_of(state) if obs else None
        if isinstance(arrivals, st.TickArrivals):
            if arrivals.rows.shape[0] < n_ticks:
                raise ValueError(
                    f"TickArrivals covers {arrivals.rows.shape[0]} ticks, "
                    f"run asked for {n_ticks}")

            def body_ta(carry, x):
                s, mb, cur = carry
                s2, _, ob = self._tick(s, x, emit_io=False,
                                       tick_indexed=True, params=params,
                                       obs=(mb, cur) if obs else None)
                if obs:
                    # fused terminal path: the tap already ran as the
                    # kernel epilogue; otherwise the ordinary post-tick tap
                    mb, cur = ob if ob is not None else \
                        obs_device.tap_tick(mb, cur, s2, tick_ms)
                return (s2, mb, cur), (st.metric_sample(s2) if record
                                       else None)

            xs = (arrivals.rows[:n_ticks], arrivals.counts[:n_ticks])
            (state, mbuf, _), series = jax.lax.scan(
                body_ta, (state, mbuf, cur0), xs, length=n_ticks)
            return finish(state, series, mbuf)

        packed = pack_arrivals(arrivals)  # once, outside the tick scan

        def body(carry, _):
            s, mb, cur = carry
            s2, _, ob = self._tick(s, packed, emit_io=False, params=params,
                                   obs=(mb, cur) if obs else None)
            if obs:
                mb, cur = ob if ob is not None else \
                    obs_device.tap_tick(mb, cur, s2, tick_ms)
            return (s2, mb, cur), (st.metric_sample(s2) if record else None)

        (state, mbuf, _), series = jax.lax.scan(body, (state, mbuf, cur0),
                                                None, length=n_ticks)
        return finish(state, series, mbuf)

    def run_prefix(self, state: SimState, arrivals: st.TickArrivals,
                   n_ticks: int, phase_limit: int, params=None):
        """``run`` over a pre-bucketed stream with the tick body truncated
        after the first ``phase_limit`` phases (obs.profile.TICK_PHASES
        order) — the profile plane's ablation driver: the cost of phase k
        at a real shape is wall(prefix k) - wall(prefix k-1), measured on
        the REAL tick body of any config (tools/profile_capture.py), not a
        hand-copied phase closure. Diagnostic only: a truncated tick is
        not a simulation."""
        def body(s, x):
            return self._tick(s, x, emit_io=False, tick_indexed=True,
                              params=params, phase_limit=phase_limit)[0], None

        xs = (arrivals.rows[:n_ticks], arrivals.counts[:n_ticks])
        return jax.lax.scan(body, state, xs, length=n_ticks)[0]

    def run_io(self, state: SimState, rows: jax.Array, counts: jax.Array,
               params=None, mbuf=None):
        """Multi-tick ``tick_io``: advance one staged TickArrivals chunk
        (``rows [T, C, K, NF]`` / ``counts [T, C]``) in a single device
        dispatch, emitting the host-visible ``TickIO`` events of every tick
        stacked over the leading axis. This is the serving tier's dispatch
        unit (services/serving.py): a live host coalesces N concurrent
        request arrivals into one chunk and pays ONE dispatch for T ticks
        instead of one ``tick_io`` round trip per tick — the per-request
        path's dominant cost (~5 ms host overhead per tick, BENCH `live`).

        Chunk composition is exact: scanning T ticks here is the same
        function composition as T single-tick calls, so a window-1 driver
        and a window-W driver over the same staged stream are bit-identical
        (tests/test_pipeline.py pins run_io == run_jit over the same
        bucket). T and K are shape parameters — serving hosts keep T fixed
        at the coalesce window and pow2-bucket K so compile count stays
        bounded at log2(max K) (the pack_arrivals_chunks discipline)."""

        obs = mbuf is not None
        tick_ms = self.cfg.tick_ms
        cur0 = obs_device.cursor_of(state) if obs else None

        def body(carry, x):
            s, mb, cur = carry
            r, c = x
            s2, io, ob = self._tick(s, (r, c), emit_io=True,
                                    tick_indexed=True, params=params,
                                    obs=(mb, cur) if obs else None)
            if obs:
                mb, cur = ob if ob is not None else \
                    obs_device.tap_tick(mb, cur, s2, tick_ms)
            return (s2, mb, cur), io

        (state, mbuf, _), io = jax.lax.scan(body, (state, mbuf, cur0),
                                            (rows, counts))
        return (state, io, mbuf) if obs else (state, io)

    def run_io_jit(self, donate: bool = False):
        """A jitted ``run_io`` (same donation contract as ``run_jit``):
        (state, rows, counts) -> (state, TickIO stacked over T). One
        executable per (T, K) shape pair — serving drivers hold T fixed
        and bucket K."""
        return jax.jit(self.run_io,
                       donate_argnums=(0,) if donate else ())

    def run_jit(self, donate: bool = False):
        """A jitted ``run``: (state, arrivals, n_ticks-static) -> state, or
        (state, MetricSample series) when cfg.record_metrics is set.

        ``donate=True`` donates the input ``SimState`` buffers to the call
        (``donate_argnums``), so the state is updated in place in HBM
        instead of double-buffered — the chunked drivers thread one state
        through many calls and never reread an input. The caller's state
        arrays are INVALID after the call; clone first (``jnp.copy``) if
        the initial state must survive, e.g. for repeat timings."""
        return jax.jit(self.run, static_argnums=(2,),
                       donate_argnums=(0,) if donate else ())

    # -- event-compressed driver --
    def run_compressed(self, state: SimState, arrivals: st.TickArrivals,
                       n_ticks: int, params=None, mbuf=None):
        """``run`` with event-compressed virtual time: a ``while_loop`` that
        executes a real full-phase tick only when something can happen, and
        otherwise leaps the clock to the next event in one step — the
        classic fixed-increment -> next-event DES speedup, bit-identical to
        the dense scan (ARCHITECTURE.md §time compression).

        After each executed tick the driver compares state fingerprints
        (``_quiescence_sig``): an unchanged fingerprint proves the
        constellation is at a fixed point, so every tick before the next
        event — the next nonempty arrival tick (from the pre-bucketed
        counts), the earliest RunningSet completion, the next DELAY
        promotion threshold, the next market cadence boundary or vnode
        expiry (``_next_event_t``) — is a no-op modulo wait accrual, which
        ``_leap_local`` applies in closed form. Under sharding both the
        quiescence vote and the leap distance ride the exchange
        (``alland``/``allmin``), so every shard jumps together.

        Returns ``(state, LeapStats)``, or ``(state, series, LeapStats)``
        when ``cfg.record_metrics``: the dense per-tick series is
        reconstructed exactly — executed ticks write their sample at their
        tick index, skipped ticks replicate the fixed point with the
        closed-form wait accrual folded into ``avg_wait_ms``.

        ``mbuf`` engages the device metrics plane (appended LAST to the
        return tuple, like ``run``): executed ticks tap normally and the
        skipped ticks' samples are applied in closed form
        (``obs.tap_leap``), so the harvested buffer is bit-identical to
        the dense scan's — tests/test_obs.py pins it."""
        cfg = self.cfg
        if params is None:
            params = self._default_params
        if not isinstance(arrivals, st.TickArrivals):
            raise ValueError("time compression requires pre-bucketed "
                             "TickArrivals (pack_arrivals_by_tick / "
                             "pack_arrivals_chunks)")
        if arrivals.rows.shape[0] < n_ticks:
            raise ValueError(
                f"TickArrivals covers {arrivals.rows.shape[0]} ticks, "
                f"run asked for {n_ticks}")
        record = cfg.record_metrics
        obs = mbuf is not None
        C = state.arr_ptr.shape[0]
        stats = st.leap_stats_init()
        if record:
            ser0 = st.MetricSample(
                t=jnp.zeros((n_ticks,), jnp.int32),
                jobs_in_queue=jnp.zeros((n_ticks, C), jnp.int32),
                avg_wait_ms=jnp.zeros((n_ticks, C), jnp.float32))
        else:
            ser0 = None

        def finish(state, ser, stats, mb):
            out = (state,) + ((ser,) if record else ()) + (stats,) \
                + ((mb,) if obs else ())
            return out

        if n_ticks == 0:
            return finish(state, ser0, stats, mbuf)

        rows, counts = arrivals.rows[:n_ticks], arrivals.counts[:n_ticks]
        tick = jnp.int32(cfg.tick_ms)
        t0 = state.t
        t_end = t0 + jnp.int32(n_ticks) * tick
        inf_t = t_end + tick  # "no event inside this run"
        # next nonempty arrival tick index, shard-local: next_arr[i] is the
        # smallest j >= i with arrivals on any local cluster (n_ticks when
        # none) — one reverse cummin over the pre-bucketed counts; the
        # cross-shard min happens on the leap target itself
        nonempty = jnp.any(counts > 0, axis=1)
        idxs = jnp.where(nonempty, jnp.arange(n_ticks, dtype=jnp.int32),
                         jnp.int32(n_ticks))
        next_arr = jnp.flip(jax.lax.cummin(jnp.flip(idxs)))
        next_arr = jnp.concatenate(
            [next_arr, jnp.full((1,), n_ticks, jnp.int32)])

        def cond(carry):
            return carry[0].t < t_end

        def body(carry):
            s, stats, ser, mb, cur = carry
            i = ((s.t - t0) // tick).astype(jnp.int32)  # tick index to run
            rows_i = jax.lax.dynamic_index_in_dim(rows, i, 0, keepdims=False)
            cnt_i = jax.lax.dynamic_index_in_dim(counts, i, 0, keepdims=False)
            sig0 = _quiescence_sig(s)
            s2, _, ob = self._tick(s, (rows_i, cnt_i), emit_io=False,
                                   tick_indexed=True, params=params,
                                   obs=(mb, cur) if obs else None)
            if obs:  # the executed tick's sample, same tap as the dense scan
                mb, cur = ob if ob is not None else \
                    obs_device.tap_tick(mb, cur, s2, cfg.tick_ms)
            quiet = self.ex.alland(jnp.all(_quiescence_sig(s2) == sig0))
            # leap target: the clock of the next tick that must execute
            ev = jnp.minimum(
                _next_event_t(s2, s2.t, cfg, self.pset, params), inf_t)
            ev_clock = ((ev + tick - 1) // tick) * tick  # ceil to tick grid
            na = next_arr[jnp.minimum(i + 1, jnp.int32(n_ticks))]
            arr_clock = t0 + (na + 1) * tick
            target = self.ex.allmin(
                jnp.minimum(jnp.minimum(ev_clock, arr_clock), inf_t))
            new_t = jnp.where(quiet, jnp.maximum(target - tick, s2.t), s2.t)
            n_skip = ((new_t - s2.t) // tick).astype(jnp.int32)

            # the whole accrual rides a scalar cond, not just the masks:
            # non-quiescent executed ticks (most of a burst/drain phase)
            # must not pay the mask computation (the FFD branch re-sorts
            # the queue) plus two full queue rewrites for an identity
            def leap(s):
                return jax.vmap(
                    functools.partial(_leap_local, cfg=cfg, pset=self.pset,
                                      params=params),
                    in_axes=(_STATE_AXES, None, None),
                    out_axes=(_STATE_AXES, 0))(s, new_t, jnp.bool_(True))

            s3, rate = jax.lax.cond(
                quiet, leap, lambda s: (s, jnp.zeros((C,), jnp.float32)), s2)
            s3 = s3.replace(t=new_t)
            if obs:  # skipped ticks' samples in closed form (n_skip=0: id)
                mb, cur = obs_device.tap_leap(mb, cur, s3, n_skip,
                                              cfg.tick_ms)
            bucket = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(
                n_skip, 1).astype(jnp.float32))).astype(jnp.int32),
                0, st.LEAP_BUCKETS - 1)
            stats = st.LeapStats(
                ticks_executed=stats.ticks_executed + 1,
                leaps=stats.leaps.at[bucket].add(
                    (n_skip > 0).astype(jnp.int32)))
            if record:
                samp = st.metric_sample(s2)
                jr = jnp.arange(n_ticks, dtype=jnp.int32)
                skip_m = jnp.logical_and(jr > i, jr <= i + n_skip)
                # skipped samples: jobs_in_queue replicates the fixed
                # point; avg_wait folds the per-tick accrual rate in
                totals = (s2.wait_total[None, :]
                          + (jr - i).astype(jnp.float32)[:, None]
                          * rate[None, :])
                avg = jnp.where(s2.wait_jobs[None, :] > 0,
                                totals / jnp.maximum(s2.wait_jobs, 1)[None, :],
                                0.0)
                ser = st.MetricSample(
                    t=jnp.where(skip_m, t0 + (jr + 1) * tick,
                                ser.t).at[i].set(samp.t),
                    jobs_in_queue=jnp.where(
                        skip_m[:, None], s2.jobs_in_queue[None, :],
                        ser.jobs_in_queue).at[i].set(samp.jobs_in_queue),
                    avg_wait_ms=jnp.where(
                        skip_m[:, None], avg,
                        ser.avg_wait_ms).at[i].set(samp.avg_wait_ms))
            return (s3, stats, ser, mb, cur)

        cur0 = obs_device.cursor_of(state) if obs else None
        state, stats, series, mbuf, _ = jax.lax.while_loop(
            cond, body, (state, stats, ser0, mbuf, cur0))
        return finish(state, series, stats, mbuf)

    def run_compressed_jit(self, donate: bool = False):
        """A jitted ``run_compressed`` (same donation contract as
        ``run_jit``): (state, TickArrivals, n_ticks-static) ->
        (state, LeapStats) or (state, MetricSample series, LeapStats)."""
        return jax.jit(self.run_compressed, static_argnums=(2,),
                       donate_argnums=(0,) if donate else ())
