"""World-state tensors.

One ``SimState`` holds the entire constellation: every per-cluster field has a
leading cluster axis ``C``. This is the tensor re-design of the reference's
per-process singletons (``var sched = Scheduler{...}``,
pkg/scheduler/server.go:20; ``var trader Trader``, pkg/trader/trader.go:327):
where the Go system is N OS processes × six locked slices each, here it is
one pytree the engine threads through ``lax.scan``, shardable over the
cluster axis on a device mesh.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core.spec import (
    CORES, MEM, RES, ClusterSpec, capacities_array, node_types_array,
)
from multi_cluster_simulator_tpu.faults.schedule import (
    FaultState, init_fault_state,
)
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R

# trace source-queue codes
SRC_L1, SRC_L0, SRC_READY, SRC_WAIT, SRC_LENT, SRC_VNODE_HOLD = 0, 1, 2, 3, 4, 5


@struct.dataclass
class Arrivals:
    """Pre-generated, time-sorted arrival stream (read-only during a run).

    The tensor form of the workload client's HTTP POST stream
    (pkg/client/client.go:85-147 -> pkg/scheduler/server.go:53-78).
    """

    t: jax.Array  # [C, A] int32 ms, nondecreasing per cluster
    id: jax.Array  # [C, A] int32
    cores: jax.Array  # [C, A] int32
    mem: jax.Array  # [C, A] int32
    gpu: jax.Array  # [C, A] int32 (3-dim extension; zeros in parity configs)
    dur: jax.Array  # [C, A] int32 ms
    n: jax.Array  # [C] int32 valid prefix length


@struct.dataclass
class TickArrivals:
    """The same arrival stream pre-bucketed by destination tick, so the tick
    scan consumes its slice as a scan input instead of re-scanning the whole
    [C, A] stream for the due window every tick (engine.pack_arrivals_by_tick
    builds it host-side; the window scan was a measured ~10% of the headline
    tick at 4k clusters). K is the maximum arrivals any (tick, cluster) pair
    receives, computed from the data — ingest can never defer, making the
    bucketed run observably identical to Go's unbounded ingest by
    construction.

    K may be the stream-global max (pack_arrivals_by_tick) or a per-chunk
    max when the run is chunked (engine.pack_arrivals_chunks): ingest masks
    rows beyond each tick's count, so the padding width K is invisible to
    the simulation — ragged chunks are how the streamed bench pipeline
    keeps burst padding off the H2D link (ARCHITECTURE.md §chunk
    pipeline)."""

    rows: jax.Array  # [T, C, K, Q.NF] pre-packed queue rows per tick
    counts: jax.Array  # [T, C] int32 arrivals per (tick, cluster)

    def nbytes(self) -> int:
        """Total payload bytes — what one host→device transfer of this
        bucket moves (bench.py reports it as h2d_bytes)."""
        return int(self.rows.nbytes) + int(self.counts.nbytes)


@struct.dataclass
class TraderState:
    """Per-cluster trader agent state (pkg/trader/trader.go:24-39,71-108).

    The snapshot fields mirror the trader's cached ``clusterState``, refreshed
    on the reference's 5 s stream cadence rather than instantaneously."""

    snap_core_util: jax.Array  # [C] f32
    snap_mem_util: jax.Array  # [C] f32
    snap_avg_wait: jax.Array  # [C] f32 ms
    # Totals are sent once on stream start and never refreshed (the
    # ClusterChange flag is only true at construction, trader_server.go:17-34;
    # SetTotalResources runs only at init, cluster.go:26-40) — so they stay
    # the *physical* totals even after virtual nodes join.
    snap_total_cores: jax.Array  # [C] i32
    snap_total_mem: jax.Array  # [C] i32
    cooldown_until: jax.Array  # [C] i32 — RequestPolicyMonitor's post-trade sleeps
    seller_locked_until: jax.Array  # [C] i32 — one-contract-at-a-time + 20s TTL
    next_contract_id: jax.Array  # [C] i32 — serial ids (trader/server.go:26,46)
    spent: jax.Array  # [C] f32 — cumulative price paid (budget accounting)
    # Buyer dual price from the last cvx market round (market/cvx.py),
    # refreshed every round cvx runs; ``mkt_smooth`` blends it into the
    # next round's descending-price opening (0 = cold start from the score
    # ceiling, the stored value then enters multiplied by zero). Part of
    # SimState, so it checkpoints/reshards with every other column — the
    # pricing plane is invisible to replay/resume (PARITY.md). Zero under
    # the greedy/sinkhorn backends.
    mkt_price: jax.Array  # [C] f32


@struct.dataclass
class Drops:
    """Per-cluster counters for every place a static bound can bind.

    The reference's Go slices are unbounded, so a padded-tensor engine must
    surface — not swallow — any overflow (VERDICT r2 weak #4). Parity and
    bench runs assert all of these stay zero; a nonzero value means the
    config's static shapes are undersized for the workload and results may
    diverge from the unbounded Go semantics."""

    queue: jax.Array  # [C] i32 — push_back/push_many overflow (any queue)
    msgs: jax.Array  # [C] i32 — finished-foreign returns beyond max_msgs
    run_full: jax.Array  # [C] i32 — placement refused only because the
    #                      RunningSet was full (job stays queued; divergence)
    vslot: jax.Array  # [C] i32 — trade won but no free virtual-node slot
    carve: jax.Array  # [C] i32 — carve planned on a node but no free
    #                      RunningSet slot for the Foreign placeholder
    ingest: jax.Array  # [C] i32 — PER-TICK deferral events: +k each tick k
    #                      due arrivals sit beyond the max_ingest_per_tick
    #                      window, so one arrival deferred for 3 ticks
    #                      counts 3 (unlike the other counters, which count
    #                      jobs). Exact for the ==0 asserts; as a magnitude
    #                      it is deferral-ticks, not jobs. (Go ingests all
    #                      due arrivals at once; a binding window skews
    #                      timing.)
    failed: jax.Array  # [C] i32 — jobs killed by node failures past their
    #                      retry budget (faults/apply.py): deliberately lost
    #                      work, not a sizing bug — zero whenever the fault
    #                      plane is off or max_retries covers the churn


@struct.dataclass
class Trace:
    """Per-cluster placement event ring (capped append)."""

    t: jax.Array  # [C, E] i32
    job: jax.Array  # [C, E] i32
    node: jax.Array  # [C, E] i32
    src: jax.Array  # [C, E] i32
    n: jax.Array  # [C] i32


@struct.dataclass
class SimState:
    t: jax.Array  # [] i32 — the virtual clock (shared; ticks are lockstep)
    # nodes
    node_cap: jax.Array  # [C, N, RES] i32 (virtual slots 0 until activated)
    node_free: jax.Array  # [C, N, RES] i32
    node_active: jax.Array  # [C, N] bool
    node_expire: jax.Array  # [C, N] i32 — virtual-node expiry (NEVER default)
    node_type: jax.Array  # [C, N] i32 — device type per slot (static world
    #                       fact from the specs; the heterogeneity-aware
    #                       policies score placements by it — policies/)
    # queues (reference scheduler.go:19-30)
    l0: Q.JobQueue  # [C, ...] DELAY Level0
    l1: Q.JobQueue  # DELAY Level1
    ready: Q.JobQueue  # FIFO ReadyQueue
    wait: Q.JobQueue  # FIFO WaitQueue
    lent: Q.JobQueue  # foreign jobs I host
    borrowed: Q.JobQueue  # my jobs sent away
    run: R.RunningSet  # [C, S]
    # workload cursor
    arr_ptr: jax.Array  # [C] i32 — next unconsumed arrival
    # WaitTime stats (scheduler.go:48-63)
    wait_total: jax.Array  # [C] f32 ms (TotalTime)
    wait_jobs: jax.Array  # [C] i32 (JobsCount)
    jobs_in_queue: jax.Array  # [C] i32 (the up/down counter, metrics.go:14)
    placed_total: jax.Array  # [C] i32 — lifetime placements (throughput metric)
    drops: Drops
    trader: TraderState
    trace: Trace
    faults: FaultState  # node churn (faults/) — inert all-healthy leaves
    #                     when cfg.faults.enabled is False


# vmap prefix for the per-cluster tick phases: map every per-cluster field
# over axis 0, broadcast the (replicated) clock. Shared by the engine's
# phase vmaps and the policy kernels' batched wrappers (policies/base.py).
STATE_AXES = SimState(
    t=None, node_cap=0, node_free=0, node_active=0, node_expire=0,
    node_type=0, l0=0, l1=0, ready=0, wait=0, lent=0, borrowed=0, run=0,
    arr_ptr=0, wait_total=0, wait_jobs=0, jobs_in_queue=0, placed_total=0,
    drops=0, trader=0, trace=0, faults=0,
)


def avg_wait_ms(s: SimState) -> jax.Array:
    """WaitTime.GetAverage() (scheduler.go:56-63)."""
    return jnp.where(s.wait_jobs > 0, s.wait_total / jnp.maximum(s.wait_jobs, 1), 0.0)


@struct.dataclass
class MetricSample:
    """One tick's metric readout — the tensor form of RunMetrics' 5 s
    recorder (pkg/scheduler/metrics.go:11-31): the ``jobs_in_queue`` up/down
    counter and the ``waitTime`` running average, per cluster. Stacked by
    ``lax.scan`` into a [T]/[T, C] time-series when
    ``SimConfig.record_metrics`` is set."""

    t: jax.Array  # [] i32 virtual ms (tick timestamp)
    jobs_in_queue: jax.Array  # [C] i32
    avg_wait_ms: jax.Array  # [C] f32


def metric_sample(s: SimState) -> MetricSample:
    return MetricSample(t=s.t, jobs_in_queue=s.jobs_in_queue,
                        avg_wait_ms=avg_wait_ms(s))


# log2 histogram width for LeapStats.leaps: bucket b counts leaps that
# skipped [2^b, 2^(b+1)) ticks; 32 buckets cover any int32 tick count, so
# no leap is ever folded into the top bucket
LEAP_BUCKETS = 32


@struct.dataclass
class LeapStats:
    """Event-compression accounting for ``Engine.run_compressed``: how many
    ticks the leap driver actually executed (vs the dense driver's one tick
    per tick_ms of virtual time) and a log2 histogram of leap lengths. The
    values are replicated across shards — every shard executes the same
    ticks and takes the same leaps (the leap distance is an ``ex.allmin``)."""

    ticks_executed: jax.Array  # [] i32
    leaps: jax.Array  # [LEAP_BUCKETS] i32


def leap_stats_init() -> LeapStats:
    return LeapStats(ticks_executed=jnp.int32(0),
                     leaps=jnp.zeros((LEAP_BUCKETS,), jnp.int32))


def utilization(s: SimState) -> tuple[jax.Array, jax.Array]:
    """(core_util, mem_util) per cluster — GetResourceUtilization
    (cluster.go:46-63): used/total over active nodes."""
    used = jnp.sum(jnp.where(s.node_active[..., None], s.node_cap - s.node_free, 0), axis=-2)
    total = jnp.sum(jnp.where(s.node_active[..., None], s.node_cap, 0), axis=-2)
    util = used.astype(jnp.float32) / jnp.maximum(total, 1).astype(jnp.float32)
    return util[..., 0], util[..., 1]


def snapshot_utilization(s: SimState) -> tuple[jax.Array, jax.Array]:
    """Utilization as the streamed ClusterState computes it
    (GetResourceUtilization, cluster.go:46-63): usage summed over *all*
    nodes (virtual included) divided by the cached *physical* totals
    (SetTotalResources runs only at init) — so it can exceed 1.0 once
    virtual nodes carry load."""
    used = jnp.sum(s.node_cap - s.node_free, axis=-2)  # inactive slots are 0-0
    cu = used[..., CORES].astype(jnp.float32) / jnp.maximum(
        s.trader.snap_total_cores, 1).astype(jnp.float32)
    mu = used[..., MEM].astype(jnp.float32) / jnp.maximum(
        s.trader.snap_total_mem, 1).astype(jnp.float32)
    return cu, mu


def init_state(cfg: SimConfig, specs: Sequence[ClusterSpec],
               plan=None, fault_events=None) -> SimState:
    """Build the initial batched state from cluster specs.

    ``plan`` is an optional ``core.compact.CompactPlan``: when given, the
    six job queues and the running set are built in the compact SoA layout
    with the plan's range-audited storage dtypes (bit-identical results;
    ARCHITECTURE.md §state layout). ``None`` keeps the wide int32 AoS
    layout.

    ``fault_events`` is the trace-mode fault schedule — a list of
    ``(cluster, node, fail_t_ms, repair_t_ms)`` tuples packed into the
    per-node interval tables (faults/schedule.py); required iff
    ``cfg.faults`` enables trace mode, ignored otherwise."""
    C = len(specs)
    N = cfg.total_nodes
    cap_phys = capacities_array(specs, cfg.max_nodes)  # [C, max_nodes, RES]
    if cfg.n_res < RES and cap_phys[..., cfg.n_res:].any():
        raise ValueError(
            f"specs declare gpu capacity but n_res={cfg.n_res} drops the axis")
    node_dt = np.int32 if plan is None else plan.node_dtype()
    phys = cap_phys[..., : cfg.n_res]
    if phys.size and int(phys.max()) > np.iinfo(node_dt).max:
        raise ValueError(
            f"compact plan's node dtype {np.dtype(node_dt).name} cannot hold "
            f"capacity {int(phys.max())} — derive the plan from these specs")
    cap = np.zeros((C, N, cfg.n_res), dtype=node_dt)
    cap[:, : cfg.max_nodes] = phys
    active = (cap.sum(-1) > 0)
    # device types: physical slots from the specs, virtual slots standard
    # (a borrowed virtual node carries generic capacity)
    ntype = np.zeros((C, N), dtype=np.int32)
    ntype[:, : cfg.max_nodes] = node_types_array(specs, cfg.max_nodes)

    def batch(tree):
        return jax.tree.map(lambda a: jnp.broadcast_to(a, (C,) + a.shape),
                            tree)

    def batched_queue():
        q = (Q.empty(cfg.queue_capacity) if plan is None
             else Q.empty_soa(cfg.queue_capacity, plan.queue_dtypes()))
        return batch(q)

    zf = jnp.zeros((C,), jnp.float32)
    zi = jnp.zeros((C,), jnp.int32)
    # trace buffers are only materialized when recording (at 4k clusters a
    # full-size buffer would be GBs of HBM)
    E = cfg.max_trace_events if cfg.record_trace else 1
    never = jnp.full((C, N), R.NEVER, jnp.int32)
    return SimState(
        t=jnp.int32(0),
        node_cap=jnp.asarray(cap),
        node_free=jnp.asarray(cap.copy()),
        node_active=jnp.asarray(active),
        node_expire=never,
        node_type=jnp.asarray(ntype),
        l0=batched_queue(),
        l1=batched_queue(),
        ready=batched_queue(),
        wait=batched_queue(),
        lent=batched_queue(),
        borrowed=batched_queue(),
        run=batch(R.empty(cfg.max_running) if plan is None
                  else R.empty_soa(cfg.max_running, plan.run_dtypes())),
        arr_ptr=zi,
        wait_total=zf,
        wait_jobs=zi,
        jobs_in_queue=zi,
        placed_total=zi,
        drops=Drops(queue=zi, msgs=zi, run_full=zi, vslot=zi, carve=zi,
                    ingest=zi, failed=zi),
        trader=TraderState(
            snap_core_util=zf,
            snap_mem_util=zf,
            snap_avg_wait=zf,
            snap_total_cores=jnp.asarray(cap[:, :, CORES].sum(1), jnp.int32),
            snap_total_mem=jnp.asarray(cap[:, :, MEM].sum(1), jnp.int32),
            cooldown_until=zi,
            seller_locked_until=zi,
            next_contract_id=jnp.ones((C,), jnp.int32),
            spent=zf,
            mkt_price=zf,
        ),
        trace=Trace(
            t=jnp.zeros((C, E), jnp.int32),
            job=jnp.full((C, E), -1, jnp.int32),
            node=jnp.full((C, E), -1, jnp.int32),
            src=jnp.full((C, E), -1, jnp.int32),
            n=zi,
        ),
        # generative churn is scoped to the machines that exist: the
        # initially-active slots (phantom padding and vacant virtual
        # slots cannot fail — trace schedules may still name any slot)
        faults=init_fault_state(cfg.faults, C, N, events=fault_events,
                                eligible=active),
    )
