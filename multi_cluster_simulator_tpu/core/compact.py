"""Range-audited narrow storage for the bandwidth-bound SimState leaves.

The headline tick is memory-bound (tools/cost_probe.json: ~0.1 flops/byte),
so after the streamed input pipeline (PR 3) and event-compressed time (PR 4)
the next multiplier is shrinking the bytes each EXECUTED tick must touch.
Two moves, both bit-identical to the wide layout (ARCHITECTURE.md §state
layout):

1. **SoA splits** — ``JobQueue.data[Q, NF]`` and ``RunningSet.data[S, RF]``
   become per-field leaves (ops/queues.SoAJobQueue, ops/runset.SoARunningSet)
   so XLA streams only the fields a phase actually reads: a read of
   ``enq_t`` no longer pays for the other seven columns of an AoS row.

2. **Range-audited storage dtypes** — this module derives per-field storage
   widths from ``SimConfig`` + the stream's measured maxima
   (``derive_plan``): i8/i16 where the config provably bounds the range
   (resource demands, node indices, owner cluster indices), i32 kept for
   ids/timestamps/durations that can exceed 2^15 (ops/fields.NARROWABLE).

All ARITHMETIC stays int32: leaves are widened on load
(``fields.widen``) and narrowed on store through the checked helper
(``fields.narrow_store``), which clamps + counts out-of-range values into
the layout's ``ovf`` counter instead of silently wrapping — the same
surface-don't-swallow contract as ``Drops`` (core/state.py). Parity and
bench runs assert the counter stays zero (utils/trace.total_drops reports
it as ``narrow``), so storage width is invisible to replay (PARITY.md).

The plan is STATIC (a frozen, hashable dataclass of dtype names): it is
fixed at ``init_state`` from the audit, baked into the pytree's leaf
dtypes, and never consulted at trace time.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.core.spec import ClusterSpec, capacities_array
from multi_cluster_simulator_tpu.ops import fields as F

# the public home of the store primitives (defined in ops/fields.py to keep
# the ops -> core import chain acyclic)
narrow_store = F.narrow_store
widen = F.widen

_CANDIDATES = (np.dtype(np.int8), np.dtype(np.int16), np.dtype(np.int32))


def fit_dtype(lo: int, hi: int) -> str:
    """Smallest signed integer dtype whose range covers [lo, hi]."""
    for dt in _CANDIDATES:
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return dt.name
    raise ValueError(f"range [{lo}, {hi}] exceeds int32")


@dataclasses.dataclass(frozen=True)
class CompactPlan:
    """Per-field storage dtypes for the SoA layouts — (field, dtype-name)
    pairs per row kind, hashable so a plan can ride config closures and
    function caches. ``None``-plan call sites keep the wide AoS layout."""

    queue: tuple  # (("id", "int32"), ("cores", "int8"), ...)
    run: tuple
    # node_cap/node_free storage dtype: one dtype for the whole resource
    # axis (it mixes cores/mem/gpu, so the widest bound — mem — decides;
    # under the trader it must also hold buyer virtual-node CONTRACT
    # totals — see derive_plan). The tick widens these once at entry and
    # CHECKED-narrows once at exit (Engine._tick, count into run.ovf): a
    # derivation gap here must surface as a counted overflow, never a
    # wrapped capacity — tests/test_compact.py
    # test_node_exit_narrow_counts_instead_of_wrapping pins it.
    node: str = "int32"

    def queue_dtypes(self) -> dict:
        return {name: np.dtype(dt) for name, dt in self.queue}

    def run_dtypes(self) -> dict:
        return {name: np.dtype(dt) for name, dt in self.run}

    def node_dtype(self) -> np.dtype:
        return np.dtype(self.node)

    def describe(self) -> dict:
        """Detail-dict / docs form: only the fields narrower than i32."""
        out = {
            "queue": {n: dt for n, dt in self.queue if dt != "int32"},
            "run": {n: dt for n, dt in self.run if dt != "int32"},
        }
        if self.node != "int32":
            out["node"] = self.node
        return out


def audit_arrivals(arrivals) -> dict:
    """Measured per-field maxima over the valid prefix of an ``Arrivals``
    stream — the data half of the range audit (the config half is node
    capacities + cluster/node counts). Host-side numpy, once per run."""
    n = np.asarray(arrivals.n)
    valid = np.arange(np.asarray(arrivals.t).shape[1])[None, :] < n[:, None]

    def mx(a):
        a = np.asarray(a)
        return int(a[valid].max(initial=0))

    return {"cores": mx(arrivals.cores), "mem": mx(arrivals.mem),
            "gpu": mx(arrivals.gpu), "id": mx(arrivals.id)}


def derive_plan(cfg: SimConfig, specs: Sequence[ClusterSpec],
                arrivals=None) -> CompactPlan:
    """Derive storage widths from the config + (optionally) the stream.

    Bounds are conservative over everything the engine can ever store in a
    row, not just what a phase is expected to write:

    - ``cores``/``mem``/``gpu``: max of the stream's demands and the node
      capacities — queue rows hold job demands; running-set rows also hold
      carved virtual-node placeholders whose amounts are bounded by a
      node's capacity (market/trader.py seller_apply).
    - ``owner``: [-2, C-1] — a borrower's global cluster index, OWN (-1),
      or FOREIGN (-2).
    - ``node``: [-1, total_nodes-1] — a placement target or NO_NODE.
    - ``id``: [-3, measured stream max] — PLACEHOLDER_ID (-3), INVALID (-1),
      or a stream id; only narrowed when a stream audit is available
      (nothing in the config bounds ids).

    Without ``arrivals`` the demand bound falls back to node capacities
    alone (a demand can exceed capacity and still legally sit in a queue
    forever); the checked-store counter remains the backstop either way —
    an out-of-range value is counted and clamped, never wrapped.
    """
    caps = capacities_array(specs, cfg.max_nodes)[..., : cfg.n_res]
    cap_max = [int(caps[..., r].max(initial=0)) for r in range(cfg.n_res)]
    while len(cap_max) < 3:
        cap_max.append(0)
    demand_hi = dict(zip(("cores", "mem", "gpu"), cap_max))
    id_hi = np.iinfo(F.WIDE_DTYPE).max  # unbounded without a stream audit
    if arrivals is not None:
        audited = audit_arrivals(arrivals)
        for k in ("cores", "mem", "gpu"):
            demand_hi[k] = max(demand_hi[k], audited[k])
        id_hi = audited["id"]
    bounds = {
        "id": (-3, id_hi),
        "cores": (0, demand_hi["cores"]),
        "mem": (0, demand_hi["mem"]),
        "gpu": (0, demand_hi["gpu"]),
        "owner": (-2, max(len(specs) - 1, 0)),
        "node": (-1, cfg.total_nodes - 1),
        # schema-bounded, not stream-bounded: job_class maps any demand
        # into [0, N_JOB_CLASSES) by construction (ops/fields.py)
        "jclass": (0, F.N_JOB_CLASSES - 1),
        # config-bounded: the fault phase only requeues while
        # retries < max_retries, so a stored value never exceeds the
        # budget (a kill at the budget drops into drops.failed instead)
        "retries": (0, max(int(cfg.faults.max_retries), 1)),
    }

    def row_plan(names):
        out = []
        for name in names:
            if name in F.NARROWABLE:
                lo, hi = bounds[name]
                out.append((name, fit_dtype(lo, hi)))
            else:
                out.append((name, F.WIDE_DTYPE.name))
        return tuple(out)

    # Node tensors hold capacities and free amounts. Without the trader,
    # both are bounded by the largest per-node physical capacity. WITH the
    # trader, a buyer's virtual node echoes the CONTRACT's totals
    # (market/trader.py buyer_apply; trader_server.go:58), and a contract
    # is sized as a cumsum over the Level1 backlog (ops/sizing.py) — up to
    # queue_capacity jobs of audited demand, which can dwarf any single
    # physical node. The seller side stays per-node bounded (carve amounts
    # never exceed a node's free), but the buyer tensor must hold the
    # total, so the bound scales with the backlog.
    node_hi = max(cap_max) if cap_max else 0
    if cfg.trader.enabled:
        node_hi = max(node_hi,
                      cfg.queue_capacity * max(demand_hi.values()))
    return CompactPlan(queue=row_plan(F.QUEUE_FIELDS),
                       run=row_plan(F.RUN_FIELDS),
                       node=fit_dtype(0, min(node_hi, 2**31 - 1)))


def wide_plan() -> CompactPlan:
    """An all-int32 plan: the SoA layout without any narrowing — used by
    tests to separate the layout move from the dtype move."""
    i32 = F.WIDE_DTYPE.name
    return CompactPlan(queue=tuple((n, i32) for n in F.QUEUE_FIELDS),
                       run=tuple((n, i32) for n in F.RUN_FIELDS))


# --------------------------------------------------------------------------
# canonicalization + accounting helpers
# --------------------------------------------------------------------------


def to_wide(state):
    """Convert a compact SimState back to the wide AoS layout (host-side or
    traced) — the canonical form for compact-vs-wide bit-equality checks
    and for checkpoints that must round-trip across layouts. Overflow
    counters are dropped (assert them zero separately: they have no wide
    ancestor). A wide state passes through unchanged."""
    from multi_cluster_simulator_tpu.ops import queues as Q
    from multi_cluster_simulator_tpu.ops import runset as R

    import jax.numpy as jnp

    kw = {}
    for qn in ("l0", "l1", "ready", "wait", "lent", "borrowed"):
        q = getattr(state, qn)
        if not isinstance(q, Q.JobQueue):
            kw[qn] = Q.soa_to_wide(q)
    if not isinstance(state.run, R.RunningSet):
        kw["run"] = R.soa_to_wide(state.run)
    if state.node_free.dtype != jnp.int32:
        kw["node_free"] = F.widen(state.node_free)
        kw["node_cap"] = F.widen(state.node_cap)
    return state.replace(**kw) if kw else state


def overflow_total(state) -> int:
    """Host-side sum of every narrow-store overflow counter in a SimState
    (0 for wide states) — the ``narrow`` entry of utils/trace.total_drops."""
    total = 0
    for qn in ("l0", "l1", "ready", "wait", "lent", "borrowed", "run"):
        ovf = getattr(getattr(state, qn), "ovf", None)
        if ovf is not None:
            total += int(np.asarray(ovf).sum())
    return total


def state_nbytes(state) -> int:
    """Total byte footprint of a SimState's leaves — the ``state_bytes``
    bench detail: the honest, backend-independent half of the bytes win
    (``tick_bytes_accessed`` is the compiler-measured half)."""
    import jax

    return int(sum(np.asarray(leaf).nbytes if not hasattr(leaf, "nbytes")
                   else leaf.nbytes for leaf in jax.tree.leaves(state)))
