"""Checkpoint / resume for simulation state.

The reference has no checkpointing at all — every queue, node counter, and
contract lives in process memory and a restart loses the world (SURVEY.md
§5: "Checkpoint / resume — absent"). Here the entire constellation is one
``SimState`` pytree (core/state.py), so a checkpoint is a single
serialization call and resume is bit-exact: the virtual clock, every queue
tensor, the running set, the arrival cursors (``arr_ptr``), drop counters,
and trader snapshots all round-trip. Long Borg-trace replays (bench.py
--checkpoint/--resume) can be killed at any jitted-chunk boundary and
continued to a final state identical to an uninterrupted run
(tests/test_checkpoint.py).

Format: flax msgpack (``flax.serialization.to_bytes``) with a small JSON
header carrying a magic/version tag. Loading requires a template state
built from the same ``SimConfig``/specs (static shapes are config-derived,
not stored).
"""

from __future__ import annotations

import json
import os
import struct as _struct
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from multi_cluster_simulator_tpu.core.state import SimState

_MAGIC = b"MCSCKPT1"


def save_state(state: SimState, path: str, extra: Optional[dict] = None) -> None:
    """Write a checkpoint. Atomic: written to ``path + '.tmp'`` then
    renamed, so a kill mid-write never corrupts an existing checkpoint.

    ``extra`` is an arbitrary JSON-able dict stored in the header — hosts
    use it for state the tensors can't carry (borrower URL table, pending
    jobs); keeping it in the same file keeps the pair atomic."""
    state = jax.tree.map(np.asarray, state)  # device -> host once
    payload = serialization.to_bytes(state)
    header = json.dumps({"t": int(state.t), "extra": extra or {}}).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(_struct.pack("<I", len(header)))
        f.write(header)
        f.write(payload)
    os.replace(tmp, path)


def load_state(path: str, template: SimState) -> SimState:
    """Restore a checkpoint into the shapes of ``template`` (normally
    ``init_state(cfg, specs)`` for the same config). Shape/dtype mismatches
    raise — a checkpoint is only valid for the config that produced it."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a simulator checkpoint")
        (hlen,) = _struct.unpack("<I", f.read(4))
        f.read(hlen)  # header is advisory (peek_checkpoint_t)
        payload = f.read()
    restored = serialization.from_bytes(template, payload)
    for a, b in zip(jax.tree.leaves(template), jax.tree.leaves(restored)):
        if np.shape(a) != np.shape(b) or np.asarray(a).dtype != np.asarray(b).dtype:
            raise ValueError(
                f"checkpoint leaf mismatch: {np.shape(b)}/{np.asarray(b).dtype}"
                f" vs {np.shape(a)}/{np.asarray(a).dtype} "
                "— was it written under a different SimConfig?")
    return jax.tree.map(jnp.asarray, restored)


def _read_header(path: str) -> dict:
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not a simulator checkpoint")
        (hlen,) = _struct.unpack("<I", f.read(4))
        return json.loads(f.read(hlen))


def peek_checkpoint_t(path: str) -> int:
    """The checkpoint's virtual time (ms) without deserializing the state —
    lets a driver compute how many ticks remain before paying the load."""
    return int(_read_header(path)["t"])


def load_extra(path: str) -> dict:
    """The host-side ``extra`` dict stored alongside the state (empty for
    checkpoints written without one)."""
    return _read_header(path).get("extra") or {}
