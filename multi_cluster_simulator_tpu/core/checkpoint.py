"""Checkpoint / resume for simulation state.

The reference has no checkpointing at all — every queue, node counter, and
contract lives in process memory and a restart loses the world (SURVEY.md
§5: "Checkpoint / resume — absent"). Here the entire constellation is one
``SimState`` pytree (core/state.py), so a checkpoint is a single
serialization call and resume is bit-exact: the virtual clock, every queue
tensor, the running set, the arrival cursors (``arr_ptr``), drop counters,
fault-plane churn clocks, and trader snapshots all round-trip. Long
Borg-trace replays (bench.py --checkpoint/--resume) can be killed at any
jitted-chunk boundary and continued to a final state identical to an
uninterrupted run (tests/test_checkpoint.py; tools/chaos.py --batch is the
standing kill -9 proof).

Format (version 2): flax msgpack (``flax.serialization.to_bytes``) behind a
JSON header that is LOAD-BEARING, not advisory. Besides the virtual clock
and the caller's ``extra`` dict, the header embeds the format version and —
when the writer supplies them — the full ``SimConfig`` description, the
compact storage plan, and the policy-params digest. ``load_state`` rejects
a version or digest mismatch with a message NAMING the differing field:
leaf shapes/dtypes alone cannot tell an undersized stale compact plan from
the right one (both produce i16 leaves; only the audited bounds differ),
and a wrong-config resume must fail fast instead of silently corrupting a
multi-hour run. Loading requires a template state built from the same
``SimConfig``/specs (static shapes are config-derived, not stored).

The run-level bundle that wraps a state together with the obs
``MetricsBuffer`` carry and the driver's resume cursors lives in
core/preempt.py (``RunCheckpoint``) and rides the same format through
``save_tree``/``load_tree``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct as _struct
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from multi_cluster_simulator_tpu.core.state import SimState

_MAGIC = b"MCSCKPT1"
# bumped whenever the header contract changes; v1 (the pre-digest format
# whose header was advisory) is refused — a stale checkpoint must be
# re-created, never trusted on shapes alone
FORMAT_VERSION = 2

# distinguishes "caller did not supply a plan to check" from "caller
# asserts the wide layout (plan None)" — the two must not be conflated:
# resuming a compact run into a wide template is exactly the class of
# mismatch the digest exists to catch
_UNSET = object()


# --------------------------------------------------------------------------
# digests: canonical descriptions of what a checkpoint is only valid for
# --------------------------------------------------------------------------


def _canon_json(obj) -> str:
    """Canonical JSON for digesting/diffing: dataclasses and str-enums
    serialize naturally (every config enum is a str subclass), keys sort."""
    return json.dumps(obj, sort_keys=True)


# Execution-STRATEGY fields whose value cannot change results (the fused
# tick kernel is pinned bit-identical to the unfused tick — PARITY.md
# §fused kernel): excluded from the header description so a run may be
# checkpointed unfused and resumed fused (or across backends, where the
# interpret default flips) without tripping the config-digest check.
_STRATEGY_FIELDS = ("fused", "fused_block", "fused_interpret")


def config_describe(cfg) -> dict:
    """The full nested ``SimConfig`` as plain JSON-able data — stored in
    the header so a mismatch can name the differing FIELD, not just fail
    a hash compare. Pure execution-strategy fields (``_STRATEGY_FIELDS``)
    are dropped: they select HOW the same results are computed."""
    d = dataclasses.asdict(cfg)
    for f in _STRATEGY_FIELDS:
        d.pop(f, None)
    return d


def digest_of(obj) -> str:
    """sha1[:12] of the canonical JSON form — THE digest recipe every
    validity record in this repo uses (config, compact plan, the
    tournament's grid digest), so conventions cannot drift apart."""
    return hashlib.sha1(_canon_json(obj).encode()).hexdigest()[:12]


def config_digest(cfg) -> str:
    return digest_of(config_describe(cfg))


def plan_describe(plan) -> Optional[dict]:
    """The compact storage plan (core/compact.CompactPlan) as JSON-able
    data; ``None`` is the wide layout and is itself a checkable value."""
    if plan is None:
        return None
    return {"queue": list(map(list, plan.queue)),
            "run": list(map(list, plan.run)), "node": plan.node}


def plan_digest(plan) -> str:
    return digest_of(plan_describe(plan))


def _dict_diff(want: dict, got: dict, prefix="") -> list:
    """Dotted paths where two nested config/plan descriptions differ —
    the 'message naming the differing field' half of header hardening."""
    out = []
    for k in sorted(set(want) | set(got)):
        w, g = want.get(k, "<absent>"), got.get(k, "<absent>")
        if isinstance(w, dict) and isinstance(g, dict):
            out.extend(_dict_diff(w, g, prefix=f"{prefix}{k}."))
        elif w != g:
            out.append(f"{prefix}{k} (checkpoint: {g!r}, expected: {w!r})")
    return out


def _check_header(header: dict, path: str, cfg=None, plan=_UNSET,
                  policy_digest: Optional[str] = None) -> None:
    v = header.get("v", 1)
    if v != FORMAT_VERSION:
        raise ValueError(
            f"{path}: checkpoint format v{v}; this build reads "
            f"v{FORMAT_VERSION} — re-create the checkpoint")
    if cfg is not None:
        if "config" not in header:
            raise ValueError(
                f"{path}: checkpoint carries no SimConfig record; cannot "
                "verify it matches the resuming config — re-create it with "
                "save_state(..., cfg=...)")
        # JSON round-trip the expected side too: the header came through
        # JSON (tuples are lists there), so both sides must compare in
        # the same canonical form
        want = json.loads(_canon_json(config_describe(cfg)))
        diffs = _dict_diff(want, header["config"])
        if diffs:
            raise ValueError(
                f"{path}: checkpoint was written under a different "
                f"SimConfig — differing field(s): " + "; ".join(diffs[:8]))
    if plan is not _UNSET:
        if "plan" not in header:
            raise ValueError(
                f"{path}: checkpoint carries no compact-plan record; "
                "cannot verify the storage layout — re-create it with "
                "save_state(..., plan=...)")
        want, got = plan_describe(plan), header["plan"]
        if want != got:
            if (want is None) != (got is None):
                detail = (f"checkpoint layout: "
                          f"{'wide' if got is None else 'compact'}, "
                          f"expected: {'wide' if want is None else 'compact'}")
            else:
                diffs = _dict_diff(want, got)
                detail = "differing field(s): " + "; ".join(diffs[:8])
            raise ValueError(
                f"{path}: checkpoint was written under a different compact "
                f"storage plan — {detail}")
    if policy_digest is not None:
        got = header.get("policy_digest")
        if got != policy_digest:
            raise ValueError(
                f"{path}: checkpoint was written under different policy "
                f"params (digest {got!r}, expected {policy_digest!r})")


# --------------------------------------------------------------------------
# low-level framed I/O (shared by state checkpoints and run bundles)
# --------------------------------------------------------------------------


def _write(path: str, header: dict, payload: bytes) -> None:
    """Atomic framed write: magic, header length, JSON header, msgpack
    payload — to ``path + '.tmp'`` then ``os.replace``, so a kill at ANY
    byte of the write never corrupts an existing checkpoint (the torn-write
    contract tests/test_checkpoint.py pins)."""
    hdr = json.dumps(header).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(_struct.pack("<I", len(hdr)))
        f.write(hdr)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read(path: str) -> tuple[dict, bytes]:
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a simulator checkpoint")
        (hlen,) = _struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hlen))
        payload = f.read()
    return header, payload


def save_tree(tree, path: str, t: int, extra: Optional[dict] = None,
              cfg=None, plan=_UNSET,
              policy_digest: Optional[str] = None) -> None:
    """Write an arbitrary pytree checkpoint (the generic core behind
    ``save_state`` and the run bundles). ``t`` is the virtual clock stored
    for ``peek_checkpoint_t``; ``cfg``/``plan``/``policy_digest`` embed the
    validity record the loader verifies."""
    tree = jax.tree.map(np.asarray, tree)  # device -> host once
    header = {"v": FORMAT_VERSION, "t": int(t), "extra": extra or {}}
    if cfg is not None:
        header["config"] = config_describe(cfg)
        header["config_digest"] = config_digest(cfg)
    if plan is not _UNSET:
        header["plan"] = plan_describe(plan)
        header["plan_digest"] = plan_digest(plan)
    if policy_digest is not None:
        header["policy_digest"] = policy_digest
    _write(path, header, serialization.to_bytes(tree))


def load_tree(path: str, template, cfg=None, plan=_UNSET,
              policy_digest: Optional[str] = None):
    """Restore a pytree checkpoint into the shapes of ``template``. The
    header is verified FIRST (version, then config/plan/policy when the
    caller supplies them — a named-field mismatch beats a shape error),
    then every leaf's shape/dtype is checked against the template."""
    header, payload = _read(path)
    _check_header(header, path, cfg=cfg, plan=plan,
                  policy_digest=policy_digest)
    restored = serialization.from_bytes(template, payload)
    for a, b in zip(jax.tree.leaves(template), jax.tree.leaves(restored)):
        if np.shape(a) != np.shape(b) or np.asarray(a).dtype != np.asarray(b).dtype:
            raise ValueError(
                f"checkpoint leaf mismatch: {np.shape(b)}/{np.asarray(b).dtype}"
                f" vs {np.shape(a)}/{np.asarray(a).dtype} "
                "— was it written under a different SimConfig?")
    return jax.tree.map(jnp.asarray, restored)


# --------------------------------------------------------------------------
# the classic SimState checkpoint surface
# --------------------------------------------------------------------------


def save_state(state: SimState, path: str, extra: Optional[dict] = None,
               cfg=None, plan=_UNSET,
               policy_digest: Optional[str] = None) -> None:
    """Write a SimState checkpoint. Atomic (tmp + rename — see ``_write``).

    ``extra`` is an arbitrary JSON-able dict stored in the header — hosts
    use it for state the tensors can't carry (borrower URL table, pending
    jobs); keeping it in the same file keeps the pair atomic.
    ``cfg``/``plan``/``policy_digest`` embed the validity record
    ``load_state`` verifies (pass them wherever they are known — the
    serving tier and the batch drivers both do)."""
    save_tree(state, path, t=int(np.asarray(state.t)), extra=extra, cfg=cfg,
              plan=plan, policy_digest=policy_digest)


def load_state(path: str, template: SimState, cfg=None, plan=_UNSET,
               policy_digest: Optional[str] = None) -> SimState:
    """Restore a checkpoint into the shapes of ``template`` (normally
    ``init_state(cfg, specs)`` for the same config). Version, digest, and
    shape/dtype mismatches all raise — a checkpoint is only valid for the
    config (and storage plan, and policy params) that produced it."""
    return load_tree(path, template, cfg=cfg, plan=plan,
                     policy_digest=policy_digest)


def _read_header(path: str) -> dict:
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not a simulator checkpoint")
        (hlen,) = _struct.unpack("<I", f.read(4))
        return json.loads(f.read(hlen))


def peek_checkpoint_t(path: str) -> int:
    """The checkpoint's virtual time (ms) without deserializing the state —
    lets a driver compute how many ticks remain before paying the load
    (tools/chaos.py --batch also uses it to watch a child's progress)."""
    return int(_read_header(path)["t"])


def load_extra(path: str) -> dict:
    """The host-side ``extra`` dict stored alongside the state (empty for
    checkpoints written without one)."""
    return _read_header(path).get("extra") or {}
