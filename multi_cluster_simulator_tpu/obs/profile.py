"""The profile plane: jax.profiler-native phase + dispatch annotation.

Two primitives, both free when no profiler session is active:

- ``phase_scope(name)`` — a ``jax.named_scope`` over one of the 7 tick
  phases (``TICK_PHASES``). Named scopes attach op metadata at TRACE time
  (zero runtime cost, no numerics impact — the bit-identity matrix pins
  that), so every HLO op in a captured trace carries its phase and a
  per-phase cost breakdown falls out of any ``jax.profiler`` capture.
- ``annotate_dispatch(name)`` — a ``jax.profiler.TraceAnnotation`` for the
  HOST side of a dispatch site (the bench chunk loop, the serving drive
  thread, the tournament grid call, the env step loop): the wall-time
  spans a trace viewer aligns the device stream against.

``tools/profile_capture.py`` drives both: it wraps a bench-shaped run in
``start_trace``/``stop_trace`` and emits the per-phase cost table from the
engine's phase-prefix ablation (``Engine.run_prefix``) — superseding the
old hand-copied ``tools/phase_probe.py``.
"""

from __future__ import annotations

import contextlib

import jax

# The documented determinization of the reference's concurrent goroutines
# (core/engine.py module docstring; PARITY.md §phase order). Order matters:
# phase k of the ablation driver (Engine.run_prefix) runs phases [1..k].
TICK_PHASES = (
    "faults",    # 1. node failures kill/requeue, repairs restore (faults/)
    "release",   # 2. completions + finished-foreign returns
    "expire",    # 3. virtual-node expiry (sane mode only)
    "ingest",    # 4. arrivals -> Level0 / ReadyQueue
    "schedule",  # 5. the policy zoo's scheduling pass
    "borrow",    # 6. cross-cluster borrow matching
    "snapshot",  # 7. trader state snapshot
    "trade",     # 8. trader market round
)


def phase_scope(name: str):
    """Named scope for one tick phase — ops lowered inside it carry
    ``tick.<name>`` in their metadata (visible in any profiler capture and
    in HLO dumps). Pure trace-time metadata: no runtime cost, no effect on
    the compiled program's numerics."""
    return jax.named_scope(f"tick.{name}")


def annotate_dispatch(name: str, **kwargs):
    """Host-side TraceAnnotation around a dispatch site (shows up as a
    named span on the host thread's profiler track). A no-op context when
    no profiler session is active; falls back to a nullcontext where the
    profiler is unavailable entirely (minimal jaxlib builds)."""
    try:
        return jax.profiler.TraceAnnotation(f"mcs.dispatch.{name}", **kwargs)
    except Exception:  # pragma: no cover - profiler-less jaxlib
        return contextlib.nullcontext()


def start_trace(logdir: str) -> None:
    """Start a jax profiler capture into ``logdir`` (TensorBoard layout:
    ``plugins/profile/<ts>/*.xplane.pb`` + ``.trace.json.gz``)."""
    jax.profiler.start_trace(logdir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


def trace_artifacts(logdir: str) -> list[str]:
    """The capture files a finished trace session left under ``logdir``
    (what tools/profile_capture.py --quick asserts non-empty)."""
    import os

    out = []
    for root, _dirs, files in os.walk(logdir):
        out.extend(os.path.join(root, f) for f in files
                   if f.endswith((".xplane.pb", ".trace.json.gz")))
    return sorted(out)
