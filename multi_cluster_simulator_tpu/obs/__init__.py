"""obs/ — the zero-sync observability subsystem (ARCHITECTURE.md
§observability).

Three planes, all gated on the project's standing invariant — bitwise
invisibility to replay:

- **device metrics plane** (``obs/device.py``): an optional
  ``MetricsBuffer`` pytree threaded through the scan carry — per-tick
  counters read off ``SimState``, accumulated into fixed-shape on-device
  rings and histogram buckets, harvested once per chunk at the existing
  chunk boundary (one transfer per chunk, never per tick);
- **profile plane** (``obs/profile.py``): ``jax.profiler``-native phase
  annotation — named scopes on the tick phases and TraceAnnotations
  around every dispatch site — plus ``tools/profile_capture.py``;
- **serving surface**: a Prometheus-text ``/metrics`` endpoint and
  ``/healthz`` on the service hosts (services/lifecycle.py,
  services/serving.py), backed by the harvested device rows bridged into
  the existing OTLP ``Meter``; ``obs/promtext.py`` parses the exposition
  back (tests + the CI scrape gate).
"""

from multi_cluster_simulator_tpu.obs.device import (  # noqa: F401
    OBS_DEPTH_BUCKETS, OBS_RING, PC_LEAVES, MetricsBuffer, TapCursor,
    cursor_of, harvest, metrics_init, queue_depth, reduce_metrics,
    tap_leap, tap_pc, tap_tick, tap_tick_global, tap_tick_local,
)
from multi_cluster_simulator_tpu.obs.profile import (  # noqa: F401
    TICK_PHASES, annotate_dispatch, phase_scope,
)
