"""Prometheus exposition-format parser — the scrape side of the serving
surface.

``telemetry.Meter.render_prometheus`` writes the text format; this module
reads it back, strictly enough to catch a malformed rendering (the CI
scrape gate and tests/test_obs.py both parse a real ``/metrics`` response
through it and then compare values against the Meter's OTLP export, so the
two surfaces can never silently diverge). Pure stdlib.
"""

from __future__ import annotations

import re

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$")
_LABEL_RE = re.compile(r'^\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*$')


class PromParseError(ValueError):
    pass


def parse_prometheus(text: str) -> dict:
    """Parse an exposition payload into
    ``{metric_name: {(sorted label items) or (): float value}}``.

    Raises ``PromParseError`` on any line that is neither a comment, a
    blank, nor a well-formed sample — a scrape "parses" only if every line
    does. ``# TYPE``/``# HELP`` lines must name a metric."""
    out: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3:
                    raise PromParseError(f"line {lineno}: bare # {parts[1]}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise PromParseError(f"line {lineno}: not a sample: {line!r}")
        labels = ()
        if m.group("labels"):
            items = []
            for part in m.group("labels").split(","):
                lm = _LABEL_RE.match(part)
                if lm is None:
                    raise PromParseError(
                        f"line {lineno}: bad label pair {part!r}")
                items.append((lm.group(1), lm.group(2)))
            labels = tuple(sorted(items))
        try:
            value = float(m.group("value").replace("+Inf", "inf"))
        except ValueError as e:
            raise PromParseError(
                f"line {lineno}: bad value {m.group('value')!r}") from e
        out.setdefault(m.group("name"), {})[labels] = value
    return out


def scalar_samples(parsed: dict) -> dict:
    """Flatten the label-free samples to ``{name: value}`` (the gauge /
    counter surface the consistency checks compare against OTLP)."""
    return {name: series[()] for name, series in parsed.items()
            if () in series}
