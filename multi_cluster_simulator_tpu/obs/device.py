"""The device metrics plane: per-tick telemetry without host syncs.

The host-side Tracer/Meter (services/telemetry.py) can only see what
crosses the host boundary — inside a dispatch the engine is a black box.
This module is the on-device half: a ``MetricsBuffer`` pytree rides the
scan carry next to ``SimState``, a tap after every executed tick READS the
state and accumulates deltas, depths, and histograms into fixed-shape
buffers, and the whole buffer is harvested ONCE per chunk at the chunk
boundary the drivers already cross — one transfer per chunk, never per
tick (Blox, arxiv 2312.12621: schedulers live or die by their
instrumentation surface; this one must not perturb the perf ladder it
observes).

Invariants, each load-bearing:

- **Write-only-to-itself.** Taps read ``SimState`` leaves and write only
  the buffer — never a state leaf (simlint rule family 9 ``obs-tap``
  enforces it statically; ``bench.py --obs ab`` and tests/test_obs.py
  prove obs-on == obs-off bit-identical on the final state).
- **Exact under time compression.** A quiescent leap applies the skipped
  ticks' samples in closed form (``tap_leap``) — per-tick deltas are zero
  at a fixed point, per-tick levels replicate it, and the wait accrual
  telescopes exactly as ``Engine._leap_local`` proves for the state — so
  the compressed run's harvested buffer equals the dense run's bit for
  bit. The one f32 leaf, ``wait_accrued``, shares the state's own
  bit-parity domain (PARITY.md §time compression): n_skip per-tick adds
  and one telescoped add agree exactly while the accrued values stay
  integer-valued f32 below 2^24 ms — the same bound ``wait_total``
  itself needs, so the buffer is never the weaker surface.
- **Shard-safe carry.** Per-cluster leaves shard over the cluster axis
  like the state; cross-cluster partials (the histogram, the ring value
  rows) carry a leading shard axis of local size 1 so the buffer
  round-trips shard_map chunk calls without double counting; the global
  view reduces through ``parallel/exchange.py`` (``reduce_metrics``,
  dispatched once per harvest by ``ShardedEngine.collect_metrics``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.core.state import LEAP_BUCKETS, SimState

# ring slots: the last OBS_RING ticks' per-tick samples (slot = tick
# ordinal mod OBS_RING, so chunked runs address the ring consistently by
# the virtual clock alone)
OBS_RING = 64
# log2 histogram of per-(tick, cluster) queue depth: bucket 0 = empty,
# bucket b>=1 = depth in [2^(b-1), 2^b)
OBS_DEPTH_BUCKETS = 16


@struct.dataclass
class MetricsBuffer:
    """Fixed-shape on-device telemetry accumulators.

    The per-tick deltas the taps accumulate are differences of CUMULATIVE
    state counters (placed_total, arr_ptr, wait_total, ...); the previous
    values ride a ``TapCursor`` that lives only INSIDE a run's scan carry
    and is re-derived from the input state at every chunk entry
    (``cursor_of`` — at a chunk boundary the cursor always equals the
    incoming state's counters, so nothing needs to cross the boundary).
    Keeping cursors out of this buffer is load-bearing: a cursor leaf
    would be bitwise equal to a state leaf at the boundary, XLA would
    alias the two output buffers, and the next DONATING dispatch would
    reject the aliased buffer as already-donated (the serving tier hit
    exactly that).

    Leaves with a leading axis of 1 are shard-local partials (summed over
    this shard's clusters); under a mesh they concatenate to
    ``[n_shards, ...]`` and the global view is the axis-0 sum
    (``reduce_metrics`` / host ``harvest``)."""

    ticks: jax.Array  # [] i32 — ticks observed (leaps included)
    # per-cluster accumulators
    placed: jax.Array  # [C] i32 — placements this window
    arrived: jax.Array  # [C] i32 — arrivals ingested
    borrows: jax.Array  # [C] i32 — jobs newly hosted for peers
    wait_accrued: jax.Array  # [C] f32 — wait-time accrued (ms)
    ovf: jax.Array  # [C] i32 — narrow-store overflows surfaced
    depth_sum: jax.Array  # [C] i32 — Σ per-tick queue depth
    depth_max: jax.Array  # [C] i32
    # fault plane (faults/): per-window deltas of the cumulative churn
    # counters — zero whenever the plane is off
    kills: jax.Array  # [C] i32 — jobs killed by node failures
    requeues: jax.Array  # [C] i32 — killed jobs granted a retry
    fail_drops: jax.Array  # [C] i32 — kills past the retry budget
    node_down_ms: jax.Array  # [C] i32 — node downtime closed this window
    # shard-local partials (leading axis 1 = this shard)
    depth_hist: jax.Array  # [1, B] i32 — log2 depth histogram
    ring_placed: jax.Array  # [1, R] i32 — per-tick placed (local sum)
    ring_depth: jax.Array  # [1, R] i32 — per-tick depth (local sum)
    # replicated (identical on every shard)
    ring_t: jax.Array  # [R] i32 — tick clock per ring slot (0 = unwritten)
    # DRIVER provenance, not a replay metric: which leaps the compressed
    # driver took (the dense driver takes none, so this is the one leaf
    # excluded from the compressed==dense equality contract — everything
    # else in the buffer must match bit for bit; tests/test_obs.py)
    leap_hist: jax.Array  # [LEAP_BUCKETS] i32 — log2 leap sizes


@struct.dataclass
class TapCursor:
    """The previous cumulative state counters a tap differences against.
    Scan-carry-internal only (never crosses a jit boundary — see
    MetricsBuffer's aliasing note); rebuild with ``cursor_of(state)`` at
    every run entry."""

    placed: jax.Array  # [C] i32 (placed_total)
    arrived: jax.Array  # [C] i32 (arr_ptr)
    lent: jax.Array  # [C] i32 (lent.count)
    wait: jax.Array  # [C] f32 (wait_total)
    ovf: jax.Array  # [C] i32 (narrow-store overflow total)
    kills: jax.Array  # [C] i32 (faults.kills)
    requeues: jax.Array  # [C] i32 (faults.requeues)
    fail_drops: jax.Array  # [C] i32 (drops.failed)
    down_ms: jax.Array  # [C] i32 (faults.down_ms)


def queue_depth(state: SimState) -> jax.Array:
    """[C] total queued jobs (l0 + l1 + ready + wait; lent/borrowed track
    ownership, not local backlog). THE canonical backlog definition —
    the taps, the serving snapshot probe, and the per-request host's
    gauge all call this one site, so the surfaces cannot silently
    diverge if a queue tier is ever added."""
    return (state.l0.count + state.l1.count + state.ready.count
            + state.wait.count)


def _ovf_total(state: SimState) -> jax.Array:
    """[C] checked-narrow overflow total across the compact layouts (zeros
    on the wide layout, which carries no counters)."""
    total = jnp.zeros_like(state.arr_ptr)
    for part in (state.l0, state.l1, state.ready, state.wait, state.lent,
                 state.borrowed, state.run):
        if hasattr(part, "ovf"):
            total = total + part.ovf
    return total


def metrics_init(state: SimState) -> MetricsBuffer:
    """A zeroed buffer shaped for ``state``'s cluster axis — build once,
    thread through every chunk call. Pure jnp (safe inside jit; the
    drivers call it host-side once)."""
    C = state.arr_ptr.shape[0]
    zi = jnp.zeros((C,), jnp.int32)
    return MetricsBuffer(
        ticks=jnp.int32(0),
        placed=zi, arrived=zi, borrows=zi,
        wait_accrued=jnp.zeros((C,), jnp.float32),
        ovf=zi, depth_sum=zi, depth_max=zi,
        kills=zi, requeues=zi, fail_drops=zi, node_down_ms=zi,
        depth_hist=jnp.zeros((1, OBS_DEPTH_BUCKETS), jnp.int32),
        ring_placed=jnp.zeros((1, OBS_RING), jnp.int32),
        ring_depth=jnp.zeros((1, OBS_RING), jnp.int32),
        ring_t=jnp.zeros((OBS_RING,), jnp.int32),
        leap_hist=jnp.zeros((LEAP_BUCKETS,), jnp.int32),
    )


def cursor_of(state: SimState) -> TapCursor:
    """The tap cursor for a run starting at ``state`` — called at run
    entry; the counters only move inside ticks, so at a chunk boundary
    this reconstructs exactly the cursor the previous chunk's last tick
    left behind."""
    return TapCursor(placed=state.placed_total, arrived=state.arr_ptr,
                     lent=state.lent.count, wait=state.wait_total,
                     ovf=_ovf_total(state),
                     kills=state.faults.kills, requeues=state.faults.requeues,
                     fail_drops=state.drops.failed,
                     down_ms=state.faults.down_ms)


def _depth_buckets(depth: jax.Array) -> jax.Array:
    """log2 bucket per cluster: 0 for empty, else 1 + floor(log2(depth))."""
    b = 1 + jnp.floor(jnp.log2(jnp.maximum(depth, 1).astype(
        jnp.float32))).astype(jnp.int32)
    return jnp.clip(jnp.where(depth > 0, b, 0), 0, OBS_DEPTH_BUCKETS - 1)


# The buffer leaves the per-cluster tap half owns: every [C]-shaped
# accumulator. This is the slice that rides the fused kernel as operands
# (kernels/fused_tick.py folds ``tap_tick_local`` into the epilogue);
# the scalar tick count, the histogram scatter, and the ring rows stay in
# ``tap_tick_global`` outside the kernel — they are cross-cluster
# reductions a per-cluster-blocked grid step cannot own.
PC_LEAVES = ("placed", "arrived", "borrows", "wait_accrued", "ovf",
             "depth_sum", "depth_max", "kills", "requeues", "fail_drops",
             "node_down_ms")


def tap_pc(mbuf: MetricsBuffer) -> dict:
    """The buffer's per-cluster slice as a plain dict — the operand form
    the fused epilogue consumes; splice back with ``mbuf.replace(**pc)``."""
    return {k: getattr(mbuf, k) for k in PC_LEAVES}


def tap_tick_local(pc: dict, cur: TapCursor, state: SimState):
    """The per-cluster half of ``tap_tick``: differences the cumulative
    state counters against the cursor and accumulates into the [C] buffer
    leaves. READS the state, writes only ``pc`` + the cursor (the obs-tap
    contract). Must not read ``state.t`` — inside the fused kernel's
    epilogue the clock has not advanced yet (``_tick`` stamps it after the
    span); everything clock-addressed lives in ``tap_tick_global``.
    Returns ``(pc', cur', placed_d, depth)`` — the two [C] vectors the
    global half needs for the ring/histogram writes."""
    placed_d = state.placed_total - cur.placed
    arrived_d = state.arr_ptr - cur.arrived
    lent_d = jnp.maximum(state.lent.count - cur.lent, 0)
    ovf_now = _ovf_total(state)
    depth = queue_depth(state)
    pc = dict(
        placed=pc["placed"] + placed_d,
        arrived=pc["arrived"] + arrived_d,
        borrows=pc["borrows"] + lent_d,
        wait_accrued=pc["wait_accrued"] + (state.wait_total - cur.wait),
        ovf=pc["ovf"] + (ovf_now - cur.ovf),
        depth_sum=pc["depth_sum"] + depth,
        depth_max=jnp.maximum(pc["depth_max"], depth),
        kills=pc["kills"] + (state.faults.kills - cur.kills),
        requeues=pc["requeues"] + (state.faults.requeues - cur.requeues),
        fail_drops=pc["fail_drops"] + (state.drops.failed - cur.fail_drops),
        node_down_ms=pc["node_down_ms"]
        + (state.faults.down_ms - cur.down_ms),
    )
    cur = TapCursor(placed=state.placed_total, arrived=state.arr_ptr,
                    lent=state.lent.count, wait=state.wait_total,
                    ovf=ovf_now,
                    kills=state.faults.kills, requeues=state.faults.requeues,
                    fail_drops=state.drops.failed,
                    down_ms=state.faults.down_ms)
    return pc, cur, placed_d, depth


def tap_tick_global(mbuf: MetricsBuffer, placed_d: jax.Array,
                    depth: jax.Array, t: jax.Array,
                    tick_ms: int) -> MetricsBuffer:
    """The cross-cluster half of ``tap_tick``: the scalar tick count, the
    depth histogram scatter, and the ring rows. ``t`` is the POST-tick
    clock, passed explicitly (on the fused path the local half ran inside
    the kernel epilogue where ``state.t`` is still the previous tick) —
    it must equal the ``state.t`` the dense tap would read. Runs as plain
    XLA on the tiny [C] vectors the kernel emitted; ``mbuf`` here already
    carries the spliced-back per-cluster leaves."""
    slot = (t // jnp.int32(tick_ms)) % OBS_RING
    return mbuf.replace(
        ticks=mbuf.ticks + 1,
        depth_hist=mbuf.depth_hist.at[0, _depth_buckets(depth)].add(1),
        ring_placed=mbuf.ring_placed.at[0, slot].set(
            jnp.sum(placed_d).astype(jnp.int32)),
        ring_depth=mbuf.ring_depth.at[0, slot].set(
            jnp.sum(depth).astype(jnp.int32)),
        ring_t=mbuf.ring_t.at[slot].set(t),
    )


def tap_tick(mbuf: MetricsBuffer, cur: TapCursor, state: SimState,
             tick_ms: int) -> tuple[MetricsBuffer, TapCursor]:
    """Accumulate one executed tick's sample — READS the post-tick state,
    writes only the buffer + cursor (the obs-tap contract). Recomposed
    from the two halves the fused path splits across the kernel boundary,
    so tap-in-epilogue == post-tick tap is equality of the SAME code, not
    of a copy (tests/test_kernels.py pins it)."""
    pc, cur, placed_d, depth = tap_tick_local(tap_pc(mbuf), cur, state)
    mbuf = tap_tick_global(mbuf.replace(**pc), placed_d, depth, state.t,
                           tick_ms)
    return mbuf, cur


def tap_leap(mbuf: MetricsBuffer, cur: TapCursor, state: SimState,
             n_skip: jax.Array, tick_ms: int
             ) -> tuple[MetricsBuffer, TapCursor]:
    """The skipped-tick samples of a quiescent leap, in closed form —
    exactly what ``n_skip`` dense ``tap_tick`` calls over the fixed point
    would have accumulated. ``state`` is the POST-leap state (clock at the
    landing tick, wait accrual applied); ``n_skip=0`` is the identity, so
    the compressed driver calls this unconditionally after the leap cond.

    Per-tick deltas (placed/arrived/borrows/ovf and the fault counters —
    the leap bound never jumps a fail/repair event, so the churn leaves
    are constant across the gap) are zero at a fixed point, so only the
    cursors that moved (the closed-form wait accrual) advance; per-tick levels replicate: depth_sum += n_skip·depth, the
    histogram bucket of the fixed depth gains n_skip, and each covered
    ring slot takes the LATEST skipped tick that maps to it (slot j keeps
    ordinal q = m + n_skip - ((m + n_skip - j) mod R), covered iff
    q > m) — bitwise what the dense writes leave behind."""
    depth = queue_depth(state)
    tick = jnp.int32(tick_ms)
    m = (state.t // tick) - n_skip  # ordinal of the executed tick
    j = jnp.arange(OBS_RING, dtype=jnp.int32)
    q = m + n_skip - ((m + n_skip - j) % OBS_RING)
    covered = jnp.logical_and(n_skip > 0, q > m)
    depth_tot = jnp.sum(depth).astype(jnp.int32)
    lbucket = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(
        n_skip, 1).astype(jnp.float32))).astype(jnp.int32),
        0, LEAP_BUCKETS - 1)
    mbuf = mbuf.replace(
        ticks=mbuf.ticks + n_skip,
        wait_accrued=mbuf.wait_accrued + (state.wait_total - cur.wait),
        depth_sum=mbuf.depth_sum + n_skip * depth,
        depth_max=jnp.maximum(mbuf.depth_max, depth),
        depth_hist=mbuf.depth_hist.at[0, _depth_buckets(depth)].add(n_skip),
        ring_placed=jnp.where(covered[None, :], 0, mbuf.ring_placed),
        ring_depth=jnp.where(covered[None, :], depth_tot, mbuf.ring_depth),
        ring_t=jnp.where(covered, q * tick, mbuf.ring_t),
        leap_hist=mbuf.leap_hist.at[lbucket].add(
            (n_skip > 0).astype(jnp.int32)),
    )
    return mbuf, cur.replace(wait=state.wait_total)


def reduce_metrics(mbuf: MetricsBuffer, ex) -> MetricsBuffer:
    """Cross-shard reduction of the shard-local partials through the
    sanctioned exchange (parallel/exchange.py): the histogram and ring
    value rows are per-shard sums over local clusters, so the global view
    is one ``allsum`` each. Per-cluster leaves are already globally
    correct (sharded like the state); replicated leaves (ticks, ring_t,
    leap_hist) are identical on every shard by construction. Called once
    per harvest — never inside the carry, where a second reduction would
    double count."""
    return mbuf.replace(
        depth_hist=ex.allsum(mbuf.depth_hist),
        ring_placed=ex.allsum(mbuf.ring_placed),
        ring_depth=ex.allsum(mbuf.ring_depth),
    )


def harvest(mbuf: MetricsBuffer) -> dict:
    """Host-side readout of one harvested buffer — the single coercion per
    chunk boundary (np.array, owned copies: the buffer leaves may share a
    donated dispatch's allocator). Returns JSON-ready totals plus the raw
    per-cluster rows under ``per_cluster``."""
    leaves = {k: np.array(getattr(mbuf, k))
              for k in mbuf.__dataclass_fields__}
    ticks = int(leaves["ticks"])
    depth_sum = int(leaves["depth_sum"].sum())
    hist = leaves["depth_hist"].sum(axis=0)
    nz = np.flatnonzero(hist)
    lh = leaves["leap_hist"]
    lnz = np.flatnonzero(lh)
    # ring rows in clock order, unwritten slots dropped
    order = np.argsort(leaves["ring_t"], kind="stable")
    rt = leaves["ring_t"][order]
    valid = rt > 0
    return {
        "ticks": ticks,
        "placed": int(leaves["placed"].sum()),
        "arrived": int(leaves["arrived"].sum()),
        "borrows": int(leaves["borrows"].sum()),
        "wait_accrued_ms": round(float(leaves["wait_accrued"].sum()), 3),
        "narrow_ovf": int(leaves["ovf"].sum()),
        "fault_kills": int(leaves["kills"].sum()),
        "fault_requeues": int(leaves["requeues"].sum()),
        "fault_drops": int(leaves["fail_drops"].sum()),
        "node_down_ms": int(leaves["node_down_ms"].sum()),
        "queue_depth_mean": round(depth_sum / max(ticks, 1), 3),
        "queue_depth_max": int(leaves["depth_max"].max(initial=0)),
        "depth_hist_log2": hist[:nz[-1] + 1].tolist() if len(nz) else [],
        "leap_hist_log2": lh[:lnz[-1] + 1].tolist() if len(lnz) else [],
        "ring": {
            "t_ms": rt[valid].tolist(),
            "placed": leaves["ring_placed"].sum(axis=0)[order][valid].tolist(),
            "queue_depth":
                leaves["ring_depth"].sum(axis=0)[order][valid].tolist(),
        },
        "per_cluster": {
            "placed": leaves["placed"].tolist(),
            "queue_depth_max": leaves["depth_max"].tolist(),
        },
    }
