from multi_cluster_simulator_tpu.utils.trace import (
    assert_no_drops, check_conservation, extract_trace, total_drops,
)

__all__ = ["extract_trace", "check_conservation", "total_drops",
           "assert_no_drops"]
