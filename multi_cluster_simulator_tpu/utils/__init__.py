from multi_cluster_simulator_tpu.utils.trace import extract_trace

__all__ = ["extract_trace"]
