"""Host-side helpers for reading engine traces and checking invariants."""

from __future__ import annotations

import numpy as np

from multi_cluster_simulator_tpu.core.state import SimState


def extract_trace(state: SimState) -> list[list[tuple[int, int, int, int]]]:
    """Per-cluster placement event lists of (t, job_id, node, src)."""
    tr = state.trace
    t = np.asarray(tr.t)
    job = np.asarray(tr.job)
    node = np.asarray(tr.node)
    src = np.asarray(tr.src)
    n = np.asarray(tr.n)
    out = []
    for c in range(t.shape[0]):
        k = int(n[c])
        out.append([(int(t[c, i]), int(job[c, i]), int(node[c, i]), int(src[c, i]))
                    for i in range(k)])
    return out


def oracle_trace_per_cluster(oracle, n_clusters: int) -> list[list[tuple[int, int, int, int]]]:
    """Reshape the oracle's global (t, cluster, job, node, src) list to the
    engine's per-cluster layout."""
    out = [[] for _ in range(n_clusters)]
    for (t, c, j, node, src) in oracle.trace:
        out[c].append((t, j, node, src))
    return out


def check_conservation(state: SimState) -> None:
    """Invariant: free + sum(running on node) == capacity for active nodes,
    and 0 <= free <= cap. Honors the configured resource width (n_res)."""
    free = np.asarray(state.node_free)
    cap = np.asarray(state.node_cap)
    active = np.asarray(state.node_active)
    run = state.run
    r_node = np.asarray(run.node)
    r_cores = np.asarray(run.cores)
    r_mem = np.asarray(run.mem)
    r_gpu = np.asarray(run.gpu)
    r_act = np.asarray(run.active)
    C, N, n_res = free.shape
    used = np.zeros((C, N, 3), np.int64)
    for c in range(C):
        for s in range(r_node.shape[1]):
            if r_act[c, s]:
                used[c, r_node[c, s], 0] += r_cores[c, s]
                used[c, r_node[c, s], 1] += r_mem[c, s]
                used[c, r_node[c, s], 2] += r_gpu[c, s]
    assert (free >= 0).all(), "negative free resources"
    recon = free + used[..., :n_res]
    mism = (recon != cap) & active[..., None]
    assert not mism.any(), f"conservation violated at {np.argwhere(mism)[:5]}"


def total_drops(state: SimState) -> dict:
    """Summed SimState.drops counters — every one should be zero on a
    correctly sized config (see core/state.py Drops). ``narrow`` is the
    compact layouts' checked-narrow overflow total (core/compact.py):
    always zero for wide states, and zero for compact states whose storage
    plan actually covers the workload's ranges — a nonzero value means a
    narrowing store clamped instead of silently wrapping."""
    from multi_cluster_simulator_tpu.core.compact import overflow_total

    d = state.drops
    out = {k: int(np.asarray(getattr(d, k)).sum())
           for k in ("queue", "msgs", "run_full", "vslot", "carve", "ingest",
                     "failed")}
    out["narrow"] = overflow_total(state)
    return out


def assert_no_drops(state: SimState) -> None:
    drops = total_drops(state)
    assert all(v == 0 for v in drops.values()), f"static bounds bound: {drops}"
