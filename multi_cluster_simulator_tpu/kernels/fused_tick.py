"""The fused tick kernel: the per-cluster PREFIX as ONE ``pallas_call``.

Why this exists (ROADMAP item 4): the tick is memory/latency-bound — the
round-5 TPU roofline record (tools/cost_probe_tpu_r05.json) puts the
headline FIFO tick at ~0.10 FLOP/byte. Under XLA the tick is a chain of
fusions that round-trips the queue/runset/node columns through HBM between
phases: each phase's fusion loads the state columns from its argument
buffers and stores them back at its output boundary. This kernel collapses
the whole PER-CLUSTER-LOCAL prefix of the tick — phases 1-5: faults →
completions/returns-pack → vnode expiry → arrival ingest → the policy
zoo's scheduling pass — into one ``pallas_call`` over cluster blocks: each
grid step loads its block's columns ONCE, runs the prefix over the
VMEM-resident values, and writes each column back ONCE. The fusion
boundary is the first cross-cluster exchange (return delivery, borrow
matching, snapshot, trade ride collectives and stay outside); the kernel's
outputs are exactly what those phases consume — ``want``, ``bjob_vec``,
the packed return rows. ``tools/cost_probe.py --fused`` measures exactly
that collapse (per-phase executable boundary bytes vs the fused
executable's), and ``bench.py --fused ab`` is the standing bitwise + bytes
gate.

The prefix is config-shaped: faults and vnode expiry are config-gated
Python branches, so a faults-off config fuses a SHORTER prefix rather than
paying dead phases — ``engaged_span`` names the per-config span and every
provenance dict records it. On a TERMINAL prefix (no borrowing, no trader
— nothing runs after the span) two more passes fold into the kernel: the
checked exit narrow of the compact node columns, and the obs metrics
tap's per-cluster half as the kernel EPILOGUE (``obs.device
.tap_tick_local`` — the tap only READS SimState, simlint family 9, so the
buffer's [C] leaves ride as ordinary operands; the cross-cluster half
stays outside on the kernel's tiny [C] outputs).

Bit-identity is BY CONSTRUCTION, not by porting: the kernel body calls
``Engine._span_prefix`` — the same function the unfused path runs — on
the block-resident values. Blocking the cluster axis is bitwise invisible
because every op in the prefix is per-cluster (vmapped); the block size is
the largest divisor of the (shard-local) cluster count <= the
``fused_block`` hint, so no block is ever padded.

Layout-generic over the PR-5 compact plan by the same construction: the
kernel refs carry each leaf's STORAGE dtype (int8/int16 queue columns
under a CompactPlan), the prefix widens on load through the SoA accessors
and narrows on store through the checked ``fields.narrow_store`` helper
inside the kernel body, and the ``ovf`` overflow counters ride the block
like any other column — counting preserved exactly.

The interpret-mode oracle: ``pallas_call(interpret=True)`` executes the
same kernel body through XLA on any backend, so the ENTIRE existing
bit-equality matrix (compact x time compression x ragged chunks x faults x
the 8-device mesh x checkpoint cuts x tenancy) gates the kernel on CPU CI
today (tests/test_kernels.py); a real TPU backend compiles the same body
via Mosaic and is gated by the same tests' interpret-vs-compiled cells.
``interpret=`` is ALWAYS threaded from config (``interpret_mode`` below) —
simlint rule family 10 rejects hardcoding it at any ``pallas_call`` site.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# The MAXIMAL fused phase span (contiguous obs.profile.TICK_PHASES
# members 1-5; all per-cluster-local, which is what makes them
# blockable). A given config engages the subset ``engaged_span`` names —
# recorded in every provenance dict so artifacts name the span they
# measured.
FUSED_SPAN = ("faults", "release", "expire", "ingest", "schedule")


def interpret_mode(cfg) -> bool:
    """The ``pallas_call(interpret=...)`` source of truth: config first
    (``fused_interpret`` pins it for tests and A/Bs), else interpret
    everywhere except a real TPU backend — the CPU/CI-oracle contract.
    Every call site threads this; simlint family 10 enforces it."""
    if cfg.fused_interpret is not None:
        return bool(cfg.fused_interpret)
    return jax.default_backend() != "tpu"


def is_active(cfg) -> bool:
    """Resolve ``cfg.fused`` to a concrete engage/skip decision (the one
    definition Engine.fused_active and the bench/probe drivers share):
    ``on`` always, ``auto`` only on a real TPU backend — interpret mode
    is an oracle, not a fast path, so CPU hosts stay unfused unless the
    config pins ``on``."""
    if cfg.fused == "on":
        return True
    if cfg.fused == "auto":
        return jax.default_backend() == "tpu"
    return False


def block_clusters(C: int, hint: int) -> int:
    """Largest divisor of ``C`` that is <= ``hint`` (>=1): the cluster
    block each grid step owns. A divisor, never a ceiling — padded blocks
    would feed garbage rows into the span's sorts and need masked stores;
    a divisor keeps blocking bitwise invisible by construction."""
    bc = max(min(C, hint), 1)
    while C % bc:
        bc -= 1
    return bc


def engaged_span(cfg) -> tuple[str, ...]:
    """The prefix phases THIS config engages, in TICK_PHASES order — the
    span the kernel actually replays. Faults and vnode expiry are
    config-gated Python branches inside ``_span_prefix``, so they are
    span members only when their gates hold; release/ingest/schedule
    always run."""
    span = []
    if cfg.faults.enabled:
        span.append("faults")
    span.append("release")
    if cfg.trader.enabled and cfg.trader.expire_virtual_nodes:
        span.append("expire")
    span += ["ingest", "schedule"]
    return tuple(span)


def provenance(cfg, C: int | None = None) -> dict:
    """The ``fused`` provenance fields bench/probe detail dicts record
    (host-side; the engage decision re-resolves from config here).
    ``span`` is the per-config ENGAGED span, not the maximal one;
    ``epilogue_tap`` records whether the prefix is terminal — i.e.
    whether an obs-on run folds the metrics tap into the kernel."""
    act = is_active(cfg)
    out = {"mode": cfg.fused, "active": act,
           "span": list(engaged_span(cfg)),
           "epilogue_tap": bool(not cfg.borrowing
                                and not cfg.trader.enabled)}
    if act:
        out["interpret"] = interpret_mode(cfg)
        out["block_hint"] = cfg.fused_block
        if C is not None:
            out["block_clusters"] = block_clusters(C, cfg.fused_block)
    return out


def _specs_for(shapes, per_cluster, bc):
    """One BlockSpec per leaf: per-cluster leaves block axis 0 into
    ``bc``-cluster slices (the grid axis); replicated leaves (the clock,
    the PolicyParams tables) load whole into every grid step."""
    specs = []
    for shape, pc in zip(shapes, per_cluster):
        nd = len(shape)
        # simlint: ignore[pallas-kernel] -- host-side spec construction:
        # `pc` is a Python bool from the static per-leaf layout table,
        # never a tracer (shapes/flags are decided before tracing)
        if pc:
            specs.append(pl.BlockSpec(
                (bc,) + tuple(shape[1:]),
                lambda i, _nd=nd: (i,) + (0,) * (_nd - 1)))
        else:
            specs.append(pl.BlockSpec(
                tuple(shape), lambda i, _nd=nd: (0,) * _nd))
    return specs


def fused_prefix(engine, state, arr_rows, arr_n, t, params, tick_indexed,
                 emit_returns: bool, obs=None):
    """Run ``Engine._span_prefix`` (tick phases 1-5) as one
    ``pallas_call`` over cluster blocks. Same signature contract as the
    unfused call: returns
    ``(state', want, bjob_vec, ret_rows, ret_valid, obs_out)`` — return
    rows are None when ``emit_returns`` is off (the pytree drops them, so
    the kernel carries no dead outputs), and ``obs_out`` mirrors the
    ``obs`` input: pass ``(pc, cursor)`` (obs.device.tap_pc form) on a
    terminal prefix to run the metrics tap's per-cluster half as the
    kernel epilogue, get ``(pc', cursor', placed_d, depth)`` back.

    Ref discipline (simlint family 10): every input is read exactly once
    into block values (``ref[...]``), the prefix runs on those values, and
    every output is written exactly once — one load + one store per
    column, which is the whole point of the kernel.

    The prefix is traced to a jaxpr FIRST (at block shape) and replayed
    inside the kernel body: the prefix's closure constants (queue invalid
    rows, policy dispatch tables, fault schedules' module arrays — things
    Pallas cannot capture) become explicit replicated kernel operands, so
    the body is a pure function of its refs for ANY policy set, fault
    mode, or state layout. Output templates derive from the traced
    jaxpr's out_avals — every prefix output leads with the cluster axis
    (asserted), so the full shape is the block shape with axis 0 scaled
    back to C."""
    cfg = engine.cfg
    C = int(state.arr_ptr.shape[0])
    bc = block_clusters(C, cfg.fused_block)
    interp = interpret_mode(cfg)

    # --- flatten the operands ------------------------------------------
    # State: every leaf is [C]-leading except the scalar clock (STATE_AXES
    # broadcasts exactly one leaf: ``t``); the clock rides as a replicated
    # (1,)-shaped operand and is re-inserted at its flatten position
    # inside the prefix, so it sees a structurally identical SimState.
    s_leaves, s_def = jax.tree_util.tree_flatten(state)
    t_pos = [i for i, leaf in enumerate(s_leaves)
             if jnp.ndim(leaf) == 0]
    if len(t_pos) != 1:
        raise ValueError(
            f"fused_prefix expects exactly one scalar state leaf (the "
            f"clock); got {len(t_pos)} — did SimState grow a scalar?")
    t_pos = t_pos[0]
    t_old = s_leaves.pop(t_pos)
    # obs: the tap's per-cluster buffer slice + cursor — all [C] leaves,
    # blocked like the state (None flattens to zero leaves)
    ob_leaves, ob_def = jax.tree_util.tree_flatten(obs)
    p_leaves, p_def = jax.tree_util.tree_flatten(params)
    p_shapes = [jnp.shape(leaf) for leaf in p_leaves]

    def lift(x):  # scalars -> (1,) so every operand is an array block
        return jnp.reshape(x, (1,)) if jnp.ndim(x) == 0 else x

    data_in = (list(s_leaves) + [arr_rows, arr_n] + list(ob_leaves)
               + [lift(t_old), lift(t)] + [lift(x) for x in p_leaves])
    data_pc = ([True] * len(s_leaves) + [True, True]
               + [True] * len(ob_leaves)
               + [False, False] + [False] * len(p_leaves))
    n_state = len(s_leaves)
    n_obs = len(ob_leaves)
    aux_cell = {}  # the aux outputs' treedef, captured during tracing

    def span_flat(*flat):
        sv = list(flat[:n_state])
        rows_b, n_b = flat[n_state:n_state + 2]
        ov = list(flat[n_state + 2:n_state + 2 + n_obs])
        t_old_b, t_new_b = flat[n_state + 2 + n_obs:n_state + 4 + n_obs]
        pv = flat[n_state + 4 + n_obs:]
        sv.insert(t_pos, jnp.reshape(t_old_b, ()))
        s_b = jax.tree_util.tree_unflatten(s_def, sv)
        ob_b = jax.tree_util.tree_unflatten(ob_def, ov)
        p_b = jax.tree_util.tree_unflatten(
            p_def, [jnp.reshape(v, sh) for v, sh in zip(pv, p_shapes)])
        s2, want, bjob, ret_rows, ret_valid, obs_out = \
            engine._span_prefix(s_b, rows_b, n_b,
                                jnp.reshape(t_new_b, ()), p_b,
                                tick_indexed, emit_returns=emit_returns,
                                obs=ob_b)
        o2 = jax.tree_util.tree_leaves(s2)
        del o2[t_pos]  # the clock is untouched by the prefix
        aux_leaves, aux_cell["def"] = jax.tree_util.tree_flatten(
            (want, bjob, ret_rows, ret_valid, obs_out))
        return tuple(o2) + tuple(aux_leaves)

    def block_shape(x, pc):
        shape = jnp.shape(x)
        return ((bc,) + tuple(shape[1:])) if pc else tuple(shape)

    abstract = [jax.ShapeDtypeStruct(block_shape(x, pc), x.dtype)
                for x, pc in zip(data_in, data_pc)]
    closed = jax.make_jaxpr(span_flat)(*abstract)
    # closure constants -> replicated operands (scalars lifted like t)
    consts = [jnp.asarray(c) for c in closed.consts]
    c_shapes = [jnp.shape(c) for c in consts]

    inputs = data_in + [lift(c) for c in consts]
    per_cluster = data_pc + [False] * len(consts)
    in_specs = _specs_for([jnp.shape(x) for x in inputs], per_cluster, bc)

    # Outputs, from the traced jaxpr: the per-cluster state leaves (same
    # order/dtypes — the prefix preserves storage dtypes, compact plans
    # included) plus the aux outputs (want/bjob/return rows/tap halves).
    # Everything the prefix emits is per-cluster-leading by construction;
    # the clock stays an input.
    out_tmpl = []
    for av in closed.out_avals:
        # simlint: ignore[pallas-kernel] -- host-side template
        # construction: `av` is an abstract value off the traced jaxpr
        # (a plain shape/dtype record), inspected before any kernel runs
        if len(av.shape) == 0 or av.shape[0] != bc:
            raise ValueError(
                f"fused prefix output is not cluster-leading: {av.shape} "
                f"(block={bc}) — every prefix output must block on axis 0")
        out_tmpl.append(jax.ShapeDtypeStruct((C,) + tuple(av.shape[1:]),
                                             av.dtype))
    out_specs = _specs_for([s.shape for s in out_tmpl],
                           [True] * len(out_tmpl), bc)

    n_data = len(data_in)

    def body(*refs):
        ins, outs = refs[:len(inputs)], refs[len(inputs):]
        vals = [r[...] for r in ins]  # ONE load per column
        cvals = [jnp.reshape(v, sh)
                 for v, sh in zip(vals[n_data:], c_shapes)]
        out_vals = jax.core.eval_jaxpr(closed.jaxpr, cvals,
                                       *vals[:n_data])
        for ref, val in zip(outs, out_vals):
            ref[...] = val  # ONE store per column

    outs = pl.pallas_call(
        body,
        grid=(C // bc,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_tmpl,
        interpret=interp,
    )(*inputs)

    new_leaves = list(outs[:n_state])
    new_leaves.insert(t_pos, t_old)
    state2 = jax.tree_util.tree_unflatten(s_def, new_leaves)
    want, bjob_vec, ret_rows, ret_valid, obs_out = \
        jax.tree_util.tree_unflatten(aux_cell["def"],
                                     list(outs[n_state:]))
    return state2, want, bjob_vec, ret_rows, ret_valid, obs_out


def span_boundary_bytes(cfg, state, arr_rows, arr_n,
                        tick_indexed: bool = True,
                        obs: bool = False) -> dict:
    """The before/after instrument for the prefix collapse (compile-only;
    nothing runs): each ENGAGED prefix phase compiled as its OWN
    executable pays argument+output buffer-boundary traffic for the state
    columns it touches — that per-phase sum (``unfused_total``) against
    the ONE fused-prefix executable's boundary bytes (``fused``) is the
    measured form of "one load + one store per column".
    ``tools/cost_probe.py --fused`` records it per shape and ``bench.py
    --fused ab`` gates on ``fused < unfused_total`` strictly.

    ``obs=True`` (terminal prefixes only) adds the epilogue-tap variant:
    the unfused side gains the standalone post-tick ``tap_tick``
    executable as one more per-phase row, the fused side carries the
    buffer's [C] leaves as kernel operands plus the cross-cluster tap
    half — the measured form of "observability stops costing a pass over
    state".

    ``state`` may be narrow (compact plan): the node columns are widened
    here exactly as the span-entry widen would, so the executables match
    the mid-tick state the real phases receive (the in-kernel
    widen/narrow of a terminal compact run is a no-op on this probe's
    wide state; the real kernel additionally loads/stores the narrow
    columns, strictly fewer bytes)."""
    import dataclasses

    from multi_cluster_simulator_tpu.core.engine import Engine
    from multi_cluster_simulator_tpu.obs import device as obs_device
    from multi_cluster_simulator_tpu.ops import fields as F

    eng = Engine(dataclasses.replace(cfg, fused="off"))
    eng_f = Engine(dataclasses.replace(cfg, fused="on"))
    params = eng._default_params
    emit_ret = bool(cfg.borrowing)  # the scan path's emit_returns
    if state.node_free.dtype != jnp.int32:
        state = state.replace(node_free=F.widen(state.node_free),
                              node_cap=F.widen(state.node_cap))
    t1 = state.t + cfg.tick_ms

    def bbytes(fn, *args):
        ma = jax.jit(fn).lower(*args).compile().memory_analysis()
        # simlint: ignore[pallas-kernel] -- host-side compile-time probe:
        # memory_analysis returns plain Python stats on an already-
        # compiled executable, never a tracer (nothing here is traced)
        return int(ma.argument_size_in_bytes + ma.output_size_in_bytes)

    span = engaged_span(cfg)
    idx = {name: i + 1 for i, name in enumerate(FUSED_SPAN)}

    def phase_fn(name):
        # one phase alone, on the REAL prefix body (``only_phase``
        # selects it); outputs restricted to what that phase actually
        # sends across its seam so the per-phase boundary is honest
        def f(s, rows, cnt, tt):
            s2, want, bjob, rr, rv, _ = eng._span_prefix(
                s, rows, cnt, tt, params, tick_indexed,
                emit_returns=emit_ret, only_phase=idx[name])
            extras = ()
            # simlint: ignore[pallas-kernel] -- `name` is the host-side
            # phase label of the probe loop and `rr is not None` is the
            # static none-as-empty-pytree test, decided at trace time
            if name == "schedule":
                extras = (want, bjob)
            # simlint: ignore[pallas-kernel] -- same static pair: host
            # phase label + the none-as-empty-pytree emptiness test
            if name == "release" and rr is not None:
                extras = extras + (rr, rv)
            return (s2,) + extras
        return f

    per_phase = {name: bbytes(phase_fn(name), state, arr_rows, arr_n, t1)
                 for name in span}

    def fused_fn(s, rows, cnt, tt):
        s2, want, bjob, rr, rv, _ = fused_prefix(
            eng_f, s, rows, cnt, tt, params, tick_indexed,
            emit_returns=emit_ret)
        extras = (rr, rv) if rr is not None else ()
        return (s2, want, bjob) + extras

    fused = bbytes(fused_fn, state, arr_rows, arr_n, t1)
    total = sum(per_phase.values())
    out = {"span": list(span),
           "unfused_per_phase": per_phase, "unfused_total": total,
           "fused": fused,
           "reduction": round(1.0 - fused / max(total, 1), 4)}

    if obs and eng_f.prefix_terminal():
        mb = obs_device.metrics_init(state)
        cur = obs_device.cursor_of(state)

        def tap_fn(m, c, s):
            return obs_device.tap_tick(m, c, s, cfg.tick_ms)

        pp_obs = dict(per_phase)
        pp_obs["tap"] = bbytes(tap_fn, mb, cur, state)

        def fused_obs_fn(s, rows, cnt, tt, m, c):
            s2, want, bjob, rr, rv, tap = fused_prefix(
                eng_f, s, rows, cnt, tt, params, tick_indexed,
                emit_returns=emit_ret, obs=(obs_device.tap_pc(m), c))
            pc2, c2, placed_d, depth = tap
            m2 = obs_device.tap_tick_global(m.replace(**pc2), placed_d,
                                            depth, tt, cfg.tick_ms)
            extras = (rr, rv) if rr is not None else ()
            return (s2, want, bjob) + extras + (m2, c2)

        fused_obs = bbytes(fused_obs_fn, state, arr_rows, arr_n, t1,
                           mb, cur)
        tot_obs = sum(pp_obs.values())
        out["obs"] = {
            "unfused_per_phase": pp_obs, "unfused_total": tot_obs,
            "fused": fused_obs,
            "reduction": round(1.0 - fused_obs / max(tot_obs, 1), 4)}
    return out
