"""The fused tick kernel: the ingest->schedule span as ONE ``pallas_call``.

Why this exists (ROADMAP item 5): the tick is memory/latency-bound — the
round-5 TPU roofline record (tools/cost_probe_tpu_r05.json) puts the
headline FIFO tick at ~0.10 FLOP/byte, and the profile plane's
phase-prefix ablation attributes most of it to the schedule pass. Under
XLA the tick is a chain of fusions that round-trips the queue/runset/node
columns through HBM between phases: each phase's fusion loads the state
columns from its argument buffers and stores them back at its output
boundary. This kernel collapses the hottest CONTIGUOUS, PER-CLUSTER span —
phase 4 (arrival ingest) + phase 5 (the policy zoo's scheduling pass) —
into one ``pallas_call`` over cluster blocks: each grid step loads its
block's columns ONCE, runs the whole span over the VMEM-resident values,
and writes each column back ONCE. ``tools/cost_probe.py --fused`` measures
exactly that collapse (per-phase executable boundary bytes vs the fused
executable's), and ``bench.py --fused ab`` is the standing bitwise + bytes
gate.

Bit-identity is BY CONSTRUCTION, not by porting: the kernel body calls
``Engine._span_ingest_schedule`` — the same function the unfused path
runs — on the block-resident values. Blocking the cluster axis is bitwise
invisible because every op in the span is per-cluster (vmapped); the block
size is the largest divisor of the (shard-local) cluster count <= the
``fused_block`` hint, so no block is ever padded.

Layout-generic over the PR-5 compact plan by the same construction: the
kernel refs carry each leaf's STORAGE dtype (int8/int16 queue columns
under a CompactPlan), the span's queue ops widen on load through the SoA
accessors and narrow on store through the checked ``fields.narrow_store``
helper inside the kernel body, and the ``ovf`` overflow counters ride the
block like any other column — counting preserved exactly.

The interpret-mode oracle: ``pallas_call(interpret=True)`` executes the
same kernel body through XLA on any backend, so the ENTIRE existing
bit-equality matrix (compact x time compression x ragged chunks x faults x
the 8-device mesh x checkpoint cuts) gates the kernel on CPU CI today
(tests/test_kernels.py); a real TPU backend compiles the same body via
Mosaic and is gated by the same tests' interpret-vs-compiled cells.
``interpret=`` is ALWAYS threaded from config (``interpret_mode`` below) —
simlint rule family 10 rejects hardcoding it at any ``pallas_call`` site.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# The fused phase span (contiguous obs.profile.TICK_PHASES members; both
# per-cluster-local, which is what makes them blockable). Recorded in
# every provenance dict so artifacts name the span they measured.
FUSED_SPAN = ("ingest", "schedule")


def interpret_mode(cfg) -> bool:
    """The ``pallas_call(interpret=...)`` source of truth: config first
    (``fused_interpret`` pins it for tests and A/Bs), else interpret
    everywhere except a real TPU backend — the CPU/CI-oracle contract.
    Every call site threads this; simlint family 10 enforces it."""
    if cfg.fused_interpret is not None:
        return bool(cfg.fused_interpret)
    return jax.default_backend() != "tpu"


def is_active(cfg) -> bool:
    """Resolve ``cfg.fused`` to a concrete engage/skip decision (the one
    definition Engine.fused_active and the bench/probe drivers share):
    ``on`` always, ``auto`` only on a real TPU backend — interpret mode
    is an oracle, not a fast path, so CPU hosts stay unfused unless the
    config pins ``on``."""
    if cfg.fused == "on":
        return True
    if cfg.fused == "auto":
        return jax.default_backend() == "tpu"
    return False


def block_clusters(C: int, hint: int) -> int:
    """Largest divisor of ``C`` that is <= ``hint`` (>=1): the cluster
    block each grid step owns. A divisor, never a ceiling — padded blocks
    would feed garbage rows into the span's sorts and need masked stores;
    a divisor keeps blocking bitwise invisible by construction."""
    bc = max(min(C, hint), 1)
    while C % bc:
        bc -= 1
    return bc


def provenance(cfg, C: int | None = None) -> dict:
    """The ``fused`` provenance fields bench/probe detail dicts record
    (host-side; the engage decision re-resolves from config here)."""
    act = is_active(cfg)
    out = {"mode": cfg.fused, "active": act, "span": list(FUSED_SPAN)}
    if act:
        out["interpret"] = interpret_mode(cfg)
        out["block_hint"] = cfg.fused_block
        if C is not None:
            out["block_clusters"] = block_clusters(C, cfg.fused_block)
    return out


def _specs_for(shapes, per_cluster, bc):
    """One BlockSpec per leaf: per-cluster leaves block axis 0 into
    ``bc``-cluster slices (the grid axis); replicated leaves (the clock,
    the PolicyParams tables) load whole into every grid step."""
    specs = []
    for shape, pc in zip(shapes, per_cluster):
        nd = len(shape)
        # simlint: ignore[pallas-kernel] -- host-side spec construction:
        # `pc` is a Python bool from the static per-leaf layout table,
        # never a tracer (shapes/flags are decided before tracing)
        if pc:
            specs.append(pl.BlockSpec(
                (bc,) + tuple(shape[1:]),
                lambda i, _nd=nd: (i,) + (0,) * (_nd - 1)))
        else:
            specs.append(pl.BlockSpec(
                tuple(shape), lambda i, _nd=nd: (0,) * _nd))
    return specs


def fused_span(engine, state, arr_rows, arr_n, t, params, tick_indexed):
    """Run ``Engine._span_ingest_schedule`` (tick phases 4+5) as one
    ``pallas_call`` over cluster blocks. Same signature contract as the
    unfused call: returns ``(state', want, bjob_vec)``.

    Ref discipline (simlint family 10): every input is read exactly once
    into block values (``ref[...]``), the span runs on those values, and
    every output is written exactly once — one load + one store per
    column, which is the whole point of the kernel.

    The span is traced to a jaxpr FIRST (at block shape) and replayed
    inside the kernel body: the span's closure constants (queue invalid
    rows, policy dispatch tables — module-level arrays Pallas cannot
    capture) become explicit replicated kernel operands, so the body is a
    pure function of its refs for ANY policy set or state layout."""
    from multi_cluster_simulator_tpu.ops import queues as Q

    cfg = engine.cfg
    C = int(state.arr_ptr.shape[0])
    bc = block_clusters(C, cfg.fused_block)
    interp = interpret_mode(cfg)

    # --- flatten the operands ------------------------------------------
    # State: every leaf is [C]-leading except the scalar clock (STATE_AXES
    # broadcasts exactly one leaf: ``t``); the clock rides as a replicated
    # (1,)-shaped operand and is re-inserted at its flatten position
    # inside the span, so it sees a structurally identical SimState.
    s_leaves, s_def = jax.tree_util.tree_flatten(state)
    t_pos = [i for i, leaf in enumerate(s_leaves)
             if jnp.ndim(leaf) == 0]
    if len(t_pos) != 1:
        raise ValueError(
            f"fused_span expects exactly one scalar state leaf (the "
            f"clock); got {len(t_pos)} — did SimState grow a scalar?")
    t_pos = t_pos[0]
    t_old = s_leaves.pop(t_pos)
    p_leaves, p_def = jax.tree_util.tree_flatten(params)
    p_shapes = [jnp.shape(leaf) for leaf in p_leaves]

    def lift(x):  # scalars -> (1,) so every operand is an array block
        return jnp.reshape(x, (1,)) if jnp.ndim(x) == 0 else x

    data_in = (list(s_leaves) + [arr_rows, arr_n]
               + [lift(t_old), lift(t)] + [lift(x) for x in p_leaves])
    data_pc = ([True] * len(s_leaves) + [True, True]
               + [False, False] + [False] * len(p_leaves))
    n_state = len(s_leaves)

    def span_flat(*flat):
        sv = list(flat[:n_state])
        rows_b, n_b, t_old_b, t_new_b = flat[n_state:n_state + 4]
        pv = flat[n_state + 4:]
        sv.insert(t_pos, jnp.reshape(t_old_b, ()))
        s_b = jax.tree_util.tree_unflatten(s_def, sv)
        p_b = jax.tree_util.tree_unflatten(
            p_def, [jnp.reshape(v, sh) for v, sh in zip(pv, p_shapes)])
        s2, want, bjob = engine._span_ingest_schedule(
            s_b, rows_b, n_b, jnp.reshape(t_new_b, ()), p_b, tick_indexed)
        o_leaves = jax.tree_util.tree_leaves(s2)
        del o_leaves[t_pos]  # the clock is untouched by the span
        return tuple(o_leaves) + (want, bjob)

    def block_shape(x, pc):
        shape = jnp.shape(x)
        return ((bc,) + tuple(shape[1:])) if pc else tuple(shape)

    abstract = [jax.ShapeDtypeStruct(block_shape(x, pc), x.dtype)
                for x, pc in zip(data_in, data_pc)]
    closed = jax.make_jaxpr(span_flat)(*abstract)
    # closure constants -> replicated operands (scalars lifted like t)
    consts = [jnp.asarray(c) for c in closed.consts]
    c_shapes = [jnp.shape(c) for c in consts]

    inputs = data_in + [lift(c) for c in consts]
    per_cluster = data_pc + [False] * len(consts)
    in_specs = _specs_for([jnp.shape(x) for x in inputs], per_cluster, bc)

    # Outputs: the per-cluster state leaves (same order/dtypes — the span
    # preserves storage dtypes, compact plans included) plus the schedule
    # pass's borrow outputs. The clock stays an input.
    out_tmpl = [jax.ShapeDtypeStruct(jnp.shape(x), x.dtype)
                for x in s_leaves]
    out_tmpl += [jax.ShapeDtypeStruct((C,), jnp.bool_),
                 jax.ShapeDtypeStruct((C, Q.NF), jnp.int32)]
    out_specs = _specs_for([s.shape for s in out_tmpl],
                           [True] * len(out_tmpl), bc)

    n_data = len(data_in)

    def body(*refs):
        ins, outs = refs[:len(inputs)], refs[len(inputs):]
        vals = [r[...] for r in ins]  # ONE load per column
        cvals = [jnp.reshape(v, sh)
                 for v, sh in zip(vals[n_data:], c_shapes)]
        out_vals = jax.core.eval_jaxpr(closed.jaxpr, cvals,
                                       *vals[:n_data])
        for ref, val in zip(outs, out_vals):
            ref[...] = val  # ONE store per column

    outs = pl.pallas_call(
        body,
        grid=(C // bc,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_tmpl,
        interpret=interp,
    )(*inputs)

    new_leaves = list(outs[:n_state])
    new_leaves.insert(t_pos, t_old)
    state2 = jax.tree_util.tree_unflatten(s_def, new_leaves)
    return state2, outs[n_state], outs[n_state + 1]


def span_boundary_bytes(cfg, state, arr_rows, arr_n,
                        tick_indexed: bool = True) -> dict:
    """The before/after instrument for the span collapse (compile-only;
    nothing runs): each span phase compiled as its OWN executable pays
    argument+output buffer-boundary traffic for the state columns it
    touches — that per-phase sum (``unfused_total``) against the ONE
    fused-span executable's boundary bytes (``fused``) is the measured
    form of "one load + one store per column". ``tools/cost_probe.py
    --fused`` records it per shape and ``bench.py --fused ab`` gates on
    ``fused < unfused_total`` strictly.

    ``state`` may be narrow (compact plan): the node columns are widened
    here exactly as the tick-entry widen would, so the executables match
    the mid-tick state the real span receives."""
    import dataclasses

    from multi_cluster_simulator_tpu.core.engine import Engine
    from multi_cluster_simulator_tpu.ops import fields as F

    eng = Engine(dataclasses.replace(cfg, fused="off"))
    eng_f = Engine(dataclasses.replace(cfg, fused="on"))
    params = eng._default_params
    if state.node_free.dtype != jnp.int32:
        state = state.replace(node_free=F.widen(state.node_free),
                              node_cap=F.widen(state.node_cap))
    t1 = state.t + cfg.tick_ms

    def bbytes(fn):
        ma = jax.jit(fn).lower(state, arr_rows, arr_n,
                               t1).compile().memory_analysis()
        # simlint: ignore[pallas-kernel] -- host-side compile-time probe:
        # memory_analysis returns plain Python stats on an already-
        # compiled executable, never a tracer (nothing here is traced)
        return int(ma.argument_size_in_bytes + ma.output_size_in_bytes)

    def phase_ingest(s, rows, cnt, tt):
        return eng._span_ingest_schedule(s, rows, cnt, tt, params,
                                         tick_indexed, do_ingest=True,
                                         do_schedule=False)[0]

    def phase_schedule(s, rows, cnt, tt):
        return eng._span_ingest_schedule(s, rows, cnt, tt, params,
                                         tick_indexed, do_ingest=False,
                                         do_schedule=True)

    def span(s, rows, cnt, tt):
        return fused_span(eng_f, s, rows, cnt, tt, params, tick_indexed)

    per_phase = {"ingest": bbytes(phase_ingest),
                 "schedule": bbytes(phase_schedule)}
    fused = bbytes(span)
    total = sum(per_phase.values())
    return {"unfused_per_phase": per_phase, "unfused_total": total,
            "fused": fused,
            "reduction": round(1.0 - fused / max(total, 1), 4)}
