"""Hand-written device kernels (Pallas).

One kernel so far: the fused per-cluster tick prefix — phases 1-5,
faults through schedule — as one ``pallas_call``
(``kernels/fused_tick.py``), gated by ``SimConfig.fused`` and pinned
bit-identical to the unfused XLA tick via the interpret-mode oracle
(ARCHITECTURE.md §fused tick kernel). simlint rule family 10
(``pallas-kernel``, LINTING.md §10) enforces the kernel-body discipline
for everything under this package.
"""

from multi_cluster_simulator_tpu.kernels.fused_tick import (  # noqa: F401
    FUSED_SPAN, block_clusters, engaged_span, fused_prefix,
    interpret_mode, is_active, provenance, span_boundary_bytes,
)
