"""Hand-written device kernels (Pallas).

One kernel so far: the fused ingest->schedule tick span
(``kernels/fused_tick.py``), gated by ``SimConfig.fused`` and pinned
bit-identical to the unfused XLA tick via the interpret-mode oracle
(ARCHITECTURE.md §fused tick kernel). simlint rule family 10
(``pallas-kernel``, LINTING.md §10) enforces the kernel-body discipline
for everything under this package.
"""

from multi_cluster_simulator_tpu.kernels.fused_tick import (  # noqa: F401
    FUSED_SPAN, block_clusters, fused_span, interpret_mode, is_active,
    provenance, span_boundary_bytes,
)
