"""The fault plane: deterministic node churn + job preemption/retry as data.

Two modules, no engine imports (core/state.py embeds ``FaultState`` in the
``SimState`` pytree, so this package must sit below core in the import
graph):

- ``faults.schedule`` — the ``FaultState`` pytree (per-cluster leaves, so
  it shards over the mesh with the rest of the state and needs zero new
  collectives), host-side schedule packing (``pack_fault_trace`` — the
  ``pack_arrivals_by_tick`` move applied to failures), the counter-based
  on-device exponential samplers for generative MTTF/MTTR churn, and the
  builders/reseeders the drivers call.
- ``faults.apply`` — the per-cluster fault phase the engine runs at tick
  entry (kill + requeue + capacity masking + repair), the next-event probe
  the time-compression leap bound folds in (a leap can never jump over a
  failure or a repair), and the quiescence-signature parts.

See ARCHITECTURE.md §fault plane.
"""

from multi_cluster_simulator_tpu.faults.apply import (  # noqa: F401
    fault_phase_local, next_fault_event_t, sig_parts,
)
from multi_cluster_simulator_tpu.faults.schedule import (  # noqa: F401
    FaultState, init_fault_state, initial_next_fail, pack_fault_trace,
    reseed,
)
