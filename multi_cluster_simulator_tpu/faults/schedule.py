"""Fault schedules as data: the ``FaultState`` pytree + its builders.

A failure schedule is a per-node alternating sequence of (fail, repair)
times. Both sources reduce to the same two device columns the fault phase
reads — ``next_fail`` (the clock of the next failure, NEVER when none is
scheduled) and ``down_until`` (the repair clock while down) — so the apply
core (faults/apply.py) is mode-blind; the mode only decides where the NEXT
interval comes from when one completes:

- **trace** — an explicit host-side event list packed once into per-node
  sorted interval tables ``fail_t``/``repair_t`` ([C, N, E], NEVER-padded)
  with the per-node cursor ``n_fails`` indexing them: the
  ``pack_arrivals_by_tick`` pattern applied to failures. Replay order,
  chunking, compression, and sharding are all invisible to it by
  construction — the tables ride the state.
- **generative** — on-device inverse-CDF exponential sampling
  (``dt = ceil(-mean * log(U))``, no rejection loops — the PR-7 lesson:
  ``jax.random``'s rejection-sampled distributions cost ~25x a whole tick
  under vmap) from COUNTER-BASED streams: draw k for node n of cluster c
  is ``fold_in(fold_in(fold_in(key_c, n), 2k + kind))``, a pure function
  of (cluster key, node, failure ordinal), never of the tick index or the
  driver — which is what makes generative churn bit-identical across
  dense/compressed/chunked/sharded execution (tests/test_faults.py).

Every leaf is per-cluster ([C, ...]), so the whole pytree shards over the
mesh's cluster axis with the rest of ``SimState`` (parallel/sharded_engine
``_state_specs``) and checkpoints with it (core/checkpoint.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from multi_cluster_simulator_tpu.config import FaultConfig

NEVER = jnp.int32(2**31 - 1)
# generative draws are clamped so ``t + dt`` stays far from int32 wrap even
# at the log(1/U) tail (U >= 1e-7 -> dt <= ~16.2 * mean)
_MAX_DT = jnp.int32(1 << 30)


@struct.dataclass
class FaultState:
    """Per-cluster node-churn state. ``health`` is the mask placement sees
    (a failed node also has ``node_active`` masked off and ``node_free``
    zeroed, so every existing feasibility/lend/carve path is failure-aware
    without a change); ``was_active`` remembers the pre-fail activation so
    repair restores a vacant virtual slot as vacant. Counters (``kills``,
    ``requeues``, ``down_ms``) are cumulative per cluster — the obs/ tap
    differences them like ``placed_total``."""

    health: jax.Array  # [C, N] bool — True = up
    was_active: jax.Array  # [C, N] bool — node_active at fail time
    next_fail: jax.Array  # [C, N] i32 — clock of the next failure (NEVER: none)
    down_until: jax.Array  # [C, N] i32 — repair clock while down (NEVER: up)
    down_since: jax.Array  # [C, N] i32 — fail clock of the current outage
    n_fails: jax.Array  # [C, N] i32 — completed outages (cursor + PRNG counter)
    kills: jax.Array  # [C] i32 — jobs killed by node failures
    requeues: jax.Array  # [C] i32 — killed jobs requeued (retry granted)
    down_ms: jax.Array  # [C] i32 — node downtime, closed at repair
    # trace-mode interval tables (E=1 NEVER-filled placeholders otherwise)
    fail_t: jax.Array  # [C, N, E] i32 — interval starts, NEVER-padded
    repair_t: jax.Array  # [C, N, E] i32 — interval ends
    key: jax.Array  # [C, 2] u32 — per-cluster generative stream root


def _exp_draws(key: jax.Array, counters: jax.Array, kind: int,
               mean_ms: int) -> jax.Array:
    """[N] exponential durations (ms, >= 1) for every node's draw ordinal
    ``counters`` — one inverse-CDF uniform per node, keys derived per
    (node, ordinal, kind). ``kind`` 0 = time-to-failure, 1 = time-to-repair
    (distinct substreams so the two sequences never collide)."""
    n = counters.shape[0]

    def draw(node, k):
        kk = jax.random.fold_in(jax.random.fold_in(key, node), 2 * k + kind)
        u = jax.random.uniform(kk, (), jnp.float32, 1e-7, 1.0)
        return jnp.ceil(-jnp.float32(mean_ms) * jnp.log(u))

    dt = jax.vmap(draw)(jnp.arange(n, dtype=jnp.int32), counters)
    return jnp.clip(dt, 1.0, _MAX_DT.astype(jnp.float32)).astype(jnp.int32)


def gather_event(table: jax.Array, cursor: jax.Array) -> jax.Array:
    """[N] entry ``table[n, cursor[n]]`` with NEVER past the last interval
    — the trace-mode next-interval lookup (single-cluster view)."""
    E = table.shape[-1]
    idx = jnp.clip(cursor, 0, E - 1)
    got = jnp.take_along_axis(table, idx[:, None], axis=-1)[:, 0]
    return jnp.where(cursor < E, got, NEVER)


def initial_next_fail(key: jax.Array, n_nodes: int, fc: FaultConfig,
                      eligible=None) -> jax.Array:
    """[N] first-failure clocks for one cluster in generative mode (draw
    ordinal 0, relative to t=0) — shared by ``init_fault_state``,
    ``reseed``, and the env auto-reset (envs/cluster_env.py), so a reset
    episode replays the exact schedule a fresh env with the same key
    sees. ``eligible`` [N] masks churn to REAL machines: phantom padded
    slots and vacant virtual slots get NEVER — generative churn models
    physical hardware failing (a node that does not exist cannot fail,
    and scheduling it anyway would both fabricate ``down_ms`` and force
    the leap driver to execute no-op ticks); trace mode can still name
    any slot explicitly."""
    nf = _exp_draws(key, jnp.zeros((n_nodes,), jnp.int32), 0, fc.mttf_ms)
    if eligible is None:
        return nf
    return jnp.where(jnp.asarray(eligible), nf, NEVER)


def pack_fault_trace(events: Sequence[tuple], C: int, N: int,
                     max_events: int) -> tuple[np.ndarray, np.ndarray]:
    """Pack an explicit ``(cluster, node, fail_t_ms, repair_t_ms)`` event
    list into the per-node sorted interval tables (host-side numpy, once
    per run — the arrivals-bucketing move). Intervals sort by fail time;
    adversarial orderings are allowed and well-defined (a repair at or
    before its fail makes a zero-length outage that still kills —
    PARITY.md §fault schedules). More than ``max_events`` intervals on one
    node fail fast rather than silently truncate."""
    fail = np.full((C, N, max_events), int(np.asarray(NEVER)), np.int32)
    repair = np.full((C, N, max_events), int(np.asarray(NEVER)), np.int32)
    per_node: dict[tuple, list] = {}
    for c, n, ft, rt in events:
        if not (0 <= c < C and 0 <= n < N):
            raise ValueError(f"fault event ({c}, {n}) outside [{C}, {N})")
        per_node.setdefault((int(c), int(n)), []).append((int(ft), int(rt)))
    for (c, n), ivals in per_node.items():
        if len(ivals) > max_events:
            raise ValueError(
                f"node ({c}, {n}) has {len(ivals)} fault intervals; "
                f"faults.max_events={max_events} — raise the bound")
        ivals.sort()
        for i, (ft, rt) in enumerate(ivals):
            fail[c, n, i] = ft
            repair[c, n, i] = rt
    return fail, repair


def init_fault_state(fc: FaultConfig, C: int, N: int,
                     events: Optional[Sequence[tuple]] = None,
                     eligible=None) -> FaultState:
    """The pristine all-healthy fault state for a fresh constellation.

    ``events`` supplies the trace-mode schedule (required iff
    ``fc.mode == "trace"`` and ``fc.enabled``); generative mode derives
    per-cluster keys from ``fc.seed`` + the GLOBAL cluster index, so the
    leaf carries each cluster's identity onto whatever shard it lands on,
    and samples first-failure clocks only for ``eligible`` [C, N] slots
    (``initial_next_fail`` — real machines, not padding/vacant virtual
    slots). With ``fc.enabled`` False the phase is statically skipped by
    the engine and these leaves are inert zeros-and-NEVERs."""
    E = max(int(fc.max_events), 1)
    never = np.full((C, N), int(np.asarray(NEVER)), np.int32)
    zeros_cn = np.zeros((C, N), np.int32)
    if fc.enabled and fc.mode == "trace":
        if events is None:
            raise ValueError("faults.mode='trace' needs an event list "
                             "(init_state(..., fault_events=...))")
        fail_t, repair_t = pack_fault_trace(events, C, N, E)
        next_fail = fail_t[:, :, 0].copy()
        keys = np.zeros((C, 2), np.uint32)
    else:
        fail_t = np.full((C, N, E), int(np.asarray(NEVER)), np.int32)
        repair_t = fail_t.copy()
        if fc.enabled:
            # one vectorized derivation for the whole constellation (a
            # per-cluster host loop would pay C tiny dispatches at init)
            root = jax.random.PRNGKey(fc.seed)
            keys = np.asarray(jax.vmap(
                lambda c: jax.random.fold_in(root, c))(
                    jnp.arange(C, dtype=jnp.int32)), np.uint32)
            elig = (jnp.ones((C, N), bool) if eligible is None
                    else jnp.asarray(eligible))
            next_fail = np.asarray(jax.vmap(
                lambda k, e: initial_next_fail(k, N, fc, e))(
                    jnp.asarray(keys), elig))
        else:
            keys = np.zeros((C, 2), np.uint32)
            next_fail = never.copy()
    zc = jnp.zeros((C,), jnp.int32)
    return FaultState(
        health=jnp.ones((C, N), bool),
        was_active=jnp.zeros((C, N), bool),
        next_fail=jnp.asarray(next_fail),
        down_until=jnp.asarray(never),
        down_since=jnp.asarray(zeros_cn),
        n_fails=jnp.asarray(zeros_cn),
        kills=zc, requeues=zc, down_ms=zc,
        fail_t=jnp.asarray(fail_t), repair_t=jnp.asarray(repair_t),
        key=jnp.asarray(keys))


def reseed(fs: FaultState, key: jax.Array, fc: FaultConfig,
           eligible=None) -> FaultState:
    """Re-derive a pristine generative fault state from a fresh root key —
    the environment mode's per-env churn (envs/cluster_env.py reset):
    every env folds its own reset key into the per-cluster streams, so a
    batch of envs trains under INDEPENDENT failure patterns. ``eligible``
    [C, N] masks churn to real machines (see ``initial_next_fail``).
    Traced-safe (pure jnp/jax.random on the existing leaf shapes)."""
    C, N = fs.health.shape
    keys = jax.vmap(lambda c: jax.random.fold_in(key, c))(
        jnp.arange(C, dtype=jnp.int32))
    elig = (jnp.ones((C, N), bool) if eligible is None
            else jnp.asarray(eligible))
    next_fail = jax.vmap(lambda k, e: initial_next_fail(k, N, fc, e))(
        keys, elig)
    return fs.replace(
        health=jnp.ones((C, N), bool),
        was_active=jnp.zeros((C, N), bool),
        next_fail=next_fail,
        down_until=jnp.full((C, N), NEVER, jnp.int32),
        down_since=jnp.zeros((C, N), jnp.int32),
        n_fails=jnp.zeros((C, N), jnp.int32),
        kills=jnp.zeros((C,), jnp.int32),
        requeues=jnp.zeros((C,), jnp.int32),
        down_ms=jnp.zeros((C,), jnp.int32),
        key=keys)
