"""The fault phase: kill, requeue, mask, repair — one cluster per call.

Runs at tick entry (core/engine.py ``_span_prefix`` phase 1, before
completions), vmapped over the cluster axis like every per-cluster
phase. As the opening phase of the fused per-cluster prefix it replays
INSIDE the Pallas kernel when ``cfg.fused`` engages and faults are
enabled (kernels/fused_tick.py: the engaged span starts at "faults") —
same function, block-resident values, bit-identical by construction.
Semantics, each documented in PARITY.md §fault schedules:

- **Failures before completions.** A job whose ``end_t`` falls on the same
  tick its node fails is killed, not completed — the failure took the node
  before the tick's release phase observed it.
- **Failures before repairs.** Within one tick, every due failure applies,
  then every due repair — so a same-tick fail+repair (a zero-length trace
  interval, or a malformed repair<=fail pair) is a zero-length outage that
  still kills and still counts one ``n_fails``.
- **Kill = requeue with a bumped retry budget.** Killed rows whose
  ``retries < max_retries`` re-enter a queue with ``enq_t = t`` (the wait
  clock restarts; the reference's WaitTime is per-enqueue),
  ``rec_wait = 0``, ``retries + 1``, and owner preserved. OWN jobs go to
  the policy's ingest queue (Level0 for the queue-sweep families,
  ReadyQueue for FIFO — the same target dispatch as the arrival phase);
  jobs a peer lent me (owner >= 0) go back to the LENT queue — where
  foreign jobs live in the reference — so a killed foreign job is
  re-placed best-effort and, when it finally completes, returns to its
  borrower like any lent job (never via the wait queue, where a second
  borrow would overwrite its ownership). Rows at the budget count into
  ``drops.failed`` instead. Trader carve placeholders (owner == FOREIGN
  == -2) are not jobs: they die with the node and are not requeued — the
  carved capacity returns to the seller at repair while the buyer keeps
  its virtual node (the reference never reconciles a broken contract
  either).
- **Capacity masks out, repair restores an empty node.** A failed node's
  ``node_free`` zeroes and ``node_active`` drops (every feasibility,
  lend, carve, and utilization path is already active-gated, so the whole
  policy zoo is failure-aware with no kernel change); ``was_active``
  remembers the pre-fail activation so repair restores a vacant virtual
  slot as vacant and an occupied node as ``free = cap`` (all its jobs
  were killed at fail time). Virtual-node ATTACH additionally skips
  unhealthy slots (market/trader.py buyer_apply, services/host_ops.py) —
  a down slot must not be reclaimed by a new contract mid-outage.

All arithmetic is int32 on widened loads (the engine widens compact node
storage before this phase); requeued rows go through the checked
``Q.push_many`` stores, so the compact layouts stay bit-identical to wide
(tests/test_faults.py pins the full parity matrix).
"""

from __future__ import annotations

import jax.numpy as jnp

from multi_cluster_simulator_tpu.config import SimConfig
from multi_cluster_simulator_tpu.faults import schedule as fsched
from multi_cluster_simulator_tpu.faults.schedule import NEVER, FaultState
from multi_cluster_simulator_tpu.ops import fields as F
from multi_cluster_simulator_tpu.ops import queues as Q
from multi_cluster_simulator_tpu.ops import runset as R

FOREIGN = jnp.int32(-2)  # market/trader.py's carve-placeholder owner


def next_fault_event_t(fs: FaultState) -> jnp.ndarray:
    """Earliest future fault event across the (local) constellation: an up
    node's next failure or a down node's repair. Folded into the
    time-compression leap bound (core/engine.py ``_next_event_t``) — a
    leap can never jump over a failure or a repair."""
    return jnp.min(jnp.where(fs.health, fs.next_fail, fs.down_until))


def sig_parts(state) -> list:
    """Fault-plane terms of the quiescence fingerprint
    (core/engine.py ``_quiescence_sig``): health membership, completed
    outages, and the kill/requeue counters — so a tick that only fails or
    repairs an (empty) node can never be judged quiescent, and the
    closed-form leap accrual stays exact between fault events."""
    fs = state.faults
    return [jnp.sum(fs.health.astype(jnp.int32)), jnp.sum(fs.n_fails),
            jnp.sum(fs.kills) + jnp.sum(fs.requeues)]


def fault_phase_local(s, t, cfg: SimConfig, to_delay: bool):
    """One cluster's fault phase (vmapped by the engine). ``t`` is this
    tick's clock; ``to_delay`` the policy's ingest target (static per
    compiled branch, exactly like the arrival phase)."""
    fc = cfg.faults
    fs = s.faults
    N = fs.health.shape[0]
    t = jnp.asarray(t, jnp.int32)
    trace_mode = fc.mode == "trace"

    # ---- failures due this tick ----
    fail_now = jnp.logical_and(fs.health, fs.next_fail <= t)  # [N]
    run = s.run
    # which running slots sit on a newly-failed node (one-hot contraction,
    # not a gather — the phase is vmapped over thousands of clusters)
    node_hot = (run.node[:, None]
                == jnp.arange(N, dtype=jnp.int32)[None, :])  # [S, N]
    on_failed = jnp.einsum("sn,n->s", node_hot.astype(jnp.int32),
                           fail_now.astype(jnp.int32)) > 0
    killed = jnp.logical_and(run.active, on_failed)  # [S]
    is_job = jnp.logical_and(killed, run.owner != FOREIGN)
    retryable = jnp.logical_and(is_job, run.retries < jnp.int32(fc.max_retries))
    exhausted = jnp.sum(jnp.logical_and(
        is_job, run.retries >= jnp.int32(fc.max_retries))).astype(jnp.int32)
    # foreign jobs I host (owner >= 0, the FIFO borrowing path) requeue
    # into the LENT queue; my own jobs into the policy's ingest target
    to_lent = jnp.logical_and(retryable, run.owner >= 0)
    to_ingest = jnp.logical_and(retryable, run.owner < 0)
    n_req = jnp.sum(retryable).astype(jnp.int32)
    n_ing = jnp.sum(to_ingest).astype(jnp.int32)

    # requeued rows in the queue schema: identity + demand from the run
    # row, the wait clock restarted at t, the retry budget bumped
    zeros = jnp.zeros_like(run.id)
    vals = {"id": run.id, "cores": run.cores, "mem": run.mem,
            "gpu": run.gpu, "dur": run.dur, "enq_t": jnp.full_like(run.id, t),
            "owner": run.owner, "rec_wait": zeros,
            "jclass": F.job_class(run.cores, run.gpu),
            "retries": run.retries + 1}
    rows = jnp.stack([vals[n] for n in F.QUEUE_FIELDS],
                     axis=-1).astype(jnp.int32)  # [S, NF]
    batch = Q.JobQueue(data=rows, count=n_req)

    run = R.kill(run, killed)
    tgt = s.l0 if to_delay else s.ready
    dropped = Q.push_many_dropped(tgt, to_ingest)
    tgt = Q.push_many(tgt, batch, to_ingest)
    ldropped = Q.push_many_dropped(s.lent, to_lent)
    lent = Q.push_many(s.lent, batch, to_lent)
    s = s.replace(
        run=run, lent=lent,
        drops=s.drops.replace(queue=s.drops.queue + dropped + ldropped,
                              failed=s.drops.failed + exhausted))
    if to_delay:
        # mirror the arrival phase's DELAY-side accounting: a requeue is a
        # re-arrival for the WaitTime stats (server.go:75-76 analogue)
        s = s.replace(l0=tgt, wait_jobs=s.wait_jobs + n_ing,
                      jobs_in_queue=s.jobs_in_queue + n_ing)
    else:
        s = s.replace(ready=tgt)

    # node bookkeeping: capacity out, activation parked, outage opened
    free = jnp.where(fail_now[:, None], 0, s.node_free)
    was_active = jnp.where(fail_now, s.node_active, fs.was_active)
    active = jnp.logical_and(s.node_active, jnp.logical_not(fail_now))
    if trace_mode:
        du_new = fsched.gather_event(fs.repair_t, fs.n_fails)
    else:
        du_new = t + fsched._exp_draws(fs.key, fs.n_fails, 1, fc.mttr_ms)
    down_until = jnp.where(fail_now, du_new, fs.down_until)
    next_fail = jnp.where(fail_now, NEVER, fs.next_fail)
    down_since = jnp.where(fail_now, t, fs.down_since)
    health = jnp.logical_and(fs.health, jnp.logical_not(fail_now))
    kills = fs.kills + jnp.sum(is_job).astype(jnp.int32)
    requeues = fs.requeues + n_req

    # ---- repairs due this tick (after failures: a same-tick pair is a
    # zero-length outage that still kills) ----
    rep_now = jnp.logical_and(jnp.logical_not(health), down_until <= t)
    active = jnp.where(rep_now, was_active, active)
    # the node comes back EMPTY (everything on it was killed at fail
    # time), so restored free is simply the capacity
    free = jnp.where(rep_now[:, None], s.node_cap, free)
    down_ms = fs.down_ms + jnp.sum(
        jnp.where(rep_now, t - down_since, 0)).astype(jnp.int32)
    n_fails = fs.n_fails + rep_now.astype(jnp.int32)
    if trace_mode:
        nf_new = fsched.gather_event(fs.fail_t, n_fails)
    else:
        nf_new = t + fsched._exp_draws(fs.key, n_fails, 0, fc.mttf_ms)
    next_fail = jnp.where(rep_now, nf_new, next_fail)
    down_until = jnp.where(rep_now, NEVER, down_until)
    health = jnp.logical_or(health, rep_now)

    return s.replace(
        node_free=free, node_active=active,
        faults=fs.replace(health=health, was_active=was_active,
                          next_fail=next_fail, down_until=down_until,
                          down_since=down_since, n_fails=n_fails,
                          kills=kills, requeues=requeues, down_ms=down_ms))
