from multi_cluster_simulator_tpu.market.trader import trade_round, FOREIGN

__all__ = ["trade_round", "FOREIGN"]
