"""The convex market kernel: assignment-LP pricing by descending-price
dual ascent, cheap enough for every serving tick (ROADMAP item 1).

The buyer<->seller contract round the trader market runs is, underneath
the protocol, one linear program — the assignment relaxation

    max  <score, x>
    s.t. sum_b x[s, b] <= 1   (each seller carves one contract per round)
         sum_s x[s, b] <= 1   (each buyer attaches one virtual node)
         0 <= x <= 1,  x = 0 outside the feasibility mask

over the same [seller, buyer] feasibility matrix ``_match_sinkhorn``
builds (trader._pair_feasibility — ApproveTrade + sane-carve capacity).
CvxCluster's observation (arxiv 2605.01614) is that this granular
allocation LP decomposes per cluster: each cluster owns one primal ROW of
``x`` and one seller dual, and the clusters couple only through the buyer
prices — a [C_tot] vector that reduces across the mesh. That is exactly
this codebase's idiom already: shard-local [s_loc, C_tot] rows, collective
column sums through ``ex.allsum``, nothing [C_tot, C_tot] replicated.

The solve is a FIXED-ITERATION primal-dual loop (``lax.scan`` over the
static trip count ``cfg.trader.cvx_iters`` — no data-dependent
``while_loop``, the PR-7 rejection-sampler lesson, machine-checked by
simlint rule family 11):

- primal: ``x = clip(step * (score - lam[b] - mu[s]), 0, 1) * feas`` —
  the exact best response to the prox-regularized Lagrangian (sharpness
  ``step`` = 1/delta), memoryless in x, so the plan is a pure function of
  the prices;
- dual: prices move by ``rho/(1+i) * clip(violation, -1, 1)`` and
  project to >= 0 — the clip bounds one iteration's move, so the loop is
  a simultaneous Dutch auction: prices OPEN AT THE SCORE CEILING (every
  pair unprofitable) and fall toward market clearing, rising again only
  where a buyer is oversubscribed. Opening at zero instead would
  saturate every feasible ``x`` to 1 in the first iteration and the
  rounding would collapse degenerately (the reason ascent-from-zero is
  the wrong shape here). The harmonic decay is load-bearing — see the
  schedule note below.

Active depth, sharpness, price step and warm-start smoothing are traced
``PolicyParams`` leaves (``mkt_iters``/``mkt_step``/``mkt_rho``/
``mkt_smooth`` — trader.MktHyper), so a tournament sweeps pricing
solvers like any other policy axis within the one compiled program.

Rounding to integer contracts is the shared deterministic rule in
``trader._round_plan_to_matching`` (documented in MARKET.md §"The
rounding rule"); determinism across compact storage, time compression,
chunking, faults, the 8-device mesh and checkpoint cuts rides the same
pins ``_match_sinkhorn`` carries (tests/test_market_cvx.py). The scipy
``linprog`` oracle gate (small shape, exact integer contracts) lives in
the same test file; tools/market_ab.py measures the three-way quality
A/B this kernel must win.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.market import trader as T

# Tie-break scale for the deterministic per-pair jitter added to the
# normalized value (trader._pair_jitter): far below any real value
# difference, large enough to keep the rounding's argmax off exact-tie
# boundaries (the same role eps/2 plays for the sinkhorn kernel).
JITTER_SCALE = 0.0001
# The opening price: one jitter band above the score ceiling (values
# normalize to <= 1 plus the jitter), so every pair opens unprofitable.
PRICE_CEIL = 1.0 + 2.0 * JITTER_SCALE
# The dual step schedule is HARMONIC: iteration i moves a price by at
# most rho / (1 + i). Subgradient ascent needs a divergent-sum,
# vanishing-step schedule to actually reach the optimal prices —
# geometric cooling freezes the prices wherever the sweep ran out
# (remaining movement after iteration i is bounded by a constant times
# DECAY^i, so a late augmenting-path correction that needs one price to
# travel can never happen), and a fixed step orbits a limit cycle (the
# price bounces across the primal band 1/step, the plan slams 0 <-> 1).
# Harmonic gives both: total sweep rho * H(n) ~ rho * ln(n) diverges
# (an unmatched buyer's price always reaches zero), while the step
# vanishes so the equilibrium sharpens. Against the scipy LP oracle
# this is the difference between ~40% and 0% mismatched matchings
# (tests/test_market_cvx.py).


def solve_prices(feas, score, lam0, hp, n_iters, ex):
    """The fixed-iteration descending-price solve. ``feas``/``score`` are
    the shard-local [s_loc, C_tot] rows, ``lam0`` the [C_tot] opening
    buyer prices (replicated — derived from gathered state), ``hp`` a
    trader.MktHyper, ``n_iters`` the STATIC scan length (``hp.iters``
    masks the active depth inside it). Returns (x [s_loc, C_tot] plan,
    lam [C_tot] closing buyer prices). Every cross-shard quantity reduces
    through ``ex.allsum`` (deterministic fixed-order combining), so the
    prices — and therefore the plan — are identical on every shard."""
    C_loc, C_tot = feas.shape
    fmask = feas.astype(jnp.float32)
    x0 = jnp.zeros((C_loc, C_tot), jnp.float32)
    mu0 = jnp.zeros((C_loc,), jnp.float32)

    def step(carry, i):
        x, lam, mu = carry
        act = i < hp.iters  # masked active depth (traced, sweepable)
        # primal best response to the current prices (prox sharpness 1/step)
        g = score - lam[None, :] - mu[:, None]
        x2 = jnp.clip(hp.step * g, 0.0, 1.0) * fmask
        # clipped, harmonically decayed dual updates: iteration i moves a
        # price at most rho / (1 + i) in either direction
        rho_i = hp.rho / (1.0 + i.astype(jnp.float32))
        col = ex.allsum(jnp.sum(x2, axis=0)) - 1.0  # buyer oversubscription
        row = jnp.sum(x2, axis=1) - 1.0  # seller oversubscription
        lam2 = jnp.maximum(lam + rho_i * jnp.clip(col, -1.0, 1.0), 0.0)
        mu2 = jnp.maximum(mu + rho_i * jnp.clip(row, -1.0, 1.0), 0.0)
        return (jnp.where(act, x2, x), jnp.where(act, lam2, lam),
                jnp.where(act, mu2, mu)), None

    (x, lam, _), _ = jax.lax.scan(step, (x0, lam0, mu0),
                                  jnp.arange(n_iters, dtype=jnp.int32))
    return x, lam


def match_cvx(state, tr, t, mcfg, ex, gidx, g_buyer, g_con, hp):
    """MatchKind.CVX: the same signature contract as the other matchers
    plus the refreshed [C_loc] buyer-price column. Feasibility, value,
    jitter and rounding are the sinkhorn kernel's own helpers — the two
    backends price the identical market and differ only in the solver
    between feasibility and rounding."""
    C_tot = g_buyer.shape[0]
    feas = T._pair_feasibility(state, tr, t, mcfg, gidx, g_buyer, g_con)
    v = T._pair_value(g_con)
    score = v[None, :] + T._pair_jitter(gidx, C_tot) * jnp.float32(JITTER_SCALE)

    # warm start: blend last round's closing prices into the opening (a
    # smooth of 0 — the default — multiplies the stored price by zero:
    # cold start from the ceiling, bit-independent of the carried column)
    g_price = ex.gather(tr.mkt_price)  # [C_tot]
    lam0 = hp.smooth * g_price + (1.0 - hp.smooth) * jnp.float32(PRICE_CEIL)
    x, lam = solve_prices(feas, score, lam0, hp, mcfg.cvx_iters, ex)

    winner, csel, amounts, win_sell = T._round_plan_to_matching(
        state, x, feas, gidx, g_con, ex)
    new_price = lam[gidx]  # this shard's clusters' closing buyer prices
    return winner, csel, amounts, win_sell, tr.seller_locked_until, new_price
