"""The trader resource market as one batched round — pkg/trader re-designed.

The reference runs one trader process per cluster: a 10 s monitor evaluates
request policies against streamed cluster state, sizes a contract from the
scheduler's Level1 backlog, fans RequestResource out to every peer trader,
collects approvals in a price min-heap, and walks the heap calling
ApproveContract until a seller successfully carves a virtual node
(trader.go:280-325, 193-278; trader/server.go:31-85). Here the entire round —
every cluster simultaneously as buyer and seller — is a handful of [C]- and
[C, C]-shaped array ops inside the jitted tick: offer collection becomes a
masked argmin over the seller axis, which lowers to collectives when the
cluster axis is sharded. MARKET.md documents the deterministic semantics and
every divergence from the Go races.

Phase structure of one round (MARKET.md):
  buyers:  policy check (snapshot state) -> contract sizing (Level1) ->
  sellers: one-request-per-round lock -> ApproveTrade predicate ->
           carve feasibility ->
  match:   per buyer, lowest approving seller index whose carve succeeds
           (all offers echo the buyer's price, trader/server.go:44, so the
           reference's price heap degenerates to arrival order — we
           determinize to seller index) ->
  apply:   seller occupies carved amounts as Foreign placeholder jobs;
           buyer activates a virtual node slot; cooldowns + locks update.
"""

from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from multi_cluster_simulator_tpu.config import MatchKind, SimConfig
from multi_cluster_simulator_tpu.core.spec import CORES, GPU, MEM
from multi_cluster_simulator_tpu.core.state import SimState
from multi_cluster_simulator_tpu.ops import carve as carve_ops
from multi_cluster_simulator_tpu.ops import sizing
from multi_cluster_simulator_tpu.ops import runset as R

FOREIGN = jnp.int32(-2)  # owner sentinel: Ownership == "Foreign" (cluster.go:116)
PLACEHOLDER_ID = jnp.int32(-3)


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _tree_take(tree, idx):
    return jax.tree.map(lambda x: x[idx], tree)


class MktHyper(typing.NamedTuple):
    """The traced solver hyperparameters one market round runs with —
    PolicyParams ``mkt_*`` leaves when a params pytree is threaded (every
    engine path), the TraderConfig constants otherwise. Iteration counts
    are ACTIVE counts masked inside the static scan lengths the config
    compiles (cfg.trader.sinkhorn_iters / cvx_iters): the trip count is
    shape, the effective depth is sweepable data."""

    sink_iters: jax.Array  # [] i32
    sink_eps: jax.Array  # [] f32
    iters: jax.Array  # [] i32 — cvx active iterations
    step: jax.Array  # [] f32 — cvx primal sharpness (1/delta)
    rho: jax.Array  # [] f32 — cvx price step
    smooth: jax.Array  # [] f32 — cvx price carry-over


def market_hyper(mcfg, params=None) -> MktHyper:
    if params is None:
        return MktHyper(sink_iters=jnp.int32(mcfg.sinkhorn_iters),
                        sink_eps=jnp.float32(mcfg.sinkhorn_eps),
                        iters=jnp.int32(mcfg.cvx_iters),
                        step=jnp.float32(mcfg.cvx_step),
                        rho=jnp.float32(mcfg.cvx_rho),
                        smooth=jnp.float32(mcfg.cvx_smooth))
    return MktHyper(sink_iters=params.mkt_sink_iters,
                    sink_eps=params.mkt_sink_eps,
                    iters=params.mkt_iters, step=params.mkt_step,
                    rho=params.mkt_rho, smooth=params.mkt_smooth)


def trade_round(state: SimState, t, cfg: SimConfig, ex, params=None) -> SimState:
    mcfg = cfg.trader
    do = (t % mcfg.monitor_period_ms) == 0
    return jax.lax.cond(do, lambda s: _round(s, t, cfg, ex, params),
                        lambda s: s, state)


def next_cadence_t(t, mcfg) -> jax.Array:
    """The next virtual time strictly after ``t`` at which the market can
    act: the 5 s state-stream refresh (phase 6 snapshot) or the 10 s
    monitor wakeup (this round gate). Between consecutive boundaries both
    phases are data-independent no-ops, which is what lets the
    event-compressed driver (core/engine.py run_compressed) leap straight
    to the boundary."""
    nxt = lambda c: (t // jnp.int32(c) + 1) * jnp.int32(c)
    return jnp.minimum(nxt(mcfg.state_cadence_ms), nxt(mcfg.monitor_period_ms))


def _match_greedy(state: SimState, tr, t, mcfg, ex, gidx, g_buyer, g_con):
    """The reference's negotiation, determinized (trader.go:193-278): each
    seller evaluates only its lowest-index requesting buyer (the
    one-contract-at-a-time lock, trader/server.go:36-44); per buyer the
    lowest approving seller whose carve succeeds wins. Returns
    (winner [C_tot] global seller idx or INF, csel per-local-seller
    Contract, amounts [C_loc, N, RES], win_sell [C_loc], new_lock)."""
    C_loc = gidx.shape[0]
    C_tot = g_buyer.shape[0]
    INF = jnp.int32(2**31 - 1)
    bidx = jnp.arange(C_tot, dtype=jnp.int32)

    # ---- sellers (local): one-request-per-round lock + ApproveTrade ----
    locked = tr.seller_locked_until > t
    req = jnp.logical_and(g_buyer[None, :], gidx[:, None] != bidx[None, :])  # [s_loc, b]
    has_req = jnp.any(req, axis=1)
    b_first = jnp.argmax(req, axis=1).astype(jnp.int32)  # lowest global buyer
    process = jnp.logical_and(has_req, jnp.logical_not(locked))

    csel = _tree_take(g_con, b_first)  # the contract each local seller evaluates
    # ApproveTrade (trader.go:141-167), all in float32 against the snapshot:
    tot_c = tr.snap_total_cores.astype(jnp.float32)
    tot_m = tr.snap_total_mem.astype(jnp.float32)
    avail_c = tot_c - tot_c * tr.snap_core_util
    avail_m = tot_m - tot_m * tr.snap_mem_util
    t_sec = csel.time_ms.astype(jnp.float32) / 1000.0
    incentive = (jnp.float32(mcfg.min_core_incentive) * csel.cores.astype(jnp.float32) * t_sec
                 + jnp.float32(mcfg.min_mem_incentive) * csel.mem.astype(jnp.float32) * t_sec)
    approve_ok = jnp.logical_and(
        jnp.logical_and(tr.snap_core_util < mcfg.approve_core_threshold,
                        tr.snap_mem_util < mcfg.approve_mem_threshold),
        jnp.logical_and(jnp.logical_and(avail_c >= csel.cores.astype(jnp.float32),
                                        avail_m >= csel.mem.astype(jnp.float32)),
                        csel.price >= incentive))
    approve = jnp.logical_and(process, approve_ok)

    # ---- carve feasibility (ApproveContract -> ProvideVirtualNode) ----
    amounts, carve_ok = jax.vmap(
        lambda free, act, ccon: carve_ops.carve_plan(
            free, act, ccon.cores, ccon.mem, ccon.gpu, mode=mcfg.carve_mode)
    )(state.node_free, state.node_active, csel)  # [C_loc, N, RES], [C_loc]

    # ---- match: per buyer, lowest approving seller whose carve succeeds;
    # the min-reduction is the collective form of the offer heap ----
    cand_ok = jnp.logical_and(approve, carve_ok)  # [s_loc]
    wmat = jnp.full((C_loc, C_tot), INF, jnp.int32).at[
        jnp.arange(C_loc), b_first].set(jnp.where(cand_ok, gidx, INF))
    winner = ex.allmin(jnp.min(wmat, axis=0))  # [C_tot] global seller idx
    has_winner = winner < INF
    # sellers the buyer called ApproveContract on: every candidate up to and
    # including the winner (heap fall-through, trader.go:265-276); all
    # candidates if none carved. Their currentContract resets immediately
    # (trader/server.go:83); non-attempted approvers stay locked until TTL.
    attempted_any = jnp.logical_and(
        approve, jnp.where(has_winner[b_first], gidx <= winner[b_first], True))

    new_lock = jnp.where(process, t + mcfg.contract_ttl_ms, tr.seller_locked_until)
    new_lock = jnp.where(attempted_any, 0, new_lock)

    win_sell = jnp.logical_and(cand_ok, winner[b_first] == gidx)
    return winner, csel, amounts, win_sell, new_lock


def _pair_feasibility(state: SimState, tr, t, mcfg, gidx, g_buyer, g_con):
    """The [s_loc, b] feasibility matrix the batched matchers (sinkhorn,
    cvx) share: ApproveTrade against the snapshot (thresholds, available
    capacity, price >= incentive, seller not locked) AND sane-carve
    capacity (total free over active nodes covers the request, per
    resource including gpu) AND the pair is (requesting buyer, not self).
    One definition so the two solvers price the identical market."""
    bidx = jnp.arange(g_buyer.shape[0], dtype=jnp.int32)
    locked = tr.seller_locked_until > t

    thresh_ok = jnp.logical_and(tr.snap_core_util < mcfg.approve_core_threshold,
                                tr.snap_mem_util < mcfg.approve_mem_threshold)
    tot_c = tr.snap_total_cores.astype(jnp.float32)
    tot_m = tr.snap_total_mem.astype(jnp.float32)
    avail_c = tot_c - tot_c * tr.snap_core_util  # [s_loc]
    avail_m = tot_m - tot_m * tr.snap_mem_util
    t_sec = g_con.time_ms.astype(jnp.float32) / 1000.0  # [b]
    incentive = (jnp.float32(mcfg.min_core_incentive) * g_con.cores.astype(jnp.float32)
                 + jnp.float32(mcfg.min_mem_incentive) * g_con.mem.astype(jnp.float32)) * t_sec
    approve = jnp.logical_and(
        jnp.logical_and(thresh_ok, jnp.logical_not(locked))[:, None],
        jnp.logical_and(
            jnp.logical_and(avail_c[:, None] >= g_con.cores[None, :].astype(jnp.float32),
                            avail_m[:, None] >= g_con.mem[None, :].astype(jnp.float32)),
            (g_con.price >= incentive)[None, :]))
    # sane-carve feasibility: total free (active nodes) covers the request,
    # per resource including gpu
    tot_free = jnp.sum(jnp.where(state.node_active[..., None],
                                 jnp.maximum(state.node_free, 0), 0),
                       axis=1)  # [s_loc, RES]
    req = jnp.stack([g_con.cores, g_con.mem, g_con.gpu], axis=-1)  # [b, RES]
    cap_ok = jnp.all(tot_free[:, None, :] >= req[None, :, :], axis=-1)
    return jnp.logical_and(jnp.logical_and(approve, cap_ok),
                           jnp.logical_and(g_buyer[None, :],
                                           gidx[:, None] != bidx[None, :]))


def _pair_value(g_con):
    """Buyer value: normalized resource volume (what a matched contract is
    worth); sellers are symmetric, the solver iterations spread buyers
    across them."""
    v = (g_con.cores.astype(jnp.float32)
         + g_con.mem.astype(jnp.float32) / 1024.0
         + 4.0 * g_con.gpu.astype(jnp.float32))
    return v / jnp.maximum(jnp.max(v), 1.0)


def _pair_jitter(gidx, C_tot):
    """Deterministic per-pair jitter in [0, 1) breaking exact ties
    (identical contracts from several buyers would otherwise produce
    identical plan columns and the argmax rounding would collapse every
    buyer onto one seller); callers scale it well under their value scale
    so it only decides degenerate cases. Rows index GLOBAL seller ids so
    every shard derives the same values."""
    sidx = gidx.astype(jnp.float32)
    bfdx = jnp.arange(C_tot, dtype=jnp.float32)
    return jnp.abs(jnp.modf(jnp.sin(sidx[:, None] * 12.9898
                                    + bfdx[None, :] * 78.233) * 43758.5453)[0])


def _round_plan_to_matching(state: SimState, plan, feas, gidx, g_con, ex):
    """The deterministic rounding rule both fractional matchers share
    (MARKET.md §"The rounding rule"): each buyer claims its argmax-plan
    feasible seller — an ``allmax`` of column maxima, ties resolved to the
    LOWEST global seller index via ``allmin`` — then each claimed seller
    keeps its highest-plan claimant, the sane carve re-checks, and the
    committed winner index min-reduces across shards. Returns
    (winner [C_tot] global seller or INF, csel, amounts, win_sell)."""
    INF = jnp.int32(2**31 - 1)
    any_s = ex.allmax(jnp.any(feas, axis=0).astype(jnp.int32)) > 0  # [b]
    colmax = ex.allmax(jnp.max(jnp.where(feas, plan, -1.0), axis=0))  # [b]
    at_max = jnp.logical_and(feas, plan >= colmax[None, :])
    cand = ex.allmin(jnp.min(jnp.where(at_max, gidx[:, None], INF), axis=0))
    cand = jnp.where(any_s, cand, INF)
    claim = jnp.logical_and(cand[None, :] == gidx[:, None], feas)  # [s_loc, b]
    best_b = jnp.argmax(jnp.where(claim, plan, -1.0), axis=1).astype(jnp.int32)
    seller_matched = jnp.any(claim, axis=1)

    # ---- local seller views + actual carve (sane mode is exactly the
    # cap_ok feasibility test, so carve_ok holds for every matched seller) ----
    sel_b = best_b  # my sellers' chosen buyers (rows are already local)
    win_sell = seller_matched
    csel = _tree_take(g_con, sel_b)
    amounts, carve_ok = jax.vmap(
        lambda free, act, ccon: carve_ops.carve_plan(
            free, act, ccon.cores, ccon.mem, ccon.gpu, mode="sane")
    )(state.node_free, state.node_active, csel)
    win_sell = jnp.logical_and(win_sell, carve_ok)

    # winner[b] = the global seller that committed to b (INF = unmatched),
    # assembled from local commitments and min-reduced across shards
    C_tot = feas.shape[1]
    local_winner = jnp.full((C_tot,), INF, jnp.int32).at[
        jnp.where(win_sell, sel_b, C_tot)].set(
        jnp.where(win_sell, gidx, INF), mode="drop")
    winner = ex.allmin(local_winner)
    return winner, csel, amounts, win_sell


def _match_sinkhorn(state: SimState, tr, t, mcfg, ex, gidx, g_buyer, g_con, hp):
    """Batched optimal-transport matching (BASELINE config 4) — the upgrade
    over the greedy heap: instead of each seller seeing only its first
    requesting buyer, the full (seller × buyer) feasibility matrix enters an
    entropic-regularized assignment relaxation (Sinkhorn iterations over the
    doubly-stochastic constraint set), then rounds to a one-to-one matching.
    One round can match as many buyer/seller pairs as feasibility allows,
    where the greedy protocol strands every seller whose first buyer was
    taken (see tests/test_sinkhorn.py for the 2-buyer/2-seller case).

    Divergences from the greedy path (all deliberate, matching is an
    *upgrade* knob, not a parity mode):
    - carve semantics are ``sane`` (min(req, avail) per node) — feasibility
      is exactly "total free >= request", which batches; the as-built
      abs-diff walk does not admit a closed-form feasibility test;
    - no seller TTL locks: a matched seller's capacity is committed in the
      same tick, so the lock protocol that serializes the Go negotiation
      has nothing to protect;
    - the gpu axis participates in capacity feasibility (3-dim resources).

    Sharding: every array stays row-sharded as (local sellers × all
    buyers) — nothing [C_tot, C_tot] is ever replicated (a 16k-cluster
    mesh would otherwise hold a 1 GB kernel per shard). The per-iteration
    column reduction rides ``ex.allsum`` (deterministic fixed-order
    combining) and the rounding's per-buyer argmax is an ``allmax`` of
    column maxima + ``allmin`` of the best seller index. Decisions are
    deterministic for a given mesh topology; across topologies the
    cross-shard float-sum grouping can differ in the last ulp, which the
    deterministic per-pair jitter (spaced ~eps/2 apart) keeps away from
    decision boundaries — the sharded tests pin decision equality on the
    8-device mesh (tests/test_sinkhorn.py::test_sinkhorn_sharded_equals_local).
    On a single device the exchange ops are identities and this computes
    exactly the replicated form.
    """
    C_loc = gidx.shape[0]
    C_tot = g_buyer.shape[0]

    feas = _pair_feasibility(state, tr, t, mcfg, gidx, g_buyer, g_con)

    # ---- shard-local kernel rows [s_loc, C_tot]; Sinkhorn iterations ----
    # the jitter is kept well under the value scale (~eps/2) so it only
    # decides degenerate cases
    v = _pair_value(g_con)
    eps = hp.sink_eps
    score = v[None, :] + _pair_jitter(gidx, C_tot) * (0.5 * eps)
    K = jnp.where(feas, jnp.exp(score / eps), 0.0)  # [s_loc, C_tot]
    tiny = jnp.float32(1e-30)

    def sink_step(uv, i):
        u, vc = uv  # u: [s_loc] (my sellers), vc: [C_tot] (all buyers)
        act = i < hp.sink_iters  # masked active depth (traced, sweepable)
        u2 = 1.0 / jnp.maximum(K @ vc, tiny)
        vc2 = 1.0 / jnp.maximum(ex.allsum(K.T @ u2), tiny)
        return (jnp.where(act, u2, u), jnp.where(act, vc2, vc)), None

    (u, vc), _ = jax.lax.scan(
        sink_step, (jnp.ones((C_loc,), jnp.float32), jnp.ones((C_tot,), jnp.float32)),
        jnp.arange(mcfg.sinkhorn_iters, dtype=jnp.int32))
    plan = u[:, None] * K * vc[None, :]  # [s_loc, C_tot]

    winner, csel, amounts, win_sell = _round_plan_to_matching(
        state, plan, feas, gidx, g_con, ex)
    return winner, csel, amounts, win_sell, tr.seller_locked_until


def _round(state: SimState, t, cfg: SimConfig, ex, params=None) -> SimState:
    """One market round over the (possibly sharded) cluster axis. Local
    arrays are [C_loc]; gathered arrays are [C_tot]. Single-device,
    C_loc == C_tot and the exchange ops are identities."""
    mcfg = cfg.trader
    tr = state.trader
    hp = market_hyper(mcfg, params)
    C_loc = state.arr_ptr.shape[0]
    INF = jnp.int32(2**31 - 1)
    gidx = ex.global_index(C_loc)

    # ---- buyers: request policies (trader.go:117-139; evaluation order
    # WaitTime -> Utilization as appended in newTrader, trader.go:55-62) ----
    eligible = tr.cooldown_until <= t
    wt_broken = tr.snap_avg_wait > mcfg.request_max_wait_ms
    ut_broken = jnp.logical_or(tr.snap_core_util > mcfg.request_core_max,
                               tr.snap_mem_util > mcfg.request_mem_max)
    want_fast = jnp.logical_and(eligible, wt_broken)
    want_small = jnp.logical_and(eligible,
                                 jnp.logical_and(jnp.logical_not(wt_broken), ut_broken))
    buyer = jnp.logical_or(want_fast, want_small)

    # ---- contract sizing from each buyer's Level1 backlog
    # (ProvideJobs streams a GetLevel1 copy, trader_server.go:69-94) ----
    budget = jnp.float32(mcfg.budget)
    cc, mc = jnp.float32(mcfg.max_core_cost), jnp.float32(mcfg.max_mem_cost)
    fast = jax.vmap(lambda q: sizing.fast_node_contract(q, budget, cc, mc))(state.l1)
    if mcfg.small_node_sizing == "asbuilt":
        small = jax.vmap(lambda q: sizing.small_node_contract_asbuilt(q, budget, cc, mc))(state.l1)
    else:
        small = jax.vmap(lambda q: sizing.small_node_contract_sane(q, budget, cc, mc))(state.l1)
    con = _tree_where(want_fast, fast, small)  # Contract with [C_loc] leaves

    # A zero-resource contract trades fine in Go (and is approved by every
    # idle seller); it happens when Level1 is empty. Keep it — parity.

    # ---- broadcast requests (the RequestResource fan-out, trader.go:211-229)
    g_buyer = ex.gather(buyer)  # [C_tot]
    g_con = jax.tree.map(ex.gather, con)

    new_price = tr.mkt_price
    if mcfg.matching == MatchKind.CVX:
        # function-level import: cvx.py imports this module's shared
        # helpers, so the dispatch edge must not close the cycle at import
        from multi_cluster_simulator_tpu.market import cvx as cvx_mod
        winner, csel, amounts, win_sell, new_lock, new_price = \
            cvx_mod.match_cvx(state, tr, t, mcfg, ex, gidx, g_buyer, g_con, hp)
    elif mcfg.matching == MatchKind.SINKHORN:
        winner, csel, amounts, win_sell, new_lock = _match_sinkhorn(
            state, tr, t, mcfg, ex, gidx, g_buyer, g_con, hp)
    else:
        winner, csel, amounts, win_sell, new_lock = _match_greedy(
            state, tr, t, mcfg, ex, gidx, g_buyer, g_con)
    has_winner = winner < INF

    # ---- apply: seller side — occupy carved amounts as Foreign placeholder
    # jobs for the contract duration (cluster.go:116). The node_free
    # decrement is gated on the placeholder row actually inserting: without
    # a RunningSet slot there is nothing to release the resources later, so
    # decrementing would leak them permanently (round-2 VERDICT weak #3);
    # the skipped occupation is surfaced in drops.carve ----
    def seller_apply(free, run, amts, ccon, win):
        def add_placeholder(carry, n):
            rn, fr, miss = carry
            occ = jnp.logical_and(win, jnp.any(amts[n] > 0))
            slot = jnp.argmin(rn.active).astype(jnp.int32)
            ok = jnp.logical_and(occ, jnp.logical_not(rn.active[slot]))
            row = R.make_row(t + ccon.time_ms, n, amts[n, CORES], amts[n, MEM],
                             amts[n, GPU], PLACEHOLDER_ID, FOREIGN,
                             ccon.time_ms, t)
            hot = jnp.logical_and(
                jnp.arange(rn.capacity, dtype=jnp.int32) == slot, ok)
            rn = R.insert_row(rn, hot, row)
            nhot = jnp.logical_and(
                jnp.arange(fr.shape[0], dtype=jnp.int32) == n, ok)
            fr = fr - nhot[:, None] * amts[n]
            miss = miss + jnp.logical_and(
                occ, jnp.logical_not(ok)).astype(jnp.int32)
            return (rn, fr, miss), None

        N = free.shape[0]
        (run, free, miss), _ = jax.lax.scan(
            add_placeholder, (run, free, jnp.int32(0)),
            jnp.arange(N, dtype=jnp.int32))
        return free, run, miss

    free, run, carve_miss = jax.vmap(seller_apply)(
        state.node_free, state.run, amounts, csel, win_sell)

    # ---- apply: buyer side — AddVirtualNode (cluster.go:65-85): the
    # NodeObject echoes the contract's cores/mem (trader_server.go:58) ----
    wcon = con  # own contract per local buyer
    got_node = jnp.logical_and(buyer, has_winner[gidx])

    def buyer_apply(cap, free_b, active, expire, health, ccon, got):
        vstart = cfg.max_nodes
        is_v = jnp.arange(cap.shape[0]) >= vstart
        # a DOWN slot (fault plane, faults/) is inactive but not vacant:
        # its was_active/cap are parked for repair, so a new contract must
        # not reclaim it mid-outage — attach only to healthy vacant slots
        slot_free = jnp.logical_and(
            is_v, jnp.logical_and(jnp.logical_not(active), health))
        slot = jnp.argmax(slot_free).astype(jnp.int32)
        ok = jnp.logical_and(got, jnp.any(slot_free))
        newcap = jnp.stack([ccon.cores, ccon.mem, ccon.gpu]).astype(jnp.int32)
        cap = cap.at[slot].set(jnp.where(ok, newcap, cap[slot]))
        free_b = free_b.at[slot].set(jnp.where(ok, newcap, free_b[slot]))
        active = active.at[slot].set(jnp.where(ok, True, active[slot]))
        exp_val = (t + ccon.time_ms) if mcfg.expire_virtual_nodes else R.NEVER
        expire = expire.at[slot].set(jnp.where(ok, exp_val, expire[slot]))
        vmiss = jnp.logical_and(got, jnp.logical_not(jnp.any(slot_free)))
        return cap, free_b, active, expire, vmiss.astype(jnp.int32)

    cap, free, active, expire, vslot_miss = jax.vmap(buyer_apply)(
        state.node_cap, free, state.node_active, state.node_expire,
        state.faults.health, wcon, got_node)

    # ---- cooldowns (the 4 min / 2 min sleeps, trader.go:296-302) ----
    cooldown = jnp.where(
        got_node, t + mcfg.cooldown_success_ms,
        jnp.where(buyer, t + mcfg.cooldown_failure_ms, tr.cooldown_until))
    spent = tr.spent + jnp.where(got_node, wcon.price, 0.0)

    return state.replace(
        node_cap=cap, node_free=free, node_active=active, node_expire=expire,
        run=run,
        drops=state.drops.replace(vslot=state.drops.vslot + vslot_miss,
                                  carve=state.drops.carve + carve_miss),
        trader=tr.replace(seller_locked_until=new_lock, cooldown_until=cooldown,
                          spent=spent, mkt_price=new_price,
                          next_contract_id=tr.next_contract_id
                          + buyer.astype(jnp.int32)))
